"""Ring attention over a TPU mesh axis: `lax.ppermute` + online softmax.

TPU-native redesign of the reference's L1+L3 (``ring.py`` /
``ring_flash_attention.py`` / ``ring_flash_attention_cuda.py`` in
lucidrains/ring-attention-pytorch).  The reference hand-rolls a P2P ring
(batched isend/irecv + barrier per hop, ``ring.py:51-60``) and hand-written
autograd Functions (``ring_flash_attention.py:60-387``).  Here the entire
communication layer is one collective — ``lax.ppermute`` over a named mesh
axis inside ``shard_map`` — which XLA pipelines with the per-hop flash
compute (the overlap the reference explicitly lacks), and differentiation
is a ``jax.custom_vjp`` whose backward rotates ``(k, v, dk, dv)`` together,
finishing with a single composed catch-up ppermute that returns partial
dk/dv to their owner shard when ``max_ring_passes`` limits the loop
(ref ``ring_flash_attention.py:380-385``).

Two interchangeable per-hop compute paths (the reference's naive/Triton
split, ``ring_attention.py:424-451``):

  - ``impl="xla"``   — blockwise jnp flash (``ops/flash.py``), runs anywhere;
  - ``impl="pallas"`` — Mosaic kernels (``ops/pallas_flash.py``) emitting
    mergeable ``(acc, m, l)`` partials, the performance path on TPU.

Ring-set math (multiple independent rings inside one world,
ref ``ring.py:35-47``) needs no code at all: ppermute over the ``seq`` mesh
axis is automatically scoped per row of the ``(data, seq)`` mesh.

Masking unification (see ``ops/flash.py``): each hop computes a single
*causal offset* scalar from ``(my_rank, origin_rank)``:

  - plain causal:   ``offset = (rank - origin) * n_local`` — covers
    "skip hop entirely" (origin > rank), "triangular" (origin == rank) and
    "fully visible" (origin < rank) in one expression
    (ref ``ring_flash_attention.py:177-192``).
  - striped causal: ``offset = 0 if origin <= rank else -1`` — the
    inclusive/exclusive diagonal flip (ref ``triton_flash_attn.py:216-221``,
    ``ring_flash_attention_cuda.py:158-160``).

Hops that provably contribute nothing (plain causal, origin ahead of rank;
or beyond the lookback window) skip their compute through ``lax.cond`` —
the per-device branch resolves at run time from ``axis_index``, while the
ppermute stays outside the cond so the collective schedule is identical on
every device.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.flash import (
    attend_blocks,
    finalize,
    flash_backward_blocks,
    init_carry,
    match_vma,
    _group_q,
    _ungroup,
)
from ..ops.pallas_flash import (
    finalize_partials,
    init_partials,
    merge_partials,
    pallas_flash_backward,
    pallas_flash_partials,
)
from ..utils.validate import check_attention_args


def _ring_perm(axis_name: str, shift: int = 1) -> list[tuple[int, int]]:
    size = lax.axis_size(axis_name)
    return [(j, (j + shift) % size) for j in range(size)]


def _rotate(x, axis_name: str):
    return lax.ppermute(x, axis_name, _ring_perm(axis_name))


def _hop_offsets(
    rank: jax.Array,
    origin: jax.Array,
    n_local: int,
    causal: bool,
    striped: bool,
    window: int | None,
    ring_size: int,
) -> tuple[jax.Array | None, jax.Array | None]:
    """Band offsets (hi, lo) for the tile (my queries) x (origin's keys).

    Attend iff ``lo <= j - i <= hi`` in local indices.  Contiguous layout:
    ``hi = (rank - origin) * n_local``, ``lo = hi - (window-1)``.  Striped
    layout (global pos ``i*W + rank`` / ``j*W + origin``): the diagonal flip
    ``hi = 0|-1`` and — exactly, unlike the reference's bucket-granular
    approximation (ref ring_flash_attention.py:95-103) — the window bound
    ``j*W + o >= i*W + r - w + 1  <=>  j >= i + ceil((r - o - w + 1)/W)``,
    an integer scalar per hop."""
    if not causal:
        return None, None
    if striped:
        hi = jnp.where(origin <= rank, 0, -1)
        if window is None:
            return hi, None
        lo = -((origin + window - 1 - rank) // ring_size)  # ceil division
        return hi, lo
    hi = (rank - origin) * n_local
    lo = hi - (window - 1) if window is not None else None
    return hi, lo


def _hop_has_work(
    hi: jax.Array | None, lo: jax.Array | None, n_local: int
) -> jax.Array:
    if hi is None:
        return jnp.bool_(True)
    ok = hi >= -(n_local - 1)
    if lo is not None:
        # lo > hi means an empty band: striped hops with window < ring_size
        # hold no in-window keys at all and can skip entirely
        return ok & (lo <= n_local - 1) & (lo <= hi)
    return ok


def _span_ops(impl, q, hk, scale, bucket_size, softclamp_value):
    """Per-hop (init, attend, final) for the chosen compute path.

    The carry is the online-softmax state; ``attend`` folds one KV span
    (the currently-held ring block) into it.
    """
    b, h, n_local, d = q.shape
    g = h // hk

    if impl == "pallas":

        def init():
            return init_partials(b, h, n_local, d, like=q)

        def attend(carry, k, v, kv_mask, hi, lo):
            parts = pallas_flash_partials(
                q, k, v, kv_mask,
                scale=scale, causal_offset=hi, window_lo=lo,
                softclamp_value=softclamp_value,
                block_q=bucket_size, block_k=bucket_size,
            )
            return merge_partials(carry, parts)

        def final(carry):
            out, lse = finalize_partials(carry)  # lse: (b, h, n)
            return out.astype(q.dtype), lse

    else:

        def init():
            return init_carry(b, hk, g, n_local, d, like=q)

        def attend(carry, k, v, kv_mask, hi, lo):
            return attend_blocks(
                q, k, v, carry,
                scale=scale, bucket_size=bucket_size, causal_offset=hi,
                window_lo=lo, kv_mask=kv_mask,
                softclamp_value=softclamp_value,
            )

        def final(carry):
            out_g, lse = finalize(carry)  # lse: (b, hk, g, n)
            return _ungroup(out_g).astype(q.dtype), lse

    return init, attend, final


def _span_bwd(impl, do, q, k, v, lse, delta, kv_mask, hi, lo, scale,
              bucket_size, softclamp_value, hk):
    """Per-hop backward: returns (dq (b,h,..), dk (b,hk,..), dv (b,hk,..))."""
    if impl == "pallas":
        return pallas_flash_backward(
            do, q, k, v, lse, delta, kv_mask,
            scale=scale, causal_offset=hi, window_lo=lo,
            softclamp_value=softclamp_value,
            block_q=bucket_size, block_k=bucket_size,
        )
    return flash_backward_blocks(
        do, q, k, v, lse, delta,
        scale=scale, bucket_size=bucket_size, causal_offset=hi,
        window_lo=lo, kv_mask=kv_mask, softclamp_value=softclamp_value,
    )


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None,
    axis_name: str,
    causal: bool = False,
    striped: bool = False,
    bucket_size: int | None = None,
    max_ring_passes: int | None = None,
    window: int | None = None,
    softclamp_value: float | None = None,
    scale: float | None = None,
    impl: str = "xla",
) -> jax.Array:
    """Sequence-parallel exact attention; call inside ``shard_map``.

    Args:
      q: ``(b, h, n_local, d)`` local query shard.
      k, v: ``(b, hk, n_local, d)`` local key/value shards (GQA when hk < h —
        the ring then only moves hk-sized blocks, the reference's
        bandwidth-saving trick, ref ``ring_attention.py:317-321``).
      kv_mask: optional ``(b, n_local)`` key-padding mask shard; rotates
        around the ring with k/v.
      axis_name: mesh axis the sequence is sharded over.
      causal/striped: causal masking, with striped (balanced) layout if the
        sequence was stripe-permuted before sharding.
      bucket_size: flash tile size within a hop.
      max_ring_passes: limit hops for per-layer lookback windows
        (ref ``ring_flash_attention.py:95-103``).
      window: exact sliding-window lookback in tokens (exact in both
        contiguous and striped layouts).
      impl: per-hop compute path, ``"xla"`` or ``"pallas"``.

    Cross-attention (unequal q/kv shard lengths) silently bypasses the ring
    and runs local flash over the local KV shard — the reference degrades
    the same way (ref ``ring_flash_attention.py:81-83``).

    Returns:
      ``(b, h, n_local, d)`` output shard, in ``q.dtype``.
    """
    check_attention_args("ring_flash_attention", q, k, v, kv_mask)
    if q.shape[2] != k.shape[2]:
        # Cross-attention: each device attends its local KV shard only,
        # exactly like the reference's non-ring fallback.  The causal band
        # (if any) is end-aligned by flash_attention.
        from ..ops.flash import flash_attention
        from ..ops.pallas_flash import pallas_flash_attention

        if impl == "pallas":
            return pallas_flash_attention(
                q, k, v, kv_mask, causal=causal, window=window,
                softclamp_value=softclamp_value, scale=scale,
            )
        return flash_attention(
            q, k, v, kv_mask, causal=causal, bucket_size=bucket_size,
            window=window, softclamp_value=softclamp_value, scale=scale,
        )
    return _ring_flash_attention_core(
        q, k, v, kv_mask, axis_name, causal, striped, bucket_size,
        max_ring_passes, window, softclamp_value, scale, impl,
    )


@partial(
    jax.custom_vjp,
    nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11, 12),
)
def _ring_flash_attention_core(
    q, k, v, kv_mask, axis_name, causal=False, striped=False,
    bucket_size=None, max_ring_passes=None, window=None,
    softclamp_value=None, scale=None, impl="xla",
):
    out, _ = _ring_fwd_impl(
        q, k, v, kv_mask, axis_name, causal, striped, bucket_size,
        max_ring_passes, window, softclamp_value, scale, impl,
    )
    return out


def _ring_fwd_impl(
    q, k, v, kv_mask, axis_name, causal, striped, bucket_size,
    max_ring_passes, window, softclamp_value, scale, impl,
):
    if window is not None:
        assert causal, "lookback windows require causal attention"
    b, h, n_local, d = q.shape
    hk = k.shape[1]
    if scale is None:
        scale = d**-0.5
    ring_size = lax.axis_size(axis_name)
    passes = min(max_ring_passes or ring_size, ring_size)
    rank = lax.axis_index(axis_name)

    init, attend, final = _span_ops(
        impl, q, hk, scale, bucket_size, softclamp_value
    )
    carry = init()
    kv = jnp.stack([k, v])  # one message per hop, ref ring_flash_attention.py:129
    mask_carry = kv_mask

    def hop(i, flash, kv, mask_carry):
        origin = (rank - i) % ring_size
        hi, lo = _hop_offsets(
            rank, origin, n_local, causal, striped, window, ring_size
        )
        has_work = _hop_has_work(hi, lo, n_local)

        flash = lax.cond(
            has_work,
            lambda f: attend(f, kv[0], kv[1], mask_carry, hi, lo),
            lambda f: f,
            flash,
        )
        # rotate AFTER compute; collective outside the cond so the schedule
        # is uniform across devices
        kv = _rotate(kv, axis_name)
        if mask_carry is not None:
            mask_carry = _rotate(mask_carry, axis_name)
        return flash, kv, mask_carry

    if mask_carry is None:
        def body(c, i):
            flash, kv = c
            flash, kv, _ = hop(i, flash, kv, None)
            return (flash, kv), None

        (carry, _), _ = lax.scan(body, (carry, kv), jnp.arange(passes))
    else:
        def body(c, i):
            flash, kv, m = c
            flash, kv, m = hop(i, flash, kv, m)
            return (flash, kv, m), None

        (carry, _, _), _ = lax.scan(body, (carry, kv, mask_carry), jnp.arange(passes))

    return final(carry)


def _ring_vjp_fwd(
    q, k, v, kv_mask, axis_name, causal, striped, bucket_size,
    max_ring_passes, window, softclamp_value, scale, impl,
):
    out, lse = _ring_fwd_impl(
        q, k, v, kv_mask, axis_name, causal, striped, bucket_size,
        max_ring_passes, window, softclamp_value, scale, impl,
    )
    return out, (q, k, v, kv_mask, out, lse)


def _ring_vjp_bwd(
    axis_name, causal, striped, bucket_size, max_ring_passes, window,
    softclamp_value, scale, impl, res, do,
):
    q, k, v, kv_mask, out, lse = res
    b, h, n_local, d = q.shape
    hk = k.shape[1]
    if scale is None:
        scale = d**-0.5
    ring_size = lax.axis_size(axis_name)
    passes = min(max_ring_passes or ring_size, ring_size)
    rank = lax.axis_index(axis_name)

    if impl == "pallas":
        # lse/delta in (b, h, n) layout
        delta = (do.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    else:
        delta = (
            _group_q(do, hk).astype(jnp.float32)
            * _group_q(out, hk).astype(jnp.float32)
        ).sum(-1)

    kv = jnp.stack([k, v])
    dkv = match_vma(jnp.zeros((2, b, hk, n_local, d), jnp.float32), q)
    dq = match_vma(jnp.zeros((b, h, n_local, d), jnp.float32), q)
    mask_carry = kv_mask

    def hop(i, dq, kv, dkv, mask_carry):
        origin = (rank - i) % ring_size
        hi, lo = _hop_offsets(
            rank, origin, n_local, causal, striped, window, ring_size
        )
        has_work = _hop_has_work(hi, lo, n_local)

        def do_bwd(args):
            dq, dkv = args
            dq_i, dk_i, dv_i = _span_bwd(
                impl, do, q, kv[0], kv[1], lse, delta, mask_carry, hi, lo,
                scale, bucket_size, softclamp_value, hk,
            )
            return dq + dq_i, dkv.at[0].add(dk_i).at[1].add(dv_i)

        dq, dkv = lax.cond(has_work, do_bwd, lambda a: a, (dq, dkv))
        kv = _rotate(kv, axis_name)
        dkv = _rotate(dkv, axis_name)
        if mask_carry is not None:
            mask_carry = _rotate(mask_carry, axis_name)
        return dq, kv, dkv, mask_carry

    if mask_carry is None:
        def body(c, i):
            dq, kv, dkv = c
            dq, kv, dkv, _ = hop(i, dq, kv, dkv, None)
            return (dq, kv, dkv), None

        (dq, kv, dkv), _ = lax.scan(body, (dq, kv, dkv), jnp.arange(passes))
    else:
        def body(c, i):
            dq, kv, dkv, m = c
            dq, kv, dkv, m = hop(i, dq, kv, dkv, m)
            return (dq, kv, dkv, m), None

        (dq, kv, dkv, _), _ = lax.scan(
            body, (dq, kv, dkv, mask_carry), jnp.arange(passes)
        )

    # Catch-up rotation: after `passes` end-of-hop rotations the dkv shard on
    # this device belongs to origin (rank - passes) % ring; one composed
    # ppermute with shift (ring - passes) returns every shard to its owner
    # in a single collective (the reference loops single hops instead,
    # ref ring_flash_attention.py:380-385).
    shift = (ring_size - passes) % ring_size
    if shift:
        dkv = lax.ppermute(dkv, axis_name, _ring_perm(axis_name, shift))

    return (
        dq.astype(q.dtype),
        dkv[0].astype(k.dtype),
        dkv[1].astype(v.dtype),
        None,
    )


_ring_flash_attention_core.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)
