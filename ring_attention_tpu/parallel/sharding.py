"""Sequence layout transforms: padding, striping, and shard specs.

The reference does its resharding with runtime all-gathers
(``sharded_batch_to_sharded_seq``, ref ``ring_attention.py:223-262``); on TPU
the same intent is expressed as *layouts*: pure index permutations applied to
the global array under ``jit``, with ``NamedSharding`` constraints deciding
which device materializes which slice.  XLA turns the stripe permutation plus
sharding into the minimal collective — there is no hand-written gather.

Striping (ref ``ring_attention.py:397-401``): device ``r`` of a ``W``-ring
should hold tokens ``{i * W + r}`` so every hop of causal ring attention has
equal work (Striped Attention, arXiv 2311.09431).  We stripe at token
granularity (the reference's fused-kernel ``buckets=1`` case,
ref ``ring_attention.py:143``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pad_to_multiple(
    x: jax.Array, multiple: int, axis: int = 1, value: float = 0.0
) -> tuple[jax.Array, int]:
    """Pad ``axis`` up to a multiple; returns (padded, original_length).

    Ref ``ring_attention.py:187-199``.
    """
    n = x.shape[axis]
    rem = n % multiple
    if rem == 0:
        return x, n
    pad = multiple - rem
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value), n


def pad_seq_and_mask(
    x: jax.Array, mask: jax.Array | None, multiple: int
) -> tuple[jax.Array, jax.Array | None, int]:
    """Pad tokens and key-padding mask together (ref ``ring_attention.py:201-221``).

    If padding is added and no mask exists, one is created so padded
    positions never receive attention.
    """
    x_padded, n = pad_to_multiple(x, multiple)
    if x_padded.shape[1] == n and mask is None:
        return x_padded, None, n
    if mask is None:
        mask = jnp.ones(x.shape[:2], bool)
    mask_padded, _ = pad_to_multiple(mask, multiple, axis=1, value=False)
    return x_padded, mask_padded, n


def stripe_permute(x: jax.Array, ring_size: int, axis: int = 1) -> jax.Array:
    """Reorder sequence so contiguous shards become stripes.

    ``[x0, x1, ..., x_{n-1}] -> [x0, x_W, x_2W, ..., x_1, x_{1+W}, ...]``;
    sharding the result contiguously over ``W`` devices gives device ``r``
    tokens ``≡ r (mod W)``.
    """
    n = x.shape[axis]
    assert n % ring_size == 0
    shape = list(x.shape)
    new_shape = shape[:axis] + [n // ring_size, ring_size] + shape[axis + 1 :]
    x = x.reshape(new_shape)
    x = jnp.swapaxes(x, axis, axis + 1)
    return x.reshape(shape)


def stripe_unpermute(x: jax.Array, ring_size: int, axis: int = 1) -> jax.Array:
    """Inverse of :func:`stripe_permute`."""
    n = x.shape[axis]
    assert n % ring_size == 0
    shape = list(x.shape)
    new_shape = shape[:axis] + [ring_size, n // ring_size] + shape[axis + 1 :]
    x = x.reshape(new_shape)
    x = jnp.swapaxes(x, axis, axis + 1)
    return x.reshape(shape)


def layout_for(
    sequence_parallel: str,
    striped: bool,
    seq_world: int,
    ulysses_size: int,
) -> tuple[str, int]:
    """``(scheme, factor)`` of the model-top sequence permutation for one
    context-parallel strategy.

    The ONE derivation both ``RingAttention`` and ``RingTransformer``
    consult, so the model-top layout can never de-synchronize from the
    per-layer band math.  The factor is the degree the layout interleaves
    at: the full sequence-parallel world for the 1-D schemes, but only the
    OUTER ring degree for hybrid — the ulysses all-to-all reassembles
    contiguous ring chunks, so striping must balance ring ranks, not
    devices.
    """
    if seq_world <= 1:
        return "contiguous", 1
    if sequence_parallel == "zigzag":
        return "zigzag", seq_world
    if not striped:
        return "contiguous", seq_world
    if sequence_parallel == "hybrid":
        return "striped", seq_world // ulysses_size
    if sequence_parallel == "ring":
        return "striped", seq_world
    return "contiguous", seq_world  # ulysses: no striping


def layout_permute(x: jax.Array, scheme: str, factor: int) -> jax.Array:
    """Apply the sequence-layout permutation one auto-shard scheme needs.

    The ONE place the scheme -> permutation mapping lives (the model-top
    auto-shard in ``models/attention.py`` and ``models/transformer.py``
    both route through here, for tokens, masks, and segment ids alike), so
    a factored (hybrid) layout only has to get its ``factor`` — the OUTER
    ring degree, not the full sequence-parallel world — right once.

    ``scheme``: ``"contiguous"`` (identity), ``"striped"`` (token-granular
    stripe over ``factor`` ring ranks), or ``"zigzag"`` (Llama-3 chunk
    pairing over ``factor`` ranks).
    """
    if scheme == "contiguous":
        return x
    if scheme == "striped":
        return stripe_permute(x, factor)
    if scheme == "zigzag":
        from .zigzag import zigzag_permute

        return zigzag_permute(x, factor)
    raise ValueError(f"unknown sequence layout scheme {scheme!r}")


def layout_unpermute(x: jax.Array, scheme: str, factor: int) -> jax.Array:
    """Inverse of :func:`layout_permute`."""
    if scheme == "contiguous":
        return x
    if scheme == "striped":
        return stripe_unpermute(x, factor)
    if scheme == "zigzag":
        from .zigzag import zigzag_unpermute

        return zigzag_unpermute(x, factor)
    raise ValueError(f"unknown sequence layout scheme {scheme!r}")
