"""Collective helpers: the reference's L1 ``distributed.py``, TPU-style.

The reference wraps ``torch.distributed`` in rank/world helpers, an
autograd all-gather, a variable-size gather, and a rank-splitter
(ref ``distributed.py:31-127``).  On a mesh almost all of that is a JAX
builtin; this module provides the named analogues so reference users find
each capability, plus the one genuinely non-trivial piece: a
**static-shape variable-size gather** (the reference's
``all_gather_variable_dim``, ref ``distributed.py:58-84``) — XLA needs
static shapes, so ragged gathers become pad-to-max + per-shard length
masks, with ``max_size`` fixed at trace time.

| reference (distributed.py)        | here                                   |
|-----------------------------------|----------------------------------------|
| ``get_rank`` :31-33               | ``axis_rank(axis)`` (lax.axis_index)   |
| ``get_world_size`` :35-37         | ``axis_world(axis)`` (lax.axis_size)   |
| ``is_distributed`` :39-41         | ``jax.device_count() > 1`` / mesh size |
| ``all_gather_same_dim`` :43-48    | ``lax.all_gather(..., tiled=True)``    |
| ``gather_sizes`` :50-53           | ``gather_sizes``                       |
| ``all_gather_variable_dim`` :58-84| ``all_gather_variable``                |
| ``AllGatherFunction`` bwd :103-107| ``lax.all_gather`` transpose (automatic)|
| ``split_by_rank`` :117-127        | ``split_by_rank``                      |

The lru-cached topology of the reference (fixed after first call — no
elastic resize, SURVEY §5) is inherent here: the mesh is part of the
compiled program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import compat


def axis_rank(axis_name: str) -> jax.Array:
    """This device's position along a mesh axis (inside shard_map)."""
    return lax.axis_index(axis_name)


def axis_world(axis_name: str) -> int:
    """Static size of a mesh axis (inside shard_map)."""
    return compat.axis_size(axis_name)


def gather_sizes(size: jax.Array, axis_name: str) -> jax.Array:
    """All shards' sizes, shape ``(world,)`` (ref ``distributed.py:50-53``)."""
    with jax.named_scope("collectives/gather_sizes"):
        return lax.all_gather(jnp.asarray(size, jnp.int32), axis_name)


def all_gather_variable(
    x: jax.Array,
    length: jax.Array,
    axis_name: str,
    *,
    max_size: int | None = None,
    axis: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """Gather shards whose *used* length differs per device.

    ``x`` must be padded to a common static ``max_size`` along ``axis``
    (defaults to ``x.shape[axis]``); ``length`` is this shard's used length.
    Returns ``(gathered, mask)`` where ``gathered`` has
    ``world * max_size`` entries along ``axis`` in rank order and ``mask``
    is a flat boolean validity mask of shape ``(world * max_size,)``.

    This is the XLA answer to the reference's ragged gather
    (pad + mask + index_select, ref ``distributed.py:58-84``): same
    semantics, but shapes are static so the program compiles once.  Use
    ``compact_masked`` on the host to drop the padding if a dense result
    is required.
    """
    if max_size is None:
        max_size = x.shape[axis]
    assert x.shape[axis] == max_size, "pad x to max_size before gathering"
    world = compat.axis_size(axis_name)

    with jax.named_scope("collectives/all_gather_variable"):
        gathered = lax.all_gather(x, axis_name, axis=axis, tiled=True)
    lengths = gather_sizes(length, axis_name)  # (world,)
    slot = jnp.arange(world * max_size) % max_size
    owner = jnp.arange(world * max_size) // max_size
    mask = slot < lengths[owner]
    return gathered, mask


def compact_masked(gathered: jax.Array, mask: jax.Array, *, axis: int = 0) -> jax.Array:
    """Drop the padding slots from an :func:`all_gather_variable` result.

    Returns the dense rank-order concatenation the reference's
    ``all_gather_variable_dim`` produces directly (ref
    ``distributed.py:77-83``).  The output length is data-dependent, so
    this runs on the host (outside ``jit``) — inside a compiled program,
    keep the static ``(gathered, mask)`` pair and mask at the use site.
    """
    import numpy as np

    g = np.asarray(gathered)  # ra: allow(RA009 compact_masked is documented host-only: output length is data-dependent)
    m = np.asarray(mask).astype(bool)  # ra: allow(RA009 compact_masked is documented host-only: output length is data-dependent)
    if m.shape != (g.shape[axis],):
        raise ValueError(
            f"mask shape {m.shape} must be ({g.shape[axis]},) — the flat "
            f"validity mask returned by all_gather_variable for axis {axis}"
        )
    return jnp.asarray(np.take(g, np.nonzero(m)[0], axis=axis))  # ra: allow(RA009 compact_masked is documented host-only: output length is data-dependent)


def split_by_rank(x: jax.Array, axis_name: str, *, axis: int = 0) -> jax.Array:
    """Take this rank's equal slice of a replicated array
    (ref ``distributed.py:117-127``)."""
    world = compat.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    assert x.shape[axis] % world == 0, (
        f"axis {axis} size {x.shape[axis]} must divide over {world} ranks; "
        "pad first (pad_to_multiple)"
    )
    size = x.shape[axis] // world
    return lax.dynamic_slice_in_dim(x, rank * size, size, axis=axis)


def quantize_ring_payload(k: jax.Array, v: jax.Array) -> jax.Array:
    """Int8-compress one ring hop's KV payload (``hop_compression="int8"``).

    Shares the per-token symmetric absmax scale machinery of the decode
    cache's ``flash_decode_q8`` path (``ops/pallas_flash.quantize_kv_cache``):
    one f32 scale per ``(head, token)`` row.  The ring quantizes ONCE at
    entry and then circulates the int8 representation unchanged — hops are
    lossless moves, so the accuracy cost is a single quantization
    (~0.4% RMS on unit-variance activations) regardless of ring size, and
    per-hop ICI bytes shrink ``d * dtype_bytes / (d + 4)``-fold (~3.8x from
    f32 at d=64; ~1.9x from bf16).  The f32 ``(acc, m, l)`` / dk/dv
    accumulators are untouched (``analysis/recompile.py::
    audit_accumulator_dtypes`` guards that contract).

    Returns one ``(2, b, hk, n, d + 4)`` int8 array with k at index 0 and
    v at index 1: channels ``[0:d]`` hold the quantized values and
    ``[d:d+4]`` the per-row f32 scale bitcast into its four bytes — the
    whole hop stays ONE ``ppermute`` (a collective move is bit-preserving,
    so the bitcast round-trips exactly), keeping the compressed variants'
    hop counts identical to the uncompressed contracts in
    ``analysis/contracts.py::CONTRACTS``.

    The codec itself lives in ``ops/quant.py`` (the one int8 seam, shared
    with the decode cache and the int8 compute path); this wrapper is the
    ring's named entry.  ``parallel/ring.py`` packs with
    ``quant.pack_kv(v_block=...)`` instead when ``compute_dtype="int8"``
    needs the dequant-free kernel feed — same wire format, kernel-ready
    v scales.
    """
    from ..ops import quant

    return quant.pack_kv(k, v)


def dequantize_ring_payload(payload: jax.Array, dtype) -> tuple[jax.Array, jax.Array]:
    """Materialize the ``(k, v)`` a compressed hop payload represents."""
    from ..ops import quant

    return quant.unpack_kv(payload, dtype)


def fold_batch_into_seq(x: jax.Array, num_sharded_batches: int) -> jax.Array:
    """Concatenate ``num_sharded_batches`` batch groups along the sequence.

    The reference gathers the batch across the world and folds
    ``world // (seq / shard)`` extra batches into sequence so a small batch
    can use a big world (``sharded_batch_to_sharded_seq``,
    ref ``ring_attention.py:223-262``).  On a mesh the same capacity choice
    is just the ``(data, seq)`` mesh shape — rings are mesh rows — so this
    helper is a pure reshape used when converting reference-style inputs:
    ``(b, n, ...) -> (b / k, k * n, ...)``.
    """
    b, n = x.shape[0], x.shape[1]
    k = num_sharded_batches
    assert b % k == 0
    return x.reshape(b // k, k * n, *x.shape[2:])


def unfold_seq_into_batch(x: jax.Array, num_sharded_batches: int) -> jax.Array:
    """Inverse of :func:`fold_batch_into_seq`
    (ref ``sharded_seq_to_sharded_batch``, ``ring_attention.py:264-279``)."""
    b, kn = x.shape[0], x.shape[1]
    k = num_sharded_batches
    assert kn % k == 0
    return x.reshape(b * k, kn // k, *x.shape[2:])
