"""Mesh construction and axis-name conventions.

The reference expresses topology as rank arithmetic over one flat world,
with "ring sets" carving the world into independent rings for hybrid
data-parallel x sequence-parallel runs (ref ``ring.py:35-47``,
``ring_attention.py:636-638``).  The TPU-native expression is a 2-D
``jax.sharding.Mesh`` with axes ``(data, seq)``: each row of the mesh is one
ring, ppermute over ``seq`` is automatically scoped per row, and gradient
psum over ``data`` is the DDP analogue.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SEQ_AXIS = "seq"
# factored (hybrid Ulysses x Ring) sequence axes: the sequence dimension is
# sharded over BOTH, ring-major / ulysses-minor, so each ulysses group of U
# devices collectively holds one contiguous ring chunk and the all-to-all
# over ``ulysses`` reassembles exactly that chunk (parallel/hybrid.py)
ULYSSES_AXIS = "ulysses"
RING_AXIS = "ring"
# hierarchical (pod-scale) outermost axis: pure data parallelism over the
# slow DCN links between slices/processes.  The sequence axes (ring /
# ulysses) must live strictly INSIDE one dcn_data group — sequence
# parallelism is placed on the physical topology (TASP, arXiv 2509.26541):
# per-hop ppermutes and bandwidth-hungry all-to-alls ride ICI, only the
# once-per-step gradient all-reduce crosses DCN.  Proven from optimized
# HLO by ``analysis/contracts.py::check_dcn_isolation``.
DCN_DATA_AXIS = "dcn_data"


def _snake_coords(dims: tuple[int, ...]) -> list[tuple[int, ...]]:
    """Boustrophedon path through a grid: consecutive coordinates differ by
    exactly 1 in exactly one axis.  On a torus the closing (last -> first)
    hop rides the wraparound link of axis 0."""
    if len(dims) == 1:
        return [(i,) for i in range(dims[0])]
    sub = _snake_coords(dims[1:])
    out: list[tuple[int, ...]] = []
    for i in range(dims[0]):
        for tail in (sub if i % 2 == 0 else sub[::-1]):
            out.append((i, *tail))
    return out


def torus_ring_order(devices: list) -> list | None:
    """Devices reordered so consecutive entries are physical ICI neighbors.

    Reads the TPU ``device.coords`` (the chip's position on the 2-D/3-D
    torus) and threads a snake (boustrophedon) path through the grid:
    every hop of a ring laid out in this order crosses exactly one ICI
    link (TASP, arXiv 2509.26541 — the flat device order makes distant
    ring ranks multi-hop stragglers that bound the whole ring's hop
    latency).  Chips exposing multiple cores sit adjacent in the path
    (same coords, consecutive ``core_on_chip``).

    Returns None when the devices expose no usable coordinates (CPU /
    simulated meshes) or do not fill a dense grid — callers fall back to a
    deterministic flat order.
    """
    coords = []
    for dev in devices:
        c = getattr(dev, "coords", None)
        if c is None:
            return None
        coords.append(tuple(int(x) for x in c))
    dims = tuple(max(c[i] for c in coords) + 1 for i in range(len(coords[0])))
    by_coord: dict[tuple[int, ...], list] = {}
    for dev, c in zip(devices, coords):
        by_coord.setdefault(c, []).append(dev)
    if len(by_coord) != int(np.prod(dims)):  # ra: allow(RA009 host-side device-topology math on python ints)
        return None  # sparse / irregular slice: no dense snake exists
    per_chip = {len(v) for v in by_coord.values()}
    if len(per_chip) != 1:
        return None
    for devs in by_coord.values():
        devs.sort(key=lambda d: getattr(d, "core_on_chip", 0) or 0)
    return [d for c in _snake_coords(dims) for d in by_coord[c]]


def create_mesh(
    ring_size: int | None = None,
    data_size: int | None = None,
    *,
    ulysses_size: int | None = None,
    dcn_data_size: int | None = None,
    devices: list | None = None,
    ring_order: str = "auto",
) -> Mesh:
    """Build a ``(data, seq)`` mesh — or ``(data, ring, ulysses)`` when
    ``ulysses_size`` factors the sequence axis for hybrid 2-D sequence
    parallelism (``sequence_parallel="hybrid"``), or a hierarchical
    ``(dcn_data, data, ...)`` mesh when ``dcn_data_size`` adds the
    pod-scale DCN level.

    ``ring_size`` defaults to all devices (one big ring); ``data_size``
    defaults to ``n_devices // ring_size`` — the reference's
    ``num_sharded_batches`` derivation (ref ``ring_attention.py:636-638``).
    With ``ulysses_size=U``, ``ring_size`` is the OUTER ring degree and the
    sequence-parallel world is ``U * ring_size``.

    ``ring_order`` controls how logical ring ranks map onto physical
    devices:

    - ``"auto"`` (default): topology-aware placement.  On TPU the device
      coordinates thread a snake path through the torus
      (:func:`torus_ring_order`) so neighboring ring ranks are physical
      ICI neighbors — every hop of the per-hop ppermute crosses exactly
      one link instead of the multi-hop stragglers a flat order produces
      on v5p 3-D torus slices (TASP, arXiv 2509.26541).  When coords are
      unusable it falls back to ``mesh_utils.create_device_mesh``, then to
      the flat order; on CPU / simulated devices the fallback is the flat
      sorted-by-id order, so "auto" is DETERMINISTIC everywhere.
    - ``"flat"``: the plain device-list order (the reference's NCCL
      flat-rank assumption) — the A/B baseline for placement shootouts.

    In the factored mesh the ``ulysses`` axis is the innermost
    (fastest-varying) array dimension, so the bandwidth-hungry all-to-all
    lands on the closest-connected device groups and the ring's per-hop
    ppermute rides the next tier out — the TASP/TokenRing
    collective-to-link-tier matching (PAPERS.md).

    ``dcn_data_size=D`` (default off) prepends the pod-scale ``dcn_data``
    axis — the OUTERMOST (slowest-varying) dimension, mapping onto the
    DCN links between slices/processes: the mesh becomes
    ``(dcn_data, data, seq)`` or ``(dcn_data, data, ring, ulysses)``,
    with ``data_size`` / ``ring_size`` / ``ulysses_size`` now describing
    ONE dcn group of ``n_devices / D``.  The placement contract (the
    whole point of the hierarchy) is that every sequence-parallel group —
    each ring and each ulysses all-to-all set — sits strictly inside one
    dcn group; under ``jax.distributed`` each group must additionally sit
    inside one *process* (rings must never hop over DCN).  The
    construction validates that and raises a one-line diagnostic when the
    device order cannot honor it; ``analysis/contracts.py::
    check_dcn_isolation`` proves the resulting collective placement from
    optimized HLO.  Pass ``dcn_data_size=jax.process_count()`` on a
    multi-host pod.
    """
    if ring_order not in ("auto", "flat"):
        raise ValueError(
            f'ring_order={ring_order!r}: want "auto" (topology-aware snake '
            'over the TPU torus, deterministic flat fallback) or "flat"'
        )
    explicit = devices is not None
    devices = devices if explicit else jax.devices()
    n = len(devices)
    dcn = int(dcn_data_size or 1)
    if dcn > 1:
        if n % dcn:
            raise ValueError(
                f"create_mesh: dcn_data_size {dcn} must divide "
                f"{n} devices"
            )
        # the inner (per-dcn-group) world: data/ring/ulysses factor THIS
        inner = create_mesh(
            ring_size, data_size, ulysses_size=ulysses_size,
            devices=list(devices)[:n // dcn], ring_order=ring_order,
        )
        shape = (dcn, *inner.devices.shape)
        axes = (DCN_DATA_AXIS, *inner.axis_names)
        arr = np.asarray(devices).reshape(shape)  # ra: allow(RA009 host-side device-object array for Mesh construction)
        # within each dcn group, reuse the inner (possibly topology-aware)
        # ordering group by group so rings still snake their slice
        for g in range(dcn):
            sub = create_mesh(
                ring_size, data_size, ulysses_size=ulysses_size,
                devices=list(np.asarray(arr[g]).reshape(-1)),  # ra: allow(RA009 host-side device-object array for Mesh construction)
                ring_order=ring_order,
            )
            arr[g] = sub.devices
        _validate_dcn_grouping(arr, axes)
        return Mesh(arr, axes)
    if ulysses_size is not None and ulysses_size > 1:
        u = ulysses_size
        assert n % u == 0, f"ulysses_size {u} must divide {n} devices"
        if ring_size is None:
            ring_size = (n // u) if data_size is None else n // (data_size * u)
        if data_size is None:
            data_size = n // (u * ring_size)
        assert data_size * u * ring_size == n, (
            f"mesh {data_size}x{u}x{ring_size} != {n} devices"
        )
        shape = (data_size, ring_size, u)
        axes = (DATA_AXIS, RING_AXIS, ULYSSES_AXIS)
    else:
        if ring_size is None:
            ring_size = n if data_size is None else n // data_size
        if data_size is None:
            data_size = n // ring_size
        assert data_size * ring_size == n, (
            f"mesh {data_size}x{ring_size} != {n} devices"
        )
        shape = (data_size, ring_size)
        axes = (DATA_AXIS, SEQ_AXIS)
    if ring_order == "auto" and devices and getattr(
        devices[0], "platform", None
    ) == "tpu":
        ordered = torus_ring_order(devices)
        if ordered is not None:
            # row-major reshape puts consecutive snake neighbors along the
            # innermost (fastest-varying) axis: ulysses groups sit on the
            # closest links, ring ranks on adjacent ones
            return Mesh(np.asarray(ordered).reshape(shape), axes)  # ra: allow(RA009 host-side device-object array for Mesh construction)
        if not explicit:
            try:
                from jax.experimental import mesh_utils

                arr = mesh_utils.create_device_mesh(shape)
                return Mesh(arr, axes)
            except (ValueError, NotImplementedError) as e:
                import warnings

                warnings.warn(
                    f"topology-aware device mesh unavailable ({e}); falling "
                    "back to flat device order — ring hops may cross "
                    "non-adjacent links"
                )
    arr = np.asarray(devices).reshape(shape)  # ra: allow(RA009 host-side device-object array for Mesh construction)
    return Mesh(arr, axes)


def _validate_dcn_grouping(arr: np.ndarray, axes: tuple[str, ...]) -> None:
    """The hierarchical placement contract: every sequence-parallel group
    (the trailing ring/ulysses/seq dims of one ``(dcn, data)`` cell) must
    sit inside ONE process — a ring whose hops cross the DCN boundary is
    exactly the straggler topology the dcn axis exists to forbid.  Only
    meaningful under ``jax.distributed``; single-process (virtual-device)
    meshes always pass."""
    if jax.process_count() <= 1:
        return
    data_i = axes.index(DATA_AXIS)
    lead = arr.shape[: data_i + 1]
    cells = arr.reshape(int(np.prod(lead)), -1)  # ra: allow(RA009 host-side device-topology math on python ints)
    for cell, devs in enumerate(cells):
        procs = {getattr(d, "process_index", 0) for d in devs}
        if len(procs) > 1:
            coords = np.unravel_index(cell, lead)  # ra: allow(RA009 host-side device-topology math on python ints)
            raise ValueError(
                f"create_mesh: sequence-parallel group at "
                f"{dict(zip(axes[:data_i + 1], map(int, coords)))} spans "
                f"processes {sorted(procs)} — rings/ulysses groups must "
                f"live inside one process (set dcn_data_size="
                f"jax.process_count() and size data/ring/ulysses to one "
                f"process's devices)"
            )


def is_factored(mesh: Mesh) -> bool:
    """True when the mesh factors the sequence axis (hybrid Ulysses x Ring)."""
    return RING_AXIS in mesh.shape


def has_dcn(mesh: Mesh | None) -> bool:
    """True when the mesh carries the pod-scale ``dcn_data`` level."""
    return mesh is not None and DCN_DATA_AXIS in mesh.shape


def data_partition(mesh: Mesh | None):
    """PartitionSpec entry for the batch dimension: ``"data"`` on flat
    meshes, ``("dcn_data", "data")`` on hierarchical ones — the batch
    shards over BOTH data-parallel tiers, so per-step traffic over the
    slow axis stays the one gradient all-reduce."""
    if has_dcn(mesh):
        return (DCN_DATA_AXIS, DATA_AXIS)
    return DATA_AXIS


def data_world(mesh: Mesh | None) -> int:
    """Total data-parallel degree (both tiers of a hierarchical mesh)."""
    if mesh is None:
        return 1
    size = int(mesh.shape.get(DATA_AXIS, 1))
    if has_dcn(mesh):
        size *= int(mesh.shape[DCN_DATA_AXIS])
    return size


def seq_axes(mesh: Mesh) -> tuple[str, ...]:
    """Mesh axis name(s) the sequence dimension shards over, major first.

    Plain meshes: ``("seq",)``.  Factored meshes: ``("ring", "ulysses")`` —
    ring-major so device ``(u, r)`` holds subchunk ``u`` of contiguous ring
    chunk ``r``, the layout the hybrid all-to-all reassembles.
    """
    if is_factored(mesh):
        return (RING_AXIS, ULYSSES_AXIS)
    return (SEQ_AXIS,)


def seq_world(mesh: Mesh) -> int:
    """Total number of sequence shards (the sequence-parallel world size)."""
    size = 1
    for ax in seq_axes(mesh):
        size *= mesh.shape[ax]
    return size


def seq_partition(mesh: Mesh):
    """PartitionSpec entry for the sequence dimension (axis name or tuple)."""
    axes = seq_axes(mesh)
    return axes[0] if len(axes) == 1 else axes


def mesh_descriptor(mesh: Mesh | None) -> dict | None:
    """JSON-able identity of a mesh: axis names + sizes, in axis order.

    This is what the elastic checkpoint manifest records
    (``elastic/checkpoint.py``): enough to decide on restore whether the
    job came back at the same factoring or needs a re-mesh, without
    serializing device objects (which don't survive a restart anyway).
    """
    if mesh is None:
        return None
    return {
        "axes": [str(a) for a in mesh.axis_names],
        "shape": [int(mesh.shape[a]) for a in mesh.axis_names],
    }


def remesh_plan(
    old: dict | None, n_devices: int, *, dcn_data_size: int | None = None
) -> tuple[dict, list[str]]:
    """Plan a mesh factoring for ``n_devices`` given a checkpoint's old
    :func:`mesh_descriptor` — the elastic-resume re-mesh rule.

    Preference order (each preserved factor keeps resume semantics
    closest to the old run): keep ``dcn_data``, ``data`` and ``ulysses``
    exactly when they still divide the new world, and absorb ALL
    growth/shrink into the ``ring``/``seq`` axis (sequence shards are
    what the resharded loader re-scatters anyway); when a preserved
    factor no longer divides, fall back to its gcd with the world.
    ``dcn_data_size`` overrides the preserved dcn level — pass the
    CURRENT ``jax.process_count()`` so a job that lost a host re-plans
    its DCN tier to the surviving cluster (1 drops the axis entirely).
    Returns ``(create_mesh_kwargs, diagnostics)`` where every decision
    that changed something is one human-readable line — the resume
    banner.
    """
    from math import gcd

    if n_devices < 1:
        raise ValueError(f"remesh_plan: n_devices must be >= 1, got {n_devices}")
    diags: list[str] = []
    if not old:
        diags.append(
            f"re-mesh: no mesh recorded in the checkpoint; defaulting to "
            f"one ring of {n_devices}"
        )
        plan: dict = {"ring_size": n_devices}
        if dcn_data_size and dcn_data_size > 1:
            if n_devices % dcn_data_size:
                raise ValueError(
                    f"remesh_plan: dcn_data_size {dcn_data_size} does not "
                    f"divide the {n_devices}-device world"
                )
            plan = {"ring_size": n_devices // dcn_data_size,
                    "dcn_data_size": dcn_data_size}
        return plan, diags
    sizes = dict(zip(old.get("axes", []), old.get("shape", [])))
    old_world = 1
    for s in sizes.values():
        old_world *= int(s)
    dcn = int(sizes.get(DCN_DATA_AXIS, 1))
    data = int(sizes.get(DATA_AXIS, 1))
    ulysses = int(sizes.get(ULYSSES_AXIS, 1))
    ring = int(sizes.get(RING_AXIS, sizes.get(SEQ_AXIS, 1)))
    if old_world != n_devices:
        diags.append(f"re-mesh: world {old_world} -> {n_devices}")
    if dcn_data_size is not None:
        new_dcn = int(dcn_data_size)
        if n_devices % max(new_dcn, 1):
            raise ValueError(
                f"remesh_plan: dcn_data_size {new_dcn} does not divide "
                f"the {n_devices}-device world"
            )
        if new_dcn != dcn:
            diags.append(
                f"re-mesh: dcn_data {dcn} -> {new_dcn} (process count "
                f"changed)"
            )
        dcn = max(new_dcn, 1)
    elif n_devices % dcn != 0:
        new_dcn = gcd(dcn, n_devices)
        diags.append(
            f"re-mesh: dcn_data {dcn} does not divide world {n_devices}; "
            f"shrinking to gcd {new_dcn}"
        )
        dcn = new_dcn
    rest = n_devices // dcn
    if rest % data != 0:
        new_data = gcd(data, rest)
        diags.append(
            f"re-mesh: data {data} does not divide world {rest}; "
            f"shrinking to gcd {new_data}"
        )
        data = new_data
    rest = rest // data
    if rest % ulysses != 0:
        new_u = gcd(ulysses, rest)
        diags.append(
            f"re-mesh: ulysses {ulysses} does not divide {rest}; "
            f"shrinking to gcd {new_u}"
        )
        ulysses = new_u
    new_ring = rest // ulysses
    if new_ring != ring:
        diags.append(f"re-mesh: ring {ring} -> {new_ring}")
    kwargs: dict = {"ring_size": new_ring, "data_size": data}
    if ulysses > 1:
        kwargs["ulysses_size"] = ulysses
    if dcn > 1:
        kwargs["dcn_data_size"] = dcn
    return kwargs, diags


def validate_seq_len(seq_len: int, mesh: Mesh | None) -> None:
    """One-line divisibility diagnostic for the resume path.

    ``auto_shard`` pads a non-divisible sequence, but a RESUMED run whose
    padding changed under it silently shifts bucket boundaries against
    the checkpointed positions — so elastic resume requires exact
    divisibility and says exactly what to change when it fails.
    """
    if mesh is None:
        return
    world = seq_world(mesh)
    if seq_len % world != 0:
        axes = "x".join(
            f"{a}={mesh.shape[a]}" for a in seq_axes(mesh)
        )
        raise ValueError(
            f"seq_len {seq_len} % sequence world {world} ({axes}) != 0 — "
            f"resume at this device count needs seq_len divisible by "
            f"{world}; pad the sequence or pick a ring size that divides "
            f"{seq_len}"
        )


def initialize_multihost(
    *, attempts: int = 3, backoff: float = 1.0, **kwargs
) -> None:
    """Join a multi-host (multi-process) TPU job before building meshes.

    ``jax.distributed.initialize`` behind the shared retry ladder
    (``utils/resilience.with_retries``) — on a real pod the workers race
    the coordinator to startup, and "coordinator not yet listening" is a
    transient that deserves ``attempts`` backed-off retries, not a crash.
    Exhaustion fires the resilience failure listeners (an installed
    FlightRecorder dumps the incident) and raises ONE line naming the
    coordinator address, so a dead coordinator is a readable diagnosis
    instead of a grpc traceback.

    On TPU pods the coordinator/process-count/process-id are discovered
    from the environment automatically, so a bare call suffices.  After
    this, ``jax.devices()`` is the *global* device list and
    ``create_mesh(dcn_data_size=jax.process_count())`` builds the
    hierarchical mesh whose rings never cross DCN (the analogue of the
    reference's NCCL multi-node process groups, SURVEY §2.3).
    """
    from ..utils.resilience import RetryError, with_retries

    import os

    # CPU clusters (the two-process test harness, dev boxes) need the
    # gloo collectives backend enabled BEFORE the first computation —
    # without it every cross-process jit dies with "Multiprocess
    # computations aren't implemented on the CPU backend".  Set it only
    # when the platform is (or defaults to) cpu; builds without the
    # option degrade gracefully like every compat shim.
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu") or (
        jax.config.jax_platforms or ""
    ).startswith("cpu"):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — older jax: no such option
            pass

    def initialize_multihost_join() -> None:
        jax.distributed.initialize(**kwargs)

    try:
        with_retries(
            initialize_multihost_join,
            max_attempts=attempts, backoff=backoff,
        )
    except RetryError as e:
        import os

        coordinator = (
            kwargs.get("coordinator_address")
            or os.environ.get("JAX_COORDINATOR_ADDRESS")
            or "<env-discovered>"
        )
        raise RuntimeError(
            f"initialize_multihost: could not join the jax cluster at "
            f"coordinator {coordinator} after {attempts} attempts "
            f"(last: {type(e.last).__name__}: {e.last}) — is the "
            f"coordinator process up and reachable?"
        ) from e


def seq_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for ``(b, n, ...)`` activations: batch over the data
    tier(s) (``(dcn_data, data)`` on a hierarchical mesh), seq over the
    ring — or over ``(ring, ulysses)`` on a factored (hybrid) mesh."""
    return NamedSharding(mesh, P(data_partition(mesh), seq_partition(mesh)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh):
    """Place a host-local token batch onto the ``(data, seq)`` mesh.

    Single-process (one host owns every device): a plain ``device_put``
    with :func:`seq_sharding`.  Multi-host (after
    :func:`initialize_multihost`): each process passes only ITS local
    slice of the global batch and the pieces assemble into one global
    array via ``jax.make_array_from_process_local_data`` — the dataloader
    never materializes the full global batch on any host, which at ring
    scale is the difference between feeding a 2^20-token sequence and
    OOMing the coordinator.  (The reference gathers the full batch onto
    every rank instead: ``all_gather`` in
    ``sharded_batch_to_sharded_seq``, ref ``ring_attention.py:223-262``.)

    Works on pytrees: leaves of rank >= 2 get batch over ``data`` and
    sequence over ``seq``; rank-1 leaves shard over ``data`` only;
    scalars replicate.
    """
    def place(x):
        # host-side ndarray: device_put / make_array_from_process_local_data
        # then transfer each shard directly, never staging the full array
        # through one device's HBM
        x = np.asarray(x)  # ra: allow(RA009 documented host-side placement helper, runs outside jit)
        if x.ndim >= 2:
            sharding = seq_sharding(mesh)
        elif x.ndim == 1:
            sharding = NamedSharding(mesh, P(data_partition(mesh)))
        else:
            sharding = replicated(mesh)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree.map(place, batch)
