"""Mesh construction and axis-name conventions.

The reference expresses topology as rank arithmetic over one flat world,
with "ring sets" carving the world into independent rings for hybrid
data-parallel x sequence-parallel runs (ref ``ring.py:35-47``,
``ring_attention.py:636-638``).  The TPU-native expression is a 2-D
``jax.sharding.Mesh`` with axes ``(data, seq)``: each row of the mesh is one
ring, ppermute over ``seq`` is automatically scoped per row, and gradient
psum over ``data`` is the DDP analogue.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SEQ_AXIS = "seq"


def create_mesh(
    ring_size: int | None = None,
    data_size: int | None = None,
    *,
    devices: list | None = None,
) -> Mesh:
    """Build a ``(data, seq)`` mesh.

    ``ring_size`` defaults to all devices (one big ring); ``data_size``
    defaults to ``n_devices // ring_size`` — the reference's
    ``num_sharded_batches`` derivation (ref ``ring_attention.py:636-638``).

    On real TPU topologies the device order comes from
    ``mesh_utils.create_device_mesh`` so the ``seq`` (ring) axis maps onto
    physically adjacent ICI links — the per-hop ppermute then never crosses
    DCN.  This replaces the reference's flat-rank assumption (its NCCL ring
    order is whatever the launcher provided).
    """
    explicit = devices is not None
    devices = devices if explicit else jax.devices()
    n = len(devices)
    if ring_size is None:
        ring_size = n if data_size is None else n // data_size
    if data_size is None:
        data_size = n // ring_size
    assert data_size * ring_size == n, (
        f"mesh {data_size}x{ring_size} != {n} devices"
    )
    if not explicit and devices and devices[0].platform == "tpu":
        try:
            from jax.experimental import mesh_utils

            arr = mesh_utils.create_device_mesh((data_size, ring_size))
            return Mesh(arr, (DATA_AXIS, SEQ_AXIS))
        except (ValueError, NotImplementedError) as e:
            import warnings

            warnings.warn(
                f"topology-aware device mesh unavailable ({e}); falling back "
                "to flat device order — ring hops may cross non-adjacent links"
            )
    arr = np.asarray(devices).reshape(data_size, ring_size)
    return Mesh(arr, (DATA_AXIS, SEQ_AXIS))


def initialize_multihost(**kwargs) -> None:
    """Join a multi-host (multi-process) TPU job before building meshes.

    Thin passthrough to ``jax.distributed.initialize`` — on TPU pods the
    coordinator/process-count/process-id are discovered from the
    environment automatically, so a bare call suffices.  After this,
    ``jax.devices()`` is the *global* device list and ``create_mesh`` spans
    the whole slice (collectives ride ICI within a slice and DCN across,
    scheduled by XLA — the analogue of the reference's NCCL multi-node
    process groups, SURVEY §2.3).
    """
    jax.distributed.initialize(**kwargs)


def seq_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for ``(b, n, ...)`` activations: batch over data, seq over ring."""
    return NamedSharding(mesh, P(DATA_AXIS, SEQ_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh):
    """Place a host-local token batch onto the ``(data, seq)`` mesh.

    Single-process (one host owns every device): a plain ``device_put``
    with :func:`seq_sharding`.  Multi-host (after
    :func:`initialize_multihost`): each process passes only ITS local
    slice of the global batch and the pieces assemble into one global
    array via ``jax.make_array_from_process_local_data`` — the dataloader
    never materializes the full global batch on any host, which at ring
    scale is the difference between feeding a 2^20-token sequence and
    OOMing the coordinator.  (The reference gathers the full batch onto
    every rank instead: ``all_gather`` in
    ``sharded_batch_to_sharded_seq``, ref ``ring_attention.py:223-262``.)

    Works on pytrees: leaves of rank >= 2 get batch over ``data`` and
    sequence over ``seq``; rank-1 leaves shard over ``data`` only;
    scalars replicate.
    """
    def place(x):
        # host-side ndarray: device_put / make_array_from_process_local_data
        # then transfer each shard directly, never staging the full array
        # through one device's HBM
        x = np.asarray(x)
        if x.ndim >= 2:
            sharding = seq_sharding(mesh)
        elif x.ndim == 1:
            sharding = NamedSharding(mesh, P(DATA_AXIS))
        else:
            sharding = replicated(mesh)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree.map(place, batch)
