"""Mesh construction and axis-name conventions.

The reference expresses topology as rank arithmetic over one flat world,
with "ring sets" carving the world into independent rings for hybrid
data-parallel x sequence-parallel runs (ref ``ring.py:35-47``,
``ring_attention.py:636-638``).  The TPU-native expression is a 2-D
``jax.sharding.Mesh`` with axes ``(data, seq)``: each row of the mesh is one
ring, ppermute over ``seq`` is automatically scoped per row, and gradient
psum over ``data`` is the DDP analogue.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SEQ_AXIS = "seq"
# factored (hybrid Ulysses x Ring) sequence axes: the sequence dimension is
# sharded over BOTH, ring-major / ulysses-minor, so each ulysses group of U
# devices collectively holds one contiguous ring chunk and the all-to-all
# over ``ulysses`` reassembles exactly that chunk (parallel/hybrid.py)
ULYSSES_AXIS = "ulysses"
RING_AXIS = "ring"


def create_mesh(
    ring_size: int | None = None,
    data_size: int | None = None,
    *,
    ulysses_size: int | None = None,
    devices: list | None = None,
) -> Mesh:
    """Build a ``(data, seq)`` mesh — or ``(data, ring, ulysses)`` when
    ``ulysses_size`` factors the sequence axis for hybrid 2-D sequence
    parallelism (``sequence_parallel="hybrid"``).

    ``ring_size`` defaults to all devices (one big ring); ``data_size``
    defaults to ``n_devices // ring_size`` — the reference's
    ``num_sharded_batches`` derivation (ref ``ring_attention.py:636-638``).
    With ``ulysses_size=U``, ``ring_size`` is the OUTER ring degree and the
    sequence-parallel world is ``U * ring_size``.

    On real TPU topologies the device order comes from
    ``mesh_utils.create_device_mesh`` so the ``seq`` (ring) axis maps onto
    physically adjacent ICI links — the per-hop ppermute then never crosses
    DCN.  This replaces the reference's flat-rank assumption (its NCCL ring
    order is whatever the launcher provided).  In the factored mesh the
    ``ulysses`` axis is the innermost (fastest-varying) array dimension, so
    the bandwidth-hungry all-to-all lands on the fastest-connected device
    groups and the ring's per-hop ppermute rides the next tier out — the
    TASP/TokenRing collective-to-link-tier matching (PAPERS.md).
    """
    explicit = devices is not None
    devices = devices if explicit else jax.devices()
    n = len(devices)
    if ulysses_size is not None and ulysses_size > 1:
        u = ulysses_size
        assert n % u == 0, f"ulysses_size {u} must divide {n} devices"
        if ring_size is None:
            ring_size = (n // u) if data_size is None else n // (data_size * u)
        if data_size is None:
            data_size = n // (u * ring_size)
        assert data_size * u * ring_size == n, (
            f"mesh {data_size}x{u}x{ring_size} != {n} devices"
        )
        shape = (data_size, ring_size, u)
        axes = (DATA_AXIS, RING_AXIS, ULYSSES_AXIS)
    else:
        if ring_size is None:
            ring_size = n if data_size is None else n // data_size
        if data_size is None:
            data_size = n // ring_size
        assert data_size * ring_size == n, (
            f"mesh {data_size}x{ring_size} != {n} devices"
        )
        shape = (data_size, ring_size)
        axes = (DATA_AXIS, SEQ_AXIS)
    if not explicit and devices and devices[0].platform == "tpu":
        try:
            from jax.experimental import mesh_utils

            arr = mesh_utils.create_device_mesh(shape)
            return Mesh(arr, axes)
        except (ValueError, NotImplementedError) as e:
            import warnings

            warnings.warn(
                f"topology-aware device mesh unavailable ({e}); falling back "
                "to flat device order — ring hops may cross non-adjacent links"
            )
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axes)


def is_factored(mesh: Mesh) -> bool:
    """True when the mesh factors the sequence axis (hybrid Ulysses x Ring)."""
    return RING_AXIS in mesh.shape


def seq_axes(mesh: Mesh) -> tuple[str, ...]:
    """Mesh axis name(s) the sequence dimension shards over, major first.

    Plain meshes: ``("seq",)``.  Factored meshes: ``("ring", "ulysses")`` —
    ring-major so device ``(u, r)`` holds subchunk ``u`` of contiguous ring
    chunk ``r``, the layout the hybrid all-to-all reassembles.
    """
    if is_factored(mesh):
        return (RING_AXIS, ULYSSES_AXIS)
    return (SEQ_AXIS,)


def seq_world(mesh: Mesh) -> int:
    """Total number of sequence shards (the sequence-parallel world size)."""
    size = 1
    for ax in seq_axes(mesh):
        size *= mesh.shape[ax]
    return size


def seq_partition(mesh: Mesh):
    """PartitionSpec entry for the sequence dimension (axis name or tuple)."""
    axes = seq_axes(mesh)
    return axes[0] if len(axes) == 1 else axes


def initialize_multihost(**kwargs) -> None:
    """Join a multi-host (multi-process) TPU job before building meshes.

    Thin passthrough to ``jax.distributed.initialize`` — on TPU pods the
    coordinator/process-count/process-id are discovered from the
    environment automatically, so a bare call suffices.  After this,
    ``jax.devices()`` is the *global* device list and ``create_mesh`` spans
    the whole slice (collectives ride ICI within a slice and DCN across,
    scheduled by XLA — the analogue of the reference's NCCL multi-node
    process groups, SURVEY §2.3).
    """
    jax.distributed.initialize(**kwargs)


def seq_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for ``(b, n, ...)`` activations: batch over data, seq over
    the ring — or over ``(ring, ulysses)`` on a factored (hybrid) mesh."""
    return NamedSharding(mesh, P(DATA_AXIS, seq_partition(mesh)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh):
    """Place a host-local token batch onto the ``(data, seq)`` mesh.

    Single-process (one host owns every device): a plain ``device_put``
    with :func:`seq_sharding`.  Multi-host (after
    :func:`initialize_multihost`): each process passes only ITS local
    slice of the global batch and the pieces assemble into one global
    array via ``jax.make_array_from_process_local_data`` — the dataloader
    never materializes the full global batch on any host, which at ring
    scale is the difference between feeding a 2^20-token sequence and
    OOMing the coordinator.  (The reference gathers the full batch onto
    every rank instead: ``all_gather`` in
    ``sharded_batch_to_sharded_seq``, ref ``ring_attention.py:223-262``.)

    Works on pytrees: leaves of rank >= 2 get batch over ``data`` and
    sequence over ``seq``; rank-1 leaves shard over ``data`` only;
    scalars replicate.
    """
    def place(x):
        # host-side ndarray: device_put / make_array_from_process_local_data
        # then transfer each shard directly, never staging the full array
        # through one device's HBM
        x = np.asarray(x)
        if x.ndim >= 2:
            sharding = seq_sharding(mesh)
        elif x.ndim == 1:
            sharding = NamedSharding(mesh, P(DATA_AXIS))
        else:
            sharding = replicated(mesh)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree.map(place, batch)
