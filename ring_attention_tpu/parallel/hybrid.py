"""Hybrid Ulysses x Ring 2-D sequence parallelism.

**Beyond reference parity**: the reference implements only 1-D context
parallelism (ring / zig-zag over one flat world).  Factoring the sequence
axis as ``seq = ulysses x ring`` matches each collective to its link tier
(TASP, arXiv 2509.26541; TokenRing, arXiv 2412.20501): the bandwidth-heavy
but latency-flat all-to-all runs over the *inner* ``ulysses`` axis (the
fastest-connected device groups — intra-node ICI), while the
latency-chained ring runs over the *outer* ``ring`` axis with
``ulysses_size`` x fewer hops than a pure ring at equal world size.
Per-device memory (O(n/world) KV resident, one circulating block) and
exact-attention semantics are unchanged.

Layout contract (``parallel/mesh.py::seq_axes``): the sequence dimension
shards ring-major / ulysses-minor — device ``(u, r)`` of a
``(data, ring, ulysses)`` mesh holds subchunk ``u`` of contiguous ring
chunk ``r``.  The all-to-all over ``ulysses`` (tiled, heads split / seq
concat) therefore reassembles exactly ring chunk ``r`` on every member of
the group, and the existing :func:`~.ring.ring_flash_attention` runs
unmodified over the ``ring`` sub-axis on that head subset.  Striped
(balanced-causal) layouts interleave at the OUTER ring degree only —
``stripe_permute(x, ring_size)`` — so the ring leg sees its usual striped
band math with ``world == ring_size``.

Composition, not new math: both legs already differentiate (the all-to-all
through its transpose, the ring through its ``custom_vjp``), so this module
is custom-vjp-free.  GQA with ``hk < ulysses_size`` rides
:func:`~.ulysses.kv_head_reshard` — the real heads transfer once and expand
locally, and the ring then circulates only the device's (deduplicated)
kv-head block.
"""

from __future__ import annotations

import jax
from jax import lax

from ..ops.attention import normalize_segment_ids
from ..utils import compat
from ..utils.validate import check_attention_args
from .ring import ring_flash_attention
from .ulysses import kv_head_reshard


def hybrid_attention(
    q: jax.Array,  # (b, h, n_local, d), sequence-sharded over both axes
    k: jax.Array,  # (b, hk, n_local, d)
    v: jax.Array,
    kv_mask: jax.Array | None,  # (b, n_local) key-padding shard
    ulysses_axis: str,
    ring_axis: str,
    *,
    causal: bool = False,
    striped: bool = False,
    bucket_size: int | None = None,
    max_ring_passes: int | None = None,
    window: int | None = None,
    softclamp_value: float | None = None,
    scale: float | None = None,
    impl: str = "xla",
    bidirectional: bool = False,
    dkv_dtype: str | None = None,
    segment_ids: jax.Array | None = None,
    counter_rotate: bool = False,
    hop_compression: str | None = None,
    compute_dtype: str | None = None,
) -> jax.Array:
    """2-D factored sequence-parallel exact attention; call inside
    ``shard_map`` over a ``(data, ring, ulysses)`` mesh (``ulysses``
    innermost — the fastest-varying device dimension carries the
    all-to-all).

    Three stages per layer:

    1. all-to-all q/k/v over the inner ``ulysses_axis``: each device trades
       its sequence subchunk for a head subset — ``h / U`` query heads over
       the full ring chunk (``U x`` the local sequence).
    2. :func:`~.ring.ring_flash_attention` over the outer ``ring_axis`` on
       that head subset — ``ring_size`` hops instead of ``U * ring_size``.
    3. all-to-all back to the sequence-sharded layout.

    ``kv_mask`` and ``segment_ids`` are per-token, so the inner leg
    all-gathers them (cheap: ``(b, n)`` ints) to the ring-chunk extent; the
    ring leg then circulates the kv copies per hop exactly as in the pure
    ring, including the segment-overlap hop skip.

    ``striped`` refers to the OUTER ring layout (stripe factor
    ``ring_size``); rotary positions must already be applied by the caller
    (``ops/rotary.py::hybrid_positions`` computes them from the combined
    rank).  All remaining knobs (``window`` / ``max_ring_passes`` /
    ``bidirectional`` / ``dkv_dtype`` / ``counter_rotate`` /
    ``hop_compression`` / ``compute_dtype`` / ``impl``) pass straight
    through to the ring leg
    (``impl="fused"`` runs the OUTER ring as the single-launch fused-ring
    kernel, ops/pallas_ring.py — the a2a legs are unchanged)
    and mean what they mean there, with ``n_local`` read as the
    post-all-to-all chunk (``U x`` the resident shard) — in particular the
    TokenRing counter-rotation and int8 hop compression apply to the OUTER
    ring's hops, the only latency-chained collectives of the factoring.

    Returns the ``(b, h, n_local, d)`` output shard, in ``q.dtype``.
    """
    check_attention_args("hybrid_attention", q, k, v, kv_mask, equal_qkv_len=True)
    segment_ids, _ = normalize_segment_ids(
        None if segment_ids is None else (segment_ids, segment_ids),
        q, q, "hybrid_attention",
    )
    b, h, n_local, d = q.shape
    ulysses = compat.axis_size(ulysses_axis)
    assert h % ulysses == 0, (
        f"query heads {h} must divide over the {ulysses}-device ulysses axis"
    )

    # inner leg: seq-sharded -> head-sharded over ulysses.  (b, h/U, U*n, d)
    # Scope names split XProf time between the a2a legs and the inner ring
    # (whose hops carry their own ring/hop{i} scopes nested under
    # hybrid/inner — docs/observability.md).
    with jax.named_scope("hybrid/a2a_in"):
        qh = lax.all_to_all(
            q, ulysses_axis, split_axis=1, concat_axis=2, tiled=True
        )
        kh, vh = kv_head_reshard(k, v, ulysses_axis, h)
        mask_c = (
            lax.all_gather(kv_mask, ulysses_axis, axis=1, tiled=True)
            if kv_mask is not None
            else None
        )
        seg_c = (
            lax.all_gather(segment_ids, ulysses_axis, axis=1, tiled=True)
            if segment_ids is not None
            else None
        )

    # outer leg: the existing ring over the sub-axis, on the head subset
    with jax.named_scope("hybrid/inner"):
        out = ring_flash_attention(
            qh, kh, vh, mask_c, ring_axis,
            causal=causal, striped=striped, bucket_size=bucket_size,
            max_ring_passes=max_ring_passes, window=window,
            softclamp_value=softclamp_value, scale=scale, impl=impl,
            bidirectional=bidirectional, dkv_dtype=dkv_dtype,
            segment_ids=seg_c, counter_rotate=counter_rotate,
            hop_compression=hop_compression, compute_dtype=compute_dtype,
        )

    # head-sharded -> seq-sharded
    with jax.named_scope("hybrid/a2a_out"):
        return lax.all_to_all(
            out, ulysses_axis, split_axis=2, concat_axis=1, tiled=True
        )
