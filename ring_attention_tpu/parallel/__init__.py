from .collectives import (
    all_gather_variable,
    axis_rank,
    axis_world,
    compact_masked,
    fold_batch_into_seq,
    gather_sizes,
    split_by_rank,
    unfold_seq_into_batch,
)
from .mesh import (
    DATA_AXIS,
    SEQ_AXIS,
    create_mesh,
    initialize_multihost,
    replicated,
    seq_sharding,
    shard_batch,
)
from .ring import ring_flash_attention
from .tree_decode import tree_attn_decode
from .ulysses import ulysses_attention
from .zigzag import (
    zigzag_attention,
    zigzag_permute,
    zigzag_positions,
    zigzag_unpermute,
)
from .sharding import (
    pad_seq_and_mask,
    pad_to_multiple,
    stripe_permute,
    stripe_unpermute,
)

__all__ = [
    "all_gather_variable",
    "axis_rank",
    "axis_world",
    "compact_masked",
    "fold_batch_into_seq",
    "gather_sizes",
    "split_by_rank",
    "unfold_seq_into_batch",
    "DATA_AXIS",
    "SEQ_AXIS",
    "create_mesh",
    "initialize_multihost",
    "replicated",
    "seq_sharding",
    "shard_batch",
    "ring_flash_attention",
    "tree_attn_decode",
    "ulysses_attention",
    "zigzag_attention",
    "zigzag_permute",
    "zigzag_positions",
    "zigzag_unpermute",
    "pad_seq_and_mask",
    "pad_to_multiple",
    "stripe_permute",
    "stripe_unpermute",
]
