from .mesh import DATA_AXIS, SEQ_AXIS, create_mesh, replicated, seq_sharding
from .ring import ring_flash_attention
from .tree_decode import tree_attn_decode
from .zigzag import (
    zigzag_attention,
    zigzag_permute,
    zigzag_positions,
    zigzag_unpermute,
)
from .sharding import (
    pad_seq_and_mask,
    pad_to_multiple,
    stripe_permute,
    stripe_unpermute,
)

__all__ = [
    "DATA_AXIS",
    "SEQ_AXIS",
    "create_mesh",
    "replicated",
    "seq_sharding",
    "ring_flash_attention",
    "tree_attn_decode",
    "zigzag_attention",
    "zigzag_permute",
    "zigzag_positions",
    "zigzag_unpermute",
    "pad_seq_and_mask",
    "pad_to_multiple",
    "stripe_permute",
    "stripe_unpermute",
]
