"""Ulysses-style sequence parallelism: all-to-all over heads.

**Beyond reference parity**: the reference implements only ring/zig-zag
context parallelism and explicitly lacks Ulysses (SURVEY §2.2, "not
implemented").  Ulysses (DeepSpeed, arXiv 2309.14509) trades the ring's
O(ring) latency chain for two all-to-alls: resharding activations from
sequence-sharded to head-sharded, running plain full-sequence flash
attention on each device's head subset, and resharding back.  On TPU both
all-to-alls ride ICI and XLA overlaps them with the surrounding matmuls;
for moderate sequence lengths this often beats the ring, while the ring
wins when ``heads < devices`` or sequences no longer fit per-device.

Composable with the rest of the stack: same layout convention, same flash
kernels underneath (``impl="xla" | "pallas"``), differentiable through
``lax.all_to_all``'s transpose.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import compat
from ..ops.attention import normalize_segment_ids
from ..ops.flash import flash_attention
from ..ops.pallas_flash import pallas_flash_attention
from ..utils.validate import check_attention_args


def ulysses_attention(
    q: jax.Array,  # (b, h, n_local, d), sequence-sharded
    k: jax.Array,  # (b, hk, n_local, d)
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    kv_mask: jax.Array | None = None,  # (b, n_local) sequence-sharded
    bucket_size: int | None = None,
    window: int | None = None,
    softclamp_value: float | None = None,
    scale: float | None = None,
    impl: str = "xla",
    segment_ids: jax.Array | None = None,
) -> jax.Array:
    """Head-parallel exact attention; call inside ``shard_map``.

    Requires ``h % world == 0`` (each device takes ``h/world`` query heads
    against the full sequence).  When ``hk`` does not divide over the axis
    (small-hk GQA), KV heads are auto-repeated up to the axis size — grads
    sum back over the copies.  Sequence layout is contiguous (no striping
    needed — head parallelism is inherently balanced under causal masking).

    ``segment_ids``: optional ``(b, n_local)`` int document-id shard for
    packed sequences; all-gathered (like ``kv_mask``) since each device
    attends the full sequence after the all-to-all.
    """
    check_attention_args("ulysses_attention", q, k, v, kv_mask, equal_qkv_len=True)
    segment_ids, _ = normalize_segment_ids(
        None if segment_ids is None else (segment_ids, segment_ids),
        q, q, "ulysses_attention",
    )
    b, h, n_local, d = q.shape
    hk = k.shape[1]
    world = compat.axis_size(axis_name)
    assert h % world == 0, f"query heads {h} must divide over {world} devices"

    if hk % world:
        # GQA with fewer KV heads than the axis size: repeat each KV head
        # r times so heads divide over the devices.  jnp.repeat keeps copies
        # of head i contiguous, so query heads [i*g, (i+1)*g) still map onto
        # copies of their own KV head after the all-to-all head split; the
        # transpose of the repeat sums dk/dv back over the copies (the
        # reference's GQA grad-reduce contract,
        # ref ring_flash_attention.py:86-89,370-371).
        gcd = math.gcd(hk, world)
        r = world // gcd
        g = h // hk
        assert g % r == 0, (
            f"cannot serve GQA with {hk} kv heads on a {world}-device ulysses "
            f"axis: repeating kv heads x{r} needs the group size {g} to be a "
            f"multiple of {r}"
        )
        k = jnp.repeat(k, r, axis=1)
        v = jnp.repeat(v, r, axis=1)
        hk = hk * r

    # seq-sharded -> head-sharded: (b, h/W, n_global, d)
    qh = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2, tiled=True)
    mask_full = (
        lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)
        if kv_mask is not None
        else None
    )
    seg_full = (
        lax.all_gather(segment_ids, axis_name, axis=1, tiled=True)
        if segment_ids is not None
        else None
    )

    if impl == "pallas":
        out = pallas_flash_attention(
            qh, kh, vh, mask_full, causal=causal, window=window,
            softclamp_value=softclamp_value, scale=scale,
            segment_ids=seg_full,
        )
    else:
        out = flash_attention(
            qh, kh, vh, mask_full, causal=causal, bucket_size=bucket_size,
            window=window, softclamp_value=softclamp_value, scale=scale,
            segment_ids=seg_full,
        )

    # head-sharded -> seq-sharded
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1, tiled=True)
