"""Ulysses-style sequence parallelism: all-to-all over heads.

**Beyond reference parity**: the reference implements only ring/zig-zag
context parallelism and explicitly lacks Ulysses (SURVEY §2.2, "not
implemented").  Ulysses (DeepSpeed, arXiv 2309.14509) trades the ring's
O(ring) latency chain for two all-to-alls: resharding activations from
sequence-sharded to head-sharded, running plain full-sequence flash
attention on each device's head subset, and resharding back.  On TPU both
all-to-alls ride ICI and XLA overlaps them with the surrounding matmuls;
for moderate sequence lengths this often beats the ring, while the ring
wins when ``heads < devices`` or sequences no longer fit per-device.

Composable with the rest of the stack: same layout convention, same flash
kernels underneath (``impl="xla" | "pallas"``), differentiable through
``lax.all_to_all``'s transpose.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import compat
from ..ops.attention import normalize_segment_ids
from ..ops.flash import flash_attention
from ..ops.pallas_flash import pallas_flash_attention
from ..utils.validate import check_attention_args


def kv_head_reshard(
    k: jax.Array,  # (b, hk, n_local, d), sequence-sharded
    v: jax.Array,
    axis_name: str,
    h: int,
) -> tuple[jax.Array, jax.Array]:
    """Reshard K/V from sequence-sharded to head-sharded over ``axis_name``.

    ``hk % world == 0``: a plain tiled all-to-all — each device ends up
    with ``hk / world`` kv heads over the full axis-local sequence.

    Small-hk GQA (``hk % world != 0``, typically ``hk < world``): the old
    path repeated kv heads up to the axis size and all-to-all'ed the
    copies, paying ``world / gcd(hk, world)`` x the real KV bytes on the
    wire.  Instead, transfer the real ``hk`` heads exactly once — an
    all-gather along the sequence — and expand to this device's head block
    *locally* after the collective.  The backward stays correct with no
    custom vjp: the local expand transposes to a scatter-add over the
    copies and the all-gather transposes to a psum-scatter, so dk/dv sum
    over every consumer (the reference's GQA grad-reduce contract, ref
    ``ring_flash_attention.py:86-89,370-371``).

    Returns ``(k, v)`` shaped ``(b, hk_local, world * n_local, d)`` where
    the local query-head block ``[rank * h/world, (rank+1) * h/world)``
    maps onto ``hk_local`` via the standard grouped convention
    (``q head j -> kv head j // (h_local // hk_local)``).
    """
    hk = k.shape[1]
    world = compat.axis_size(axis_name)
    if hk % world == 0:
        with jax.named_scope("kv_head_reshard/a2a"):
            kh = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2, tiled=True)
            vh = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2, tiled=True)
        return kh, vh
    assert h % world == 0, f"query heads {h} must divide over {world} devices"
    g = h // hk  # query heads per kv head
    hql = h // world  # query heads per device
    rank = lax.axis_index(axis_name)
    with jax.named_scope("kv_head_reshard/gather"):
        k_full = lax.all_gather(k, axis_name, axis=2, tiled=True)
        v_full = lax.all_gather(v, axis_name, axis=2, tiled=True)
    if hql <= g and g % hql == 0:
        # every query head on this device shares ONE kv head (hk divides
        # world): slice it — the ulysses flash (and any downstream ring)
        # then reads/circulates exactly one head's worth of KV
        start = (rank * hql) // g
        kh = lax.dynamic_slice_in_dim(k_full, start, 1, axis=1)
        vh = lax.dynamic_slice_in_dim(v_full, start, 1, axis=1)
    else:
        # unaligned group boundaries: one kv copy per local query head
        # (group size 1) — always correct, duplicates only within a device
        idx = (rank * hql + jnp.arange(hql)) // g
        kh = jnp.take(k_full, idx, axis=1)
        vh = jnp.take(v_full, idx, axis=1)
    return kh, vh


def ulysses_attention(
    q: jax.Array,  # (b, h, n_local, d), sequence-sharded
    k: jax.Array,  # (b, hk, n_local, d)
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    kv_mask: jax.Array | None = None,  # (b, n_local) sequence-sharded
    bucket_size: int | None = None,
    window: int | None = None,
    softclamp_value: float | None = None,
    scale: float | None = None,
    impl: str = "xla",
    segment_ids: jax.Array | None = None,
) -> jax.Array:
    """Head-parallel exact attention; call inside ``shard_map``.

    Requires ``h % world == 0`` (each device takes ``h/world`` query heads
    against the full sequence).  When ``hk`` does not divide over the axis
    (small-hk GQA), the real KV heads transfer once and repeat locally —
    grads sum back over the copies.  Sequence layout is contiguous (no
    striping needed — head parallelism is inherently balanced under causal
    masking).

    ``segment_ids``: optional ``(b, n_local)`` int document-id shard for
    packed sequences; all-gathered (like ``kv_mask``) since each device
    attends the full sequence after the all-to-all.

    Small-hk GQA (``hk % world != 0``) ships the real ``hk`` heads once
    and expands locally after the collective — see :func:`kv_head_reshard`.
    """
    check_attention_args("ulysses_attention", q, k, v, kv_mask, equal_qkv_len=True)
    segment_ids, _ = normalize_segment_ids(
        None if segment_ids is None else (segment_ids, segment_ids),
        q, q, "ulysses_attention",
    )
    b, h, n_local, d = q.shape
    world = compat.axis_size(axis_name)
    assert h % world == 0, f"query heads {h} must divide over {world} devices"

    # seq-sharded -> head-sharded: (b, h/W, n_global, d).  Stable scope
    # names attribute XProf time to the a2a legs vs the local flash
    # (docs/observability.md).
    with jax.named_scope("ulysses/a2a_in"):
        qh = lax.all_to_all(
            q, axis_name, split_axis=1, concat_axis=2, tiled=True
        )
        kh, vh = kv_head_reshard(k, v, axis_name, h)
        mask_full = (
            lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)
            if kv_mask is not None
            else None
        )
        seg_full = (
            lax.all_gather(segment_ids, axis_name, axis=1, tiled=True)
            if segment_ids is not None
            else None
        )

    with jax.named_scope("ulysses/flash"):
        if impl == "pallas":
            out = pallas_flash_attention(
                qh, kh, vh, mask_full, causal=causal, window=window,
                softclamp_value=softclamp_value, scale=scale,
                segment_ids=seg_full,
            )
        else:
            out = flash_attention(
                qh, kh, vh, mask_full, causal=causal, bucket_size=bucket_size,
                window=window, softclamp_value=softclamp_value, scale=scale,
                segment_ids=seg_full,
            )

    # head-sharded -> seq-sharded
    with jax.named_scope("ulysses/a2a_out"):
        return lax.all_to_all(
            out, axis_name, split_axis=2, concat_axis=1, tiled=True
        )
