"""Tree-attention single-token decoding over sharded KV.

TPU-native equivalent of the reference's ``tree_attn_decoding.py``: at decode
time the query is one token (replicated) while the KV cache is sharded over
devices; each device computes its local flash partial ``(acc, m, l)`` and the
partials merge with three collectives — MAX over the running max, SUM over
the rescaled numerator and denominator (ref ``tree_attn_decoding.py:87-102``).

On a TPU pod ``pmax``/``psum`` ride ICI with topology-aware reductions, the
two-level tree the paper (and the reference's comment) describe — XLA builds
the hierarchy, no hand-written intra/inter-node split needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import EPSILON
from ..ops.flash import attend_blocks, init_carry, _ungroup
from ..ops.pallas_flash import pallas_flash_decode
from ..utils.validate import check_attention_args


def tree_attn_decode(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None = None,
    *,
    axis_name: str,
    bucket_size: int | None = None,
    softclamp_value: float | None = None,
    scale: float | None = None,
    impl: str = "xla",
) -> jax.Array:
    """Single(-few)-token decode attention; call inside ``shard_map``.

    Args:
      q: ``(b, h, nq, d)`` queries, replicated across ``axis_name``
        (``nq`` is typically 1).
      k, v: ``(b, hk, n_local, d)`` local KV-cache shards (GQA supported).
      kv_mask: optional ``(b, n_local)`` mask for padded cache slots —
        the static-shape answer to the reference's ragged "rank holds no KV"
        edge case (ref ``tree_attn_decoding.py:81-85``): pad the cache and
        mask the tail.
      impl: local-partial compute path.  ``"xla"`` = blockwise jnp sweep;
        ``"pallas"`` = :func:`~ring_attention_tpu.ops.pallas_flash.pallas_flash_decode`,
        which reads each cache byte exactly once per kv head (decode is
        HBM-bandwidth-bound; the training kernels re-fetch KV per query
        head under GQA).

    Returns:
      ``(b, h, nq, d)`` decoded output, replicated across ``axis_name``.
    """
    check_attention_args("tree_attn_decode", q, k, v, kv_mask)
    b, h, nq, d = q.shape
    hk = k.shape[1]
    g = h // hk
    if scale is None:
        scale = d**-0.5

    # local online-softmax partial over the KV shard
    if impl == "pallas":
        acc, m, l = pallas_flash_decode(
            q, k, v, kv_mask,
            scale=scale, softclamp_value=softclamp_value,
            block_k=bucket_size, fused=False,
        )
    else:
        carry = init_carry(b, hk, g, nq, d, like=k)
        carry = attend_blocks(
            q, k, v, carry,
            scale=scale, bucket_size=bucket_size, kv_mask=kv_mask,
            softclamp_value=softclamp_value,
        )
        acc, m, l = carry

    # three-collective merge (ref tree_attn_decoding.py:89-100)
    m_global = lax.pmax(m, axis_name)
    correction = jnp.exp(m - m_global)
    num = lax.psum(acc * correction[..., None], axis_name)
    den = lax.psum(l * correction, axis_name)
    out = num / jnp.maximum(den, EPSILON)[..., None]
    return _ungroup(out).astype(q.dtype)
