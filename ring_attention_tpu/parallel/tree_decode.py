"""Tree-attention single-token decoding over sharded KV.

TPU-native equivalent of the reference's ``tree_attn_decoding.py``: at decode
time the query is one token (replicated) while the KV cache is sharded over
devices; each device computes its local flash partial ``(acc, m, l)`` and the
partials merge with three collectives — MAX over the running max, SUM over
the rescaled numerator and denominator (ref ``tree_attn_decoding.py:87-102``).

On a TPU pod ``pmax``/``psum`` ride ICI with topology-aware reductions, the
two-level tree the paper (and the reference's comment) describe — XLA builds
the hierarchy, no hand-written intra/inter-node split needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import EPSILON
from ..ops.flash import attend_blocks, init_carry, _ungroup
from ..ops.pallas_flash import (
    QuantizedKV,
    dequantize_kv_cache,
    pallas_flash_decode,
    pallas_flash_decode_q8,
)
from ..utils.validate import check_attention_args


def tree_attn_decode(
    q: jax.Array,
    k: jax.Array | None,
    v: jax.Array | None,
    kv_mask: jax.Array | None = None,
    *,
    axis_name: str,
    bucket_size: int | None = None,
    softclamp_value: float | None = None,
    scale: float | None = None,
    impl: str | None = None,
    kv_quantized: QuantizedKV | None = None,
) -> jax.Array:
    """Single(-few)-token decode attention; call inside ``shard_map``.

    Args:
      q: ``(b, h, nq, d)`` queries, replicated across ``axis_name``
        (``nq`` is typically 1).
      k, v: ``(b, hk, n_local, d)`` local KV-cache shards (GQA supported).
      kv_mask: optional ``(b, n_local)`` mask for padded cache slots —
        the static-shape answer to the reference's ragged "rank holds no KV"
        edge case (ref ``tree_attn_decoding.py:81-85``): pad the cache and
        mask the tail.
      impl: local-partial compute path.  ``"xla"`` = blockwise jnp sweep;
        ``"pallas"`` = :func:`~ring_attention_tpu.ops.pallas_flash.pallas_flash_decode`,
        which reads each cache byte exactly once per kv head (decode is
        HBM-bandwidth-bound; the training kernels re-fetch KV per query
        head under GQA).  ``None`` (default) = ``"xla"`` for a plain
        cache, the q8 pallas kernel when ``kv_quantized`` is given.
      kv_quantized: int8 local cache shard
        (:func:`~ring_attention_tpu.ops.pallas_flash.quantize_kv_cache`);
        when given, ``k``/``v`` must be None and the local partial runs
        :func:`~ring_attention_tpu.ops.pallas_flash.pallas_flash_decode_q8`
        (1.88x fewer cache HBM bytes per step).  An explicit
        ``impl="xla"`` is honored by dequantizing the cache and running
        the jnp sweep instead.

    Returns:
      ``(b, h, nq, d)`` decoded output, replicated across ``axis_name``.
    """
    b, h, nq, d = q.shape
    if scale is None:
        scale = d**-0.5

    if impl not in (None, "xla", "pallas"):
        raise ValueError(f"tree_attn_decode: unknown impl {impl!r}")

    # local online-softmax partial over the KV shard
    if kv_quantized is not None:
        if k is not None or v is not None:
            raise ValueError(
                "tree_attn_decode: pass either k/v or kv_quantized, not both"
            )
        # mirror check_attention_args' layout contract for the int8 cache
        kq = kv_quantized.k_q
        if q.ndim != 4 or kq.ndim != 4:
            raise ValueError(
                "tree_attn_decode: q and kv_quantized.k_q must be "
                "(batch, heads, seq, dim) — a (batch, seq, heads, dim) "
                f"call usually trips this (got q {q.shape}, k_q {kq.shape})"
            )
        if (q.shape[0] != kq.shape[0] or q.shape[3] != kq.shape[3]
                or q.shape[1] % kq.shape[1]):
            raise ValueError(
                f"tree_attn_decode: q {q.shape} incompatible with int8 "
                f"cache {kq.shape} (batch/dim must match, heads must be a "
                f"multiple of kv heads)"
            )
        if kv_mask is not None and kv_mask.shape != (kq.shape[0], kq.shape[2]):
            raise ValueError(
                f"tree_attn_decode: kv_mask must be (batch, seq_local) = "
                f"{(kq.shape[0], kq.shape[2])}, got {kv_mask.shape}"
            )
        if impl == "xla":
            # honor the explicit XLA request: materialize the KV and fall
            # through to the jnp sweep instead of silently running pallas
            k, v = dequantize_kv_cache(kv_quantized, q.dtype)
            kv_quantized = None

    with jax.named_scope("tree_decode/local"):
        if kv_quantized is not None:
            acc, m, l = pallas_flash_decode_q8(
                q, kv_quantized, kv_mask,
                scale=scale, softclamp_value=softclamp_value,
                block_k=bucket_size, fused=False,
            )
        elif impl == "pallas":
            check_attention_args("tree_attn_decode", q, k, v, kv_mask)
            acc, m, l = pallas_flash_decode(
                q, k, v, kv_mask,
                scale=scale, softclamp_value=softclamp_value,
                block_k=bucket_size, fused=False,
            )
        else:
            check_attention_args("tree_attn_decode", q, k, v, kv_mask)
            hk = k.shape[1]
            g = h // hk
            carry = init_carry(b, hk, g, nq, d, like=k)
            carry = attend_blocks(
                q, k, v, carry,
                scale=scale, bucket_size=bucket_size, kv_mask=kv_mask,
                softclamp_value=softclamp_value,
            )
            acc, m, l = carry

    # three-collective merge (ref tree_attn_decoding.py:89-100); the
    # scope is the decode step's collective cost in an XProf capture
    with jax.named_scope("tree_decode/gather"):
        m_global = lax.pmax(m, axis_name)
        correction = jnp.exp(m - m_global)
        num = lax.psum(acc * correction[..., None], axis_name)
        den = lax.psum(l * correction, axis_name)
        out = num / jnp.maximum(den, EPSILON)[..., None]
    return _ungroup(out).astype(q.dtype)
