"""Zig-zag context parallelism (Llama-3 style CP).

TPU-native equivalent of the reference's ``zig_zag_attention.py``: the
sequence is cut into ``2 * ring_size`` chunks and device ``r`` owns chunks
``(r, 2W-1-r)`` so causal work is balanced (ref ``zig_zag_attention.py:65-69``);
attention all-gathers K/V over the sequence axis and applies an explicit
causal mask derived from chunk positions (ref ``zig_zag_attention.py:121-139``).

Differences by design:
  - the chunk permutation is a pure static reshape/transpose applied to the
    global array before sharding (no gather pipeline, no closure-based
    inverse — ref ``zig_zag_attention.py:84-98``);
  - inside ``shard_map`` the gathered K/V are un-permuted back to canonical
    order (static slice reorder), so the causal mask for each of the two
    local query chunks is a plain end-aligned band and the compute reuses
    the blockwise flash kernel (``ops/flash.py``) instead of materializing
    an ``(n_local, n_global)`` boolean mask;
  - gradients flow through ``lax.all_gather``'s transpose (reduce-scatter),
    the analogue of the reference's autograd AllGather backward
    (ref ``distributed.py:103-107``).
"""

from __future__ import annotations

import warnings
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import compat
from ..ops.attention import normalize_segment_ids
from ..ops.flash import attend_blocks, finalize, init_carry, _ungroup
from ..ops.pallas_flash import (
    finalize_partials,
    pallas_flash_backward,
    pallas_flash_partials,
)
from ..utils.validate import check_attention_args


def zigzag_permute(x: jax.Array, ring_size: int, axis: int = 1) -> jax.Array:
    """Reorder sequence chunks ``[0..2W)`` to ``[0, 2W-1, 1, 2W-2, ...]``.

    Sharding the result contiguously over ``W`` devices gives device ``r``
    chunks ``(r, 2W-1-r)`` (ref ``zig_zag_attention.py:65-69``).
    """
    n = x.shape[axis]
    assert n % (2 * ring_size) == 0, "sequence must divide into 2*ring chunks"
    chunk = n // (2 * ring_size)
    idx = []
    for r in range(ring_size):
        idx.extend([r, 2 * ring_size - 1 - r])
    x = _chunk_take(x, idx, chunk, axis)
    return x


def zigzag_unpermute(x: jax.Array, ring_size: int, axis: int = 1) -> jax.Array:
    """Inverse of :func:`zigzag_permute`."""
    n = x.shape[axis]
    chunk = n // (2 * ring_size)
    order = []
    for r in range(ring_size):
        order.extend([r, 2 * ring_size - 1 - r])
    inv = [0] * len(order)
    for pos, c in enumerate(order):
        inv[c] = pos
    return _chunk_take(x, inv, chunk, axis)


def _chunk_take(x: jax.Array, chunk_order: list[int], chunk: int, axis: int) -> jax.Array:
    shape = list(x.shape)
    nchunks = len(chunk_order)
    x = x.reshape(shape[:axis] + [nchunks, chunk] + shape[axis + 1 :])
    x = jnp.take(x, jnp.asarray(chunk_order), axis=axis)
    return x.reshape(shape)


def zigzag_positions(n_local: int, rank: jax.Array, ring_size: int) -> jax.Array:
    """Global token positions of a zig-zag shard (for rotary / masks).

    Local layout is ``[chunk rank, chunk 2W-1-rank]``; the reference returns
    the same indices from ``zig_zag_shard`` (ref ``zig_zag_attention.py:73-80``).
    """
    chunk = n_local // 2
    i = jnp.arange(chunk)
    first = rank * chunk + i
    second = (2 * ring_size - 1 - rank) * chunk + i
    return jnp.concatenate([first, second])


@partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _pallas_chunk_attention(qc, k_all, v_all, qc_seg, kv_seg, causal_offset,
                            scale, softclamp_value, block):
    """Differentiable Pallas attention of one zig-zag query chunk against the
    gathered canonical KV.  ``causal_offset`` is the chunk's global start
    (traced — it depends on the device's rank); dk/dv flow into the
    enclosing ``lax.all_gather``'s transpose (reduce-scatter), the analogue
    of the reference's autograd AllGather backward (ref distributed.py:103-107).
    ``qc_seg``/``kv_seg`` are the chunk's / gathered canonical segment ids
    for packed sequences (None when unsegmented)."""
    out, _ = _pallas_chunk_fwd_impl(
        qc, k_all, v_all, qc_seg, kv_seg, causal_offset, scale,
        softclamp_value, block
    )
    return out


def _seg_pair(q_seg, kv_seg):
    return None if q_seg is None else (q_seg, kv_seg)


def _pallas_chunk_fwd_impl(qc, k_all, v_all, qc_seg, kv_seg, causal_offset,
                           scale, softclamp_value, block):
    parts = pallas_flash_partials(
        qc, k_all, v_all,
        scale=scale, causal_offset=causal_offset,
        softclamp_value=softclamp_value,
        block_q=block, block_k=block,
        segment_ids=_seg_pair(qc_seg, kv_seg),
    )
    out, lse = finalize_partials(parts)
    return out, lse


def _pallas_chunk_vjp_fwd(qc, k_all, v_all, qc_seg, kv_seg, causal_offset,
                          scale, softclamp_value, block):
    out, lse = _pallas_chunk_fwd_impl(
        qc, k_all, v_all, qc_seg, kv_seg, causal_offset, scale,
        softclamp_value, block
    )
    return out, (qc, k_all, v_all, qc_seg, kv_seg, causal_offset, out, lse)


def _pallas_chunk_vjp_bwd(scale, softclamp_value, block, res, do):
    qc, k_all, v_all, qc_seg, kv_seg, causal_offset, out, lse = res
    delta = (do.astype(jnp.float32) * out).sum(-1)
    dq, dk, dv = pallas_flash_backward(
        do, qc, k_all, v_all, lse, delta,
        scale=scale, causal_offset=causal_offset,
        softclamp_value=softclamp_value,
        block_q=block, block_k=block,
        segment_ids=_seg_pair(qc_seg, kv_seg),
    )
    return (dq.astype(qc.dtype), dk.astype(k_all.dtype),
            dv.astype(v_all.dtype), None, None, None)


_pallas_chunk_attention.defvjp(_pallas_chunk_vjp_fwd, _pallas_chunk_vjp_bwd)


# Trace-time warning threshold for the per-device gathered KV (bytes).
# Zig-zag faithfully mirrors the reference's all-gather design
# (ref ``zig_zag_attention.py:121-127``): every device materializes the
# FULL global K and V, an O(n_global) memory profile — ~537 MB/layer at
# 262k tokens (hk=8, d=64, bf16) and 2.1 GB/layer at 1M.  A "chunked
# gather" variant was considered and REJECTED: gathering KV chunk-by-chunk
# over the axis while accumulating online-softmax partials is exactly ring
# attention, which this framework already ships with compute/transfer
# overlap and O(n_local) memory (``parallel/ring.py``).  When the warning
# below fires, the answer is ``sequence_parallel="ring"``, not a slower
# re-implementation of it inside the zig-zag scheme.
GATHERED_KV_BUDGET_BYTES = 2 * 1024**3


def zigzag_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = True,
    bucket_size: int | None = None,
    softclamp_value: float | None = None,
    scale: float | None = None,
    impl: str = "xla",
    gathered_kv_budget: int | None = GATHERED_KV_BUDGET_BYTES,
    segment_ids: jax.Array | None = None,
) -> jax.Array:
    """Zig-zag sharded attention; call inside ``shard_map``.

    ``q, k, v: (b, [h|hk], n_local, d)`` local shards in zig-zag layout
    (``n_local = 2 * chunk``).  K/V are all-gathered over ``axis_name`` and
    un-permuted to canonical order; each local query chunk then attends its
    end-aligned causal prefix via blockwise flash (``impl="xla"``) or the
    Pallas kernels (``impl="pallas"``).

    ``segment_ids``: optional ``(b, n_local)`` int document-id shard (in
    zig-zag layout, like q) for packed sequences; gathered and un-permuted
    alongside K/V so each chunk masks cross-document attention.

    ``gathered_kv_budget``: warn at trace time when the per-device gathered
    K+V exceed this many bytes (``None`` disables) — see
    :data:`GATHERED_KV_BUDGET_BYTES` for why the fix is the ring scheme,
    not a chunked gather.
    """
    assert causal, "zig-zag CP is a causal-load-balancing scheme (ref zig_zag_attention.py:102-103)"
    check_attention_args("zigzag_attention", q, k, v, equal_qkv_len=True)
    segment_ids, _ = normalize_segment_ids(
        None if segment_ids is None else (segment_ids, segment_ids),
        q, q, "zigzag_attention",
    )
    b, h, n_local, d = q.shape
    hk = k.shape[1]
    g = h // hk
    if scale is None:
        scale = d**-0.5
    ring_size = compat.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    chunk = n_local // 2

    gathered_bytes = 2 * k.size * ring_size * k.dtype.itemsize  # k+v, global
    if gathered_kv_budget is not None and gathered_bytes > gathered_kv_budget:
        warnings.warn(
            f"zigzag_attention gathers {gathered_bytes / 2**30:.2f} GiB of "
            f"global K+V onto EVERY device (O(n_global) by design, ref "
            f"zig_zag_attention.py:121-127) — over the "
            f"{gathered_kv_budget / 2**30:.2f} GiB budget. For long "
            f"sequences use sequence_parallel='ring' (O(n_local) memory, "
            f"overlapped transfers) instead of zig-zag",
            stacklevel=2,
        )

    # gather K/V over sequence: (b, hk, n_global, d) in zig-zag shard order
    with jax.named_scope("zigzag/gather"):
        k_all = lax.all_gather(k, axis_name, axis=2, tiled=True)
        v_all = lax.all_gather(v, axis_name, axis=2, tiled=True)
        # static un-permute back to canonical sequence order
        k_all = zigzag_unpermute(k_all, ring_size, axis=2)
        v_all = zigzag_unpermute(v_all, ring_size, axis=2)
        seg_all = None
        if segment_ids is not None:
            seg_all = lax.all_gather(
                segment_ids, axis_name, axis=1, tiled=True
            )
            seg_all = zigzag_unpermute(seg_all, ring_size, axis=1)

    # flash tile over the gathered keys: largest divisor of the global length
    n_global = k_all.shape[2]
    if bucket_size is not None:
        bucket = min(bucket_size, n_global)
        while n_global % bucket:
            bucket -= 1
    else:
        bucket = None

    outs = []
    for which, start_expr in enumerate(
        (rank * chunk, (2 * ring_size - 1 - rank) * chunk)
    ):
        qc = lax.dynamic_slice_in_dim(q, which * chunk, chunk, axis=2)
        qc_seg = (
            lax.dynamic_slice_in_dim(segment_ids, which * chunk, chunk, axis=1)
            if segment_ids is not None
            else None
        )
        # causal band, end-aligned to the chunk's global end: local row i
        # (global start_expr + i) sees keys j <= start_expr + i
        with jax.named_scope(f"zigzag/chunk{which}"):
            if impl == "pallas":
                outs.append(
                    _pallas_chunk_attention(
                        qc, k_all, v_all, qc_seg, seg_all, start_expr, scale,
                        softclamp_value, bucket,
                    )
                )
            else:
                carry = init_carry(b, hk, g, chunk, d, like=qc)
                carry = attend_blocks(
                    qc, k_all, v_all, carry,
                    scale=scale, bucket_size=bucket,
                    causal_offset=start_expr,
                    softclamp_value=softclamp_value,
                    q_segment_ids=qc_seg, kv_segment_ids=seg_all,
                )
                out_g, _ = finalize(carry)
                outs.append(_ungroup(out_g))
    return jnp.concatenate(outs, axis=2).astype(q.dtype)
