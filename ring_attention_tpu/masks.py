"""Certified block-sparse mask algebra.

Splash-attention (SNIPPETS.md [3]) showed that long-context attention
workloads are really a composable *mask algebra* — causal bands, local
windows, prefix-LM bidirectionality, per-head mixtures, document
packings — and that the win is resolving each mask into block-sparse
kernel work at trace time.  This module is that algebra for this repo,
built around the two seams the earlier PRs proved out:

  - every mask carries an **oracle**: an exact predicate over *global*
    ``(q_pos, k_pos, head)`` coordinates (``Mask.oracle``) — the ground
    truth the certifier holds every lowering to;
  - every mask carries a **lowering**: compact ``BandPlan``-style tile
    tables plus per-hop work/skip schedules for each execution geometry
    (``lower`` over a :class:`GridSpec` — single sweep, ring hops in
    contiguous or striped layout, TokenRing counter-rotation; q-major
    AND k-major tables for the backward passes).  Band-shaped masks
    lower through the REAL seams — ``ops.pallas_flash.band_plan`` and
    the hop-band helpers of ``parallel/ring.py`` — so certifying them
    certifies the shipping kernels' grids; other masks lower through
    the generic tile classifier here (closed forms per leaf, exact
    refinement at combinators), the extension seam for future kernels.

The certifying-compiler contract: a lowering is only *admitted* with a
machine-checked certificate (``certify`` -> ``analysis/coverage.py``'s
prover) that it is **sound** (no live tile skipped, edge masks
elementwise-equal to the oracle), **tight** (no dead tile visited,
closed-form tile count == enumeration), and **complete** (each element
enters the online softmax exactly once across hops).  Certificates are
computed at trace time on first use and cached by
``(mask, shape, blocks, strategy, layout)`` — in memory and optionally
on disk next to the compile cache — so the proof is paid once; an
uncertifiable lowering raises :class:`MaskCertificationError` with a
one-line diagnostic naming the mask, hop, and tile.

Execution wiring: masks whose canonical form the kernels already speak
(``Causal``, causal sliding windows, document packings, runtime
segments) map onto the existing knobs via :func:`kernel_form` and run
the proven fast paths (``ops.attention(mask=...)``,
``RingAttention(mask=...)``, ``causal=True`` is sugar for ``Causal()``).
Masks beyond the kernel surface (prefix-LM, dilated, per-head, ``Or``/
``Not`` compositions) still certify and lower to grids — the
:class:`MaskLoweringError` they raise at execution names exactly what
the kernels support today.

Elementwise certificates are enumerated up to ``CERT_ELEMENTWISE_MAX``
total positions per side; larger calls are proven on the leading
``CERT_ELEMENTWISE_MAX`` positions plus the closed-form-vs-enumeration
tile accounting at the full shape (the CPU-countable half that
``bench.py``'s ``window262k`` phase reports at 262144).  Pure numpy at
module level; jax/kernel imports stay inside functions.

See ``docs/masks.md`` for the lowering table per strategy and the
certification semantics.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from bisect import bisect_right
from dataclasses import dataclass, field

import numpy as np

__all__ = [
    "Mask", "Full", "Causal", "SlidingWindow", "Dilated", "Striped",
    "PrefixLM", "DocumentMask", "Segments", "PerHead",
    "And", "Or", "Not",
    "GridSpec", "KernelForm", "Certificate",
    "MaskLoweringError", "MaskCertificationError", "MaskParseError",
    "band_form", "kernel_form", "lower", "certify", "require_certified",
    "parse_mask", "MASK_REGISTRY", "dense_mask",
]

# Above this many positions per side, certify() proves the elementwise
# half on the leading CERT_ELEMENTWISE_MAX positions and the tile
# accounting at the full shape (an O(n^2) oracle at 262k is 6.9e10
# elements — not a trace-time cost anyone should pay).
CERT_ELEMENTWISE_MAX = 2048


class MaskLoweringError(ValueError):
    """The mask has no lowering for the requested target (named in the
    message, along with the forms the target supports)."""


class MaskCertificationError(ValueError):
    """A lowering failed its soundness/tightness/completeness proof.
    The message is the first violation line: mask, hop, tile."""


class MaskParseError(ValueError):
    """A textual mask expression did not parse; lists the registry."""


# ---------------------------------------------------------------------------
# The algebra
# ---------------------------------------------------------------------------


class Mask:
    """Base class: combinators plus the oracle/lowering contract.

    Subclasses are frozen dataclasses (hashable — they key the
    certificate cache and sit as static flax module attributes).
    """

    def __and__(self, other: "Mask") -> "Mask":
        return And((self, other))

    def __or__(self, other: "Mask") -> "Mask":
        return Or((self, other))

    def __invert__(self) -> "Mask":
        return Not(self)

    # -- oracle ---------------------------------------------------------
    def oracle(self, qpos, kpos, head: int = 0, doc_ids=None) -> np.ndarray:
        """Exact ``(len(qpos), len(kpos))`` bool truth over GLOBAL token
        positions — the independent ground truth every lowering is
        certified against."""
        raise NotImplementedError

    # -- exact tile classification (the generic lowering's closed forms) -
    def tile_status(self, qlo: int, qhi: int, klo: int, khi: int,
                    head: int = 0) -> tuple[bool, bool]:
        """Exact ``(any_live, all_live)`` of the tile spanning global
        rows ``[qlo, qhi]`` x cols ``[klo, khi]`` (inclusive,
        contiguous).  Leaves use closed forms; combinators combine them
        and refine the genuinely ambiguous cases elementwise."""
        raise NotImplementedError

    @property
    def key(self) -> str:
        """Canonical textual form — the certificate-cache key half and
        the diagnostic name; round-trips through :func:`parse_mask` for
        every parseable form."""
        raise NotImplementedError

    @property
    def per_head(self) -> bool:
        return False

    @property
    def head_period(self) -> int:
        """Number of distinct head variants (1 for head-independent
        masks; combinators take the lcm of their children) — what a
        certificate must enumerate."""
        return 1

    def head_mask(self, head: int) -> "Mask":
        """The mask head ``head`` actually attends under (identity for
        head-independent masks)."""
        return self

    def __repr__(self) -> str:
        return f"{type(self).__name__}<{self.key}>"


def _lcm_all(values) -> int:
    import math

    out = 1
    for v in values:
        out = out * v // math.gcd(out, v)
    return out


def static_mask(mask: "Mask") -> "Mask":
    """The trace-time part of a mask: :class:`Segments` leaves (runtime
    per-token ids, masked in-kernel) drop out of conjunctions — the
    grids a lowering emits are those of the remaining static terms,
    exactly like the misaligned-document fallback.  A ``Segments``
    under ``Or``/``Not`` has no sound static grid and stays (its oracle
    raises with the DocumentMask pointer)."""
    if isinstance(mask, Segments):
        return Full()
    if isinstance(mask, And):
        kept = tuple(static_mask(m) for m in mask.operands
                     if not isinstance(m, Segments))
        if not kept:
            return Full()
        return kept[0] if len(kept) == 1 else And(kept)
    if isinstance(mask, PerHead):
        return PerHead(tuple(static_mask(m) for m in mask.masks))
    return mask


def _tile_eval(mask: Mask, qlo, qhi, klo, khi, head) -> tuple[bool, bool]:
    """Elementwise refinement for combinator tiles the tri-state rules
    cannot decide (exact, O(tile))."""
    m = mask.oracle(np.arange(qlo, qhi + 1), np.arange(klo, khi + 1), head)
    return bool(m.any()), bool(m.all())


@dataclass(frozen=True)
class Full(Mask):
    """Every query attends every key."""

    def oracle(self, qpos, kpos, head=0, doc_ids=None):
        return np.ones((len(qpos), len(kpos)), bool)

    def tile_status(self, qlo, qhi, klo, khi, head=0):
        return True, True

    @property
    def key(self):
        return "full"


@dataclass(frozen=True)
class Causal(Mask):
    """Attend iff ``k_pos <= q_pos``."""

    def oracle(self, qpos, kpos, head=0, doc_ids=None):
        return np.asarray(kpos)[None, :] <= np.asarray(qpos)[:, None]

    def tile_status(self, qlo, qhi, klo, khi, head=0):
        return klo <= qhi, khi <= qlo

    @property
    def key(self):
        return "causal"


@dataclass(frozen=True)
class SlidingWindow(Mask):
    """Attend iff ``|q_pos - k_pos| < window`` (two-sided local band).

    Compose with :class:`Causal` for the usual causal sliding window —
    ``Causal() & SlidingWindow(w)`` keeps exactly the last ``w`` keys,
    matching the kernels' ``window=`` contract — or use standalone for
    bidirectional local attention."""

    window: int

    def __post_init__(self):
        if int(self.window) < 1:
            raise ValueError(f"SlidingWindow needs window >= 1, "
                             f"got {self.window}")

    def oracle(self, qpos, kpos, head=0, doc_ids=None):
        d = np.asarray(kpos)[None, :] - np.asarray(qpos)[:, None]
        return np.abs(d) < int(self.window)

    def tile_status(self, qlo, qhi, klo, khi, head=0):
        w = int(self.window)
        # diff d = k - q ranges over [klo - qhi, khi - qlo]
        any_live = klo - qhi < w and khi - qlo > -w
        all_live = klo - qhi > -w and khi - qlo < w
        return any_live, all_live

    @property
    def key(self):
        return f"window:{int(self.window)}"


@dataclass(frozen=True)
class Dilated(Mask):
    """Attend iff ``(q_pos - k_pos) % stride == offset`` — the dilated /
    strided sparse pattern (LongNet-style; the stripe/zigzag schedules of
    Striped Attention, arXiv 2311.09431, are the ``stride = ring``
    member of this family)."""

    stride: int
    offset: int = 0

    def __post_init__(self):
        if int(self.stride) < 1:
            raise ValueError(f"Dilated needs stride >= 1, got {self.stride}")
        if not 0 <= int(self.offset) < int(self.stride):
            raise ValueError(
                f"Dilated offset must be in [0, stride), got {self.offset}"
            )

    def oracle(self, qpos, kpos, head=0, doc_ids=None):
        d = np.asarray(qpos)[:, None] - np.asarray(kpos)[None, :]
        return d % int(self.stride) == int(self.offset)

    def tile_status(self, qlo, qhi, klo, khi, head=0):
        s, o = int(self.stride), int(self.offset)
        d_lo, d_hi = qlo - khi, qhi - klo  # d = q - k range
        # any: an integer d in [d_lo, d_hi] with d ≡ o (mod s)
        any_live = (d_hi - o) // s >= -((o - d_lo) // s)
        all_live = s == 1 or (d_lo == d_hi and (d_lo - o) % s == 0)
        return any_live, all_live

    @property
    def key(self):
        o = int(self.offset)
        return f"dilated:{int(self.stride)}" + (f"+{o}" if o else "")


# the issue's Dilated/Striped(stride) are one pattern; keep both names
Striped = Dilated


@dataclass(frozen=True)
class PrefixLM(Mask):
    """Attend iff ``k_pos < prefix_len`` or ``k_pos <= q_pos`` —
    bidirectional over the prompt prefix, causal after (T5/PaLM-style
    prefix language modeling)."""

    prefix_len: int

    def __post_init__(self):
        if int(self.prefix_len) < 0:
            raise ValueError(
                f"PrefixLM needs prefix_len >= 0, got {self.prefix_len}"
            )

    def oracle(self, qpos, kpos, head=0, doc_ids=None):
        k = np.asarray(kpos)[None, :]
        return (k < int(self.prefix_len)) | (k <= np.asarray(qpos)[:, None])

    def tile_status(self, qlo, qhi, klo, khi, head=0):
        p = int(self.prefix_len)
        return (klo < p or klo <= qhi), (khi < p or khi <= qlo)

    @property
    def key(self):
        return f"prefix:{int(self.prefix_len)}"


@dataclass(frozen=True)
class DocumentMask(Mask):
    """Attend iff ``q_pos`` and ``k_pos`` lie in the same document of a
    DECLARED packing layout: ``doc_starts`` are sorted unique global
    start offsets beginning at 0 (the trace-time twin of runtime
    :class:`Segments`; block-aligned layouts compile the document mask
    into the tile tables, misaligned ones fall back to in-kernel
    runtime ids — see docs/masks.md)."""

    doc_starts: tuple[int, ...]

    def __post_init__(self):
        ds = tuple(int(s) for s in self.doc_starts)
        if not ds or ds[0] != 0 or list(ds) != sorted(set(ds)):
            raise ValueError(
                f"DocumentMask doc_starts must be sorted unique offsets "
                f"starting at 0, got {self.doc_starts!r}"
            )
        object.__setattr__(self, "doc_starts", ds)

    def _doc_of_scalar(self, pos: int) -> int:
        return bisect_right(self.doc_starts, pos) - 1

    def _doc_of(self, pos) -> np.ndarray:
        return np.searchsorted(
            np.asarray(self.doc_starts), np.asarray(pos), side="right"
        ) - 1

    def oracle(self, qpos, kpos, head=0, doc_ids=None):
        return self._doc_of(qpos)[:, None] == self._doc_of(kpos)[None, :]

    def tile_status(self, qlo, qhi, klo, khi, head=0):
        dq_lo, dq_hi = self._doc_of_scalar(qlo), self._doc_of_scalar(qhi)
        dk_lo, dk_hi = self._doc_of_scalar(klo), self._doc_of_scalar(khi)
        any_live = dq_lo <= dk_hi and dk_lo <= dq_hi
        all_live = dq_lo == dq_hi == dk_lo == dk_hi
        return any_live, all_live

    @property
    def key(self):
        return "docs:" + ",".join(str(s) for s in self.doc_starts)


@dataclass(frozen=True)
class Segments(Mask):
    """Runtime packed-sequence masking: attend iff the per-token segment
    ids (a RUNTIME array, supplied at call time) match.  Has no static
    oracle — certification rows use :class:`DocumentMask`, the declared
    trace-time layout; :func:`kernel_form` maps this leaf onto the
    ``segment_ids`` execution path."""

    def oracle(self, qpos, kpos, head=0, doc_ids=None):
        if doc_ids is None:
            raise MaskLoweringError(
                "Segments is a runtime mask (per-token ids supplied at "
                "call time); a static oracle needs doc_ids — declare the "
                "layout with DocumentMask to certify it"
            )
        ids = np.asarray(doc_ids)
        return ids[np.asarray(qpos)][:, None] == ids[np.asarray(kpos)][None, :]

    def tile_status(self, qlo, qhi, klo, khi, head=0):
        raise MaskLoweringError(
            "Segments has no trace-time tile classification (runtime "
            "ids); use DocumentMask for a declared layout"
        )

    @property
    def key(self):
        return "segments"


@dataclass(frozen=True)
class PerHead(Mask):
    """Per-head mask selection: head ``h`` attends under
    ``masks[h % len(masks)]`` (splash-attention's ``MultiHeadMask``)."""

    masks: tuple[Mask, ...]

    def __post_init__(self):
        ms = tuple(self.masks)
        if not ms or not all(isinstance(m, Mask) for m in ms):
            raise ValueError("PerHead needs a non-empty tuple of masks")
        if any(m.per_head for m in ms):
            raise ValueError("PerHead masks cannot nest PerHead")
        object.__setattr__(self, "masks", ms)

    def oracle(self, qpos, kpos, head=0, doc_ids=None):
        return self.head_mask(head).oracle(qpos, kpos, head, doc_ids)

    def tile_status(self, qlo, qhi, klo, khi, head=0):
        return self.head_mask(head).tile_status(qlo, qhi, klo, khi, head)

    @property
    def per_head(self):
        return True

    @property
    def head_period(self):
        return len(self.masks)

    def head_mask(self, head: int) -> Mask:
        return self.masks[head % len(self.masks)]

    @property
    def key(self):
        return "perhead(" + ";".join(m.key for m in self.masks) + ")"


@dataclass(frozen=True)
class And(Mask):
    """Intersection of the operand masks."""

    operands: tuple[Mask, ...]

    def __post_init__(self):
        flat: list[Mask] = []
        for m in self.operands:
            flat.extend(m.operands if isinstance(m, And) else (m,))
        object.__setattr__(self, "operands", tuple(flat))

    def oracle(self, qpos, kpos, head=0, doc_ids=None):
        out = self.operands[0].oracle(qpos, kpos, head, doc_ids)
        for m in self.operands[1:]:
            out = out & m.oracle(qpos, kpos, head, doc_ids)
        return out

    def tile_status(self, qlo, qhi, klo, khi, head=0):
        stats = [m.tile_status(qlo, qhi, klo, khi, head)
                 for m in self.operands]
        if not all(any_live for any_live, _ in stats):
            return False, False
        if all(all_live for _, all_live in stats):
            return True, True
        # children each touch the tile but none fills it alone — the
        # intersection may still be empty; decide exactly
        return _tile_eval(self, qlo, qhi, klo, khi, head)

    @property
    def per_head(self):
        return any(m.per_head for m in self.operands)

    @property
    def head_period(self):
        return _lcm_all(m.head_period for m in self.operands)

    def head_mask(self, head):
        return And(tuple(m.head_mask(head) for m in self.operands))

    @property
    def key(self):
        return "(" + "&".join(m.key for m in self.operands) + ")"


@dataclass(frozen=True)
class Or(Mask):
    """Union of the operand masks."""

    operands: tuple[Mask, ...]

    def __post_init__(self):
        flat: list[Mask] = []
        for m in self.operands:
            flat.extend(m.operands if isinstance(m, Or) else (m,))
        object.__setattr__(self, "operands", tuple(flat))

    def oracle(self, qpos, kpos, head=0, doc_ids=None):
        out = self.operands[0].oracle(qpos, kpos, head, doc_ids)
        for m in self.operands[1:]:
            out = out | m.oracle(qpos, kpos, head, doc_ids)
        return out

    def tile_status(self, qlo, qhi, klo, khi, head=0):
        stats = [m.tile_status(qlo, qhi, klo, khi, head)
                 for m in self.operands]
        if any(all_live for _, all_live in stats):
            return True, True
        if not any(any_live for any_live, _ in stats):
            return False, False
        any_live = True  # some child touches the tile
        # full only if the union covers it — decide exactly
        _, all_live = _tile_eval(self, qlo, qhi, klo, khi, head)
        return any_live, all_live

    @property
    def per_head(self):
        return any(m.per_head for m in self.operands)

    @property
    def head_period(self):
        return _lcm_all(m.head_period for m in self.operands)

    def head_mask(self, head):
        return Or(tuple(m.head_mask(head) for m in self.operands))

    @property
    def key(self):
        return "(" + "|".join(m.key for m in self.operands) + ")"


@dataclass(frozen=True)
class Not(Mask):
    """Complement of the operand mask."""

    operand: Mask

    def oracle(self, qpos, kpos, head=0, doc_ids=None):
        return ~self.operand.oracle(qpos, kpos, head, doc_ids)

    def tile_status(self, qlo, qhi, klo, khi, head=0):
        any_live, all_live = self.operand.tile_status(
            qlo, qhi, klo, khi, head
        )
        return not all_live, not any_live

    @property
    def per_head(self):
        return self.operand.per_head

    @property
    def head_period(self):
        return self.operand.head_period

    def head_mask(self, head):
        return Not(self.operand.head_mask(head))

    @property
    def key(self):
        return "~" + self.operand.key


# ---------------------------------------------------------------------------
# Canonical band / kernel forms (the execution mapping)
# ---------------------------------------------------------------------------


def band_form(mask: Mask) -> tuple[int | None, int | None] | None:
    """``(hi, lo)`` of a pure band mask — attend iff
    ``lo <= k_pos - q_pos <= hi`` with ``None`` meaning unbounded — or
    ``None`` when the mask is not a band.  This is the repo's unified
    banded-offset contract (``ops/flash.py``), in global coordinates."""
    if isinstance(mask, Full):
        return (None, None)
    if isinstance(mask, Causal):
        return (0, None)
    if isinstance(mask, SlidingWindow):
        w = int(mask.window)
        return (w - 1, -(w - 1))
    if isinstance(mask, And):
        hi: int | None = None
        lo: int | None = None
        for m in mask.operands:
            b = band_form(m)
            if b is None:
                return None
            mhi, mlo = b
            hi = mhi if hi is None else (hi if mhi is None else min(hi, mhi))
            lo = mlo if lo is None else (lo if mlo is None else max(lo, mlo))
        return (hi, lo)
    return None


@dataclass(frozen=True)
class KernelForm:
    """A mask resolved onto the knobs the shipping kernels speak:
    ``causal``/``window`` (the banded-offset contract), a declared
    ``doc_starts`` packing, and/or runtime ``segment_ids``."""

    causal: bool = False
    window: int | None = None
    doc_starts: tuple[int, ...] | None = None
    needs_segment_ids: bool = False


_KERNEL_FORMS = (
    "Full() / None", "Causal()", "Causal() & SlidingWindow(w)",
    "... & DocumentMask(starts)", "... & Segments()",
)


def kernel_form(mask: Mask) -> KernelForm:
    """Map a mask onto the existing kernel knobs, or raise
    :class:`MaskLoweringError` naming the supported forms.

    Masks that fail here still certify and lower to grids (the
    extension seam for future kernels); they just have no fast
    execution path yet."""
    terms = mask.operands if isinstance(mask, And) else (mask,)
    docs: list[DocumentMask] = []
    segments = False
    band_terms: list[Mask] = []
    for t in terms:
        if isinstance(t, DocumentMask):
            docs.append(t)
        elif isinstance(t, Segments):
            segments = True
        else:
            band_terms.append(t)
    if len(docs) > 1:
        raise MaskLoweringError(
            f"mask {mask.key!r}: at most one DocumentMask per "
            f"conjunction (merge the layouts first)"
        )
    band = band_form(And(tuple(band_terms)) if len(band_terms) > 1
                     else (band_terms[0] if band_terms else Full()))
    if band is None:
        raise MaskLoweringError(
            f"mask {mask.key!r} has no kernel lowering yet — the kernels "
            f"speak {', '.join(_KERNEL_FORMS)}; it still certifies and "
            f"lowers to grids (analysis/coverage.py)"
        )
    hi, lo = band
    if hi is None and lo is None:
        causal, window = False, None
    elif hi == 0 and lo is None:
        causal, window = True, None
    elif hi == 0 and lo is not None and lo <= 0:
        causal, window = True, 1 - lo
    else:
        raise MaskLoweringError(
            f"mask {mask.key!r} lowers to the band [{lo}, {hi}] which the "
            f"kernel entry points do not expose (they speak "
            f"{', '.join(_KERNEL_FORMS)}); it still certifies and lowers "
            f"to grids"
        )
    return KernelForm(
        causal=causal, window=window,
        doc_starts=docs[0].doc_starts if docs else None,
        needs_segment_ids=segments,
    )


# ---------------------------------------------------------------------------
# Lowering: mask -> tile grids + hop schedules per execution geometry
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class GridSpec:
    """One execution geometry a mask lowers onto (the cache key's
    geometry half).

    ``strategy``: ``"single"`` (one sweep), ``"ring"`` (KV rotation —
    also the hybrid outer ring, whose ring leg is this schedule at the
    outer ring size), or ``"counter"`` (TokenRing counter-rotation).
    ``layout``: ``"contiguous"`` or ``"striped"`` token placement.
    """

    strategy: str = "single"
    layout: str = "contiguous"
    ring: int = 1
    n_local: int = 64
    block_q: int = 8
    block_k: int = 8
    passes: int | None = None
    head: int = 0

    def __post_init__(self):
        if self.strategy not in ("single", "ring", "counter"):
            raise ValueError(
                f"GridSpec strategy {self.strategy!r}: known strategies "
                f"are single, ring, counter (hybrid = ring at the outer "
                f"ring size; zigzag stays causal-only, see docs/masks.md)"
            )
        if self.layout not in ("contiguous", "striped"):
            raise ValueError(f"GridSpec layout {self.layout!r}")
        if self.strategy == "single" and self.ring != 1:
            raise ValueError("single-sweep specs have ring == 1")
        if self.n_local % self.block_q or self.n_local % self.block_k:
            raise ValueError(
                f"blocks ({self.block_q}, {self.block_k}) must divide "
                f"n_local {self.n_local}"
            )

    @property
    def n_total(self) -> int:
        return self.ring * self.n_local

    @property
    def n_passes(self) -> int:
        return min(self.passes or self.ring, self.ring)


def positions(layout: str, origin: int, n_local: int, ring: int) -> np.ndarray:
    """Global token positions of rank/origin ``origin``'s local shard."""
    i = np.arange(n_local)
    if layout == "striped":
        return i * ring + origin
    if layout == "contiguous":
        return origin * n_local + i
    raise ValueError(f"unknown layout {layout!r}")


@dataclass
class RankPlan:
    """One rank's runtime decisions at one hop — what the compiled
    program would do, recorded for the certifier to hold to the oracle."""

    rank: int
    q_origin: int
    kv_origin: int
    has_work: bool
    hi: int | None = None  # runtime band scalars (band lowerings)
    lo: int | None = None
    rt_mask: np.ndarray | None = None  # generic runtime edge mask


@dataclass
class LoweredHop:
    """One hop of a lowering: the shared tile tables (q-major and
    k-major) plus every rank's runtime schedule decisions."""

    hop: int
    full: bool  # trace-time full-span elision (no mask, no tables)
    plan: object | None  # BandPlan (band route) or GenericPlan
    plan_kmajor: object | None
    ranks: list[RankPlan] = field(default_factory=list)
    nk: int = 0  # key extent this hop attends


@dataclass
class Lowering:
    """A mask's grids for one :class:`GridSpec` — what the compiler
    emits, as data.  ``route`` records which seam produced it
    (``"band"`` = the shipping band_plan/ring-hop machinery,
    ``"generic"`` = the algebra's tile classifier)."""

    mask: Mask
    spec: GridSpec
    route: str
    hops: list[LoweredHop] = field(default_factory=list)

    @property
    def tiles(self) -> int:
        return sum(len(h.plan.tile_q) for h in self.hops
                   if h.plan is not None)


@dataclass
class GenericPlan:
    """Duck-type of :class:`~ring_attention_tpu.ops.pallas_flash.BandPlan`
    for generic (non-band) lowerings: same tables, flags, and
    closed-form-vs-enumeration contract, built from the algebra's exact
    tile classifier instead of the band arithmetic."""

    tile_q: np.ndarray
    tile_k: np.ndarray
    flags: np.ndarray
    tiles: int
    block_q: int
    block_k: int
    n_q_blocks: int
    n_k_blocks: int
    outer_is_q: bool

    @property
    def work_tiles(self) -> int:
        from .ops.pallas_flash import _TF_WORK

        return int((self.flags & _TF_WORK != 0).sum())

    @property
    def edge_tiles(self) -> int:
        from .ops.pallas_flash import _TF_EDGE, _TF_WORK

        return int((self.flags & (_TF_WORK | _TF_EDGE)
                    == (_TF_WORK | _TF_EDGE)).sum())


def _tables_from_classes(work: np.ndarray, interior: np.ndarray,
                         bq: int, bk: int, outer_is_q: bool) -> GenericPlan:
    """Build FIRST/LAST-bracketed tile tables from per-tile (work,
    interior) classifications — the same dummy-row and accumulator-
    lifecycle contract as ``ops.pallas_flash._band_tables``."""
    from .ops.pallas_flash import _TF_EDGE, _TF_FIRST, _TF_LAST, _TF_WORK

    nqb, nkb = work.shape
    outer_n = nqb if outer_is_q else nkb
    inner_n = nkb if outer_is_q else nqb
    tq, tk, tf = [], [], []
    for o in range(outer_n):
        start = len(tf)
        for i in range(inner_n):
            qi, ki = (o, i) if outer_is_q else (i, o)
            if work[qi, ki]:
                tq.append(qi)
                tk.append(ki)
                tf.append(_TF_WORK
                          | (0 if interior[qi, ki] else _TF_EDGE))
        if len(tf) == start:  # empty row: dummy entry, write zeros
            tq.append(o if outer_is_q else 0)
            tk.append(0 if outer_is_q else o)
            tf.append(0)
        tf[start] |= _TF_FIRST
        tf[-1] |= _TF_LAST
    return GenericPlan(
        tile_q=np.asarray(tq, np.int32), tile_k=np.asarray(tk, np.int32),
        flags=np.asarray(tf, np.int32), tiles=len(tf), block_q=bq,
        block_k=bk, n_q_blocks=nqb, n_k_blocks=nkb, outer_is_q=outer_is_q,
    )


def _hop_pairings(spec: GridSpec):
    """``(hop, [(rank, q_origin, kv_origin)])`` per hop — the visit
    schedule of each strategy, recomputed here from first principles
    (the certifier recomputes it independently and cross-checks)."""
    W = spec.ring
    if spec.strategy == "single":
        return [(0, [(0, 0, 0)])]
    out = []
    for i in range(spec.n_passes):
        if spec.strategy == "counter":
            from .parallel.ring import _counter_origins

            rows = []
            for r in range(W):
                qo, ko = _counter_origins(r, i, W)
                rows.append((r, int(qo), int(ko)))
        else:  # ring: rank r holds its own q, hop i delivers origin r-i
            rows = [(r, r, (r - i) % W) for r in range(W)]
        out.append((i, rows))
    return out


def _lower_band(mask: Mask, spec: GridSpec, band) -> Lowering:
    """Band-shaped masks lower through the SHIPPING seams: the ring-hop
    band helpers of ``parallel/ring.py`` (causal-style bands) and
    ``ops.pallas_flash.band_plan`` tables — certifying this lowering
    certifies the real kernels' grids."""
    from .ops.pallas_flash import band_plan
    from .parallel import ring as ring_mod

    hi_g, lo_g = band
    causal_style = hi_g == 0  # the ring layer's causal(+window) contract
    window = None if lo_g is None else 1 - lo_g
    windowed = window is not None
    striped = spec.layout == "striped"
    n = spec.n_local
    low = Lowering(mask=mask, spec=spec, route="band")

    if spec.strategy != "single" and not causal_style:
        raise MaskLoweringError(
            f"mask {mask.key!r}: the ring/counter hop schedules lower "
            f"causal-style bands (hi == 0) only; band [{lo_g}, {hi_g}] "
            f"lowers on single-sweep specs or through the generic route"
        )

    for i, rows in _hop_pairings(spec):
        if spec.strategy == "single":
            hi_l, lo_l = hi_g, lo_g  # nq == nk: global diff == local diff
            full = (hi_l is None or hi_l >= n - 1) and (
                lo_l is None or lo_l <= -(n - 1)
            )
            plan = plan_k = None
            if not full:
                hint_hi = n - 1 if hi_l is None else hi_l
                hint = (hint_hi, hint_hi, lo_l or 0, lo_l or 0)
                plan = band_plan((n, n), (spec.block_q, spec.block_k),
                                 hint, windowed=windowed)
                plan_k = band_plan((n, n), (spec.block_q, spec.block_k),
                                   hint, windowed=windowed,
                                   outer_is_q=False)
            ranks = [RankPlan(
                0, 0, 0, has_work=True, hi=None if full else hi_l,
                lo=None if full else lo_l,
            )]
            low.hops.append(LoweredHop(
                hop=i, full=full, plan=plan, plan_kmajor=plan_k,
                ranks=ranks, nk=n,
            ))
            continue
        stream = (1, 0, n)
        if spec.strategy == "counter":
            full, hint = ring_mod._counter_static_band(
                i, n, True, striped, window, spec.ring
            )
        else:
            full, hint = ring_mod._static_hop_band(
                stream, i, n, True, striped, window, spec.ring
            )
        ranks = []
        for r, qo, ko in rows:
            hi, lo = ring_mod._hop_offsets(
                qo, ko, n, True, striped, window, spec.ring
            )
            hi = None if hi is None else int(hi)
            lo = None if lo is None else int(lo)
            has_work = bool(ring_mod._hop_has_work(hi, lo, n, n))
            ranks.append(RankPlan(
                r, qo, ko, has_work=has_work,
                hi=None if full else hi, lo=None if full else lo,
            ))
        plan = plan_k = None
        if not full:
            plan = band_plan((n, n), (spec.block_q, spec.block_k), hint,
                             windowed=windowed)
            plan_k = band_plan((n, n), (spec.block_q, spec.block_k), hint,
                               windowed=windowed, outer_is_q=False)
        low.hops.append(LoweredHop(
            hop=i, full=bool(full), plan=plan, plan_kmajor=plan_k,
            ranks=ranks, nk=n,
        ))
    return low


def _lower_generic(mask: Mask, spec: GridSpec) -> Lowering:
    """Generic lowering: exact per-tile classification from the
    algebra's closed forms (refined elementwise only at genuinely
    ambiguous combinator tiles), shared tables = union over ranks,
    interior = full for every working rank — the same hint semantics
    the band route compiles."""
    if spec.layout != "contiguous":
        raise MaskLoweringError(
            f"mask {mask.key!r}: the generic lowering places tokens "
            f"contiguously; striped layouts lower band-shaped masks only"
        )
    head = spec.head
    n, bq, bk = spec.n_local, spec.block_q, spec.block_k
    nqb, nkb = n // bq, n // bk
    low = Lowering(mask=mask, spec=spec, route="generic")
    for i, rows in _hop_pairings(spec):
        any_l = np.zeros((len(rows), nqb, nkb), bool)
        all_l = np.zeros((len(rows), nqb, nkb), bool)
        for x, (r, qo, ko) in enumerate(rows):
            q0, k0 = qo * n, ko * n
            for qi in range(nqb):
                for ki in range(nkb):
                    a, f = mask.tile_status(
                        q0 + qi * bq, q0 + qi * bq + bq - 1,
                        k0 + ki * bk, k0 + ki * bk + bk - 1, head,
                    )
                    any_l[x, qi, ki] = a
                    all_l[x, qi, ki] = f
        rank_any = any_l.any(axis=(1, 2))
        work = any_l.any(axis=0)
        # interior: full for every rank that computes this hop at all
        interior = work & (all_l[rank_any].all(axis=0)
                           if rank_any.any() else work)
        full = bool(rank_any.any()) and all(
            bool(all_l[x].all()) or not rank_any[x]
            for x in range(len(rows))
        )
        ranks = []
        for x, (r, qo, ko) in enumerate(rows):
            rt = None
            if not full and rank_any[x]:
                rt = mask.oracle(
                    positions("contiguous", qo, n, spec.ring),
                    positions("contiguous", ko, n, spec.ring),
                    head,
                )
            ranks.append(RankPlan(
                r, qo, ko, has_work=bool(rank_any[x]), rt_mask=rt,
            ))
        plan = plan_k = None
        if not full:
            plan = _tables_from_classes(work, interior, bq, bk, True)
            plan_k = _tables_from_classes(work, interior, bq, bk, False)
        low.hops.append(LoweredHop(
            hop=i, full=full, plan=plan, plan_kmajor=plan_k, ranks=ranks,
            nk=n,
        ))
    return low


def lower(mask: Mask, spec: GridSpec) -> Lowering:
    """Lower ``mask`` onto ``spec``: band-shaped masks through the
    shipping band seams, everything else through the generic tile
    classifier.  Runtime :class:`Segments` terms drop out first
    (:func:`static_mask` — they mask in-kernel, not in the grids).
    Raises :class:`MaskLoweringError` when neither route applies (the
    diagnostic names the mask and the supported routes)."""
    mask = static_mask(mask)
    m = mask.head_mask(spec.head) if mask.per_head else mask
    band = band_form(m)
    if band is not None:
        hi, lo = band
        causal_style = hi == 0
        if spec.strategy == "single" or causal_style:
            return _lower_band(m, spec, band)
    return _lower_generic(m, spec)


def dense_mask(mask: Mask, nq: int, nk: int, heads: int = 1,
               q_offset: int = 0, k_offset: int = 0) -> np.ndarray:
    """Materialized oracle over a contiguous span — ``(nq, nk)`` bool,
    or ``(heads, nq, nk)`` for per-head masks.  The O(n^2) reference a
    fallback execution path or a parity test compares against."""
    qpos = q_offset + np.arange(nq)
    kpos = k_offset + np.arange(nk)
    if mask.per_head:
        return np.stack([
            mask.oracle(qpos, kpos, h) for h in range(heads)
        ])
    return mask.oracle(qpos, kpos, 0)


# ---------------------------------------------------------------------------
# Certification: prove a lowering, cache the certificate
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Certificate:
    """One proven (mask, spec) row: the verdict plus the tile accounting
    the coverage fingerprint and the perf gate pin."""

    key: str
    ok: bool
    violations: tuple[str, ...]
    hops: int
    tiles: int
    work: int
    edge: int
    tiles_kmajor: int
    proof_n: int  # positions per side the elementwise half enumerated

    def to_json(self) -> dict:
        return {
            "key": self.key, "ok": self.ok,
            "violations": list(self.violations), "hops": self.hops,
            "tiles": self.tiles, "work": self.work, "edge": self.edge,
            "tiles_kmajor": self.tiles_kmajor, "proof_n": self.proof_n,
        }


_CERT_MEMO: dict[str, Certificate] = {}
_CERT_SCHEMA = 1


def cert_cache_key(mask: Mask, spec: GridSpec) -> str:
    """The (mask, shape, blocks, strategy, layout) cache key."""
    return (
        f"v{_CERT_SCHEMA}|{mask.key}|{spec.strategy}|{spec.layout}|"
        f"ring{spec.ring}|n{spec.n_local}|b{spec.block_q}x{spec.block_k}|"
        f"p{spec.n_passes}|h{spec.head}"
    )


def cert_cache_dir() -> str | None:
    """On-disk certificate cache directory: ``RING_ATTN_CERT_CACHE``,
    else a ``mask_certificates`` subdir of the configured jax compile
    cache (the proof lives next to the compile it certifies), else
    memory-only."""
    env = os.environ.get("RING_ATTN_CERT_CACHE")
    if env:
        return env
    try:
        import jax

        base = jax.config.jax_compilation_cache_dir
    except Exception:  # jax absent or too old — memory-only cache
        base = None
    if base:
        return os.path.join(base, "mask_certificates")
    return None


def _disk_load(key: str, cache_dir: str | None) -> Certificate | None:
    if not cache_dir:
        return None
    path = os.path.join(
        cache_dir, hashlib.sha256(key.encode()).hexdigest()[:24] + ".json"
    )
    try:
        with open(path) as f:
            data = json.load(f)
        if data.get("key") != key or not data.get("ok"):
            return None
        return Certificate(
            key=key, ok=True, violations=(), hops=int(data["hops"]),
            tiles=int(data["tiles"]), work=int(data["work"]),
            edge=int(data["edge"]),
            tiles_kmajor=int(data["tiles_kmajor"]),
            proof_n=int(data["proof_n"]),
        )
    except (OSError, ValueError, KeyError, TypeError):
        return None  # any corrupt cache entry re-proves, never aborts


def _disk_store(cert: Certificate, cache_dir: str | None) -> None:
    if not cache_dir or not cert.ok:
        return  # failures are re-proven (and re-diagnosed) every run
    try:
        os.makedirs(cache_dir, exist_ok=True)
        path = os.path.join(
            cache_dir,
            hashlib.sha256(cert.key.encode()).hexdigest()[:24] + ".json",
        )
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(cert.to_json(), f)
        os.replace(tmp, path)
    except OSError:
        pass  # a read-only cache dir must never fail the proof itself


def _proof_spec(spec: GridSpec) -> GridSpec:
    """The spec the elementwise half actually enumerates: the leading
    ``CERT_ELEMENTWISE_MAX`` positions when the full shape would cost an
    O(n^2) oracle (the tile-accounting half still runs at full shape)."""
    if spec.n_total <= CERT_ELEMENTWISE_MAX:
        return spec
    n_local = max(spec.block_q, spec.block_k,
                  CERT_ELEMENTWISE_MAX // spec.ring)
    n_local -= n_local % max(spec.block_q, spec.block_k)
    n_local = max(n_local, max(spec.block_q, spec.block_k))
    return GridSpec(
        strategy=spec.strategy, layout=spec.layout, ring=spec.ring,
        n_local=n_local, block_q=spec.block_q, block_k=spec.block_k,
        passes=spec.passes, head=spec.head,
    )


def certify(mask: Mask, spec: GridSpec, *, use_cache: bool = True,
            cache_dir: str | None = None) -> Certificate:
    """Prove ``mask``'s lowering on ``spec`` sound, tight, and complete
    (``analysis/coverage.py::prove_mask_lowering``), caching the
    certificate by (mask, shape, blocks, strategy, layout).

    Per-head masks certify every distinct head variant (the lcm period
    across combinators); the certificate aggregates their tile
    accounting.  Runtime ``Segments`` terms are stripped first — the
    certificate describes the static grids, which is also what the
    launch emits (runtime ids mask in-kernel).
    """
    mask = static_mask(mask)
    key = cert_cache_key(mask, spec)
    if use_cache:
        hit = _CERT_MEMO.get(key)
        if hit is not None:
            return hit
        cache_dir = cache_dir if cache_dir is not None else cert_cache_dir()
        hit = _disk_load(key, cache_dir)
        if hit is not None:
            _CERT_MEMO[key] = hit
            return hit
    from .analysis.coverage import prove_mask_lowering

    pspec = _proof_spec(spec)
    heads = mask.head_period
    violations: list[str] = []
    hops = tiles = work = edge = tiles_k = 0
    for h in range(heads):
        hspec = GridSpec(
            strategy=pspec.strategy, layout=pspec.layout, ring=pspec.ring,
            n_local=pspec.n_local, block_q=pspec.block_q,
            block_k=pspec.block_k, passes=pspec.passes, head=h,
        )
        report = prove_mask_lowering(mask, hspec)
        violations.extend(report.violations)
        hops += report.hops
        tiles += report.tiles
        work += report.work
        edge += report.edge
        tiles_k += report.tiles_kmajor
    if pspec is not spec:
        # full-shape tile accounting: closed form vs enumeration on the
        # real grid (CPU-countable even at 262k — bench window262k)
        try:
            full_low = lower(mask, spec)
            for hop in full_low.hops:
                for plan in (hop.plan, hop.plan_kmajor):
                    if plan is not None and plan.tiles != len(plan.tile_q):
                        violations.append(
                            f"{mask.key}/{spec.strategy}/hop{hop.hop}: "
                            f"closed-form count {plan.tiles} != enumerated "
                            f"{len(plan.tile_q)} at full shape "
                            f"[rule: tile-count]"
                        )
        except MaskLoweringError as e:
            violations.append(f"{mask.key}: full-shape lowering failed: {e}")
    cert = Certificate(
        key=key, ok=not violations, violations=tuple(violations),
        hops=hops, tiles=tiles, work=work, edge=edge,
        tiles_kmajor=tiles_k, proof_n=pspec.n_total,
    )
    if use_cache:
        _CERT_MEMO[key] = cert
        _disk_store(cert, cache_dir)
    return cert


def require_certified(mask: Mask, spec: GridSpec, **kw) -> Certificate:
    """``certify``, raising :class:`MaskCertificationError` with the
    first violation (one line: mask, hop, tile) on failure."""
    cert = certify(mask, spec, **kw)
    if not cert.ok:
        raise MaskCertificationError(cert.violations[0])
    return cert


def spec_for_call(strategy: str, *, n: int, ring: int = 1,
                  striped: bool = False, block_q: int | None = None,
                  block_k: int | None = None,
                  passes: int | None = None) -> GridSpec:
    """The :class:`GridSpec` an attention call's lowering runs under —
    the bridge from model-layer knobs (``sequence_parallel``, layout,
    kernel block fitting) to the certificate cache key.

    ``ulysses`` attends the full sequence locally after its all-to-all
    (a single sweep); ``hybrid`` is the ring schedule at the OUTER ring
    size; ``zigzag`` stays causal-only at the model layer and keeps its
    dedicated prover row.
    """
    from .ops.pallas_flash import _block_sizes

    name = {"ring": "ring", "counter": "counter", "single": "single",
            "ulysses": "single", "hybrid": "ring",
            "zigzag": "single"}.get(strategy)
    if name is None:
        raise ValueError(f"spec_for_call: unknown strategy {strategy!r}")
    if name != "single" and ring <= 1:
        name = "single"
    r = ring if name != "single" else 1
    n_local = n // r if r else n
    bq, bk = _block_sizes(n_local, n_local, block_q, block_k)
    return GridSpec(
        strategy=name, layout="striped" if (striped and name != "single")
        else "contiguous", ring=r, n_local=n_local, block_q=bq,
        block_k=bk, passes=passes,
    )


# ---------------------------------------------------------------------------
# The textual mini-language (tools/check_contracts.py --mask)
# ---------------------------------------------------------------------------

MASK_REGISTRY: dict[str, str] = {
    "full": "Full() — every pair attends",
    "causal": "Causal() — k <= q",
    "window": "window:W — SlidingWindow(W), |q - k| < W",
    "prefix": "prefix:P — PrefixLM(P), bidirectional prefix + causal",
    "dilated": "dilated:S[+O] — Dilated(S, O), (q - k) % S == O",
    "docs": "docs:0,16,32 — DocumentMask(starts)",
    "segments": "Segments() — runtime per-token ids",
    "perhead": "perhead(a;b;...) — per-head mask selection",
}

_TOKEN_RE = re.compile(
    r"\s*(perhead\(|[()&|~;]|[a-z]+(?::[0-9,+]+)?)\s*"
)


def _leaf(tok: str) -> Mask:
    name, _, arg = tok.partition(":")
    if name == "full":
        return Full()
    if name == "causal":
        return Causal()
    if name == "segments":
        return Segments()
    if name == "window":
        if not arg:
            raise MaskParseError("window needs an argument: window:W")
        return SlidingWindow(int(arg))
    if name == "prefix":
        if not arg:
            raise MaskParseError("prefix needs an argument: prefix:P")
        return PrefixLM(int(arg))
    if name == "dilated":
        if not arg:
            raise MaskParseError("dilated needs an argument: dilated:S[+O]")
        stride, _, off = arg.partition("+")
        return Dilated(int(stride), int(off) if off else 0)
    if name == "docs":
        if not arg:
            raise MaskParseError("docs needs arguments: docs:0,16,32")
        return DocumentMask(tuple(int(s) for s in arg.split(",")))
    raise MaskParseError(
        f"unknown mask {name!r}; the registry knows: "
        + "; ".join(f"{k} ({v})" for k, v in sorted(MASK_REGISTRY.items()))
    )


def parse_mask(expr: str) -> Mask:
    """Parse the tiny textual form: leaves from :data:`MASK_REGISTRY`,
    combinators ``&`` (and), ``|`` (or), ``~`` (not), parentheses, and
    ``perhead(a;b)``.  Examples: ``causal&window:512``,
    ``prefix:128|docs:0,64``, ``perhead(causal;causal&window:64)``.
    """
    tokens: list[str] = []
    pos = 0
    s = expr.strip()
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m or not m.group(1):
            raise MaskParseError(
                f"cannot tokenize mask expression at {s[pos:]!r}; the "
                f"registry knows: " + ", ".join(sorted(MASK_REGISTRY))
            )
        tokens.append(m.group(1))
        pos = m.end()
    tokens.append("$")
    idx = [0]

    def peek() -> str:
        return tokens[idx[0]]

    def eat(tok: str | None = None) -> str:
        t = tokens[idx[0]]
        if tok is not None and t != tok:
            raise MaskParseError(f"expected {tok!r}, got {t!r} in {expr!r}")
        idx[0] += 1
        return t

    def atom() -> Mask:
        t = peek()
        if t == "~":
            eat()
            return Not(atom())
        if t == "(":
            eat()
            m = or_expr()
            eat(")")
            return m
        if t == "perhead(":
            eat()
            parts = [or_expr()]
            while peek() == ";":
                eat()
                parts.append(or_expr())
            eat(")")
            return PerHead(tuple(parts))
        if t in ("&", "|", ")", ";", "$"):
            raise MaskParseError(f"expected a mask at {t!r} in {expr!r}")
        eat()
        return _leaf(t)

    def and_expr() -> Mask:
        m = atom()
        while peek() == "&":
            eat()
            m = m & atom()
        return m

    def or_expr() -> Mask:
        m = and_expr()
        while peek() == "|":
            eat()
            m = m | and_expr()
        return m

    out = or_expr()
    if peek() != "$":
        raise MaskParseError(f"trailing input {peek()!r} in {expr!r}")
    return out
