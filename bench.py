"""Benchmark: causal flash attention + train-step throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "TFLOPs/chip", "vs_baseline": N,
   "fwdbwd_tflops": ..., "tokens_per_sec": ..., ...}

North-star config (BASELINE.json): seq_len=262144, causal, 8 heads — both
attention TFLOPs/chip AND tokens/sec (train step: fwd+bwd+adam).  The
reference publishes no performance numbers (BASELINE.md), so
``vs_baseline`` reports the fraction of the chip's bf16 peak (MFU) —
a hardware-grounded, round-over-round comparable scalar.

Measurement hygiene: seeded random inputs (degenerate softmax rows on
constant inputs can distort timing), compile time recorded separately from
step time, per-attempt subprocess isolation with hard timeouts (TPU
compiles through this image's remote-compile relay can take minutes or
hang), and a quick-guarantee + target-first ladder so the parent never
fails to print a JSON line.

Relay-aware timing: through this image's axon TPU tunnel,
``block_until_ready`` returns immediately and independently-enqueued
executions can complete out of order — both standard timing idioms
report fiction.  Each measurement is therefore a single jitted
``lax.scan`` whose iterations are chained by a data dependency, synced by
fetching a scalar, with the separately-measured fetch round-trip
subtracted.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import traceback

TARGET_SEQ = 262144
HEADS = 8
DIM_HEAD = 64


def _load_repo_module(name: str, *relpath: str):
    """Load a package module by FILE PATH, bypassing the package
    ``__init__`` chain: this parent process must touch no jax code before
    the subprocess-isolated device probe (a wedged tunnel can hang
    jax-level work — the exact state the probe exists to detect).  Only
    valid for the modules that are stdlib-only at module level by design
    (resilience.py, telemetry.py, analysis/perfgate.py)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name,
        os.path.join(os.path.dirname(os.path.abspath(__file__)), *relpath),
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod  # dataclass field resolution
    spec.loader.exec_module(mod)
    return mod


_GATE_SCHEMA_CACHE: list[int] = []


def _gate_schema() -> int:
    """The perf-gate history schema version (``analysis/perfgate.py``),
    stamped on every phase payload so ``tools/perf_gate.py``'s ingest can
    version-check rounds.  Loaded by file path ONCE per process; returns
    0 (unknown) if the module cannot load — a stamping failure must
    never cost a bench round."""
    if _GATE_SCHEMA_CACHE:
        return _GATE_SCHEMA_CACHE[0]
    try:
        mod = _load_repo_module(
            "_bench_perfgate", "ring_attention_tpu", "analysis",
            "perfgate.py",
        )
        version = int(mod.GATE_SCHEMA_VERSION)
    except Exception:  # noqa: BLE001
        version = 0
    _GATE_SCHEMA_CACHE.append(version)
    return version

# bf16 peak TFLOPs per chip by TPU generation (dense)
PEAK_TFLOPS = {
    "v5 lite": 197.0,  # v5e
    "v5e": 197.0,
    "v5p": 459.0,
    "v4": 275.0,
    "v6e": 918.0,
}

# attention FLOPs: 2 matmuls fwd; bwd recomputes scores + 4 grad matmuls
# (dv, dp, dq, dk) => 2.5x fwd; causal halves the work
FWD_MATMULS = 2
FWDBWD_MATMULS = 7


def _attn_fn(impl: str, seq_len: int, head_chunks: int | None = None):
    from functools import partial

    if impl == "pallas":
        from ring_attention_tpu.ops.pallas_flash import pallas_flash_attention

        return partial(
            pallas_flash_attention, causal=True, head_chunks=head_chunks
        )
    from ring_attention_tpu.ops.flash import flash_attention

    bucket = min(1024, seq_len)
    qc = 2048 if seq_len > 2048 else None  # two-level blocking for memory
    return partial(
        flash_attention, causal=True, bucket_size=bucket, q_chunk_size=qc
    )


def _device_peak():
    import jax

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    peak = next((v for k, v in PEAK_TFLOPS.items() if k in kind), 197.0)
    return dev, peak


def _fetch_rtt(samples: int = 3):
    from ring_attention_tpu.utils.benchtime import fetch_rtt

    return fetch_rtt(samples)


def _timed(chained_fn, args, iters):
    from ring_attention_tpu.utils.benchtime import timed_chained

    return timed_chained(chained_fn, args, iters)


def _cost_fields(chained, args, secs_per_iter, iters):
    """Best-effort XLA cost + memory analysis of the timed executable:
    the compiler-counted FLOPs/bytes next to the analytic formula, the
    achieved HBM bandwidth (``bytes accessed`` over the measured wall
    time), and the compiled peak-memory accounting (``temp_bytes`` is the
    scratch high-water mark the chunking/remat knobs shrink — the 1M
    claim as a number, not prose).  The lowering hits the jit cache, so
    this re-lower is cheap; any failure returns ``{}`` — diagnostics
    never fail a measurement."""
    try:
        from ring_attention_tpu.utils.telemetry import (
            compiled_cost,
            compiled_memory,
        )

        exe = chained.lower(*args).compile()
        cost = compiled_cost(exe)
        mem = compiled_memory(exe)
    except Exception:  # noqa: BLE001
        return {}
    out = {}
    if cost.get("xla_flops"):
        out["xla_flops"] = cost["xla_flops"]
    if cost.get("bytes_accessed") and secs_per_iter > 0:
        out["bytes_accessed"] = cost["bytes_accessed"]
        # the executable runs `iters` chained iterations per call
        out["hbm_gbps"] = round(
            cost["bytes_accessed"] / (secs_per_iter * iters) / 1e9, 1
        )
    for key in ("temp_bytes", "argument_bytes", "output_bytes",
                "host_temp_bytes", "host_argument_bytes"):
        if key in mem:
            out[key] = mem[key]
    return out


def _degradation_fields():
    """Kernel-fallback record for this worker's JSON (utils/telemetry.py):
    a run that silently lost its Pallas kernels must say so in the bench
    output, not only in a scrolled-away warning."""
    try:
        from ring_attention_tpu.utils.telemetry import degradation_fields

        return degradation_fields()
    except Exception:  # noqa: BLE001
        return {}


def _fingerprint_worker() -> None:
    """Collective fingerprint of the hot entry points, from the contract
    checker (``analysis/contracts.py``) on simulated CPU devices.

    Per-strategy forward collective counts (ppermute / all_to_all /
    all_gather) land in the bench JSON so the perf trajectory catches a
    comms regression — an extra hop, an accidental O(seq) gather — even
    when tokens/sec moves for unrelated reasons.  Needs no TPU: the
    compiled collective sequence is backend-independent at this level, so
    the fingerprint is emitted even on rounds where the TPU tunnel is
    wedged.  Env must be set before the first jax import, which is why
    this worker runs in its own subprocess.
    """
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"

    from ring_attention_tpu.analysis.contracts import collective_fingerprint

    print(json.dumps(collective_fingerprint()))


def _coverage_worker() -> None:
    """Tile-coverage fingerprint (``analysis/coverage.py``): per-row
    compact-grid tile counts from the coverage prover, next to the
    collective fingerprint in the bench JSON — a mask/hint change that
    starts visiting dead tiles (or dropping live ones) shows up as a
    fingerprint diff in the perf trajectory even on wedged-TPU rounds.
    Pure numpy + trace-time helpers: no devices, no compiles."""
    os.environ["JAX_PLATFORMS"] = "cpu"

    from ring_attention_tpu.analysis.coverage import coverage_fingerprint

    print(json.dumps(coverage_fingerprint()))


def _protocol_worker() -> None:
    """Fused-ring DMA-protocol fingerprint (bench phase 0f): schedverify's
    derived primitive counts, PROTOCOL row count, per-ring model event
    counts, and total violations (0 on a healthy tree), from
    ``analysis/schedverify.py::protocol_fingerprint`` — the verified hop
    schedule as a pinned number, so any edit to the kernel's DMA/
    semaphore protocol (or to its declared table) shows up in the perf
    trajectory even on wedged-TPU rounds.  The extraction cross-check
    traces the kernel on the simulated 8-device ring; env must precede
    the first jax import, hence the subprocess."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"

    from ring_attention_tpu.analysis.schedverify import protocol_fingerprint

    print(json.dumps(protocol_fingerprint()))


def _multihost_worker() -> None:
    """Multihost dryrun fingerprint (bench phase 0e): the hierarchical
    ``(dcn_data, data, ring[, ulysses])`` mesh's forward collective
    counts + the machine-checked dcn-isolation verdict, from
    ``analysis/contracts.py::dcn_collective_fingerprint`` on simulated
    CPU devices.

    This is the pod-scale placement contract as a pinned number: zero
    ring/ulysses collectives over the dcn axis, proven from optimized
    HLO — so a change that starts hopping rings over DCN shows up in the
    perf trajectory (``analysis/perfgate.py`` gates the family exactly)
    even on wedged-TPU rounds.  Env must precede the first jax import,
    hence the subprocess."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"

    from ring_attention_tpu.analysis.contracts import (
        dcn_collective_fingerprint,
    )

    print(json.dumps(dcn_collective_fingerprint()))


def _window262k_worker(extra: dict) -> None:
    """Sliding-window 262k certified-grid accounting (CPU-countable).

    Lowers ``Causal() & SlidingWindow(w)`` and plain ``Causal()`` at the
    north-star forward shape through the mask algebra (the same
    ``band_plan`` grids a Pallas launch would run), certifies both
    (``masks.certify`` — elementwise proof at the capped spec, closed-
    form-vs-enumeration tile accounting at the full 262k shape), and
    reports the certified work-tile reduction the window buys over
    causal.  Pure numpy — rides the pre-probe slot like the coverage
    fingerprint, so the number lands even on wedged-TPU rounds; a timed
    windowed forward belongs to a future hardware phase.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"

    from ring_attention_tpu import masks as M

    seq = int(extra.get("seq", TARGET_SEQ))
    window = int(extra.get("window", 4096))
    block = int(extra.get("block", 1024))
    spec = M.GridSpec(strategy="single", n_local=seq, block_q=block,
                      block_k=block)
    masks = {
        "causal": M.Causal(),
        "window": M.Causal() & M.SlidingWindow(window),
    }
    payload: dict = {"seq": seq, "window": window, "block": block}
    tiles = {}
    for name, mask in masks.items():
        cert = M.certify(mask, spec)
        low = M.lower(mask, spec)
        work = sum(h.plan.work_tiles for h in low.hops if h.plan is not None)
        total = sum(len(h.plan.tile_q) for h in low.hops
                    if h.plan is not None)
        tiles[name] = work
        payload[f"{name}_work_tiles"] = work
        payload[f"{name}_tiles"] = total
        payload[f"{name}_certified"] = cert.ok
        payload[f"{name}_proof_n"] = cert.proof_n
    payload["tile_reduction_x"] = round(
        tiles["causal"] / max(tiles["window"], 1), 2
    )
    print(json.dumps(payload))


def _train1m_mem_worker(extra: dict) -> None:
    """CPU-provable half of the ``train1m`` phase: the memory claim.

    Compiles the SAME train-step program twice at a proof shape — once
    with the memory-axis knobs on (blockwise FFN + chunked CE +
    ``nothing_saveable`` remat), once dense — and reports the compiler's
    own peak-scratch accounting (``memory_analysis`` temp bytes) for
    both: the acceptance relation is *chunked strictly below dense at
    equal shape*.  Rides the forced-CPU pre-probe slot like the
    fingerprint worker, so the number lands even on wedged-TPU rounds
    (the backend-independent program structure is what the knobs change;
    hardware tokens/sec comes from the post-probe timed phase).  Also
    emits the analytic peak-HBM estimate of the full 2^20-token target
    config (``telemetry.train_memory_estimate``) next to a v5e chip's
    16 GB so the "1M fits" claim is checkable arithmetic.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    import jax.numpy as jnp

    from ring_attention_tpu.utils import enable_compile_cache
    from ring_attention_tpu.utils.telemetry import (
        compiled_memory,
        train_memory_estimate,
    )

    enable_compile_cache()
    target_seq = int(extra.get("target_seq", 1 << 20))
    proof_seq = int(extra.get("proof_seq", 8192))
    ff_chunk = int(extra.get("ff_chunk", 512))
    loss_chunk = int(extra.get("loss_chunk", 512))
    vocab = int(extra.get("vocab", 256))

    from ring_attention_tpu.models import RingTransformer

    def proof_model(chunk: bool):
        # the train worker's dims, but bucket 512 instead of 2048: the
        # relation under proof is the FFN term, and at bucket 2048 the
        # attention recompute's tile scratch (h x bucket^2 f32) swamps it
        # with scheduling noise at CPU-compilable sequence lengths
        return RingTransformer(
            num_tokens=vocab, dim=512, depth=2, causal=True, heads=HEADS,
            dim_head=DIM_HEAD, bucket_size=min(512, proof_seq), rotary=True,
            remat=True, remat_policy="nothing_saveable",
            ff_chunk_size=ff_chunk if chunk else None,
            loss_chunk_size=loss_chunk if chunk else None,
            dtype=jnp.bfloat16,
        )

    chunked, dense = proof_model(True), proof_model(False)
    params = chunked.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 129), jnp.int32),
        return_loss=True,
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (1, proof_seq + 1), 0, vocab, jnp.int32
    )

    def temp_bytes(model):
        fn = jax.jit(jax.value_and_grad(
            lambda p, t: model.apply(p, t, return_loss=True)
        ))
        return compiled_memory(fn.lower(params, tokens).compile())

    mem_c = temp_bytes(chunked)
    mem_d = temp_bytes(dense)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    # the estimate describes the TARGET (phase 7) configuration — its
    # chunk sizes are emitted alongside so the arithmetic is checkable
    # against exactly the config the row claims to describe
    target_ff = int(extra.get("target_ff_chunk", 2048))
    target_loss = int(extra.get("target_loss_chunk", 2048))
    est_kw = dict(
        seq_len=target_seq, dim=512, depth=2, heads=HEADS, vocab=vocab,
        n_params=n_params, dtype_bytes=2, remat_policy="save_attn",
    )
    est_chunked = train_memory_estimate(
        ff_chunk_size=target_ff, loss_chunk_size=target_loss, **est_kw
    )
    est_dense = train_memory_estimate(**est_kw)
    tc, td = mem_c.get("temp_bytes"), mem_d.get("temp_bytes")
    print(json.dumps({
        "target_seq": target_seq,
        "target_ff_chunk": target_ff,
        "target_loss_chunk": target_loss,
        "peak_hbm_estimate_gb": est_chunked["peak_hbm_gb"],
        "peak_hbm_dense_estimate_gb": est_dense["peak_hbm_gb"],
        "proof_seq": proof_seq,
        "proof_ff_chunk": ff_chunk,
        "proof_loss_chunk": loss_chunk,
        "temp_bytes_chunked": tc,
        "temp_bytes_dense": td,
        "chunked_below_dense": (
            tc is not None and td is not None and tc < td
        ),
        "temp_ratio": (
            round(td / tc, 2) if tc and td else None
        ),
    }))


def _worker(impl: str, seq_len: int, mode: str, extra: dict) -> None:
    """Runs one timed measurement and prints its own JSON line.

    ``extra`` carries per-attempt config: heads / kv_heads / dim_head for
    shape variants (GQA, wide head), remat_policy for the train step.
    """
    import jax
    import jax.numpy as jnp

    from ring_attention_tpu.utils import enable_compile_cache

    enable_compile_cache()

    if mode == "train":
        _train_worker(impl, seq_len, extra.get("remat_policy"),
                      vocab=extra.get("vocab", 256),
                      loss_chunk_size=extra.get("loss_chunk_size"),
                      ff_chunk_size=extra.get("ff_chunk_size"))
        return
    if mode == "hops":
        _hops_worker(seq_len, int(extra.get("ring", 4)))
        return
    if mode == "hybrid":
        # "world" = TOTAL sequence-parallel degree (outer ring = world /
        # ulysses); "ring" is accepted as a legacy alias for it
        _hybrid_worker(seq_len,
                       int(extra.get("world", extra.get("ring", 4))),
                       int(extra.get("ulysses", 2)))
        return
    if mode == "counter":
        _counter_worker(seq_len, int(extra.get("ring", 4)),
                        extra.get("hop_compression"))
        return
    if mode == "q8":
        _q8_worker(seq_len, int(extra.get("ring", 4)))
        return
    if mode == "fused":
        _fused_worker(seq_len, int(extra.get("ring", 4)))
        return
    if mode == "decode":
        _decode_worker(impl, seq_len, extra)
        return
    if mode == "packed":
        _packed_worker(impl, seq_len, extra)
        return

    heads = int(extra.get("heads", HEADS))
    kv_heads = int(extra.get("kv_heads", heads))
    dim_head = int(extra.get("dim_head", DIM_HEAD))
    head_chunks = extra.get("head_chunks")
    if head_chunks and impl != "pallas":
        # fail fast: a sweep step must not silently measure the default
        # config in a scarce hardware window
        raise ValueError(f"head_chunks only applies to impl='pallas', "
                         f"got impl={impl!r}")

    dev, peak = _device_peak()
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (1, heads, seq_len, dim_head), jnp.bfloat16)
    k = jax.random.normal(ks[1], (1, kv_heads, seq_len, dim_head), jnp.bfloat16)
    v = jax.random.normal(ks[2], (1, kv_heads, seq_len, dim_head), jnp.bfloat16)

    attn = _attn_fn(
        impl, seq_len, int(head_chunks) if head_chunks else None
    )
    iters = 3 if seq_len >= TARGET_SEQ else 10

    if mode == "fwdbwd":
        grad_fn = jax.grad(
            lambda q, k, v: attn(q, k, v).astype(jnp.float32).sum(),
            argnums=(0, 1, 2),
        )

        @jax.jit
        def chained(q, k, v):
            def body(carry, _):
                dq, dk, dv = grad_fn(carry, k, v)
                # chain through all three grads so none is dead code
                nxt = (carry + 1e-6 * dq.astype(carry.dtype)
                       + (dk.mean() + dv.mean()).astype(carry.dtype) * 1e-9)
                return nxt, dq[0, 0, 0, 0]
            out, ys = jax.lax.scan(body, q, None, length=iters)
            return ys.sum()

        matmuls = FWDBWD_MATMULS
    else:

        @jax.jit
        def chained(q, k, v):
            def body(carry, _):
                o = attn(carry, k, v)
                # perturb rather than replace: feeding o back as q would
                # collapse score variance into the degenerate-softmax
                # regime the seeded inputs exist to avoid
                return carry + 1e-3 * o.astype(carry.dtype), o[0, 0, 0, 0]
            out, ys = jax.lax.scan(body, q, None, length=iters)
            return ys.astype(jnp.float32).sum()

        matmuls = FWD_MATMULS

    compile_s, secs = _timed(chained, (q, k, v), iters)

    flops = matmuls * 2 * seq_len * seq_len * heads * dim_head * 0.5  # causal
    tflops = flops / secs / 1e12
    print(
        json.dumps(
            {
                # 4 decimals: small-shape CPU-backend runs (the test
                # suite's contract checks) land in the 1e-3 TFLOPs range
                # and must not round to a zero measurement
                "value": round(tflops, 4),
                "vs_baseline": round(tflops / peak, 4),
                # same number under its proper name (docs/observability.md)
                "mfu": round(tflops / peak, 4),
                **_cost_fields(chained, (q, k, v), secs, iters),
                **_degradation_fields(),
                "seq_len": seq_len,
                "impl": impl,
                "heads": heads,
                "kv_heads": kv_heads,
                "dim_head": dim_head,
                # head_chunks only applies to the pallas launcher; don't
                # record it on impls where _attn_fn drops it
                **({"head_chunks": int(head_chunks)}
                   if head_chunks and impl == "pallas" else {}),
                "device": getattr(dev, "device_kind", str(dev)),
                "ms_per_step": round(secs * 1e3, 2),
                "compile_s": round(compile_s, 1),
            }
        )
    )


def _hop_sequence(q, k, v, ring: int, n_local: int, scale: float):
    """Device R-1's per-hop span calls of a contiguous causal ring: seed
    partials, in-kernel carry resume, fused normalized final write
    (parallel/ring.py ``_ring_fwd_pallas``).  Shared by the pure-ring and
    hybrid hop workers so their kernel schedules cannot diverge."""
    from ring_attention_tpu.ops.pallas_flash import (
        pallas_flash_fused,
        pallas_flash_partials,
    )

    def hop_kv(i):  # device R-1's hop i holds origin (R-1-i)'s block
        j = ring - 1 - i
        sl = slice(j * n_local, (j + 1) * n_local)
        return k[:, :, sl], v[:, :, sl]

    if ring == 1:  # degenerate factoring: one fused local sweep
        out, _ = pallas_flash_fused(
            q, k, v, scale=scale, causal_offset=0, block_q=1024, block_k=1024,
        )
        return out
    kh, vh = hop_kv(0)
    carry = pallas_flash_partials(
        q, kh, vh, scale=scale, causal_offset=0, block_q=1024, block_k=1024,
    )
    for i in range(1, ring - 1):
        kh, vh = hop_kv(i)
        carry = pallas_flash_partials(  # fully-visible span, resumed
            q, kh, vh, scale=scale, block_q=1024, block_k=1024, carry=carry,
        )
    kh, vh = hop_kv(ring - 1)
    out, _ = pallas_flash_fused(
        q, kh, vh, scale=scale, block_q=1024, block_k=1024, carry=carry,
    )
    return out


def _hybrid_worker(seq_len: int, world: int, ulysses: int) -> None:
    """Single-chip simulation of the hybrid Ulysses x Ring hop sequence.

    At equal sequence-parallel world, the hybrid factoring trades the
    ``world``-hop ring for a ``world/ulysses``-hop ring over ``h/ulysses``
    heads (the Ulysses all-to-all legs ride the fast intra-node tier and
    have no per-hop latency chain).  This worker runs the per-device span
    calls that remain after the all-to-all — the exact kernel sequence of
    ``parallel/hybrid.py``'s ring leg: seed, in-kernel resume, fused final
    write — and reports the hop count next to tokens/sec so the
    ``hybrid262k`` entry is directly comparable with the ``ring_hops``
    one."""
    import jax
    import jax.numpy as jnp

    assert world % ulysses == 0, f"ulysses {ulysses} must divide world {world}"
    ring = world // ulysses
    heads = HEADS // ulysses
    assert heads >= 1, f"ulysses {ulysses} needs at least {ulysses} heads"
    dev, peak = _device_peak()
    n_local = seq_len // ring
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (1, heads, n_local, DIM_HEAD), jnp.bfloat16)
    k = jax.random.normal(ks[1], (1, heads, seq_len, DIM_HEAD), jnp.bfloat16)
    v = jax.random.normal(ks[2], (1, heads, seq_len, DIM_HEAD), jnp.bfloat16)
    scale = DIM_HEAD**-0.5

    def hop_sequence(q):
        return _hop_sequence(q, k, v, ring, n_local, scale)

    iters = 3

    @jax.jit
    def chained(q):
        def body(carry, _):
            o = hop_sequence(carry)
            return carry + 1e-3 * o.astype(carry.dtype), o[0, 0, 0, 0]

        out, ys = jax.lax.scan(body, q, None, length=iters)
        return ys.astype(jnp.float32).sum()

    compile_s, secs = _timed(chained, (q,), iters)
    flops = (
        FWD_MATMULS * 2 * heads * DIM_HEAD * n_local * n_local * (ring - 0.5)
    )
    tflops = flops / secs / 1e12
    print(
        json.dumps(
            {
                "value": round(tflops, 4),
                "vs_baseline": round(tflops / peak, 4),
                "mfu": round(tflops / peak, 4),
                "seq_len": seq_len,
                "world": world,
                "ulysses": ulysses,
                "ring": ring,
                # inter-device transfers in the latency chain, vs world-1
                # for the pure ring at the same world size
                "hops": ring - 1,
                "pure_ring_hops": world - 1,
                # whole-slice rate: the world processes seq_len queries per
                # step while each device runs this hop sequence
                "tokens_per_sec": round(seq_len / secs),
                "impl": "pallas-hybrid",
                "device": getattr(dev, "device_kind", str(dev)),
                "ms_per_step": round(secs * 1e3, 2),
                "compile_s": round(compile_s, 1),
            }
        )
    )


def _hops_worker(seq_len: int, ring: int) -> None:
    """Single-chip simulation of a causal ring's per-device hop sequence.

    Runs the exact span calls device ``ring-1`` of a contiguous causal ring
    makes (parallel/ring.py ``_ring_fwd_pallas``): hop 0 = compact diagonal
    sweep seeding the carry, hops 1..R-2 = full sweeps resuming the carry
    in-kernel, last hop = fused normalized write.  Validates that the
    measured static-offset kernel rates survive on the path a real
    multi-chip ring executes (VERDICT r2 missing #1 'done' criterion).
    """
    import jax
    import jax.numpy as jnp

    dev, peak = _device_peak()
    n_local = seq_len // ring
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (1, HEADS, n_local, DIM_HEAD), jnp.bfloat16)
    k = jax.random.normal(ks[1], (1, HEADS, seq_len, DIM_HEAD), jnp.bfloat16)
    v = jax.random.normal(ks[2], (1, HEADS, seq_len, DIM_HEAD), jnp.bfloat16)
    scale = DIM_HEAD**-0.5

    def hop_sequence(q):
        return _hop_sequence(q, k, v, ring, n_local, scale)

    iters = 3

    @jax.jit
    def chained(q):
        def body(carry, _):
            o = hop_sequence(carry)
            return carry + 1e-3 * o.astype(carry.dtype), o[0, 0, 0, 0]

        out, ys = jax.lax.scan(body, q, None, length=iters)
        return ys.astype(jnp.float32).sum()

    compile_s, secs = _timed(chained, (q,), iters)
    # hop 0 is half-masked; hops 1..R-1 are full n_local x n_local spans
    flops = (
        FWD_MATMULS * 2 * HEADS * DIM_HEAD * n_local * n_local * (ring - 0.5)
    )
    tflops = flops / secs / 1e12
    print(
        json.dumps(
            {
                "value": round(tflops, 4),
                "vs_baseline": round(tflops / peak, 4),
                "mfu": round(tflops / peak, 4),
                "seq_len": seq_len,
                "ring": ring,
                "impl": "pallas-hops",
                "device": getattr(dev, "device_kind", str(dev)),
                "ms_per_step": round(secs * 1e3, 2),
                "compile_s": round(compile_s, 1),
            }
        )
    )


def _fused_worker(seq_len: int, ring: int) -> None:
    """Single-chip timing of the fused-ring kernel's whole hop chain.

    Where ``_hops_worker`` times the scan path's per-hop SEQUENCE of span
    launches (one ``pallas_call`` per hop, carry re-materialized through
    HBM at every boundary), this worker times the SAME work as ONE
    launch: ``ops/pallas_ring.py::fused_ring_local`` sweeps every hop's
    KV span inside a single kernel, the f32 ``(acc, m, l)`` state
    resident in VMEM scratch across hops.  The hop schedule is the real
    one — ``parallel/ring.py::_fused_tables`` for the causal last rank,
    the exact tables the multi-chip fused ring prefetches — so
    ``fused262k / ring_hops_tflops`` is the measured launch-boundary
    cost the fused path deletes.  The analytic comms terms ride from
    ``telemetry.ring_comms_accounting(impl="fused")``: ``kernel_launches
    == 1``, ``dispatch_overhead_s == 0``, ``fwd_collectives == 0`` (hops
    are in-kernel remote DMAs, pinned by phase 0's ``fused_ring``
    fingerprint row), overlap ~1.0 at the north-star shape.
    """
    import jax
    import jax.numpy as jnp

    from ring_attention_tpu.ops import pallas_ring
    from ring_attention_tpu.parallel import ring as ring_mod
    from ring_attention_tpu.utils.telemetry import ring_comms_accounting

    dev, peak = _device_peak()
    n_local = seq_len // ring
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (1, HEADS, n_local, DIM_HEAD), jnp.bfloat16)
    k = jax.random.normal(ks[1], (1, HEADS, seq_len, DIM_HEAD), jnp.bfloat16)
    v = jax.random.normal(ks[2], (1, HEADS, seq_len, DIM_HEAD), jnp.bfloat16)
    scale = DIM_HEAD**-0.5

    # causal last rank: hop 0 = banded diagonal, hops 1..R-1 full spans —
    # the same (ring - 0.5) work as _hops_worker's span sequence
    origins, his, los, works = ring_mod._fused_tables(
        ring - 1, ring, n_local, True, False, None, ring
    )

    def hop_sequence(q):
        out, _ = pallas_ring.fused_ring_local(
            q, k, v, origins=origins, his=his, los=los, works=works,
            n_local=n_local, scale=scale, block_q=1024, block_k=1024,
        )
        return out

    iters = 3

    @jax.jit
    def chained(q):
        def body(carry, _):
            o = hop_sequence(carry)
            return carry + 1e-3 * o.astype(carry.dtype), o[0, 0, 0, 0]

        out, ys = jax.lax.scan(body, q, None, length=iters)
        return ys.astype(jnp.float32).sum()

    compile_s, secs = _timed(chained, (q,), iters)
    flops = (
        FWD_MATMULS * 2 * HEADS * DIM_HEAD * n_local * n_local * (ring - 0.5)
    )
    tflops = flops / secs / 1e12
    comms = ring_comms_accounting(
        ring_size=ring, seq_len=seq_len, kv_heads=HEADS, heads=HEADS,
        dim_head=DIM_HEAD, dtype_bytes=2, impl="fused", peak_tflops=peak,
    )
    print(
        json.dumps(
            {
                "value": round(tflops, 4),
                "vs_baseline": round(tflops / peak, 4),
                "mfu": round(tflops / peak, 4),
                "seq_len": seq_len,
                "ring": ring,
                "kernel_launches": comms["kernel_launches"],
                "dispatch_overhead_s": comms["dispatch_overhead_s"],
                "hop_bytes": comms["hop_bytes"],
                "fwd_collectives": comms["fwd_collectives"],
                "bwd_collectives": comms["bwd_collectives"],
                "hop_overlap_fraction": comms["hop_overlap_fraction"],
                "tokens_per_sec": round(seq_len / secs),
                "impl": "pallas-fused",
                "device": getattr(dev, "device_kind", str(dev)),
                "ms_per_step": round(secs * 1e3, 2),
                "compile_s": round(compile_s, 1),
            }
        )
    )


def _counter_worker(seq_len: int, ring: int, hop_compression: str | None) -> None:
    """Single-chip simulation of the TokenRing counter-rotated hop chain.

    The counter schedule's per-device COMPUTE is the same span sequence as
    the baseline ring (pairing ``i`` attends the block ``i`` ranks behind
    — ``parallel/ring.py::_counter_fwd``); what changes on hardware is the
    communication (full-duplex split, int8 payloads).  This worker times
    the compute chain the compressed variant actually executes — per-hop
    int8 dequantization feeding the resumed span kernels — and reports
    the ANALYTIC comms terms (bytes/hop for the compressed KV handle and
    the f32 Q-pack, fwd/bwd collective counts) from
    ``telemetry.ring_comms_accounting``, so the ``counter262k`` entry sits
    next to ``ring_hops`` with directly comparable fields.  The collective
    fingerprint (phase 0) pins the corresponding hop COUNTS from compiled
    HLO even on wedged-TPU rounds.
    """
    import jax
    import jax.numpy as jnp

    from ring_attention_tpu.ops.pallas_flash import (
        pallas_flash_fused,
        pallas_flash_partials,
    )
    from ring_attention_tpu.parallel.collectives import (
        dequantize_ring_payload,
        quantize_ring_payload,
    )
    from ring_attention_tpu.utils.telemetry import ring_comms_accounting

    dev, peak = _device_peak()
    n_local = seq_len // ring
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (1, HEADS, n_local, DIM_HEAD), jnp.bfloat16)
    k = jax.random.normal(ks[1], (1, HEADS, seq_len, DIM_HEAD), jnp.bfloat16)
    v = jax.random.normal(ks[2], (1, HEADS, seq_len, DIM_HEAD), jnp.bfloat16)
    scale = DIM_HEAD**-0.5

    def hop_sequence(q):
        if hop_compression != "int8":
            return _hop_sequence(q, k, v, ring, n_local, scale)
        handle = quantize_ring_payload(k, v)  # once at ring entry

        def hop_kv(i):
            j = ring - 1 - i
            kh, vh = dequantize_ring_payload(
                handle[:, :, :, j * n_local:(j + 1) * n_local], q.dtype
            )
            return kh, vh

        kh, vh = hop_kv(0)
        carry = pallas_flash_partials(
            q, kh, vh, scale=scale, causal_offset=0,
            block_q=1024, block_k=1024,
        )
        for i in range(1, ring - 1):
            kh, vh = hop_kv(i)
            carry = pallas_flash_partials(
                q, kh, vh, scale=scale, block_q=1024, block_k=1024,
                carry=carry,
            )
        kh, vh = hop_kv(ring - 1)
        out, _ = pallas_flash_fused(
            q, kh, vh, scale=scale, block_q=1024, block_k=1024, carry=carry,
        )
        return out

    iters = 3

    @jax.jit
    def chained(q):
        def body(carry, _):
            o = hop_sequence(carry)
            return carry + 1e-3 * o.astype(carry.dtype), o[0, 0, 0, 0]

        out, ys = jax.lax.scan(body, q, None, length=iters)
        return ys.astype(jnp.float32).sum()

    compile_s, secs = _timed(chained, (q,), iters)
    flops = (
        FWD_MATMULS * 2 * HEADS * DIM_HEAD * n_local * n_local * (ring - 0.5)
    )
    tflops = flops / secs / 1e12
    comms = ring_comms_accounting(
        ring_size=ring, seq_len=seq_len, kv_heads=HEADS, heads=HEADS,
        dim_head=DIM_HEAD, dtype_bytes=2, counter_rotate=True,
        hop_compression=hop_compression, peak_tflops=peak,
    )
    print(
        json.dumps(
            {
                "value": round(tflops, 4),
                "vs_baseline": round(tflops / peak, 4),
                "mfu": round(tflops / peak, 4),
                "seq_len": seq_len,
                "ring": ring,
                "hop_compression": hop_compression,
                "hop_bytes": comms["hop_bytes"],
                "q_pack_bytes": comms["q_pack_bytes"],
                "fwd_collectives": comms["fwd_collectives"],
                "bwd_collectives": comms["bwd_collectives"],
                "hop_overlap_fraction": comms["hop_overlap_fraction"],
                "tokens_per_sec": round(seq_len / secs),
                "impl": "pallas-counter",
                "device": getattr(dev, "device_kind", str(dev)),
                "ms_per_step": round(secs * 1e3, 2),
                "compile_s": round(compile_s, 1),
            }
        )
    )


def _q8_worker(seq_len: int, ring: int) -> None:
    """Single-chip simulation of the int8 COMPUTE hop chain (PR 13).

    Where ``_counter_worker`` times the compressed ring's per-hop
    dequant feeding bf16 kernels, this worker times what the dequant-free
    composition actually executes: the KV payload quantized ONCE at ring
    entry with kernel-ready scales (``quant.pack_kv(v_block=...)``), each
    hop's span kernel consuming the int8 values + scales DIRECTLY
    (``compute_dtype="int8"`` / ``kv_quantized=``) with q re-quantized
    per hop and the f32 ``(acc, m, l)`` carry resumed in-kernel.  On
    v5e/v5p the int8 MXU rate is ~2x bf16 peak, so ``vs_baseline`` /
    ``mfu`` are reported against the BF16 peak (a number > the bf16 MFU
    ceiling is the int8 win, not an accounting error).  Operand/
    accumulator byte accounting and the wire terms ride along from
    ``telemetry.ring_comms_accounting(compute_dtype="int8")``; phase 0's
    collective fingerprint pins the ``counter_q8`` hop counts from
    compiled HLO even on wedged-TPU rounds.
    """
    import jax
    import jax.numpy as jnp

    from ring_attention_tpu.ops import quant
    from ring_attention_tpu.ops.pallas_flash import (
        pallas_flash_fused,
        pallas_flash_partials,
    )
    from ring_attention_tpu.utils.telemetry import ring_comms_accounting

    dev, peak = _device_peak()
    n_local = seq_len // ring
    blk = 1024
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (1, HEADS, n_local, DIM_HEAD), jnp.bfloat16)
    k = jax.random.normal(ks[1], (1, HEADS, seq_len, DIM_HEAD), jnp.bfloat16)
    v = jax.random.normal(ks[2], (1, HEADS, seq_len, DIM_HEAD), jnp.bfloat16)
    scale = DIM_HEAD**-0.5

    def hop_sequence(q):
        payload = quant.pack_kv(k, v, v_block=blk)  # once at ring entry

        def hop_feed(i):
            j = ring - 1 - i
            return quant.payload_kernel_feed(
                payload[:, :, :, j * n_local:(j + 1) * n_local], blk
            )

        carry = pallas_flash_partials(
            q, None, None, scale=scale, causal_offset=0,
            block_q=blk, block_k=blk,
            compute_dtype="int8", kv_quantized=hop_feed(0),
        )
        for i in range(1, ring - 1):
            carry = pallas_flash_partials(
                q, None, None, scale=scale, block_q=blk, block_k=blk,
                carry=carry, compute_dtype="int8", kv_quantized=hop_feed(i),
            )
        out, _ = pallas_flash_fused(
            q, None, None, scale=scale, block_q=blk, block_k=blk,
            carry=carry, compute_dtype="int8",
            kv_quantized=hop_feed(ring - 1),
        )
        return out

    iters = 3

    @jax.jit
    def chained(q):
        def body(carry, _):
            o = hop_sequence(carry)
            return carry + 1e-3 * o.astype(carry.dtype), o[0, 0, 0, 0]

        out, ys = jax.lax.scan(body, q, None, length=iters)
        return ys.astype(jnp.float32).sum()

    compile_s, secs = _timed(chained, (q,), iters)
    flops = (
        FWD_MATMULS * 2 * HEADS * DIM_HEAD * n_local * n_local * (ring - 0.5)
    )
    tflops = flops / secs / 1e12
    comms = ring_comms_accounting(
        ring_size=ring, seq_len=seq_len, kv_heads=HEADS, heads=HEADS,
        dim_head=DIM_HEAD, dtype_bytes=2, counter_rotate=True,
        hop_compression="int8", compute_dtype="int8", peak_tflops=peak,
    )
    print(
        json.dumps(
            {
                "value": round(tflops, 4),
                "vs_baseline": round(tflops / peak, 4),
                "mfu": round(tflops / peak, 4),
                "seq_len": seq_len,
                "ring": ring,
                "compute_dtype": "int8",
                "hop_compression": "int8",
                "hop_bytes": comms["hop_bytes"],
                "matmul_operand_bytes": comms["matmul_operand_bytes"],
                "accumulator_bytes": comms["accumulator_bytes"],
                "fwd_collectives": comms["fwd_collectives"],
                "bwd_collectives": comms["bwd_collectives"],
                "hop_overlap_fraction": comms["hop_overlap_fraction"],
                "tokens_per_sec": round(seq_len / secs),
                "impl": "pallas-q8",
                "device": getattr(dev, "device_kind", str(dev)),
                "ms_per_step": round(secs * 1e3, 2),
                "compile_s": round(compile_s, 1),
            }
        )
    )


def _decode_worker(impl: str, seq_len: int, extra: dict) -> None:
    """Single-token decode latency against a ``seq_len``-token KV cache.

    BASELINE config 5 (million-token context) is HBM-bandwidth-bound:
    the cost of a decode step IS the KV read.  ``impl="pallas"`` =
    ``pallas_flash_decode`` (cache read once per kv head);
    ``impl="dense"`` = the dense ``default_attention`` tile (the r2
    hardware-log path, 1.05 ms/token at 1M).  Reports ms/token and the
    effective KV-read bandwidth."""
    import jax
    import jax.numpy as jnp

    heads = int(extra.get("heads", HEADS))
    kv_heads = int(extra.get("kv_heads", 2))
    dev, _ = _device_peak()
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (1, heads, 1, DIM_HEAD), jnp.bfloat16)
    k = jax.random.normal(ks[1], (1, kv_heads, seq_len, DIM_HEAD), jnp.bfloat16)
    v = jax.random.normal(ks[2], (1, kv_heads, seq_len, DIM_HEAD), jnp.bfloat16)
    # live decode always carries a cache-validity mask (models/attention.py
    # _decode_mask); include its read in the measurement
    mask = jnp.ones((1, seq_len), jnp.bool_)

    block_k = extra.get("block_k")
    if block_k and impl not in ("pallas", "pallas_q8"):
        raise ValueError(f"decode block_k only applies to the pallas "
                         f"impls, got impl={impl!r}")
    if impl == "pallas":
        from ring_attention_tpu.ops.pallas_flash import pallas_flash_decode

        def attend(q, k, v, mask):
            out, _ = pallas_flash_decode(
                q, k, v, mask, block_k=int(block_k) if block_k else None
            )
            return out
    elif impl == "pallas_q8":
        # int8 cache: quantized OUTSIDE the timed loop (a live cache is
        # written quantized at decode_step time, read many times)
        from ring_attention_tpu.ops.pallas_flash import (
            pallas_flash_decode_q8,
            quantize_kv_cache,
        )

        def attend(q, kv, mask):
            out, _ = pallas_flash_decode_q8(
                q, kv, mask, block_k=int(block_k) if block_k else None
            )
            return out
    else:
        from ring_attention_tpu.ops.attention import default_attention

        def attend(q, k, v, mask):
            return default_attention(q, k, v, mask)

    iters = 50
    if impl == "pallas_q8":
        cache = (jax.jit(quantize_kv_cache)(k, v),)
        # int8 rows + f32 per-token scales actually read per step
        kv_bytes = 2 * kv_heads * seq_len * (DIM_HEAD + 4)
    else:
        cache = (k, v)
        kv_bytes = 2 * kv_heads * seq_len * DIM_HEAD * 2  # k+v, bf16

    # cache/mask as arguments, never closures: a jit-captured 537 MB cache
    # becomes an embedded constant (the relay's HTTP 413 failure mode)
    @jax.jit
    def chained(q, cache, mask):
        def body(carry, _):
            o = attend(carry, *cache, mask)
            return carry + 1e-3 * o.astype(carry.dtype), o[0, 0, 0, 0]

        out, ys = jax.lax.scan(body, q, None, length=iters)
        return ys.astype(jnp.float32).sum()

    compile_s, secs = _timed(chained, (q, cache, mask), iters)

    # per-call latency distribution: the chained scan above gives the
    # amortized mean; this eager loop (one dispatch + block per token,
    # the shape of a live decode server) feeds the mergeable fixed-bucket
    # histogram that the perfgate latency family and generate.py share
    from ring_attention_tpu.utils import tracing

    single = jax.jit(lambda q, cache, mask: attend(q, *cache, mask))
    single(q, cache, mask).block_until_ready()  # compile outside the loop
    hist = tracing.LatencyHistogram()
    for _ in range(30):
        t0 = tracing.perf_counter()
        single(q, cache, mask).block_until_ready()
        hist.record(tracing.perf_counter() - t0)
    print(
        json.dumps(
            {
                "decode_ms_per_token": round(secs * 1e3, 3),
                "decode_ms_p50": round(hist.percentile_ms(50), 3),
                "decode_ms_p95": round(hist.percentile_ms(95), 3),
                "decode_ms_p99": round(hist.percentile_ms(99), 3),
                "decode_kv_gbps": round(kv_bytes / secs / 1e9, 1),
                "decode_seq_len": seq_len,
                "decode_impl": impl,
                "decode_kv_heads": kv_heads,
                **({"decode_block_k": int(block_k)} if block_k else {}),
                "decode_compile_s": round(compile_s, 1),
                "device": getattr(dev, "device_kind", str(dev)),
            }
        )
    )


def _bench_transformer(impl: str, vocab: int, remat_policy: str | None,
                       loss_chunk_size: int | None = None,
                       ff_chunk_size: int | None = None):
    """The ONE benchmark RingTransformer config + its init, shared by the
    train and packed workers so their tokens/sec stay comparable (same
    dims, remat, dtype; params are seq-independent so init runs on a
    short sequence to keep it cheap)."""
    import jax
    import jax.numpy as jnp

    from ring_attention_tpu.models import RingTransformer

    model = RingTransformer(
        num_tokens=vocab,
        dim=512,
        depth=2,
        causal=True,
        heads=HEADS,
        dim_head=DIM_HEAD,
        bucket_size=2048,
        rotary=True,
        use_pallas=(impl == "pallas"),
        remat=True,
        remat_policy=remat_policy,
        loss_chunk_size=loss_chunk_size,
        ff_chunk_size=ff_chunk_size,
        dtype=jnp.bfloat16,
    )
    init_tokens = jnp.zeros((1, 129), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), init_tokens, return_loss=True,
                        segment_ids=jnp.zeros((1, 129), jnp.int32))
    return model, params


def _packed_worker(impl: str, seq_len: int, extra: dict) -> None:
    """Packed vs padded train-step throughput at one position budget.

    Real corpora are unequal documents.  The *padded* batch mimics the
    classic recipe: ``docs`` fixed slots per row, each holding a document
    filling 75% of the slot plus 25% pad (pad slots carry their own
    segment id, so they attend nothing real — but they still occupy
    positions).  The *packed* batch fills every position with a document
    token under segment-id masking.  Same (1, seq_len) compiled shapes,
    same step cost structure; the honest metric is USEFUL tokens/sec —
    what the padded recipe wastes, packing recovers (the tentpole win),
    on top of the kernels skipping/masking cross-document attention.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ring_attention_tpu.utils import make_train_step
    from ring_attention_tpu.utils.benchtime import timed_chained

    docs = int(extra.get("docs", 8))
    pad_frac = float(extra.get("pad_frac", 0.25))
    vocab = int(extra.get("vocab", 256))
    dev, _ = _device_peak()
    if seq_len % docs:
        raise ValueError(
            f"packed worker: docs={docs} must divide seq_len={seq_len}"
        )
    slot = seq_len // docs

    model, params = _bench_transformer(impl, vocab, "save_attn")
    opt = optax.adam(1e-3)

    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (1, seq_len + 1), 0, vocab, jnp.int32
    )
    # segment rows span seq_len + 1 tokens (the model shifts labels off the
    # last token); the final doc simply extends one slot position
    def with_tail(row):
        return jnp.asarray(np.append(row, row[-1])[None, :])

    # packed: docs equal slots, every position useful
    seg_packed = with_tail(np.repeat(np.arange(docs, dtype=np.int32), slot))
    # padded: each slot = useful prefix + pad tail in its own segment
    useful = int(slot * (1.0 - pad_frac))
    row = np.repeat(np.arange(docs, dtype=np.int32) * 2, slot)
    for i in range(docs):
        row[i * slot + useful:(i + 1) * slot] = 2 * i + 1  # pad segment
    seg_padded = with_tail(row)

    step = make_train_step(
        lambda p, t, s: model.apply(p, t, return_loss=True, segment_ids=s),
        opt,
    )
    iters = 3 if seq_len >= 65536 else 5

    def chained(params, opt_state, tokens, segs):
        def body(carry, _):
            params, opt_state = carry
            params, opt_state, loss = step(params, opt_state, tokens, segs)
            return (params, opt_state), loss
        _, losses = jax.lax.scan(body, (params, opt_state), None, length=iters)
        return losses[-1]

    chained = jax.jit(chained)
    out = {"packed_seq_len": seq_len, "packed_docs": docs,
           "packed_pad_frac": pad_frac, "packed_impl": impl,
           "device": getattr(dev, "device_kind", str(dev))}
    for label, segs, n_useful in (
        ("packed", seg_packed, seq_len),
        ("padded", seg_padded, docs * useful),
    ):
        opt_state = opt.init(params)
        compile_s, secs = timed_chained(
            chained, (params, opt_state, tokens, segs), iters
        )
        out[f"{label}_tokens_per_sec"] = round(n_useful / secs)
        out[f"{label}_ms_per_step"] = round(secs * 1e3, 2)
        out[f"{label}_compile_s"] = round(compile_s, 1)
    out["packed_speedup"] = round(
        out["packed_tokens_per_sec"] / max(out["padded_tokens_per_sec"], 1), 3
    )
    print(json.dumps(out))


def _train_worker(impl: str, seq_len: int, remat_policy: str | None,
                  vocab: int = 256,
                  loss_chunk_size: int | None = None,
                  ff_chunk_size: int | None = None) -> None:
    """Full train step (fwd+bwd+adam) tokens/sec on one chip.

    ``remat_policy="save_attn"`` saves each layer's flash output + lse so
    the backward skips re-running the O(n^2) attention forward (VERDICT r2
    weak #1: the elective recompute cost the r2 headline ~2 s/step).
    ``vocab``/``loss_chunk_size`` measure the realistic-vocabulary
    configuration: at vocab 50257 the full-logits CE cannot fit a chip at
    262k tokens, so the chunked loss is what makes the shape trainable.
    ``ff_chunk_size`` adds the blockwise feedforward — with it, the
    train1m phase's 2^20-token step fits one chip (docs/memory.md)."""
    import jax
    import jax.numpy as jnp
    import optax

    dev, peak = _device_peak()
    model, params = _bench_transformer(impl, vocab, remat_policy,
                                       loss_chunk_size, ff_chunk_size)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (1, seq_len + 1), 0, vocab, jnp.int32
    )

    from ring_attention_tpu.utils import make_train_step

    # the framework's own composed step (utils/train.py) — the bench
    # measures the API users actually call
    step = make_train_step(
        lambda p, t: model.apply(p, t, return_loss=True), opt
    )

    iters = 3 if seq_len >= 65536 else 5

    @jax.jit
    def chained(params, opt_state, tokens):
        def body(carry, _):
            params, opt_state = carry
            params, opt_state, loss = step(params, opt_state, tokens)
            return (params, opt_state), loss
        _, losses = jax.lax.scan(body, (params, opt_state), None, length=iters)
        return losses[-1]

    from ring_attention_tpu.utils.benchtime import timed_chained

    compile_s, secs, loss = timed_chained(
        chained, (params, opt_state, tokens), iters, return_value=True
    )

    # achieved MFU of the whole step (fwd+bwd+adam): XLA's counted FLOPs
    # when the backend reports them, the analytic transformer formula
    # otherwise — next to tokens/sec so a regression says WHICH of
    # "the model got slower" vs "the chip got slower" happened
    from ring_attention_tpu.utils.telemetry import (
        achieved_mfu, transformer_step_flops,
    )

    cost = _cost_fields(chained, (params, opt_state, tokens), secs, iters)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    step_flops = transformer_step_flops(
        n_params, seq_len, depth=2, heads=HEADS, dim_head=DIM_HEAD,
        seq_len=seq_len, causal=True,
    )
    if cost.get("xla_flops"):
        step_flops = cost["xla_flops"] / iters
    print(
        json.dumps(
            {
                "tokens_per_sec": round(seq_len / secs),
                "train_seq_len": seq_len,
                "train_impl": impl,
                "train_remat_policy": remat_policy or "full",
                "train_vocab": vocab,
                **({"train_loss_chunk_size": loss_chunk_size}
                   if loss_chunk_size else {}),
                **({"train_ff_chunk_size": ff_chunk_size}
                   if ff_chunk_size else {}),
                "train_ms_per_step": round(secs * 1e3, 2),
                "train_compile_s": round(compile_s, 1),
                "train_loss": round(float(loss), 4),
                "train_mfu": round(achieved_mfu(step_flops, secs, peak), 4),
                "train_flops_per_step": step_flops,
                **cost,
                **_degradation_fields(),
                "device": getattr(dev, "device_kind", str(dev)),
            }
        )
    )


def _run_attempt(impl: str, seq: int, mode: str, budget: float,
                 extra: dict | None = None):
    """Subprocess-isolated measurement; returns parsed dict or error string."""
    tag = f"{mode}:{impl}@{seq}" + (
        f"[{','.join(f'{k}={v}' for k, v in extra.items())}]" if extra else ""
    )
    try:
        proc = subprocess.run(
            [
                sys.executable, os.path.abspath(__file__),
                "--worker", impl, str(seq), mode, json.dumps(extra or {}),
            ],
            capture_output=True,
            text=True,
            timeout=budget,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode == 0:
            payload = json.loads(proc.stdout.strip().splitlines()[-1])
            if isinstance(payload, dict):
                # stamp the perf-gate history schema on every phase
                # payload (analysis/perfgate.py ingests these rounds)
                payload.setdefault("gate_schema", _gate_schema())
            return payload, None
        return None, f"{tag}: rc={proc.returncode} {proc.stderr[-200:]}"
    except subprocess.TimeoutExpired:
        return None, f"{tag}: timeout"
    except Exception:
        return None, f"{tag}: {traceback.format_exc(limit=1)}"


def _last_measured() -> dict:
    """Standing on-silicon numbers from ``docs/hwlogs/results.jsonl``.

    The TPU tunnel in this image can wedge for entire rounds
    (docs/hardware_log.md); when the health probe fails, the emitted JSON
    still carries the latest measured values (with their dates) so a
    wedged round doesn't read as "this framework benches 0.0".
    """
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "docs", "hwlogs", "results.jsonl",
    )
    latest: dict[str, dict] = {}
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                step, res = rec.get("step"), rec.get("result")
                if step and isinstance(res, dict) and "value" in res:
                    latest[step] = {
                        "value": res["value"],
                        **({"unit": res["unit"]} if "unit" in res else {}),
                        **({"date": rec["date"]} if "date" in rec else {}),
                    }
    except OSError:
        pass
    return latest


class ProbeKilled(RuntimeError):
    """The device-probe child exceeded its hard deadline and was killed
    (SIGKILL to its whole process group — a wedged tunnel can leave
    grandchildren holding the TPU lockfile, so killing just the child
    is not enough)."""


def _kill_probe_group(proc) -> None:
    import signal as _signal

    try:
        os.killpg(proc.pid, _signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        proc.kill()
    try:  # reap; never hang the parent on a corpse
        proc.communicate(timeout=10)
    except Exception:  # noqa: BLE001 — already killed; nothing to salvage
        pass


def _probe_device() -> str:
    """One killable device-probe attempt with a hard wall-clock deadline.

    The child runs in its OWN session (``start_new_session``) so a
    deadline overrun kills the whole process group, not just the python
    shim — the round 3-5 wedge survived ``subprocess.run(timeout=...)``
    because the hang was below the child.  ``BENCH_PROBE_DEADLINE_S``
    sets the deadline (default 180); ``BENCH_PROBE_WEDGE_S`` makes the
    child sleep first — the chaos harness's wedge simulation, so the
    kill path is testable on any backend (tests/test_elastic.py).
    """
    deadline = float(os.environ.get("BENCH_PROBE_DEADLINE_S", 180))
    code = (
        "import os, time; "
        "time.sleep(float(os.environ.get('BENCH_PROBE_WEDGE_S') or 0)); "
        "import jax; print(jax.devices()[0].platform)"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True,
    )
    try:
        out, err = proc.communicate(timeout=deadline)
    except subprocess.TimeoutExpired:
        _kill_probe_group(proc)
        raise ProbeKilled(
            f"device probe exceeded its {deadline:.0f}s hard deadline; "
            f"process group killed (TPU tunnel unresponsive)"
        ) from None
    if proc.returncode != 0:
        raise RuntimeError(f"device probe failed: {err[-300:]}")
    return out.strip()


def _run_probe() -> dict:
    """Retry ladder around :func:`_probe_device` (utils/resilience.py):
    a transient blip gets one backed-off retry, a wedge costs exactly one
    deadline per attempt (the child is killed, never awaited), and the
    verdict records whether a kill happened (the structured
    ``probe_failure`` row keeps it queryable)."""
    res = _load_repo_module(
        "_bench_resilience", "ring_attention_tpu", "utils", "resilience.py"
    )
    deadline = float(os.environ.get("BENCH_PROBE_DEADLINE_S", 180))
    try:
        res.with_retries(
            _probe_device,
            timeout=deadline + 60,  # backstop over the child's own kill
            backoff=float(os.environ.get("BENCH_PROBE_BACKOFF_S", 30)),
            max_attempts=int(os.environ.get("BENCH_PROBE_ATTEMPTS", 2)),
        )
    except res.RetryError as e:
        if isinstance(e.last, ProbeKilled):
            return {
                "ok": False,
                "killed": True,
                "error": (
                    f"device probe hung (TPU tunnel unresponsive; child "
                    f"killed after {deadline:.0f}s hard deadline)"
                ),
            }
        if isinstance(e.last, (subprocess.TimeoutExpired, TimeoutError)):
            # the WRAPPER's backstop fired, not the child's deadline: the
            # child was NOT killed (the thread owning its handle was
            # abandoned) and may still be running — say so truthfully
            # instead of asserting a kill that never happened
            return {
                "ok": False,
                "killed": False,
                "error": (
                    f"device probe hung past the wrapper backstop "
                    f"({deadline + 60:.0f}s); child not confirmed killed "
                    f"and may still be running"
                ),
            }
        return {"ok": False, "killed": False, "error": str(e.last)}
    return {"ok": True}


def _wedge_streak(path: str | None = None) -> int:
    """Length of the trailing run of consecutive ``probe_failure`` rows
    in the hardware log — the wedge-streak number surfaced in the BENCH
    tail, so "how long has this tunnel been down" is one field instead
    of an archaeology session over results.jsonl."""
    path = path or os.environ.get("BENCH_HWLOG") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "docs", "hwlogs", "results.jsonl",
    )
    streak = 0
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("step") == "probe_failure":
                    streak += 1
                else:
                    streak = 0
    except OSError:
        return 0
    return streak


def _log_probe_failure(probe: dict) -> None:
    """Append a structured probe-failure row to the hardware results log.

    BENCH_r04/r05's only trace of the wedge was a tail string inside the
    bench JSON.  A ``probe_failure`` row in ``docs/hwlogs/results.jsonl``
    (same record shape as the measurement rows; ``_last_measured`` skips
    it — no ``value`` field) makes hang history queryable:
    ``grep probe_failure docs/hwlogs/results.jsonl`` is the wedge
    timeline.  ``BENCH_HWLOG`` overrides the path (tests point it at a
    temp file so CI probe-failure exercises never touch the real log);
    the single-line append is atomic for concurrent benches.
    """
    path = os.environ.get("BENCH_HWLOG") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "docs", "hwlogs", "results.jsonl",
    )
    rec = {
        "step": "probe_failure",
        "date": time.strftime("%Y-%m-%d"),
        "result": {
            "error": probe.get("error", "device probe failed"),
            "cached": bool(probe.get("cached")),
            # whether the hard deadline killed the probe's process group
            # (a wedge) vs the probe failing on its own (a real error)
            "killed": bool(probe.get("killed")),
            **({"age_s": probe["age_s"]} if probe.get("cached") else {}),
            "env": probe.get("env", ""),
        },
    }
    try:
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass  # the log is an archive; never fail the bench over it


def _cached_probe(run_probe):
    """Run the device probe through a small on-disk cache.

    BENCH_r03–r05 each re-paid the full wedged-tunnel hang (2 x 180 s
    subprocess kills + backoff) because every bench invocation re-probed a
    tunnel whose state had not changed.  The probe verdict — healthy or
    wedged — is cached with a timestamp (``BENCH_PROBE_CACHE``, default
    under the system temp dir) and reused for ``BENCH_PROBE_TTL_S``
    seconds (default 900), so back-to-back phases/invocations pay the hang
    at most once per TTL window.  The emitted JSON marks reused verdicts
    (``probe_cached`` + age) so a wedged round is never mistaken for a
    fresh measurement.
    """
    import tempfile

    ttl = float(os.environ.get("BENCH_PROBE_TTL_S", 900))
    path = os.environ.get(
        "BENCH_PROBE_CACHE",
        os.path.join(tempfile.gettempdir(), "ring_attention_bench_probe.json"),
    )
    # a verdict is only reusable from the same backend selection: the
    # fault-injection suite probes with JAX_PLATFORMS=nonexistent_backend,
    # and its wedged verdict must never short-circuit a real TPU round
    # (nor a healthy CPU verdict mask a wedged tunnel)
    env_key = os.environ.get("JAX_PLATFORMS", "")
    if ttl > 0:
        try:
            with open(path) as f:
                rec = json.load(f)
            age = time.time() - rec["time"]
            if (0 <= age <= ttl and isinstance(rec.get("ok"), bool)
                    and rec.get("env") == env_key):
                rec["cached"] = True
                rec["age_s"] = round(age, 1)
                return rec
        except (OSError, ValueError, KeyError, TypeError):
            pass
    rec = run_probe()
    rec["time"] = time.time()
    rec["env"] = env_key
    try:
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, path)  # atomic: concurrent benches see old or new
    except OSError:
        pass  # cache is an optimization; never fail the bench over it
    return rec


def main() -> None:
    result = {
        "metric": (
            f"causal flash attention fwd TFLOPs/chip + train tokens/sec "
            f"(h={HEADS}, d={DIM_HEAD}, bf16)"
        ),
        "value": 0.0,
        "unit": "TFLOPs/chip",
        "vs_baseline": 0.0,
        "gate_schema": _gate_schema(),
    }
    # fast health gate: this image's TPU tunnel can wedge such that even
    # jax.devices() hangs; don't burn the full fallback budget in that
    # state.  The probe (module-level _probe_device/_run_probe) runs in a
    # KILLABLE subprocess session with a hard deadline — a wedged tunnel
    # costs one deadline per attempt, never a hung round — through the
    # shared retry/backoff helper (utils/resilience.py).  On failure the
    # emitted JSON is unchanged: error + last_measured standing numbers +
    # wedge_streak, so a wedged round still never reads as "this
    # framework benches 0.0".

    # phase 0 — collective fingerprint (CPU-only, before the TPU probe so
    # it lands even on wedged rounds): per-strategy collective counts from
    # the contract checker, the comms half of the perf trajectory
    fp, fp_err = _run_attempt(
        "cpu", 0, "fingerprint", float(os.environ.get("BENCH_FP_BUDGET_S", 420))
    )
    if fp is not None:
        result["collective_fingerprint"] = fp
    else:
        result["collective_fingerprint"] = {"error": (fp_err or "failed")[-200:]}

    # phase 0b — tile-coverage fingerprint (numpy-only, rides the same
    # pre-probe slot): per-row compact-grid tile counts, gated exactly in
    # analysis/perfgate.py next to the collective counts
    cov, cov_err = _run_attempt(
        "cpu", 0, "coverage", float(os.environ.get("BENCH_COV_BUDGET_S", 180))
    )
    if cov is not None:
        result["coverage_fingerprint"] = cov
    else:
        result["coverage_fingerprint"] = {"error": (cov_err or "failed")[-200:]}

    # phase 0d — sliding-window 262k certified-grid accounting (numpy-
    # only, pre-probe): the work-tile reduction the certified window
    # grid buys over causal at the north-star shape — the scenario-
    # diversity half of the mask algebra as a number in BENCH output,
    # wedged rounds included
    win, win_err = _run_attempt(
        "cpu", 0, "window262k",
        float(os.environ.get("BENCH_WIN_BUDGET_S", 180)),
    )
    if win is not None:
        result["window262k"] = win
    else:
        result["window262k"] = {"error": (win_err or "failed")[-200:]}

    # phase 0e — multihost dryrun (CPU-only, pre-probe): the DCN-aware
    # collective fingerprint over the hierarchical mesh — zero ring/
    # ulysses collectives over dcn_data, machine-checked, pinned as an
    # exact perf-gate family even on wedged rounds
    mh, mh_err = _run_attempt(
        "cpu", 0, "multihost",
        float(os.environ.get("BENCH_MH_BUDGET_S", 420)),
    )
    if mh is not None:
        result["multihost_dryrun"] = mh
    else:
        result["multihost_dryrun"] = {"error": (mh_err or "failed")[-200:]}

    # phase 0f — fused-ring DMA-protocol fingerprint (CPU-only, pre-
    # probe): schedverify's verified hop schedule as pinned numbers —
    # derived DMA/semaphore counts, model event counts for rings 2..8,
    # zero violations — gated exactly in analysis/perfgate.py
    pr, pr_err = _run_attempt(
        "cpu", 0, "protocol",
        float(os.environ.get("BENCH_PROTO_BUDGET_S", 420)),
    )
    if pr is not None:
        result["protocol_fingerprint"] = pr
    else:
        result["protocol_fingerprint"] = {"error": (pr_err or "failed")[-200:]}

    # phase 0c — train1m memory proof (CPU-only, pre-probe like the
    # fingerprint): chunked-vs-dense compiled peak temp bytes at equal
    # shape + the analytic 2^20-token peak-HBM estimate, so the
    # memory-axis claim is a number in BENCH output even on wedged rounds
    mm, mm_err = _run_attempt(
        "cpu", 0, "train1m_mem",
        float(os.environ.get("BENCH_MEM_BUDGET_S", 900)),
    )
    if mm is not None:
        result["train1m_memory"] = mm
    else:
        result["train1m_memory"] = {"error": (mm_err or "failed")[-200:]}

    # probe once, reuse across phases AND back-to-back invocations: the
    # verdict is cached on disk with a TTL (see _cached_probe) so a wedged
    # tunnel costs its 180 s hang once per window, not once per round
    probe = _cached_probe(_run_probe)
    if probe.get("cached"):
        result["probe_cached"] = True
        result["probe_age_s"] = probe.get("age_s")
    if not probe["ok"]:
        err = probe.get("error", "device probe failed")
        if probe.get("cached"):
            # the verdict's age belongs IN the error: "wedged 840s ago"
            # and "wedged just now" direct different operator responses
            err += f" [cached verdict, {probe.get('age_s', 0.0)}s old]"
        result["error"] = err
        result["last_measured"] = _last_measured()
        _log_probe_failure(probe)
        # after appending this round's row: the streak INCLUDES it, so
        # the tail says "wedged N rounds running" in one field
        result["wedge_streak"] = _wedge_streak()
        print(json.dumps(result))
        return

    deadline = time.monotonic() + float(os.environ.get("BENCH_BUDGET_S", 3600))
    log = []

    def budget_left(need: float) -> bool:
        return deadline - time.monotonic() >= need / 3

    # phase 1 — forward TFLOPs: one quick config first (guarantees a real
    # measurement), then the north-star config directly; intermediate sizes
    # only as fallbacks if the target fails.
    attempts = [
        ("xla", 8192, 420, False),
        ("pallas", TARGET_SEQ, 1500, False),
        ("pallas", 65536, 900, True),   # fallback-only
        ("pallas", 16384, 600, True),   # fallback-only
    ]
    best = None  # (impl, seq) of the best successful fwd run
    got_target = False
    got_fallback = False
    for impl, seq, budget, fallback_only in attempts:
        # fallbacks are ordered largest-first: stop after the first success
        # so a smaller one never overwrites it
        if fallback_only and (got_target or got_fallback):
            continue
        if not budget_left(budget):
            log.append(f"fwd:{impl}@{seq}: skipped (budget exhausted)")
            continue
        payload, err = _run_attempt(
            impl, seq, "fwd", min(budget, deadline - time.monotonic())
        )
        if payload is None:
            log.append(err)
            continue
        result.update(payload)
        best = (impl, seq)
        got_target = got_target or seq == TARGET_SEQ
        got_fallback = got_fallback or fallback_only
        log.append(f"fwd:{impl}@{seq}: ok")

    # phase 2 — fwd+bwd TFLOPs at the best forward config (bwd timing is
    # half the north-star training story; BASELINE.md)
    if best is not None and budget_left(900):
        impl, seq = best
        payload, err = _run_attempt(
            impl, seq, "fwdbwd", min(900, deadline - time.monotonic())
        )
        if payload is not None:
            result["fwdbwd_tflops"] = payload["value"]
            result["fwdbwd_ms_per_step"] = payload["ms_per_step"]
            result["fwdbwd_compile_s"] = payload["compile_s"]
            log.append(f"fwdbwd:{impl}@{seq}: ok")
        else:
            log.append(err)

    # phase 3 — train-step tokens/sec (fwd+bwd+adam), largest seq that
    # fits; both remat variants (save_attn skips the backward's attention
    # recompute and should lead — report both, headline the best)
    if best is not None:
        impl = best[0]
        train_seqs = []
        for s in (best[1], best[1] // 4, 8192):
            if s >= 1024 and s not in train_seqs:
                train_seqs.append(s)
        variants = {}  # policy label -> full worker payload (incl. its seq)
        for policy in ("save_attn", None):
            label = policy or "full"
            for seq in train_seqs:
                if label in variants:
                    break
                if not budget_left(1200):
                    log.append(f"train:{impl}@{seq}: skipped (budget exhausted)")
                    continue
                payload, err = _run_attempt(
                    impl, seq, "train", min(1200, deadline - time.monotonic()),
                    {"remat_policy": policy},
                )
                if payload is not None:
                    variants[label] = payload
                    # per-variant keys carry their own seq so a fallback-
                    # sized variant can never masquerade as the north star
                    result[f"tokens_per_sec_{label}"] = payload["tokens_per_sec"]
                    result[f"train_seq_len_{label}"] = payload["train_seq_len"]
                    result[f"train_ms_per_step_{label}"] = payload[
                        "train_ms_per_step"
                    ]
                    log.append(f"train:{impl}@{seq}[{label}]: ok")
                else:
                    log.append(err)
        if variants:
            # headline: largest measured seq wins; tokens/sec breaks ties
            # (tokens/sec at a shorter seq is not comparable for O(n^2) work)
            winner = max(
                variants.values(),
                key=lambda p: (p["train_seq_len"], p["tokens_per_sec"]),
            )
            result.update(winner)

    # phase 3b — packed-sequence (segment-id) train throughput vs the
    # padded recipe at the same position budget (~25% pad): the packed
    # entry (`packed262k` at the north-star seq) sits next to the train
    # tokens/sec entries; `packed_speedup` is the pad-waste recovery
    if best is not None:
        impl = best[0]
        packed_seqs = []
        for s in (TARGET_SEQ, best[1], 8192):
            if s >= 1024 and s not in packed_seqs:
                packed_seqs.append(s)
        for seq in packed_seqs:
            if not budget_left(1200):
                log.append(f"packed:{impl}@{seq}: skipped (budget exhausted)")
                continue
            payload, err = _run_attempt(
                impl, seq, "packed", min(1200, deadline - time.monotonic())
            )
            if payload is not None:
                key = "packed262k" if seq == TARGET_SEQ else f"packed{seq}"
                result[key] = payload["packed_tokens_per_sec"]
                result["packed_seq_len"] = payload["packed_seq_len"]
                result["padded_tokens_per_sec"] = payload["padded_tokens_per_sec"]
                result["packed_speedup"] = payload["packed_speedup"]
                result["packed_pad_frac"] = payload["packed_pad_frac"]
                log.append(f"packed:{impl}@{seq}: ok")
                break
            log.append(err)

    # phase 4 — ring-hop sequence on one chip: the per-device span calls a
    # real causal ring makes (resume + fused last hop).  Done criterion:
    # >= 95% of the static single-sweep fwd rate (VERDICT r2 #1).
    if got_target and budget_left(900):
        payload, err = _run_attempt(
            "pallas", TARGET_SEQ, "hops",
            min(900, deadline - time.monotonic()), {"ring": 4},
        )
        if payload is not None:
            result["ring_hops_tflops"] = payload["value"]
            result["ring_hops_ms"] = payload["ms_per_step"]
            if result.get("value"):
                result["ring_hops_frac_of_fwd"] = round(
                    payload["value"] / result["value"], 4
                )
            log.append(f"hops:pallas@{TARGET_SEQ}: ok")
        else:
            log.append(err)

    # phase 4c — hybrid Ulysses x Ring hop sequence at the same world as
    # phase 4's pure ring: world/ulysses hops on h/ulysses heads (the
    # Ulysses all-to-all legs are latency-flat; this measures the kernel
    # hop chain that remains).  `hybrid262k` sits next to the ring/ulysses
    # entries with its hop count and whole-slice tokens/sec.
    if got_target and budget_left(900):
        payload, err = _run_attempt(
            "pallas", TARGET_SEQ, "hybrid",
            min(900, deadline - time.monotonic()),
            {"world": 4, "ulysses": 2},
        )
        if payload is not None:
            result["hybrid262k"] = payload["value"]
            result["hybrid_hops"] = payload["hops"]
            result["hybrid_pure_ring_hops"] = payload["pure_ring_hops"]
            result["hybrid_ulysses"] = payload["ulysses"]
            result["hybrid_tokens_per_sec"] = payload["tokens_per_sec"]
            result["hybrid_ms"] = payload["ms_per_step"]
            if result.get("ring_hops_tflops"):
                result["hybrid_vs_ring_hops"] = round(
                    payload["value"] / result["ring_hops_tflops"], 4
                )
            log.append(f"hybrid:pallas@{TARGET_SEQ}[u2]: ok")
        else:
            log.append(err)

    # phase 4d — TokenRing counter-rotation hop chain with int8-compressed
    # KV payloads, at the same ring degree as phase 4's baseline.  The
    # compute chain includes the per-hop dequant the compressed ring pays;
    # bytes/hop + fwd/bwd collective counts ride along analytically, and
    # phase 0's collective fingerprint pins the counter/compressed hop
    # counts from compiled HLO even on wedged-TPU rounds.
    if got_target and budget_left(900):
        payload, err = _run_attempt(
            "pallas", TARGET_SEQ, "counter",
            min(900, deadline - time.monotonic()),
            {"ring": 4, "hop_compression": "int8"},
        )
        if payload is not None:
            result["counter262k"] = payload["value"]
            result["counter_hop_bytes"] = payload["hop_bytes"]
            result["counter_q_pack_bytes"] = payload["q_pack_bytes"]
            result["counter_fwd_collectives"] = payload["fwd_collectives"]
            result["counter_bwd_collectives"] = payload["bwd_collectives"]
            result["counter_tokens_per_sec"] = payload["tokens_per_sec"]
            result["counter_ms"] = payload["ms_per_step"]
            if result.get("ring_hops_tflops"):
                # dequant overhead of the compressed hop chain vs the
                # model-dtype baseline hop chain on the same device
                result["counter_vs_ring_hops"] = round(
                    payload["value"] / result["ring_hops_tflops"], 4
                )
            log.append(f"counter:pallas@{TARGET_SEQ}[int8]: ok")
        else:
            log.append(err)

    # phase 4e — fwd262k_q8: the int8 COMPUTE hop chain (PR 13) at the
    # same ring degree — quantized QK^T/PV kernels fed directly from the
    # once-quantized hop payload (no per-hop dequant), f32 accumulators
    # resumed in-kernel.  ROADMAP item 3's acceptance number: on silicon
    # this should beat the fused bf16 fwd (int8 MXU ~2x peak); operand/
    # accumulator byte accounting rides the JSON, the counter_q8 HLO
    # fingerprint (phase 0) and the ring8_262k_q8 comms row are the
    # wedge-honest CPU signals.
    if got_target and budget_left(900):
        payload, err = _run_attempt(
            "pallas", TARGET_SEQ, "q8",
            min(900, deadline - time.monotonic()),
            {"ring": 4},
        )
        if payload is not None:
            result["fwd262k_q8"] = payload["value"]
            result["fwd262k_q8_tokens_per_sec"] = payload["tokens_per_sec"]
            result["fwd262k_q8_ms"] = payload["ms_per_step"]
            result["fwd262k_q8_hop_bytes"] = payload["hop_bytes"]
            result["fwd262k_q8_operand_bytes"] = (
                payload["matmul_operand_bytes"]
            )
            result["fwd262k_q8_accumulator_bytes"] = (
                payload["accumulator_bytes"]
            )
            if result.get("ring_hops_tflops"):
                # the int8-vs-bf16 matmul-feed speedup on the same device
                # and hop schedule (>1 = the MXU rate win materialized)
                result["fwd262k_q8_vs_ring_hops"] = round(
                    payload["value"] / result["ring_hops_tflops"], 4
                )
            log.append(f"q8:pallas@{TARGET_SEQ}[int8-compute]: ok")
        else:
            log.append(err)

    # phase 4f — fused262k (PR 18): the same hop chain as phase 4, ONE
    # kernel launch — ops/pallas_ring.py sweeps every hop's span with the
    # f32 carry resident in VMEM, so fused_vs_ring_hops is the measured
    # launch-boundary cost the fused path deletes.  The analytic row
    # (kernel_launches=1, dispatch overhead 0, fwd_collectives=0, overlap
    # ~1.0) rides along; phase 0's fused_ring fingerprint pins the
    # in-kernel remote-DMA counts (zero ppermutes) from lowered Mosaic
    # even on wedged-TPU rounds.
    if got_target and budget_left(900):
        payload, err = _run_attempt(
            "pallas", TARGET_SEQ, "fused",
            min(900, deadline - time.monotonic()),
            {"ring": 4},
        )
        if payload is not None:
            result["fused262k"] = payload["value"]
            result["fused_kernel_launches"] = payload["kernel_launches"]
            result["fused_fwd_collectives"] = payload["fwd_collectives"]
            result["fused_overlap_fraction"] = payload["hop_overlap_fraction"]
            result["fused_tokens_per_sec"] = payload["tokens_per_sec"]
            result["fused_ms"] = payload["ms_per_step"]
            if result.get("ring_hops_tflops"):
                # launch-free-hops dividend: one launch vs ring launches
                # on the identical span schedule and device
                result["fused_vs_ring_hops"] = round(
                    payload["value"] / result["ring_hops_tflops"], 4
                )
            log.append(f"fused:pallas@{TARGET_SEQ}[1-launch]: ok")
        else:
            log.append(err)

    # phase 5 — BASELINE.json config-4 GQA shape (heads=32, kv 4) and a
    # d=128 variant.  h=32 x seq 262144 is a known relay 500 (memory:
    # tpu-tunnel-operations); try it, fall back to 131072.
    for extra, key, seqs in (
        ({"heads": 32, "kv_heads": 4}, "gqa32_tflops", (TARGET_SEQ, 131072)),
        ({"dim_head": 128}, "d128_tflops", (TARGET_SEQ, 131072)),
    ):
        for seq in seqs:
            if key in result:
                break
            if not budget_left(900):
                log.append(f"fwd:pallas@{seq}[{key}]: skipped (budget)")
                continue
            payload, err = _run_attempt(
                "pallas", seq, "fwd",
                min(900, deadline - time.monotonic()), extra,
            )
            if payload is not None:
                result[key] = payload["value"]
                result[key.replace("_tflops", "_seq_len")] = seq
                result[key.replace("_tflops", "_mfu")] = payload["vs_baseline"]
                log.append(f"fwd:pallas@{seq}[{key}]: ok")
            else:
                log.append(err)

    # phase 6 — million-token decode (BASELINE config 5): ms/token against
    # a 2^20-token GQA cache — decode kernel, int8-cache kernel, dense tile
    for impl in ("pallas", "pallas_q8", "dense"):
        if not budget_left(600):
            log.append(f"decode:{impl}: skipped (budget)")
            continue
        payload, err = _run_attempt(
            impl, 1 << 20, "decode", min(600, deadline - time.monotonic())
        )
        if payload is not None:
            suffix = {"pallas": "", "pallas_q8": "_q8", "dense": "_dense"}[impl]
            for key in ("decode_ms_per_token", "decode_kv_gbps"):
                result[key + suffix] = payload[key]
            for key in ("decode_ms_p50", "decode_ms_p95", "decode_ms_p99"):
                if key in payload:
                    result[key + suffix] = payload[key]
            if impl == "pallas":
                result["decode_seq_len"] = payload["decode_seq_len"]
                result["decode_kv_heads"] = payload["decode_kv_heads"]
            log.append(f"decode:{impl}@{1 << 20}: ok")
        else:
            log.append(err)

    # phase 7 — train1m (ROADMAP item 4): the 2^20-token train step on one
    # chip — blockwise FFN + chunked CE + save_attn, the configuration the
    # memory phase (0c) proves fits.  tokens/sec plus the compiled
    # peak-memory fields land next to counter262k.
    if best is not None and budget_left(1800):
        payload, err = _run_attempt(
            best[0], 1 << 20, "train",
            min(1800, deadline - time.monotonic()),
            {"remat_policy": "save_attn", "loss_chunk_size": 2048,
             "ff_chunk_size": 2048},
        )
        if payload is not None:
            result["train1m"] = payload["tokens_per_sec"]
            result["train1m_tokens_per_sec"] = payload["tokens_per_sec"]
            result["train1m_ms_per_step"] = payload["train_ms_per_step"]
            result["train1m_compile_s"] = payload["train_compile_s"]
            for key in ("temp_bytes", "argument_bytes"):
                if key in payload:
                    result[f"train1m_{key}"] = payload[key]
            log.append(f"train1m:{best[0]}@{1 << 20}: ok")
        else:
            log.append(err)

    # keep the attempt trail even on success so a fallback-sized result is
    # never mistaken for a clean north-star run round-over-round
    result["attempts"] = " | ".join(log)[-900:]
    if best is None:
        result["error"] = result["attempts"]
        result["last_measured"] = _last_measured()
    print(json.dumps(result))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        mode = sys.argv[4] if len(sys.argv) > 4 else "fwd"
        extra = json.loads(sys.argv[5]) if len(sys.argv) > 5 else {}
        if mode == "fingerprint":
            # env setup must precede the first jax import (see the worker)
            _fingerprint_worker()
        elif mode == "multihost":
            _multihost_worker()
        elif mode == "protocol":
            _protocol_worker()
        elif mode == "coverage":
            _coverage_worker()
        elif mode == "window262k":
            _window262k_worker(extra)
        elif mode == "train1m_mem":
            # likewise CPU-forced before the first jax import
            _train1m_mem_worker(extra)
        else:
            _worker(sys.argv[2], int(sys.argv[3]), mode, extra)
    else:
        main()
