"""Benchmark: causal flash attention throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "TFLOPs/chip", "vs_baseline": N, ...}

North-star config (BASELINE.json): seq_len=262144, causal, 8 heads.  The
reference publishes no performance numbers (BASELINE.md), so
``vs_baseline`` reports the fraction of the chip's bf16 peak (MFU) —
a hardware-grounded, round-over-round comparable scalar.

Robustness: each (impl, seq_len) attempt runs in its own subprocess with a
hard timeout (TPU compiles through this image's remote-compile relay can
take minutes or hang), falling back to smaller lengths and the pure-XLA
path; the parent never initializes the TPU and always prints a JSON line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import traceback

TARGET_SEQ = 262144
HEADS = 8
DIM_HEAD = 64

# bf16 peak TFLOPs per chip by TPU generation (dense)
PEAK_TFLOPS = {
    "v5 lite": 197.0,  # v5e
    "v5e": 197.0,
    "v5p": 459.0,
    "v4": 275.0,
    "v6e": 918.0,
}


def _worker(impl: str, seq_len: int) -> None:
    """Runs one timed measurement and prints its own JSON line."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    peak = next((v for k, v in PEAK_TFLOPS.items() if k in kind), 197.0)

    q = jnp.ones((1, HEADS, seq_len, DIM_HEAD), jnp.bfloat16)
    k = jnp.ones((1, HEADS, seq_len, DIM_HEAD), jnp.bfloat16)
    v = jnp.ones((1, HEADS, seq_len, DIM_HEAD), jnp.bfloat16)

    if impl == "pallas":
        from ring_attention_tpu.ops.pallas_flash import pallas_flash_attention

        fn = jax.jit(partial(pallas_flash_attention, causal=True))
    else:
        from ring_attention_tpu.ops.flash import flash_attention

        bucket = min(1024, seq_len)
        qc = 2048 if seq_len > 2048 else None  # two-level blocking for memory
        fn = jax.jit(partial(flash_attention, causal=True, bucket_size=bucket,
                             q_chunk_size=qc))

    out = fn(q, k, v)
    jax.block_until_ready(out)
    iters = 3 if seq_len >= TARGET_SEQ else 10
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(q, k, v)
    jax.block_until_ready(out)
    secs = (time.perf_counter() - t0) / iters

    # causal fwd FLOPs: 2 matmuls x 2 flops x n^2 x h x d x 1/2
    flops = 2 * 2 * seq_len * seq_len * HEADS * DIM_HEAD * 0.5
    tflops = flops / secs / 1e12
    print(
        json.dumps(
            {
                "value": round(tflops, 2),
                "vs_baseline": round(tflops / peak, 4),
                "seq_len": seq_len,
                "impl": impl,
                "device": getattr(dev, "device_kind", str(dev)),
                "ms_per_step": round(secs * 1e3, 2),
            }
        )
    )


def main() -> None:
    result = {
        "metric": f"causal flash attention fwd TFLOPs/chip (h={HEADS}, d={DIM_HEAD}, bf16)",
        "value": 0.0,
        "unit": "TFLOPs/chip",
        "vs_baseline": 0.0,
    }
    # fast health gate: this image's TPU tunnel can wedge such that even
    # jax.devices() hangs; don't burn the full fallback budget in that state
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=180,
        )
        if probe.returncode != 0:
            result["error"] = f"device probe failed: {probe.stderr[-300:]}"
            print(json.dumps(result))
            return
    except subprocess.TimeoutExpired:
        result["error"] = "device probe hung (TPU tunnel unresponsive after 180s)"
        print(json.dumps(result))
        return

    # strategy: one quick config first (guarantees a real measurement), then
    # the north-star config directly; intermediate sizes only as fallbacks
    # if the target fails.  Later successes upgrade the reported number.
    attempts = [
        ("xla", 8192, 420, False),
        ("pallas", TARGET_SEQ, 1500, False),
        ("pallas", 65536, 900, True),   # fallback-only
        ("pallas", 16384, 600, True),   # fallback-only
    ]
    deadline = time.monotonic() + float(os.environ.get("BENCH_BUDGET_S", 3600))
    log = []
    got_target = False
    got_fallback = False
    got_any = False
    for impl, seq, budget, fallback_only in attempts:
        # fallbacks are ordered largest-first: stop after the first success
        # so a smaller one never overwrites it
        if fallback_only and (got_target or got_fallback):
            continue
        remaining = deadline - time.monotonic()
        if remaining < budget / 3:
            log.append(f"{impl}@{seq}: skipped (budget exhausted)")
            continue
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--worker", impl, str(seq)],
                capture_output=True,
                text=True,
                timeout=min(budget, remaining),
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            if proc.returncode == 0:
                line = proc.stdout.strip().splitlines()[-1]
                result.update(json.loads(line))
                got_any = True
                got_target = got_target or seq == TARGET_SEQ
                got_fallback = got_fallback or fallback_only
                log.append(f"{impl}@{seq}: ok")
                continue
            log.append(f"{impl}@{seq}: rc={proc.returncode} {proc.stderr[-200:]}")
        except subprocess.TimeoutExpired:
            log.append(f"{impl}@{seq}: timeout")
        except Exception:
            log.append(f"{impl}@{seq}: {traceback.format_exc(limit=1)}")
    # keep the attempt trail even on success so a fallback-sized result is
    # never mistaken for a clean north-star run round-over-round
    result["attempts"] = " | ".join(log)[-500:]
    if not got_any:
        result["error"] = result["attempts"]
    print(json.dumps(result))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        _worker(sys.argv[2], int(sys.argv[3]))
    else:
        main()
