#!/usr/bin/env python
"""Render a telemetry run into per-metric and per-stage tables.

Input is a metrics directory (or JSONL file) written by
``examples/train.py --metrics-dir`` / ``MetricsLogger``
(``docs/observability.md`` is the schema glossary), plus optionally an
XProf capture directory (``tools/xprof_capture.py`` / ``utils.profiling
.trace``).  Output:

- run summary (rows, step span, schema version, degradation events);
- per-metric table (last / mean / p50 / p95) over the numeric metric
  columns — loss, grad_norm, tokens_per_sec, step latency, mfu;
- comms accounting echo (ring hops, bytes per hop, overlap fraction);
- when ``--xprof DIR`` points at a capture with ``*.xplane.pb`` planes, a
  per-stage device-time table keyed on the stack's stable trace names
  (``ring/hop*``, ``ulysses/*``, ``hybrid/*``, ``flash*``,
  ``tree_decode/*``) — where the step's wall time actually went.

Stdlib-only except the optional xplane proto parser (the same
best-effort import as ``tools/xprof_capture.py``); parsing never fails
the report.  Usage::

  python tools/trace_report.py /tmp/m [--xprof docs/hwlogs/xprof/train]
"""

from __future__ import annotations

import argparse
import glob
import os
import sys
from collections import defaultdict

# metric columns the table summarizes, in display order (other numeric
# fields are appended alphabetically)
PREFERRED = [
    "loss",
    "grad_norm",
    "tokens_per_sec",
    "steps_per_sec",
    "step_ms_p50",
    "step_ms_p95",
    "mfu",
]

# comms-accounting + compiled-memory fields echoed as a static block
# (they do not vary per step — one line each beats 5 columns of constants)
ACCOUNTING = [
    "ring_size",
    "ulysses_size",
    "ring_hops",
    "pure_ring_hops",
    "ring_hops_per_step",
    "hop_bytes",
    "ring_bytes_per_step",
    "ring_bytes_per_step_bwd",
    "a2a_bytes_per_step",
    "hop_overlap_fraction",
    # compiled peak-memory accounting of the train step (telemetry
    # .compiled_memory — temp_bytes is the scratch high-water mark the
    # ff_chunk_size / loss_chunk_size / remat-policy knobs shrink)
    "temp_bytes",
    "argument_bytes",
    "output_bytes",
    "alias_bytes",
    "host_temp_bytes",
    "host_argument_bytes",
    "host_output_bytes",
]

# stage buckets for the xprof table, keyed on the stable scope/kernel
# names threaded through parallel/ and ops/ (docs/observability.md)
STAGES = [
    ("ring/hop", "ring hop compute"),
    ("ring/rotate", "ring kv rotation"),
    ("ring/bwd", "ring backward"),
    ("ring/catchup", "ring dkv catch-up"),
    ("ulysses/a2a", "ulysses all-to-all"),
    ("ulysses/flash", "ulysses local flash"),
    ("hybrid/a2a", "hybrid all-to-all"),
    ("hybrid/inner", "hybrid inner ring"),
    ("zigzag/", "zigzag"),
    ("tree_decode/gather", "tree-decode merge"),
    ("tree_decode/", "tree-decode local"),
    ("flash_bwd", "flash backward kernel"),  # pallas kernel name
    ("flash/bwd", "flash backward"),  # XLA-path named_scope
    ("flash_decode", "flash decode kernel"),
    ("flash", "flash forward kernel"),
]


def _read_rows(path: str) -> list[dict]:
    """The library's own reader (``telemetry.read_metrics`` — the one the
    killed-writer tests pin), loaded by file path so this tool never
    imports the package (whose ``__init__`` pulls in jax/flax)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_report_telemetry",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "ring_attention_tpu", "utils", "telemetry.py",
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod.read_metrics(path)


def _percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    values = sorted(values)
    pos = q * (len(values) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(values) - 1)
    frac = pos - lo
    return values[lo] * (1 - frac) + values[hi] * frac


def _fmt(x: float) -> str:
    if x == 0:
        return "0"
    if abs(x) >= 1e5 or abs(x) < 1e-3:
        return f"{x:.3e}"
    return f"{x:,.4f}".rstrip("0").rstrip(".")


def metrics_report(rows: list[dict], out: list[str]) -> None:
    metric_rows = [r for r in rows if "event" not in r]
    events = [r for r in rows if "event" in r]
    steps = [r.get("step") for r in metric_rows if "step" in r]
    schemas = sorted({r.get("schema") for r in rows if "schema" in r})
    out.append(
        f"rows: {len(metric_rows)} metric + {len(events)} event | "
        f"steps {min(steps) if steps else '-'}..{max(steps) if steps else '-'}"
        f" | schema {','.join(str(s) for s in schemas) or '-'}"
    )
    for ev in events:
        kind = ev.get("event")
        detail = ev.get("component") or ev.get("reason") or ""
        out.append(f"  event: {kind} {detail}".rstrip())
    degraded = sum(int(r.get("degraded", 0)) for r in rows)
    if degraded:
        out.append(f"  DEGRADED run: {degraded} kernel-fallback event(s) — "
                   f"see ring_attention_tpu.utils.resilience.degradation")
    if not metric_rows:
        return

    numeric: dict[str, list[float]] = defaultdict(list)
    for r in metric_rows:
        for key, val in r.items():
            if key in ("schema", "step", "time") or isinstance(val, bool):
                continue
            if isinstance(val, (int, float)):
                numeric[key].append(float(val))

    acct = [k for k in ACCOUNTING if k in numeric]
    if acct:
        out.append("")
        out.append("comms accounting (analytic, per device)")
        for key in acct:
            out.append(f"  {key:24s} {_fmt(numeric[key][-1])}")

    cols = [k for k in PREFERRED if k in numeric]
    cols += sorted(k for k in numeric if k not in cols and k not in acct)
    out.append("")
    out.append(f"  {'metric':20s} {'last':>12s} {'mean':>12s} "
               f"{'p50':>12s} {'p95':>12s}")
    for key in cols:
        vals = numeric[key]
        out.append(
            f"  {key:20s} {_fmt(vals[-1]):>12s} "
            f"{_fmt(sum(vals) / len(vals)):>12s} "
            f"{_fmt(_percentile(vals, 0.5)):>12s} "
            f"{_fmt(_percentile(vals, 0.95)):>12s}"
        )


def _stage_of(op_name: str) -> str | None:
    n = op_name.lower()
    for needle, label in STAGES:
        if needle in n:
            return label
    return None


def xprof_report(trace_dir: str, out: list[str]) -> None:
    """Per-stage device time from an xplane capture, keyed on the stable
    scope names.  Best-effort: a missing proto parser or an empty capture
    degrades to a note, never an error (the metrics table above is the
    primary product)."""
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except Exception as e:  # ImportError or any TF-init failure
        out.append(f"[xprof] parser unavailable ({type(e).__name__}); "
                   f"traces under {trace_dir} — parse offline")
        return
    paths = glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    )
    if not paths:
        out.append(f"[xprof] no .xplane.pb under {trace_dir}")
        return
    space = xplane_pb2.XSpace()
    with open(max(paths, key=os.path.getmtime), "rb") as f:
        space.ParseFromString(f.read())
    planes = [
        p for p in space.planes if "TPU" in p.name or "/device:" in p.name
    ] or list(space.planes)
    per_stage: dict[str, float] = defaultdict(float)
    total = 0.0
    for plane in planes:
        op_lines = [l for l in plane.lines if "XLA Ops" in l.name]
        for line in op_lines or plane.lines:
            for ev in line.events:
                meta = plane.event_metadata.get(ev.metadata_id)
                name = meta.name if meta else ""
                # scope names ride the op's display name or its metadata
                label = _stage_of(name) or _stage_of(
                    getattr(meta, "display_name", "") if meta else ""
                )
                ms = ev.duration_ps / 1e9
                total += ms
                per_stage[label or "other"] += ms
    if not total:
        out.append(f"[xprof] no events parsed under {trace_dir}")
        return
    out.append("")
    out.append(f"per-stage device time ({trace_dir})")
    out.append(f"  {'stage':28s} {'ms':>10s} {'share':>7s}")
    for label, ms in sorted(per_stage.items(), key=lambda kv: -kv[1]):
        out.append(f"  {label:28s} {ms:10.3f} {100 * ms / total:6.1f}%")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="Render telemetry JSONL (+ optional xprof capture) "
                    "into per-metric / per-stage tables"
    )
    ap.add_argument("metrics",
                    help="metrics directory (holding metrics.jsonl) or a "
                         "JSONL file written by MetricsLogger")
    ap.add_argument("--xprof", default=None,
                    help="xprof capture dir (tools/xprof_capture.py / "
                         "utils.profiling.trace): adds a per-stage device-"
                         "time table keyed on the stable trace names")
    ap.add_argument("--last", type=int, default=None,
                    help="summarize only the last N metric rows")
    args = ap.parse_args(argv)

    rows = _read_rows(args.metrics)
    if args.last is not None:
        events = [r for r in rows if "event" in r]
        metric = [r for r in rows if "event" not in r][-args.last:]
        rows = events + metric
    out: list[str] = [f"trace report: {args.metrics}"]
    metrics_report(rows, out)
    if args.xprof:
        xprof_report(args.xprof, out)
    print("\n".join(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
