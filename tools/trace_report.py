#!/usr/bin/env python
"""Render a telemetry run into per-metric, per-stage, and per-hop tables.

Input is a metrics directory (or JSONL file) written by
``examples/train.py --metrics-dir`` / ``MetricsLogger``
(``docs/observability.md`` is the schema glossary), plus optionally an
XProf capture directory (``tools/xprof_capture.py`` / ``utils.profiling
.trace``).  Output:

- run summary (rows, step span, schema version, degradation events);
- per-metric table (last / mean / p50 / p95) over the numeric metric
  columns — loss, grad_norm, tokens_per_sec, step latency, mfu;
- comms accounting echo (ring hops, bytes per hop, overlap fraction);
- when ``--xprof DIR`` points at a capture with ``*.xplane.pb`` planes:
  the per-stage device-time table (busy ms / share / p50 / p95 keyed on
  the stack's stable trace names), the per-hop compute-vs-transfer
  timeline, and the MEASURED compute/transfer overlap fraction — printed
  next to the analytic ``hop_overlap_fraction`` from the metrics rows
  when both exist; disagreement beyond ``--overlap-tolerance`` is
  reported as a FINDING line (the comms model no longer describes the
  capture);
- ``--diff OLD NEW`` (instead of a single run): side-by-side per-metric
  table over two runs with delta and percent columns — the manual
  version of ``tools/perf_gate.py`` for a human bisecting a regression.

Stdlib-only: the xplane parser is ``utils/profiling.py``'s wire-format
reader (loaded by file path, no jax import), so this tool runs on a box
where jax cannot.  Usage::

  python tools/trace_report.py /tmp/m [--xprof /tmp/profile]
  python tools/trace_report.py --diff /tmp/m_before /tmp/m_after
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys
from collections import defaultdict

_HERE = os.path.dirname(os.path.abspath(__file__))
_PKG_UTILS = os.path.join(
    os.path.dirname(_HERE), "ring_attention_tpu", "utils"
)

# metric columns the table summarizes, in display order (other numeric
# fields are appended alphabetically)
PREFERRED = [
    "loss",
    "grad_norm",
    "tokens_per_sec",
    "steps_per_sec",
    "step_ms_p50",
    "step_ms_p95",
    "mfu",
]

# comms-accounting + compiled-memory fields echoed as a static block
# (they do not vary per step — one line each beats 5 columns of constants)
ACCOUNTING = [
    "ring_size",
    "ulysses_size",
    "ring_hops",
    "pure_ring_hops",
    "ring_hops_per_step",
    "hop_bytes",
    "ring_bytes_per_step",
    "ring_bytes_per_step_bwd",
    "a2a_bytes_per_step",
    "hop_overlap_fraction",
    # compiled peak-memory accounting of the train step (telemetry
    # .compiled_memory — temp_bytes is the scratch high-water mark the
    # ff_chunk_size / loss_chunk_size / remat-policy knobs shrink)
    "temp_bytes",
    "argument_bytes",
    "output_bytes",
    "alias_bytes",
    "host_temp_bytes",
    "host_argument_bytes",
    "host_output_bytes",
]


def _load_module(name: str, filename: str):
    """Load a utils module by file path so this tool never imports the
    package (whose ``__init__`` pulls in jax/flax) — the same pattern as
    ``bench.py``'s parent process; both modules are stdlib-only at module
    level by design.  Memoized: one exec per module per run."""
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_PKG_UTILS, filename)
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _read_rows(path: str) -> list[dict]:
    """The library's own reader (``telemetry.read_metrics`` — the one the
    killed-writer tests pin)."""
    return _load_module("_report_telemetry", "telemetry.py").read_metrics(path)


def _profiling():
    return _load_module("_report_profiling", "profiling.py")


def _percentile(values: list[float], q: float) -> float:
    """The library's own percentile (``profiling.percentile`` — the one
    the timer and the timeline use), so the three tables can never
    disagree on interpolation."""
    return _profiling().percentile(values, q)


def _fmt(x: float) -> str:
    if x == 0:
        return "0"
    if abs(x) >= 1e5 or abs(x) < 1e-3:
        return f"{x:.3e}"
    return f"{x:,.4f}".rstrip("0").rstrip(".")


def _numeric_columns(rows: list[dict]) -> dict[str, list[float]]:
    numeric: dict[str, list[float]] = defaultdict(list)
    for r in rows:
        if "event" in r:
            continue
        for key, val in r.items():
            if key in ("schema", "step", "time") or isinstance(val, bool):
                continue
            if isinstance(val, (int, float)):
                numeric[key].append(float(val))
    return numeric


def metrics_report(rows: list[dict], out: list[str]) -> None:
    metric_rows = [r for r in rows if "event" not in r]
    events = [r for r in rows if "event" in r]
    steps = [r.get("step") for r in metric_rows if "step" in r]
    schemas = sorted({r.get("schema") for r in rows if "schema" in r})
    out.append(
        f"rows: {len(metric_rows)} metric + {len(events)} event | "
        f"steps {min(steps) if steps else '-'}..{max(steps) if steps else '-'}"
        f" | schema {','.join(str(s) for s in schemas) or '-'}"
    )
    for ev in events:
        kind = ev.get("event")
        detail = ev.get("component") or ev.get("reason") or ""
        out.append(f"  event: {kind} {detail}".rstrip())
    degraded = sum(int(r.get("degraded", 0)) for r in rows)
    if degraded:
        out.append(f"  DEGRADED run: {degraded} kernel-fallback event(s) — "
                   f"see ring_attention_tpu.utils.resilience.degradation")
    if not metric_rows:
        return

    numeric = _numeric_columns(rows)
    acct = [k for k in ACCOUNTING if k in numeric]
    if acct:
        out.append("")
        out.append("comms accounting (analytic, per device)")
        for key in acct:
            out.append(f"  {key:24s} {_fmt(numeric[key][-1])}")

    cols = [k for k in PREFERRED if k in numeric]
    cols += sorted(k for k in numeric if k not in cols and k not in acct)
    out.append("")
    out.append(f"  {'metric':20s} {'last':>12s} {'mean':>12s} "
               f"{'p50':>12s} {'p95':>12s}")
    for key in cols:
        vals = numeric[key]
        out.append(
            f"  {key:20s} {_fmt(vals[-1]):>12s} "
            f"{_fmt(sum(vals) / len(vals)):>12s} "
            f"{_fmt(_percentile(vals, 0.5)):>12s} "
            f"{_fmt(_percentile(vals, 0.95)):>12s}"
        )


def diff_report(old_path: str, new_path: str, out: list[str]) -> None:
    """Side-by-side per-metric comparison of two runs: p50 over each run
    plus delta and percent — the human-facing half of the perf gate."""
    old = _numeric_columns(_read_rows(old_path))
    new = _numeric_columns(_read_rows(new_path))
    out.append(f"diff: OLD={old_path}  NEW={new_path}")
    keys = [k for k in PREFERRED if k in old or k in new]
    keys += sorted((set(old) | set(new)) - set(keys))
    out.append("")
    out.append(f"  {'metric':24s} {'old p50':>12s} {'new p50':>12s} "
               f"{'delta':>12s} {'pct':>8s}")
    for key in keys:
        a = _percentile(old[key], 0.5) if key in old else None
        b = _percentile(new[key], 0.5) if key in new else None
        if a is None or b is None:
            side = "only OLD" if b is None else "only NEW"
            old_s = _fmt(a) if a is not None else "-"
            new_s = _fmt(b) if b is not None else "-"
            out.append(f"  {key:24s} {old_s:>12s} {new_s:>12s} "
                       f"{side:>12s} {'-':>8s}")
            continue
        delta = b - a
        pct = f"{delta / a * 100:+.1f}%" if a else "-"
        out.append(
            f"  {key:24s} {_fmt(a):>12s} {_fmt(b):>12s} "
            f"{_fmt(delta):>12s} {pct:>8s}"
        )


def xprof_report(trace_dir: str, out: list[str], *,
                 analytic: float | None = None,
                 tolerance: float = 0.25,
                 ring_size: int | None = None) -> None:
    """Per-stage/per-hop device time + measured overlap from an xplane
    capture, via the stdlib parser in ``utils/profiling.py``.
    ``ring_size`` (from the run's accounting rows) folds multi-step
    captures into per-step hop samples.  Best-effort: an unreadable
    capture degrades to a note, never an error (the metrics table above
    is the primary product)."""
    prof = _profiling()
    report = prof.overlap_report(trace_dir, analytic=analytic,
                                 tolerance=tolerance, ring_size=ring_size)
    if "note" in report:
        out.append(f"[xprof] {report['note']}")
        return
    timeline = report["timeline"]
    total = timeline["total_busy_ms"] or 1.0
    out.append("")
    out.append(f"per-stage device time ({trace_dir})")
    out.append(f"  {'stage':26s} {'kind':>8s} {'busy ms':>10s} "
               f"{'share':>7s} {'p50 ms':>9s} {'p95 ms':>9s}")
    for row in timeline["stages"]:
        out.append(
            f"  {row['stage']:26s} {row['kind']:>8s} "
            f"{row['busy_ms']:10.3f} {100 * row['busy_ms'] / total:6.1f}% "
            f"{row['p50_ms']:9.3f} {row['p95_ms']:9.3f}"
        )
    if timeline["hops"]:
        out.append("")
        out.append("per-hop timeline (ring schedule)")
        out.append(f"  {'hop':>4s} {'compute ms':>11s} {'transfer ms':>12s} "
                   f"{'samples':>8s}")
        for row in timeline["hops"]:
            out.append(
                f"  {row['hop']:4d} {row['compute_ms']:11.3f} "
                f"{row['transfer_ms']:12.3f} {row['samples']:8d}"
            )
    out.append("")
    out.append(
        f"measured overlap: {report['overlap_fraction']:.3f} "
        f"(transfer {report['transfer_ms']:.3f} ms, compute "
        f"{report['compute_ms']:.3f} ms, overlapped "
        f"{report['overlapped_ms']:.3f} ms)"
    )
    if "analytic_overlap_fraction" in report:
        out.append(
            f"analytic overlap: {report['analytic_overlap_fraction']:.3f} "
            f"(ring_comms_accounting hop_overlap_fraction)"
        )
        if not report["agrees"]:
            out.append(f"FINDING: {report['finding']}")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="Render telemetry JSONL (+ optional xprof capture) "
                    "into per-metric / per-stage / per-hop tables"
    )
    ap.add_argument("metrics", nargs="?", default=None,
                    help="metrics directory (holding metrics.jsonl) or a "
                         "JSONL file written by MetricsLogger")
    ap.add_argument("--xprof", default=None,
                    help="xprof capture dir (tools/xprof_capture.py / "
                         "utils.profiling.trace): adds per-stage and "
                         "per-hop device-time tables plus the measured "
                         "compute/transfer overlap fraction")
    ap.add_argument("--last", type=int, default=None,
                    help="summarize only the last N metric rows")
    ap.add_argument("--diff", nargs=2, metavar=("OLD", "NEW"), default=None,
                    help="compare two metrics runs: per-metric p50 "
                         "side-by-side with delta and percent columns")
    ap.add_argument("--overlap-tolerance", type=float, default=0.25,
                    help="measured-vs-analytic overlap disagreement beyond "
                         "this is reported as a FINDING (default 0.25)")
    args = ap.parse_args(argv)

    out: list[str] = []
    if args.diff:
        diff_report(args.diff[0], args.diff[1], out)
        print("\n".join(out))
        return 0
    if args.metrics is None:
        ap.error("metrics path required (or use --diff OLD NEW)")

    rows = _read_rows(args.metrics)
    if args.last is not None:
        events = [r for r in rows if "event" in r]
        metric = [r for r in rows if "event" not in r][-args.last:]
        rows = events + metric
    out.append(f"trace report: {args.metrics}")
    metrics_report(rows, out)
    if args.xprof:
        # analytic overlap + ring size from the run's own accounting
        # rows, when present
        numeric = _numeric_columns(rows)
        analytic = (
            numeric["hop_overlap_fraction"][-1]
            if numeric.get("hop_overlap_fraction") else None
        )
        ring_size = (
            int(numeric["ring_size"][-1])
            if numeric.get("ring_size") else None
        )
        xprof_report(args.xprof, out, analytic=analytic,
                     tolerance=args.overlap_tolerance,
                     ring_size=ring_size)
    print("\n".join(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
