#!/usr/bin/env python
"""Merge per-process span files into one cluster timeline.

Input is a trace directory written by ``utils/tracing.py`` — one
``spans_pNNNNN.jsonl`` per process (``examples/train.py --trace-dir``,
``examples/generate.py --trace-dir``, or ``RING_ATTN_TRACE_DIR`` on a
chaos worker).  The merger stamps every row with its process, corrects
each process's wall clock against the reference process using shared
barrier-rendezvous rows (all processes leave the same named barrier at
approximately the same true instant), and renders:

- the default text table: one line per span/instant in corrected time
  order, with process, duration, and attributes — the cluster's actual
  interleaving, stragglers visible as long ``barrier/wait`` spans;
- ``--chrome OUT.json``: Chrome trace-event JSON (open in Perfetto or
  ``chrome://tracing``) with one track per process;
- ``--incident``: the reconstruction around the last ``chaos/kill`` or
  ``watchdog/abort`` anchor — names the victim process, the armed fault
  window, the survivors' barrier waits (straggler watch), and the
  timeline slice around the death.  Exit code 3 when no anchor exists
  (the run died some other way, or didn't die).

Stdlib-only: ``tracing.py`` is loaded by file path (no jax import), so
this runs on a box where jax cannot.  Usage::

  python tools/cluster_timeline.py /tmp/trace
  python tools/cluster_timeline.py /tmp/trace --chrome /tmp/trace.json
  python tools/cluster_timeline.py /tmp/trace --incident
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_PKG_UTILS = os.path.join(
    os.path.dirname(_HERE), "ring_attention_tpu", "utils"
)


def _load_tracing():
    """Load ``utils/tracing.py`` by file path so this tool never imports
    the package (whose ``__init__`` pulls in jax/flax) — the same
    pattern as ``tools/trace_report.py``.  Memoized."""
    name = "_timeline_tracing"
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_PKG_UTILS, "tracing.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-process span JSONL files into one "
                    "clock-corrected cluster timeline "
                    "(docs/observability.md §6)"
    )
    ap.add_argument("trace_dir",
                    help="directory of spans_pNNNNN.jsonl files "
                         "(utils/tracing.py)")
    ap.add_argument("--chrome", metavar="OUT.json", default=None,
                    help="write Chrome trace-event JSON (Perfetto / "
                         "chrome://tracing) instead of the text table")
    ap.add_argument("--incident", action="store_true",
                    help="reconstruct the last chaos/kill or "
                         "watchdog/abort incident (exit 3 if none)")
    ap.add_argument("--last", type=int, default=None, metavar="N",
                    help="text table: only the last N rows")
    ap.add_argument("--reference", type=int, default=None, metavar="P",
                    help="clock-reference process (default: lowest "
                         "process index)")
    args = ap.parse_args(argv)

    tracing = _load_tracing()
    if not os.path.isdir(args.trace_dir):
        print(f"cluster_timeline: no such directory: {args.trace_dir}",
              file=sys.stderr)
        return 2
    merged = tracing.merge_trace_dir(
        args.trace_dir, reference=args.reference
    )
    if not merged["spans"]:
        print(f"cluster_timeline: no span rows under {args.trace_dir}",
              file=sys.stderr)
        return 2

    if args.chrome:
        payload = tracing.to_chrome_trace(merged)
        with open(args.chrome, "w") as fh:
            json.dump(payload, fh)
        print(f"chrome trace: {args.chrome} "
              f"({len(payload['traceEvents'])} events, "
              f"{len(merged['processes'])} processes)")
        return 0

    if args.incident:
        report = tracing.reconstruct_incident(merged)
        if report is None:
            print("cluster_timeline: no incident anchor (chaos/kill or "
                  "watchdog/abort) in this trace", file=sys.stderr)
            return 3
        print(report)
        return 0

    print(tracing.render_timeline(merged, limit=args.last))
    return 0


if __name__ == "__main__":
    sys.exit(main())
