#!/usr/bin/env python
"""First real use of the profiling subsystem (VERDICT r3 next #6).

Captures XProf traces of (a) the fused forward kernel and (b) a full
train step on the live chip via ``ring_attention_tpu.utils.profiling``,
then parses the xplane protobuf to report where device time goes (the
MXU/VPU/DMA split that directs the next MFU push).  Traces land in
``docs/hwlogs/xprof/``, the summary in ``docs/hwlogs/xprof_summary.txt``.

Run only inside a healthy TPU window (tools/hw_session.sh step `xprof`).
"""

from __future__ import annotations

import glob
import os
import sys
from collections import defaultdict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACE_ROOT = os.path.join(REPO, "docs", "hwlogs", "xprof")
SUMMARY = os.path.join(REPO, "docs", "hwlogs", "xprof_summary.txt")

SEQ = 65536  # warm-compile shape with known rates (68.7 TFLOPs fwd)
HEADS, DIM_HEAD = 8, 64


def _parse_args():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=SEQ,
                    help="trace shape; the CPU preflight shrinks this so "
                         "the capture path is launchable without silicon "
                         "(kernels auto-select interpret mode off-TPU)")
    ap.add_argument("--out-dir", default=None,
                    help="trace/summary root override (the CPU preflight "
                         "points this at a temp dir so docs/hwlogs/ only "
                         "ever holds real silicon traces)")
    return ap.parse_args()


def _categorize(name: str) -> str:
    n = name.lower()
    if any(t in n for t in ("dot", "convolution", "matmul", "mxu")):
        return "MXU (dot/conv)"
    if "custom-call" in n or "mosaic" in n or "tpu_custom_call" in n:
        return "Pallas kernel (custom-call)"
    if any(t in n for t in ("copy", "dynamic-update", "dynamic-slice",
                            "transpose", "reshape", "broadcast", "pad",
                            "concatenate", "slice")):
        return "data movement"
    if any(t in n for t in ("all-reduce", "all-gather", "collective",
                            "permute", "reduce-scatter")):
        return "collectives"
    if "fusion" in n:
        return "XLA fusion (VPU/elementwise)"
    if "infeed" in n or "outfeed" in n or "host" in n:
        return "host transfer"
    return "other"


def summarize(trace_dir: str, tag: str, out: list[str]) -> None:
    # parsing is best-effort: the traces on disk are the scarce artifact
    # (captured in a healthy TPU window); a missing/broken proto parser
    # must not fail the step and burn a re-capture on the next window
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: E501 (the one xplane proto in this image)
    except Exception as e:  # ImportError or any TF-init failure
        out.append(
            f"[{tag}] xplane parser unavailable ({type(e).__name__}: {e}); "
            f"traces saved under {trace_dir} — parse offline"
        )
        return

    paths = glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    )
    if not paths:
        out.append(f"[{tag}] no .xplane.pb produced under {trace_dir}")
        return
    space = xplane_pb2.XSpace()
    with open(max(paths, key=os.path.getmtime), "rb") as f:
        space.ParseFromString(f.read())

    device_planes = [
        p for p in space.planes
        if "TPU" in p.name or "/device:" in p.name
    ] or list(space.planes)
    out.append(f"[{tag}] planes: {[p.name for p in space.planes]}")
    for plane in device_planes:
        # "XLA Modules" / "Steps" lines nest the "XLA Ops" line's events;
        # summing every line would double-count, so keep only the op line
        # when the plane has one (the TPU device-plane convention)
        op_lines = [l for l in plane.lines if "XLA Ops" in l.name]
        lines = op_lines or plane.lines
        per_op: dict[str, float] = defaultdict(float)
        span_lo, span_hi = float("inf"), 0.0
        for line in lines:
            for ev in line.events:
                meta = plane.event_metadata.get(ev.metadata_id)
                name = meta.name if meta else str(ev.metadata_id)
                dur = ev.duration_ps / 1e9  # -> ms
                per_op[name] += dur
                span_lo = min(span_lo, ev.offset_ps / 1e9)
                span_hi = max(span_hi, (ev.offset_ps + ev.duration_ps) / 1e9)
        if not per_op:
            continue
        busy = sum(per_op.values())
        span = max(span_hi - span_lo, 1e-9)
        cats: dict[str, float] = defaultdict(float)
        for name, ms in per_op.items():
            cats[_categorize(name)] += ms
        out.append(
            f"[{tag}] plane '{plane.name}': busy {busy:.2f} ms over a "
            f"{span:.2f} ms span ({100 * busy / span:.1f}% occupancy)"
        )
        for cat, ms in sorted(cats.items(), key=lambda kv: -kv[1]):
            out.append(f"[{tag}]   {cat:32s} {ms:10.3f} ms "
                       f"({100 * ms / busy:5.1f}% of busy)")
        top = sorted(per_op.items(), key=lambda kv: -kv[1])[:12]
        out.append(f"[{tag}]   top ops:")
        for name, ms in top:
            out.append(f"[{tag}]     {ms:9.3f} ms  {name[:90]}")


def main() -> int:
    import jax
    import jax.numpy as jnp

    from ring_attention_tpu.ops.pallas_flash import pallas_flash_fused
    from ring_attention_tpu.utils import enable_compile_cache
    from ring_attention_tpu.utils.profiling import trace

    args = _parse_args()
    seq = args.seq
    trace_root, summary = TRACE_ROOT, SUMMARY
    if args.out_dir:
        trace_root = os.path.join(args.out_dir, "xprof")
        summary = os.path.join(args.out_dir, "xprof_summary.txt")
    enable_compile_cache()

    os.makedirs(trace_root, exist_ok=True)
    out: list[str] = []
    dev = jax.devices()[0]
    out.append(f"device: {dev.device_kind} ({dev.platform})")

    # --- phase 1: fused fwd kernel ------------------------------------
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (1, HEADS, seq, DIM_HEAD), jnp.bfloat16)
    k = jax.random.normal(ks[1], (1, HEADS, seq, DIM_HEAD), jnp.bfloat16)
    v = jax.random.normal(ks[2], (1, HEADS, seq, DIM_HEAD), jnp.bfloat16)

    @jax.jit
    def fwd(q, k, v):
        o, _ = pallas_flash_fused(
            q, k, v, scale=DIM_HEAD**-0.5, causal_offset=0,
            block_q=1024, block_k=1024,
        )
        return o

    compiled = fwd.lower(q, k, v).compile()
    ca = compiled.cost_analysis()
    if ca:
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        out.append(
            f"fwd cost_analysis: flops={ca.get('flops', 0):.3e} "
            f"bytes accessed={ca.get('bytes accessed', 0):.3e}"
        )
    jax.block_until_ready(fwd(q, k, v))  # warm outside the trace
    fwd_dir = os.path.join(trace_root, "fwd")
    with trace(fwd_dir):
        for _ in range(5):
            r = fwd(q, k, v)
        jax.block_until_ready(r)
    summarize(fwd_dir, "fwd-kernel", out)

    # --- phase 2: train step (flagship config, save_attn remat) -------
    import optax

    from ring_attention_tpu.models import RingTransformer
    from ring_attention_tpu.utils import make_train_step

    model = RingTransformer(
        num_tokens=256, dim=512, depth=2, causal=True, heads=HEADS,
        dim_head=DIM_HEAD, bucket_size=min(2048, max(seq // 4, 8)),
        rotary=True, use_pallas=True,
        remat=True, remat_policy="save_attn", dtype=jnp.bfloat16,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 129), jnp.int32),
        return_loss=True,
    )
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (1, seq + 1), 0, 256, jnp.int32
    )
    step = jax.jit(make_train_step(
        lambda p, t: model.apply(p, t, return_loss=True), opt
    ))
    params, opt_state, loss = step(params, opt_state, tokens)  # warm
    jax.block_until_ready(loss)
    train_dir = os.path.join(trace_root, "train")
    with trace(train_dir):
        params, opt_state, loss = step(params, opt_state, tokens)
        jax.block_until_ready(loss)
    out.append(f"train step loss={float(loss):.4f}")
    summarize(train_dir, "train-step", out)

    text = "\n".join(out)
    print(text)
    with open(summary, "w") as f:
        f.write(text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
