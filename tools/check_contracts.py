#!/usr/bin/env python
"""Verify the package's collective/HLO contracts on CPU virtual devices.

Compiles every requested sequence-parallel entry point over a simulated
mesh and checks the optimized-HLO collective counts, axis discipline, and
jaxpr structure against the declarative table in
``ring_attention_tpu/analysis/contracts.py`` — the machine-checked version
of "exactly ring-1 ppermutes per forward".

``--memory`` runs the memory-axis audit suite instead
(``analysis/recompile.py``): f32 accumulator dtypes, remat-residual
policy leaks on the chunked-FFN path (with a negative toy proving the
audit is live), donation aliasing and host-offload placement of the
composed train step, and the chunked-vs-dense compiled peak-temp-bytes
relation — the machine-checked version of docs/memory.md's claims.

``--coverage`` runs the tile-coverage prover (``analysis/coverage.py``):
every strategy x layout x masking row's compact skip grid held to a
global-position oracle — soundness (no live tile skipped), tightness
(no dead tile visited, closed-form count == enumeration), and schedule
completeness (each element exactly once across the hops).

``--dataflow`` runs the jaxpr dataflow passes (``analysis/dataflow.py``):
the precision-flow auditor (bf16/int8 taint to every reduction and
accumulator carry — both flash paths, the int8 hop chain, the counter
bwd pack) and the SPMD divergence checker (branch-invariant collective
sequences for every strategy, on simulated devices).

``--dma`` runs the fused-ring DMA/semaphore protocol verifier
(``analysis/schedverify.py``): the symbolic N-device model check over
ring sizes 2..8 (matched waits on both ends, no slot overwritten while a
concurrent reader holds it, semaphore drain, deadlock freedom under
arbitrary compute skew) plus the jaxpr extraction cross-check of the
traced kernel against the declared ``PROTOCOL`` table, for the plain and
q8 feeds.

``--elastic`` runs the elastic checkpoint contracts
(``elastic/verify.py``): manifest schema round-trip (mesh descriptor,
per-leaf dtype/spec, shard digests matching disk), resharded-load ==
direct-load at a changed mesh (bit-exact), corrupt-shard fallback, and
commit-protocol debris sweeping — all on CPU virtual devices.

Examples:
  python tools/check_contracts.py --strategy all
  python tools/check_contracts.py --strategy hybrid --mesh 1x2x4
  python tools/check_contracts.py --strategy ring --mesh 2x4 --json
  python tools/check_contracts.py --memory
  python tools/check_contracts.py --coverage
  python tools/check_contracts.py --dataflow
  python tools/check_contracts.py --dma
  python tools/check_contracts.py --elastic

Exit status 0 = every contract holds.  Runs anywhere (no TPU needed):
``--devices N`` simulated host devices, default 8.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

try:  # prefer the installed package (pip install -e .)
    import ring_attention_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout, any cwd
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _parse_mesh(spec: str):
    """``"1x8"`` -> plain (data, seq) mesh; ``"1x4x2"`` -> factored
    (data, ring, ulysses) mesh."""
    from ring_attention_tpu.parallel.mesh import create_mesh

    dims = [int(x) for x in spec.lower().split("x")]
    if len(dims) == 2:
        data, ring = dims
        return create_mesh(ring_size=ring, data_size=data)
    if len(dims) == 3:
        data, ring, ulysses = dims
        return create_mesh(ring_size=ring, data_size=data,
                           ulysses_size=ulysses)
    raise SystemExit(f"--mesh {spec!r}: want DxR (plain) or DxRxU (factored)")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--strategy", default="all",
                        help="strategy name or 'all' (default); "
                             "comma-separate for a subset")
    parser.add_argument("--mesh", default=None,
                        help="mesh shape like 1x8 (data x seq) or 1x4x2 "
                             "(data x ring x ulysses); default: all devices "
                             "on the sequence axis")
    parser.add_argument("--devices", type=int, default=8,
                        help="simulated host devices (default 8)")
    parser.add_argument("--seq", type=int, default=64)
    parser.add_argument("--heads", type=int, default=8)
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON object instead of the table")
    parser.add_argument("--memory", action="store_true",
                        help="run the memory-axis audits (accumulator "
                             "dtypes, remat-residual leaks, donation "
                             "aliasing, host-offload placement, chunked-"
                             "vs-dense peak temp bytes) instead of the "
                             "collective contracts")
    parser.add_argument("--coverage", action="store_true",
                        help="run the tile-coverage prover (skip-grid "
                             "soundness/tightness/schedule completeness "
                             "per strategy x layout x masking row) "
                             "instead of the collective contracts")
    parser.add_argument("--mask", default=None, metavar="EXPR",
                        help="with --coverage: re-prove ONE mask-algebra "
                             "row in isolation — a textual mask "
                             "expression like 'causal&window:512' or "
                             "'prefix:128|docs:0,64' (leaves: full, "
                             "causal, window:W, prefix:P, dilated:S[+O], "
                             "docs:a,b,..., segments, perhead(a;b); "
                             "combinators & | ~ and parentheses), "
                             "lowered and certified on the standard "
                             "single/ring/counter geometries")
    parser.add_argument("--dataflow", action="store_true",
                        help="run the jaxpr dataflow passes (precision-"
                             "flow audit + SPMD divergence checker) "
                             "instead of the collective contracts")
    parser.add_argument("--dma", action="store_true",
                        help="run the fused-ring DMA/semaphore protocol "
                             "verifier (rings-2..8 model check: matched "
                             "waits, overwrite races, semaphore drain, "
                             "deadlock freedom; plus the jaxpr extraction "
                             "cross-check against the declared PROTOCOL "
                             "table) instead of the collective contracts")
    parser.add_argument("--elastic", action="store_true",
                        help="run the elastic checkpoint contracts "
                             "(manifest schema round-trip, resharded-"
                             "load == direct-load at a changed mesh, "
                             "corrupt-shard fallback, commit-debris "
                             "sweep, and the spawned two-process rows: "
                             "barrier semantics + 2->1/1->2 commit "
                             "round-trips) instead of the collective "
                             "contracts")
    parser.add_argument("--no-multiprocess", action="store_true",
                        help="with --elastic: skip the spawned two-"
                             "process cluster rows (4 checks instead of "
                             "7) — the quick in-process subset")
    args = parser.parse_args(argv)

    # must precede the first jax import
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.devices}"
    )
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if args.mask is not None and not args.coverage:
        raise SystemExit("--mask re-proves a coverage row; add --coverage")

    if args.coverage:
        from ring_attention_tpu.analysis.coverage import run_coverage_suite

        if args.mask is not None:
            from ring_attention_tpu.analysis.coverage import (
                MaskCoverageCase,
                prove_mask_case,
            )
            from ring_attention_tpu.masks import MaskParseError, parse_mask

            try:
                expr = parse_mask(args.mask).key
            except MaskParseError as e:
                # unknown names list the registry, not a traceback
                print(f"--mask {args.mask!r}: {e}", file=sys.stderr)
                return 2
            geometries = [
                ("single", "contiguous", 1, 64, 8),
                ("ring", "contiguous", 4, 16, 4),
                ("counter", "contiguous", 4, 16, 4),
            ]
            from ring_attention_tpu.masks import MaskLoweringError

            reports = []
            for strategy, layout, ring, n_local, block in geometries:
                try:
                    reports.append(prove_mask_case(MaskCoverageCase(
                        name=f"mask/{strategy}/{expr}", expr=args.mask,
                        strategy=strategy, layout=layout, ring=ring,
                        n_local=n_local, block=block,
                    )))
                except MaskLoweringError as e:
                    # e.g. a striped/generic combination with no lowering
                    # on this geometry — skipped loudly, other errors raise
                    print(f"skip mask/{strategy}: {e}", file=sys.stderr)
            if not reports:
                # every geometry skipped = nothing was proven; exiting 0
                # here would let an unproven mask read as certified
                print(f"--mask {args.mask!r}: no geometry produced a "
                      f"lowering — nothing was proven", file=sys.stderr)
                return 2
        else:
            reports = run_coverage_suite()
        failed = [r for r in reports if not r.ok]
        if args.json:
            print(json.dumps({
                "ok": not failed,
                "checked": len(reports),
                "reports": [r.to_json() for r in reports],
            }, indent=2))
        else:
            for r in reports:
                mark = "ok  " if r.ok else "FAIL"
                print(f"{mark} {r.name:<32} hops={r.hops:<2} "
                      f"tiles={r.tiles:<4} work={r.work:<4} "
                      f"edge={r.edge:<4} kmajor={r.tiles_kmajor}")
                for v in r.violations:
                    print(f"     {v}")
            print(f"{len(reports) - len(failed)}/{len(reports)} coverage "
                  f"rows sound and tight")
        return 1 if failed else 0

    if args.dma:
        from ring_attention_tpu.analysis.schedverify import (
            run_schedverify_suite,
        )

        checks = run_schedverify_suite()
        failed_names = [name for name, v in checks if v]
        if args.json:
            print(json.dumps({
                "ok": not failed_names,
                "checked": len(checks),
                "checks": [
                    {"name": name, "ok": not v, "violations": v}
                    for name, v in checks
                ],
            }, indent=2))
        else:
            for name, v in checks:
                print(f"{'ok  ' if not v else 'FAIL'} {name}")
                for line in v:
                    print(f"     {line}")
            print(f"{len(checks) - len(failed_names)}/{len(checks)} "
                  f"DMA-protocol checks hold")
        return 1 if failed_names else 0

    if args.elastic:
        from ring_attention_tpu.elastic.verify import run_elastic_suite

        checks = run_elastic_suite(multiprocess=not args.no_multiprocess)
        failed_names = [name for name, v in checks if v]
        if args.json:
            print(json.dumps({
                "ok": not failed_names,
                "checked": len(checks),
                "checks": [
                    {"name": name, "ok": not v, "violations": v}
                    for name, v in checks
                ],
            }, indent=2))
        else:
            for name, v in checks:
                print(f"{'ok  ' if not v else 'FAIL'} {name}")
                for line in v:
                    print(f"     {line}")
            print(f"{len(checks) - len(failed_names)}/{len(checks)} "
                  f"elastic checks hold")
        return 1 if failed_names else 0

    if args.dataflow:
        from ring_attention_tpu.analysis.dataflow import (
            run_divergence_suite,
            run_precision_suite,
        )

        checks = run_precision_suite() + run_divergence_suite()
        failed_names = [name for name, v in checks if v]
        if args.json:
            print(json.dumps({
                "ok": not failed_names,
                "checked": len(checks),
                "checks": [
                    {"name": name, "ok": not v, "violations": v}
                    for name, v in checks
                ],
            }, indent=2))
        else:
            for name, v in checks:
                print(f"{'ok  ' if not v else 'FAIL'} {name}")
                for line in v:
                    print(f"     {line}")
            print(f"{len(checks) - len(failed_names)}/{len(checks)} "
                  f"dataflow checks hold")
        return 1 if failed_names else 0

    if args.memory:
        from ring_attention_tpu.analysis.recompile import run_memory_suite

        checks = run_memory_suite()
        failed_names = [name for name, v in checks if v]
        if args.json:
            print(json.dumps({
                "ok": not failed_names,
                "checked": len(checks),
                "checks": [
                    {"name": name, "ok": not v, "violations": v}
                    for name, v in checks
                ],
            }, indent=2))
        else:
            for name, v in checks:
                print(f"{'ok  ' if not v else 'FAIL'} {name}")
                for line in v:
                    print(f"     {line}")
            print(f"{len(checks) - len(failed_names)}/{len(checks)} "
                  f"memory checks hold")
        return 1 if failed_names else 0

    from ring_attention_tpu.analysis import contracts

    shape_kw = {"seq": args.seq, "heads": args.heads}
    if args.strategy == "all" and args.mesh is None:
        reports = contracts.run_contract_suite(**shape_kw)
    else:
        names = (list(contracts.CONTRACTS) if args.strategy == "all"
                 else args.strategy.split(","))
        mesh = _parse_mesh(args.mesh) if args.mesh else None
        mesh_kind = (
            None if mesh is None
            else "factored" if len(mesh.shape) == 3 else "plain"
        )
        reports = []
        for name in names:
            if name not in contracts.CONTRACTS:
                raise SystemExit(
                    f"unknown strategy {name!r}; "
                    f"known: {', '.join(sorted(contracts.CONTRACTS))}"
                )
            want_kind = contracts.CONTRACTS[name].get("mesh")
            if mesh_kind is not None and want_kind != mesh_kind:
                # a single --mesh cannot satisfy both plain and factored
                # strategies; skip the mismatches (loudly) instead of
                # aborting the whole run on the first incompatible one
                print(f"skip {name:<16} needs a {want_kind} mesh, "
                      f"--mesh {args.mesh} is {mesh_kind}", file=sys.stderr)
                continue
            reports.extend(contracts.check_strategy(name, mesh, **shape_kw))
            if "scan" in contracts.CONTRACTS[name]:
                reports.extend(
                    contracts.check_scan_contract(name, mesh, **shape_kw)
                )
        if not reports:
            raise SystemExit(
                f"--mesh {args.mesh} matched no requested strategy "
                f"(all need a different mesh kind)"
            )

    failed = [r for r in reports if not r.ok]
    if args.json:
        print(json.dumps({
            "ok": not failed,
            "checked": len(reports),
            "reports": [r.to_json() for r in reports],
        }, indent=2))
    else:
        for r in reports:
            mark = "ok  " if r.ok else "FAIL"
            counts = r.counts or r.jaxpr_counts
            print(f"{mark} {r.strategy:<16} {r.direction:<7} "
                  f"impl={r.impl:<7} mesh={'x'.join(map(str, r.mesh_shape))}"
                  f"  {counts}")
            for v in r.violations:
                print(f"     {v}")
        print(f"{len(reports) - len(failed)}/{len(reports)} contracts hold")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
