"""One-shot hardware validation + block sweep for the Pallas kernels.

Run on a machine with a live TPU (single chip is enough):

    python tools/tpu_kernel_validate.py [--seq 262144] [--sweep]

Prints JSON lines: a parity check of the compact causal grid against the
rectangular grid and the dense oracle, then timed fwd / fwd+bwd
measurements (relay-aware chained timing, ``utils/benchtime.py``), and
optionally a block-size sweep.  Exists because this image's TPU tunnel is
intermittently wedged — when it heals, one command re-establishes the
hardware evidence (VERDICT r1 item 1).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

try:  # prefer the installed package (pip install -e .)
    import ring_attention_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout, any cwd
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=262144)
    ap.add_argument("--sweep", action="store_true")
    ap.add_argument("--bwd-sweep", action="store_true",
                    help="sweep per-pass backward block sizes "
                         "(block_*_dkv / block_*_dq, VERDICT r2 #5)")
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--kv-heads", type=int, default=None,
                    help="GQA: fewer KV heads (BASELINE config 4 is 32/4)")
    ap.add_argument("--dim-head", type=int, default=64)
    ap.add_argument("--interpret", action="store_true",
                    help="run kernels in interpret mode (CPU preflight of "
                         "this tool's queued invocations; no Mosaic)")
    ap.add_argument("--segments", type=int, default=None, metavar="N",
                    help="packed-sequence sweep: N equal block-aligned "
                         "documents — parity vs the per-document oracle, "
                         "compact-grid tile counts (trace-time doc skip), "
                         "and timed fwd packed vs plain causal")
    ap.add_argument("--q8", action="store_true",
                    help="int8 compute sweep (PR 13): parity of the "
                         "quantized QK^T/PV kernels vs bf16 at the small "
                         "shape, then timed int8 fwd per (block, head-dim) "
                         "next to the bf16 rows — on silicon the int8 MXU "
                         "rate is ~2x bf16 peak (docs/precision.md)")
    ap.add_argument("--fused", action="store_true",
                    help="fused-ring sweep (PR 18): parity of the single-"
                         "launch fused hop chain (ops/pallas_ring.py, "
                         "in-kernel carry across hops) vs the scan-path "
                         "span sequence and the dense oracle at the small "
                         "shape, then a timed fused fwd per block size at "
                         "--seq — the launch-boundary cost the fused path "
                         "deletes, readable against the plain fwd rows")
    ap.add_argument("--hybrid", type=int, default=None, metavar="U",
                    help="hybrid Ulysses x Ring sweep: for every factoring "
                         "(u, r) of the available devices with u <= U, "
                         "oracle parity of the 2-D factored attention at "
                         "the small shape plus a timed fwd at --seq — on a "
                         "multi-chip slice this measures the real "
                         "all-to-all + shortened-ring collectives "
                         "(docs/hybrid_parallelism.md)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from ring_attention_tpu.utils import enable_compile_cache

    # persistent executable cache: a long relay compile only has to
    # succeed once across sessions (docs/hardware_log.md wedge pathology)
    enable_compile_cache()

    from ring_attention_tpu.ops.attention import default_attention
    from ring_attention_tpu.ops.pallas_flash import (
        finalize_partials,
        pallas_flash_attention,
        pallas_flash_partials,
    )
    from ring_attention_tpu.utils.benchtime import timed_chained

    dev = jax.devices()[0]
    print(json.dumps({"device": getattr(dev, "device_kind", str(dev))}))
    h, d = args.heads, args.dim_head
    hk = args.kv_heads or h
    scale = d**-0.5

    # ---- parity at a small shape: compact grid vs rectangular vs oracle
    n0 = 2048
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (1, h, n0, d), jnp.bfloat16)
    k, v = (jax.random.normal(kk, (1, hk, n0, d), jnp.bfloat16) for kk in ks[1:])
    compact = finalize_partials(
        pallas_flash_partials(q, k, v, scale=scale, causal_offset=0,
                              interpret=args.interpret)
    )[0]
    rect = finalize_partials(
        jax.jit(
            lambda q, k, v, o: pallas_flash_partials(
                q, k, v, scale=scale, causal_offset=o, interpret=args.interpret
            )
        )(q, k, v, jnp.int32(0))
    )[0]
    oracle = default_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        causal=True,
    )
    print(json.dumps({
        "parity_seq": n0,
        "compact_vs_rect_max_err": float(jnp.abs(compact - rect).max()),
        "compact_vs_oracle_max_err": float(jnp.abs(compact - oracle).max()),
    }))

    # ---- packed-sequence (--segments N) sweep
    if args.segments:
        import numpy as np

        from ring_attention_tpu.ops.pallas_flash import band_plan

        n_docs = args.segments
        if n0 % n_docs:
            # a scarce TPU window must not die on an unlucky N: report and
            # continue with the rest of the sweep (same convention as the
            # tile-accounting section below)
            print(json.dumps({
                "segments": n_docs, "parity_seq": n0,
                "note": f"--segments must divide the parity length {n0}; "
                        f"skipping the packed parity check",
            }))
            n_docs = None
    if args.segments and n_docs:
        # parity at the small shape: N equal docs, runtime segment ids AND
        # the trace-time doc-skip tables, both vs the per-document oracle
        doc_len = n0 // n_docs
        starts = tuple(range(0, n0, doc_len))
        seg = jnp.asarray(
            np.repeat(np.arange(n_docs, dtype=np.int32), doc_len)[None, :]
        )
        packed_rt = finalize_partials(
            pallas_flash_partials(q, k, v, scale=scale, causal_offset=0,
                                  segment_ids=seg, interpret=args.interpret)
        )[0]
        packed_tt = finalize_partials(
            pallas_flash_partials(q, k, v, scale=scale, causal_offset=0,
                                  doc_starts=starts, interpret=args.interpret)
        )[0]
        per_doc = jnp.concatenate(
            [
                default_attention(
                    q[:, :, s:s + doc_len].astype(jnp.float32),
                    k[:, :, s:s + doc_len].astype(jnp.float32),
                    v[:, :, s:s + doc_len].astype(jnp.float32),
                    causal=True,
                )
                for s in starts
            ],
            axis=2,
        )
        print(json.dumps({
            "segments": n_docs, "parity_seq": n0,
            "runtime_vs_per_doc_max_err":
                float(jnp.abs(packed_rt - per_doc).max()),
            "tables_vs_per_doc_max_err":
                float(jnp.abs(packed_tt - per_doc).max()),
        }))

        # tile accounting at the target shape: how much of the compact
        # causal grid the declared packing drops at trace time
        bq = bk = 1024
        if args.seq % n_docs == 0 and (args.seq // n_docs) % bq == 0:
            starts_t = tuple(range(0, args.seq, args.seq // n_docs))
            plain = band_plan((args.seq, args.seq), (bq, bk), 0)
            docs_p = band_plan((args.seq, args.seq), (bq, bk), 0,
                               doc_starts=starts_t)
            print(json.dumps({
                "segments": n_docs, "seq": args.seq, "block": bq,
                "work_tiles_plain": plain.work_tiles,
                "work_tiles_docs": docs_p.work_tiles,
                "tiles_dropped_frac": round(
                    1 - docs_p.work_tiles / plain.work_tiles, 4
                ),
                "compact": docs_p.compact,
                "doc_aligned": docs_p.doc_aligned,
            }))
        else:
            print(json.dumps({
                "segments": n_docs, "seq": args.seq,
                "note": "seq must split into N block-aligned docs for the "
                        "tile accounting",
            }))

    # ---- int8 compute sweep (--q8): parity at the small shape, then the
    # timed section below adds int8 rows per (block, head-dim)
    if args.q8:
        q8_small = finalize_partials(
            pallas_flash_partials(q, k, v, scale=scale, causal_offset=0,
                                  compute_dtype="int8",
                                  interpret=args.interpret)
        )[0]
        print(json.dumps({
            "mode": "q8-parity", "parity_seq": n0,
            "q8_vs_bf16_max_err": float(jnp.abs(
                q8_small.astype(jnp.float32) - compact.astype(jnp.float32)
            ).max()),
            "q8_vs_oracle_max_err": float(jnp.abs(
                q8_small.astype(jnp.float32) - oracle
            ).max()),
        }))

    # ---- fused-ring parity (--fused): the single-launch hop chain for the
    # causal last rank of a ring=4 slice of the parity shape, vs the same
    # rows of the scan-path compact grid (both f32-accumulated Pallas —
    # expected bit-exact) and the dense oracle
    if args.fused:
        from ring_attention_tpu.ops.pallas_ring import fused_ring_local
        from ring_attention_tpu.parallel.ring import _fused_tables

        f_ring = 4
        f_n = n0 // f_ring
        origins, his, los, works = _fused_tables(
            f_ring - 1, f_ring, f_n, True, False, None, f_ring
        )
        fused_small = fused_ring_local(
            q[:, :, -f_n:], k, v,
            origins=origins, his=his, los=los, works=works,
            n_local=f_n, scale=scale, interpret=args.interpret,
        )[0]
        print(json.dumps({
            "mode": "fused-parity", "parity_seq": n0, "ring": f_ring,
            "fused_vs_scan_max_err": float(jnp.abs(
                fused_small.astype(jnp.float32)
                - compact[:, :, -f_n:].astype(jnp.float32)
            ).max()),
            "fused_vs_oracle_max_err": float(jnp.abs(
                fused_small.astype(jnp.float32) - oracle[:, :, -f_n:]
            ).max()),
        }))

    # ---- hybrid Ulysses x Ring sweep (--hybrid U): parity + timed fwd at
    # each factoring of the available devices.  u == 1 is the pure-ring
    # baseline the other rows are read against; each row reports its ring
    # hop count so the hop-chain shrinkage is visible next to the timing.
    if args.hybrid:
        from functools import partial

        from jax.sharding import NamedSharding, PartitionSpec as P

        from ring_attention_tpu.parallel import (
            create_mesh,
            hybrid_attention,
            ring_flash_attention,
            seq_partition,
        )
        from ring_attention_tpu.utils.compat import shard_map

        n_dev = len(jax.devices())
        factorings = [
            (u, n_dev // u)
            for u in range(1, min(args.hybrid, n_dev) + 1)
            if n_dev % u == 0
        ]
        # the functional hybrid/ring entry points pick interpret mode from
        # the platform, not per-call — so --interpret (the no-Mosaic
        # preflight contract) routes the sweep through the XLA compute
        # path instead; without it the real Mosaic kernels run on TPU
        sweep_impl = "xla" if args.interpret else "pallas"
        ksp = jax.random.split(jax.random.PRNGKey(3), 3)
        qs = jax.random.normal(ksp[0], (1, h, n0, d), jnp.bfloat16)
        ks_, vs = (
            jax.random.normal(kk, (1, hk, n0, d), jnp.bfloat16)
            for kk in ksp[1:]
        )
        oracle_s = default_attention(
            qs.astype(jnp.float32), ks_.astype(jnp.float32),
            vs.astype(jnp.float32), causal=True,
        )
        seq_flops = 2 * 2 * args.seq * args.seq * h * d * 0.5
        for u, r in factorings:
            if h % u:
                print(json.dumps({
                    "mode": "hybrid", "ulysses": u, "ring": r,
                    "note": f"{h} heads do not divide over u={u}; skipped",
                }))
                continue
            try:
                mesh = (
                    create_mesh(ulysses_size=u, ring_size=r, data_size=1)
                    if u > 1 else create_mesh(ring_size=r, data_size=1)
                )
                spec = P("data", None, seq_partition(mesh), None)
                if u > 1:
                    core = partial(
                        hybrid_attention, kv_mask=None,
                        ulysses_axis="ulysses", ring_axis="ring",
                        causal=True, impl=sweep_impl,
                    )
                else:
                    core = partial(
                        ring_flash_attention, kv_mask=None, axis_name="seq",
                        causal=True, impl=sweep_impl,
                    )
                attn = shard_map(
                    core, mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
                    check_vma=False,
                )
                err = float(jnp.abs(
                    attn(qs, ks_, vs).astype(jnp.float32) - oracle_s
                ).max())
                print(json.dumps({
                    "mode": "hybrid-parity", "ulysses": u, "ring": r,
                    "impl": sweep_impl, "parity_seq": n0, "hops": r - 1,
                    "max_err_vs_oracle": err,
                }))

                sharding = NamedSharding(mesh, spec)
                kst = jax.random.split(jax.random.PRNGKey(4), 3)
                qt = jax.device_put(jax.random.normal(
                    kst[0], (1, h, args.seq, d), jnp.bfloat16), sharding)
                kt = jax.device_put(jax.random.normal(
                    kst[1], (1, hk, args.seq, d), jnp.bfloat16), sharding)
                vt = jax.device_put(jax.random.normal(
                    kst[2], (1, hk, args.seq, d), jnp.bfloat16), sharding)

                @jax.jit
                def chained(q, k, v, attn=attn):
                    def body(c, _):
                        o = attn(c, k, v)
                        return c + 1e-3 * o.astype(c.dtype), o[0, 0, 0, 0]
                    _, ys = jax.lax.scan(body, q, None, length=3)
                    return ys.astype(jnp.float32).sum()

                compile_s, secs = timed_chained(chained, (qt, kt, vt), 3)
                print(json.dumps({
                    "mode": "hybrid-fwd", "seq": args.seq,
                    "ulysses": u, "ring": r, "hops": r - 1,
                    "impl": sweep_impl,
                    # 4 decimals: CPU-backend preflights land in the 1e-3
                    # TFLOPs range and must not round to zero
                    "tflops": round(seq_flops / secs / 1e12, 4),
                    "ms": round(secs * 1e3, 1),
                    "compile_s": round(compile_s, 1),
                }))
            except Exception as e:  # noqa: BLE001 - sweep survives rejects
                print(json.dumps({
                    "mode": "hybrid", "ulysses": u, "ring": r,
                    "error": f"{type(e).__name__}: {str(e)[:160]}",
                }))

    # ---- timing at the target shape
    seq = args.seq
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (1, h, seq, d), jnp.bfloat16)
    k, v = (jax.random.normal(kk, (1, hk, seq, d), jnp.bfloat16) for kk in ks[1:])
    flops_fwd = 2 * 2 * seq * seq * h * d * 0.5

    def fwd_chained(bq, bk, iters, doc_starts=None, compute_dtype=None,
                    sweep_scale=None):
        # one timing harness for every fwd row (bf16, packed, q8, d128):
        # rows are read against each other, so they must measure the
        # same chained computation
        row_scale = scale if sweep_scale is None else sweep_scale

        @jax.jit
        def chained(q, k, v):
            def body(c, _):
                p = pallas_flash_partials(
                    c, k, v, scale=row_scale, causal_offset=0,
                    block_q=bq, block_k=bk, interpret=args.interpret,
                    doc_starts=doc_starts, compute_dtype=compute_dtype,
                )
                o = finalize_partials(p)[0]
                return c + 1e-3 * o.astype(c.dtype), p.m[0, 0, 0]
            _, ys = jax.lax.scan(body, q, None, length=iters)
            return ys.sum()
        return chained

    iters = 3
    pairs = (
        [(512, 512), (512, 1024), (1024, 1024), (1024, 2048), (2048, 512)]
        if args.sweep
        else [(None, None)]
    )
    for bq, bk in pairs:
        try:
            compile_s, secs = timed_chained(
                fwd_chained(bq, bk, iters), (q, k, v), iters
            )
            print(json.dumps({
                "mode": "fwd", "seq": seq, "block_q": bq, "block_k": bk,
                "tflops": round(flops_fwd / secs / 1e12, 1),
                "ms": round(secs * 1e3, 1), "compile_s": round(compile_s, 1),
            }))
        except Exception as e:  # noqa: BLE001 - sweep must survive rejects
            print(json.dumps({
                "mode": "fwd", "seq": seq, "block_q": bq, "block_k": bk,
                "error": f"{type(e).__name__}: {str(e)[:160]}",
            }))

    # ---- int8 timed fwd (--q8): same (block_q, block_k) grid as the
    # bf16 sweep above at the configured head dim, plus a d=128 row —
    # "per (block, head-dim)" so the int8 MXU win is readable against the
    # bf16 rows it sits next to (vs_bf16_peak > 1.0 is the win, not an
    # accounting error: the TFLOPs are counted against useful flops)
    if args.q8:
        for bq, bk in pairs:
            try:
                compile_s, secs = timed_chained(
                    fwd_chained(bq, bk, iters, compute_dtype="int8"),
                    (q, k, v), iters,
                )
                print(json.dumps({
                    "mode": "fwd-q8", "seq": seq, "dim_head": d,
                    "block_q": bq, "block_k": bk,
                    "tflops": round(flops_fwd / secs / 1e12, 1),
                    "ms": round(secs * 1e3, 1),
                    "compile_s": round(compile_s, 1),
                }))
            except Exception as e:  # noqa: BLE001 - sweep survives rejects
                print(json.dumps({
                    "mode": "fwd-q8", "seq": seq, "dim_head": d,
                    "block_q": bq, "block_k": bk,
                    "error": f"{type(e).__name__}: {str(e)[:160]}",
                }))
        d128 = 128
        ks128 = jax.random.split(jax.random.PRNGKey(5), 3)
        q128 = jax.random.normal(ks128[0], (1, h, seq, d128), jnp.bfloat16)
        k128, v128 = (
            jax.random.normal(kk, (1, hk, seq, d128), jnp.bfloat16)
            for kk in ks128[1:]
        )

        try:
            compile_s, secs = timed_chained(
                fwd_chained(1024, 1024, iters, compute_dtype="int8",
                            sweep_scale=d128**-0.5),
                (q128, k128, v128), iters,
            )
            print(json.dumps({
                "mode": "fwd-q8", "seq": seq, "dim_head": d128,
                "block_q": 1024, "block_k": 1024,
                "tflops": round(
                    2 * 2 * seq * seq * h * d128 * 0.5 / secs / 1e12, 1
                ),
                "ms": round(secs * 1e3, 1),
                "compile_s": round(compile_s, 1),
            }))
        except Exception as e:  # noqa: BLE001
            print(json.dumps({
                "mode": "fwd-q8", "seq": seq, "dim_head": d128,
                "error": f"{type(e).__name__}: {str(e)[:160]}",
            }))

    # ---- fused-ring timed fwd (--fused): the ONE-launch hop chain at the
    # target shape per (block_q, block_k), same span schedule and flop
    # accounting as the plain fwd rows above — the row-to-row delta is
    # the measured launch-boundary + carry-rematerialization cost the
    # fused kernel deletes
    if args.fused:
        f_ring = 4
        if seq % f_ring or (seq // f_ring) % 1024:
            print(json.dumps({
                "mode": "fused-fwd", "seq": seq,
                "note": f"--seq must split into {f_ring} block-aligned "
                        "shards for the fused timing",
            }))
        else:
            f_n = seq // f_ring
            tables_t = _fused_tables(
                f_ring - 1, f_ring, f_n, True, False, None, f_ring
            )

            def fused_chained(bq, bk):
                @jax.jit
                def chained(qf, k, v):
                    def body(c, _):
                        o, _lse = fused_ring_local(
                            c, k, v, origins=tables_t[0], his=tables_t[1],
                            los=tables_t[2], works=tables_t[3],
                            n_local=f_n, scale=scale, block_q=bq, block_k=bk,
                            interpret=args.interpret,
                        )
                        return c + 1e-3 * o.astype(c.dtype), o[0, 0, 0, 0]
                    _, ys = jax.lax.scan(body, qf, None, length=iters)
                    return ys.astype(jnp.float32).sum()
                return chained

            qf = jax.random.normal(
                jax.random.PRNGKey(6), (1, h, f_n, d), jnp.bfloat16
            )
            # last-rank causal work: half the diagonal span + R-1 full spans
            flops_fused = 2 * 2 * h * d * f_n * f_n * (f_ring - 0.5)
            for bq, bk in pairs:
                try:
                    compile_s, secs = timed_chained(
                        fused_chained(bq, bk), (qf, k, v), iters
                    )
                    print(json.dumps({
                        "mode": "fused-fwd", "seq": seq, "ring": f_ring,
                        "block_q": bq, "block_k": bk, "kernel_launches": 1,
                        "tflops": round(flops_fused / secs / 1e12, 4),
                        "ms": round(secs * 1e3, 1),
                        "compile_s": round(compile_s, 1),
                    }))
                except Exception as e:  # noqa: BLE001 - sweep survives rejects
                    print(json.dumps({
                        "mode": "fused-fwd", "seq": seq, "ring": f_ring,
                        "block_q": bq, "block_k": bk,
                        "error": f"{type(e).__name__}: {str(e)[:160]}",
                    }))

    # ---- packed fwd timing: the trace-time doc skip vs plain causal at
    # the same shape (useful FLOPs shrink to the per-document triangles)
    if args.segments and seq % args.segments == 0 and (
        (seq // args.segments) % 1024 == 0
    ):
        starts_t = tuple(range(0, seq, seq // args.segments))
        doc_flops = flops_fwd / args.segments  # N equal causal triangles
        try:
            compile_s, secs = timed_chained(
                fwd_chained(1024, 1024, iters, doc_starts=starts_t),
                (q, k, v), iters,
            )
            print(json.dumps({
                "mode": "fwd-packed", "seq": seq, "segments": args.segments,
                "tflops_useful": round(doc_flops / secs / 1e12, 1),
                "ms": round(secs * 1e3, 1), "compile_s": round(compile_s, 1),
            }))
        except Exception as e:  # noqa: BLE001
            print(json.dumps({
                "mode": "fwd-packed", "seq": seq,
                "error": f"{type(e).__name__}: {str(e)[:160]}",
            }))

    # ---- fwd+bwd at default blocks
    do = jax.random.normal(jax.random.PRNGKey(2), q.shape, jnp.bfloat16)
    grad_fn = jax.grad(
        lambda q, k, v, do: (
            pallas_flash_attention(q, k, v, causal=True,
                                   interpret=args.interpret).astype(jnp.bfloat16)
            * do
        ).astype(jnp.float32).sum(),
        argnums=(0, 1, 2),
    )

    @jax.jit
    def bwd_chained(q, k, v, do):
        def body(c, _):
            dq, dk, dv = grad_fn(c, k, v, do)
            nxt = (c + 1e-6 * dq.astype(c.dtype)
                   + (dk.mean() + dv.mean()).astype(c.dtype) * 1e-9)
            return nxt, dq[0, 0, 0, 0]
        _, ys = jax.lax.scan(body, q, None, length=iters)
        return ys.sum()

    try:
        compile_s, secs = timed_chained(bwd_chained, (q, k, v, do), iters)
        flops_fb = 7 * 2 * seq * seq * h * d * 0.5
        print(json.dumps({
            "mode": "fwdbwd", "seq": seq,
            "tflops": round(flops_fb / secs / 1e12, 1),
            "ms": round(secs * 1e3, 1), "compile_s": round(compile_s, 1),
        }))
    except Exception as e:  # noqa: BLE001
        print(json.dumps({
            "mode": "fwdbwd", "seq": seq,
            "error": f"{type(e).__name__}: {str(e)[:160]}",
        }))

    if not args.bwd_sweep:
        return

    # ---- backward-pass block sweep: time pallas_flash_backward alone with
    # per-pass tile overrides; stage 1 sweeps the dk/dv pass with the dq
    # pass pinned, stage 2 vice versa (independent grids, VERDICT r2 #5)
    from ring_attention_tpu.ops.pallas_flash import pallas_flash_backward

    parts = pallas_flash_partials(q, k, v, scale=scale, causal_offset=0,
                                  interpret=args.interpret)
    out, lse = finalize_partials(parts)
    delta = (do.astype(jnp.float32) * out).sum(-1)
    lse = jax.block_until_ready(lse)
    # executed matmuls: dkv pass (sT, dv, dpT, dk) + dq pass (s, dp, dq)
    flops_bwd = 7 * 2 * seq * seq * h * d * 0.5

    def bwd_only_chained(blocks):
        @jax.jit
        def chained(do, q, k, v, lse, delta):
            def body(c, _):
                dq, dk, dv = pallas_flash_backward(
                    c, q, k, v, lse, delta, scale=scale, causal_offset=0,
                    interpret=args.interpret, **blocks,
                )
                nxt = (c + 1e-6 * dq.astype(c.dtype)
                       + (dk.mean() + dv.mean()).astype(c.dtype) * 1e-9)
                return nxt, dq[0, 0, 0, 0]
            _, ys = jax.lax.scan(body, do, None, length=iters)
            return ys.sum()
        return chained

    pairs = [(512, 512), (512, 1024), (1024, 512), (1024, 1024),
             (1024, 2048), (2048, 512), (2048, 1024), (512, 2048)]
    results = {}
    for stage, prefix in (("dkv", "block_{}_dkv"), ("dq", "block_{}_dq")):
        for bq, bk in pairs:
            blocks = {prefix.format("q"): bq, prefix.format("k"): bk}
            try:
                compile_s, secs = timed_chained(
                    bwd_only_chained(blocks), (do, q, k, v, lse, delta), iters
                )
                results[(stage, bq, bk)] = secs
                print(json.dumps({
                    "mode": f"bwd-{stage}", "seq": seq,
                    "block_q": bq, "block_k": bk,
                    "tflops": round(flops_bwd / secs / 1e12, 1),
                    "ms": round(secs * 1e3, 1),
                    "compile_s": round(compile_s, 1),
                }))
            except Exception as e:  # noqa: BLE001 - sweep survives rejects
                print(json.dumps({
                    "mode": f"bwd-{stage}", "seq": seq,
                    "block_q": bq, "block_k": bk,
                    "error": f"{type(e).__name__}: {str(e)[:160]}",
                }))
    for stage in ("dkv", "dq"):
        timed = {k_: v_ for k_, v_ in results.items() if k_[0] == stage}
        if timed:
            best = min(timed, key=timed.get)
            print(json.dumps({
                "mode": f"bwd-{stage}-best", "block_q": best[1],
                "block_k": best[2], "ms": round(timed[best] * 1e3, 1),
            }))


if __name__ == "__main__":
    main()
