#!/usr/bin/env bash
# Round-3 hardware session: run the pending measurements serially, one TPU
# client at a time (docs/hardware_log.md "Tunnel pathology"), each with its
# own budget.  Run AFTER a health probe succeeds:
#
#   timeout 120 python -c "import jax; print(jax.devices()[0].device_kind)"
#   bash tools/hw_session.sh           # logs to /tmp/hw_r3_*.log
#
# Steps (VERDICT r2 items #1 done-criterion at 262k, #5, #6 + decode):
#   1. validate --sweep          parity + fwd/fwdbwd re-baseline   (~5 min)
#   2. hops @262k ring=4         900 s+ compile budget             (~15 min)
#   3. validate --bwd-sweep      per-pass backward block sweep     (~20 min)
#   4. decode 2^20 pallas/dense  ms/token + KV GB/s                (~10 min)
#   5. GQA 32/4 + d128 fwd       BASELINE config-4 shapes          (~15 min)
# Full bench.py is NOT here: the driver runs it at round end.
set -u
cd "$(dirname "$0")/.."

run() {  # run <tag> <budget_s> <cmd...>
  local tag=$1 budget=$2; shift 2
  echo "=== $tag (budget ${budget}s) ==="
  timeout "$budget" "$@" > "/tmp/hw_r3_${tag}.log" 2>&1
  local rc=$?
  tail -5 "/tmp/hw_r3_${tag}.log"
  echo "=== $tag rc=$rc ==="
  if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    # a killed relay compile wedges the far-side grant (hardware_log.md
    # "Tunnel pathology"); every later step would hang through its full
    # budget against a dead tunnel — stop the session instead
    echo "ABORT: $tag was killed at its budget; tunnel grant is likely" \
         "wedged — probe health before running anything else" >&2
    exit 124
  fi
}

run validate 900  python tools/tpu_kernel_validate.py --sweep --seq 262144
run hops262k 1500 python bench.py --worker pallas 262144 hops '{"ring": 4}'
run bwdsweep 1800 python tools/tpu_kernel_validate.py --bwd-sweep --seq 262144
run decode_pallas 700 python bench.py --worker pallas 1048576 decode '{}'
run decode_dense 700 python bench.py --worker dense 1048576 decode '{}'
run gqa32 900 python bench.py --worker pallas 131072 fwd '{"heads": 32, "kv_heads": 4}'
run d128 900 python bench.py --worker pallas 131072 fwd '{"dim_head": 128}'
echo "session done; logs: /tmp/hw_r3_*.log"
