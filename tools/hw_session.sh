#!/usr/bin/env bash
# Round-4 hardware session: run the pending measurements serially, one TPU
# client at a time (docs/hardware_log.md "Tunnel pathology").
#
# Wedge-aware AND resumable:
#   - per-step logs land in docs/hwlogs/ (in-repo, survive the session)
#   - completed steps are recorded in docs/hwlogs/done.txt and skipped on
#     re-run, so a mid-session wedge doesn't void finished work
#   - tunnel health is probed (120 s) before every step; a failed probe
#     aborts the session instead of burning every remaining budget
#   - a step killed at its budget aborts the session: a killed relay
#     compile wedges the far-side grant for hours (hardware_log.md)
#
# Usage:
#   pkill -f tpu_health_loop; sleep 1; pgrep -f tpu-health-probe-inner && exit
#   bash tools/hw_session.sh          # runs all pending steps
#   bash tools/hw_session.sh hops262k # run just one step (ignores done.txt)
set -u
cd "$(dirname "$0")/.."
LOGDIR=docs/hwlogs
DONE=$LOGDIR/done.txt
mkdir -p "$LOGDIR"
touch "$DONE"
ONLY=${1:-}

probe() {
  timeout -k 30 120 python -c "import jax; print(jax.devices()[0].device_kind)  # tpu-health-probe-inner" >/dev/null 2>&1
}

run() {  # run <tag> <budget_s> <cmd...>
  local tag=$1 budget=$2; shift 2
  if [ -n "$ONLY" ] && [ "$tag" != "$ONLY" ]; then return 0; fi
  if [ -z "$ONLY" ] && grep -qx "$tag" "$DONE"; then
    echo "=== $tag already done, skipping ==="
    return 0
  fi
  if ! probe; then
    echo "ABORT before $tag: health probe hung — tunnel is wedged" >&2
    exit 125
  fi
  echo "=== $tag (budget ${budget}s) ==="
  timeout -k 30 "$budget" "$@" > "$LOGDIR/${tag}.log" 2>&1
  local rc=$?
  tail -5 "$LOGDIR/${tag}.log"
  echo "=== $tag rc=$rc ==="
  if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    # a killed relay compile wedges the far-side grant (hardware_log.md
    # "Tunnel pathology"); every later step would hang through its full
    # budget against a dead tunnel — stop the session instead
    echo "ABORT: $tag was killed at its budget; tunnel grant is likely" \
         "wedged — probe health before running anything else" >&2
    exit 124
  fi
  if [ "$rc" -eq 0 ]; then
    echo "$tag" >> "$DONE"
    # aggregate every JSON measurement line under its step tag so the
    # whole session reads as one results file
    grep '^{' "$LOGDIR/${tag}.log" | while IFS= read -r line; do
      printf '{"step": "%s", "date": "%s", "result": %s}\n' \
        "$tag" "$(date -u +%F)" "$line"
    done >> "$LOGDIR/results.jsonl"
  fi
}

# --- round-4 pending measurements (VERDICT r3 next #1-#6), ordered so a
# SHORT healthy window still cashes the never-measured kernels (cheap
# compiles) before the expensive multi-program compiles -----------------
# 1. re-baseline: parity + fwd/fwdbwd at the north star
run validate 1200 python tools/tpu_kernel_validate.py --sweep --seq 262144
# 2. decode kernels' FIRST real Mosaic runs: the bf16 decode kernel, the
#    int8-cache kernel (pre-registered prediction ~0.56 ms/token,
#    docs/hardware_log.md), and the dense comparison point
run decode_pallas 700 python bench.py --worker pallas 1048576 decode '{}'
run decode_q8     700 python bench.py --worker pallas_q8 1048576 decode '{}'
run decode_dense  700 python bench.py --worker dense  1048576 decode '{}'
# 3. hop-sequence at 262k — needs the 900s+ compile budget (4 kernel
#    programs in one jit); r2 done-criterion at the north-star length
run hops262k 1800 python bench.py --worker pallas 262144 hops '{"ring": 4}'
# 3b. decode block_k sweep around the 8192 default
run decode_bk16k  500 python bench.py --worker pallas 1048576 decode '{"block_k": 16384}'
run decode_bk32k  500 python bench.py --worker pallas 1048576 decode '{"block_k": 32768}'
run decode_bk4k   500 python bench.py --worker pallas 1048576 decode '{"block_k": 4096}'
# 4. backward block sweep -> pin block_*_dkv / block_*_dq defaults
run bwdsweep 1800 python tools/tpu_kernel_validate.py --bwd-sweep --seq 262144
# 5. train headline, both remat variants (save_attn expected >30k tok/s)
run train_save 1200 python bench.py --worker pallas 262144 train '{"remat_policy": "save_attn"}'
run train_full 1200 python bench.py --worker pallas 262144 train '{}'
# 5a. realistic vocabulary: 262k tokens x 50k vocab trains on ONE chip
#     only because the chunked CE never materializes the ~53 GB logits
#     (models/transformer.py loss_chunk_size)
run train_vocab50k 1500 python bench.py --worker pallas 262144 train '{"remat_policy": "save_attn", "vocab": 50257, "loss_chunk_size": 8192}'
# 5b. log2-space scoring A/B (RING_ATTN_EXP2=1, docs/hardware_log.md
#     round-5 roofline note): candidate VPU win, zero if exp and exp2
#     dispatch at the same rate.  Same shapes as the standing fwd/fwdbwd
#     numbers so the delta reads directly.
run fwd_exp2    900 env RING_ATTN_EXP2=1 python bench.py --worker pallas 262144 fwd '{}'
run fwdbwd_exp2 1200 env RING_ATTN_EXP2=1 python bench.py --worker pallas 262144 fwdbwd '{}'
# 6. BASELINE config-4 shapes: GQA 32/4 and d128 (131072 = known-good,
#    262144 = the full shape via the head-split launch)
run gqa32      900 python bench.py --worker pallas 131072 fwd '{"heads": 32, "kv_heads": 4}'
# full config-4 shape: the single-program compile 500s at h=32 x 262k,
# so split the launch over the 4 kv-head groups (ops/pallas_flash.py
# head_chunks); also grab the fwdbwd number
run gqa32_262k 1500 python bench.py --worker pallas 262144 fwd '{"heads": 32, "kv_heads": 4, "head_chunks": 4}'
run gqa32_262k_bwd 1800 python bench.py --worker pallas 262144 fwdbwd '{"heads": 32, "kv_heads": 4, "head_chunks": 4}'
run d128       900 python bench.py --worker pallas 131072 fwd '{"dim_head": 128}'
run d128_262k  1500 python bench.py --worker pallas 262144 fwd '{"dim_head": 128}'
# 7. first real XProf capture: MXU/VPU/DMA split for the next MFU push
run xprof 900 python tools/xprof_capture.py
echo "session done; logs in $LOGDIR/ (done steps: $(tr '\n' ' ' < "$DONE"))"
