#!/usr/bin/env bash
# Watch for a healthy TPU tunnel window and cash it in IMMEDIATELY.
#
# Wraps tools/tpu_health_loop.sh's probe cadence, but instead of only
# logging, the FIRST healthy probe launches tools/hw_session.sh (the
# queued round-5 measurements) right away — windows have opened and
# closed between operator checks before, and the queue is worth hours.
#
# One-client discipline: the watcher stops probing the moment it decides
# to launch (hw_session does its own per-step probes), and only one
# watcher may run (lockfile).  Everything logs to /tmp/tpu_health.log
# plus docs/hwlogs/ via hw_session itself.
#
# Usage:  nohup bash tools/tpu_window_watch.sh >/dev/null 2>&1 &
set -u
cd "$(dirname "$0")/.."
INTERVAL=${1:-600}
LOCK=/tmp/tpu_window_watch.lock
# a takeover candidate must be at least this old (seconds): a lock younger
# than this belongs to a watcher that is still starting up, never stale
MIN_LOCK_AGE=${TPU_WATCH_LOCK_MIN_AGE:-60}

# PID-stamped lock with staleness takeover: a SIGKILLed watcher (EXIT trap
# never runs) must not permanently block future watchers — an unwatched
# window opening unnoticed is the exact failure this tool prevents.
#
# Acquisition is ATOMIC: the pid is written into a temp dir which is
# rename(2)d into place, so a held lock always contains its pid — there is
# no window where a concurrent starter reads an empty pid, declares the
# lock stale, and proceeds alongside the holder (the round-5 advisor
# race).  rename onto an existing non-empty directory fails, so exactly
# one of N concurrent acquirers wins.
acquire_lock() {
  local tmp
  tmp=$(mktemp -d "${LOCK}.acquire.XXXXXX") || return 1
  echo $$ > "$tmp/pid"
  if mv -T "$tmp" "$LOCK" 2>/dev/null; then
    return 0
  fi
  rm -rf "$tmp"
  return 1
}

if ! acquire_lock; then
  oldpid=$(cat "$LOCK/pid" 2>/dev/null)
  lock_mtime=$(stat -c %Y "$LOCK" 2>/dev/null || echo 0)
  lock_age=$(( $(date +%s) - lock_mtime ))
  if [ -n "$oldpid" ] && kill -0 "$oldpid" 2>/dev/null; then
    echo "another window watcher is running (pid $oldpid)" >&2
    echo "$(date -u +%H:%M:%S) watcher refused: pid $oldpid alive" >> /tmp/tpu_health.log
    exit 1
  fi
  # stale ONLY when all three hold: the pid file exists, its pid is dead,
  # and the lock is old enough that no healthy starter could still own it
  if [ -z "$oldpid" ] || [ "$lock_age" -lt "$MIN_LOCK_AGE" ]; then
    echo "watcher lock $LOCK in indeterminate state (pid=${oldpid:-none}, age=${lock_age}s); refusing" >&2
    echo "$(date -u +%H:%M:%S) watcher refused: lock indeterminate (pid=${oldpid:-none}, age=${lock_age}s)" >> /tmp/tpu_health.log
    exit 1
  fi
  echo "$(date -u +%H:%M:%S) stale watcher lock (pid $oldpid dead, age ${lock_age}s), taking over" >> /tmp/tpu_health.log
  # atomic takeover: rename the stale lock aside first — of N concurrent
  # takeover attempts exactly one mv wins; the losers must NOT rm -rf (a
  # plain rm here could delete the winner's freshly acquired lock)
  if ! mv -T "$LOCK" "$LOCK.stale.$$" 2>/dev/null; then
    echo "$(date -u +%H:%M:%S) watcher lost takeover race; exiting" >> /tmp/tpu_health.log
    exit 1
  fi
  rm -rf "$LOCK.stale.$$"
  if ! acquire_lock; then
    echo "$(date -u +%H:%M:%S) watcher lost takeover race; exiting" >> /tmp/tpu_health.log
    exit 1
  fi
fi
trap 'rm -rf "$LOCK" 2>/dev/null' EXIT

while true; do
  touch /tmp/tpu_probe.lock
  ts=$(date -u +%H:%M:%S)
  out=$(timeout -k 30 120 python -c "import jax; print(jax.devices()[0].device_kind)  # tpu-health-probe-inner" 2>/dev/null)
  rc=$?
  rm -f /tmp/tpu_probe.lock
  if [ "$rc" -eq 0 ]; then
    echo "$ts HEALTHY ${out##*$'\n'} -> launching hw_session" >> /tmp/tpu_health.log
    bash tools/hw_session.sh >> /tmp/tpu_health.log 2>&1
    src=$?
    echo "$(date -u +%H:%M:%S) hw_session exited rc=$src" >> /tmp/tpu_health.log
    # session done (or aborted on a re-wedge): resume watching so a later
    # window can pick up the remaining steps (done.txt resume)
    if [ "$src" -eq 0 ]; then
      echo "$(date -u +%H:%M:%S) all steps done; watcher exiting" >> /tmp/tpu_health.log
      exit 0
    fi
  else
    echo "$ts WEDGED rc=$rc" >> /tmp/tpu_health.log
  fi
  sleep "$INTERVAL"
done
