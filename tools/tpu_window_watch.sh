#!/usr/bin/env bash
# Watch for a healthy TPU tunnel window and cash it in IMMEDIATELY.
#
# Wraps tools/tpu_health_loop.sh's probe cadence, but instead of only
# logging, the FIRST healthy probe launches tools/hw_session.sh (the
# queued round-5 measurements) right away — windows have opened and
# closed between operator checks before, and the queue is worth hours.
#
# One-client discipline: the watcher stops probing the moment it decides
# to launch (hw_session does its own per-step probes), and only one
# watcher may run (lockfile).  Everything logs to /tmp/tpu_health.log
# plus docs/hwlogs/ via hw_session itself.
#
# Usage:  nohup bash tools/tpu_window_watch.sh >/dev/null 2>&1 &
set -u
cd "$(dirname "$0")/.."
INTERVAL=${1:-600}
LOCK=/tmp/tpu_window_watch.lock
# PID-stamped lock with staleness takeover: a SIGKILLed watcher (EXIT trap
# never runs) must not permanently block future watchers — an unwatched
# window opening unnoticed is the exact failure this tool prevents.
if ! mkdir "$LOCK" 2>/dev/null; then
  oldpid=$(cat "$LOCK/pid" 2>/dev/null)
  if [ -n "$oldpid" ] && kill -0 "$oldpid" 2>/dev/null; then
    echo "another window watcher is running (pid $oldpid)" >&2
    echo "$(date -u +%H:%M:%S) watcher refused: pid $oldpid alive" >> /tmp/tpu_health.log
    exit 1
  fi
  echo "$(date -u +%H:%M:%S) stale watcher lock (pid ${oldpid:-unknown} dead), taking over" >> /tmp/tpu_health.log
  rm -rf "$LOCK"
  mkdir "$LOCK" || exit 1
fi
echo $$ > "$LOCK/pid"
trap 'rm -rf "$LOCK" 2>/dev/null' EXIT

while true; do
  touch /tmp/tpu_probe.lock
  ts=$(date -u +%H:%M:%S)
  out=$(timeout -k 30 120 python -c "import jax; print(jax.devices()[0].device_kind)  # tpu-health-probe-inner" 2>/dev/null)
  rc=$?
  rm -f /tmp/tpu_probe.lock
  if [ "$rc" -eq 0 ]; then
    echo "$ts HEALTHY ${out##*$'\n'} -> launching hw_session" >> /tmp/tpu_health.log
    bash tools/hw_session.sh >> /tmp/tpu_health.log 2>&1
    src=$?
    echo "$(date -u +%H:%M:%S) hw_session exited rc=$src" >> /tmp/tpu_health.log
    # session done (or aborted on a re-wedge): resume watching so a later
    # window can pick up the remaining steps (done.txt resume)
    if [ "$src" -eq 0 ]; then
      echo "$(date -u +%H:%M:%S) all steps done; watcher exiting" >> /tmp/tpu_health.log
      exit 0
    fi
  else
    echo "$ts WEDGED rc=$rc" >> /tmp/tpu_health.log
  fi
  sleep "$INTERVAL"
done
