#!/usr/bin/env python
"""Perf-observatory regression gate CLI (docs/observability.md §Observatory).

Ingests the repo's benchmark history (``BENCH_r*.json`` + ``docs/hwlogs/
results.jsonl``) plus the committed CPU-signal baseline
(``docs/perf_baseline.json``), collects the current build's CPU
signals — collective fingerprint, analytic hop/byte reference table,
compiled cost/memory of the reference train step — and fails (exit 1)
with one line per regressed series.  Wedge-honest: rounds whose TPU
probe never ran contribute notes, not hardware points, and wedge
frequency is itself reported.

Usage::

  python tools/perf_gate.py --check              # the gate (default)
  python tools/perf_gate.py --check --json       # machine-readable report
  python tools/perf_gate.py --history-only       # no compiles: ingest+trend
  python tools/perf_gate.py --update-baseline    # re-record docs/perf_baseline.json
  python tools/perf_gate.py --check --strategies ring --skip-compiled
                                                 # cheap subset (CI smoke)

Runs on CPU anywhere: the fingerprint needs 8 simulated devices, which
this script forces before the first jax import (like bench.py's
fingerprint worker).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# must precede the first jax import (the fingerprint compiles per-strategy
# entries over an 8-device simulated mesh)
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="benchmark-history + CPU-signal perf regression gate"
    )
    ap.add_argument("--check", action="store_true",
                    help="run the gate (the default action)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as one JSON object")
    ap.add_argument("--history-only", action="store_true",
                    help="ingest + trend-check the history without "
                         "collecting live signals (no compiles, no jax "
                         "device work)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="record the current CPU signals as "
                         "docs/perf_baseline.json (conscious act: exact-"
                         "count families tolerate nothing until re-recorded)")
    ap.add_argument("--repo", default=REPO,
                    help="repo root holding BENCH_r*.json (default: this "
                         "checkout)")
    ap.add_argument("--baseline", default=None,
                    help="baseline path (default: REPO/docs/perf_baseline.json)")
    ap.add_argument("--strategies", nargs="*", default=None,
                    help="fingerprint strategy subset (default: the full "
                         "bench set; pass none to skip the fingerprint)")
    ap.add_argument("--skip-compiled", action="store_true",
                    help="skip the reference-step compile (fingerprint + "
                         "arithmetic comms table still collected)")
    ap.add_argument("--compile-cache-dir", default=None,
                    help="persistent XLA compile cache (reuse the test "
                         "suite's tests/.jax_cache to make the gate cheap)")
    ap.add_argument("--note", default="",
                    help="free-form note stored in the baseline on "
                         "--update-baseline")
    args = ap.parse_args(argv)

    baseline_path = args.baseline or os.path.join(
        args.repo, "docs", "perf_baseline.json"
    )
    if args.update_baseline and (
        args.skip_compiled or args.strategies is not None
    ):
        # a baseline recorded from a subset run would silently DROP the
        # missing families: check_baseline treats absent baseline
        # families as notes, so future full --check runs would green
        # with the fingerprint/compiled gates effectively deleted
        ap.error("--update-baseline requires the full signal set: drop "
                 "--skip-compiled/--strategies (the cheap subset is for "
                 "--check only)")

    from ring_attention_tpu.analysis import perfgate

    if args.history_only:
        report = perfgate.run_gate(None, root=args.repo,
                                   baseline_path=baseline_path)
        return _emit(report, args)

    if args.compile_cache_dir:
        from ring_attention_tpu.utils import enable_compile_cache

        enable_compile_cache(args.compile_cache_dir)

    strategies = args.strategies
    if strategies is None:
        current = perfgate.collect_current(compiled=not args.skip_compiled)
    else:
        current = perfgate.collect_current(
            strategies=tuple(strategies) or None,
            compiled=not args.skip_compiled,
        )

    if args.update_baseline:
        payload = perfgate.write_baseline(
            current, baseline_path, note=args.note
        )
        print(f"baseline recorded: {baseline_path} "
              f"(jax {payload.get('jax')}, "
              f"{len(payload['signals'])} signal families)")
        return 0

    report = perfgate.run_gate(current, root=args.repo,
                               baseline_path=baseline_path)
    return _emit(report, args)


def _emit(report, args) -> int:
    if args.json:
        print(json.dumps(report.to_json(), sort_keys=True))
    else:
        for f in report.findings:
            print(str(f))
        for note in report.notes:
            print(f"  note: {note}")
        verdict = "FAIL" if report.findings else "ok"
        print(f"perf-gate: {verdict} — {len(report.findings)} finding(s), "
              f"{len(report.checked)} series checked, "
              f"{len(report.notes)} note(s)")
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())
