#!/usr/bin/env bash
# Background TPU tunnel health probe loop.  Appends one line per probe to
# /tmp/tpu_health.log.  A probe IS a TPU client, so before starting any
# real TPU work: kill this loop (pkill -f tpu_health_loop), then confirm
# no probe is in flight (pgrep -f tpu-health-probe-inner), THEN start.
#
# The probe itself holds a lockfile while running so an operator can also
# check /tmp/tpu_probe.lock.
set -u
INTERVAL=${1:-600}
while true; do
  touch /tmp/tpu_probe.lock
  ts=$(date -u +%H:%M:%S)
  # the trailing comment tags the probe's argv for pgrep; no pipe here so
  # $? is the probe's own exit status (124 = timeout = wedged)
  out=$(timeout -k 30 120 python -c "import jax; print(jax.devices()[0].device_kind)  # tpu-health-probe-inner" 2>/dev/null)
  rc=$?
  rm -f /tmp/tpu_probe.lock
  if [ "$rc" -eq 0 ]; then
    echo "$ts HEALTHY ${out##*$'\n'}" >> /tmp/tpu_health.log
  else
    echo "$ts WEDGED rc=$rc" >> /tmp/tpu_health.log
  fi
  sleep "$INTERVAL"
done
