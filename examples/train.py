"""Minimal end-to-end training example: striped ring attention on a mesh.

Runs anywhere: on a TPU slice this uses every chip (data x ring mesh); on a
CPU dev box pass --fake-devices 8 to simulate the mesh.  Trains a small
char-level model on synthetic data and prints loss + throughput.

  python examples/train.py --fake-devices 8 --steps 20
"""

from __future__ import annotations

import argparse
import contextlib
import os
import sys
import warnings

try:  # prefer the installed package (pip install -e .)
    import ring_attention_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout, any cwd
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fake-devices", type=int, default=0,
                    help="simulate N CPU devices (for dev boxes)")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--ring-size", type=int, default=None)
    ap.add_argument("--multihost", action="store_true",
                    help="join a multi-process cluster via "
                         "jax.distributed (coordinator discovered from "
                         "the environment on TPU pods; set "
                         "JAX_COORDINATOR_ADDRESS etc. elsewhere) — "
                         "meshes then span every host and the elastic "
                         "checkpoint writes one shard group per process "
                         "(docs/resilience.md §multi-host)")
    ap.add_argument("--dcn-data-size", type=int, default=None,
                    help="hierarchical mesh: outermost pure-data-"
                         "parallel axis over the slow DCN links between "
                         "slices/processes; rings and ulysses groups "
                         "then live strictly inside one group (defaults "
                         "to the process count under --multihost; "
                         "contract-proven by check_contracts.py)")
    ap.add_argument("--ulysses-size", type=int, default=None,
                    help="factor the sequence axis as ulysses x ring and "
                         "train with sequence_parallel='hybrid': all-to-all "
                         "head parallelism over the inner (fastest) axis, "
                         "KV-rotation ring over the outer one — "
                         "ulysses-size x fewer ring hops at equal world "
                         "size (docs/hybrid_parallelism.md)")
    ap.add_argument("--bf16", action="store_true")
    ap.add_argument("--accum-steps", type=int, default=1,
                    help="gradient-accumulation microbatches per update")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize blocks (activation memory savings)")
    ap.add_argument("--remat-policy", default=None,
                    help="what each rematted block may KEEP instead of "
                         "recomputing (implies --remat): nothing_saveable, "
                         "everything_saveable, checkpoint_dots, "
                         "checkpoint_dots_no_batch, save_attn, "
                         "save_ffn_inputs, save_attn_and_ffn_inputs, "
                         "offload_attn — the policy table is "
                         "docs/memory.md; validation lists the registry")
    ap.add_argument("--ff-chunk-size", type=int, default=None,
                    help="blockwise feedforward: run each FFN as a "
                         "rematted scan over sequence chunks of this size "
                         "so the (seq, mult*dim) intermediate never exists "
                         "at full extent (Ring Attention's blockwise FFN; "
                         "docs/memory.md)")
    ap.add_argument("--loss-chunk-size", type=int, default=None,
                    help="chunked cross-entropy: at most (batch, chunk, "
                         "vocab) logits materialize — required at real LM "
                         "vocabularies with long sequences")
    ap.add_argument("--offload-opt-state", action="store_true",
                    help="host offload of the optimizer state (Adam "
                         "moments leave HBM between steps); a no-op on "
                         "backends without an addressable host memory "
                         "space, e.g. jax 0.4.x CPU (docs/memory.md)")
    ap.add_argument("--shard-opt-state", action="store_true",
                    help="ZeRO-1: shard the optimizer state (Adam "
                         "moments) over the data axes — both tiers on a "
                         "hierarchical --dcn-data-size mesh — so per-"
                         "chip moment memory divides by the data-"
                         "parallel world; composes with "
                         "--offload-opt-state (docs/resilience.md)")
    ap.add_argument("--watchdog-deadline", type=float, default=None,
                    help="heartbeat watchdog: abort (exit 114, flight "
                         "incident dumped) when a step boundary takes "
                         "longer than this many seconds — a wedged "
                         "collective (dead peer, hung device) becomes a "
                         "bounded restart instead of an eternal hang")
    ap.add_argument("--use-pallas", action="store_true",
                    help="Mosaic kernels (TPU; interpreter elsewhere)")
    ap.add_argument("--impl", choices=["auto", "fused", "pallas", "xla"],
                    default=None,
                    help="kernel path with graceful degradation (overrides "
                         "--use-pallas): fused = single-launch fused-ring "
                         "kernel with in-kernel remote KV DMA "
                         "(ops/pallas_ring.py); auto prefers fused, then "
                         "pallas, then xla, recording each fallback")
    ap.add_argument("--bidirectional", action="store_true",
                    help="circulate KV halves both ring directions (duplex ICI)")
    ap.add_argument("--counter-rotate", action="store_true",
                    help="TokenRing full-duplex schedule: the Q shard + its "
                         "online-softmax accumulators rotate one ring "
                         "direction while KV rotates the other; the backward "
                         "keeps KV/dKV resident (docs/ring_overlap.md)")
    ap.add_argument("--hop-compression", choices=["int8"], default=None,
                    help="ship forward KV ring hops int8-quantized (per-"
                         "token absmax values + bitcast f32 scales in one "
                         "payload); accumulators and grads stay exact-dtype")
    ap.add_argument("--compute-dtype", choices=["int8"], default=None,
                    help="run the forward's QK^T/PV matmuls on int8 "
                         "operands (pallas kernels; ~2x MXU rate on "
                         "v5e/v5p); backward stays bf16 from exact "
                         "residuals; composes with --hop-compression int8 "
                         "into the dequant-free ring (docs/precision.md)")
    ap.add_argument("--pack", action="store_true",
                    help="packed-sequence training: concatenate variable-"
                         "length documents per row with segment ids — "
                         "attention stays within each document and no "
                         "position is padding (docs/packing.md)")
    ap.add_argument("--docs-per-seq", type=int, default=4,
                    help="documents packed into each row with --pack")
    ap.add_argument("--compile-cache-dir", default=None,
                    help="persistent XLA compilation cache directory: "
                         "repeated runs skip recompiles (utils/benchtime.py)")
    ap.add_argument("--ckpt-dir", default=None,
                    help="checkpoint directory: saves every --ckpt-every "
                         "steps and resumes from the last good checkpoint "
                         "(kill the run mid-way and rerun the same command)")
    ap.add_argument("--ckpt-every", type=int, default=5)
    ap.add_argument("--ckpt-keep", type=int, default=3,
                    help="keep-last-N checkpoint retention")
    ap.add_argument("--elastic-ckpt", action="store_true",
                    help="elastic runtime (docs/resilience.md): sharded "
                         "ASYNC checkpoints (one file per shard group, "
                         "atomic manifest commit), SIGTERM/SIGINT drain "
                         "(finish the step, save, dump a flight "
                         "incident, exit cleanly), and re-mesh resume — "
                         "restart this command at a DIFFERENT device "
                         "count and it reshards the checkpoint onto the "
                         "new mesh (requires --ckpt-dir)")
    ap.add_argument("--skip-nonfinite", action="store_true",
                    help="guarded train step: skip (don't apply) optimizer "
                         "updates whose loss/grads are non-finite")
    ap.add_argument("--clip-grad-norm", type=float, default=None,
                    help="clip gradients to this global L2 norm")
    ap.add_argument("--metrics-dir", default=None,
                    help="telemetry: write one schema-versioned JSONL row "
                         "per --log-every window (loss, grad_norm, "
                         "tokens_per_sec, step p50/p95, mfu, ring hop/byte "
                         "accounting, skipped-step counts) — render with "
                         "tools/trace_report.py (docs/observability.md)")
    ap.add_argument("--log-every", type=int, default=5,
                    help="steps between metric rows / console lines")
    ap.add_argument("--flight-window", type=int, default=64,
                    help="numerics flight recorder: keep the last N metric "
                         "rows in memory and dump them as JSON on a "
                         "nonfinite step, kernel degradation, or crash — "
                         "a NaN arrives with its preceding trajectory, "
                         "not a bare counter (needs --metrics-dir; 0 "
                         "disables; docs/observability.md §Observatory)")
    ap.add_argument("--flight-dir", default=None,
                    help="flight-dump directory (default: "
                         "METRICS_DIR/flight)")
    ap.add_argument("--trace-dir", default=None,
                    help="span tracing: write one per-process span JSONL "
                         "file (step phases, checkpoint save/commit, "
                         "barrier waits, watchdog beats) — merge across "
                         "processes with tools/cluster_timeline.py "
                         "(docs/observability.md §6)")
    args = ap.parse_args()
    if args.log_every < 1:
        ap.error("--log-every must be >= 1")
    if args.elastic_ckpt and not args.ckpt_dir:
        ap.error("--elastic-ckpt needs --ckpt-dir")

    if args.fake_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.fake_devices}"
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    if args.multihost:
        # join the cluster before ANY device query: jax.devices() must be
        # the global list when the meshes are built (retry ladder + one-
        # line coordinator diagnostics live in parallel/mesh.py)
        from ring_attention_tpu.parallel import initialize_multihost

        initialize_multihost()
        print(f"multihost: process {jax.process_index()}/"
              f"{jax.process_count()}, "
              f"{len(jax.local_devices())} local devices")
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ring_attention_tpu import RingTransformer, create_mesh
    from ring_attention_tpu.parallel import shard_batch
    from ring_attention_tpu.utils import (
        CheckpointManager,
        MetricsLogger,
        StepTimer,
        achieved_mfu,
        device_peak_tflops,
        enable_compile_cache,
        init_step_stats,
        init_train_metrics,
        make_train_step,
        ring_comms_accounting,
        transformer_step_flops,
    )
    from ring_attention_tpu.utils.train import StepStats

    if args.compile_cache_dir:
        # before any jit: every compile from here on lands in the cache
        enable_compile_cache(args.compile_cache_dir)
    # CPU dev boxes can't honor donation; the hint is still correct on TPU
    warnings.filterwarnings(
        "ignore", message="Some donated buffers were not usable"
    )

    n_dev = len(jax.devices())
    n_proc = jax.process_count()

    # span tracing (docs/observability.md §6): each process appends to
    # its own spans_pNNNNN.jsonl; tools/cluster_timeline.py merges them
    # into one clock-corrected cluster timeline
    if args.trace_dir:
        from ring_attention_tpu.utils import tracing

        tracing.configure(args.trace_dir, process=jax.process_index())
    if args.dcn_data_size is None and n_proc > 1:
        # multihost default: one dcn group per process, rings inside
        args.dcn_data_size = n_proc

    # elastic resume plans the mesh BEFORE building it: when the job
    # comes back at a different device count and no explicit factoring
    # was requested, the checkpoint manifest's mesh descriptor + the new
    # world pick the closest factoring (ring absorbs the change, the
    # dcn tier re-plans to the current process count)
    elastic_mgr = None
    guard = None
    if args.elastic_ckpt:
        from ring_attention_tpu.elastic import (
            ElasticCheckpointManager,
            PreemptionGuard,
        )
        from ring_attention_tpu.parallel import remesh_plan

        elastic_mgr = ElasticCheckpointManager(
            args.ckpt_dir, keep=args.ckpt_keep
        )
        manifest = elastic_mgr.latest_manifest()
        if (manifest is not None and args.ring_size is None
                and args.ulysses_size is None):
            plan, diags = remesh_plan(
                manifest.get("mesh"), n_dev,
                dcn_data_size=args.dcn_data_size or n_proc,
            )
            for line in diags:
                print(f"  {line}")
            args.ring_size = plan.get("ring_size")
            args.ulysses_size = plan.get("ulysses_size")
            args.dcn_data_size = plan.get("dcn_data_size")
        # constructed here, INSTALLED just before the train loop: during
        # the multi-minute init/compile/restore window a latched signal
        # would get no drain check, so the default Ctrl-C behavior is
        # the right response there.  The handler prints on first signal
        # so a drain never looks like a hang.
        guard = PreemptionGuard(on_preempt=lambda sig: print(
            f"\n{sig} received: finishing the in-flight step, then "
            f"draining (save + incident dump); signal again to abort"
        ))

    ulysses = args.ulysses_size or 1
    hybrid = ulysses > 1
    dcn = args.dcn_data_size or 1
    inner_dev = n_dev // dcn  # per-dcn-group world
    if hybrid:
        ring = args.ring_size or inner_dev // ulysses
        mesh = create_mesh(ring_size=ring, ulysses_size=ulysses,
                           dcn_data_size=args.dcn_data_size)
        seq_shards = ulysses * ring
    else:
        ring = args.ring_size or inner_dev
        mesh = create_mesh(
            ring_size=ring, dcn_data_size=args.dcn_data_size
        ) if n_dev > 1 else None
        seq_shards = ring
    print(f"devices={n_dev} mesh={dict(mesh.shape) if mesh else None}")

    model = RingTransformer(
        num_tokens=256,
        dim=args.dim,
        depth=args.depth,
        heads=4,
        dim_head=args.dim // 4,
        causal=True,
        striped=True,
        bucket_size=max(args.seq_len // max(seq_shards, 1), 1),
        mesh=mesh,
        use_ring=mesh is not None,
        sequence_parallel="hybrid" if hybrid else "ring",
        use_pallas=args.use_pallas,
        impl=args.impl,
        ring_bidirectional=args.bidirectional,
        ring_counter_rotate=args.counter_rotate,
        ring_hop_compression=args.hop_compression,
        compute_dtype=args.compute_dtype,
        remat=args.remat or args.remat_policy is not None,
        remat_policy=args.remat_policy,
        ff_chunk_size=args.ff_chunk_size,
        loss_chunk_size=args.loss_chunk_size,
        dtype=jnp.bfloat16 if args.bf16 else None,
    )

    rng = np.random.default_rng(0)
    segments = None
    if args.pack:
        # packed batches: each row concatenates --docs-per-seq variable-
        # length "copy task" documents; segment ids keep attention (and
        # the loss) within each document — zero positions are padding
        tokens = np.empty((args.batch, args.seq_len), np.int32)
        segments = np.empty((args.batch, args.seq_len), np.int32)
        for row in range(args.batch):
            cuts = np.sort(rng.choice(
                np.arange(2, args.seq_len - 1, 2),
                size=args.docs_per_seq - 1, replace=False,
            ))
            bounds = [0, *cuts.tolist(), args.seq_len]
            for doc, (lo, hi) in enumerate(zip(bounds[:-1], bounds[1:])):
                half = (hi - lo) // 2
                piece = rng.integers(0, 256, half + ((hi - lo) % 2))
                tokens[row, lo:hi] = np.concatenate([piece, piece[:half]])
                segments[row, lo:hi] = doc
    else:
        # synthetic "copy task" data: predictable structure so loss falls fast
        base = rng.integers(0, 256, (args.batch, args.seq_len // 2))
        tokens = np.concatenate([base, base], axis=1).astype(np.int32)

    if n_proc > 1:
        # every process passes only ITS rows of the global batch: the
        # batch dimension shards over (dcn_data, data) with one dcn
        # group per process, so the local slab is a contiguous row range
        if args.batch % n_proc:
            ap.error(f"--batch {args.batch} must divide by the "
                     f"{n_proc}-process cluster")
        rows = args.batch // n_proc
        row0 = jax.process_index() * rows
        tokens = tokens[row0:row0 + rows]
        if segments is not None:
            segments = segments[row0:row0 + rows]
    if mesh is not None:
        # host array straight onto the mesh: batch over data, sequence over
        # the ring, one per-shard transfer (multi-host: each process passes
        # its local slice)
        tokens = shard_batch(tokens, mesh)
        if segments is not None:
            segments = shard_batch(segments, mesh)
    else:
        tokens = jnp.asarray(tokens)
        if segments is not None:
            segments = jnp.asarray(segments)
    params = model.init(jax.random.PRNGKey(0), tokens)
    opt = optax.adamw(3e-4)
    opt_state = opt.init(params)
    if args.shard_opt_state:
        if mesh is None:
            ap.error("--shard-opt-state needs a mesh (more than 1 device)")
        # seed the loop sharded; the step's in-graph constraint keeps the
        # updated state sharded (utils/train.py)
        from ring_attention_tpu.parallel import data_partition
        from ring_attention_tpu.utils.train import shard_optimizer_state

        opt_state = shard_optimizer_state(
            opt_state, mesh, axis=data_partition(mesh)
        )
    if args.offload_opt_state:
        # seed the loop host-side; the step keeps it there (utils/train.py)
        from ring_attention_tpu.utils import compat

        opt_state = compat.host_device_put(opt_state, mesh)

    if args.pack:
        def loss_fn(p, t, s):
            return model.apply(p, t, return_loss=True, segment_ids=s)
        batch = (tokens, segments)
    else:
        def loss_fn(p, t):
            return model.apply(p, t, return_loss=True)
        batch = (tokens,)

    guarded = args.skip_nonfinite
    collect = args.metrics_dir is not None
    # jit_donate: (params, opt_state) buffers are donated so XLA updates
    # them in place instead of double-allocating model + Adam state.
    # collect_metrics extends the carry to TrainMetrics (loss, grad_norm,
    # skipped/nonfinite counters) with no extra collectives in the step.
    train_step = make_train_step(
        loss_fn, opt,
        accum_steps=args.accum_steps,
        skip_nonfinite=guarded,
        clip_grad_norm=args.clip_grad_norm,
        jit_donate=True,
        collect_metrics=collect,
        offload_opt_state=args.offload_opt_state,
        offload_mesh=mesh,
        shard_opt_state=args.shard_opt_state,
        shard_mesh=mesh,
    )

    # preemption-safe resume: atomic saves, keep-last-N, corrupt-checkpoint
    # fallback — kill this process at any point and rerun the same command
    # to continue from the last good step (see docs/resilience.md)
    mgr = None
    start = 0
    stats = init_step_stats()
    nonfinite = jnp.asarray(0, jnp.int32)
    if args.ckpt_dir:
        mgr = elastic_mgr or CheckpointManager(
            args.ckpt_dir, keep=args.ckpt_keep
        )
        # stats ride along in the checkpoint so a resumed guarded run
        # keeps its skipped-step telemetry (a growing skip streak is the
        # "this run diverged" signal and must survive preemption).  With
        # metrics on, the nonfinite counter rides too — unguarded runs
        # have skipped == 0, so losing it would silently reset the "run
        # is corrupting itself" alarm across preemption.
        def fresh():
            state = {"params": params, "opt_state": opt_state,
                     "stats": stats}
            if collect:
                state["nonfinite"] = nonfinite
            return state

        if elastic_mgr is not None:
            # elastic resume: resharded-loads the checkpoint onto the
            # CURRENT mesh (whatever factoring it was written at) and
            # revalidates seq_len divisibility with a one-line error
            state, start = mgr.resume_or_init(
                fresh, mesh=mesh, seq_len=args.seq_len
            )
            if mgr.last_resume is not None:
                for line in mgr.last_resume["diagnostics"]:
                    print(f"  {line}")
        else:
            state, start = mgr.resume_or_init(fresh)
        params, opt_state = state["params"], state["opt_state"]
        stats = state["stats"]
        nonfinite = state.get("nonfinite", nonfinite)
        if start:
            print(f"resumed from checkpoint (continuing at step {start})")

    # telemetry (docs/observability.md): the instrumented step carries
    # TrainMetrics; the logger writes one schema-versioned JSONL row per
    # --log-every window, with MFU and ring-hop/byte accounting computed
    # analytically once (they derive from shapes and the mesh factoring)
    metrics = None
    logger = None
    mfu_flops = 0.0
    comms = {}
    peak = device_peak_tflops() * max(n_dev, 1)
    if collect:
        # a resumed run continues its counters in the metrics carry
        metrics = init_train_metrics(skipped=int(stats.skipped),
                                     nonfinite=int(nonfinite))
        logger = MetricsLogger(args.metrics_dir)
        n_params = sum(x.size for x in jax.tree.leaves(params))
        mfu_flops = transformer_step_flops(
            n_params, tokens.size, depth=args.depth, heads=4,
            dim_head=args.dim // 4, seq_len=args.seq_len, causal=True,
            batch=args.batch,
        )
        if mesh is not None:
            pad_seq = args.seq_len + (-args.seq_len) % seq_shards
            comms = ring_comms_accounting(
                ring_size=ring, ulysses_size=ulysses, seq_len=pad_seq,
                heads=4, kv_heads=4, dim_head=args.dim // 4,
                dtype_bytes=2 if args.bf16 else 4, batch=args.batch,
                depth=args.depth, counter_rotate=args.counter_rotate,
                hop_compression=args.hop_compression,
                compute_dtype=args.compute_dtype,
            )
        else:
            comms = {"ring_hops": 0, "ring_hops_per_step": 0, "hop_bytes": 0}
        # compiled peak-memory accounting of the step that actually runs
        # (telemetry.compiled_memory): AOT-compile once, log temp/argument
        # bytes next to the analytic comms numbers, and drive the loop on
        # the same executable — no second compile
        try:
            from ring_attention_tpu.utils.telemetry import compiled_memory

            compiled_exe = train_step.lower(
                params, opt_state, metrics, *batch
            ).compile()
            comms.update(compiled_memory(compiled_exe))
            train_step = compiled_exe
        except Exception:  # noqa: BLE001 — diagnostics never fail the run
            pass

    # numerics flight recorder (docs/observability.md §Observatory): the
    # last --flight-window metric rows ride in memory; a nonfinite step,
    # kernel degradation, exhausted retry ladder, or crash dumps them as
    # JSON next to the metrics — the NaN arrives with its trajectory
    recorder = None
    if collect and args.flight_window > 0:
        from ring_attention_tpu.utils import FlightRecorder

        recorder = FlightRecorder(
            args.flight_dir or os.path.join(args.metrics_dir, "flight"),
            window=args.flight_window,
            context={
                "mesh": dict(mesh.shape) if mesh is not None else None,
                "seq_len": args.seq_len, "batch": args.batch,
                "dim": args.dim, "depth": args.depth,
                "ulysses": ulysses, "ring": ring,
                "counter_rotate": args.counter_rotate,
                "hop_compression": args.hop_compression,
                "compute_dtype": args.compute_dtype,
                "remat_policy": args.remat_policy,
                "ff_chunk_size": args.ff_chunk_size,
                "skip_nonfinite": guarded,
            },
        ).install()

    # heartbeat watchdog (docs/resilience.md): a step boundary further
    # apart than the deadline means a wedged collective — abort with a
    # flight incident so the supervisor restarts from the checkpoint
    dog = None
    if args.watchdog_deadline:
        from ring_attention_tpu.elastic import Watchdog

        dog = Watchdog(args.watchdog_deadline, recorder=recorder).start()

    timer = StepTimer(tokens_per_step=tokens.size * max(n_proc, 1))
    loop_guard = recorder.guard() if recorder is not None else (
        contextlib.nullcontext()
    )
    try:
        if guard is not None:
            guard.install()  # compile/init/restore are behind us
        with loop_guard:
            _train_loop(args, recorder, timer, train_step, params,
                        opt_state, metrics, stats, batch, collect, guarded,
                        mgr, logger, start, mfu_flops, comms, peak, guard,
                        n_proc=n_proc, dog=dog)
    finally:
        if dog is not None:
            dog.stop()
        if elastic_mgr is not None:
            elastic_mgr.close()  # flush any in-flight async save
        if guard is not None:
            guard.uninstall()
        if args.trace_dir:
            from ring_attention_tpu.utils import tracing

            tracing.shutdown()
    if logger is not None:
        logger.close()
        print(f"metrics: {logger.path} (render with tools/trace_report.py)")
    if recorder is not None and recorder.dumps:
        print("flight dumps: " + ", ".join(recorder.dumps))


def _train_loop(args, recorder, timer, train_step, params, opt_state,
                metrics, stats, batch, collect, guarded, mgr, logger,
                start, mfu_flops, comms, peak, guard=None, n_proc=1,
                dog=None):
    from ring_attention_tpu.utils import achieved_mfu, tracing
    from ring_attention_tpu.utils.train import StepStats

    def make_ckpt():
        ckpt = {"params": params, "opt_state": opt_state, "stats": stats}
        if collect:
            ckpt["nonfinite"] = metrics.nonfinite
        return ckpt

    def drain_requested(step: int) -> bool:
        if guard is None:
            return False
        if n_proc > 1:
            # one host's SIGTERM drains the whole pod: the flag OR-reduces
            # across processes at the step boundary — the train step's
            # own compiled program is untouched (elastic/preemption.py)
            return guard.should_stop_cluster(step=step)
        return guard.should_stop()

    tracer = tracing.get_tracer()
    for step in range(start, args.steps):
        # the step-phase span measures host-side dispatch + the loss
        # sync inside timer.step; the compiled program itself is pinned
        # untraced (tests/test_tracing.py HLO pin)
        with tracer.span("train/step", step=step):
            if collect:
                params, opt_state, metrics, loss = train_step(
                    params, opt_state, metrics, *batch
                )
                # checkpointed StepStats stays structure-compatible with
                # uninstrumented runs; it mirrors the metrics counters
                stats = StepStats(step_ok=metrics.step_ok,
                                  skipped=metrics.skipped)
                if recorder is not None:
                    dump = recorder.observe_step(step, metrics)
                    if dump:
                        print(f"flight recorder: nonfinite step {step} "
                              f"-> {dump}")
            elif guarded:
                params, opt_state, stats, loss = train_step(
                    params, opt_state, stats, *batch
                )
            else:
                params, opt_state, loss = train_step(
                    params, opt_state, *batch
                )
            timer.step(loss)
        if dog is not None:
            dog.beat(step)
        if step % args.log_every == 0 or step == args.steps - 1:
            with tracer.span("train/log", step=step):
                skipped = int(stats.skipped) if (guarded or collect) else 0
                print(
                    f"step {step:4d}  loss {float(loss):.4f}  "
                    f"{timer.tokens_per_sec:,.0f} tok/s"
                    + (f"  [skipped {skipped}]" if skipped else "")
                )
                if logger is not None:
                    sps = timer.steps_per_sec
                    logger.log(
                        step,
                        loss=float(loss),
                        grad_norm=float(metrics.grad_norm),
                        step_ok=bool(metrics.step_ok),
                        skipped=int(metrics.skipped),
                        nonfinite=int(metrics.nonfinite),
                        tokens_per_sec=round(timer.tokens_per_sec, 1),
                        steps_per_sec=round(sps, 4),
                        step_ms_p50=round(timer.step_ms_p50, 2),
                        step_ms_p95=round(timer.step_ms_p95, 2),
                        mfu=round(
                            achieved_mfu(mfu_flops, 1.0 / sps, peak), 6
                        ) if sps > 0 else 0.0,
                        **comms,
                    )
        if drain_requested(step):
            # preemption drain: this step FINISHED (we're at the step
            # boundary); save synchronously, dump the incident with its
            # trajectory, and leave the loop cleanly — the restarted job
            # resumes at step + 1, possibly at another device count
            guard.drain(
                lambda: mgr.save(step, make_ckpt(), block=True),
                recorder=recorder, step=step,
            )
            print(f"preemption ({guard.signal_name}): drained and saved "
                  f"step {step}; exiting cleanly")
            break
        if mgr is not None and (
            step % args.ckpt_every == 0 or step == args.steps - 1
        ):
            with tracer.span("train/ckpt", step=step):
                mgr.save(step, make_ckpt())


if __name__ == "__main__":
    main()
