"""Incremental decoding demo: prefill a prompt, then stream tokens.

The KV cache is sharded over the mesh's seq axis; every step merges shard
partials with tree attention (arXiv 2408.04093).  Runs on a TPU slice or a
simulated CPU mesh:

  python examples/generate.py --fake-devices 8 --steps 16
"""

from __future__ import annotations

import argparse
import os
import sys
import time

try:  # prefer the installed package (pip install -e .)
    import ring_attention_tpu  # noqa: F401
except ModuleNotFoundError:  # running from a source checkout, any cwd
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fake-devices", type=int, default=0)
    ap.add_argument("--steps", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--max-len", type=int, default=128)
    ap.add_argument("--use-pallas", action="store_true",
                    help="decode with the pallas decode kernel (each cache "
                         "byte read once per kv head; interpret mode on CPU)")
    ap.add_argument("--q8-cache", action="store_true",
                    help="store the decode KV cache as per-token int8 "
                         "(1.88x fewer cache HBM bytes at d=64)")
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="sample with this temperature via the scan-based "
                         "generate() (0 = greedy token-by-token streaming)")
    ap.add_argument("--top-k", type=int, default=None)
    ap.add_argument("--top-p", type=float, default=None)
    ap.add_argument("--compile-cache-dir", default=None,
                    help="persistent XLA compilation cache directory: "
                         "repeated runs skip recompiles (utils/benchtime.py)")
    ap.add_argument("--metrics-dir", default=None,
                    help="telemetry: append decode-throughput JSONL rows "
                         "(tok/s, ms/token, prefill length) for "
                         "tools/trace_report.py (docs/observability.md)")
    ap.add_argument("--trace-dir", default=None,
                    help="span tracing: one span per decoded token plus "
                         "prefill, merged with tools/cluster_timeline.py "
                         "(docs/observability.md §6)")
    args = ap.parse_args()

    if args.temperature <= 0.0 and (args.top_k is not None
                                    or args.top_p is not None):
        ap.error("--top-k/--top-p need --temperature > 0 (sampling mode)")
    if args.prompt_len + args.steps - 1 > args.max_len:
        ap.error(
            f"--max-len {args.max_len} too small for prompt {args.prompt_len} "
            f"+ {args.steps} steps (cache writes would clamp silently)"
        )

    if args.fake_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.fake_devices}"
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import warnings

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ring_attention_tpu import RingTransformer, create_mesh
    from ring_attention_tpu.utils import compat, enable_compile_cache

    if args.compile_cache_dir:
        # before any jit: every compile from here on lands in the cache
        enable_compile_cache(args.compile_cache_dir)
    # CPU dev boxes can't honor donation; the hint is still correct on TPU
    warnings.filterwarnings(
        "ignore", message="Some donated buffers were not usable"
    )

    from ring_attention_tpu.utils import tracing

    if args.trace_dir:
        tracing.configure(args.trace_dir, process=jax.process_index())
    tracer = tracing.get_tracer()

    n_dev = len(jax.devices())
    mesh = create_mesh(ring_size=n_dev) if n_dev > 1 else None
    model = RingTransformer(
        num_tokens=256, dim=128, depth=2, heads=4, dim_head=32,
        causal=True, bucket_size=64, mesh=mesh, use_ring=mesh is not None,
        use_pallas=args.use_pallas, quantize_cache=args.q8_cache,
    )
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, 256, (1, args.prompt_len)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)

    def log_decode(**fields):
        if args.metrics_dir is None:
            return
        from ring_attention_tpu.utils import MetricsLogger

        with MetricsLogger(args.metrics_dir) as logger:
            logger.log(0, mode="decode", devices=n_dev,
                       prompt_len=args.prompt_len,
                       use_pallas=bool(args.use_pallas),
                       q8_cache=bool(args.q8_cache), **fields)

    if args.temperature > 0.0:
        # whole loop as ONE compiled scan (models/transformer.py generate)
        t0 = time.perf_counter()
        out = model.apply(
            params, prompt, args.max_len, args.steps,
            method=RingTransformer.generate,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, rng=jax.random.PRNGKey(1),
        )
        dt = time.perf_counter() - t0
        toks = [int(t) for t in np.asarray(out[0])]
        print(f"devices={n_dev}  sampled {len(toks)} tokens in one "
              f"compile+scan ({len(toks) / dt:.1f} tok/s incl. compile)")
        print("tokens:", toks)
        log_decode(tokens=len(toks), seconds=round(dt, 4),
                   tokens_per_sec=round(len(toks) / dt, 2),
                   sampled=True, compile_included=True)
        if args.trace_dir:
            tracing.shutdown()
        return

    # prefill once, then jit one decode step and stream
    with tracer.span("decode/prefill", prompt_len=args.prompt_len):
        cache = model.apply(params, 1, args.max_len, method=RingTransformer.init_cache)
        logits, cache = model.apply(params, prompt, cache, method=RingTransformer.prefill)

    # donate the KV cache: each step's updated cache reuses the previous
    # step's buffers instead of double-allocating the whole cache
    step = compat.jit(
        lambda p, tok, c, i: model.apply(
            p, tok, c, i, method=RingTransformer.decode_step
        ),
        donate_argnums=(2,),
    )
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    toks = [int(tok[0])]
    # per-token latency distribution: each iteration is a traced span
    # AND a histogram sample (the `int(tok[0])` conversion syncs on the
    # device, so the span covers the real token latency, first-token
    # compile included in sample 0)
    hist = tracing.LatencyHistogram()
    t0 = time.perf_counter()
    for i in range(args.steps - 1):
        ts = time.perf_counter()
        with tracer.span("decode/token", index=i):
            logits, cache = step(params, tok, cache,
                                 jnp.int32(args.prompt_len + i))
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            toks.append(int(tok[0]))
        hist.record(time.perf_counter() - ts)
    dt = time.perf_counter() - t0
    print(f"devices={n_dev}  generated {len(toks)} tokens "
          f"({(len(toks) - 1) / dt:.1f} tok/s after prefill)")
    print("tokens:", toks)
    if hist.n:
        print(f"token latency: p50 {hist.percentile_ms(50):.2f} ms  "
              f"p95 {hist.percentile_ms(95):.2f} ms  "
              f"p99 {hist.percentile_ms(99):.2f} ms")
    if len(toks) > 1:
        log_decode(tokens=len(toks), seconds=round(dt, 4),
                   tokens_per_sec=round((len(toks) - 1) / dt, 2),
                   ms_per_token=round(dt * 1e3 / (len(toks) - 1), 3),
                   decode_ms_p50=round(hist.percentile_ms(50), 3),
                   decode_ms_p95=round(hist.percentile_ms(95), 3),
                   decode_ms_p99=round(hist.percentile_ms(99), 3),
                   latency_hist=hist.to_dict(),
                   sampled=False, compile_included=False)
    if args.trace_dir:
        tracing.shutdown()


if __name__ == "__main__":
    main()
