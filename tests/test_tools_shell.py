"""Static + behavioral smoke tests over ``tools/*.sh``.

The round-5 advisor found a stale-lock takeover race in
``tpu_window_watch.sh`` that no test could have caught — shell has no
import-time syntax check, so a broken watcher is only discovered when a
scarce TPU window opens.  This module gives the shell tooling a fast CI
tier: ``bash -n`` parse checks on every script, shellcheck when the host
has it, and a real two-contender exercise of the watcher's atomic lock
protocol (temp-dir + rename acquisition; pid-dead + min-age staleness).
"""

import glob
import json
import os
import py_compile
import re
import shutil
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = sorted(glob.glob(os.path.join(REPO, "tools", "*.sh")))
WATCHER = os.path.join(REPO, "tools", "tpu_window_watch.sh")
KERNEL_VALIDATE = os.path.join(REPO, "tools", "tpu_kernel_validate.py")
TRACE_REPORT = os.path.join(REPO, "tools", "trace_report.py")
CLUSTER_TIMELINE = os.path.join(REPO, "tools", "cluster_timeline.py")
CHECK_CONTRACTS = os.path.join(REPO, "tools", "check_contracts.py")
PERF_GATE = os.path.join(REPO, "tools", "perf_gate.py")


def test_tools_exist():
    assert TOOLS, "tools/*.sh vanished — update this suite"


@pytest.mark.parametrize(
    "script", TOOLS, ids=[os.path.basename(t) for t in TOOLS]
)
def test_bash_syntax(script):
    proc = subprocess.run(
        ["bash", "-n", script], capture_output=True, text=True, timeout=60
    )
    assert proc.returncode == 0, f"bash -n {script}: {proc.stderr}"


@pytest.mark.parametrize(
    "script", TOOLS, ids=[os.path.basename(t) for t in TOOLS]
)
def test_shellcheck_if_available(script):
    if shutil.which("shellcheck") is None:
        pytest.skip("shellcheck not installed on this host")
    proc = subprocess.run(
        # severity=error: catch real breakage without churning on style
        ["shellcheck", "--severity=error", script],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, f"shellcheck {script}:\n{proc.stdout}"


# ----------------------------------------------------------------------
# Python hardware tools: flag-surface smoke (the shell "bash -n" analogue
# — a broken flag is otherwise only discovered when a TPU window opens)
# ----------------------------------------------------------------------


def test_tpu_kernel_validate_compiles():
    py_compile.compile(KERNEL_VALIDATE, doraise=True)


def test_tpu_kernel_validate_segments_flag_parses():
    """``--segments`` (the packed-sequence sweep) must be a real flag:
    ``--help`` exits 0 and documents it — argparse runs before any jax
    work, so this needs no TPU."""
    proc = subprocess.run(
        [sys.executable, KERNEL_VALIDATE, "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "--segments" in proc.stdout


def test_tpu_kernel_validate_hybrid_flag_parses():
    """``--hybrid U`` (the Ulysses x Ring factoring sweep) must be a real
    flag — same contract as ``--segments``: a broken flag is otherwise
    only discovered when a scarce TPU window opens."""
    proc = subprocess.run(
        [sys.executable, KERNEL_VALIDATE, "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "--hybrid" in proc.stdout


def test_tpu_kernel_validate_q8_flag_parses():
    """``--q8`` (the int8 compute sweep, PR 13) must be a real flag —
    same contract as ``--segments``: a broken flag is otherwise only
    discovered when a scarce TPU window opens."""
    proc = subprocess.run(
        [sys.executable, KERNEL_VALIDATE, "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "--q8" in proc.stdout


def test_tpu_kernel_validate_fused_flag_parses():
    """``--fused`` (the single-launch fused-ring sweep, PR 18) must be a
    real flag — same contract as ``--q8``: a broken flag is otherwise
    only discovered when a scarce TPU window opens."""
    proc = subprocess.run(
        [sys.executable, KERNEL_VALIDATE, "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "--fused" in proc.stdout


def test_trace_report_compiles():
    py_compile.compile(TRACE_REPORT, doraise=True)


def test_trace_report_flags_parse():
    """``trace_report.py`` is stdlib-only and its flag surface (``--xprof``
    / ``--last``) must parse without any jax import — the telemetry
    analogue of the kernel-validate smoke: a broken report tool is
    otherwise only discovered when someone needs the numbers."""
    proc = subprocess.run(
        [sys.executable, TRACE_REPORT, "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "--xprof" in proc.stdout
    assert "--last" in proc.stdout


def test_trace_report_diff_renders(tmp_path):
    """``--diff OLD NEW`` — the human-facing half of the perf gate — must
    produce the side-by-side delta/percent table from two metrics runs
    (stdlib-only, no jax import)."""
    old = tmp_path / "old"
    new = tmp_path / "new"
    for d, tps in ((old, 100.0), (new, 80.0)):
        d.mkdir()
        (d / "metrics.jsonl").write_text(
            f'{{"schema": 1, "step": 0, "loss": 2.0, '
            f'"tokens_per_sec": {tps}}}\n'
        )
    proc = subprocess.run(
        [sys.executable, TRACE_REPORT, "--diff", str(old), str(new)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "tokens_per_sec" in proc.stdout
    assert "-20.0%" in proc.stdout
    assert "pct" in proc.stdout


def test_cluster_timeline_compiles():
    py_compile.compile(CLUSTER_TIMELINE, doraise=True)


def test_cluster_timeline_flags_parse():
    """``cluster_timeline.py`` is stdlib-only and its flag surface
    (``--chrome`` / ``--incident`` / ``--last``) must parse without any
    jax import — the tracing analogue of the trace-report smoke."""
    proc = subprocess.run(
        [sys.executable, CLUSTER_TIMELINE, "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    for flag in ("--chrome", "--incident", "--last", "--reference"):
        assert flag in proc.stdout, f"{flag} missing from --help"


def test_cluster_timeline_renders_and_incident_exit_codes(tmp_path):
    """The three exits, each from a real span file: a table on a healthy
    trace (0), exit 3 on ``--incident`` with no anchor, and the
    annotated incident when a chaos kill is present (stdlib-only, no
    jax import in the tool)."""
    span = {"schema": 1, "trace": "t", "proc": 0, "kind": "span",
            "name": "train/step", "span": 2, "parent": None,
            "mono": 1.0, "wall": 100.0, "dur": 0.25,
            "attrs": {"step": 0}}
    trace = tmp_path / "trace"
    trace.mkdir()
    path = trace / "spans_p00000.jsonl"
    path.write_text(json.dumps(span) + "\n")
    proc = subprocess.run(
        [sys.executable, CLUSTER_TIMELINE, str(trace)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "train/step" in proc.stdout

    proc = subprocess.run(
        [sys.executable, CLUSTER_TIMELINE, str(trace), "--incident"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 3, (proc.stdout, proc.stderr)
    assert "no incident anchor" in proc.stderr

    kill = {**span, "kind": "instant", "name": "chaos/kill", "span": 3,
            "wall": 101.0, "attrs": {"fault": "kill_at_step"}}
    del kill["dur"]
    path.write_text(json.dumps(span) + "\n" + json.dumps(kill) + "\n")
    proc = subprocess.run(
        [sys.executable, CLUSTER_TIMELINE, str(trace), "--incident"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "chaos/kill on process 0" in proc.stdout

    out = tmp_path / "chrome.json"
    proc = subprocess.run(
        [sys.executable, CLUSTER_TIMELINE, str(trace),
         "--chrome", str(out)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(out.read_text())
    assert {e["ph"] for e in payload["traceEvents"]} == {"M", "X", "i"}


def test_perf_gate_compiles():
    py_compile.compile(PERF_GATE, doraise=True)


def test_perf_gate_flags_parse():
    proc = subprocess.run(
        [sys.executable, PERF_GATE, "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    for flag in ("--check", "--json", "--history-only", "--update-baseline",
                 "--strategies", "--skip-compiled"):
        assert flag in proc.stdout, f"{flag} missing from --help"


def test_perf_gate_refuses_subset_baseline():
    """``--update-baseline`` from a subset run would silently drop the
    missing signal families (absent baseline families are notes, not
    findings) — the CLI must refuse before collecting anything."""
    proc = subprocess.run(
        [sys.executable, PERF_GATE, "--update-baseline", "--skip-compiled"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode != 0
    assert "full signal set" in proc.stderr


def test_perf_gate_check_json_smoke():
    """``--check --json`` on the real repo history, history-only (no
    compiles — the live-signal gate runs in tests/test_observatory.py):
    one valid JSON object, ok verdict, wedge record present."""
    import json

    proc = subprocess.run(
        [sys.executable, PERF_GATE, "--check", "--history-only", "--json"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["ok"] is True
    assert report["gate_schema"] >= 1
    assert any("wedge record" in n for n in report["notes"])


def test_check_contracts_compiles():
    py_compile.compile(CHECK_CONTRACTS, doraise=True)


def test_check_contracts_flags_parse():
    """``check_contracts.py`` must keep its documented flag surface
    (``--strategy/--mesh/--json/--memory``): argparse runs before any jax
    device work, so this smoke needs no simulated mesh.  The full
    contract run lives in tests/test_analysis.py; the memory-audit suite
    in tests/test_memory.py."""
    proc = subprocess.run(
        [sys.executable, CHECK_CONTRACTS, "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    for flag in ("--strategy", "--mesh", "--json", "--devices", "--memory",
                 "--coverage", "--dataflow", "--dma", "--elastic"):
        assert flag in proc.stdout, f"{flag} missing from --help"


def test_check_contracts_coverage_exits_zero():
    """Acceptance: ``check_contracts.py --coverage`` proves soundness AND
    tightness for every strategy x layout x masking row on CPU and exits
    0.  Numpy-only after import — no mesh, no compiles, cheap enough for
    a subprocess smoke."""
    proc = subprocess.run(
        [sys.executable, CHECK_CONTRACTS, "--coverage"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "coverage rows sound and tight" in proc.stdout


def test_check_contracts_dma_exits_zero():
    """Acceptance: ``check_contracts.py --dma`` re-proves the fused-ring
    DMA/semaphore protocol — the rings-2..8 model check plus the jaxpr
    extraction cross-check for the plain and q8 feeds — on CPU virtual
    devices and exits 0."""
    proc = subprocess.run(
        [sys.executable, CHECK_CONTRACTS, "--dma"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "3/3 DMA-protocol checks hold" in proc.stdout
    assert "protocol model (rings 2-8" in proc.stdout


def test_check_contracts_elastic_exits_zero():
    """Acceptance: ``check_contracts.py --elastic`` holds the elastic
    checkpoint contracts (manifest schema round-trip, resharded-load ==
    direct-load at a changed mesh, corrupt-shard fallback, commit-debris
    sweep) on CPU virtual devices and exits 0.  The quick in-process
    subset (``--no-multiprocess``) runs here; the full 7/7 including the
    spawned two-process rows is the slow-tier
    ``tests/test_multihost.py::test_elastic_cli_multiprocess_rows``."""
    proc = subprocess.run(
        [sys.executable, CHECK_CONTRACTS, "--elastic",
         "--no-multiprocess"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "4/4 elastic checks hold" in proc.stdout
    as_json = subprocess.run(
        [sys.executable, CHECK_CONTRACTS, "--elastic",
         "--no-multiprocess", "--json"],
        capture_output=True, text=True, timeout=300,
    )
    assert as_json.returncode == 0, as_json.stdout + as_json.stderr
    payload = json.loads(as_json.stdout)
    assert payload["ok"] is True and payload["checked"] == 4


def test_check_contracts_mask_filter():
    """``--coverage --mask EXPR`` re-proves one mask row in isolation;
    an unknown mask name lists the registry instead of tracebacking."""
    proc = subprocess.run(
        [sys.executable, CHECK_CONTRACTS, "--coverage", "--mask",
         "causal&window:24"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "(causal&window:24)" in proc.stdout
    assert "coverage rows sound and tight" in proc.stdout
    bad = subprocess.run(
        [sys.executable, CHECK_CONTRACTS, "--coverage", "--mask", "wat:7"],
        capture_output=True, text=True, timeout=300,
    )
    assert bad.returncode != 0
    assert "Traceback" not in bad.stderr
    assert "registry" in bad.stderr and "window" in bad.stderr
    # --mask without --coverage is a usage error, not a silent no-op
    usage = subprocess.run(
        [sys.executable, CHECK_CONTRACTS, "--mask", "causal"],
        capture_output=True, text=True, timeout=300,
    )
    assert usage.returncode != 0 and "--coverage" in usage.stderr


def test_check_contracts_knows_counter_variants():
    """The counter-rotation / int8-compression strategies are enumerable
    by name: an unknown strategy's error message lists every CONTRACTS
    key, so this pins the rows' existence without compiling anything
    (the full run lives in tests/test_analysis.py)."""
    proc = subprocess.run(
        [sys.executable, CHECK_CONTRACTS, "--strategy", "nonesuch"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode != 0
    for name in ("counter", "ring_compressed", "counter_compressed"):
        assert name in proc.stderr, f"{name} missing from strategy listing"


def test_check_contracts_mesh_mismatch_is_a_diagnostic():
    """A --mesh that fits none of the requested strategies must exit with
    a one-line diagnostic, not a traceback (hybrid needs a factored
    mesh); mixed requests skip the mismatches loudly instead of aborting
    the run on the first incompatible strategy.  Argparse-level only: no
    strategy compiles, so this stays cheap."""
    proc = subprocess.run(
        [sys.executable, CHECK_CONTRACTS,
         "--strategy", "hybrid", "--mesh", "1x8"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode != 0
    assert "Traceback" not in proc.stderr
    assert "matched no requested strategy" in proc.stderr


# ----------------------------------------------------------------------
# Static analysis: the repo-native lint and ruff, alongside bash -n
# ----------------------------------------------------------------------


def test_repo_lint_self_run():
    """The repo lint over the package tree exits clean — the python
    analogue of ``bash -n``: every one-liner fix that landed with rules
    RA001-RA008 stays landed.  Run in the script-path form, which is the
    documented jax-free invocation (the ``-m`` form imports the package
    ``__init__`` chain and therefore jax)."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "ring_attention_tpu", "analysis", "lint.py")],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode == 0, f"repo lint:\n{proc.stdout}{proc.stderr}"


def test_ruff_if_available():
    """``ruff check`` with the pyproject config (import hygiene + the
    correctness subset the codebase already satisfies) — the shellcheck
    pattern: enforced where the host has ruff, skipped where it doesn't."""
    if shutil.which("ruff") is None:
        pytest.skip("ruff not installed on this host")
    proc = subprocess.run(
        ["ruff", "check", "ring_attention_tpu", "tools", "tests", "bench.py"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode == 0, f"ruff:\n{proc.stdout}"


# ----------------------------------------------------------------------
# Watcher lock protocol (the advisor's race, exercised for real)
# ----------------------------------------------------------------------

def _extract_acquire_lock() -> str:
    """Pull ``acquire_lock()`` out of the shipped watcher script, so the
    behavioral tests below exercise the REAL code — an edit to the
    script's locking (e.g. moving the pid write after the rename,
    reintroducing the empty-pid race) fails these tests, not a pasted
    copy of what the function used to be."""
    src = open(WATCHER).read()
    m = re.search(r"^acquire_lock\(\) \{\n.*?\n\}\n", src, re.S | re.M)
    assert m, "acquire_lock() not found in tpu_window_watch.sh"
    return m.group(0)


_LOCK_LIB = _extract_acquire_lock()


def _run_lock_snippet(body: str, lock: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        ["bash", "-c", f'LOCK="{lock}"\n{_LOCK_LIB}\n{body}'],
        capture_output=True, text=True, timeout=60,
    )


def test_watcher_script_uses_atomic_acquisition():
    """Regression pin: the watcher must keep the temp-dir + rename pattern
    (a bare ``mkdir $LOCK`` followed by a later pid write reintroduces the
    empty-pid takeover window)."""
    src = open(os.path.join(REPO, "tools", "tpu_window_watch.sh")).read()
    assert 'mv -T "$tmp" "$LOCK"' in src
    assert "MIN_LOCK_AGE" in src
    # the pid is written into the temp dir BEFORE the rename
    assert src.index('echo $$ > "$tmp/pid"') < src.index('mv -T "$tmp" "$LOCK"')


def test_lock_acquire_is_exclusive(tmp_path):
    lock = os.path.join(str(tmp_path), "watch.lock")
    first = _run_lock_snippet("acquire_lock && echo WON", lock)
    assert "WON" in first.stdout
    assert os.path.exists(os.path.join(lock, "pid"))
    second = _run_lock_snippet(
        "acquire_lock && echo WON || echo BLOCKED", lock
    )
    assert "BLOCKED" in second.stdout


def test_lock_held_lock_always_contains_pid(tmp_path):
    """The race's precondition — a held lock with no pid file — can no
    longer exist: N concurrent acquirers leave exactly one winner and the
    lock contains a pid from the instant it exists."""
    lock = os.path.join(str(tmp_path), "watch.lock")
    procs = [
        subprocess.Popen(
            ["bash", "-c",
             f'LOCK="{lock}"\n{_LOCK_LIB}\n'
             "acquire_lock && echo WON || echo LOST"],
            stdout=subprocess.PIPE, text=True,
        )
        for _ in range(8)
    ]
    outcomes = [p.communicate(timeout=60)[0].strip() for p in procs]
    assert outcomes.count("WON") == 1, outcomes
    with open(os.path.join(lock, "pid")) as f:
        assert f.read().strip().isdigit()


def test_stale_lock_rules(tmp_path):
    """Takeover requires pid-file-present AND pid-dead AND min age — the
    three-way rule from ADVICE.md, checked via the watcher's own logic."""
    lock = os.path.join(str(tmp_path), "watch.lock")

    def staleness_check(min_age: int) -> str:
        # mirrors the watcher's takeover decision block
        body = f"""
        MIN_LOCK_AGE={min_age}
        oldpid=$(cat "$LOCK/pid" 2>/dev/null)
        lock_mtime=$(stat -c %Y "$LOCK" 2>/dev/null || echo 0)
        lock_age=$(( $(date +%s) - lock_mtime ))
        if [ -n "$oldpid" ] && kill -0 "$oldpid" 2>/dev/null; then
          echo ALIVE
        elif [ -z "$oldpid" ] || [ "$lock_age" -lt "$MIN_LOCK_AGE" ]; then
          echo INDETERMINATE
        else
          echo STALE
        fi
        """
        return _run_lock_snippet(textwrap.dedent(body), lock).stdout.strip()

    # live holder -> never stale
    os.makedirs(lock)
    with open(os.path.join(lock, "pid"), "w") as f:
        f.write(str(os.getpid()))
    assert staleness_check(0) == "ALIVE"

    # dead pid but young lock -> indeterminate (no takeover)
    with open(os.path.join(lock, "pid"), "w") as f:
        f.write("999999999")
    assert staleness_check(3600) == "INDETERMINATE"

    # dead pid + old lock -> stale (takeover allowed)
    old = 1_000_000_000  # year 2001
    os.utime(lock, (old, old))
    assert staleness_check(60) == "STALE"

    # missing pid file -> indeterminate even when old
    os.remove(os.path.join(lock, "pid"))
    assert staleness_check(60) == "INDETERMINATE"
