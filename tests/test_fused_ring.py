"""Parity: the single-launch fused ring vs the scan-path Pallas ring.

``ring_flash_attention(impl="fused")`` carries the whole hop schedule —
and its f32 ``(acc, m, l)`` online-softmax state — inside ONE Pallas
launch (``ops/pallas_ring.py``), where the scan path runs one flash call
per hop with a ``ppermute`` between launches.  Both paths accumulate in
f32 over the SAME per-hop span partition, so on this container the fused
forward is pinned BIT-EXACT against the scan path for plain / striped /
windowed / packed / GQA / int8-wire configs (the int8 COMPUTE feed
differs only by its per-row q requantization order, pinned at float
tolerance).  The backward is the retained scan-path Pallas backward in
both cases, so gradients are pinned exact too.

On CPU the fused kernel runs in interpret mode when called explicitly
(this file — the parity tier); the RESOLUTION seam
(``utils.resilience.resolve_ring_impl``) instead records a
``fused_ring`` degradation and falls back to the scan path, pinned at
the end of this file.
"""

import warnings
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ring_attention_tpu.parallel import (
    create_mesh,
    ring_flash_attention,
    stripe_permute,
    stripe_unpermute,
)
from ring_attention_tpu.utils import resilience
from ring_attention_tpu.utils.compat import shard_map

# fused-vs-q8 forward: identical span schedule, q requantized per row in
# both paths — only the fused path's in-kernel requant order differs
Q8_ATOL = 1e-5


@pytest.fixture(scope="module")
def mesh():
    return create_mesh(ring_size=4, data_size=2)


def make_qkv(rng, b=2, h=4, hk=None, n=128, d=16):
    hk = hk or h
    q = jnp.asarray(rng.standard_normal((b, h, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    return q, k, v


def ring_attn(q, k, v, mask=None, seg=None, *, mesh, impl, striped=False,
              **kw):
    """Global-array harness: shard over (data, seq), run one impl."""
    ring = mesh.shape["seq"]
    if striped:
        q = stripe_permute(q, ring, axis=2)
        k = stripe_permute(k, ring, axis=2)
        v = stripe_permute(v, ring, axis=2)

    base = partial(
        ring_flash_attention, axis_name="seq", causal=True,
        striped=striped, bucket_size=32, impl=impl, **kw,
    )
    qspec = P("data", None, "seq", None)
    mspec = P("data", "seq")
    if seg is not None:
        fn = lambda q, k, v, m, s: base(q, k, v, m, segment_ids=s)  # noqa: E731
        specs = (qspec, qspec, qspec,
                 mspec if mask is not None else P(), mspec)
        operands = (q, k, v, mask, seg)
    else:
        fn = base
        specs = (qspec, qspec, qspec, mspec if mask is not None else P())
        operands = (q, k, v, mask)
    out = shard_map(
        fn, mesh=mesh,
        in_specs=specs,
        out_specs=qspec,
        check_vma=False,  # device-varying scalars trip jax's vma checker
    )(*operands)
    if striped:
        out = stripe_unpermute(out, ring, axis=2)
    return out


def assert_fused_matches_scan(rng, mesh, *, exact=True, atol=0.0, **kw):
    """One config, both impls, same inputs — the parity pin."""
    q, k, v = make_qkv(rng, hk=kw.pop("hk", None))
    mask = kw.pop("mask", None)
    seg = kw.pop("seg", None)
    fused = ring_attn(q, k, v, mask, seg, mesh=mesh, impl="fused", **kw)
    scan = ring_attn(q, k, v, mask, seg, mesh=mesh, impl="pallas", **kw)
    if exact:
        np.testing.assert_array_equal(np.asarray(fused), np.asarray(scan))
    else:
        np.testing.assert_allclose(
            np.asarray(fused), np.asarray(scan), atol=atol
        )


def test_fused_plain(rng, mesh, devices):
    assert_fused_matches_scan(rng, mesh)


def test_fused_striped(rng, mesh, devices):
    assert_fused_matches_scan(rng, mesh, striped=True)


def test_fused_windowed(rng, mesh, devices):
    assert_fused_matches_scan(rng, mesh, window=48)


def test_fused_striped_windowed(rng, mesh, devices):
    assert_fused_matches_scan(rng, mesh, striped=True, window=40)


def test_fused_gqa(rng, mesh, devices):
    assert_fused_matches_scan(rng, mesh, hk=2)


def test_fused_key_padding(rng, mesh, devices):
    mask = jnp.asarray(rng.random((2, 128)) > 0.3)
    assert_fused_matches_scan(rng, mesh, mask=mask)


def test_fused_packed_segments(rng, mesh, devices):
    # 4 equal shard-aligned documents: the packed grid masks cross-doc
    # pairs identically in both paths
    seg = jnp.repeat(jnp.arange(4, dtype=jnp.int32), 32)[None, :]
    seg = jnp.broadcast_to(seg, (2, 128))
    assert_fused_matches_scan(rng, mesh, seg=seg)


def test_fused_limited_passes(rng, mesh, devices):
    assert_fused_matches_scan(rng, mesh, max_ring_passes=2, window=32)


def test_fused_wire8(rng, mesh, devices):
    # int8 HOP payload (PR 13 wire format): quantized once at ring entry,
    # dequantized identically by both paths — still exact
    assert_fused_matches_scan(rng, mesh, hop_compression="int8")


def test_fused_q8_compute(rng, mesh, devices):
    # int8 COMPUTE: both paths quantize q per row and feed int8 matmuls;
    # only the fused kernel's in-kernel requant placement differs
    assert_fused_matches_scan(
        rng, mesh, exact=False, atol=Q8_ATOL, compute_dtype="int8",
    )


def test_fused_wire8_q8_compute(rng, mesh, devices):
    # the dequant-free ring: one packed payload feeds every hop directly
    assert_fused_matches_scan(
        rng, mesh, exact=False, atol=Q8_ATOL,
        hop_compression="int8", compute_dtype="int8",
    )


@pytest.mark.parametrize("kw", [{}, {"window": 48}, {"striped": True}])
def test_fused_grads_match_scan(rng, mesh, devices, kw):
    """The fused forward retains the scan-path Pallas backward — the
    custom-vjp residuals it saves are the same ``(out, lse)`` contract,
    so dq/dk/dv are pinned exact against the scan path."""
    q, k, v = make_qkv(rng)

    def loss(impl):
        def f(q, k, v):
            o = ring_attn(q, k, v, mesh=mesh, impl=impl, **kw)
            return (o * o).sum()
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    for gf, gs in zip(loss("fused"), loss("pallas")):
        np.testing.assert_array_equal(np.asarray(gf), np.asarray(gs))


def test_neighbor_mesh_coords_multiaxis(mesh, devices):
    """The remote tier's device-id table, pinned on a MULTI-axis mesh —
    the exact shape where a ring-rank-only LOGICAL id addresses the wrong
    replica group.  Every device's ``(2, naxes)`` MESH coordinates must
    vary ONLY the ring axis and keep its own data coordinate, so each
    replica group circulates KV strictly within itself."""
    from ring_attention_tpu.ops.pallas_ring import neighbor_mesh_coords

    ring = mesh.shape["seq"]

    def core(x):
        c = neighbor_mesh_coords("seq", ring)
        assert c is not None  # trace-time: axes introspectable here
        return c.reshape(1, 1, 2, c.shape[-1])

    out = shard_map(
        core, mesh=mesh,
        in_specs=(P("data", "seq"),),
        out_specs=P("data", "seq", None, None),
        check_vma=False,
    )(jnp.zeros((2, ring)))
    coords = np.asarray(out)  # [di, si] -> that device's (2, naxes) table
    assert coords.shape == (2, ring, 2, 2)
    for di in range(2):
        for si in range(ring):
            np.testing.assert_array_equal(
                coords[di, si, 0], [di, (si - 1) % ring])
            np.testing.assert_array_equal(
                coords[di, si, 1], [di, (si + 1) % ring])


def test_fused_remote_probe_degrades_on_cpu(devices):
    """Finding-4 pin: the REMOTE tier has its own probe + component.  On a
    backend that cannot execute in-kernel remote DMA the probe records a
    ``fused_ring_remote`` degradation (one-shot warning, queryable event)
    instead of letting the model path hit a hard runtime failure — and
    the fallback is ``fused_ring_local``, still the single-launch tier,
    NOT the scan ring (``FUSED_COMPONENT`` stays healthy)."""
    resilience.reset()
    try:
        with pytest.warns(UserWarning, match="fused_ring_remote degraded"):
            assert resilience.fused_remote_available() is False
        assert resilience.degradation.is_degraded(
            resilience.FUSED_REMOTE_COMPONENT)
        assert not resilience.degradation.is_degraded(
            resilience.FUSED_COMPONENT)
        # sticky: the probe is cached, no second warning
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            assert resilience.fused_remote_available() is False
    finally:
        resilience.reset()


def test_fused_remote_fault_injection_degrades(devices):
    """Armed ``FUSED_REMOTE_FAULT``: the remote-tier probe fails before
    touching the kernel and records its own degradation — the
    chaos-harness hook for the ICI tier specifically."""
    resilience.reset()
    try:
        with resilience.inject(resilience.FUSED_REMOTE_FAULT):
            with pytest.warns(UserWarning, match="fused_ring_remote"):
                assert resilience.fused_remote_available() is False
        assert resilience.degradation.is_degraded(
            resilience.FUSED_REMOTE_COMPONENT)
    finally:
        resilience.reset()


def test_fused_resolution_degrades_on_cpu(devices):
    """The resolution seam: on a backend without in-kernel remote copies
    (this CPU container), ``resolve_ring_impl`` records a ``fused_ring``
    degradation — one-shot warning, queryable event — and lands on the
    scan path's own resolution; an explicit ``impl="fused"`` CALL still
    runs (interpret mode — the tests above), the RESOLVER is the seam
    models go through."""
    resilience.reset()
    try:
        with pytest.warns(UserWarning, match="fused_ring degraded"):
            resolved = resilience.resolve_ring_impl("fused")
        assert resolved == "xla"  # CPU: the scan path resolves to XLA too
        assert resilience.degradation.is_degraded(resilience.FUSED_COMPONENT)
        events = resilience.degradation.events()
        assert any(e.component == resilience.FUSED_COMPONENT for e in events)
        # sticky: "auto" now skips the fused probe silently
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            assert resilience.resolve_ring_impl("auto") == "xla"
    finally:
        resilience.reset()


def test_fused_fault_injection_degrades(devices):
    """Armed ``FUSED_FAULT``: the probe fails before touching the kernel,
    the degradation is recorded, and ``"auto"`` resolution falls back —
    the chaos-harness hook for the fused tier."""
    resilience.reset()
    try:
        with resilience.inject(resilience.FUSED_FAULT):
            with pytest.warns(UserWarning, match="degraded"):
                assert resilience.resolve_ring_impl("auto") == "xla"
        assert resilience.degradation.is_degraded(resilience.FUSED_COMPONENT)
    finally:
        resilience.reset()
