"""The DMA/semaphore protocol verifier (``analysis/schedverify.py``).

Three layers, mirroring tests/test_analysis.py:

  - **positive proofs**: the shipped ``fused_ring_remote`` protocol
    model-checks clean for every ring size 2..8 (bare ring AND 2-group
    mesh) — grant balance, no overwrite-before-read, semaphore drain,
    deadlock freedom — and the jaxpr extraction cross-check matches the
    declared ``PROTOCOL`` table site-by-site for the plain and q8 feeds;
  - **negative toys**: both REAL PR-18 review bugs, kept alive as
    protocol variants, must each fail with a one-line diagnostic naming
    the hop/slot (the grant-less push's mid-read overwrite) or the
    hop/device (the logical ring-rank id's replica-group escape) — plus
    tampered tables failing the cross-check;
  - **derivation**: the fused contract's expected counts are DERIVED
    from the verified table (no more hand-pinned numbers), and the
    protocol fingerprint the perf gate pins exactly is deterministic.
"""

import pytest

from ring_attention_tpu.analysis import schedverify as sv
from ring_attention_tpu.analysis.lint import lint_source
from ring_attention_tpu.ops.pallas_ring import PROTOCOL


# ----------------------------------------------------------------------
# Positive proofs: the shipped protocol
# ----------------------------------------------------------------------


@pytest.mark.parametrize("ring", [2, 3, 4, 5, 6, 7, 8])
def test_shipped_protocol_model_checks_clean(ring):
    """Acceptance: the shipped protocol proves clean at every ring size —
    matched waits on both ends, no slot overwritten while a reader holds
    it, semaphores drained, no deadlock — on the bare ring and on the
    2-group mesh (MESH addressing stays inside the replica group)."""
    assert sv.verify_ring(ring=ring, groups=1) == []
    assert sv.verify_ring(ring=ring, groups=2) == []


def test_verify_protocol_full_sweep_clean():
    assert sv.verify_protocol() == []


@pytest.mark.parametrize("quantized", [False, True],
                         ids=["plain", "q8"])
def test_extraction_matches_declared_protocol(devices, quantized):
    """The traced kernel IS the table: every DMA/semaphore equation in
    the pallas jaxpr resolves to named refs and matches a declared row's
    signature, every row's site count matches the trace, and every
    remote op addresses by MESH coordinates — for both feeds (the q8
    payload must not grow its own copies)."""
    ops = sv.extract_fused_schedule(quantized=quantized)
    assert len(ops) == sum(sum(r["sites"].values()) for r in PROTOCOL)
    assert sv.crosscheck_protocol(ops) == []
    # extraction resolved real names, not fallbacks — a "?" would mean
    # ref identity got lost crossing a cond/while boundary
    for op in ops:
        assert "?" not in op.bufs + op.sems, str(op)


def test_run_schedverify_suite_green(devices):
    for name, violations in sv.run_schedverify_suite():
        assert violations == [], f"{name}: " + "\n".join(violations)


# ----------------------------------------------------------------------
# Negative toys: the two PR-18 review bugs
# ----------------------------------------------------------------------


def test_grantless_push_races(ring=4):
    """Review bug #1: dropping the receiver->sender grant handshake lets
    hop i+1's incoming DMA overwrite the slot hop i is still reading.
    The verifier reports the overwrite race with a one-line diagnostic
    naming the slot and hops."""
    violations = sv.verify_ring(sv.grantless_protocol(), ring=ring)
    races = [v for v in violations if "[rule: slot-overwrite-race]" in v]
    assert races, violations
    for v in races:
        assert "\n" not in v  # one-line diagnostics, house style
    # the diagnostic names the slot, the writing hop, and the reading hop
    assert any("kvbuf slot" in v and "written at hop" in v and "hop-" in v
               for v in races), races


def test_grantless_ring2_needs_no_grant():
    """Ring 2 has no granted pushes (the guard window is empty), so the
    grant-less variant is genuinely safe there — the verifier must agree,
    or the race check is too coarse."""
    assert sv.verify_ring(sv.grantless_protocol(), ring=2) == []


def test_grantless_fails_at_every_ring_from_3():
    for ring in (3, 5, 8):
        assert any("[rule: slot-overwrite-race]" in v
                   for v in sv.verify_ring(sv.grantless_protocol(),
                                           ring=ring)), ring


def test_logical_id_escapes_replica_group():
    """Review bug #2: addressing the push by flat ring-rank LOGICAL id.
    Invisible on the bare ring (group 0 IS the mesh) — the verifier must
    pass there, exactly how the bug hid — and on the 2-group mesh it
    reports the replica-group escape (naming hop and devices), the
    resulting recv imbalance, and the deadlock of the starved group."""
    toy = sv.logical_id_protocol()
    assert sv.verify_ring(toy, ring=4, groups=1) == []
    violations = sv.verify_ring(toy, ring=4, groups=2)
    escapes = [v for v in violations if "[rule: dma-device-id]" in v]
    assert escapes, violations
    for v in escapes:
        assert "\n" not in v
    assert any("hop 0" in v and "outside its replica group" in v
               for v in escapes), escapes
    assert any("[rule: dma-matched-wait]" in v for v in violations)
    assert any("[rule: ring-deadlock]" in v for v in violations)


def test_crosscheck_flags_logical_device_id_at_jaxpr_level():
    """The jaxpr-side guard for the same bug: an extracted remote op
    whose DeviceIdType is not MESH flags, whatever the model says."""
    op = sv.ExtractedOp(
        kind="dma_start", path="pallas_call#0::dma_start#2 -> ()",
        bufs=("kvbuf", "kvbuf"), sems=("send_sem", "recv_sem"),
        remote=True, device_id_type="logical", lits=(0, 1),
    )
    violations = sv.crosscheck_protocol([op], protocol=())
    assert any("[rule: dma-device-id]" in v for v in violations)


def test_crosscheck_flags_undeclared_and_miscounted_sites():
    """An op matching no row is undeclared protocol; a row whose traced
    site count disagrees with its ``sites`` declaration is drift."""
    rogue = sv.ExtractedOp(
        kind="semaphore_signal", path="pallas_call#0::semaphore_signal#9",
        bufs=(), sems=("rogue_sem",), remote=True,
        device_id_type="mesh", lits=(1,),
    )
    violations = sv.crosscheck_protocol([rogue])
    assert any("[rule: protocol-coverage]" in v for v in violations)
    # every declared site is now missing from the (near-empty) trace
    assert any("[rule: protocol-sites]" in v for v in violations)


def test_semaphore_drain_catches_unmatched_signal():
    """A protocol with a stray extra grant signal must fail the
    matched-wait and drain checks, naming the semaphore."""
    extra = tuple(
        {**r, "guard": "hop < hops - 1"} if r["row"] == "grant" else r
        for r in PROTOCOL
    )
    violations = sv.verify_ring(extra, ring=4)
    assert any("grant_sem" in v and "[rule: dma-matched-wait]" in v
               for v in violations), violations


def test_missing_drain_deadlocks():
    """Dropping the hop drain starves the matched-wait balance and the
    schedule's semaphores never drain — the wait-side dual of the
    deadlock check."""
    toy = tuple(r for r in PROTOCOL if r["row"] != "hop-drain")
    violations = sv.verify_ring(toy, ring=4)
    assert any("[rule: dma-matched-wait]" in v for v in violations)
    assert any("[rule: semaphore-drain]" in v for v in violations)


# ----------------------------------------------------------------------
# Derivation: contract counts come from the verified table
# ----------------------------------------------------------------------


def test_derived_counts_match_lowered_module():
    """The numbers PR 18 hand-pinned, now derived from the table — and
    the contracts module serves them via FUSED_RING_EXPECTED."""
    from ring_attention_tpu.analysis import contracts

    want = {
        "dma_start": 14, "dma_wait": 14, "semaphore_signal": 3,
        "semaphore_wait": 2, "get_barrier_semaphore": 1, "ppermute": 0,
    }
    assert sv.derived_fused_counts() == want
    assert contracts.FUSED_RING_EXPECTED == want


def test_protocol_fingerprint_deterministic(devices):
    """The perf gate pins this family exactly: two collections must be
    identical, violations zero, and the derived counts embedded."""
    fp = sv.protocol_fingerprint()
    assert fp == sv.protocol_fingerprint()
    assert fp["violations"] == 0
    assert fp["rows"] == len(PROTOCOL)
    assert fp["counts"] == sv.derived_fused_counts()
    assert fp["plain_ops"] == fp["q8_ops"] == 34


# ----------------------------------------------------------------------
# Lint RA015: the verified-seam fence
# ----------------------------------------------------------------------


def test_lint_ra015_primitive_outside_declared_row():
    """Inside the fused module, a primitive call in a function no
    PROTOCOL row names is protocol the model never saw — flagged; a
    declared fn and a reasoned allow are clean."""
    src = (
        'PROTOCOL = (\n'
        '    {"row": "seed", "fn": "_seed", "op": "copy",\n'
        '     "sites": {"dma_start": 1}},\n'
        ')\n'
        'def _seed():\n'
        '    pltpu.make_async_copy(a, b, sem)\n'
        'def _rogue():\n'
        '    pltpu.semaphore_signal(sem, inc=1)\n'
        'def _excused():\n'
        '    pltpu.semaphore_wait(sem, 1)'
        '  # ra: allow(RA015 probe outside the hop schedule)\n'
    )
    violations = lint_source(src, "ring_attention_tpu/ops/pallas_ring.py")
    assert [v.rule for v in violations] == ["RA015"]
    assert violations[0].line == 8
    assert "PROTOCOL row" in violations[0].message


def test_lint_ra015_missing_table_flags_everything():
    """No parseable literal ``PROTOCOL`` assignment = no declared seam:
    every primitive site flags, which keeps the table honest (it cannot
    become computed without the lint noticing)."""
    src = "def f():\n    pltpu.semaphore_wait(s, 1)\n"
    violations = lint_source(src, "ring_attention_tpu/ops/pallas_ring.py")
    assert [v.rule for v in violations] == ["RA015"]


def test_lint_ra015_shipped_module_clean():
    """Package acceptance: every primitive site in the shipped fused
    module is covered by a declared row (RA013's file fence tightened to
    the verified seam, with nothing to excuse)."""
    from pathlib import Path

    import ring_attention_tpu.ops.pallas_ring as pr

    src = Path(pr.__file__).read_text()
    violations = lint_source(src, "ring_attention_tpu/ops/pallas_ring.py")
    assert [str(v) for v in violations] == []
