"""Span tracing layer (utils/tracing.py + tools/cluster_timeline.py).

The acceptance property of PR 19: from per-process span files ALONE, the
merger reconstructs what the whole cluster was doing — who died, what
fault window killed it, and which survivor sat in a barrier watching.
Pinned here at three levels:

* the span file format itself — O_APPEND JSONL round-trip, torn-final-
  line tolerance (a chaos kill mid-write), nested span parentage, error
  stamping, flushed-open rows on abort paths;
* the merge — the shared-rendezvous clock-offset model (an exact
  synthetic pin: a +5 s skewed process comes back into alignment),
  Chrome trace-event golden output, and the incident reconstruction
  naming victim / fault window / straggler from synthetic rows;
* the real thing — a two-process ChaosWorker cluster with one worker
  killed pre-commit, whose merged timeline must name the victim, the
  armed fault window, and the survivor's barrier wait (slow tier);

plus the :class:`LatencyHistogram` contracts the perfgate latency
family leans on (merge associativity, deterministic integer
percentiles, codec round-trip, cross-scale rejection) and the HLO pin
that a CONFIGURED tracer adds zero collectives to the compiled train
step (host-side spans only — PR-4 style).
"""

import json
import os
import subprocess
import sys

import pytest

from ring_attention_tpu.utils import tracing
from ring_attention_tpu.utils.tracing import LatencyHistogram

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "elastic_worker.py")
TIMELINE = os.path.join(REPO, "tools", "cluster_timeline.py")


@pytest.fixture(autouse=True)
def _no_global_tracer():
    """Every test starts and ends with the null tracer installed."""
    tracing.shutdown()
    yield
    tracing.shutdown()


# ----------------------------------------------------------------------
# Span file round-trip
# ----------------------------------------------------------------------


def test_span_roundtrip_nesting_and_schema(tmp_path):
    t = tracing.Tracer(tmp_path, process=0, trace_id="t" * 16)
    with t.span("outer", step=3) as outer:
        with t.span("inner") as inner:
            assert inner.parent_id == outer.span_id
        t.instant("mark", value=1)
    t.rendezvous("b0")
    t.close()

    rows = tracing.read_spans(t.path)
    assert [r["kind"] for r in rows] == [
        "process", "span", "instant", "span", "rendezvous"
    ]
    by_name = {r["name"]: r for r in rows}
    # inner closes before outer, so it lands first; parentage survives
    assert by_name["inner"]["parent"] == by_name["outer"]["span"]
    assert by_name["mark"]["parent"] == by_name["outer"]["span"]
    assert by_name["outer"]["parent"] is None
    assert by_name["outer"]["attrs"] == {"step": 3}
    assert by_name["outer"]["dur"] >= by_name["inner"]["dur"] >= 0
    for r in rows:
        assert r["schema"] == tracing.TRACE_SCHEMA_VERSION
        assert r["trace"] == "t" * 16
        assert r["proc"] == 0
        assert {"mono", "wall", "span"} <= set(r)


def test_torn_final_line_and_unknown_schema_skipped(tmp_path):
    t = tracing.Tracer(tmp_path, process=0)
    t.instant("good")
    t.close()
    with open(t.path, "a") as fh:
        fh.write(json.dumps({"schema": 99, "kind": "instant",
                             "name": "future", "wall": 0.0}) + "\n")
        fh.write('{"schema": 1, "kind": "inst')  # killed mid-write
    rows = tracing.read_spans(t.path)
    assert [r["name"] for r in rows] == ["process", "good"]


def test_span_error_stamp_and_flush_open(tmp_path):
    t = tracing.Tracer(tmp_path, process=0)
    with pytest.raises(RuntimeError):
        with t.span("barrier/wait", barrier="b1"):
            raise RuntimeError("peer died")
    # an abort path flushes whatever is still open, durably
    with t.span("ckpt/save", step=2):
        t.flush_open("chaos_kill")
        recent = t.last_spans()
        assert any(r["kind"] == "open" and r["name"] == "ckpt/save"
                   for r in recent)
    t.close()
    rows = tracing.read_spans(t.path)
    by = {(r["kind"], r["name"]): r for r in rows}
    assert by[("span", "barrier/wait")]["attrs"]["error"] == "RuntimeError"
    flushed = by[("open", "ckpt/save")]
    assert flushed["attrs"] == {"step": 2, "flush": "chaos_kill"}
    assert flushed["dur"] >= 0


def test_registry_env_opt_in_and_null_default(tmp_path, monkeypatch):
    assert tracing.get_tracer() is tracing.NULL
    # no env -> no tracer, nothing installed
    monkeypatch.delenv(tracing.TRACE_DIR_ENV, raising=False)
    assert tracing.configure_from_env() is None
    assert tracing.get_tracer() is tracing.NULL
    monkeypatch.setenv(tracing.TRACE_DIR_ENV, str(tmp_path))
    monkeypatch.setenv("RING_ATTN_TRACE_PROC", "7")
    t = tracing.configure_from_env()
    assert t is tracing.get_tracer() and t.process == 7
    assert os.path.basename(t.path) == "spans_p00007.jsonl"
    tracing.shutdown()
    assert tracing.get_tracer() is tracing.NULL


# ----------------------------------------------------------------------
# Merge: the clock-offset model
# ----------------------------------------------------------------------


def _row(proc, kind, name, wall, *, dur=None, attrs=None, span=1):
    r = {"schema": tracing.TRACE_SCHEMA_VERSION, "trace": "t",
         "proc": proc, "kind": kind, "name": name, "span": span,
         "parent": None, "mono": wall, "wall": wall,
         "attrs": attrs or {}}
    if dur is not None:
        r["dur"] = dur
    return r


def test_clock_offset_correction_exact_pin():
    # process 1's wall clock runs 5 s AHEAD; both stamp two shared
    # barrier rendezvous.  The merger must subtract the skew exactly.
    by_proc = {
        0: [_row(0, "rendezvous", "rendezvous", 100.0,
                 attrs={"tag": "s0"}),
            _row(0, "rendezvous", "rendezvous", 110.0,
                 attrs={"tag": "s1"}),
            _row(0, "span", "train/step", 100.5, dur=1.0)],
        1: [_row(1, "rendezvous", "rendezvous", 105.0,
                 attrs={"tag": "s0"}),
            _row(1, "rendezvous", "rendezvous", 115.0,
                 attrs={"tag": "s1"}),
            _row(1, "span", "train/step", 105.5, dur=1.0)],
    }
    merged = tracing.merge_spans(by_proc)
    assert merged["offsets"] == {0: 0.0, 1: -5.0}
    steps = [r for r in merged["spans"] if r["name"] == "train/step"]
    # after correction the two processes' steps coincide
    assert [round(r["t"], 6) for r in steps] == [100.5, 100.5]
    assert [round(r["t_end"], 6) for r in steps] == [101.5, 101.5]
    # no shared rendezvous -> offset stays 0.0 (same-host assumption)
    lonely = {0: by_proc[0], 2: [_row(2, "span", "x", 50.0, dur=0.1)]}
    assert tracing.merge_spans(lonely)["offsets"][2] == 0.0


def test_chrome_trace_golden():
    by_proc = {
        0: [_row(0, "span", "train/step", 10.0, dur=0.5,
                 attrs={"step": 1}, span=2),
            _row(0, "instant", "chaos/kill", 10.75,
                 attrs={"fault": "kill_pre_commit"}, span=3)],
    }
    got = tracing.to_chrome_trace(tracing.merge_spans(by_proc))
    assert got == {
        "traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 0, "tid": 0,
             "args": {"name": "process 0"}},
            {"name": "train/step", "cat": "span", "pid": 0, "tid": 0,
             "ts": 0, "args": {"step": 1}, "ph": "X", "dur": 500000},
            {"name": "chaos/kill", "cat": "instant", "pid": 0, "tid": 0,
             "ts": 750000, "args": {"fault": "kill_pre_commit"},
             "ph": "i", "s": "p"},
        ],
        "displayTimeUnit": "ms",
    }


def test_incident_reconstruction_synthetic():
    by_proc = {
        0: [_row(0, "instant", "chaos/armed", 10.0,
                 attrs={"faults": "kill_pre_commit"}, span=2),
            _row(0, "instant", "chaos/kill", 12.0,
                 attrs={"fault": "kill_pre_commit", "exit_code": 113},
                 span=3)],
        1: [_row(1, "span", "barrier/wait", 11.5, dur=3.0,
                 attrs={"barrier": "elastic:ck:s1:committed",
                        "error": "BarrierTimeout"}, span=2)],
    }
    report = tracing.reconstruct_incident(tracing.merge_spans(by_proc))
    assert report is not None
    assert "chaos/kill on process 0" in report
    assert "fault window: armed at" in report and "2.0000s armed" in report
    assert "STRAGGLER WATCH: process 1 barrier/wait" in report
    assert "BarrierTimeout" in report
    # no anchor -> no incident
    calm = {0: [_row(0, "span", "train/step", 1.0, dur=0.1)]}
    assert tracing.reconstruct_incident(tracing.merge_spans(calm)) is None


# ----------------------------------------------------------------------
# LatencyHistogram: the perfgate latency family's substrate
# ----------------------------------------------------------------------


def test_histogram_percentiles_are_deterministic_bucket_edges():
    h = LatencyHistogram()
    for ms in (1, 1, 2, 4, 8, 100):
        h.record(ms / 1e3)
    # every percentile is the UPPER edge of the covering bucket — an
    # integer from the fixed table, never an interpolated float
    for q in (50, 95, 99):
        assert h.percentile_ns(q) in (
            tracing.BUCKET_BOUNDS_NS + (tracing.OVERFLOW_EDGE_NS,)
        )
    assert h.percentile_ns(50) <= h.percentile_ns(95) <= h.percentile_ns(99)
    assert LatencyHistogram().percentile_ns(50) == 0
    # overflow: something absurd still lands (and reports the edge)
    h.record(10_000.0)
    assert h.percentile_ns(100) == tracing.OVERFLOW_EDGE_NS


def test_histogram_merge_associative_and_order_free():
    samples = [[0.001, 0.002], [0.004, 0.5], [0.032, 0.001, 7.0]]

    def hist(vals):
        h = LatencyHistogram()
        for v in vals:
            h.record(v)
        return h

    a, b, c = (hist(s) for s in samples)
    left = hist(samples[0]).merge(b).merge(c)          # (a+b)+c
    right = hist(samples[1]).merge(c).merge(a)          # (b+c)+a
    assert left.counts == right.counts
    assert left.n == right.n == 7
    assert left.sum_ns == right.sum_ns
    one = hist([v for s in samples for v in s])         # single-process
    assert one.counts == left.counts


def test_histogram_codec_roundtrip_and_scale_rejection():
    h = LatencyHistogram()
    for v in (0.001, 0.016, 2.5):
        h.record(v)
    back = LatencyHistogram.from_dict(json.loads(json.dumps(h.to_dict())))
    assert back.counts == h.counts
    assert back.n == h.n and back.sum_ns == h.sum_ns
    assert back.percentile_ns(95) == h.percentile_ns(95)
    with pytest.raises(ValueError, match="scale"):
        LatencyHistogram.from_dict({"scale": "ns-linear-10", "counts": {}})


def test_perfgate_latency_family_is_pinned_and_gated():
    from ring_attention_tpu.analysis import perfgate

    sig = perfgate.latency_reference_signals()
    # deterministic: no clock, no rng state — two calls are identical
    assert sig == perfgate.latency_reference_signals()
    assert sig["hist_scale"] == tracing.HIST_SCALE
    assert sig["hist_buckets"] == tracing.HIST_BUCKETS
    assert sig["edge_checksum"] == sum(tracing.BUCKET_BOUNDS_NS)
    current = {"latency": sig}
    baseline = {"signals": {"latency": dict(sig)}}
    report = perfgate.check_baseline(current, baseline)
    assert not [f for f in report.findings
                if f.series.startswith("latency.")]
    # a changed bucket rule fails the gate in one line, never silently
    baseline["signals"]["latency"]["p95_ns"] = sig["p95_ns"] * 2
    report = perfgate.check_baseline(current, baseline)
    bad = [f for f in report.findings if f.series == "latency.p95_ns"]
    assert bad, report.findings
    # an absent family is a NOTE (subset run), not a silent pass
    report = perfgate.check_baseline({}, baseline)
    assert any("latency" in n for n in report.notes)


def test_decode_series_registered_direction_lower_is_better():
    from ring_attention_tpu.analysis.perfgate import HARDWARE_SERIES

    for name in ("decode_ms_p50", "decode_ms_p95"):
        key, direction = HARDWARE_SERIES[name]
        assert key == name and direction == -1


# ----------------------------------------------------------------------
# The compiled step is untouched by instrumentation (PR-4 style HLO pin)
# ----------------------------------------------------------------------


def test_tracer_adds_zero_collectives_to_train_step(tmp_path, monkeypatch):
    """Spans are host-side only: the train step compiled with a live
    tracer installed must issue the byte-identical collective sequence
    as the uninstrumented one."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ring_attention_tpu import RingTransformer, create_mesh
    from ring_attention_tpu.analysis.contracts import hlo_collective_sequence
    from ring_attention_tpu.utils import make_train_step

    mesh = create_mesh(ring_size=4)
    model = RingTransformer(
        num_tokens=64, dim=32, depth=1, heads=4, dim_head=8, causal=True,
        striped=True, bucket_size=8, mesh=mesh, use_ring=True,
    )
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (2, 64)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), toks, return_loss=True)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    step = make_train_step(
        lambda p, t: model.apply(p, t, return_loss=True), opt
    )
    args = (params, opt_state, toks)

    txt_base = jax.jit(step).lower(*args).compile().as_text()
    tracing.configure(tmp_path, process=0)
    with tracing.get_tracer().span("train/step", step=0):
        txt_traced = jax.jit(step).lower(*args).compile().as_text()
    seq_base = hlo_collective_sequence(txt_base)
    assert seq_base, "expected ring collectives in the train step"
    assert hlo_collective_sequence(txt_traced) == seq_base


# ----------------------------------------------------------------------
# FlightRecorder carries the span window (telemetry satellite)
# ----------------------------------------------------------------------


def test_flight_dump_carries_active_tracer_spans(tmp_path):
    from ring_attention_tpu.utils import FlightRecorder, read_flight_dump

    tracing.configure(tmp_path / "trace", process=0)
    rec = FlightRecorder(tmp_path / "flight", window=8)
    with tracing.get_tracer().span("ckpt/save", step=4):
        path = rec.dump("chaos", step=4)
    dump = read_flight_dump(path)
    names = {s["name"] for s in dump["spans"]}
    assert "ckpt/save" in names, dump["spans"]
    open_rows = [s for s in dump["spans"] if s["kind"] == "open"]
    assert open_rows and open_rows[-1]["attrs"] == {"step": 4}


# ----------------------------------------------------------------------
# The real thing: two processes, one violent death, one merged timeline
# ----------------------------------------------------------------------


@pytest.mark.slow
def test_cluster_kill_one_worker_merged_timeline(tmp_path):
    """PR 19's acceptance run: a two-process cluster where chaos kills
    worker 1 mid-shard-write.  From the per-process span files ALONE,
    the merged timeline must name the victim (chaos/kill instant on
    process 1), the fault window (chaos/armed -> kill), and the
    survivor's errored barrier wait — and tools/cluster_timeline.py
    renders it.  (The victim is process 1, not 0: process 0 hosts the
    jax.distributed coordinator, and killing the coordinator takes the
    survivor down by heartbeat loss before its barrier wait can even
    time out — the straggler evidence this test pins would never be
    written.)"""
    from ring_attention_tpu.elastic import chaos

    trace = tmp_path / "trace"
    w = chaos.ChaosWorker(
        [sys.executable, WORKER,
         "--ckpt-dir", str(tmp_path / "ck"),
         "--loss-log", str(tmp_path / "loss.jsonl"),
         "--steps", "4", "--save-every", "2", "--sync-save",
         "--barrier-timeout", "15"],
        cwd=REPO, timeout=300,
    )
    rs = w.run_cluster(
        processes=2, devices_per_process=2,
        chaos=[chaos.KILL_MID_SHARD], chaos_process=1,
        extra_env={tracing.TRACE_DIR_ENV: str(trace)},
    )
    assert rs[1].returncode == chaos.CHAOS_EXIT_CODE, (
        rs[1].stdout + rs[1].stderr
    )

    files = sorted(os.listdir(trace))
    assert files == ["spans_p00000.jsonl", "spans_p00001.jsonl"], files
    merged = tracing.merge_trace_dir(trace)
    by_proc_kind = {
        (r["proc"], r.get("kind"), r.get("name")) for r in merged["spans"]
    }
    # victim: the kill instant is durable despite os._exit
    assert (1, "instant", "chaos/kill") in by_proc_kind
    assert (1, "instant", "chaos/armed") in by_proc_kind
    # the survivor's save stalls on the dead peer's barrier: a wait
    # span that ends in an error (BarrierTimeout, or the distributed
    # runtime's own peer-death conversion) is the straggler evidence
    waits = [r for r in merged["spans"]
             if r["proc"] == 0 and r["name"] == "barrier/wait"]
    assert waits, [r["name"] for r in merged["spans"] if r["proc"] == 0]
    assert any((r.get("attrs") or {}).get("error") for r in waits), waits
    # both processes traced real work before the death
    assert (0, "span", "train/step") in by_proc_kind
    assert (1, "span", "train/step") in by_proc_kind

    # the incident reconstruction names all three from the files alone
    report = tracing.reconstruct_incident(merged)
    assert report is not None
    assert "chaos/kill on process 1" in report
    assert "fault window: armed at" in report
    assert "process 0 barrier/wait" in report

    # and the CLI renders the same story
    r = subprocess.run(
        [sys.executable, TIMELINE, str(trace), "--incident"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stderr
    assert "chaos/kill on process 1" in r.stdout
