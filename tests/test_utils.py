"""Checkpoint round-trip, throughput meter, and trace context."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ring_attention_tpu.models import RingTransformer
from ring_attention_tpu.utils import StepTimer, restore_checkpoint, save_checkpoint, trace

VOCAB = 64


def test_checkpoint_roundtrip(rng, tmp_path):
    model = RingTransformer(
        num_tokens=VOCAB, dim=16, depth=1, heads=2, dim_head=8,
        causal=True, bucket_size=8, use_ring=False,
    )
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 8)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    state = {"params": params, "step": jnp.int32(17)}

    path = tmp_path / "ckpt"
    save_checkpoint(path, state)

    template = {
        "params": model.init(jax.random.PRNGKey(1), tokens),  # different values
        "step": jnp.int32(0),
    }
    restored = restore_checkpoint(path, template)
    assert int(restored["step"]) == 17
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_leaves_with_path(restored["params"]),
        jax.tree_util.tree_leaves_with_path(params),
    ):
        np.testing.assert_array_equal(a, b, err_msg=str(ka))

    # resumed model produces identical outputs
    np.testing.assert_allclose(
        model.apply(restored["params"], tokens), model.apply(params, tokens)
    )


def test_step_timer():
    t = StepTimer(tokens_per_step=100)
    for _ in range(3):
        t.step(jnp.ones(()))
    assert t.steps_per_sec > 0
    assert t.tokens_per_sec == 100 * t.steps_per_sec


def test_trace_context(tmp_path):
    """XProf trace context manager writes a profile directory."""
    logdir = str(tmp_path / "profile")
    with trace(logdir):
        jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))).block_until_ready()
    assert os.path.isdir(logdir) and os.listdir(logdir)
