"""Checkpoint round-trip, throughput meter, and trace context."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ring_attention_tpu.models import RingTransformer
from ring_attention_tpu.utils import StepTimer, restore_checkpoint, save_checkpoint, trace

VOCAB = 64


def test_checkpoint_roundtrip(rng, tmp_path):
    model = RingTransformer(
        num_tokens=VOCAB, dim=16, depth=1, heads=2, dim_head=8,
        causal=True, bucket_size=8, use_ring=False,
    )
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 8)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    state = {"params": params, "step": jnp.int32(17)}

    path = tmp_path / "ckpt"
    save_checkpoint(path, state)

    template = {
        "params": model.init(jax.random.PRNGKey(1), tokens),  # different values
        "step": jnp.int32(0),
    }
    restored = restore_checkpoint(path, template)
    assert int(restored["step"]) == 17
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_leaves_with_path(restored["params"]),
        jax.tree_util.tree_leaves_with_path(params),
    ):
        np.testing.assert_array_equal(a, b, err_msg=str(ka))

    # resumed model produces identical outputs
    np.testing.assert_allclose(
        model.apply(restored["params"], tokens), model.apply(params, tokens)
    )


def test_step_timer():
    t = StepTimer(tokens_per_step=100)
    for _ in range(3):
        t.step(jnp.ones(()))
    assert t.steps_per_sec > 0
    assert t.tokens_per_sec == 100 * t.steps_per_sec


def test_trace_context(tmp_path):
    """XProf trace context manager writes a profile directory."""
    logdir = str(tmp_path / "profile")
    with trace(logdir):
        jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))).block_until_ready()
    assert os.path.isdir(logdir) and os.listdir(logdir)


def test_make_train_step_accumulation_matches_full_batch(rng):
    """accum_steps=N must produce the same update as one full-batch step
    (same averaged gradient into the same optimizer) up to float assoc."""
    import optax

    from ring_attention_tpu.utils import make_train_step

    w = {"w": jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)}
    x = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)

    def loss_fn(params, x, y):
        return jnp.mean((x @ params["w"] - y) ** 2)

    opt = optax.adam(1e-2)
    full = jax.jit(make_train_step(loss_fn, opt))
    accum = jax.jit(make_train_step(loss_fn, opt, accum_steps=4))

    p1, s1, l1 = full(w, opt.init(w), x, y)
    p2, s2, l2 = accum(w, opt.init(w), x, y)
    np.testing.assert_allclose(l1, l2, rtol=1e-6)
    np.testing.assert_allclose(p1["w"], p2["w"], atol=1e-6)

    # and it actually trains through the real model
    model = RingTransformer(
        num_tokens=64, dim=16, depth=1, heads=2, dim_head=8, causal=True,
        bucket_size=4, use_ring=False,
    )
    tokens = jnp.asarray(rng.integers(0, 64, (4, 17)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens, return_loss=True)
    step = jax.jit(make_train_step(
        lambda p, t: model.apply(p, t, return_loss=True), opt, accum_steps=2
    ))
    state = opt.init(params)
    losses = []
    for _ in range(3):
        params, state, loss = step(params, state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_make_train_step_rejects_ragged_accum(rng):
    import optax

    from ring_attention_tpu.utils import make_train_step

    step = make_train_step(lambda p, x: jnp.sum(p["w"] * x), optax.sgd(1e-2),
                           accum_steps=3)
    w = {"w": jnp.ones((4,), jnp.float32)}
    with pytest.raises(ValueError, match="not divisible"):
        step(w, optax.sgd(1e-2).init(w), jnp.ones((4, 4)))


def test_shard_optimizer_state_over_data_axis(rng):
    """ZeRO-1 sharding: adam moments spread over the data axis, step
    counter replicated; the sharded-state step still matches replicated."""
    import optax

    from ring_attention_tpu.parallel import create_mesh
    from ring_attention_tpu.utils import make_train_step, shard_optimizer_state

    mesh = create_mesh(ring_size=4, data_size=2)
    w = {"w": jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)}
    x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)

    def loss_fn(params, x, y):
        return jnp.mean((x @ params["w"] - y) ** 2)

    opt = optax.adam(1e-2)
    step = make_train_step(loss_fn, opt)

    state0 = opt.init(w)
    sharded0 = shard_optimizer_state(state0, mesh)
    mu = sharded0[0].mu["w"]
    assert "data" in str(mu.sharding), mu.sharding

    @jax.jit
    def sharded_step(params, opt_state, x, y):
        params, opt_state, loss = step(params, opt_state, x, y)
        return params, shard_optimizer_state(opt_state, mesh), loss

    p_ref, s_ref, l_ref = jax.jit(step)(w, state0, x, y)
    p_sh, s_sh, l_sh = sharded_step(w, sharded0, x, y)
    np.testing.assert_allclose(l_ref, l_sh, rtol=1e-6)
    np.testing.assert_allclose(p_ref["w"], p_sh["w"], atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(s_ref[0].mu["w"]), np.asarray(s_sh[0].mu["w"]), atol=1e-6
    )
