"""The static-analysis subsystem, tier-1: contracts, lint, retrace sentinel.

Three layers of coverage:

  - **positive contracts**: every sequence-parallel strategy's compiled
    collective signature matches the declarative table on CPU meshes —
    the generalized replacement for the old one-off HLO pins;
  - **negative toys**: deliberately broken functions (an accidental
    all-gather in a ring hot path, a collective under ``lax.cond``, a
    retrace-per-step static arg, a compat-shim bypass) must each fail
    their pass with a one-line diagnostic naming the violated rule;
  - **self-runs**: the repo lint over ``ring_attention_tpu/`` and the f32
    accumulator audit pin ZERO violations — the package stays clean by
    construction.
"""

import textwrap

import jax
import jax.numpy as jnp
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from ring_attention_tpu.analysis import (
    RetraceError,
    assert_compiles_once,
    audit_accumulator_dtypes,
    lint_package,
    lint_source,
)
from ring_attention_tpu.analysis import contracts
from ring_attention_tpu.parallel.mesh import SEQ_AXIS, create_mesh
from ring_attention_tpu.parallel.ring import ring_flash_attention
from ring_attention_tpu.utils import compat


# ----------------------------------------------------------------------
# Positive contracts: the strategy matrix on CPU meshes
# ----------------------------------------------------------------------


def _assert_ok(reports):
    bad = [v for r in reports for v in r.violations]
    assert not bad, "\n".join(bad)


@pytest.mark.parametrize("strategy", ["ring", "zigzag", "ulysses", "hybrid"])
def test_contract_fwd_and_bwd(devices, strategy):
    """Forward AND backward collective counts, axis discipline, and the
    no-undeclared-collective rule on the canonical 8-device mesh."""
    _assert_ok(contracts.check_strategy(strategy))


def test_contract_counter(devices):
    """The TokenRing counter-rotation row: exact hop counts fwd AND bwd
    from compiled HLO, permute pairs in BOTH ring directions (the
    both-directions rule), zero undeclared collective kinds, and the
    scan-multiplied jaxpr counts — all on 8 virtual CPU devices."""
    _assert_ok(contracts.check_strategy("counter"))
    _assert_ok(contracts.check_scan_contract("counter"))


@pytest.mark.parametrize(
    "strategy", ["ring_compressed", "counter_compressed"]
)
def test_contract_compressed(devices, strategy):
    """The int8-compressed rows: compressed bytes/hop pinned from the
    traced ppermute avals (the hop-bytes rule) plus forward HLO counts;
    the fwd+bwd hop counts are pinned at the jaxpr level by the scan
    contract (backward recomputes from exact residuals, so its HLO is
    the ring/counter contract already compiled above — kept out of the
    fast tier; tools/check_contracts.py --strategy all runs it)."""
    _assert_ok(contracts.check_strategy(strategy, directions=("fwd",)))
    _assert_ok(contracts.check_scan_contract(strategy))


def test_counter_collective_budget(devices):
    """Acceptance: the counter-rotated step issues NO MORE collectives
    than the unidirectional baseline, proven from compiled HLO — fwd pays
    one extra (the out/lse catch-up: ring vs ring-1) and the resident-KV
    backward repays it (2*ring vs 3*ring-2 per step)."""
    report = contracts.check_counter_collective_budget()
    assert report.ok, "\n".join(report.violations)
    ring = report.dims["ring"]
    assert report.counts["counter_step"] == 2 * ring
    assert report.counts["baseline_step"] == 3 * ring - 2
    assert report.counts["counter_step"] < report.counts["baseline_step"]


def test_counter_contract_catches_missing_direction(devices):
    """The both-directions rule is live: verifying the UNIDIRECTIONAL
    ring's HLO against the counter contract (which demands permute pairs
    in both ring directions) must fail naming the rule."""
    mesh = contracts.default_mesh("ring")
    fn, args, dims = contracts.build_entry("ring", mesh)
    txt = compat.jit(fn).lower(*args).compile().as_text()
    violations = contracts.verify_hlo(
        "counter", "fwd", txt, dims, tuple(mesh.shape.values()),
        list(mesh.shape.keys()),
    )
    assert any("both-directions" in v for v in violations), violations


@pytest.mark.parametrize("strategy", ["striped", "ulysses_gqa", "tree_decode"])
def test_contract_fwd_only(devices, strategy):
    """Single-direction strategies (striped shares the ring's backward
    formula — its forward already pins the permutation-vs-count claim)."""
    _assert_ok(contracts.check_strategy(strategy, directions=("fwd",)))


def test_contract_ring_on_data_parallel_mesh(devices):
    """A (data=2, seq=4) mesh: the ppermute pairs must keep the data
    coordinate fixed — the axis rule with a non-trivial second axis."""
    _assert_ok(contracts.check_strategy(
        "ring", create_mesh(ring_size=4, data_size=2), directions=("fwd",),
    ))


def test_contract_hybrid_alternate_factoring(devices):
    """ring=2 x ulysses=4: the other 8-device factoring (the table's count
    expressions must track the mesh, not hard-code 4x2)."""
    _assert_ok(contracts.check_strategy(
        "hybrid", create_mesh(ulysses_size=4, ring_size=2),
        directions=("fwd",),
    ))


def test_hybrid_hop_reduction_relation(devices):
    """Acceptance: the hybrid contract PROVES ulysses-x fewer ring hops
    than the pure ring at equal world size, from two compiled programs."""
    report = contracts.check_hybrid_hop_reduction(world=8, ulysses=2)
    assert report.ok, "\n".join(report.violations)
    assert report.counts == {"hybrid_hops": 3, "pure_ring_hops": 7}


@pytest.mark.parametrize("strategy", ["ring", "hybrid"])
def test_scan_contract(devices, strategy):
    """The traced (scanned-XLA) side: jaxpr collective counts with scan
    bodies multiplied by trip count.  No XLA compile — make_jaxpr only."""
    _assert_ok(contracts.check_scan_contract(strategy))


def test_contract_table_is_documentation():
    """The count expressions evaluate for arbitrary dims — the table can
    be rendered straight into docs and stays arithmetic-only."""
    dims = {"data": 1, "ring": 16, "ulysses": 4, "world": 64, "passes": 16}
    assert contracts.expected_counts("ring", "fwd", dims) == {
        "collective-permute": 15,
    }
    assert contracts.expected_counts("ring", "fwdbwd", dims) == {
        "collective-permute": 46,  # (ring-1 fwd) + (ring-1 kv + ring dkv bwd)
    }
    assert contracts.expected_counts("hybrid", "fwd", dims) == {
        "all-to-all": 4, "collective-permute": 15,
    }


# ----------------------------------------------------------------------
# Negative toys: each pass must fail loudly, one line, naming its rule
# ----------------------------------------------------------------------


def test_accidental_all_gather_fails_contract(devices):
    """A ring entry that also all-gathers K (the exact regression the
    global no-undeclared-gather rule exists for) must fail with a one-line
    diagnostic naming the collective-contract rule."""
    mesh = create_mesh(ring_size=8)
    spec = P("data", None, "seq", None)

    def leaky(q, k, v):
        out = ring_flash_attention(
            q, k, v, None, SEQ_AXIS, causal=True, bucket_size=4,
            impl="pallas",
        )
        # accidental O(seq) activation gather in the hot path
        k_all = lax.all_gather(k, SEQ_AXIS, axis=2, tiled=True)
        return out + k_all.mean() * 1e-9

    fn = compat.shard_map(leaky, mesh=mesh, in_specs=(spec,) * 3,
                          out_specs=spec, check_vma=False)
    x = jnp.ones((1, 8, 64, 8), jnp.float32)
    txt = compat.jit(fn).lower(x, x, x).compile().as_text()
    dims = {"data": 1, "ring": 8, "ulysses": 1, "world": 8, "passes": 8}
    violations = contracts.verify_hlo(
        "ring", "fwd", txt, dims, mesh_shape=(1, 8),
        axis_names=["data", "seq"],
    )
    assert len(violations) == 1
    line = violations[0]
    assert "\n" not in line
    assert "all-gather" in line and "[rule: collective-contract]" in line


def test_collective_inside_cond_fails(devices):
    """A ppermute under lax.cond (a data-dependent collective schedule —
    the SPMD deadlock hazard) is caught from jaxpr structure alone."""
    mesh = create_mesh(ring_size=8)
    spec = P("data", None, "seq", None)

    def divergent(q):
        rank = lax.axis_index(SEQ_AXIS)
        perm = [(j, (j + 1) % 8) for j in range(8)]
        return lax.cond(
            rank % 2 == 0,
            lambda x: lax.ppermute(x, SEQ_AXIS, perm),
            lambda x: x,
            q,
        )

    fn = compat.shard_map(divergent, mesh=mesh, in_specs=(spec,),
                          out_specs=spec, check_vma=False)
    x = jnp.ones((1, 8, 64, 8), jnp.float32)
    jc = contracts.jaxpr_collectives(jax.make_jaxpr(fn)(x))
    assert jc.in_cond == ["ppermute"]


def test_collective_inside_while_fails(devices):
    """A ppermute under lax.while_loop: the trip count is unknown
    statically, so the checker must flag it (never undercount it)."""
    mesh = create_mesh(ring_size=8)
    spec = P("data", None, "seq", None)

    def dynamic(q):
        perm = [(j, (j + 1) % 8) for j in range(8)]
        return lax.while_loop(
            lambda carry: carry[1] < 3,
            lambda carry: (lax.ppermute(carry[0], SEQ_AXIS, perm),
                           carry[1] + 1),
            (q, 0),
        )[0]

    fn = compat.shard_map(dynamic, mesh=mesh, in_specs=(spec,),
                          out_specs=spec, check_vma=False)
    x = jnp.ones((1, 8, 64, 8), jnp.float32)
    jc = contracts.jaxpr_collectives(jax.make_jaxpr(fn)(x))
    assert jc.in_while == ["ppermute"] and jc.dynamic


def test_replica_groups_iota_form_parsed():
    """The iota (v2) replica_groups spelling some XLA builds print must
    parse to the same groups as the brace form — and an unknown format
    must surface as a violation, never a silent pass."""
    brace = "all-to-all.1 = f32[] all-to-all(x), replica_groups={{0,2},{1,3}}"
    iota = "all-to-all.1 = f32[] all-to-all(x), replica_groups=[2,2]<=[4]"
    iota_t = ("all-to-all.1 = f32[] all-to-all(x), "
              "replica_groups=[2,2]<=[2,2]T(1,0)")
    assert contracts._parse_replica_groups(brace) == [[0, 2], [1, 3]]
    assert contracts._parse_replica_groups(iota) == [[0, 1], [2, 3]]
    assert contracts._parse_replica_groups(iota_t) == [[0, 2], [1, 3]]
    assert contracts._parse_replica_groups("all-to-all.1 = f32[] ...") is None

    weird = "all-to-all.1 = f32[] all-to-all(x), replica_groups=<opaque>"
    out = contracts.check_groups_axis(weird, "all-to-all", (2, 2), 1, "seq")
    assert len(out) == 1 and "unrecognized replica_groups" in out[0]
    # and the iota spelling passes/fails the axis rule like the brace one:
    # groups [[0,1],[2,3]] on a (2, 2) mesh span exactly axis 1
    assert contracts.check_groups_axis(iota, "all-to-all", (2, 2), 1, "seq") == []
    assert contracts.check_groups_axis(iota, "all-to-all", (2, 2), 0, "data")


def test_retrace_per_step_fails():
    """A static arg that changes per step forces a recompile every call;
    the sentinel names the entry point and the compile-once rule."""
    bad = compat.jit(lambda x, n: x * n, static_argnums=(1,))
    with pytest.raises(RetraceError) as err:
        assert_compiles_once(bad, lambda step: (jnp.ones(8), step),
                             steps=3, label="toy_step")
    line = str(err.value)
    assert "\n" not in line
    assert "toy_step" in line and "[rule: compile-once]" in line
    assert "3 compilations" in line


def test_prewarmed_other_shape_not_charged():
    """A cache entry from an earlier call at a DIFFERENT shape must not
    count against the loop (the sentinel audits this loop's compiles, not
    the callable's history); same-shape pre-warm is a healthy 0."""
    f = compat.jit(lambda x: x * 2)
    f(jnp.ones(4))  # pre-warm at another shape
    assert assert_compiles_once(f, lambda s: (jnp.ones(8),), steps=3) == 1
    assert assert_compiles_once(f, lambda s: (jnp.ones(8),), steps=3) == 0


def test_entry_point_compiles_once():
    """A real entry point (flash_attention) through the sentinel: three
    same-shape steps with fresh arrays, exactly one compilation."""
    from functools import partial

    from ring_attention_tpu.ops.flash import flash_attention

    step = compat.jit(partial(flash_attention, causal=True, bucket_size=16))

    def make_args(step_i):
        x = jnp.full((1, 2, 32, 8), 1.0 + step_i, jnp.float32)
        return (x, x, x)

    assert assert_compiles_once(step, make_args, steps=3) == 1


def test_shim_bypass_fails_lint():
    """The three shim-bypass spellings each produce exactly one RA001/2."""
    src = textwrap.dedent("""
        import jax
        from jax.experimental.shard_map import shard_map

        def f(fn, mesh, specs):
            return jax.experimental.shard_map.shard_map(
                fn, mesh=mesh, in_specs=specs, out_specs=specs)

        g = jax.jit(lambda x: x)
    """)
    violations = lint_source(src, "ring_attention_tpu/parallel/toy.py")
    rules = [v.rule for v in violations]
    assert rules.count("RA001") == 2 and rules.count("RA002") == 1
    for v in violations:
        assert "\n" not in str(v)
        assert "compat" in v.message


def test_lint_toy_violations_each_rule():
    """One toy module tripping RA003-RA007, each a one-line diagnostic."""
    src = textwrap.dedent("""
        import time
        from jax import lax
        from jax.experimental import pallas as pl

        def launch(x, kernel, spec):
            return pl.pallas_call(kernel, out_shape=spec)(x)

        def rotate(x):
            return lax.ppermute(x, "seq", [(0, 1)])

        def stamp(x):
            print("step", time.time())
            return x

        def attention(q, k, v):
            return q
    """)
    violations = lint_source(src, "ring_attention_tpu/ops/toy.py")
    rules = sorted(v.rule for v in violations)
    assert rules == ["RA003", "RA004", "RA005", "RA006", "RA007"]


def test_lint_ra008_observe_guard_and_unit_suffix():
    """RA008: a library-level ``Telemetry.observe`` outside a
    ``collecting()`` block silently drops its scalar; an unsuffixed
    metric name has no unit.  Both flag; the guarded, suffixed form and
    the reasoned allow are clean."""
    bad = textwrap.dedent("""
        from ring_attention_tpu.utils.telemetry import telemetry

        def f(x):
            telemetry.observe("kv_hop", x)
            return x
    """)
    violations = lint_source(bad, "ring_attention_tpu/parallel/toy.py")
    assert [v.rule for v in violations] == ["RA008", "RA008"]
    assert any("collecting()" in v.message for v in violations)
    assert any("unit" in v.message for v in violations)
    good = textwrap.dedent("""
        from ring_attention_tpu.utils.telemetry import telemetry

        def f(x):
            with telemetry.collecting() as col:
                telemetry.observe("kv_hop_bytes", x)
            return x, col.values()
    """)
    assert lint_source(good, "ring_attention_tpu/parallel/toy.py") == []
    allowed = textwrap.dedent("""
        from ring_attention_tpu.utils.telemetry import telemetry

        def f(x):
            telemetry.observe("kv_hop", x)  # ra: allow(RA008 collected by caller at this trace level; name pinned by dashboard)
            return x
    """)
    assert lint_source(allowed, "ring_attention_tpu/parallel/toy.py") == []


def test_lint_pragma_silences_with_reason():
    src = 'from jax import lax\n' \
          'def f(x):\n' \
          '    return lax.psum(x, "seq")  # ra: allow(RA004 toy reason)\n'
    assert lint_source(src, "ring_attention_tpu/parallel/toy.py") == []
    bare = src.replace(" toy reason", "")
    violations = lint_source(bare, "ring_attention_tpu/parallel/toy.py")
    assert len(violations) == 1 and "reason is mandatory" in violations[0].message


def test_lint_named_scope_satisfies_ra004():
    src = textwrap.dedent("""
        import jax
        from jax import lax

        def f(x):
            with jax.named_scope("toy/rotate"):
                return lax.ppermute(x, "seq", [(0, 1)])
    """)
    assert lint_source(src, "ring_attention_tpu/parallel/toy.py") == []


def test_corrupted_band_table_fails_soundness():
    """A band table missing a live tile (the exact silent-wrong-attention
    regression the prover exists for) fails with a one-line diagnostic
    naming the tile and the soundness rule."""
    import numpy as np

    from ring_attention_tpu.analysis import coverage
    from ring_attention_tpu.ops.pallas_flash import _TF_WORK, band_plan

    n, blk = 32, 8
    plan = band_plan((n, n), (blk, blk), 0)
    truth = coverage.oracle_mask(np.arange(n), np.arange(n), None)
    inst = [coverage.HopInstance(
        rank=0, q_origin=0, kv_origin=0, oracle=truth, static_live=truth,
        hi=0, lo=None, has_work=True, full=False, kpos=np.arange(n),
    )]
    assert coverage.verify_plan(plan, inst, "toy") == []
    flags = plan.flags.copy()
    live = [t for t in range(len(flags)) if flags[t] & _TF_WORK][2]
    flags[live] &= ~_TF_WORK  # drop a live tile from the grid
    violations = coverage.verify_plan(plan._replace(flags=flags), inst,
                                      "toy")
    line = violations[0]
    assert "\n" not in line
    assert "live tile" in line and "[rule: tile-coverage-sound]" in line
    assert "q-tile" in line  # names the offending tile


def test_widened_band_table_fails_tightness():
    """A table built from a too-wide WORK bound visits dead tiles —
    silent perf loss — and fails the tightness rule naming each tile."""
    import numpy as np

    from ring_attention_tpu.analysis import coverage
    from ring_attention_tpu.ops.pallas_flash import band_plan

    n, blk = 32, 8
    truth = coverage.oracle_mask(np.arange(n), np.arange(n), None)
    inst = [coverage.HopInstance(
        rank=0, q_origin=0, kv_origin=0, oracle=truth, static_live=truth,
        hi=0, lo=None, has_work=True, full=False, kpos=np.arange(n),
    )]
    wide = band_plan((n, n), (blk, blk), (blk, 0, 0, 0), windowed=False)
    violations = coverage.verify_plan(wide, inst, "toy")
    assert violations and all("\n" not in v for v in violations)
    assert all("[rule: tile-coverage-tight]" in v for v in violations)
    assert "dead tile" in violations[0]


def test_bf16_accumulator_toy_fails_precision_flow():
    """A bf16 accumulator carried through a scan (the drift bug the f32
    contract forbids) fails the precision-flow pass in one line."""
    from ring_attention_tpu.analysis import dataflow

    def bad(x):
        def body(acc, xi):
            return acc + xi, None
        acc, _ = lax.scan(body, jnp.zeros((8,), jnp.bfloat16), x)
        return acc.astype(jnp.float32).sum()

    violations = dataflow.audit_precision_flow(
        bad, jnp.ones((4, 8), jnp.bfloat16), label="bf16_toy",
    )
    [line] = [v for v in violations if "loop carry" in v]
    assert "\n" not in line
    assert "bf16_toy" in line and "[rule: f32-accumulator-flow]" in line


def test_int8_without_dequant_toy_fails_precision_flow():
    """Quantized int8 content reaching a dot without its scale multiply
    (the hop-compression hazard) is flagged; the real dequant pattern —
    scale multiply first — is clean."""
    from ring_attention_tpu.analysis import dataflow

    y = jnp.ones((8, 8), jnp.float32)

    def no_dequant(xq, y):
        return (xq.astype(jnp.float32) @ y).sum()

    violations = dataflow.audit_precision_flow(
        no_dequant, jnp.ones((8, 8), jnp.int8), y, label="q_toy",
    )
    assert any("[rule: int8-dequant]" in v and "\n" not in v
               for v in violations)

    def dequant(xq, scale, y):
        return ((xq.astype(jnp.float32) * scale) @ y).sum()

    assert dataflow.audit_precision_flow(
        dequant, jnp.ones((8, 8), jnp.int8), jnp.float32(0.1), y,
        label="q_toy",
    ) == []


def test_branch_divergent_collective_toy_fails(devices):
    """A cond whose branches issue DIFFERENT collective sequences (one
    rank ppermutes, the other doesn't — the deadlock) fails the
    divergence checker naming the branch; branches issuing the SAME
    sequence pass — the proof-level upgrade over the PR-5 blanket ban."""
    from ring_attention_tpu.analysis import dataflow

    mesh = create_mesh(ring_size=8)
    spec = P("data", None, "seq", None)
    perm = [(j, (j + 1) % 8) for j in range(8)]

    def divergent(q):
        rank = lax.axis_index(SEQ_AXIS)
        return lax.cond(
            rank % 2 == 0,
            lambda x: lax.ppermute(x, SEQ_AXIS, perm),
            lambda x: x,
            q,
        )

    fn = compat.shard_map(divergent, mesh=mesh, in_specs=(spec,),
                          out_specs=spec, check_vma=False)
    x = jnp.ones((1, 8, 64, 8), jnp.float32)
    [line] = dataflow.check_spmd_divergence(jax.make_jaxpr(fn)(x), "toy")
    assert "\n" not in line
    assert "branch 1" in line
    assert "[rule: branch-collective-divergence]" in line

    def convergent(q):
        rank = lax.axis_index(SEQ_AXIS)
        return lax.cond(
            rank % 2 == 0,
            lambda x: lax.ppermute(x * 2, SEQ_AXIS, perm),
            lambda x: lax.ppermute(x + 1, SEQ_AXIS, perm),
            q,
        )

    fn2 = compat.shard_map(convergent, mesh=mesh, in_specs=(spec,),
                           out_specs=spec, check_vma=False)
    assert dataflow.check_spmd_divergence(jax.make_jaxpr(fn2)(x)) == []


def test_lint_ra009_host_numpy_in_traced_code():
    """RA009: a host numpy call in a traced subpackage flags; the
    reasoned allow and non-traced modules are clean (np.random stays
    RA005's)."""
    import textwrap as tw

    bad = tw.dedent("""
        import numpy as np

        def f(x):
            return np.exp(x)
    """)
    violations = lint_source(bad, "ring_attention_tpu/ops/toy.py")
    assert [v.rule for v in violations] == ["RA009"]
    assert "jnp" in violations[0].message

    allowed = bad.replace(
        "np.exp(x)",
        "np.exp(x)  # ra: allow(RA009 static trace-time constant)",
    )
    assert lint_source(allowed, "ring_attention_tpu/ops/toy.py") == []
    # utils/ is host-side: not in RA009 scope
    assert lint_source(bad, "ring_attention_tpu/utils/toy.py") == []
    rng = "import numpy as np\ndef f():\n    return np.random.rand(3)\n"
    assert [v.rule for v in
            lint_source(rng, "ring_attention_tpu/ops/toy.py")] == ["RA005"]


def test_lint_ra010_grid_seam_bypass():
    """RA010: constructing Pallas grid tables or hop skip-predicates
    outside the band_plan()/mask-algebra seam flags (the bypass that
    would dodge certification); the seam modules themselves, the
    certifier, and a reasoned allow are clean."""
    bad = (
        "from ring_attention_tpu.ops.pallas_flash import _band_tables\n"
        "def my_grid():\n"
        "    return _band_tables(4, 4, 8, 8, (0, 0, 0, 0), False, True)\n"
    )
    violations = lint_source(bad, "ring_attention_tpu/parallel/newpath.py")
    assert [v.rule for v in violations] == ["RA010"]
    assert "band_plan" in violations[0].message
    # hop skip-predicates are part of the seam too
    skip = ("def f(hi, lo):\n"
            "    return _hop_has_work(hi, lo, 16, 16)\n")
    assert [v.rule for v in lint_source(
        skip, "ring_attention_tpu/models/custom.py")] == ["RA010"]
    # the seam's home modules, the algebra, and the certifier are exempt
    for seam in ("ring_attention_tpu/ops/pallas_flash.py",
                 "ring_attention_tpu/parallel/ring.py",
                 "ring_attention_tpu/masks.py",
                 "ring_attention_tpu/analysis/coverage.py"):
        assert lint_source(bad, seam) == [], seam
    allowed = bad.replace(
        "(0, 0, 0, 0), False, True)",
        "(0, 0, 0, 0), False, True)  "
        "# ra: allow(RA010 prototyping a grid the prover covers in-test)",
    )
    assert lint_source(allowed,
                       "ring_attention_tpu/parallel/newpath.py") == []
    bare = bad.replace(
        "(0, 0, 0, 0), False, True)",
        "(0, 0, 0, 0), False, True)  # ra: allow(RA010)",
    )
    [v] = lint_source(bare, "ring_attention_tpu/parallel/newpath.py")
    assert "reason is mandatory" in v.message


def test_lint_ra011_signal_outside_elastic():
    """RA011: signal handlers / process-kill primitives outside the
    elastic runtime or utils/resilience.py flag (an ad-hoc handler
    silently replaces PreemptionGuard's drain); the owning modules and
    a reasoned allow are clean."""
    bad = (
        "import os, signal\n"
        "def install():\n"
        "    signal.signal(signal.SIGTERM, lambda *_: None)\n"
        "def die(pid):\n"
        "    os.kill(pid, 9)\n"
        "    os._exit(1)\n"
    )
    violations = lint_source(bad, "ring_attention_tpu/utils/train.py")
    assert [v.rule for v in violations] == ["RA011"] * 3
    assert "PreemptionGuard" in violations[0].message
    # the owners of preemption semantics are exempt
    for home in ("ring_attention_tpu/elastic/preemption.py",
                 "ring_attention_tpu/elastic/chaos.py",
                 "ring_attention_tpu/utils/resilience.py"):
        assert lint_source(bad, home) == [], home
    allowed = bad.replace(
        "os.kill(pid, 9)",
        "os.kill(pid, 0)  # ra: allow(RA011 liveness probe, signal 0)",
    ).replace(
        "signal.signal(signal.SIGTERM, lambda *_: None)",
        "signal.signal(signal.SIGTERM, h)  "
        "# ra: allow(RA011 restoring a saved handler)",
    ).replace(
        "os._exit(1)",
        "os._exit(1)  # ra: allow(RA011 post-fork child must not atexit)",
    )
    assert lint_source(allowed, "ring_attention_tpu/utils/train.py") == []
    bare = bad.replace(
        "os.kill(pid, 9)", "os.kill(pid, 9)  # ra: allow(RA011)"
    )
    assert any("reason is mandatory" in v.message for v in lint_source(
        bare, "ring_attention_tpu/utils/train.py"
    ))


def test_lint_ra013_remote_dma_outside_fused_kernel():
    """RA013: remote-DMA / semaphore primitives outside the fused ring
    kernel module flag with a one-line diagnostic (a second module
    issuing raw semaphore ops can deadlock the ring and invalidates the
    counted contract); the owning module and a reasoned allow are
    clean."""
    bad = (
        "def hop(src, dst, s, r):\n"
        "    copy = pltpu.make_async_remote_copy(src, dst, s, r,\n"
        "                                        device_id=(1,))\n"
        "    barrier = pltpu.get_barrier_semaphore()\n"
        "    pltpu.semaphore_signal(barrier, inc=1, device_id=(0,))\n"
        "    pltpu.semaphore_wait(barrier, 1)\n"
        "    sem = pltpu.SemaphoreType.DMA\n"
    )
    violations = lint_source(bad, "ring_attention_tpu/parallel/newhop.py")
    assert [v.rule for v in violations] == ["RA013"] * 5
    assert "ops/pallas_ring.py" in violations[0].message
    # the fused kernel module IS the seam — provided the function is a
    # declared PROTOCOL row (RA015 fences the seam to the verified table)
    declared = (
        'PROTOCOL = (\n'
        '    {"row": "hop", "fn": "hop", "op": "remote_copy",\n'
        '     "sites": {"dma_start": 1}},\n'
        ')\n' + bad
    )
    assert lint_source(declared, "ring_attention_tpu/ops/pallas_ring.py") == []
    allowed = bad.replace(
        "    pltpu.semaphore_wait(barrier, 1)\n",
        "    pltpu.semaphore_wait(barrier, 1)  "
        "# ra: allow(RA013 local-only probe, no ring peer waits on it)\n",
    )
    assert [v.rule for v in lint_source(
        allowed, "ring_attention_tpu/parallel/newhop.py"
    )] == ["RA013"] * 4
    bare = bad.replace(
        "    barrier = pltpu.get_barrier_semaphore()\n",
        "    barrier = pltpu.get_barrier_semaphore()  # ra: allow(RA013)\n",
    )
    assert any("reason is mandatory" in v.message for v in lint_source(
        bare, "ring_attention_tpu/parallel/newhop.py"
    ))


def test_lint_ra014_raw_clock_outside_tracing_seam():
    """RA014: a raw ``time.*`` clock read in the observability-
    instrumented subpackages (elastic/, utils/) flags — emitted
    timestamps must route through the ``utils/tracing.py`` seam so the
    cluster-timeline merger's clock-offset correction covers them.  The
    seam module itself, a reasoned allow, and out-of-scope packages are
    clean."""
    bad = (
        "import time\n"
        "def stamp():\n"
        "    wall = time.time()\n"
        "    mono = time.monotonic()\n"
        "    return {'time': wall, 'mono': mono}\n"
    )
    violations = lint_source(bad, "ring_attention_tpu/elastic/toy.py")
    assert [v.rule for v in violations] == ["RA014"] * 2
    assert "utils/tracing.py" in violations[0].message
    assert [v.rule for v in lint_source(
        bad, "ring_attention_tpu/utils/toy.py"
    )] == ["RA014"] * 2
    # the seam module IS the allowed home of the raw reads
    assert lint_source(bad, "ring_attention_tpu/utils/tracing.py") == []
    # models/ etc. stay RA005's concern, not RA014's
    assert [v.rule for v in lint_source(
        bad, "ring_attention_tpu/models/toy.py"
    )] == ["RA005"] * 2
    allowed = bad.replace(
        "time.monotonic()",
        "time.monotonic()  # ra: allow(RA014 deadline arithmetic, "
        "not an emitted timestamp)",
    )
    assert [v.rule for v in lint_source(
        allowed, "ring_attention_tpu/elastic/toy.py"
    )] == ["RA014"]
    bare = bad.replace(
        "time.monotonic()", "time.monotonic()  # ra: allow(RA014)"
    )
    assert any("reason is mandatory" in v.message for v in lint_source(
        bare, "ring_attention_tpu/elastic/toy.py"
    ))


# ----------------------------------------------------------------------
# Self-runs: the package itself is clean
# ----------------------------------------------------------------------


def test_lint_self_run_zero_violations():
    """The whole package tree passes its own lint — every fix that landed
    with these rules stays landed."""
    violations = lint_package()
    assert violations == [], "\n".join(str(v) for v in violations)


def test_accumulator_dtype_audit_clean():
    """Both flash paths accumulate (acc, m, l) in f32 under bf16 inputs."""
    assert audit_accumulator_dtypes() == []


def test_collective_fingerprint_shape(devices):
    """The bench-JSON fingerprint: per-strategy fwd collective counts,
    cheap enough to ride along every bench round.  Since PR 18 the ring
    row brings the fused-ring rows with it: the in-kernel remote-DMA /
    semaphore counts from the lowered module, with ``ppermute: 0`` — the
    launch-free-hops pin — for plain and int8-fed variants."""
    fp = contracts.collective_fingerprint(strategies=("ring",))
    fused_counts = dict(sorted(contracts.FUSED_RING_EXPECTED.items()))
    assert fp == {
        "ring": {"ppermute": 7},
        "fused_ring": fused_counts,
        "fused_ring_q8": fused_counts,
        "contract_ok": True,
    }


# ----------------------------------------------------------------------
# DCN isolation: the pod-scale placement contract (PR 15)
# ----------------------------------------------------------------------


def test_contract_dcn_isolation(devices):
    """The hierarchical-mesh rows: ring and hybrid compiled over a
    ``(dcn_data, ...)`` mesh hold their ordinary collective contracts
    AND provably issue zero sequence-parallel collectives over the dcn
    axis — from optimized HLO and the jaxpr walk, fwd and fwdbwd."""
    _assert_ok(contracts.check_dcn_isolation())


def test_dcn_isolation_negative_toy(devices):
    """A deliberate collective OVER the dcn axis must be flagged by both
    halves of the proof — the HLO permute-pair scan and the traced
    axis-name walk — each with a one-line diagnostic naming the rule."""
    from ring_attention_tpu.parallel.mesh import DCN_DATA_AXIS, create_mesh

    mesh = create_mesh(dcn_data_size=2, ring_size=4)

    def bad(x):
        # a "ring hop" straight over the slow inter-slice links
        return lax.ppermute(
            x, DCN_DATA_AXIS, [(i, (i + 1) % 2) for i in range(2)]
        )

    fn = compat.shard_map(
        bad, mesh=mesh, in_specs=P(DCN_DATA_AXIS),
        out_specs=P(DCN_DATA_AXIS),
    )
    x = jnp.arange(8.0)
    txt = compat.jit(fn).lower(x).compile().as_text()
    violations = contracts.hlo_dcn_isolation(
        txt, tuple(mesh.shape.values()), list(mesh.shape.keys())
    )
    assert violations, "cross-dcn permute escaped the HLO scan"
    assert all("dcn-isolation" in v for v in violations)
    axes_by_prim = contracts.jaxpr_collective_axis_names(
        jax.make_jaxpr(fn)(x)
    )
    assert DCN_DATA_AXIS in axes_by_prim.get("ppermute", set())
    # a mesh with no dcn axis has nothing to prove — reported, not passed
    flat = create_mesh(ring_size=8)
    note = contracts.hlo_dcn_isolation(
        txt, tuple(flat.shape.values()), list(flat.shape.keys())
    )
    assert note and "nothing to prove" in note[0]


def test_dcn_collective_fingerprint_deterministic(devices):
    """The bench phase-0e payload: per-row fwd collective counts over
    the hierarchical mesh + the machine-checked verdict, deterministic
    across calls (it rides the exact perf-gate family)."""
    fp = contracts.dcn_collective_fingerprint()
    assert fp["dcn_ok"] is True
    assert fp["ring_dcn"] == {"ppermute": 3}
    assert "hybrid_dcn" in fp
    assert contracts.dcn_collective_fingerprint() == fp
