"""Collective helpers: variable-size gather, rank splitting, batch folding.

Covers the analogues of the reference's ``distributed.py`` surface —
especially the static-shape variable-size gather replacing
``all_gather_variable_dim`` (ref ``distributed.py:58-84``), which the
reference exercises via per-rank batch sizes in ``assert_attn.py:81-82``.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from ring_attention_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from ring_attention_tpu.parallel import create_mesh
from ring_attention_tpu.parallel.collectives import (
    all_gather_variable,
    compact_masked,
    fold_batch_into_seq,
    gather_sizes,
    split_by_rank,
    unfold_seq_into_batch,
)


@pytest.fixture(scope="module")
def mesh():
    return create_mesh(ring_size=8, data_size=1)


def test_all_gather_variable(rng, mesh):
    """Per-rank used lengths rank+1 (the reference's variable batch test
    pattern): gathered data is in rank order, mask selects exactly the
    used entries."""
    max_size, world = 8, 8
    data = jnp.asarray(rng.standard_normal((world * max_size, 4)), jnp.float32)
    lengths_global = jnp.arange(1, world + 1, dtype=jnp.int32)  # rank r uses r+1

    def core(x, lengths):
        rank = jax.lax.axis_index("seq")
        gathered, mask = all_gather_variable(
            x, lengths[rank], "seq", max_size=max_size
        )
        return gathered, mask

    g, m = shard_map(
        core, mesh=mesh,
        in_specs=(P("seq", None), P()),
        out_specs=(P(None, None), P()),
        check_vma=False,  # outputs identical on all devices post-gather
    )(data, lengths_global)

    np.testing.assert_allclose(g, data)
    expect_mask = np.concatenate(
        [np.arange(max_size) < (r + 1) for r in range(world)]
    )
    np.testing.assert_array_equal(np.asarray(m), expect_mask)


def test_compact_masked(rng, mesh):
    """compact_masked on a variable gather reproduces the reference's dense
    concatenated result (ref ``distributed.py:77-83``): each rank's used
    prefix, in rank order, with all padding dropped."""
    max_size, world = 8, 8
    data = jnp.asarray(rng.standard_normal((world * max_size, 4)), jnp.float32)
    lengths_global = jnp.arange(1, world + 1, dtype=jnp.int32)

    def core(x, lengths):
        rank = jax.lax.axis_index("seq")
        return all_gather_variable(x, lengths[rank], "seq", max_size=max_size)

    g, m = shard_map(
        core, mesh=mesh,
        in_specs=(P("seq", None), P()),
        out_specs=(P(None, None), P()),
        check_vma=False,
    )(data, lengths_global)

    dense = compact_masked(g, m)
    expect = np.concatenate(
        [np.asarray(data)[r * max_size : r * max_size + r + 1] for r in range(world)]
    )
    assert dense.shape == (int(lengths_global.sum()), 4)
    np.testing.assert_allclose(np.asarray(dense), expect)

    with pytest.raises(ValueError, match="mask shape"):
        compact_masked(g, m[:-1])


def test_split_by_rank(rng, mesh):
    x = jnp.asarray(rng.standard_normal((64, 3)), jnp.float32)

    out = shard_map(
        partial(split_by_rank, axis_name="seq"),
        mesh=mesh, in_specs=P(), out_specs=P("seq", None),
    )(x)
    np.testing.assert_allclose(out, x)


def test_gather_sizes(mesh):
    def core(_):
        rank = jax.lax.axis_index("seq")
        return gather_sizes(rank * 2, "seq")

    sizes = shard_map(
        core, mesh=mesh, in_specs=P("seq"), out_specs=P(None),
        check_vma=False,
    )(jnp.zeros(8))
    np.testing.assert_array_equal(np.asarray(sizes), np.arange(8) * 2)


def test_fold_roundtrip(rng):
    x = jnp.asarray(rng.standard_normal((6, 10, 3)), jnp.float32)
    y = fold_batch_into_seq(x, 3)
    assert y.shape == (2, 30, 3)
    np.testing.assert_array_equal(unfold_seq_into_batch(y, 3), x)


def test_shard_batch_places_on_mesh(rng):
    """shard_batch: host batch lands with batch over data, seq over ring;
    a model forward consumes it without resharding transfers."""
    from ring_attention_tpu.parallel import create_mesh, shard_batch

    mesh = create_mesh(ring_size=4, data_size=2)
    tokens = jnp.asarray(rng.integers(0, 256, (4, 64)), jnp.int32)
    weights = jnp.asarray(rng.standard_normal((4,)), jnp.float32)
    placed = shard_batch(
        {"tokens": tokens, "weights": weights, "step": 3}, mesh
    )
    from jax.sharding import PartitionSpec as P

    t = placed["tokens"]
    assert t.sharding.spec == P("data", "seq"), t.sharding.spec
    assert placed["weights"].sharding.spec == P("data")
    assert int(placed["step"]) == 3  # scalar leaf replicates
    np.testing.assert_array_equal(np.asarray(t), np.asarray(tokens))
