"""Collective helpers: variable-size gather, rank splitting, batch folding.

Covers the analogues of the reference's ``distributed.py`` surface —
especially the static-shape variable-size gather replacing
``all_gather_variable_dim`` (ref ``distributed.py:58-84``), which the
reference exercises via per-rank batch sizes in ``assert_attn.py:81-82``.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from ring_attention_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from ring_attention_tpu.parallel import create_mesh
from ring_attention_tpu.parallel.collectives import (
    all_gather_variable,
    compact_masked,
    fold_batch_into_seq,
    gather_sizes,
    split_by_rank,
    unfold_seq_into_batch,
)


@pytest.fixture(scope="module")
def mesh():
    return create_mesh(ring_size=8, data_size=1)


def test_all_gather_variable(rng, mesh):
    """Per-rank used lengths rank+1 (the reference's variable batch test
    pattern): gathered data is in rank order, mask selects exactly the
    used entries."""
    max_size, world = 8, 8
    data = jnp.asarray(rng.standard_normal((world * max_size, 4)), jnp.float32)
    lengths_global = jnp.arange(1, world + 1, dtype=jnp.int32)  # rank r uses r+1

    def core(x, lengths):
        rank = jax.lax.axis_index("seq")
        gathered, mask = all_gather_variable(
            x, lengths[rank], "seq", max_size=max_size
        )
        return gathered, mask

    g, m = shard_map(
        core, mesh=mesh,
        in_specs=(P("seq", None), P()),
        out_specs=(P(None, None), P()),
        check_vma=False,  # outputs identical on all devices post-gather
    )(data, lengths_global)

    np.testing.assert_allclose(g, data)
    expect_mask = np.concatenate(
        [np.arange(max_size) < (r + 1) for r in range(world)]
    )
    np.testing.assert_array_equal(np.asarray(m), expect_mask)


def test_compact_masked(rng, mesh):
    """compact_masked on a variable gather reproduces the reference's dense
    concatenated result (ref ``distributed.py:77-83``): each rank's used
    prefix, in rank order, with all padding dropped."""
    max_size, world = 8, 8
    data = jnp.asarray(rng.standard_normal((world * max_size, 4)), jnp.float32)
    lengths_global = jnp.arange(1, world + 1, dtype=jnp.int32)

    def core(x, lengths):
        rank = jax.lax.axis_index("seq")
        return all_gather_variable(x, lengths[rank], "seq", max_size=max_size)

    g, m = shard_map(
        core, mesh=mesh,
        in_specs=(P("seq", None), P()),
        out_specs=(P(None, None), P()),
        check_vma=False,
    )(data, lengths_global)

    dense = compact_masked(g, m)
    expect = np.concatenate(
        [np.asarray(data)[r * max_size : r * max_size + r + 1] for r in range(world)]
    )
    assert dense.shape == (int(lengths_global.sum()), 4)
    np.testing.assert_allclose(np.asarray(dense), expect)

    with pytest.raises(ValueError, match="mask shape"):
        compact_masked(g, m[:-1])


def test_split_by_rank(rng, mesh):
    x = jnp.asarray(rng.standard_normal((64, 3)), jnp.float32)

    out = shard_map(
        partial(split_by_rank, axis_name="seq"),
        mesh=mesh, in_specs=P(), out_specs=P("seq", None),
    )(x)
    np.testing.assert_allclose(out, x)


def test_gather_sizes(mesh):
    def core(_):
        rank = jax.lax.axis_index("seq")
        return gather_sizes(rank * 2, "seq")

    sizes = shard_map(
        core, mesh=mesh, in_specs=P("seq"), out_specs=P(None),
        check_vma=False,
    )(jnp.zeros(8))
    np.testing.assert_array_equal(np.asarray(sizes), np.arange(8) * 2)


def test_fold_roundtrip(rng):
    x = jnp.asarray(rng.standard_normal((6, 10, 3)), jnp.float32)
    y = fold_batch_into_seq(x, 3)
    assert y.shape == (2, 30, 3)
    np.testing.assert_array_equal(unfold_seq_into_batch(y, 3), x)


def test_shard_batch_places_on_mesh(rng):
    """shard_batch: host batch lands with batch over data, seq over ring;
    a model forward consumes it without resharding transfers."""
    from ring_attention_tpu.parallel import create_mesh, shard_batch

    mesh = create_mesh(ring_size=4, data_size=2)
    tokens = jnp.asarray(rng.integers(0, 256, (4, 64)), jnp.int32)
    weights = jnp.asarray(rng.standard_normal((4,)), jnp.float32)
    placed = shard_batch(
        {"tokens": tokens, "weights": weights, "step": 3}, mesh
    )
    from jax.sharding import PartitionSpec as P

    t = placed["tokens"]
    assert t.sharding.spec == P("data", "seq"), t.sharding.spec
    assert placed["weights"].sharding.spec == P("data")
    assert int(placed["step"]) == 3  # scalar leaf replicates
    np.testing.assert_array_equal(np.asarray(t), np.asarray(tokens))


# ----------------------------------------------------------------------
# int8 ring-hop payload quantization (hop_compression="int8")
# ----------------------------------------------------------------------


def test_ring_payload_quant_roundtrip(rng):
    """quantize -> dequantize reconstructs (k, v) within one int8 step of
    the per-(head, token) absmax scale, and the payload is ONE int8 array
    whose last axis carries values + 4 bitcast f32 scale bytes."""
    from ring_attention_tpu.parallel.collectives import (
        dequantize_ring_payload,
        quantize_ring_payload,
    )

    k = jnp.asarray(rng.standard_normal((2, 4, 16, 8)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 4, 16, 8)), jnp.float32)
    payload = quantize_ring_payload(k, v)
    assert payload.dtype == jnp.int8
    assert payload.shape == (2, 2, 4, 16, 8 + 4)
    k2, v2 = dequantize_ring_payload(payload, jnp.float32)
    # one quantization step = scale (absmax/127) per row
    for exact, got in ((k, k2), (v, v2)):
        step = np.asarray(jnp.abs(exact).max(axis=-1)) / 127.0
        err = np.abs(np.asarray(got - exact)).max(axis=-1)
        np.testing.assert_array_less(err, step + 1e-7)


def test_ring_payload_token_slices_share_scales(rng):
    """Slicing the payload along tokens (bidirectional half-streams) keeps
    each row's scale bytes with its values: dequantizing a slice equals
    slicing the dequantization."""
    from ring_attention_tpu.parallel.collectives import (
        dequantize_ring_payload,
        quantize_ring_payload,
    )

    k = jnp.asarray(rng.standard_normal((1, 2, 16, 8)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 2, 16, 8)), jnp.float32)
    payload = quantize_ring_payload(k, v)
    k_half, v_half = dequantize_ring_payload(payload[:, :, :, :8], jnp.float32)
    k_full, v_full = dequantize_ring_payload(payload, jnp.float32)
    np.testing.assert_array_equal(k_half, k_full[:, :, :8])
    np.testing.assert_array_equal(v_half, v_full[:, :, :8])


# ----------------------------------------------------------------------
# Topology-aware ring placement (create_mesh(ring_order="auto"))
# ----------------------------------------------------------------------


class _FakeTpu:
    """Just enough device surface for torus_ring_order."""

    platform = "tpu"

    def __init__(self, coords, core=0):
        self.coords = coords
        self.core_on_chip = core

    def __repr__(self):
        return f"tpu{self.coords}/{self.core_on_chip}"


def test_snake_coords_are_ici_neighbors():
    """Every consecutive pair in the boustrophedon path differs by exactly
    1 in exactly one torus axis — each ring hop is one physical link."""
    from ring_attention_tpu.parallel.mesh import _snake_coords

    for dims in ((4,), (2, 4), (2, 2, 2), (4, 2, 2)):
        path = _snake_coords(dims)
        assert len(path) == int(np.prod(dims))
        assert len(set(path)) == len(path)
        for a, b in zip(path, path[1:]):
            diff = [abs(x - y) for x, y in zip(a, b)]
            assert sum(diff) == 1, f"{a} -> {b} is not one ICI hop"


def test_torus_ring_order_snakes_a_3d_slice():
    """A shuffled 2x2x2 v5p-like slice comes back in snake order: every
    consecutive pair of chips is one link apart (TASP placement)."""
    from ring_attention_tpu.parallel.mesh import torus_ring_order

    devs = [
        _FakeTpu((x, y, z))
        for x in range(2) for y in range(2) for z in range(2)
    ]
    shuffled = [devs[i] for i in (5, 0, 3, 6, 1, 4, 7, 2)]
    ordered = torus_ring_order(shuffled)
    assert ordered is not None and len(ordered) == 8
    for a, b in zip(ordered, ordered[1:]):
        diff = [abs(x - y) for x, y in zip(a.coords, b.coords)]
        assert sum(diff) == 1


def test_torus_ring_order_multicore_chips_adjacent():
    """Chips exposing two cores keep both cores adjacent in the path."""
    from ring_attention_tpu.parallel.mesh import torus_ring_order

    devs = [
        _FakeTpu((x, y), core)
        for x in range(2) for y in range(2) for core in (1, 0)
    ]
    ordered = torus_ring_order(devs)
    assert ordered is not None
    for i in range(0, 8, 2):
        a, b = ordered[i], ordered[i + 1]
        assert a.coords == b.coords and (a.core_on_chip, b.core_on_chip) == (0, 1)


def test_torus_ring_order_falls_back():
    """No coords (CPU) or a sparse slice -> None, so create_mesh uses the
    deterministic flat order instead of a bogus snake."""
    from ring_attention_tpu.parallel.mesh import torus_ring_order

    assert torus_ring_order(jax.devices()) is None  # CPU: no coords
    sparse = [_FakeTpu((0, 0)), _FakeTpu((1, 1))]
    assert torus_ring_order(sparse) is None


def test_create_mesh_ring_order_validation_and_determinism():
    """ring_order accepts only "auto"/"flat"; on CPU both give the same
    deterministic mesh (auto's fallback is the flat sorted order)."""
    with pytest.raises(ValueError, match="ring_order"):
        create_mesh(ring_size=8, ring_order="snake")
    auto = create_mesh(ring_size=8, ring_order="auto")
    flat = create_mesh(ring_size=8, ring_order="flat")
    assert (np.asarray(auto.devices) == np.asarray(flat.devices)).all()
    again = create_mesh(ring_size=8, ring_order="auto")
    assert (np.asarray(auto.devices) == np.asarray(again.devices)).all()
