"""Parity: Pallas flash kernels (interpret mode on CPU) vs the oracle.

The kernels are exercised through the same contract as the XLA blockwise
path: forward outputs, lse, partial merging, and the two-pass backward must
match ``default_attention`` and its autodiff gradients.  On CPU the kernels
run in Pallas interpreter mode; identical code compiles to Mosaic on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ring_attention_tpu.ops import default_attention
from ring_attention_tpu.ops.pallas_flash import (
    finalize_partials,
    merge_partials,
    pallas_flash_attention,
    pallas_flash_partials,
)

ATOL = 2e-5
GRAD_ATOL = 5e-4


def make_qkv(rng, b=2, h=4, hk=None, n=128, d=32):
    hk = hk or h
    q = jnp.asarray(rng.standard_normal((b, h, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_fwd_parity(rng, causal):
    q, k, v = make_qkv(rng)
    ref = default_attention(q, k, v, causal=causal)
    out = pallas_flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_fwd_gqa(rng):
    q, k, v = make_qkv(rng, h=4, hk=2)
    ref = default_attention(q, k, v, causal=True)
    out = pallas_flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_head_chunked_launch_bit_exact(rng):
    """head_chunks splits the launch into per-head-group programs (the
    relay program-size workaround for h=32 @ 262k); heads are independent,
    so outputs AND grads must be bit-identical to the unsplit launch."""
    q, k, v = make_qkv(rng, h=8, hk=4)

    def loss(q, k, v, hc):
        out = pallas_flash_attention(
            q, k, v, causal=True, head_chunks=hc, interpret=True
        )
        return (out * out).sum(), out

    (ref_l, ref_out), ref_grads = jax.value_and_grad(
        loss, argnums=(0, 1, 2), has_aux=True)(q, k, v, None)
    (spl_l, spl_out), spl_grads = jax.value_and_grad(
        loss, argnums=(0, 1, 2), has_aux=True)(q, k, v, 4)
    np.testing.assert_array_equal(spl_out, ref_out)
    for g_ref, g_spl in zip(ref_grads, spl_grads):
        np.testing.assert_array_equal(g_spl, g_ref)

    with pytest.raises(ValueError):
        pallas_flash_attention(
            q, k, v, causal=True, head_chunks=3, interpret=True
        )


def test_fwd_mask(rng):
    q, k, v = make_qkv(rng)
    mask = jnp.asarray(rng.random((2, 128)) > 0.3)
    ref = default_attention(q, k, v, mask)
    out = pallas_flash_attention(q, k, v, mask, interpret=True)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_fwd_softclamp(rng):
    q, k, v = make_qkv(rng)
    ref = default_attention(q, k, v, causal=True, softclamp_value=5.0)
    out = pallas_flash_attention(
        q, k, v, causal=True, softclamp_value=5.0, interpret=True
    )
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_fwd_window(rng):
    q, k, v = make_qkv(rng)
    n, w = 128, 48
    out = pallas_flash_attention(q, k, v, causal=True, window=w, interpret=True)
    i = jnp.arange(n)[:, None]
    j = jnp.arange(n)[None, :]
    band = (j <= i) & (j >= i - (w - 1))
    s = jnp.einsum("bhid,bhjd->bhij", q, k) * (q.shape[-1] ** -0.5)
    ref = jnp.einsum(
        "bhij,bhjd->bhid", jax.nn.softmax(jnp.where(band, s, -1e30), -1), v
    )
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_partials_merge(rng):
    """Two half-KV sweeps merged == one full sweep (the ring-hop contract)."""
    q, k, v = make_qkv(rng)
    scale = q.shape[-1] ** -0.5
    full = pallas_flash_partials(q, k, v, scale=scale, interpret=True)
    left = pallas_flash_partials(
        q, k[:, :, :64], v[:, :, :64], scale=scale, interpret=True
    )
    right = pallas_flash_partials(
        q, k[:, :, 64:], v[:, :, 64:], scale=scale, interpret=True
    )
    merged = merge_partials(left, right)
    out_full, lse_full = finalize_partials(full)
    out_merged, lse_merged = finalize_partials(merged)
    np.testing.assert_allclose(out_merged, out_full, atol=ATOL)
    np.testing.assert_allclose(lse_merged, lse_full, atol=ATOL)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("hk", [4, 2])
def test_grad_parity(rng, causal, hk):
    q, k, v = make_qkv(rng, hk=hk)

    g_ref = jax.grad(
        lambda *a: (default_attention(*a, causal=causal) ** 2).sum(), (0, 1, 2)
    )(q, k, v)
    g_out = jax.grad(
        lambda *a: (
            pallas_flash_attention(*a, causal=causal, interpret=True) ** 2
        ).sum(),
        (0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


def test_grad_softclamp_mask(rng):
    q, k, v = make_qkv(rng)
    mask = jnp.asarray(rng.random((2, 128)) > 0.3)

    g_ref = jax.grad(
        lambda *a: (default_attention(*a, softclamp_value=5.0) ** 2).sum(),
        (0, 1, 2),
    )(q, k, v, mask)
    g_out = jax.grad(
        lambda *a: (
            pallas_flash_attention(
                *a, softclamp_value=5.0, interpret=True
            )
            ** 2
        ).sum(),
        (0, 1, 2),
    )(q, k, v, mask)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


def test_wide_head_dim(rng):
    """dim_head=128 (full lane width) through fwd and bwd kernels."""
    q, k, v = make_qkv(rng, h=2, n=256, d=128)
    ref = default_attention(q, k, v, causal=True)
    out = pallas_flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(out, ref, atol=ATOL)
    g_ref = jax.grad(lambda *a: (default_attention(*a, causal=True) ** 2).sum(), (0, 1, 2))(q, k, v)
    g_out = jax.grad(
        lambda *a: (pallas_flash_attention(*a, causal=True, interpret=True) ** 2).sum(),
        (0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=1e-3, err_msg=f"d{name}")


# ---------------------------------------------------------------------------
# Compacted causal grid: with static band offsets the kernels run on a
# flattened grid of only the active tiles (scalar-prefetched tile tables);
# a traced offset keeps the rectangular grid.  The two grids must agree
# bit-for-bit on every band shape, including fully-empty rows.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "co,wlo,masked",
    [
        (0, None, False),
        (-1, None, False),
        (0, -95, False),
        (-300, None, False),
        (0, None, True),
    ],
    ids=["causal", "striped-flip", "window", "all-empty", "kvmask"],
)
def test_compact_grid_matches_rectangular(rng, co, wlo, masked):
    q, k, v = make_qkv(rng, b=1, h=2, n=256, d=32)
    mask = jnp.asarray(rng.random((1, 256)) > 0.3) if masked else None
    scale = q.shape[-1] ** -0.5

    static = pallas_flash_partials(
        q, k, v, mask, scale=scale, causal_offset=co, window_lo=wlo,
        block_q=64, block_k=64, interpret=True,
    )
    traced = jax.jit(
        lambda q, k, v, o, w: pallas_flash_partials(
            q, k, v, mask, scale=scale, causal_offset=o,
            window_lo=w if wlo is not None else None,
            block_q=64, block_k=64, interpret=True,
        )
    )(q, k, v, jnp.int32(co), jnp.int32(wlo if wlo is not None else 0))
    for a, b, name in zip(static, traced, ("acc", "m", "l")):
        np.testing.assert_array_equal(a, b, err_msg=name)


@pytest.mark.parametrize(
    "co,wlo,masked",
    [(0, None, False), (0, -95, False), (0, None, True)],
    ids=["causal", "window", "kvmask"],
)
def test_compact_grid_backward_matches_rectangular(rng, co, wlo, masked):
    from ring_attention_tpu.ops.pallas_flash import pallas_flash_backward

    q, k, v = make_qkv(rng, b=1, h=4, hk=2, n=256, d=32)
    do = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)
    mask = jnp.asarray(rng.random((1, 256)) > 0.3) if masked else None
    scale = q.shape[-1] ** -0.5
    parts = pallas_flash_partials(
        q, k, v, mask, scale=scale, causal_offset=co, window_lo=wlo,
        block_q=64, block_k=64, interpret=True,
    )
    out, lse = finalize_partials(parts)
    delta = (do * out).sum(-1)

    static = pallas_flash_backward(
        do, q, k, v, lse, delta, mask, scale=scale, causal_offset=co,
        window_lo=wlo, block_q=64, block_k=64, interpret=True,
    )
    traced = jax.jit(
        lambda o, w: pallas_flash_backward(
            do, q, k, v, lse, delta, mask, scale=scale, causal_offset=o,
            window_lo=w if wlo is not None else None,
            block_q=64, block_k=64, interpret=True,
        )
    )(jnp.int32(co), jnp.int32(wlo if wlo is not None else 0))
    for a, b, name in zip(static, traced, ("dq", "dk", "dv")):
        np.testing.assert_array_equal(a, b, err_msg=name)


@pytest.mark.parametrize("outer_is_q", [True, False])
@pytest.mark.parametrize(
    "hi,lo,windowed",
    [(0, 0, False), (-1, 0, False), (64, 0, False), (0, -95, True),
     (-256, 0, False), (0, -31, True)],
)
def test_band_tile_count_matches_tables(hi, lo, windowed, outer_is_q):
    """The closed-form count used for the SMEM cap must equal the real
    table length for every band shape (incl. empty/dummy rows)."""
    from ring_attention_tpu.ops.pallas_flash import (
        _band_tables,
        _band_tile_count,
    )

    args = (4, 4, 64, 64, (hi, hi, lo, lo), windowed, outer_is_q)
    assert _band_tile_count(*args) == _band_tables(*args)[0].shape[0]


def test_compact_table_cap_demotes_to_rectangular(rng, monkeypatch):
    """A static band whose tile tables exceed _MAX_COMPACT_TILES (SMEM
    scalar-prefetch budget) must take the rectangular grid, produce
    identical results fwd and bwd, and WARN about the lost compact grid
    (VERDICT r2 weak #5: the cliff must be observable) — with no warning
    when the compact grid engages."""
    import warnings as _warnings

    import ring_attention_tpu.ops.pallas_flash as pf

    q, k, v = make_qkv(rng, b=1, h=2, n=256, d=32)
    do = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)
    scale = q.shape[-1] ** -0.5

    def run_all():
        parts = pf.pallas_flash_partials(
            q, k, v, scale=scale, causal_offset=0,
            block_q=64, block_k=64, interpret=True,
        )
        out, lse = finalize_partials(parts)
        delta = (do * out).sum(-1)
        grads = pf.pallas_flash_backward(
            do, q, k, v, lse, delta, scale=scale, causal_offset=0,
            block_q=64, block_k=64, interpret=True,
        )
        return (parts.acc, parts.m, parts.l, *grads)

    with _warnings.catch_warnings():
        _warnings.simplefilter("error")  # compact path: no demotion warning
        compact = run_all()
    monkeypatch.setattr(pf, "_MAX_COMPACT_TILES", 2)  # force demotion
    with pytest.warns(UserWarning, match="demoted to the rectangular grid"):
        demoted = run_all()
    for a, b, name in zip(compact, demoted,
                          ("acc", "m", "l", "dq", "dk", "dv")):
        np.testing.assert_array_equal(a, b, err_msg=name)


def test_fused_forward_matches_finalized_partials(rng):
    """pallas_flash_fused (normalization folded into the kernel's final
    write — ref triton_flash_attn.py:273-275) must equal
    finalize_partials(pallas_flash_partials(...)) on every mask variant."""
    from ring_attention_tpu.ops.pallas_flash import pallas_flash_fused

    q, k, v = make_qkv(rng, b=1, h=4, hk=2, n=256, d=32)
    mask = jnp.asarray(rng.random((1, 256)) > 0.3)
    scale = q.shape[-1] ** -0.5
    cases = [
        dict(causal_offset=0),
        dict(causal_offset=0, window_lo=-95),
        dict(kv_mask=mask, softclamp_value=5.0),
        dict(),
    ]
    for kw in cases:
        kv_mask = kw.pop("kv_mask", None)
        parts = pallas_flash_partials(
            q, k, v, kv_mask, scale=scale, block_q=64, block_k=64,
            interpret=True, **kw,
        )
        ref_out, ref_lse = finalize_partials(parts)
        out, lse = pallas_flash_fused(
            q, k, v, kv_mask, scale=scale, block_q=64, block_k=64,
            interpret=True, **kw,
        )
        assert out.dtype == q.dtype
        np.testing.assert_allclose(out, ref_out, atol=1e-6, err_msg=str(kw))
        np.testing.assert_allclose(lse, ref_lse, atol=1e-6, err_msg=str(kw))


def test_band_hint_compact_matches_static(rng):
    """A traced offset + exact band_hint must reproduce the static-offset
    compact grid bit-for-bit (the unrolled ring hop contract: contiguous
    hops have one exact per-hop offset, VERDICT r2 missing #1)."""
    q, k, v = make_qkv(rng, b=1, h=2, n=256, d=32)
    scale = q.shape[-1] ** -0.5
    for co in (0, 64, -64):
        static = pallas_flash_partials(
            q, k, v, scale=scale, causal_offset=co,
            block_q=64, block_k=64, interpret=True,
        )
        hinted = jax.jit(
            lambda o, co=co: pallas_flash_partials(
                q, k, v, scale=scale, causal_offset=o,
                band_hint=(co, co, 0, 0),
                block_q=64, block_k=64, interpret=True,
            )
        )(jnp.int32(co))
        for a, b, name in zip(static, hinted, ("acc", "m", "l")):
            np.testing.assert_array_equal(a, b, err_msg=f"co={co} {name}")


def test_band_hint_superset_merges_exactly(rng):
    """Striped-hop contract: offsets in {0, -1} under one superset hint
    (hi_work=0, hi_int=-1).  Superset-only tiles are masked at run time and
    any band-empty row's garbage is wiped by the online-softmax rescale in
    the ring merge — so the merged result must match merging the exact
    static-offset partials."""
    from ring_attention_tpu.ops.pallas_flash import pallas_flash_backward

    q, k, v = make_qkv(rng, b=1, h=2, n=256, d=32)
    scale = q.shape[-1] ** -0.5
    diag = pallas_flash_partials(  # "own block" hop: offset 0
        q, k, v, scale=scale, causal_offset=0,
        block_q=64, block_k=64, interpret=True,
    )
    hop_static = pallas_flash_partials(  # strict-diagonal hop: offset -1
        q, k, v, scale=scale, causal_offset=-1,
        block_q=64, block_k=64, interpret=True,
    )
    hop_hinted = jax.jit(
        lambda o: pallas_flash_partials(
            q, k, v, scale=scale, causal_offset=o,
            band_hint=(0, -1, 0, 0),
            block_q=64, block_k=64, interpret=True,
        )
    )(jnp.int32(-1))
    ref_out, ref_lse = finalize_partials(merge_partials(diag, hop_static))
    out, lse = finalize_partials(merge_partials(diag, hop_hinted))
    np.testing.assert_allclose(out, ref_out, atol=ATOL)
    np.testing.assert_allclose(lse, ref_lse, atol=ATOL)

    # backward: superset-only tiles contribute exact zeros (p masked to 0),
    # so grads match the static-offset grads directly
    do = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)
    out_s, lse_s = finalize_partials(hop_static)
    delta = (do * out_s).sum(-1)
    g_static = pallas_flash_backward(
        do, q, k, v, lse_s, delta, scale=scale, causal_offset=-1,
        block_q=64, block_k=64, interpret=True,
    )
    g_hinted = jax.jit(
        lambda o: pallas_flash_backward(
            do, q, k, v, lse_s, delta, scale=scale, causal_offset=o,
            band_hint=(0, -1, 0, 0), block_q=64, block_k=64, interpret=True,
        )
    )(jnp.int32(-1))
    for a, b, name in zip(g_hinted, g_static, ("dq", "dk", "dv")):
        np.testing.assert_allclose(a, b, atol=1e-5, err_msg=name)


@pytest.mark.parametrize(
    "traced,masked", [(False, False), (True, False), (True, True)],
    ids=["compact", "rectangular", "rectangular-masked"],
)
def test_backward_per_pass_block_sizes(rng, traced, masked):
    """dkv and dq passes accept independent tile shapes on both grids."""
    from ring_attention_tpu.ops.pallas_flash import pallas_flash_backward

    q, k, v = make_qkv(rng, b=1, h=2, n=256, d=32)
    do = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)
    mask = jnp.asarray(rng.random((1, 256)) > 0.3) if masked else None
    scale = q.shape[-1] ** -0.5
    parts = pallas_flash_partials(
        q, k, v, mask, scale=scale, causal_offset=0,
        block_q=64, block_k=64, interpret=True,
    )
    out, lse = finalize_partials(parts)
    delta = (do * out).sum(-1)

    def run(**blocks):
        co = jnp.int32(0) if traced else 0
        f = lambda c: pallas_flash_backward(  # noqa: E731
            do, q, k, v, lse, delta, mask, scale=scale, causal_offset=c,
            interpret=True, **blocks,
        )
        return jax.jit(f)(co) if traced else f(co)

    base = run(block_q=64, block_k=64)
    split = run(block_q_dkv=32, block_k_dkv=128,
                block_q_dq=128, block_k_dq=32)
    for a, b, name in zip(base, split, ("dq", "dk", "dv")):
        np.testing.assert_allclose(a, b, atol=1e-4, err_msg=name)


def test_carry_resume_matches_merge(rng):
    """In-kernel accumulator resume (carry=...) must equal the XLA-side
    merge_partials of two independent sweeps — the LOAD_ACCUMULATED
    contract (ref triton_flash_attn.py:124-165) the ring hops rely on —
    and resuming into a fused final write must equal finalizing that
    merge (ref ring_flash_attention_cuda.py:134,182-186)."""
    from ring_attention_tpu.ops.pallas_flash import pallas_flash_fused

    q, k, v = make_qkv(rng, b=1, h=2, n=256, d=32)
    scale = q.shape[-1] ** -0.5
    left = pallas_flash_partials(
        q, k[:, :, :128], v[:, :, :128], scale=scale,
        block_q=64, block_k=64, interpret=True,
    )
    right = pallas_flash_partials(
        q, k[:, :, 128:], v[:, :, 128:], scale=scale,
        block_q=64, block_k=64, interpret=True,
    )
    merged = merge_partials(left, right)
    resumed = pallas_flash_partials(
        q, k[:, :, 128:], v[:, :, 128:], scale=scale,
        block_q=64, block_k=64, carry=left, interpret=True,
    )
    # resume rescales the carry tile-by-tile where merge rescales once:
    # same math, different summation order -> tiny float drift allowed
    for a, b, name in zip(resumed, merged, ("acc", "m", "l")):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5, err_msg=name)

    out_ref, lse_ref = finalize_partials(merged)
    out, lse = pallas_flash_fused(
        q, k[:, :, 128:], v[:, :, 128:], scale=scale,
        block_q=64, block_k=64, carry=left, interpret=True,
    )
    np.testing.assert_allclose(out, out_ref, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(lse, lse_ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("hk,nq", [(4, 1), (2, 1), (2, 3)])
def test_decode_kernel_parity(rng, hk, nq):
    """pallas_flash_decode (head group folded onto query rows, KV read once
    per kv head) vs the dense oracle: fused output + lse, and the raw
    FlashCarry-layout partials the tree merge consumes."""
    from ring_attention_tpu.ops.flash import FlashCarry, finalize, _ungroup
    from ring_attention_tpu.ops.pallas_flash import pallas_flash_decode

    b, h, n, d = 2, 4, 256, 32
    q = jnp.asarray(rng.standard_normal((b, h, nq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    mask = jnp.asarray(rng.random((b, n)) < 0.8)
    ref = default_attention(q, k, v, mask)

    out, lse = pallas_flash_decode(q, k, v, mask, block_k=64, interpret=True)
    assert out.shape == q.shape and lse.shape == (b, h, nq)
    np.testing.assert_allclose(out, ref, atol=ATOL)

    acc, m, l = pallas_flash_decode(
        q, k, v, mask, block_k=64, fused=False, interpret=True
    )
    assert acc.shape == (b, hk, h // hk, nq, d)
    o2, _ = finalize(FlashCarry(acc, m, l))
    np.testing.assert_allclose(_ungroup(o2), ref, atol=ATOL)


def test_decode_kernel_softclamp(rng):
    from ring_attention_tpu.ops.pallas_flash import pallas_flash_decode

    b, h, hk, n, d = 1, 4, 2, 128, 32
    q, k, v = make_qkv(rng, b=b, h=h, hk=hk, n=n, d=d)
    q = q[:, :, :1]
    ref = default_attention(q, k, v, softclamp_value=15.0)
    out, _ = pallas_flash_decode(
        q, k, v, softclamp_value=15.0, block_k=32, interpret=True
    )
    np.testing.assert_allclose(out, ref, atol=ATOL)


@pytest.mark.parametrize("hk,nq,masked", [(2, 1, False), (2, 1, True),
                                          (4, 2, False), (1, 1, False)])
def test_decode_q8_kernel_parity(rng, hk, nq, masked):
    """Kernel correctness isolated from quantization error: the q8 decode
    against a quantized cache must match the dense oracle run on the
    DEQUANTIZED cache to float tolerance."""
    from ring_attention_tpu.ops.pallas_flash import (
        pallas_flash_decode_q8,
        quantize_kv_cache,
    )

    b, h, n, d = 2, 4, 256, 32
    q = jnp.asarray(rng.standard_normal((b, h, nq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    mask = jnp.asarray(rng.random((b, n)) > 0.25) if masked else None
    kv = quantize_kv_cache(k, v)
    k_deq = kv.k_q.astype(jnp.float32) * kv.k_scale[..., None]
    v_deq = kv.v_q.astype(jnp.float32) * kv.v_scale[..., None]
    ref = default_attention(q, k_deq, v_deq, mask)
    out, lse = pallas_flash_decode_q8(q, kv, mask, block_k=64, interpret=True)
    assert out.shape == (b, h, nq, d) and lse.shape == (b, h, nq)
    np.testing.assert_allclose(out, ref, atol=3e-5)

    # end-to-end quantized accuracy vs the unquantized oracle: per-token
    # absmax int8 stays within ~2% on gaussian activations
    full = default_attention(q, k, v, mask)
    err = jnp.abs(out - full).max() / jnp.abs(full).max()
    assert float(err) < 0.02, float(err)


def test_decode_q8_partials_merge(rng):
    """fused=False partials from the q8 kernel must finalize to the fused
    output (the tree-decode cross-device merge contract)."""
    from ring_attention_tpu.ops.pallas_flash import (
        pallas_flash_decode_q8,
        quantize_kv_cache,
    )

    b, h, hk, n, d = 1, 4, 2, 128, 32
    q = jnp.asarray(rng.standard_normal((b, h, 1, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    kv = quantize_kv_cache(k, v)
    out, lse = pallas_flash_decode_q8(q, kv, block_k=32, interpret=True)
    acc, m, l = pallas_flash_decode_q8(
        q, kv, block_k=32, fused=False, interpret=True
    )
    g = h // hk
    assert acc.shape == (b, hk, g, 1, d)
    fin = acc / jnp.maximum(l, 1e-10)[..., None]
    np.testing.assert_allclose(
        fin.reshape(b, h, 1, d), out, atol=2e-5
    )
    np.testing.assert_allclose(
        (m + jnp.log(jnp.maximum(l, 1e-10))).reshape(b, h, 1), lse, atol=2e-5
    )


@pytest.mark.parametrize("dtype,atol", [
    (jnp.bfloat16, 2e-2),  # itemsize 2 -> sublane tile 16 rows
    (jnp.float16, 2e-2),   # itemsize 2 -> 16 (the pre-ADVICE code padded 8)
    (jnp.float32, 1e-5),   # itemsize 4 -> 8
])
def test_decode_kernel_row_padding(rng, dtype, atol):
    """Decode pads query rows to a full sublane tile, keyed on dtype
    itemsize (ADVICE r3: an exact-bf16 check under-padded f16); results
    must be unchanged and pad rows invisible."""
    from ring_attention_tpu.ops.pallas_flash import pallas_flash_decode

    b, h, hk, n, d = 1, 2, 2, 128, 32  # rows = g*nq = 1 -> pad to tile
    q = jnp.asarray(rng.standard_normal((b, h, 1, d)), dtype)
    k = jnp.asarray(rng.standard_normal((b, hk, n, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, hk, n, d)), dtype)
    ref = default_attention(q, k, v)
    out, lse = pallas_flash_decode(q, k, v, block_k=32, interpret=True)
    assert out.shape == (b, h, 1, d) and lse.shape == (b, h, 1)
    np.testing.assert_allclose(
        out.astype(jnp.float32), ref.astype(jnp.float32), atol=atol
    )


def test_exp2_log2_space_parity(rng, monkeypatch):
    """RING_ATTN_EXP2=1 (log2-space scoring, docs/hardware_log.md round-5
    roofline note) is value-identical at the kernel boundary: fwd outputs
    AND grads match the natural-basis oracle, including softclamp + mask
    + GQA, and the emitted lse stays in natural units."""
    monkeypatch.setenv("RING_ATTN_EXP2", "1")
    q, k, v = make_qkv(rng, hk=2, n=128, d=32)
    mask = jnp.broadcast_to(jnp.arange(128)[None, :] < 100, (2, 128))
    ref = default_attention(q, k, v, mask, causal=True, softclamp_value=15.0)
    out = pallas_flash_attention(
        q, k, v, mask, causal=True, softclamp_value=15.0, interpret=True
    )
    np.testing.assert_allclose(out, ref, atol=2e-5)

    def loss_p(q, k, v):
        return (pallas_flash_attention(
            q, k, v, mask, causal=True, softclamp_value=15.0, interpret=True
        ) ** 2).sum()

    def loss_o(q, k, v):
        return (default_attention(
            q, k, v, mask, causal=True, softclamp_value=15.0
        ) ** 2).sum()

    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    go = jax.grad(loss_o, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gp, go):
        np.testing.assert_allclose(a, b, atol=3e-5, err_msg=f"d{name}")

    # partials keep the natural-units contract (ring merging / carry interop)
    from ring_attention_tpu.ops.pallas_flash import pallas_flash_partials

    monkeypatch.setenv("RING_ATTN_EXP2", "0")
    nat = pallas_flash_partials(q, k, v, scale=32**-0.5, causal_offset=0,
                                interpret=True)
    monkeypatch.setenv("RING_ATTN_EXP2", "1")
    l2 = pallas_flash_partials(q, k, v, scale=32**-0.5, causal_offset=0,
                               interpret=True)
    # rtol covers rows whose l (a sum of up to n exponentials) is large:
    # the bases legitimately differ by ~1 ulp per accumulation step
    np.testing.assert_allclose(l2.m, nat.m, atol=2e-5, rtol=1e-5)
    np.testing.assert_allclose(l2.l, nat.l, atol=2e-5, rtol=1e-5)
    np.testing.assert_allclose(l2.acc, nat.acc, atol=2e-5, rtol=1e-5)

    # the explicit keyword (ADVICE.md: the env var is captured at trace
    # time, so in-process A/B passes exp2= instead) matches the env path
    monkeypatch.setenv("RING_ATTN_EXP2", "0")
    l2kw = pallas_flash_partials(q, k, v, scale=32**-0.5, causal_offset=0,
                                 interpret=True, exp2=True)
    np.testing.assert_allclose(l2kw.m, l2.m, atol=0)
    np.testing.assert_allclose(l2kw.l, l2.l, atol=0)
    np.testing.assert_allclose(l2kw.acc, l2.acc, atol=0)
    out_kw = pallas_flash_attention(
        q, k, v, mask, causal=True, softclamp_value=15.0, interpret=True,
        exp2=True,
    )
    np.testing.assert_allclose(out_kw, ref, atol=2e-5)


def test_exp2_carry_resume_parity(rng, monkeypatch):
    """Ring-hop carry resume under RING_ATTN_EXP2=1: the carry crosses the
    kernel boundary in natural units and converts on load (the subtlest
    line of the log2-space feature), so a partials hop + fused carry hop
    must equal the single full sweep — including when the two hops run in
    DIFFERENT bases (one kernel natural, the next log2)."""
    from ring_attention_tpu.ops.pallas_flash import (
        pallas_flash_fused,
        pallas_flash_partials,
    )

    q, k, v = make_qkv(rng, hk=2, n=128, d=32)
    scale = 32**-0.5
    ref = default_attention(q, k, v)

    def two_hop(basis_hop0, basis_hop1):
        monkeypatch.setenv("RING_ATTN_EXP2", basis_hop0)
        carry = pallas_flash_partials(
            q, k[:, :, :64], v[:, :, :64], scale=scale, interpret=True
        )
        monkeypatch.setenv("RING_ATTN_EXP2", basis_hop1)
        out, lse = pallas_flash_fused(
            q, k[:, :, 64:], v[:, :, 64:], scale=scale, carry=carry,
            interpret=True,
        )
        return out, lse

    out_nat, lse_nat = two_hop("0", "0")
    for hops in (("1", "1"), ("0", "1"), ("1", "0")):
        out, lse = two_hop(*hops)
        np.testing.assert_allclose(out, ref, atol=2e-5, err_msg=hops)
        np.testing.assert_allclose(lse, lse_nat, atol=2e-5, err_msg=hops)
