"""Parity: Pallas flash kernels (interpret mode on CPU) vs the oracle.

The kernels are exercised through the same contract as the XLA blockwise
path: forward outputs, lse, partial merging, and the two-pass backward must
match ``default_attention`` and its autodiff gradients.  On CPU the kernels
run in Pallas interpreter mode; identical code compiles to Mosaic on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ring_attention_tpu.ops import default_attention
from ring_attention_tpu.ops.pallas_flash import (
    finalize_partials,
    merge_partials,
    pallas_flash_attention,
    pallas_flash_partials,
)

ATOL = 2e-5
GRAD_ATOL = 5e-4


def make_qkv(rng, b=2, h=4, hk=None, n=128, d=32):
    hk = hk or h
    q = jnp.asarray(rng.standard_normal((b, h, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_fwd_parity(rng, causal):
    q, k, v = make_qkv(rng)
    ref = default_attention(q, k, v, causal=causal)
    out = pallas_flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_fwd_gqa(rng):
    q, k, v = make_qkv(rng, h=4, hk=2)
    ref = default_attention(q, k, v, causal=True)
    out = pallas_flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_fwd_mask(rng):
    q, k, v = make_qkv(rng)
    mask = jnp.asarray(rng.random((2, 128)) > 0.3)
    ref = default_attention(q, k, v, mask)
    out = pallas_flash_attention(q, k, v, mask, interpret=True)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_fwd_softclamp(rng):
    q, k, v = make_qkv(rng)
    ref = default_attention(q, k, v, causal=True, softclamp_value=5.0)
    out = pallas_flash_attention(
        q, k, v, causal=True, softclamp_value=5.0, interpret=True
    )
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_fwd_window(rng):
    q, k, v = make_qkv(rng)
    n, w = 128, 48
    out = pallas_flash_attention(q, k, v, causal=True, window=w, interpret=True)
    i = jnp.arange(n)[:, None]
    j = jnp.arange(n)[None, :]
    band = (j <= i) & (j >= i - (w - 1))
    s = jnp.einsum("bhid,bhjd->bhij", q, k) * (q.shape[-1] ** -0.5)
    ref = jnp.einsum(
        "bhij,bhjd->bhid", jax.nn.softmax(jnp.where(band, s, -1e30), -1), v
    )
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_partials_merge(rng):
    """Two half-KV sweeps merged == one full sweep (the ring-hop contract)."""
    q, k, v = make_qkv(rng)
    scale = q.shape[-1] ** -0.5
    full = pallas_flash_partials(q, k, v, scale=scale, interpret=True)
    left = pallas_flash_partials(
        q, k[:, :, :64], v[:, :, :64], scale=scale, interpret=True
    )
    right = pallas_flash_partials(
        q, k[:, :, 64:], v[:, :, 64:], scale=scale, interpret=True
    )
    merged = merge_partials(left, right)
    out_full, lse_full = finalize_partials(full)
    out_merged, lse_merged = finalize_partials(merged)
    np.testing.assert_allclose(out_merged, out_full, atol=ATOL)
    np.testing.assert_allclose(lse_merged, lse_full, atol=ATOL)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("hk", [4, 2])
def test_grad_parity(rng, causal, hk):
    q, k, v = make_qkv(rng, hk=hk)

    g_ref = jax.grad(
        lambda *a: (default_attention(*a, causal=causal) ** 2).sum(), (0, 1, 2)
    )(q, k, v)
    g_out = jax.grad(
        lambda *a: (
            pallas_flash_attention(*a, causal=causal, interpret=True) ** 2
        ).sum(),
        (0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


def test_grad_softclamp_mask(rng):
    q, k, v = make_qkv(rng)
    mask = jnp.asarray(rng.random((2, 128)) > 0.3)

    g_ref = jax.grad(
        lambda *a: (default_attention(*a, softclamp_value=5.0) ** 2).sum(),
        (0, 1, 2),
    )(q, k, v, mask)
    g_out = jax.grad(
        lambda *a: (
            pallas_flash_attention(
                *a, softclamp_value=5.0, interpret=True
            )
            ** 2
        ).sum(),
        (0, 1, 2),
    )(q, k, v, mask)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


def test_wide_head_dim(rng):
    """dim_head=128 (full lane width) through fwd and bwd kernels."""
    q, k, v = make_qkv(rng, h=2, n=256, d=128)
    ref = default_attention(q, k, v, causal=True)
    out = pallas_flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(out, ref, atol=ATOL)
    g_ref = jax.grad(lambda *a: (default_attention(*a, causal=True) ** 2).sum(), (0, 1, 2))(q, k, v)
    g_out = jax.grad(
        lambda *a: (pallas_flash_attention(*a, causal=True, interpret=True) ** 2).sum(),
        (0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=1e-3, err_msg=f"d{name}")


# ---------------------------------------------------------------------------
# Compacted causal grid: with static band offsets the kernels run on a
# flattened grid of only the active tiles (scalar-prefetched tile tables);
# a traced offset keeps the rectangular grid.  The two grids must agree
# bit-for-bit on every band shape, including fully-empty rows.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "co,wlo,masked",
    [
        (0, None, False),
        (-1, None, False),
        (0, -95, False),
        (-300, None, False),
        (0, None, True),
    ],
    ids=["causal", "striped-flip", "window", "all-empty", "kvmask"],
)
def test_compact_grid_matches_rectangular(rng, co, wlo, masked):
    q, k, v = make_qkv(rng, b=1, h=2, n=256, d=32)
    mask = jnp.asarray(rng.random((1, 256)) > 0.3) if masked else None
    scale = q.shape[-1] ** -0.5

    static = pallas_flash_partials(
        q, k, v, mask, scale=scale, causal_offset=co, window_lo=wlo,
        block_q=64, block_k=64, interpret=True,
    )
    traced = jax.jit(
        lambda q, k, v, o, w: pallas_flash_partials(
            q, k, v, mask, scale=scale, causal_offset=o,
            window_lo=w if wlo is not None else None,
            block_q=64, block_k=64, interpret=True,
        )
    )(q, k, v, jnp.int32(co), jnp.int32(wlo if wlo is not None else 0))
    for a, b, name in zip(static, traced, ("acc", "m", "l")):
        np.testing.assert_array_equal(a, b, err_msg=name)


@pytest.mark.parametrize(
    "co,wlo,masked",
    [(0, None, False), (0, -95, False), (0, None, True)],
    ids=["causal", "window", "kvmask"],
)
def test_compact_grid_backward_matches_rectangular(rng, co, wlo, masked):
    from ring_attention_tpu.ops.pallas_flash import pallas_flash_backward

    q, k, v = make_qkv(rng, b=1, h=4, hk=2, n=256, d=32)
    do = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)
    mask = jnp.asarray(rng.random((1, 256)) > 0.3) if masked else None
    scale = q.shape[-1] ** -0.5
    parts = pallas_flash_partials(
        q, k, v, mask, scale=scale, causal_offset=co, window_lo=wlo,
        block_q=64, block_k=64, interpret=True,
    )
    out, lse = finalize_partials(parts)
    delta = (do * out).sum(-1)

    static = pallas_flash_backward(
        do, q, k, v, lse, delta, mask, scale=scale, causal_offset=co,
        window_lo=wlo, block_q=64, block_k=64, interpret=True,
    )
    traced = jax.jit(
        lambda o, w: pallas_flash_backward(
            do, q, k, v, lse, delta, mask, scale=scale, causal_offset=o,
            window_lo=w if wlo is not None else None,
            block_q=64, block_k=64, interpret=True,
        )
    )(jnp.int32(co), jnp.int32(wlo if wlo is not None else 0))
    for a, b, name in zip(static, traced, ("dq", "dk", "dv")):
        np.testing.assert_array_equal(a, b, err_msg=name)


@pytest.mark.parametrize("outer_is_q", [True, False])
@pytest.mark.parametrize(
    "hi,lo,windowed",
    [(0, 0, False), (-1, 0, False), (64, 0, False), (0, -95, True),
     (-256, 0, False), (0, -31, True)],
)
def test_band_tile_count_matches_tables(hi, lo, windowed, outer_is_q):
    """The closed-form count used for the SMEM cap must equal the real
    table length for every band shape (incl. empty/dummy rows)."""
    from ring_attention_tpu.ops.pallas_flash import (
        _band_tables,
        _band_tile_count,
    )

    args = (4, 4, 64, 64, hi, lo, windowed, outer_is_q)
    assert _band_tile_count(*args) == _band_tables(*args)[0].shape[0]


def test_compact_table_cap_demotes_to_rectangular(rng, monkeypatch):
    """A static band whose tile tables exceed _MAX_COMPACT_TILES (SMEM
    scalar-prefetch budget) must silently take the rectangular grid and
    produce identical results, fwd and bwd."""
    import ring_attention_tpu.ops.pallas_flash as pf

    q, k, v = make_qkv(rng, b=1, h=2, n=256, d=32)
    do = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)
    scale = q.shape[-1] ** -0.5

    def run_all():
        parts = pf.pallas_flash_partials(
            q, k, v, scale=scale, causal_offset=0,
            block_q=64, block_k=64, interpret=True,
        )
        out, lse = finalize_partials(parts)
        delta = (do * out).sum(-1)
        grads = pf.pallas_flash_backward(
            do, q, k, v, lse, delta, scale=scale, causal_offset=0,
            block_q=64, block_k=64, interpret=True,
        )
        return (parts.acc, parts.m, parts.l, *grads)

    compact = run_all()
    monkeypatch.setattr(pf, "_MAX_COMPACT_TILES", 2)  # force demotion
    demoted = run_all()
    for a, b, name in zip(compact, demoted,
                          ("acc", "m", "l", "dq", "dk", "dv")):
        np.testing.assert_array_equal(a, b, err_msg=name)


@pytest.mark.parametrize(
    "traced,masked", [(False, False), (True, False), (True, True)],
    ids=["compact", "rectangular", "rectangular-masked"],
)
def test_backward_per_pass_block_sizes(rng, traced, masked):
    """dkv and dq passes accept independent tile shapes on both grids."""
    from ring_attention_tpu.ops.pallas_flash import pallas_flash_backward

    q, k, v = make_qkv(rng, b=1, h=2, n=256, d=32)
    do = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)
    mask = jnp.asarray(rng.random((1, 256)) > 0.3) if masked else None
    scale = q.shape[-1] ** -0.5
    parts = pallas_flash_partials(
        q, k, v, mask, scale=scale, causal_offset=0,
        block_q=64, block_k=64, interpret=True,
    )
    out, lse = finalize_partials(parts)
    delta = (do * out).sum(-1)

    def run(**blocks):
        co = jnp.int32(0) if traced else 0
        f = lambda c: pallas_flash_backward(  # noqa: E731
            do, q, k, v, lse, delta, mask, scale=scale, causal_offset=c,
            interpret=True, **blocks,
        )
        return jax.jit(f)(co) if traced else f(co)

    base = run(block_q=64, block_k=64)
    split = run(block_q_dkv=32, block_k_dkv=128,
                block_q_dq=128, block_k_dq=32)
    for a, b, name in zip(base, split, ("dq", "dk", "dv")):
        np.testing.assert_allclose(a, b, atol=1e-4, err_msg=name)
