"""Perf observatory: measured-overlap profiler, benchmark-history
regression gate, and numerics flight recorder (ISSUE 8 /
docs/observability.md §Observatory).

The contracts under test:

- the stdlib xplane parser reconstructs a per-hop/per-stage timeline
  from a REAL CPU capture (the same artifact XProf reads on TPU), and
  the measured compute/transfer overlap fraction sits within tolerance
  of ``ring_comms_accounting``'s analytic one — and a disagreement is a
  reportable finding, not a silent number;
- the perf gate passes on the repo's actual BENCH history + committed
  baseline, and each injected regression (fingerprint drift, inflated
  temp bytes, dropped hop, hardware slowdown) fails with a ONE-LINE
  diagnostic naming the regressed series;
- a NaN injected at step k dumps a flight recording carrying the
  preceding metric rows and the triggering event.
"""

import json
import os
import warnings

import jax
import jax.numpy as jnp
import optax
import pytest

from ring_attention_tpu.analysis import perfgate
from ring_attention_tpu.utils import (
    FlightRecorder,
    init_train_metrics,
    make_train_step,
    read_flight_dump,
    ring_comms_accounting,
)
from ring_attention_tpu.utils import resilience
from ring_attention_tpu.utils.profiling import (
    overlap_report,
    read_xplane_events,
    stage_timeline,
)
from ring_attention_tpu.utils.telemetry import FLIGHT_SCHEMA_VERSION

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------
# Measured-overlap profiler on a real CPU capture
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def ring_capture(tmp_path_factory):
    """One real xplane capture of the compiled ring-attention program —
    the same model/shapes as test_telemetry's HLO-pin test, so the
    persistent compile cache makes this a trace + one execution, not a
    new large compile (tier-1 budget)."""
    import numpy as np

    from ring_attention_tpu.models.attention import RingAttention
    from ring_attention_tpu.parallel.mesh import create_mesh
    from ring_attention_tpu.utils.profiling import trace

    mesh = create_mesh(ring_size=4)
    att = RingAttention(dim=32, heads=4, dim_head=8, bucket_size=8,
                        causal=True, use_ring=True, auto_shard=True,
                        mesh=mesh)
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 64, 32)), jnp.float32
    )
    params = att.init(jax.random.PRNGKey(0), x)
    f = jax.jit(lambda p, x: att.apply(p, x))
    # compile + warm to steady state outside the trace: the first
    # post-compile executions carry allocator/scheduler noise that the
    # overlap numbers would inherit
    for _ in range(3):
        jax.block_until_ready(f(params, x))
    logdir = str(tmp_path_factory.mktemp("xprof"))
    with trace(logdir):
        jax.block_until_ready(f(params, x))
    # the capture describes a (data 2, ring 4) mesh over 8 CPU devices:
    # per-device batch 1, per-shard seq 16, f32 payloads
    comms_kwargs = dict(
        ring_size=4, seq_len=64, kv_heads=4, heads=4, dim_head=8,
        dtype_bytes=4, batch=1,
    )
    return logdir, comms_kwargs


def test_xplane_timeline_from_real_capture(ring_capture):
    """The golden timeline: the stdlib parser resolves scope paths from
    the embedded HloProto (no tensorflow protos anywhere in this image)
    and buckets ring compute vs KV rotation into per-hop rows."""
    logdir, _ = ring_capture
    events, note = read_xplane_events(logdir)
    assert events, f"no events parsed: {note}"
    # the HloProto join recovered named_scope paths for real op events
    scoped = [e for e in events if e.scope]
    assert scoped, "no event carried a resolved op_name scope path"
    assert any("ring/hop" in e.scope for e in scoped)
    assert any("ring/rotate" in e.scope for e in scoped)

    timeline = stage_timeline(events)
    stages = {row["stage"]: row for row in timeline["stages"]}
    assert "ring hop compute" in stages and "ring kv rotation" in stages
    assert stages["ring hop compute"]["kind"] == "compute"
    assert stages["ring kv rotation"]["kind"] == "transfer"
    for row in stages.values():
        assert row["busy_ms"] > 0
        assert row["p95_ms"] >= row["p50_ms"] > 0
    # per-hop reconstruction: a 4-ring schedule shows its hop structure
    hops = timeline["hops"]
    assert hops, "no per-hop rows reconstructed"
    assert 2 <= len(hops) <= 8
    assert hops[0]["hop"] == 0 and hops[0]["compute_ms"] > 0
    assert sum(h["transfer_ms"] for h in hops) > 0
    assert all(h["samples"] > 0 for h in hops)


def _calibrated_analytic(logdir, comms_kwargs):
    """``ring_comms_accounting`` with compute/link rates calibrated from
    the capture itself — the model's documented use (its default
    constants are v5e parameters, meaningless for a CPU timeline).  The
    effective rates come from the per-instance stage medians: by
    construction the model's per-hop compute time equals the measured
    p50 hop time and the transfer time the measured p50 rotation, so
    model and measurement describe the same platform."""
    events, note = read_xplane_events(logdir)
    assert events, note
    stages = {r["stage"]: r for r in stage_timeline(events)["stages"]}
    hop_ms = stages["ring hop compute"]["p50_ms"]
    rot_ms = stages["ring kv rotation"]["p50_ms"]
    probe = ring_comms_accounting(
        peak_tflops=1.0, ici_gbps=1.0, **comms_kwargs
    )  # only for the hop flop/byte terms
    from ring_attention_tpu.utils.telemetry import flash_attention_flops

    n_chunk = comms_kwargs["seq_len"] // comms_kwargs["ring_size"]
    hop_flops = 0.5 * flash_attention_flops(
        n_chunk, n_chunk, heads=comms_kwargs["heads"],
        dim_head=comms_kwargs["dim_head"], batch=comms_kwargs["batch"],
    )
    eff_tflops = hop_flops / (hop_ms * 1e-3) / 1e12
    eff_gbps = probe["hop_bytes"] / (rot_ms * 1e-3) / 1e9
    return ring_comms_accounting(
        peak_tflops=eff_tflops, ici_gbps=eff_gbps, **comms_kwargs
    )


def test_measured_overlap_within_tolerance_of_analytic(ring_capture):
    """The acceptance pin: the measured overlap fraction sits within
    tolerance of ``ring_comms_accounting``'s analytic one, with the
    model's rate parameters calibrated from the same capture (on
    hardware you pass the chip's peak/ICI figures; on a CPU capture the
    effective rates are what the timeline measured).  Both numbers then
    describe the same platform and must agree — and they co-move under
    scheduler noise, which is what makes this a stable pin where a
    fixed-constant comparison would flake."""
    logdir, comms_kwargs = ring_capture
    analytic = _calibrated_analytic(logdir, comms_kwargs)
    report = overlap_report(logdir, analytic=analytic, tolerance=0.35)
    assert report["parsed_events"] > 0
    assert report["transfer_ms"] > 0, "no transfer spans in the capture"
    assert 0.0 <= report["overlap_fraction"] <= 1.0
    assert report["analytic_overlap_fraction"] == analytic[
        "hop_overlap_fraction"
    ]
    # the CPU mesh serializes devices over 2 cores: both worlds must
    # call the ring transfer-bound at these shapes (fraction well under
    # full overlap) AND agree within tolerance
    assert report["analytic_overlap_fraction"] < 0.6
    assert report["agrees"], (
        f"measured {report['overlap_fraction']} vs calibrated analytic "
        f"{report['analytic_overlap_fraction']}"
    )


def test_overlap_disagreement_is_a_finding(ring_capture):
    """A model that no longer describes the hardware is itself a
    regression: force a wrong analytic value and the report flags it."""
    logdir, _ = ring_capture
    report = overlap_report(logdir, analytic=0.99, tolerance=0.25)
    assert not report["agrees"]
    assert "finding" in report
    assert "tolerance" in report["finding"]
    assert "\n" not in report["finding"]


def test_trace_report_renders_capture(ring_capture, tmp_path):
    """End-to-end through the CLI: metrics + --xprof renders the
    per-stage and per-hop tables and the measured-vs-analytic pair."""
    import subprocess
    import sys

    logdir, _ = ring_capture
    measured = overlap_report(logdir)["overlap_fraction"]
    mdir = tmp_path / "m"
    mdir.mkdir()
    # the run's logged analytic fraction agrees with the capture (on
    # hardware this is ring_comms_accounting with the chip's real rates)
    row = {"schema": 1, "step": 0, "loss": 1.0,
           "hop_overlap_fraction": measured}
    (mdir / "metrics.jsonl").write_text(json.dumps(row) + "\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         str(mdir), "--xprof", logdir],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "per-stage device time" in proc.stdout
    assert "ring kv rotation" in proc.stdout
    assert "per-hop timeline" in proc.stdout
    assert "measured overlap:" in proc.stdout
    assert "analytic overlap:" in proc.stdout
    assert "FINDING" not in proc.stdout  # model and capture agree
    # and a wrong logged model IS flagged through the CLI
    row["hop_overlap_fraction"] = 0.99
    (mdir / "metrics.jsonl").write_text(json.dumps(row) + "\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         str(mdir), "--xprof", logdir],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "FINDING" in proc.stdout


# ----------------------------------------------------------------------
# Perf gate: the real history passes; injected regressions fail one-line
# ----------------------------------------------------------------------


def test_gate_passes_on_repo_history(devices):
    """The acceptance run: current build vs the committed baseline +
    BENCH_r*.json history, on CPU.  Cheap subset (ring fingerprint +
    arithmetic comms table); the full set is tools/perf_gate.py."""
    current = perfgate.collect_current(strategies=("ring",), compiled=False)
    report = perfgate.run_gate(current, root=REPO)
    assert report.ok, "\n".join(str(f) for f in report.findings)
    assert report.checked, "gate checked nothing — vacuous pass"
    assert any(s.startswith("comms.") for s in report.checked)
    assert any(s == "fingerprint.ring.ppermute" for s in report.checked)
    # wedge-honest: the 4 wedged rounds are RECORDED, not silently passed
    assert any("wedge record" in n for n in report.notes)


def test_committed_baseline_schema():
    """The baseline file the gate reads is committed and version-matched
    — deleting it cannot green a regression (run_gate would only note its
    absence; THIS pin is what fails)."""
    path = os.path.join(REPO, "docs", "perf_baseline.json")
    assert os.path.exists(path), "docs/perf_baseline.json missing"
    with open(path) as f:
        baseline = json.load(f)
    assert baseline["gate_schema"] == perfgate.GATE_SCHEMA_VERSION
    assert "comms" in baseline["signals"]
    assert "fingerprint" in baseline["signals"]
    assert "compiled" in baseline["signals"]


def _baseline(**signals):
    return {"gate_schema": perfgate.GATE_SCHEMA_VERSION,
            "jax": jax.__version__, "signals": signals}


def test_gate_toy_fingerprint_drift():
    """An extra (or missing) collective in a strategy's compiled HLO
    fails with one line naming the series."""
    base = _baseline(fingerprint={"ring": {"ppermute": 7}})
    current = {"jax": jax.__version__,
               "fingerprint": {"ring": {"ppermute": 8}}}
    report = perfgate.check_baseline(current, base)
    assert len(report.findings) == 1
    line = str(report.findings[0])
    assert "fingerprint.ring.ppermute" in line
    assert "7" in line and "8" in line
    assert "\n" not in line


def test_gate_toy_inflated_temp_bytes():
    """Compiled peak-scratch growth beyond tolerance (the memory-axis
    regression PR 7's knobs exist to prevent) fails one-line."""
    base = _baseline(compiled={"temp_bytes": 50_000})
    current = {"jax": jax.__version__,
               "compiled": {"temp_bytes": 100_000}}
    report = perfgate.check_baseline(current, base)
    assert len(report.findings) == 1
    line = str(report.findings[0])
    assert "compiled.temp_bytes" in line and "tolerance" in line
    assert "\n" not in line
    # within tolerance: clean
    ok = perfgate.check_baseline(
        {"jax": jax.__version__, "compiled": {"temp_bytes": 52_000}}, base
    )
    assert ok.ok


def test_gate_toy_dropped_hop():
    """A hop vanishing from the analytic reference table (an attention
    pass silently skipped — wrong results that bench FASTER) fails
    one-line; exact families tolerate nothing in either direction."""
    base = _baseline(comms={"ring8_262k": {"ring_hops": 7,
                                           "hop_bytes": 67108864}})
    current = {"jax": jax.__version__,
               "comms": {"ring8_262k": {"ring_hops": 6,
                                        "hop_bytes": 67108864}}}
    report = perfgate.check_baseline(current, base)
    assert len(report.findings) == 1
    line = str(report.findings[0])
    assert "comms.ring8_262k.ring_hops" in line
    assert "7" in line and "6" in line
    assert "\n" not in line


def test_gate_toy_compiler_version_scoping():
    """Compiled signals recorded under another jax version are noted and
    skipped — a compiler upgrade is not a regression."""
    base = {"gate_schema": perfgate.GATE_SCHEMA_VERSION, "jax": "9.9.9",
            "signals": {"compiled": {"temp_bytes": 1}}}
    report = perfgate.check_baseline(
        {"jax": jax.__version__, "compiled": {"temp_bytes": 10**9}}, base
    )
    assert report.ok
    assert any("not compared" in n for n in report.notes)


def _round(number, payload):
    return perfgate.BenchRound(number, f"BENCH_r{number:02d}.json", payload)


def test_gate_toy_hardware_regression_and_wedge_honesty():
    """tokens/sec drop beyond tolerance between two MEASURED rounds is a
    finding; a wedged round in between contributes a note, never a pass
    or a false failure."""
    hist = perfgate.History(rounds=[
        _round(1, {"value": 60.0, "tokens_per_sec": 26000}),
        _round(2, {"value": 0.0, "error": "device probe hung"}),
        _round(3, {"value": 61.0, "tokens_per_sec": 18000}),
    ])
    report = perfgate.check_history(hist)
    series = [f.series for f in report.findings]
    assert "hardware.tokens_per_sec" in series
    line = str(next(f for f in report.findings
                    if f.series == "hardware.tokens_per_sec"))
    assert "26,000" in line and "18,000" in line and "\n" not in line
    # fwd tflops moved +1.7%: no finding
    assert "hardware.fwd_tflops" not in series
    assert any("round 2" in n and "no hardware measurement" in n
               for n in report.notes)


def test_gate_toy_latency_direction():
    """decode ms/token is lower-is-better: an INCREASE is the finding."""
    hist = perfgate.History(rounds=[
        _round(1, {"value": 60.0, "decode_ms_per_token": 1.0}),
        _round(2, {"value": 60.0, "decode_ms_per_token": 1.5}),
    ])
    report = perfgate.check_history(hist)
    assert [f.series for f in report.findings] == [
        "hardware.decode_ms_per_token"
    ]
    # and the reverse (a speedup) is clean
    hist2 = perfgate.History(rounds=[
        _round(1, {"value": 60.0, "decode_ms_per_token": 1.5}),
        _round(2, {"value": 60.0, "decode_ms_per_token": 1.0}),
    ])
    assert perfgate.check_history(hist2).ok


def test_gate_acknowledged_drift_downgrades_to_note():
    """The conscious-override escape for HISTORY drift: once the current
    build matches a re-recorded baseline for the same series, archived
    round-to-round drift demotes to a note — an intentional collective
    change is not a permanent red gate.  Unacknowledged drift stays a
    finding."""
    hist_report = perfgate.GateReport(findings=[
        perfgate.GateFinding("fingerprint.ring.ppermute", 7, 9,
                             "drift r1 -> r2: 7 -> 9"),
        perfgate.GateFinding("fingerprint.ulysses.all_to_all", 4, 6,
                             "drift r1 -> r2: 4 -> 6"),
    ])
    base_report = perfgate.GateReport(
        checked=["fingerprint.ring.ppermute"],  # passed vs baseline
        findings=[],
    )
    perfgate._downgrade_acknowledged_drift(hist_report, base_report)
    assert [f.series for f in hist_report.findings] == [
        "fingerprint.ulysses.all_to_all"
    ]
    assert any("acknowledged" in n for n in hist_report.notes)


def test_gate_toy_round_fingerprint_drift():
    """Fingerprint drift BETWEEN bench rounds (both wedged — the CPU
    signal lands regardless) is caught without any baseline."""
    fp1 = {"ring": {"ppermute": 7}, "contract_ok": True}
    fp2 = {"ring": {"ppermute": 9}, "contract_ok": True}
    hist = perfgate.History(rounds=[
        _round(1, {"value": 0.0, "error": "wedged",
                   "collective_fingerprint": fp1}),
        _round(2, {"value": 0.0, "error": "wedged",
                   "collective_fingerprint": fp2}),
    ])
    report = perfgate.check_history(hist)
    assert len(report.findings) == 1
    assert report.findings[0].series == "fingerprint.ring.ppermute"


def test_history_ingest(tmp_path):
    """BENCH_r*.json (driver-wrapped or bare) + results.jsonl rows +
    probe_failure rows all land in one History."""
    (tmp_path / "docs" / "hwlogs").mkdir(parents=True)
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "n": 1, "parsed": {"value": 68.99, "tokens_per_sec": 26549},
    }))
    # tail-only wrapping (no parsed key) and a bare payload
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "n": 2, "tail": 'garbage\n{"value": 0.0, "error": "wedged"}\n',
    }))
    (tmp_path / "BENCH_r03.json").write_text(json.dumps({
        "value": 70.0, "metric": "x",
    }))
    (tmp_path / "BENCH_rBAD.json").write_text("{not json")
    rows = [
        {"step": "fwd262k", "date": "2026-07-29",
         "result": {"value": 68.99}},
        {"step": "probe_failure", "date": "2026-08-01",
         "result": {"error": "hung"}},
        {"step": "probe_failure", "date": "2026-08-02",
         "result": {"error": "hung again"}},
    ]
    (tmp_path / "docs" / "hwlogs" / "results.jsonl").write_text(
        "\n".join(json.dumps(r) for r in rows) + "\ntorn{"
    )
    hist = perfgate.load_history(str(tmp_path))
    assert [r.number for r in hist.rounds] == [1, 2, 3]
    assert [r.probe_ok for r in hist.rounds] == [True, False, True]
    assert len(hist.probe_failures) == 2
    assert hist.hwlog["fwd262k"]["result"]["value"] == 68.99


# ----------------------------------------------------------------------
# Numerics flight recorder
# ----------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _clean_resilience():
    yield
    resilience.reset()


def _guarded_quad_step():
    opt = optax.sgd(0.1)
    loss_fn = resilience.faulty_loss(
        lambda p, x: ((p["w"] * x) ** 2).mean()
    )
    step = jax.jit(make_train_step(
        loss_fn, opt, collect_metrics=True, skip_nonfinite=True
    ))
    params = {"w": jnp.asarray([1.0, 2.0])}
    return step, params, opt.init(params), jnp.ones((2,))


def test_flight_dump_on_injected_nan(tmp_path):
    """The acceptance pin: a NaN injected at step k (FaultInjector) dumps
    a JSON carrying the preceding rows AND the trigger — the trajectory,
    not a bare counter."""
    step, params, opt_state, x = _guarded_quad_step()
    rec = FlightRecorder(str(tmp_path), window=8,
                         context={"mesh": None, "seq_len": 2})
    m = init_train_metrics()
    for k in range(3):  # healthy prefix
        params, opt_state, m, _ = step(params, opt_state, m, x)
        assert rec.observe_step(k, m) is None
    with resilience.inject("nan_loss"):
        params, opt_state, m, _ = step(params, opt_state, m, x)
    path = rec.observe_step(3, m)
    assert path is not None and os.path.exists(path)
    dump = read_flight_dump(path)
    assert dump["schema"] == FLIGHT_SCHEMA_VERSION
    assert dump["trigger"]["kind"] == "nonfinite_skip"
    assert dump["trigger"]["step"] == 3
    assert dump["context"]["seq_len"] == 2
    rows = dump["rows"]
    assert [r["step"] for r in rows] == [0, 1, 2, 3]
    assert all(r["step_ok"] for r in rows[:3])
    assert not rows[-1]["step_ok"] and rows[-1]["nonfinite"] == 1
    # recovery does NOT re-dump (counters flat again)
    params, opt_state, m, _ = step(params, opt_state, m, x)
    assert rec.observe_step(4, m) is None
    assert len(rec.dumps) == 1


def test_flight_window_is_a_ring_buffer(tmp_path):
    rec = FlightRecorder(str(tmp_path), window=4)
    for k in range(10):
        rec.record(k, loss=float(k))
    path = rec.dump("manual")
    rows = read_flight_dump(path)["rows"]
    assert [r["step"] for r in rows] == [6, 7, 8, 9]


def test_flight_guard_dumps_on_crash(tmp_path):
    from ring_attention_tpu.analysis.recompile import RetraceError

    rec = FlightRecorder(str(tmp_path), window=4)
    rec.record(0, loss=1.0)
    with pytest.raises(RetraceError):
        with rec.guard("loop"):
            raise RetraceError("entry recompiled 3x")
    dump = read_flight_dump(rec.dumps[-1])
    assert dump["trigger"]["kind"] == "crash"
    assert "RetraceError" in dump["trigger"]["error"]
    assert dump["rows"][-1]["loss"] == 1.0


def test_flight_install_dumps_on_degradation_and_retry_failure(tmp_path):
    """install() wires the host-side triggers: a forced Pallas failure
    and an exhausted retry ladder each produce a dump."""
    resilience.reset()
    rec = FlightRecorder(str(tmp_path), window=4).install()
    rec.record(7, loss=2.0)
    with resilience.inject(resilience.PALLAS_FAULT):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert not resilience.pallas_available(refresh=True)
    kinds = [read_flight_dump(p)["trigger"]["kind"] for p in rec.dumps]
    assert "degraded" in kinds

    def always_fails():
        raise RuntimeError("boom")

    with pytest.raises(resilience.RetryError):
        resilience.with_retries(always_fails, max_attempts=2, backoff=0.0,
                                sleep=lambda s: None)
    kinds = [read_flight_dump(p)["trigger"]["kind"] for p in rec.dumps]
    assert "retry_exhausted" in kinds
    last = read_flight_dump(rec.dumps[-1])
    assert last["trigger"]["where"] == "always_fails"
    assert "boom" in last["trigger"]["error"]
    assert last["rows"][-1]["step"] == 7  # the trajectory rode along
    rec.uninstall()  # detach from the process-global registries


def test_truncated_capture_degrades_to_note(tmp_path):
    """A capture truncated mid-write (killed profiler — the wedge mode
    this repo knows) must return a note, never raise."""
    bad = tmp_path / "x.xplane.pb"
    # field 1, length-delimited, claims 200 bytes then ends mid-varint
    bad.write_bytes(b"\x0a\xc8\x01" + b"\x08\xff\xff")
    events, note = read_xplane_events(str(tmp_path))
    assert events == []
    assert note  # a reason, not a traceback


def test_flight_resume_counters_do_not_false_alarm(tmp_path):
    """A resumed run whose checkpoint carried nonzero skipped/nonfinite
    counters (train.py seeds init_train_metrics from the checkpoint)
    must not dump on its first healthy step — watermarks seed from the
    first observed row."""
    rec = FlightRecorder(str(tmp_path), window=4)
    resumed = init_train_metrics(skipped=3, nonfinite=3)
    assert rec.observe_step(100, resumed) is None
    assert rec.dumps == []
    # but a genuinely advancing counter after the seed still triggers
    advanced = init_train_metrics(skipped=4, nonfinite=4)
    assert rec.observe_step(101, advanced) is not None


def test_flight_dump_rejects_unknown_schema(tmp_path):
    path = tmp_path / "flight_bad.json"
    path.write_text(json.dumps({"schema": 99, "rows": []}))
    with pytest.raises(ValueError, match="schema"):
        read_flight_dump(str(path))


def test_flight_dump_cap_per_trigger(tmp_path):
    """A run that goes permanently non-finite must not write one dump
    per step forever: the per-trigger cap keeps the first N and counts
    the rest as suppressed (a different trigger kind still dumps)."""
    rec = FlightRecorder(str(tmp_path), window=4, max_dumps_per_trigger=2)
    assert rec.dump("nonfinite_skip") is not None
    assert rec.dump("nonfinite_skip") is not None
    assert rec.dump("nonfinite_skip") is None  # capped
    assert rec.dump("nonfinite_skip") is None
    assert rec.suppressed["nonfinite_skip"] == 2
    assert len(rec.dumps) == 2
    path = rec.dump("crash")  # other kinds unaffected
    assert path is not None
    assert any(e.get("event") == "flight_dumps_capped"
               for e in read_flight_dump(path)["events"])


def test_flight_dump_write_failure_returns_none(tmp_path):
    """A failed write (full disk) must not hand the caller a path to a
    file that was never written."""
    rec = FlightRecorder(str(tmp_path), window=4)
    rec.directory = os.path.join(str(tmp_path), "gone", "deeper")
    assert rec.dump("manual") is None
    assert rec.dumps == []
    rec.directory = str(tmp_path)
    path = rec.dump("manual")  # the failure event rode into this dump
    assert path is not None
    assert any(e.get("event") == "flight_dump_failed"
               for e in read_flight_dump(path)["events"])


def test_flight_uninstall_detaches_listeners(tmp_path):
    resilience.reset()
    rec = FlightRecorder(str(tmp_path), window=4).install()
    rec.uninstall()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # first-degradation warning
        resilience.degradation.record("toy_component", "boom")
    assert rec.dumps == []  # detached: the degradation did not dump
