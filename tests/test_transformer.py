"""Parity: end-to-end RingTransformer, ring vs regular attention.

JAX-native analogue of the reference's ``assert.py``: a depth-2 transformer
with ring attention + auto-shard over 8 devices must match the identical
parameters run with regular attention — forward logits, loss, and
token-embedding gradients (ref ``assert.py:114-137``) — including striped
layout, odd sequence lengths (padding), GQA, and a 2x4 mesh
(``num_sharded_batches`` analogue).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ring_attention_tpu.models import RingTransformer
from ring_attention_tpu.parallel import create_mesh

ATOL = 3e-5
GRAD_ATOL = 1e-3  # ref uses 1e-2 for embedding grads (assert.py:131-135)

VOCAB = 256


def make_pair(mesh, **kw):
    common = dict(
        num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
        bucket_size=4, causal=True,
    )
    common.update(kw)
    ring_model = RingTransformer(use_ring=True, mesh=mesh, **common)
    ref_model = RingTransformer(
        use_ring=False, force_regular_attn=True,
        **{k: v for k, v in common.items() if k not in ("striped", "use_pallas", "sequence_parallel")},
    )
    return ring_model, ref_model


@pytest.fixture(scope="module")
def mesh():
    return create_mesh(ring_size=8)


@pytest.mark.parametrize("striped", [False, True])
@pytest.mark.parametrize("seq_len", [64, 63])
def test_logits_parity(rng, mesh, striped, seq_len):
    ring_model, ref_model = make_pair(mesh, striped=striped)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, seq_len)), jnp.int32)
    params = ref_model.init(jax.random.PRNGKey(0), tokens)
    ref = ref_model.apply(params, tokens)
    out = ring_model.apply(params, tokens)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_loss_and_embedding_grads(rng, mesh):
    """Token-embedding gradient parity through loss (ref assert.py:125-135)."""
    ring_model, ref_model = make_pair(mesh, striped=True)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 63)), jnp.int32)
    params = ref_model.init(jax.random.PRNGKey(0), tokens)

    def loss(model, p):
        return model.apply(p, tokens, return_loss=True)

    l_ref = loss(ref_model, params)
    l_ring = loss(ring_model, params)
    np.testing.assert_allclose(l_ring, l_ref, atol=ATOL)

    g_ref = jax.grad(lambda p: loss(ref_model, p))(params)
    g_ring = jax.grad(lambda p: loss(ring_model, p))(params)
    emb_ref = g_ref["params"]["embed"]["embedding"]
    emb_ring = g_ring["params"]["embed"]["embedding"]
    np.testing.assert_allclose(emb_ring, emb_ref, atol=GRAD_ATOL)


def test_gqa_and_lookback(rng, mesh):
    """GQA + per-layer lookback tuple (local -> global over depth)."""
    ring_model, ref_model = make_pair(
        mesh, striped=False, kv_heads=2, max_lookback_seq_len=(16, None)
    )
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 64)), jnp.int32)
    params = ref_model.init(jax.random.PRNGKey(0), tokens)
    np.testing.assert_allclose(
        ring_model.apply(params, tokens), ref_model.apply(params, tokens), atol=ATOL
    )


def test_data_parallel_rings(rng):
    """2x4 mesh: batch over data axis, two independent rings."""
    mesh = create_mesh(ring_size=4, data_size=2)
    ring_model, ref_model = make_pair(mesh, striped=True)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (4, 64)), jnp.int32)
    params = ref_model.init(jax.random.PRNGKey(0), tokens)
    np.testing.assert_allclose(
        ring_model.apply(params, tokens), ref_model.apply(params, tokens), atol=ATOL
    )


def test_non_causal_with_mask(rng, mesh):
    ring_model, ref_model = make_pair(mesh, causal=False)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 63)), jnp.int32)
    mask = jnp.asarray(rng.random((2, 63)) > 0.2)
    params = ref_model.init(jax.random.PRNGKey(0), tokens, mask)
    np.testing.assert_allclose(
        ring_model.apply(params, tokens, mask),
        ref_model.apply(params, tokens, mask),
        atol=ATOL,
    )


def test_non_causal_padding_without_mask(rng, mesh):
    """Padding in non-causal mode must not let real tokens attend pad slots
    even when the user passes no mask (regression: synthesized pad mask)."""
    ring_model, ref_model = make_pair(mesh, causal=False)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 61)), jnp.int32)
    params = ref_model.init(jax.random.PRNGKey(0), tokens)
    np.testing.assert_allclose(
        ring_model.apply(params, tokens), ref_model.apply(params, tokens), atol=ATOL
    )


def test_odd_bucket_interaction(rng, mesh):
    """seq 56 over ring 8 -> n_local 7, bucket_size 4 not a divisor."""
    ring_model, ref_model = make_pair(mesh, striped=True)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 56)), jnp.int32)
    params = ref_model.init(jax.random.PRNGKey(0), tokens)
    np.testing.assert_allclose(
        ring_model.apply(params, tokens), ref_model.apply(params, tokens), atol=ATOL
    )


def test_pallas_transformer_parity(rng, mesh):
    """End-to-end transformer on the Pallas kernel path (interpret on CPU)."""
    ring_model, ref_model = make_pair(mesh, striped=True, use_pallas=True)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 64)), jnp.int32)
    params = ref_model.init(jax.random.PRNGKey(0), tokens)
    np.testing.assert_allclose(
        ring_model.apply(params, tokens), ref_model.apply(params, tokens),
        atol=ATOL,
    )


def test_bf16_training_path(rng, mesh):
    """bf16 activations end-to-end: loss finite and grads flow."""
    model = RingTransformer(
        num_tokens=VOCAB, dim=32, depth=1, heads=4, dim_head=8,
        causal=True, striped=True, bucket_size=8, mesh=mesh,
        dtype=jnp.bfloat16,
    )
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 65)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    loss, grads = jax.value_and_grad(
        lambda p: model.apply(p, tokens, return_loss=True)
    )(params)
    assert bool(jnp.isfinite(loss))
    assert all(bool(jnp.isfinite(g).all()) for g in jax.tree.leaves(grads))


def test_remat_parity(rng, mesh):
    """remat=True must not change values (only memory/recompute)."""
    common = dict(num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
                  bucket_size=4, causal=True, striped=True, mesh=mesh)
    m1 = RingTransformer(**common)
    m2 = RingTransformer(remat=True, **common)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 64)), jnp.int32)
    params = m1.init(jax.random.PRNGKey(0), tokens)
    # remat + shard_map requires jit (as any real train step is)
    l1, g1 = jax.jit(jax.value_and_grad(lambda p: m1.apply(p, tokens, return_loss=True)))(params)
    l2, g2 = jax.jit(jax.value_and_grad(lambda p: m2.apply(p, tokens, return_loss=True)))(params)
    np.testing.assert_allclose(l1, l2, atol=1e-6)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(a, b, atol=1e-5)


def test_remat_save_attn_policy_parity(rng, mesh):
    """remat_policy="save_attn" (saved flash residuals, no O(n^2) recompute
    in the backward) must be value-identical to plain full-block remat."""
    common = dict(num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
                  bucket_size=4, causal=True, striped=True, mesh=mesh,
                  remat=True)
    m1 = RingTransformer(**common)
    m2 = RingTransformer(remat_policy="save_attn", **common)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 64)), jnp.int32)
    params = m1.init(jax.random.PRNGKey(0), tokens)
    l1, g1 = jax.jit(jax.value_and_grad(lambda p: m1.apply(p, tokens, return_loss=True)))(params)
    l2, g2 = jax.jit(jax.value_and_grad(lambda p: m2.apply(p, tokens, return_loss=True)))(params)
    np.testing.assert_allclose(l1, l2, atol=1e-6)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(a, b, atol=1e-5)


def _train_dots(model, params, tokens):
    """Number of dot ops in the compiled train step (scan bodies count once,
    so an elided attention recompute is a strict drop regardless of trip
    count — CPU cost_analysis flops don't scale scan bodies and can't see
    the gap)."""
    f = jax.jit(
        jax.value_and_grad(lambda p, t: model.apply(p, t, return_loss=True))
    )
    return f.lower(params, tokens).compile().as_text().count("dot(")


@pytest.mark.parametrize("use_mesh", [False, True], ids=["local", "ring"])
def test_remat_save_attn_actually_elides(rng, mesh, use_mesh):
    """remat_policy="save_attn" must REDUCE backward compute, not just match
    values: the saved (flash_out, flash_lse) residuals let the backward's
    residual recompute dead-code-eliminate the attention forward.  The
    parity test above passes even if the policy names match nothing
    (ADVICE r2); this pins the elision itself in the compiled program: the
    score and pv matmuls (2 per layer) must vanish from the recompute."""
    common = dict(num_tokens=32, dim=32, depth=2, heads=4, dim_head=8,
                  bucket_size=8, causal=True, remat=True)
    if use_mesh:
        common.update(mesh=mesh, striped=True)
    else:
        common.update(use_ring=False)
    m_plain = RingTransformer(**common)
    m_save = RingTransformer(remat_policy="save_attn", **common)
    tokens = jnp.asarray(rng.integers(0, 32, (2, 128)), jnp.int32)
    params = m_plain.init(jax.random.PRNGKey(0), tokens)
    dots_plain = _train_dots(m_plain, params, tokens)
    dots_save = _train_dots(m_save, params, tokens)
    assert dots_save <= dots_plain - 2 * m_plain.depth, (dots_save, dots_plain)


@pytest.mark.slow
def test_variable_per_rank_batch(rng):
    """Variable per-rank batch through the model path (the reference's
    ``batch_size_var_len``, assert_attn.py:81-82 via distributed.py:58-84):
    data-parallel rows contribute DIFFERENT numbers of real examples, padded
    to a static max and masked out of the loss with ``example_mask``.  Loss
    and token-embedding grads must match running only the real examples."""
    mesh = create_mesh(ring_size=4, data_size=2)
    ring_model, ref_model = make_pair(mesh, striped=True)

    n = 64
    # data row 0 holds 1 real example, row 1 holds 2 (base + rank, like the
    # reference's var-len test); pad both rows to 2
    real = jnp.asarray(rng.integers(0, VOCAB, (3, n)), jnp.int32)
    pad_example = jnp.zeros((1, n), jnp.int32)
    padded = jnp.concatenate([real[:1], pad_example, real[1:]], axis=0)  # (4, n)
    example_mask = jnp.asarray([True, False, True, True])

    params = ref_model.init(jax.random.PRNGKey(0), real)

    l_ref = ref_model.apply(params, real, return_loss=True)
    l_ring = ring_model.apply(
        params, padded, return_loss=True, example_mask=example_mask
    )
    np.testing.assert_allclose(l_ring, l_ref, atol=ATOL)

    g_ref = jax.grad(lambda p: ref_model.apply(p, real, return_loss=True))(params)
    g_ring = jax.grad(
        lambda p: ring_model.apply(
            p, padded, return_loss=True, example_mask=example_mask
        )
    )(params)
    np.testing.assert_allclose(
        g_ring["params"]["embed"]["embedding"],
        g_ref["params"]["embed"]["embedding"],
        atol=GRAD_ATOL,
    )


def test_variable_batch_gather_roundtrip(rng):
    """all_gather_variable feeds the padded-batch recipe: ragged per-device
    shards gather into (padded global, validity mask) whose real rows are
    exactly the unpadded examples — the mask is what example_mask consumes."""
    from ring_attention_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from ring_attention_tpu.parallel import all_gather_variable, create_mesh

    mesh = create_mesh(ring_size=1, data_size=8)
    max_b, n = 3, 8
    x = jnp.asarray(rng.integers(0, VOCAB, (8 * max_b, n)), jnp.int32)
    lengths = jnp.asarray([(1 + r) % (max_b + 1) for r in range(8)], jnp.int32)

    def gather(x, length):
        g, m = all_gather_variable(x, length[0], "data", axis=0)
        return g, m

    g, m = shard_map(
        gather, mesh=mesh,
        in_specs=(P("data", None), P("data")),
        out_specs=(P(), P()),
        check_vma=False,  # outputs replicated over the trivial seq axis too
    )(x, lengths)
    assert g.shape == (8 * max_b, n)
    assert int(m.sum()) == int(lengths.sum())
    # masked rows are exactly each shard's first `length` rows
    want = np.zeros(8 * max_b, bool)
    for r in range(8):
        want[r * max_b : r * max_b + int(lengths[r])] = True
    np.testing.assert_array_equal(np.asarray(m), want)


@pytest.mark.parametrize("sp", ["zigzag", "ulysses"])
def test_transformer_sequence_parallel_modes(rng, mesh, sp):
    """End-to-end transformer under each context-parallel scheme."""
    ring_model, ref_model = make_pair(mesh, sequence_parallel=sp, heads=8, dim_head=4)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 63)), jnp.int32)
    params = ref_model.init(jax.random.PRNGKey(0), tokens)
    np.testing.assert_allclose(
        ring_model.apply(params, tokens), ref_model.apply(params, tokens), atol=ATOL
    )


def test_ring_dkv_dtype_through_model(rng, mesh):
    """ring_dkv_dtype="bfloat16" must reach the ring through the model
    layer (the train-path consumer it exists for): loss matches the f32
    circulation and grads stay finite and close."""
    common = dict(num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
                  bucket_size=8, causal=True, striped=True, mesh=mesh)
    m32 = RingTransformer(**common)
    m16 = RingTransformer(ring_dkv_dtype="bfloat16", **common)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 64)), jnp.int32)
    params = m32.init(jax.random.PRNGKey(0), tokens)
    l32, g32 = jax.jit(jax.value_and_grad(
        lambda p: m32.apply(p, tokens, return_loss=True)))(params)
    l16, g16 = jax.jit(jax.value_and_grad(
        lambda p: m16.apply(p, tokens, return_loss=True)))(params)
    np.testing.assert_allclose(l16, l32, atol=1e-6)  # fwd identical
    for a, b in zip(jax.tree.leaves(g16), jax.tree.leaves(g32)):
        assert bool(jnp.isfinite(a).all())
        np.testing.assert_allclose(a, b, atol=3e-2, rtol=3e-2)


@pytest.mark.parametrize("chunk,layout", [
    (8, "local"), (5, "local"), (64, "local"),  # 64 > n: clamp path
    (8, "striped"), (8, "zigzag"),
])
def test_chunked_ce_matches_dense(rng, chunk, layout):
    """loss_chunk_size: the rematted chunk-scan loss (and its gradients)
    equals the dense logits+CE path — including a chunk size that doesn't
    divide the sequence, one larger than the sequence (clamped), an
    ignore_index tail, and the striped/zig-zag paths where the features
    (not the logits) get un-permuted."""
    kw = dict(
        num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
        causal=True, bucket_size=8,
        **({"local": dict(use_ring=False),
            "striped": dict(mesh=create_mesh(ring_size=8), striped=True),
            "zigzag": dict(mesh=create_mesh(ring_size=8),
                           sequence_parallel="zigzag")}[layout]),
    )
    dense = RingTransformer(**kw)
    chunked = RingTransformer(loss_chunk_size=chunk, **kw)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 33)), jnp.int32)
    tokens = tokens.at[0, 20:].set(-1)  # ignore_index tail in row 0
    params = dense.init(jax.random.PRNGKey(0), jnp.abs(tokens))

    def loss_fn(model):
        return lambda p: model.apply(p, tokens, return_loss=True)

    ld = loss_fn(dense)(params)
    lc = loss_fn(chunked)(params)
    np.testing.assert_allclose(lc, ld, rtol=2e-6)

    gd = jax.grad(loss_fn(dense))(params)
    gc = jax.grad(loss_fn(chunked))(params)
    flat_d = jax.tree_util.tree_leaves_with_path(gd)
    flat_c = {jax.tree_util.keystr(p): l
              for p, l in jax.tree_util.tree_leaves_with_path(gc)}
    for p, leaf in flat_d:
        key = jax.tree_util.keystr(p)
        np.testing.assert_allclose(
            flat_c[key], leaf, atol=5e-6, err_msg=key
        )


def test_chunked_ce_program_does_not_materialize_logits(rng):
    """The chunked-loss jaxpr must contain no (b, n, vocab) intermediate —
    the whole point is that only (b, chunk, vocab) logits ever exist."""
    n, chunk = 64, 8
    model = RingTransformer(
        num_tokens=VOCAB, dim=16, depth=1, heads=2, dim_head=8,
        causal=True, bucket_size=8, use_ring=False, loss_chunk_size=chunk,
    )
    tokens = jnp.asarray(rng.integers(0, VOCAB, (1, n + 1)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    jaxpr = jax.make_jaxpr(
        lambda p: model.apply(p, tokens, return_loss=True)
    )(params)
    full = f"1,{n},{VOCAB}"
    assert full not in str(jaxpr), f"found full-logits shape ({full})"
