"""Smoke tests for the documented example entry points.

The examples are the README's advertised way in (`examples/train.py`,
`examples/generate.py`); these drive them as real subprocesses on the
8-virtual-device CPU mesh with tiny shapes so API drift in the package
surfaces here instead of on a user's terminal (VERDICT r4 weak #6).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(script: str, *args: str) -> str:
    env = dict(os.environ)
    # the example manages its own fake-device XLA flags; start clean so the
    # conftest's flags don't double up with conflicting values
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, (
        f"{script} rc={proc.returncode}\nstdout:\n{proc.stdout[-2000:]}"
        f"\nstderr:\n{proc.stderr[-2000:]}"
    )
    return proc.stdout


@pytest.mark.slow
def test_train_example_smoke():
    out = _run_example(
        "train.py", "--fake-devices", "8", "--steps", "4",
        "--seq-len", "64", "--dim", "32", "--batch", "2",
    )
    losses = [
        float(line.split("loss")[1].split()[0])
        for line in out.splitlines() if "loss" in line
    ]
    assert losses, f"no loss lines in output:\n{out[-1500:]}"
    # smoke bar, not an optimization bar: finite and not exploding after a
    # handful of updates (strict decrease over 3 tiny-lr steps would be
    # brittle to dependency-version numerics)
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0] * 1.05, f"loss exploded: {losses}"


@pytest.mark.slow
def test_train_example_hybrid():
    """--ulysses-size trains with the factored (data, ring, ulysses) mesh
    end-to-end (hybrid 2-D sequence parallelism + packing)."""
    out = _run_example(
        "train.py", "--fake-devices", "8", "--steps", "3",
        "--seq-len", "64", "--dim", "32", "--batch", "2",
        "--ulysses-size", "2", "--pack",
    )
    assert "'ring': 4" in out and "'ulysses': 2" in out, out[-1500:]
    losses = [
        float(line.split("loss")[1].split()[0])
        for line in out.splitlines() if "loss" in line
    ]
    assert losses and all(np.isfinite(losses)), losses


@pytest.mark.slow
def test_train_example_int8_compute():
    """--compute-dtype int8 trains end-to-end on the pallas ring (PR 13):
    quantized forward matmuls + dequant-free int8 hops, bf16 backward
    from exact residuals — losses stay finite and non-exploding."""
    out = _run_example(
        "train.py", "--fake-devices", "8", "--steps", "3",
        "--seq-len", "64", "--dim", "32", "--batch", "2",
        "--use-pallas", "--counter-rotate",
        "--hop-compression", "int8", "--compute-dtype", "int8",
    )
    losses = [
        float(line.split("loss")[1].split()[0])
        for line in out.splitlines() if "loss" in line
    ]
    assert losses and all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0] * 1.05, f"loss exploded: {losses}"


@pytest.mark.slow
def test_train_example_accum_remat_chunked_ce():
    out = _run_example(
        "train.py", "--fake-devices", "8", "--steps", "2",
        "--seq-len", "64", "--dim", "32", "--batch", "2",
        "--accum-steps", "2", "--remat", "--loss-chunk-size", "16",
    )
    assert "loss" in out


@pytest.mark.slow
def test_generate_example_greedy():
    out = _run_example(
        "generate.py", "--fake-devices", "8", "--steps", "4",
        "--prompt-len", "16", "--max-len", "32",
    )
    assert "generated 4 tokens" in out, out[-1500:]
    assert "tokens:" in out


@pytest.mark.slow
def test_generate_example_sampled_q8():
    out = _run_example(
        "generate.py", "--fake-devices", "8", "--steps", "4",
        "--prompt-len", "16", "--max-len", "32",
        "--temperature", "0.8", "--top-k", "50", "--q8-cache",
    )
    assert "sampled 4 tokens" in out, out[-1500:]


@pytest.mark.slow
def test_train_example_kill_and_resume(tmp_path):
    """The resilience acceptance check: a run killed mid-way and restarted
    with the same command resumes from the last good checkpoint and ends
    at the same loss as an uninterrupted run of the same length."""
    common = [
        "train.py", "--fake-devices", "2", "--steps", "6",
        "--seq-len", "64", "--dim", "32", "--batch", "2",
        "--ckpt-every", "1",
    ]

    def final_loss(out: str) -> float:
        losses = [
            float(line.split("loss")[1].split()[0])
            for line in out.splitlines() if "loss" in line
        ]
        assert losses, out[-1500:]
        return losses[-1]

    ref = final_loss(_run_example(*common))

    # the "kill": an identical run stopped after 3 steps (checkpointing
    # every step), then the full-length command rerun on the same dir
    ckpt = str(tmp_path / "ckpts")
    _run_example(*common[:4], "3", *common[5:], "--ckpt-dir", ckpt)
    out = _run_example(*common, "--ckpt-dir", ckpt)
    assert "resumed from checkpoint (continuing at step 3)" in out, out[-1500:]
    resumed = final_loss(out)
    assert abs(resumed - ref) < 1e-4, (ref, resumed)


@pytest.mark.slow
def test_train_example_guarded_flags():
    out = _run_example(
        "train.py", "--fake-devices", "2", "--steps", "3",
        "--seq-len", "64", "--dim", "32", "--batch", "2",
        "--skip-nonfinite", "--clip-grad-norm", "1.0",
    )
    assert "loss" in out
