"""Tile-coverage prover + jaxpr dataflow passes, tier-1.

Three layers, mirroring the PR-5 conventions in ``test_analysis.py``:

  - **positive proofs**: every strategy x layout x masking row of the
    coverage matrix is sound AND tight against the global-position
    oracle; the precision-flow and SPMD-divergence suites hold
    package-wide; the ``band_plan`` seam agrees with the launches.
  - **fingerprints**: the coverage fingerprint is deterministic, rides
    the perf gate's exact family, and a doctored tile count fails the
    gate with a one-line finding naming the row.
  - **seam checks**: ``band_plan`` validates its inputs, mirrors the
    launch-time doc-alignment fallback, and its closed-form/enumerated
    tile counts agree (the fuzz in ``tests/test_fuzz.py`` widens this).
"""

import numpy as np
import pytest

from ring_attention_tpu.analysis import coverage, dataflow
from ring_attention_tpu.ops.pallas_flash import (
    _MAX_COMPACT_TILES,
    _TF_EDGE,
    _TF_WORK,
    band_plan,
)


# ----------------------------------------------------------------------
# Positive proofs: the full matrix
# ----------------------------------------------------------------------


@pytest.mark.parametrize("case", coverage.CASES, ids=lambda c: c.name)
def test_coverage_case_sound_and_tight(case):
    """Acceptance: every row reports soundness (no live tile skipped, no
    interior tile hiding dead elements, schedule complete) and tightness
    (no dead tile visited, closed-form == enumeration) on CPU."""
    report = coverage.prove_case(case)
    assert report.ok, "\n".join(report.violations)
    assert report.hops > 0 and (report.tiles > 0 or report.name)


@pytest.mark.parametrize("case", coverage.MASK_CASES, ids=lambda c: c.name)
def test_mask_coverage_case_sound_and_tight(case):
    """Acceptance (PR 11): every mask-algebra row — band masks through
    the shipping band_plan/ring-hop seams, generic masks (prefix-LM,
    dilated, per-head, Or/Not compositions) through the algebra's tile
    classifier — proves sound, tight, and schedule-complete against the
    mask's own global-position oracle."""
    report = coverage.prove_mask_case(case)
    assert report.ok, "\n".join(report.violations)
    assert report.hops > 0


def test_mask_rows_match_legacy_band_rows():
    """The mask-algebra route re-derives the PR-9 rows bit-for-bit: the
    same geometries lowered through ``mask=`` produce exactly the legacy
    matrix's tile accounting (two independent routes, one grid)."""
    fp = coverage.coverage_fingerprint()
    for mask_row, legacy_row in [
        ("mask/single/causal", "single/causal"),
        ("mask/single/causal-window", "single/causal/window"),
        ("mask/ring/causal", "ring/contiguous"),
        ("mask/ring/causal-window", "ring/contiguous/window"),
        ("mask/ring/striped-window", "ring/striped/window"),
        ("mask/ring/limited-passes", "ring/limited-passes"),
        ("mask/counter/causal", "counter/contiguous"),
        ("mask/counter/window", "counter/window"),
    ]:
        assert fp[mask_row] == fp[legacy_row], (mask_row, legacy_row)


def test_coverage_matrix_is_enlarged():
    """Acceptance: the enlarged matrix holds >= 30 rows and is a strict
    superset of the original 16."""
    reports = coverage.run_coverage_suite()
    assert len(reports) >= 30
    names = {r.name for r in reports}
    assert {c.name for c in coverage.CASES} | {"zigzag/causal"} <= names


def test_coverage_zigzag_rect_grid():
    """The zig-zag path's rectangular-grid predicates (traced offsets, no
    tables) against the same oracle — including the ~half tile skip the
    causal band buys."""
    report = coverage.prove_zigzag()
    assert report.ok, "\n".join(report.violations)
    assert 0 < report.work < report.tiles  # the skip is real and partial


def test_precision_suite_package_clean():
    """Acceptance: the precision-flow auditor passes package-wide — both
    flash paths (fwd+bwd through the custom_vjps, Pallas kernel jaxprs
    included), the int8 hop chain, the counter bwd pack, the q8 decode."""
    for name, violations in dataflow.run_precision_suite():
        assert violations == [], f"{name}:\n" + "\n".join(violations)


def test_divergence_suite_all_strategies(devices):
    """Acceptance: branch-invariant collective sequences proven for every
    strategy, both impls, fwd and fwdbwd."""
    for name, violations in dataflow.run_divergence_suite():
        assert violations == [], f"{name}:\n" + "\n".join(violations)


# ----------------------------------------------------------------------
# The band_plan seam
# ----------------------------------------------------------------------


def test_band_plan_matches_launch_tables():
    """The public seam returns exactly the tables a launch would build
    (same internals, public signature) and the closed form matches."""
    plan = band_plan((64, 64), (8, 8), 0)
    assert plan.tiles == len(plan.tile_q) == 36
    assert plan.compact and plan.block_q == plan.block_k == 8
    # block sizes default through the same fitting as the launches
    auto = band_plan((64, 64), None, 0)
    assert (auto.block_q, auto.block_k) == (64, 64)  # min(nq, DEFAULT)


def test_band_plan_hint_forms():
    """int / (hi, lo) / 4-tuple hints normalize identically."""
    a = band_plan((64, 64), (8, 8), 5)
    b = band_plan((64, 64), (8, 8), (5, None))
    c = band_plan((64, 64), (8, 8), (5, 5, 0, 0), windowed=False)
    assert a.hint == b.hint == c.hint == (5, 5, 0, 0)
    w = band_plan((64, 64), (8, 8), (0, -15))
    assert w.windowed and w.hint == (0, 0, -15, -15)
    with pytest.raises(ValueError, match="windowed"):
        band_plan((64, 64), (8, 8), (0, 0, -15, -15))
    with pytest.raises(ValueError, match="hi"):
        band_plan((64, 64), (8, 8), 0, windowed=True)


def test_band_plan_doc_alignment_fallback():
    """A misaligned declared layout mirrors the launch-time fallback:
    band-only tables, doc_aligned=False; aligned layouts drop the
    cross-document tiles."""
    aligned = band_plan((64, 64), (8, 8), 0, doc_starts=(0, 32))
    misaligned = band_plan((64, 64), (8, 8), 0, doc_starts=(0, 33))
    plain = band_plan((64, 64), (8, 8), 0)
    assert aligned.doc_aligned and aligned.work_tiles < plain.work_tiles
    assert not misaligned.doc_aligned
    assert misaligned.work_tiles == plain.work_tiles
    with pytest.raises(ValueError, match="sorted unique"):
        band_plan((64, 64), (8, 8), 0, doc_starts=(16, 32))


def test_band_plan_compact_flag_tracks_smem_cap():
    plan = band_plan((64, 64), (8, 8), 64)  # full rectangle, 64 tiles
    assert plan.tiles == 64 and plan.compact
    assert _MAX_COMPACT_TILES >= plan.tiles


# ----------------------------------------------------------------------
# Fingerprint + gate wiring
# ----------------------------------------------------------------------


def test_coverage_fingerprint_deterministic_and_ok():
    fp1 = coverage.coverage_fingerprint()
    fp2 = coverage.coverage_fingerprint()
    assert fp1 == fp2
    assert fp1["coverage_ok"] is True
    assert fp1["single/causal"]["tiles"] == 36
    # every matrix row lands in the fingerprint — the fixed strategy x
    # layout x masking rows, zig-zag, the mask-algebra rows, and the
    # fused-ring table rows (PR 18)
    assert set(fp1) - {"coverage_ok"} == (
        {c.name for c in coverage.CASES}
        | {"zigzag/causal"}
        | {c.name for c in coverage.MASK_CASES}
        | {c.name for c in coverage.FUSED_CASES}
    )


def test_gate_catches_coverage_regression(tmp_path):
    """A tile-count change (a future mask change visiting dead tiles)
    fails the perf gate exactly like a collective-contract violation —
    and the committed baseline carries the coverage family so the gate
    actually compares it."""
    import json

    from ring_attention_tpu.analysis import perfgate

    baseline_path = tmp_path / "perf_baseline.json"
    current = {
        "gate_schema": perfgate.GATE_SCHEMA_VERSION,
        "jax": "0",
        "coverage": coverage.coverage_fingerprint(),
    }
    perfgate.write_baseline(current, str(baseline_path))
    report = perfgate.check_baseline(
        current, json.loads(baseline_path.read_text())
    )
    assert report.ok and any(
        s.startswith("coverage.") for s in report.checked
    )
    drifted = json.loads(json.dumps(current))
    drifted["coverage"]["single/causal"]["tiles"] += 3
    report = perfgate.check_baseline(
        drifted, json.loads(baseline_path.read_text())
    )
    assert not report.ok
    [finding] = report.findings
    assert finding.series == "coverage.single/causal.tiles"
    assert "\n" not in str(finding)


def test_committed_baseline_has_coverage_family():
    """docs/perf_baseline.json carries the coverage rows and the current
    build matches them exactly (the compile-free gate subset)."""
    import json
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    baseline = json.load(open(os.path.join(root, "docs",
                                           "perf_baseline.json")))
    assert baseline["signals"]["coverage"] == coverage.coverage_fingerprint()


# ----------------------------------------------------------------------
# The walker itself: descent + fixpoint behavior the passes rely on
# ----------------------------------------------------------------------


def test_walker_descends_into_pallas_kernels():
    """The precision pass must see INSIDE pl.pallas_call — the kernel
    jaxpr's dots and reductions are the actual accumulator contract."""
    import jax
    import jax.numpy as jnp

    from ring_attention_tpu.ops import pallas_flash

    pf = dataflow.PrecisionFlow()
    closed = jax.make_jaxpr(
        lambda q, k, v: pallas_flash.pallas_flash_partials(
            q, k, v, scale=1.0, causal_offset=0, block_q=16, block_k=16,
            interpret=True,
        )
    )(*[jnp.ones((1, 2, 32, 8), jnp.bfloat16)] * 3)
    assert pf.run(closed) == []
    kernel_sinks = [s for s in pf.sinks_checked if "pallas_call" in s]
    assert kernel_sinks, "kernel jaxpr was not walked"


def test_walker_scan_carry_fixpoint():
    """Taint introduced on a later scan iteration still reaches the
    carry's consumers (the fixpoint sweep, not a single pass)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def f(x8, y):
        def body(c, _):
            # carry picks up int8-derived content only via the loop
            return c + x8.astype(jnp.float32).sum(), None
        out, _ = lax.scan(body, y, jnp.arange(3))
        return out

    closed = jax.make_jaxpr(f)(jnp.ones((4,), jnp.int8),
                               jnp.float32(0.0))
    violations = dataflow.PrecisionFlow().run(closed, label="toy")
    assert any("int8" in v for v in violations)


def test_collective_signature_structural():
    """Signatures are scan-aware and order-sensitive — the property the
    divergence equality check rests on."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ring_attention_tpu.parallel.mesh import SEQ_AXIS, create_mesh
    from ring_attention_tpu.utils import compat

    mesh = create_mesh(ring_size=8)
    spec = P("data", None, "seq", None)
    perm = [(j, (j + 1) % 8) for j in range(8)]

    def scanned(q):
        def body(c, _):
            return lax.ppermute(c, SEQ_AXIS, perm), None
        out, _ = lax.scan(body, q, jnp.arange(4))
        return out

    fn = compat.shard_map(scanned, mesh=mesh, in_specs=(spec,),
                          out_specs=spec, check_vma=False)
    x = jnp.ones((1, 2, 64, 8), jnp.float32)
    sig = dataflow.collective_signature(jax.make_jaxpr(fn)(x))
    flat = str(sig)
    assert "scan" in flat and "ppermute" in flat
