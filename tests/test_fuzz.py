"""Randomized-config sweep: every context-parallel mode vs the oracle.

A compact fuzz over (batch, heads, kv_heads, seq, dim_head, mode, causal,
softclamp, window) combinations with fixed seeds — robustness evidence
beyond the targeted parity tests.
"""

import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ring_attention_tpu.models import RingAttention
from ring_attention_tpu.parallel import create_mesh

ATOL = 3e-5

CASES = [
    # (b, heads, kv_heads, n, dh, sp, striped, causal, softclamp, window, bidi)
    (1, 2, 1, 37, 8, "ring", False, True, None, None, False),
    (2, 4, 2, 96, 16, "ring", True, True, 5.0, None, False),
    (1, 4, 4, 64, 8, "ring", False, True, None, 16, False),
    (2, 4, 2, 80, 8, "ring", True, True, None, 24, False),
    (1, 8, 8, 48, 8, "zigzag", False, True, None, None, False),
    (2, 8, 4, 61, 16, "zigzag", False, True, 5.0, None, False),
    (1, 8, 8, 72, 8, "ulysses", False, True, None, None, False),
    (2, 16, 8, 56, 8, "ulysses", False, False, None, None, False),
    (2, 4, 4, 33, 8, "ring", False, False, None, None, False),
    (1, 8, 8, 40, 16, "ulysses", False, True, None, 12, False),
    # bidirectional half-KV streams (even and odd-shard-fallback shapes)
    (2, 4, 2, 96, 8, "ring", True, True, None, None, True),
    (1, 4, 4, 64, 8, "ring", False, True, 5.0, None, True),
    (2, 4, 2, 33, 8, "ring", False, False, None, None, True),  # odd: warns
]


@pytest.fixture(scope="module")
def mesh():
    return create_mesh(ring_size=8)


@pytest.mark.parametrize("case", CASES, ids=[f"case{i}" for i in range(len(CASES))])
def test_fuzz_configs(mesh, case):
    b, h, kvh, n, dh, sp, striped, causal, softclamp, window, bidi = case
    rng = np.random.default_rng(zlib.crc32(repr(case).encode()))
    dim = h * dh
    common = dict(
        dim=dim, heads=h, dim_head=dh, kv_heads=kvh, causal=causal,
        bucket_size=8, softclamp_value=softclamp, max_lookback_seq_len=window,
    )
    sharded = RingAttention(
        use_ring=True, auto_shard=True, mesh=mesh, sequence_parallel=sp,
        striped=striped, ring_bidirectional=bidi, **common,
    )
    oracle = RingAttention(use_ring=False, **common)
    x = jnp.asarray(rng.standard_normal((b, n, dim)), jnp.float32)
    params = oracle.init(jax.random.PRNGKey(0), x)
    n_local = -(-n // 8)  # auto_shard pads n up to the ring multiple
    if bidi and n_local % 2:
        # odd shard: must fall back to unidirectional LOUDLY
        with pytest.warns(UserWarning, match="ring_bidirectional requested"):
            out = sharded.apply(params, x)
    else:
        out = sharded.apply(params, x)
    np.testing.assert_allclose(
        out, oracle.apply(params, x), atol=ATOL, err_msg=str(case),
    )


SEG_CASES = [
    # (b, heads, kv_heads, n, dh, sp, striped, causal, n_docs, use_pallas)
    # (the targeted layout/path matrix lives in tests/test_segments.py;
    # these draw RANDOM packings over the schemes)
    (1, 2, 1, 37, 8, "ring", False, True, 3, False),
    (1, 8, 4, 48, 8, "zigzag", False, True, 3, False),
    (2, 8, 8, 56, 8, "ulysses", False, True, 2, False),
    (1, 4, 2, 64, 8, "ring", True, True, 3, True),  # pallas interpret
]


@pytest.mark.parametrize(
    "case", SEG_CASES, ids=[f"seg{i}" for i in range(len(SEG_CASES))]
)
def test_fuzz_random_packings(mesh, case):
    """Random document packings (case-seeded boundaries) through every
    context-parallel scheme vs the dense per-document oracle
    (force_regular_attn -> default_attention's independent segment-mask
    path)."""
    b, h, kvh, n, dh, sp, striped, causal, n_docs, use_pallas = case
    rng = np.random.default_rng(zlib.crc32(repr(("seg", case)).encode()))
    dim = h * dh
    # random packing: n_docs documents with random (>=2 token) boundaries
    cuts = np.sort(rng.choice(np.arange(2, n - 1), n_docs - 1, replace=False))
    ids = np.zeros(n, np.int32)
    for doc, start in enumerate(cuts):
        ids[start:] = doc + 1
    seg = jnp.asarray(np.broadcast_to(ids, (b, n)).copy())
    common = dict(dim=dim, heads=h, dim_head=dh, kv_heads=kvh, causal=causal,
                  bucket_size=8)
    sharded = RingAttention(
        use_ring=True, auto_shard=True, mesh=mesh, sequence_parallel=sp,
        striped=striped, use_pallas=use_pallas, **common,
    )
    oracle = RingAttention(use_ring=False, force_regular_attn=True, **common)
    x = jnp.asarray(rng.standard_normal((b, n, dim)), jnp.float32)
    params = oracle.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        sharded.apply(params, x, None, seg),
        oracle.apply(params, x, None, seg),
        atol=ATOL, err_msg=str(case),
    )


def test_band_tile_count_matches_enumeration_fuzz():
    """Hypothesis-style property, fixed seed: over fuzzed (nq, nk, bq,
    bk, band, window, doc_starts, iteration-order) shapes, the
    closed-form ``_band_tile_count`` equals the enumerated
    ``_band_tables`` length — the property every launch's SMEM-cap
    decision (and the coverage prover's tile accounting) rides on.
    Exercised through the public ``band_plan`` seam, which keeps the two
    implementations deliberately un-merged so this test means something.
    """
    from ring_attention_tpu.ops.pallas_flash import band_plan

    rng = np.random.default_rng(0xBA2D)
    for trial in range(150):
        bq = int(2 ** rng.integers(0, 4))  # 1..8
        bk = int(2 ** rng.integers(0, 4))
        n_blocks = int(rng.integers(1, 9))
        # doc_starts requires nq == nk; the band arithmetic itself is
        # exercised at unequal extents when no docs are drawn
        nq = bq * n_blocks
        nk = bk * n_blocks if rng.random() < 0.5 else bk * int(
            rng.integers(1, 9)
        )
        hi_w = int(rng.integers(-nq - 2, nk + 2))
        hi_i = hi_w - int(rng.integers(0, 3))
        windowed = bool(rng.random() < 0.5)
        lo_w = int(rng.integers(-nq - 2, hi_w + 1)) if windowed else 0
        lo_i = lo_w + int(rng.integers(0, 3)) if windowed else 0
        doc_starts = None
        if nq == nk and nq > 1 and rng.random() < 0.4:
            n_docs = int(rng.integers(1, 4))
            cuts = sorted({0, *(
                int(x) for x in rng.integers(1, nq, n_docs - 1)
            )})
            doc_starts = tuple(cuts)
        outer_is_q = bool(rng.random() < 0.5)
        plan = band_plan(
            (nq, nk), (bq, bk), (hi_w, hi_i, lo_w, lo_i),
            windowed=windowed, doc_starts=doc_starts,
            outer_is_q=outer_is_q,
        )
        assert plan.tiles == len(plan.tile_q), (
            f"trial {trial}: closed form {plan.tiles} != enumerated "
            f"{len(plan.tile_q)} at nq={nq} nk={nk} bq={bq} bk={bk} "
            f"hint={(hi_w, hi_i, lo_w, lo_i)} windowed={windowed} "
            f"docs={doc_starts} outer_is_q={outer_is_q}"
        )
        assert len(plan.tile_q) == len(plan.tile_k) == len(plan.flags)


def _rand_composition(rng, depth=0):
    """Random mask-algebra composition: window ∧ causal, prefix ∨ docs,
    dilated, negations — the space the certifier must hold."""
    from ring_attention_tpu import masks as M

    roll = rng.random()
    if depth < 2 and roll < 0.35:
        kind = rng.integers(0, 3)
        if kind == 0:
            return M.And((_rand_composition(rng, depth + 1),
                          _rand_composition(rng, depth + 1)))
        if kind == 1:
            return M.Or((_rand_composition(rng, depth + 1),
                         _rand_composition(rng, depth + 1)))
        return M.Not(_rand_composition(rng, depth + 1))
    kind = rng.integers(0, 6)
    if kind == 0:
        return M.Causal()
    if kind == 1:
        return M.Full()
    if kind == 2:
        return M.SlidingWindow(int(rng.integers(1, 48)))
    if kind == 3:
        return M.PrefixLM(int(rng.integers(0, 48)))
    if kind == 4:
        s = int(rng.integers(1, 6))
        return M.Dilated(s, int(rng.integers(0, s)))
    cuts = sorted({0, *(int(x) for x in rng.integers(1, 60, 2))})
    return M.DocumentMask(tuple(cuts))


def test_mask_composition_lowering_property_fuzz():
    """Property test over ~150 random mask COMPOSITIONS (window ∧
    causal, prefix ∨ document, dilated, negations) across single / ring
    / counter geometries: every lowered grid proves sound, tight, and
    schedule-complete against the composition's own oracle; every
    plan's closed-form tile count equals its enumerated table; and on
    single sweeps the grid reconstructs the dense oracle exactly
    (work/edge tiles + runtime masks == the mask, element for element).
    """
    from ring_attention_tpu import masks as M
    from ring_attention_tpu.analysis import coverage
    from ring_attention_tpu.ops.pallas_flash import _TF_EDGE, _TF_WORK

    rng = np.random.default_rng(0xC0FFEE)
    for trial in range(150):
        mask = _rand_composition(rng)
        pick = trial % 3
        if pick == 0:
            spec = M.GridSpec(strategy="ring", ring=4, n_local=16,
                              block_q=4, block_k=4)
        elif pick == 1:
            spec = M.GridSpec(strategy="single",
                              n_local=int(rng.choice([32, 48, 64])),
                              block_q=8, block_k=8)
        else:
            spec = M.GridSpec(strategy="counter", ring=4, n_local=16,
                              block_q=4, block_k=4)
        report = coverage.prove_mask_lowering(mask, spec)
        assert report.ok, (
            f"trial {trial} {mask.key} on {spec.strategy}:\n"
            + "\n".join(report.violations)
        )
        low = M.lower(mask, spec)
        for hop in low.hops:
            for plan in (hop.plan, hop.plan_kmajor):
                if plan is not None:
                    assert plan.tiles == len(plan.tile_q), (
                        f"trial {trial} {mask.key}: closed form "
                        f"{plan.tiles} != enumerated {len(plan.tile_q)}"
                    )
        if spec.strategy != "single":
            continue
        # dense-oracle parity of the lowered grid, reconstructed tile
        # by tile exactly as a kernel would compute it
        n, bq, bk = spec.n_local, spec.block_q, spec.block_k
        oracle = mask.oracle(np.arange(n), np.arange(n))
        hop = low.hops[0]
        rp = hop.ranks[0]
        if not rp.has_work:
            assert not oracle.any()
            continue
        if hop.full:
            assert oracle.all(), f"trial {trial} {mask.key}"
            continue
        rt = (rp.rt_mask if rp.rt_mask is not None
              else coverage.band_mask(n, n, rp.hi, rp.lo))
        computed = np.zeros((n, n), bool)
        for t in range(len(hop.plan.flags)):
            f = int(hop.plan.flags[t])
            if not f & _TF_WORK:
                continue
            qs = slice(hop.plan.tile_q[t] * bq,
                       (hop.plan.tile_q[t] + 1) * bq)
            ks = slice(hop.plan.tile_k[t] * bk,
                       (hop.plan.tile_k[t] + 1) * bk)
            computed[qs, ks] = rt[qs, ks] if f & _TF_EDGE else True
        np.testing.assert_array_equal(
            computed, oracle, err_msg=f"trial {trial} {mask.key}"
        )


def test_bidirectional_bucket_divides_full_but_not_half():
    """Bucket divides the full shard but not the half-streams (n_local=12,
    bucket=4): the per-stream refit in parallel/ring.py must fit the bucket
    to the half length instead of tripping the XLA-path divisibility assert
    (ADVICE r2).  Gradients covered too (backward shares the refit)."""
    mesh = create_mesh(ring_size=8)
    b, h, dh, n = 2, 4, 8, 96  # n_local = 12
    common = dict(dim=h * dh, heads=h, dim_head=dh, causal=True, bucket_size=4)
    sharded = RingAttention(
        use_ring=True, auto_shard=True, mesh=mesh, striped=True,
        ring_bidirectional=True, **common,
    )
    oracle = RingAttention(use_ring=False, **common)
    rng = np.random.default_rng(12)
    x = jnp.asarray(rng.standard_normal((b, n, h * dh)), jnp.float32)
    params = oracle.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        sharded.apply(params, x), oracle.apply(params, x), atol=ATOL
    )
    g1 = jax.grad(lambda p: sharded.apply(p, x).astype(jnp.float32).sum())(params)
    g2 = jax.grad(lambda p: oracle.apply(p, x).astype(jnp.float32).sum())(params)
    for a, c in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(a, c, atol=1e-3)
