"""Parity: RingAttention module, ring vs regular attention.

JAX-native analogue of the reference's ``assert_attn.py``: the full module
(prenorm, fused qkv, rotary, ring dispatch, output projection) with
``use_ring + auto_shard`` over 8 devices must match the same parameters run
through the single-device oracle, for outputs and input gradients —
including an odd sequence length (31) to exercise padding, striped layout,
and GQA.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ring_attention_tpu.models import RingAttention
from ring_attention_tpu.parallel import create_mesh

ATOL = 2e-5
GRAD_ATOL = 5e-4


@pytest.fixture(scope="module")
def mesh():
    return create_mesh(ring_size=8)


def make_pair(mesh, **kw):
    """Ring module + oracle module sharing identical parameters."""
    common = dict(dim=32, heads=4, dim_head=8, bucket_size=4, **kw)
    ring_mod = RingAttention(
        use_ring=True, auto_shard=True, mesh=mesh, **common
    )
    ref_mod = RingAttention(
        use_ring=False, force_regular_attn=True,
        **{k: v for k, v in common.items() if k != "striped"},
    )
    return ring_mod, ref_mod


@pytest.mark.parametrize("striped", [False, True])
@pytest.mark.parametrize("seq_len", [32, 31])
def test_module_parity(rng, mesh, striped, seq_len):
    ring_mod, ref_mod = make_pair(mesh, causal=True, striped=striped)
    x = jnp.asarray(rng.standard_normal((2, seq_len, 32)), jnp.float32)
    params = ref_mod.init(jax.random.PRNGKey(0), x)
    ref = ref_mod.apply(params, x)
    out = ring_mod.apply(params, x)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_module_gqa(rng, mesh):
    ring_mod, ref_mod = make_pair(mesh, causal=True, striped=True, kv_heads=2)
    x = jnp.asarray(rng.standard_normal((2, 31, 32)), jnp.float32)
    params = ref_mod.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        ring_mod.apply(params, x), ref_mod.apply(params, x), atol=ATOL
    )


def test_module_key_padding(rng, mesh):
    ring_mod, ref_mod = make_pair(mesh, causal=False)
    x = jnp.asarray(rng.standard_normal((2, 31, 32)), jnp.float32)
    mask = jnp.asarray(rng.random((2, 31)) > 0.3)
    params = ref_mod.init(jax.random.PRNGKey(0), x, mask)
    np.testing.assert_allclose(
        ring_mod.apply(params, x, mask), ref_mod.apply(params, x, mask), atol=ATOL
    )


def test_module_input_grads(rng, mesh):
    """Input-gradient parity (ref assert_attn.py:126-137)."""
    ring_mod, ref_mod = make_pair(mesh, causal=True, striped=True)
    x = jnp.asarray(rng.standard_normal((2, 31, 32)), jnp.float32)
    params = ref_mod.init(jax.random.PRNGKey(0), x)

    g_ref = jax.grad(lambda x: (ref_mod.apply(params, x) ** 2).sum())(x)
    g_out = jax.grad(lambda x: (ring_mod.apply(params, x) ** 2).sum())(x)
    np.testing.assert_allclose(g_out, g_ref, atol=GRAD_ATOL)


def test_module_param_grads(rng, mesh):
    ring_mod, ref_mod = make_pair(mesh, causal=True)
    x = jnp.asarray(rng.standard_normal((2, 32, 32)), jnp.float32)
    params = ref_mod.init(jax.random.PRNGKey(0), x)

    g_ref = jax.grad(lambda p: (ref_mod.apply(p, x) ** 2).sum())(params)
    g_out = jax.grad(lambda p: (ring_mod.apply(p, x) ** 2).sum())(params)
    flat_ref = jax.tree_util.tree_leaves_with_path(g_ref)
    flat_out = dict(
        (jax.tree_util.keystr(k), v)
        for k, v in jax.tree_util.tree_leaves_with_path(g_out)
    )
    for key, ref_leaf in flat_ref:
        np.testing.assert_allclose(
            flat_out[jax.tree_util.keystr(key)], ref_leaf, atol=GRAD_ATOL,
            err_msg=jax.tree_util.keystr(key),
        )


def test_module_pallas_head_chunks(rng):
    """The model-level head-split launch is bit-identical to unsplit."""
    kw = dict(dim=32, heads=4, dim_head=8, kv_heads=2, causal=True,
              use_ring=False, use_pallas=True)
    split = RingAttention(pallas_head_chunks=2, **kw)
    plain = RingAttention(**kw)
    x = jnp.asarray(rng.standard_normal((2, 32, 32)), jnp.float32)
    params = plain.init(jax.random.PRNGKey(0), x)
    np.testing.assert_array_equal(split.apply(params, x),
                                  plain.apply(params, x))

    # threaded through the transformer stack too (the documented escape
    # hatch must be reachable from the train path)
    from ring_attention_tpu.models import RingTransformer

    tkw = dict(num_tokens=64, dim=32, depth=1, heads=4, dim_head=8,
               kv_heads=2, causal=True, use_ring=False, use_pallas=True)
    tok = jnp.asarray(rng.integers(0, 64, (1, 16)), jnp.int32)
    p = RingTransformer(**tkw).init(jax.random.PRNGKey(0), tok)
    np.testing.assert_array_equal(
        RingTransformer(pallas_head_chunks=2, **tkw).apply(p, tok),
        RingTransformer(**tkw).apply(p, tok),
    )


def test_module_lookback(rng, mesh):
    """Per-layer lookback window vs oracle with the same window."""
    common = dict(dim=32, heads=4, dim_head=8, bucket_size=4, causal=True,
                  max_lookback_seq_len=8)
    ring_mod = RingAttention(use_ring=True, auto_shard=True, mesh=mesh, **common)
    ref_mod = RingAttention(use_ring=False, **common)
    x = jnp.asarray(rng.standard_normal((2, 32, 32)), jnp.float32)
    params = ref_mod.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        ring_mod.apply(params, x), ref_mod.apply(params, x), atol=ATOL
    )


@pytest.mark.parametrize("sp", ["zigzag", "ulysses"])
def test_module_sequence_parallel_modes(rng, mesh, sp):
    """zig-zag and Ulysses behind the same module API match the oracle
    (the reference integrates neither into its module layer)."""
    common = dict(dim=32, heads=8, dim_head=8, bucket_size=4, causal=True)
    ring_mod = RingAttention(
        use_ring=True, auto_shard=True, mesh=mesh, sequence_parallel=sp, **common
    )
    ref_mod = RingAttention(use_ring=False, force_regular_attn=True, **common)
    x = jnp.asarray(rng.standard_normal((2, 31, 32)), jnp.float32)
    params = ref_mod.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        ring_mod.apply(params, x), ref_mod.apply(params, x), atol=ATOL
    )


def test_module_ulysses_mask_grads(rng, mesh):
    common = dict(dim=32, heads=8, dim_head=8, bucket_size=4, causal=False)
    ring_mod = RingAttention(
        use_ring=True, auto_shard=True, mesh=mesh, sequence_parallel="ulysses",
        **common,
    )
    ref_mod = RingAttention(use_ring=False, force_regular_attn=True, **common)
    x = jnp.asarray(rng.standard_normal((2, 32, 32)), jnp.float32)
    mask = jnp.asarray(rng.random((2, 32)) > 0.3)
    params = ref_mod.init(jax.random.PRNGKey(0), x, mask)
    np.testing.assert_allclose(
        ring_mod.apply(params, x, mask), ref_mod.apply(params, x, mask), atol=ATOL
    )
    g_ref = jax.grad(lambda x: (ref_mod.apply(params, x, mask) ** 2).sum())(x)
    g_out = jax.grad(lambda x: (ring_mod.apply(params, x, mask) ** 2).sum())(x)
    np.testing.assert_allclose(g_out, g_ref, atol=GRAD_ATOL)


def test_module_lookback_striped(rng, mesh):
    """Striped + lookback is exact end-to-end through the module."""
    common = dict(dim=32, heads=4, dim_head=8, bucket_size=4, causal=True,
                  max_lookback_seq_len=8)
    ring_mod = RingAttention(use_ring=True, auto_shard=True, mesh=mesh,
                             striped=True, **common)
    ref_mod = RingAttention(use_ring=False, **common)
    x = jnp.asarray(rng.standard_normal((2, 32, 32)), jnp.float32)
    params = ref_mod.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        ring_mod.apply(params, x), ref_mod.apply(params, x), atol=ATOL
    )


def test_module_counter_and_compression_plumbing(rng, mesh, monkeypatch):
    """ring_counter_rotate / ring_hop_compression reach the ring call in
    the module's RING branch (not just hybrid) — the exact bug class a
    dropped kwarg produces.  A recording stub stands in for
    ring_flash_attention so the pin costs one cheap local-flash compile;
    the full counter+int8 numerics through the module are the slow test
    below, and function-level parity lives in tests/test_ring.py."""
    from ring_attention_tpu.models import attention as attn_mod
    from ring_attention_tpu.ops.flash import flash_attention

    seen = {}

    def stub(q, k, v, mask, axis_name, *args, **kwargs):
        seen.update(kwargs)
        return flash_attention(q, k, v, mask, causal=True, bucket_size=8)

    monkeypatch.setattr(attn_mod, "ring_flash_attention", stub)
    ring_mod, ref_mod = make_pair(
        mesh, causal=True, ring_counter_rotate=True,
        ring_hop_compression="int8",
    )
    x = jnp.asarray(rng.standard_normal((2, 32, 32)), jnp.float32)
    params = ref_mod.init(jax.random.PRNGKey(0), x)
    ring_mod.apply(params, x)
    assert seen.get("counter_rotate") is True
    assert seen.get("hop_compression") == "int8"


@pytest.mark.slow
def test_module_counter_rotate_with_compression(rng, mesh):
    """Full numerics through the module: counter-rotation + int8 hops
    stay within the single-quantization envelope of the oracle, and the
    output provably differs from the exact oracle (compression actually
    engaged)."""
    ring_mod, ref_mod = make_pair(
        mesh, causal=True, ring_counter_rotate=True,
        ring_hop_compression="int8",
    )
    x = jnp.asarray(rng.standard_normal((2, 32, 32)), jnp.float32)
    params = ref_mod.init(jax.random.PRNGKey(0), x)
    ref = ref_mod.apply(params, x)
    out = ring_mod.apply(params, x)
    assert not np.allclose(out, ref, atol=1e-7)  # compression engaged
    np.testing.assert_allclose(out, ref, atol=2.5e-2)
