"""Parity: blockwise flash attention vs the dense oracle.

JAX-native analogue of the reference's ``assert_flash.py`` (single-process
unit test): forward outputs and dq/dk/dv gradients of ``flash_attention``
must match ``default_attention`` to tight tolerance, across causal,
key-padding mask, GQA, softclamp and bucket-size variations.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ring_attention_tpu.ops import default_attention, flash_attention

ATOL = 2e-5  # float32 CPU; reference uses 1e-6 on torch CPU (assert_flash.py:66)


def make_qkv(rng, b=2, h=4, hk=None, n=64, d=16):
    hk = hk or h
    q = jnp.asarray(rng.standard_normal((b, h, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("bucket_size", [None, 16, 64])
def test_forward_parity(rng, causal, bucket_size):
    q, k, v = make_qkv(rng)
    ref = default_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, bucket_size=bucket_size)
    np.testing.assert_allclose(out, ref, atol=ATOL)


@pytest.mark.parametrize("hk", [1, 2])
def test_gqa_parity(rng, hk):
    q, k, v = make_qkv(rng, h=4, hk=hk)
    ref = default_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, bucket_size=16)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_key_padding_mask(rng):
    q, k, v = make_qkv(rng)
    mask = jnp.asarray(rng.random((2, 64)) > 0.3)
    ref = default_attention(q, k, v, mask)
    out = flash_attention(q, k, v, mask, bucket_size=16)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_softclamp(rng):
    q, k, v = make_qkv(rng)
    ref = default_attention(q, k, v, causal=True, softclamp_value=5.0)
    out = flash_attention(q, k, v, causal=True, bucket_size=16, softclamp_value=5.0)
    np.testing.assert_allclose(out, ref, atol=ATOL)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("softclamp_value", [None, 5.0])
@pytest.mark.parametrize("hk", [4, 2])
def test_grad_parity(rng, causal, softclamp_value, hk):
    q, k, v = make_qkv(rng, hk=hk)

    def loss_ref(q, k, v):
        return (
            default_attention(q, k, v, causal=causal, softclamp_value=softclamp_value)
            ** 2
        ).sum()

    def loss_flash(q, k, v):
        return (
            flash_attention(
                q, k, v, causal=causal, bucket_size=16, softclamp_value=softclamp_value
            )
            ** 2
        ).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-4, err_msg=f"d{name}")


def test_grad_with_mask(rng):
    q, k, v = make_qkv(rng)
    mask = jnp.asarray(rng.random((2, 64)) > 0.3)

    g_ref = jax.grad(lambda *a: (default_attention(*a) ** 2).sum(), (0, 1, 2))(
        q, k, v, mask
    )
    g_out = jax.grad(
        lambda *a: (flash_attention(*a, bucket_size=16) ** 2).sum(), (0, 1, 2)
    )(q, k, v, mask)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-4, err_msg=f"d{name}")


def test_window(rng):
    """Lookback window: flash with window=w matches oracle with banded mask."""
    q, k, v = make_qkv(rng)
    n = q.shape[2]
    w = 24
    out = flash_attention(q, k, v, causal=True, bucket_size=16, window=w)

    # dense oracle with explicit band mask
    i = jnp.arange(n)[:, None]
    j = jnp.arange(n)[None, :]
    band = (j <= i) & (j >= i - (w - 1))
    s = jnp.einsum("bhid,bhjd->bhij", q, k) * (q.shape[-1] ** -0.5)
    s = jnp.where(band, s, -1e30)
    ref = jnp.einsum("bhij,bhjd->bhid", jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_causal_decode_style(rng):
    """nq < nk causal: band end-aligned like the oracle (decode shape)."""
    q = jnp.asarray(rng.standard_normal((2, 4, 8, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 4, 64, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 4, 64, 16)), jnp.float32)
    ref = default_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, bucket_size=16)
    np.testing.assert_allclose(out, ref, atol=ATOL)


@pytest.mark.parametrize("causal", [False, True])
def test_non_divisible_bucket(rng, causal):
    """KV length not a multiple of bucket_size: padded internally."""
    q, k, v = make_qkv(rng, n=48)
    ref = default_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, bucket_size=32)
    np.testing.assert_allclose(out, ref, atol=ATOL)
    g_ref = jax.grad(lambda *a: (default_attention(*a, causal=causal) ** 2).sum(), (0, 1, 2))(q, k, v)
    g_out = jax.grad(
        lambda *a: (flash_attention(*a, causal=causal, bucket_size=32) ** 2).sum(),
        (0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-4, err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [False, True])
def test_q_chunked(rng, causal):
    """Two-level blocking (q chunks): identical values and gradients."""
    q, k, v = make_qkv(rng)
    ref = default_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, bucket_size=16, q_chunk_size=16)
    np.testing.assert_allclose(out, ref, atol=ATOL)

    g_ref = jax.grad(lambda *a: (default_attention(*a, causal=causal) ** 2).sum(), (0, 1, 2))(q, k, v)
    g_out = jax.grad(
        lambda *a: (
            flash_attention(*a, causal=causal, bucket_size=16, q_chunk_size=16) ** 2
        ).sum(),
        (0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-4, err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [False, True])
def test_q_chunked_ragged(rng, causal):
    """Query length not a multiple of q_chunk_size: padded rows are computed
    then sliced off; values and gradients still match the oracle."""
    q, k, v = make_qkv(rng, n=50)
    ref = default_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, bucket_size=16, q_chunk_size=16)
    np.testing.assert_allclose(out, ref, atol=ATOL)
    g_ref = jax.grad(lambda *a: (default_attention(*a, causal=causal) ** 2).sum(), (0, 1, 2))(q, k, v)
    g_out = jax.grad(
        lambda *a: (
            flash_attention(*a, causal=causal, bucket_size=16, q_chunk_size=16) ** 2
        ).sum(),
        (0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-4, err_msg=f"d{name}")


def test_q_chunked_graph_size_constant():
    """The q-chunk loop is a lax.scan, so the traced graph is O(1) in the
    number of chunks — the property that makes the XLA fallback viable at
    seq 262144 (a Python loop would unroll one custom_vjp core per chunk)."""

    def eqn_count(n):
        s = jax.ShapeDtypeStruct((1, 2, n, 16), jnp.float32)
        jaxpr = jax.make_jaxpr(
            lambda q, k, v: flash_attention(
                q, k, v, causal=True, bucket_size=16, q_chunk_size=16
            )
        )(s, s, s)
        return len(jaxpr.jaxpr.eqns)

    assert eqn_count(64) == eqn_count(1024)


def test_bf16_long_accumulation(rng):
    """bf16 inputs over a longer sequence: f32 online-softmax accumulators
    must keep flash within bf16 round-off of the f32 oracle (the reference
    keeps m/lse fp32 for the same reason, ring_flash_attention_cuda.py:251-259)."""
    n = 2048
    q = jnp.asarray(rng.standard_normal((1, 2, n, 32)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 2, n, 32)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 2, n, 32)), jnp.float32)
    ref = default_attention(q, k, v, causal=True)
    out = flash_attention(
        q.astype(jnp.bfloat16), k.astype(jnp.bfloat16), v.astype(jnp.bfloat16),
        causal=True, bucket_size=256, q_chunk_size=512,
    )
    # bf16 has ~3 decimal digits; inputs O(1), outputs O(1)
    np.testing.assert_allclose(out.astype(jnp.float32), ref, atol=3e-2)
