"""Parity: tree-attention decoding vs dense decode.

JAX-native analogue of the reference's ``assert_tree_attn.py``: a single
replicated query against a KV cache sharded over 8 devices must match dense
attention over the full cache, including GQA and padded-cache (the
reference's seq < world edge case, handled here with a static mask).
"""

from functools import partial

import jax.numpy as jnp
import numpy as np
import pytest
from ring_attention_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from ring_attention_tpu.ops import default_attention
from ring_attention_tpu.parallel import create_mesh, tree_attn_decode

ATOL = 1e-5  # ref uses 1e-5 CPU (assert_tree_attn.py:90-92)


@pytest.fixture(scope="module")
def mesh():
    return create_mesh(ring_size=8)


def decode_global(q, k, v, mask=None, *, mesh, **kw):
    kspec = P("data", None, "seq", None)
    out = shard_map(
        partial(tree_attn_decode, axis_name="seq", **kw),
        mesh=mesh,
        in_specs=(P("data"), kspec, kspec, P("data", "seq") if mask is not None else P()),
        out_specs=P("data"),
        # pallas_call trips jax's vma checker (same workaround the
        # attention module applies for its pallas paths)
        check_vma=kw.get("impl") != "pallas",
    )(q, k, v, mask)
    return out


@pytest.mark.parametrize("hk", [8, 2])
def test_tree_decode_parity(rng, mesh, hk):
    q = jnp.asarray(rng.standard_normal((2, 8, 1, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, hk, 256, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, hk, 256, 16)), jnp.float32)
    ref = default_attention(q, k, v)
    out = decode_global(q, k, v, mesh=mesh)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_tree_decode_padded_cache(rng, mesh):
    """Cache shorter than what some shards hold: mask the padded tail
    (static-shape answer to ref tree_attn_decoding.py:81-85)."""
    n_real, n_pad = 40, 64  # shards of 8; last 3 shards fully padded
    q = jnp.asarray(rng.standard_normal((2, 4, 1, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 4, n_real, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 4, n_real, 16)), jnp.float32)
    ref = default_attention(q, k, v)

    kp = jnp.pad(k, [(0, 0), (0, 0), (0, n_pad - n_real), (0, 0)])
    vp = jnp.pad(v, [(0, 0), (0, 0), (0, n_pad - n_real), (0, 0)])
    mask = jnp.broadcast_to(jnp.arange(n_pad)[None, :] < n_real, (2, n_pad))
    out = decode_global(q, kp, vp, mask, mesh=mesh)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_tree_decode_multi_query(rng, mesh):
    """nq > 1 (speculative decoding burst) also merges correctly."""
    q = jnp.asarray(rng.standard_normal((2, 4, 4, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 4, 128, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 4, 128, 16)), jnp.float32)
    ref = default_attention(q, k, v)
    out = decode_global(q, k, v, mesh=mesh, bucket_size=8)
    np.testing.assert_allclose(out, ref, atol=ATOL)


@pytest.mark.parametrize("hk", [8, 2])
def test_tree_decode_pallas_impl(rng, mesh, hk):
    """impl="pallas": the decode kernel's local partials feed the same
    three-collective merge (interpret mode on the CPU mesh)."""
    q = jnp.asarray(rng.standard_normal((2, 8, 1, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, hk, 256, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, hk, 256, 16)), jnp.float32)
    ref = default_attention(q, k, v)
    out = decode_global(q, k, v, mesh=mesh, impl="pallas", bucket_size=16)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_tree_decode_q8_cache(rng, mesh):
    """Int8 cache shards through the same three-collective merge: exact vs
    the dequantized oracle, ~2% vs the unquantized one, with a ragged
    cache-validity mask (exercises vma unification inside shard_map)."""
    from ring_attention_tpu.ops.pallas_flash import (
        QuantizedKV,
        quantize_kv_cache,
    )

    n = 256
    q = jnp.asarray(rng.standard_normal((2, 8, 1, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 2, n, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 2, n, 16)), jnp.float32)
    mask = jnp.broadcast_to(jnp.arange(n)[None, :] < 200, (2, n))
    kv = quantize_kv_cache(k, v)
    k_deq = kv.k_q.astype(jnp.float32) * kv.k_scale[..., None]
    v_deq = kv.v_q.astype(jnp.float32) * kv.v_scale[..., None]
    ref_deq = default_attention(q, k_deq, v_deq, mask)
    ref_full = default_attention(q, k, v, mask)

    kspec = P("data", None, "seq", None)
    sspec = P("data", None, "seq")
    out = shard_map(
        lambda q, m, kv: tree_attn_decode(
            q, None, None, m, axis_name="seq", bucket_size=16,
            kv_quantized=kv,
        ),
        mesh=mesh,
        in_specs=(P("data"), P("data", "seq"),
                  QuantizedKV(kspec, sspec, kspec, sspec)),
        out_specs=P("data"),
        check_vma=False,
    )(q, mask, kv)
    np.testing.assert_allclose(out, ref_deq, atol=ATOL)
    rel = float(jnp.abs(out - ref_full).max() / jnp.abs(ref_full).max())
    assert rel < 0.03, rel

    with pytest.raises(ValueError):
        tree_attn_decode(q, k, v, axis_name="seq", kv_quantized=kv)

    # an explicit impl="xla" with a quantized cache is honored: the cache
    # dequantizes internally and the jnp sweep runs (no silent pallas)
    out_xla = shard_map(
        lambda q, m, kv: tree_attn_decode(
            q, None, None, m, axis_name="seq", bucket_size=16,
            kv_quantized=kv, impl="xla",
        ),
        mesh=mesh,
        in_specs=(P("data"), P("data", "seq"),
                  QuantizedKV(kspec, sspec, kspec, sspec)),
        out_specs=P("data"),
        check_vma=False,
    )(q, mask, kv)
    np.testing.assert_allclose(out_xla, ref_deq, atol=ATOL)

    with pytest.raises(ValueError, match="unknown impl"):
        tree_attn_decode(q, None, None, axis_name="seq",
                         kv_quantized=kv, impl="triton")


def test_tree_decode_pallas_padded_cache(rng, mesh):
    """Pallas impl handles the fully-masked-shard edge (l=0 partials on
    shards past the cache tail) identically to the XLA path."""
    n_real, n_pad = 40, 64
    q = jnp.asarray(rng.standard_normal((2, 4, 1, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 4, n_real, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 4, n_real, 16)), jnp.float32)
    ref = default_attention(q, k, v)

    kp = jnp.pad(k, [(0, 0), (0, 0), (0, n_pad - n_real), (0, 0)])
    vp = jnp.pad(v, [(0, 0), (0, 0), (0, n_pad - n_real), (0, 0)])
    mask = jnp.broadcast_to(jnp.arange(n_pad)[None, :] < n_real, (2, n_pad))
    out = decode_global(q, kp, vp, mask, mesh=mesh, impl="pallas")
    np.testing.assert_allclose(out, ref, atol=ATOL)
