"""Cross-framework parity: this package vs the original PyTorch reference.

Runs the actual reference implementation (mounted read-only at
``/root/reference``, torch CPU) on identical inputs and asserts numerical
agreement with our JAX ops — function-level (no weights involved):
``default_attention`` and single-process ``ring_flash_attn`` vs our oracle
and blockwise flash, including causal, GQA, softclamp and key-pad masks.

Skipped automatically when the reference checkout isn't present.
"""

import sys
import types

import numpy as np
import pytest

REFERENCE = "/root/reference"


def _import_reference():
    """Import the reference with a no-op beartype stub (not installed here)."""
    if "beartype" not in sys.modules:
        stub = types.ModuleType("beartype")
        stub.beartype = lambda fn=None, **kw: fn if fn is not None else (lambda f: f)
        sys.modules["beartype"] = stub
    if REFERENCE not in sys.path:
        sys.path.insert(0, REFERENCE)
    import ring_attention_pytorch.ring_attention as ref_attn
    import ring_attention_pytorch.ring_flash_attention as ref_flash

    return ref_attn, ref_flash


torch = pytest.importorskip("torch")
pytest.importorskip("einops")

try:
    ref_attn, ref_flash = _import_reference()
    HAVE_REF = True
except Exception:  # pragma: no cover - reference not mounted
    HAVE_REF = False

pytestmark = pytest.mark.skipif(not HAVE_REF, reason="reference not available")

ATOL = 2e-5


def make_inputs(rng, b=2, h=4, hk=None, n=48, d=16):
    hk = hk or h
    q = rng.standard_normal((b, h, n, d)).astype(np.float32)
    k = rng.standard_normal((b, hk, n, d)).astype(np.float32)
    v = rng.standard_normal((b, hk, n, d)).astype(np.float32)
    return q, k, v


def ours_default(q, k, v, mask=None, **kw):
    import jax.numpy as jnp

    from ring_attention_tpu.ops import default_attention

    out = default_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(mask) if mask is not None else None, **kw
    )
    return np.asarray(out)


def ours_flash(q, k, v, mask=None, **kw):
    import jax.numpy as jnp

    from ring_attention_tpu.ops import flash_attention

    out = flash_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(mask) if mask is not None else None, bucket_size=16, **kw
    )
    return np.asarray(out)


def ref_default(q, k, v, mask=None, causal=False, softclamp_value=None):
    """Adapter: reference uses (b, n, h, d) layout and a softclamp flag."""
    out = ref_attn.default_attention(
        torch.from_numpy(q).transpose(1, 2),
        torch.from_numpy(k).transpose(1, 2),
        torch.from_numpy(v).transpose(1, 2),
        mask=torch.from_numpy(mask) if mask is not None else None,
        causal=causal,
        softclamp_qk_sim=softclamp_value is not None,
        softclamp_value=softclamp_value or 50.0,
    )
    return out.transpose(1, 2).numpy()


@pytest.mark.parametrize("causal", [False, True])
def test_default_attention_matches_reference(rng, causal):
    q, k, v = make_inputs(rng)
    theirs = ref_default(q, k, v, causal=causal)
    np.testing.assert_allclose(ours_default(q, k, v, causal=causal), theirs, atol=ATOL)
    np.testing.assert_allclose(ours_flash(q, k, v, causal=causal), theirs, atol=ATOL)


def test_gqa_matches_reference(rng):
    """GQA parity, accounting for a deliberate convention difference: the
    reference's ``(g h)`` repeat pairs query head j with kv head ``j % hk``
    (interleaved, ref ring_attention.py:68), while we use the Llama/HF
    convention ``j // g`` (contiguous blocks).  Permuting query heads maps
    one onto the other exactly."""
    h, hk = 4, 2
    g = h // hk
    q, k, v = make_inputs(rng, h=h, hk=hk)
    # our head j pairs kv j // g; reference head i pairs kv i % hk.
    # feed the reference q' with q'[i] = q[perm[i]], perm[i] = (i % hk) * g + i // hk
    perm = np.asarray([(i % hk) * g + i // hk for i in range(h)])
    theirs = ref_default(q[:, perm], k, v, causal=True)
    ours = ours_flash(q, k, v, causal=True)
    # reference output head i corresponds to our head perm[i]
    np.testing.assert_allclose(ours[:, perm], theirs, atol=ATOL)


def test_softclamp_matches_reference(rng):
    q, k, v = make_inputs(rng)
    theirs = ref_default(q, k, v, causal=True, softclamp_value=5.0)
    np.testing.assert_allclose(
        ours_flash(q, k, v, causal=True, softclamp_value=5.0), theirs, atol=ATOL
    )


def test_key_padding_matches_reference(rng):
    q, k, v = make_inputs(rng)
    mask = rng.random((2, 48)) > 0.3
    theirs = ref_default(q, k, v, mask=mask)
    np.testing.assert_allclose(ours_flash(q, k, v, mask), theirs, atol=ATOL)


def test_ring_flash_single_process_matches_reference(rng):
    """The reference's ring_flash_attn with ring off (1 process) is its
    blockwise flash path (assert_flash.py pattern); ours must agree."""
    q, k, v = make_inputs(rng)
    theirs = ref_flash.ring_flash_attn(
        torch.from_numpy(q).transpose(1, 2),  # reference uses (b, n, h, d)
        torch.from_numpy(k).transpose(1, 2),
        torch.from_numpy(v).transpose(1, 2),
        causal=True,
        bucket_size=16,
        ring_reduce_col=False,
    ).transpose(1, 2).numpy()
    np.testing.assert_allclose(ours_flash(q, k, v, causal=True), theirs, atol=ATOL)


def test_grads_match_reference(rng):
    """dq/dk/dv parity with the reference's autograd through its flash path."""
    import jax
    import jax.numpy as jnp

    from ring_attention_tpu.ops import flash_attention

    q, k, v = make_inputs(rng, n=32)

    tq = torch.from_numpy(q.copy()).transpose(1, 2).requires_grad_(True)
    tk = torch.from_numpy(k.copy()).transpose(1, 2).requires_grad_(True)
    tv = torch.from_numpy(v.copy()).transpose(1, 2).requires_grad_(True)
    out = ref_flash.ring_flash_attn(tq, tk, tv, causal=True, bucket_size=16,
                                    ring_reduce_col=False)
    (out ** 2).sum().backward()

    g = jax.grad(
        lambda q, k, v: (
            flash_attention(q, k, v, causal=True, bucket_size=16) ** 2
        ).sum(),
        (0, 1, 2),
    )(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))

    for ours, theirs, name in zip(
        g, (tq.grad, tk.grad, tv.grad), "qkv"
    ):
        np.testing.assert_allclose(
            np.asarray(ours), theirs.transpose(1, 2).numpy(), atol=5e-4,
            err_msg=f"d{name}",
        )


def test_rotary_matches_reference(rng):
    """Rotary freqs + NeoX half-rotation application vs the reference's
    RingRotaryEmbedding / apply_rotary_pos_emb (ref ring_attention.py:
    102-172), contiguous (non-ring) positions."""
    import jax.numpy as jnp

    from ring_attention_tpu.ops.rotary import apply_rotary, rotary_freqs

    n, d = 24, 16
    x = rng.standard_normal((2, 4, n, d)).astype(np.float32)

    ref_rot = ref_attn.RingRotaryEmbedding(dim=d, ring=False)
    pos_freqs = ref_rot(n)  # (n, d)
    theirs = ref_attn.apply_rotary_pos_emb(
        pos_freqs, torch.from_numpy(x).permute(0, 2, 1, 3)  # ref: (b n h d)
    ).permute(0, 2, 1, 3).numpy()

    freqs = rotary_freqs(jnp.arange(n), d)
    np.testing.assert_allclose(pos_freqs.numpy(), np.asarray(freqs), atol=ATOL)
    ours = np.asarray(apply_rotary(jnp.asarray(x), freqs))
    np.testing.assert_allclose(ours, theirs, atol=ATOL)


def test_model_matches_reference_with_copied_weights(rng):
    """Model-level cross-framework parity: our RingTransformer's weights
    copied into the reference's RingTransformer (ref ring_attention.py:
    488-685) must give the same logits AND the same causal-LM loss on the
    same tokens — embedding, prenorm fused-qkv attention with rotary, exact
    gelu FF, final norm, label-shifted cross entropy, end to end.  The
    reference's FF Linears carry biases (ours are bias-free by design);
    they are zeroed after the copy."""
    import jax
    import jax.numpy as jnp

    from ring_attention_tpu.models import RingTransformer

    vocab, dim, depth, heads, dh, n = 64, 32, 2, 4, 8, 24
    ours_model = RingTransformer(
        num_tokens=vocab, dim=dim, depth=depth, heads=heads, dim_head=dh,
        causal=True, bucket_size=8, use_ring=False, rotary=True,
    )
    tokens_np = rng.integers(0, vocab, (2, n))
    tokens = jnp.asarray(tokens_np, jnp.int32)
    params = ours_model.init(jax.random.PRNGKey(0), tokens)

    ref_model = ref_attn.RingTransformer(
        num_tokens=vocab, dim=dim, depth=depth, heads=heads, dim_head=dh,
        causal=True, bucket_size=8, ring_attn=False, use_cuda_kernel=False,
    )

    def t(a):  # flax (in, out) kernel -> torch (out, in) weight
        return torch.from_numpy(np.asarray(a).copy())

    p = params["params"]
    with torch.no_grad():
        ref_model.token_emb.weight.copy_(t(p["embed"]["embedding"]))
        for i, (attn, ff) in enumerate(ref_model.layers):
            a = p[f"attn_layers_{i}"]
            attn.to_qkv[0].gamma.copy_(t(a["prenorm"]["gamma"]))
            attn.to_qkv[1].weight.copy_(t(a["to_qkv"]["kernel"]).T)
            attn.to_out.weight.copy_(t(a["to_out"]["kernel"]).T)
            f = p[f"ff_layers_{i}"]
            ff[0].gamma.copy_(t(f["RMSNorm_0"]["gamma"]))
            ff[1].weight.copy_(t(f["Dense_0"]["kernel"]).T)
            ff[1].bias.zero_()
            ff[3].weight.copy_(t(f["Dense_1"]["kernel"]).T)
            ff[3].bias.zero_()
        ref_model.to_logits[0].gamma.copy_(t(p["final_norm"]["gamma"]))
        ref_model.to_logits[1].weight.copy_(t(p["to_logits"]["kernel"]).T)

    theirs = ref_model(torch.from_numpy(tokens_np)).detach().numpy()
    ours = np.asarray(ours_model.apply(params, tokens))
    np.testing.assert_allclose(ours, theirs, atol=5e-4)

    with torch.no_grad():
        theirs_loss = float(ref_model(torch.from_numpy(tokens_np), return_loss=True))
    ours_loss = float(ours_model.apply(params, tokens, return_loss=True))
    assert abs(ours_loss - theirs_loss) < 1e-4, (ours_loss, theirs_loss)


def test_gqa_softclamp_grads_match_reference(rng):
    """dq/dk/dv parity vs the reference's hand-written ring-flash backward
    under GQA + softclamp together (the two features whose backward terms
    interact: group-summed dk/dv, ref ring_flash_attention.py:370-371, and
    the tanh-clamp chain rule, :330-333) — with the head-pairing
    permutation from test_gqa_matches_reference applied to q/dq."""
    import jax
    import jax.numpy as jnp

    from ring_attention_tpu.ops import flash_attention

    h, hk, n = 4, 2, 32
    g = h // hk
    q, k, v = make_inputs(rng, h=h, hk=hk, n=n)
    perm = np.asarray([(i % hk) * g + i // hk for i in range(h)])

    tq = torch.from_numpy(q[:, perm].copy()).transpose(1, 2).requires_grad_(True)
    tk = torch.from_numpy(k.copy()).transpose(1, 2).requires_grad_(True)
    tv = torch.from_numpy(v.copy()).transpose(1, 2).requires_grad_(True)
    out = ref_flash.ring_flash_attn(
        tq, tk, tv, causal=True, bucket_size=16, ring_reduce_col=False,
        softclamp_qk_sim=True, softclamp_value=5.0,
    )
    (out ** 2).sum().backward()

    gq, gk, gv = jax.grad(
        lambda q, k, v: (flash_attention(
            q, k, v, causal=True, bucket_size=16, softclamp_value=5.0,
        ) ** 2).sum(),
        (0, 1, 2),
    )(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))

    np.testing.assert_allclose(
        np.asarray(gq)[:, perm], tq.grad.transpose(1, 2).numpy(),
        atol=5e-4, err_msg="dq",
    )
    for ours, theirs, name in ((gk, tk.grad, "dk"), (gv, tv.grad, "dv")):
        np.testing.assert_allclose(
            np.asarray(ours), theirs.transpose(1, 2).numpy(),
            atol=5e-4, err_msg=name,
        )
