"""Worker for tests/test_multihost.py: one process of a 2-process cluster.

Each process owns 4 virtual CPU devices; together they form one 8-device
jax cluster over the distributed runtime — the single-host analogue of a
multi-host TPU pod (one process per host, ICI within, DCN across), which
is exactly what ``initialize_multihost`` + ``create_mesh`` target.  Run:

    python tests/multihost_worker.py <process_id> <num_processes> <port>
"""

import os
import sys


def main() -> None:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import jax.numpy as jnp
    import numpy as np

    from ring_attention_tpu.models import RingTransformer
    from ring_attention_tpu.parallel import (
        create_mesh,
        initialize_multihost,
        shard_batch,
    )

    initialize_multihost(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    assert jax.device_count() == 8, jax.device_count()
    assert len(jax.local_devices()) == 4

    # (data=2, seq=4) mesh: the data axis spans the two processes (the
    # "across hosts" direction), each ring row lives inside one process
    mesh = create_mesh(ring_size=4, data_size=2)

    # every process holds only ITS slice of the global batch;
    # shard_batch assembles the global array without any host gather
    rng = np.random.default_rng(0)
    full = rng.integers(0, 256, (4, 128)).astype(np.int32)
    local = full[pid * 2:(pid + 1) * 2]
    tokens = shard_batch(local, mesh)
    assert tokens.shape == (4, 128), tokens.shape

    # cross-process collective: a global reduction over the sharded batch.
    # Global arrays span non-addressable devices — results come back to
    # the host via process_allgather, and globals go INTO jit as
    # arguments, never closures (the two multi-host rules this test pins).
    from jax.experimental import multihost_utils

    total = int(multihost_utils.process_allgather(jax.jit(jnp.sum)(tokens), tiled=True))
    assert total == int(full.sum()), (total, int(full.sum()))

    # end-to-end: ring-attention LM loss + grads on the 2-process mesh
    # (ring ppermute within each process row, grad psum across processes)
    model = RingTransformer(
        num_tokens=256, dim=32, depth=1, heads=4, dim_head=8,
        causal=True, striped=True, bucket_size=8, mesh=mesh,
    )
    params = model.init(jax.random.PRNGKey(0), tokens)
    loss, grads = jax.jit(jax.value_and_grad(
        lambda p, t: model.apply(p, t, return_loss=True)
    ))(params, tokens)
    gnorm = jax.jit(
        lambda g: sum(jnp.sum(x.astype(jnp.float32) ** 2)
                      for x in jax.tree.leaves(g))
    )(grads)
    loss = float(multihost_utils.process_allgather(loss, tiled=True))
    gnorm = float(multihost_utils.process_allgather(gnorm, tiled=True))
    assert np.isfinite(loss) and np.isfinite(gnorm)

    # decode across the process boundary: a (data=1, seq=8) mesh puts the
    # KV-cache shards of ONE ring on both processes, so the tree-decode
    # collectives (pmax + 2 psum) cross the gloo transport for real — the
    # cross-host decode path a multi-host pod serves
    dmesh = create_mesh(ring_size=8)
    dmodel = RingTransformer(
        num_tokens=256, dim=32, depth=1, heads=4, dim_head=8,
        kv_heads=2, causal=True, bucket_size=8, mesh=dmesh,
    )
    prompt = jnp.asarray(full[:1, :8], jnp.int32)  # same on both processes
    dparams = dmodel.init(jax.random.PRNGKey(0), prompt)
    toks = jax.jit(lambda p, t: dmodel.apply(
        p, t, 16, 3, method=RingTransformer.generate))(dparams, prompt)
    # the output is replicated: tiled=True fetches the global value to the
    # host; re-gathering that HOST value stacks one copy per process, so
    # the equality check proves both processes decoded identical tokens
    local_toks = np.asarray(multihost_utils.process_allgather(toks, tiled=True))
    per_proc = np.asarray(
        multihost_utils.process_allgather(local_toks)
    ).reshape(nproc, -1)
    assert (per_proc[0] == per_proc[1]).all(), per_proc
    dec = ",".join(str(t) for t in per_proc[0])

    print(f"MULTIHOST-OK {pid} loss={loss:.4f} gnorm={gnorm:.4f} "
          f"decode={dec}", flush=True)


if __name__ == "__main__":
    main()
