"""Preflight every queued hardware-session step at tiny shapes on CPU.

TPU windows on this image are scarce (multi-round tunnel wedges,
docs/hardware_log.md); a queued `tools/hw_session.sh` step that dies on a
Python-level bug — an argument the worker no longer accepts, a broken env
flag, a typo in the step line — burns window budget that may not come
back.  This suite parses the session script and runs each distinct worker
invocation verbatim except for the sequence length (shrunk to CPU scale),
so every step is known-launchable before a window ever opens.
"""

import json
import os
import re
import shlex
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TINY_SEQ = 512
TINY_DECODE_SEQ = 1024


def _session_commands():
    """(tag, argv, env_extra) for each `run <tag> <budget> ...` bench/tool
    step in tools/hw_session.sh."""
    path = os.path.join(REPO, "tools", "hw_session.sh")
    steps = []
    for line in open(path):
        m = re.match(r"run (\S+)\s+\d+\s+(.+)$", line.strip())
        if not m:
            continue
        tag, rest = m.group(1), m.group(2)
        parts = shlex.split(rest)
        env_extra = {}
        if parts[0] == "env":
            parts = parts[1:]
            while "=" in parts[0]:
                k, v = parts[0].split("=", 1)
                env_extra[k] = v
                parts = parts[1:]
        steps.append((tag, parts, env_extra))
    return steps


STEPS = _session_commands()


def test_session_script_parses():
    """The session must queue every measurement family the round plans:
    validation, decode (incl. q8), ring hops, bwd sweep, train, exp2 A/B,
    config-4 shapes, xprof."""
    tags = {t for t, _, _ in STEPS}
    for expected in ("validate", "decode_q8", "hops262k", "bwdsweep",
                     "train_save", "fwd_exp2", "gqa32_262k", "d128",
                     "xprof"):
        assert expected in tags, f"hw_session.sh lost step {expected}"


def _bench_steps():
    out = []
    for tag, argv, env_extra in STEPS:
        if "bench.py" not in " ".join(argv):
            continue
        args = list(argv[2:])  # strip "python bench.py"
        seq_i = args.index("--worker") + 2
        mode = args[seq_i + 1]
        args[seq_i] = str(TINY_DECODE_SEQ if mode == "decode" else TINY_SEQ)
        out.append((tag, args, env_extra))
    return out


BENCH_STEPS = _bench_steps()


@pytest.fixture(scope="module")
def preflight_records():
    """Exec every queued bench-worker step in ONE subprocess.

    Two constraints shape this (same as tests/test_graft_entry.py's bench
    fixture): (a) this image's sitecustomize pre-imports jax and re-exports
    JAX_PLATFORMS=axon in every python subprocess, so env vars can't force
    CPU — only an in-process jax.config.update before exec'ing the script
    can (passing env would silently probe the possibly-wedged TPU tunnel);
    (b) a fresh jax import per step would cost ~10 s for every queued
    bench step (len(BENCH_STEPS) of them) on this 1-CPU box, so all steps
    share one interpreter."""
    bench_path = os.path.join(REPO, "bench.py")
    lines = [
        "import json, os, sys, traceback",
        "import jax; jax.config.update('jax_platforms', 'cpu')",
    ]
    for tag, args, env_extra in BENCH_STEPS:
        lines += [f"os.environ[{k!r}] = {v!r}" for k, v in env_extra.items()]
        lines += [
            "try:",
            f"    sys.argv = {['bench.py'] + args!r}",
            f"    exec(open({bench_path!r}).read())",
            "except Exception:",
            f"    print(json.dumps({{'step_error': {tag!r},"
            " 'tb': traceback.format_exc()[-600:]}))",
        ]
        lines += [f"os.environ.pop({k!r}, None)" for k in env_extra]
    proc = subprocess.run(
        [sys.executable, "-c", "\n".join(lines)], capture_output=True,
        text=True, timeout=1800, env=dict(os.environ), cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    recs = [json.loads(ln) for ln in proc.stdout.splitlines()
            if ln.startswith("{")]
    assert len(recs) == len(BENCH_STEPS), proc.stdout[-800:]
    return dict(zip((t for t, _, _ in BENCH_STEPS), recs))


@pytest.mark.slow
@pytest.mark.parametrize("tag", [t for t, _, _ in BENCH_STEPS])
def test_bench_step_launches(tag, preflight_records):
    """Each queued bench-worker step ran end-to-end at a tiny seq and
    printed one parseable JSON measurement with a nonzero value."""
    rec = preflight_records[tag]
    assert "step_error" not in rec, f"{tag}:\n{rec.get('tb', '')}"
    # metric key differs per mode: fwd/fwdbwd emit `value` (TFLOPs),
    # train `tokens_per_sec`, decode `decode_ms_per_token`
    metric = (rec.get("value", 0) or rec.get("tokens_per_sec", 0)
              or rec.get("decode_ms_per_token", 0))
    assert metric > 0, (tag, rec)


def _run_tool(script_name, argv, timeout):
    """Exec a tools/ script CPU-forced in-process (see preflight_records)."""
    script = os.path.join(REPO, "tools", script_name)
    wrapper = (
        "import sys\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        f"sys.argv = {[script_name] + argv!r}\n"
        # scripts resolve repo paths via __file__, which a bare exec lacks
        f"exec(open({script!r}).read(),"
        f" {{'__name__': '__main__', '__file__': {script!r}}})\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", wrapper], capture_output=True, text=True,
        timeout=timeout, env=dict(os.environ), cwd=REPO,
    )
    assert proc.returncode == 0, (
        f"{script_name} {argv}: rc={proc.returncode}"
        f"\nstdout:{proc.stdout[-1500:]}\nstderr:{proc.stderr[-1500:]}"
    )
    return proc.stdout


def _tool_step_args(tag, script_name):
    """The QUEUED argv for a tools/ step (flag drift on the session line
    must fail here, not at argparse inside a TPU window), with --seq
    shrunk to CPU scale."""
    matches = [(argv) for t, argv, _ in STEPS
               if t == tag and script_name in " ".join(argv)]
    assert matches, f"hw_session.sh lost the {tag} step"
    args = list(matches[0][2:])  # strip "python tools/<script>"
    if "--seq" in args:
        args[args.index("--seq") + 1] = str(TINY_SEQ)
    return args


@pytest.mark.slow
def test_kernel_validate_step_launches():
    """tools/tpu_kernel_validate.py with the `validate` step's queued
    flags (--sweep ...) completes at a tiny seq, with NO per-mode errors
    (the tool prints {"mode": ..., "error": ...} and exits 0 on kernel
    failures — a green run must mean every launch actually ran)."""
    args = _tool_step_args("validate", "tpu_kernel_validate.py")
    out = _run_tool(
        "tpu_kernel_validate.py", args + ["--interpret"], timeout=900,
    )
    recs = [json.loads(l) for l in out.splitlines() if l.startswith("{")]
    errors = [r for r in recs if "error" in r]
    assert not errors, errors
    modes = {r.get("mode") for r in recs}
    assert "fwd" in modes and "fwdbwd" in modes, modes


@pytest.mark.slow
def test_kernel_validate_bwd_sweep_launches():
    """The `bwdsweep` step's queued flags: the per-pass block-override
    path (the code that will pin DEFAULT_BLOCK_*_DKV/_DQ) runs end-to-end
    at a tiny seq with no per-combination errors."""
    args = _tool_step_args("bwdsweep", "tpu_kernel_validate.py")
    out = _run_tool(
        "tpu_kernel_validate.py", args + ["--interpret"], timeout=900,
    )
    recs = [json.loads(l) for l in out.splitlines() if l.startswith("{")]
    errors = [r for r in recs if "error" in r]
    assert not errors, errors
    modes = {r.get("mode") for r in recs}
    assert "bwd-dkv-best" in modes and "bwd-dq-best" in modes, modes


@pytest.mark.slow
def test_xprof_step_launches(tmp_path):
    """tools/xprof_capture.py with the `xprof` step's queued argv (plus
    the tiny-seq/temp-dir overrides — docs/hwlogs/ is reserved for real
    silicon traces) captures both trace phases and writes its summary."""
    args = _tool_step_args("xprof", "xprof_capture.py")
    out = _run_tool(
        "xprof_capture.py",
        args + ["--seq", str(TINY_SEQ), "--out-dir", str(tmp_path)],
        timeout=900,
    )
    assert "train step loss=" in out, out[-1500:]
    assert (tmp_path / "xprof_summary.txt").exists()
