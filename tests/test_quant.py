"""The int8 seam (``ops/quant.py``) and the int8 COMPUTE path (PR 13).

Three layers of coverage:

1. **Codec units** — row/block absmax roundtrips with per-element error
   bounds, the single-array hop payload's bit-compatibility across scale
   granularities, slice-scale properties (slicing a payload at block
   boundaries commutes with extracting kernel scales), and the dedupe pin
   that ``quantize_ring_payload`` IS ``quant.pack_kv``.

2. **Kernel + ring parity fuzz** — int8 QK^T/PV vs the bf16 kernels on
   plain/striped/counter/windowed/packed configs (CPU interpret mode),
   with pinned tolerances.  The int8 COMPUTE path quantizes BOTH matmul
   feeds (q, k, p, v) where PR 6's hop compression quantized only the
   wire (k, v), so its worst-case elementwise bound is wider than the
   hop bound (2.5e-2): error concentrates on rows with two near-tied
   sharp softmax weights (logit noise × weight gap — docs/precision.md
   §4), while the bulk of the distribution stays at bf16-noise level.
   Both pins below (max-abs AND relative L2) regress loudly if a second
   quantization or a broken scale creeps in.

3. **Composition proofs** — the dequant-free ring feed is BIT-IDENTICAL
   to launcher-side quantization (same codec, same granularity), the
   requant pin counts exactly one quantization per payload per
   circulation from the jaxpr, and the precision auditor's negative toy
   (a dropped dequant) fails one-line.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ring_attention_tpu.ops import quant
from ring_attention_tpu.ops.pallas_flash import (
    finalize_partials,
    pallas_flash_attention,
    pallas_flash_backward,
    pallas_flash_partials,
)
from ring_attention_tpu.parallel.collectives import (
    dequantize_ring_payload,
    quantize_ring_payload,
)
from ring_attention_tpu.parallel.mesh import create_mesh
from ring_attention_tpu.parallel.ring import ring_flash_attention
from ring_attention_tpu.utils.compat import shard_map

# Pinned int8-COMPUTE parity bounds on unit-variance inputs (measured
# worst ~9.5e-2 max-abs / ~1.4e-2 rel-L2 across seeds and configs under
# the suite's highest-precision matmuls; see the module docstring for
# why the elementwise tail is wider than PR 6's wire-only 2.5e-2 — the
# relative-L2 pin is the tight regression signal, the max-abs pin the
# tail rail).
Q8_FWD_MAX_ABS = 0.12
Q8_FWD_REL_L2 = 2e-2
Q8_GRAD_REL_L2 = 3e-2
Q8_GRAD_MAX_ABS = 0.2


@pytest.fixture(scope="module")
def mesh():
    # ring 2 keeps the unrolled-pallas compile count down (tier-1 is
    # compile-dominated) while still exercising rotation, in-kernel
    # carry resume, the dequant-free hop feed, and the counter catch-up;
    # the slow-tier sweep and the PR 6 hop tests cover larger rings
    return create_mesh(ring_size=2, data_size=4)


def make_qkv(rng, b=4, h=4, hk=None, n=64, d=16):
    hk = hk or h
    q = jnp.asarray(rng.standard_normal((b, h, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    return q, k, v


# ----------------------------------------------------------------------
# 1. codec units
# ----------------------------------------------------------------------


def test_rows_roundtrip(rng):
    x = jnp.asarray(rng.standard_normal((2, 3, 32, 8)), jnp.float32)
    xq, s = quant.quantize_rows(x)
    assert xq.dtype == jnp.int8 and s.shape == (2, 3, 32)
    back = quant.dequantize_rows(xq, s, jnp.float32)
    # per element: half an LSB of that row's scale (a hair of float
    # slack: the scale itself is rounded, so exact half-LSB ties land
    # epsilon past 0.5 * s)
    bound = np.asarray(s)[..., None] * 0.505 + 1e-7
    np.testing.assert_array_less(
        np.abs(np.asarray(back - x)), np.broadcast_to(bound, x.shape))
    # all-zero rows: zero values under the RAW (zero) scale — the PR 6
    # wire convention — so dequantization is exactly 0.0, never NaN
    zq, zs = quant.quantize_rows(jnp.zeros((1, 4, 8)))
    assert float(jnp.abs(zq).max()) == 0 and float(zs.max()) == 0.0
    assert float(jnp.abs(
        quant.dequantize_rows(zq, zs, jnp.float32)).max()) == 0.0


def test_blocks_roundtrip(rng):
    x = jnp.asarray(rng.standard_normal((2, 32, 8)), jnp.float32)
    xq, s = quant.quantize_blocks(x, 8)
    assert s.shape == (2, 4)
    back = quant.dequantize_blocks(xq, s, 8, jnp.float32)
    bound = np.repeat(np.asarray(s), 8, axis=-1)[..., None] * 0.505 + 1e-7
    np.testing.assert_array_less(
        np.abs(np.asarray(back - x)), np.broadcast_to(bound, x.shape))
    with pytest.raises(ValueError, match="divide"):
        quant.quantize_blocks(x, 7)


def test_quantize_p(rng):
    p = jnp.asarray(rng.uniform(0, 1, (16, 32)), jnp.float32)
    p = p.at[3].set(0.0)  # a fully-masked row
    p8, s = quant.quantize_p(p)
    assert p8.dtype == jnp.int8 and s.shape == (16, 1)
    back = np.asarray(p8, np.float32) * np.asarray(s)
    bound = np.maximum(np.asarray(p).max(-1, keepdims=True), 1.0) / 254 * 1.02 + 1e-7
    np.testing.assert_array_less(
        np.abs(back - np.asarray(p)), np.broadcast_to(bound, p.shape))
    assert float(jnp.abs(p8[3]).max()) == 0  # zero row quantizes to zeros


def test_pack_kv_is_the_ring_codec(rng):
    """Dedupe pin: the PR 6 wire codec IS quant.pack_kv — bit-for-bit."""
    k = jnp.asarray(rng.standard_normal((2, 2, 16, 8)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((2, 2, 16, 8)), jnp.bfloat16)
    np.testing.assert_array_equal(
        np.asarray(quantize_ring_payload(k, v)),
        np.asarray(quant.pack_kv(k, v)),
    )


def test_pack_kv_block_payload_row_compatible(rng):
    """A v_block payload is a VALID row payload: unpack_kv dequantizes it
    exactly (block scales ride per-row), so _handle_kv / backward-side
    consumers never need to know the granularity."""
    k = jnp.asarray(rng.standard_normal((1, 2, 32, 8)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((1, 2, 32, 8)), jnp.bfloat16)
    payload = quant.pack_kv(k, v, v_block=8)
    k2, v2 = quant.unpack_kv(payload, jnp.float32)
    feed = quant.payload_kernel_feed(payload, 8)
    np.testing.assert_allclose(
        np.asarray(v2),
        np.asarray(quant.dequantize_blocks(feed.v_q, feed.v_scale, 8,
                                           jnp.float32)),
        rtol=0, atol=0,
    )
    np.testing.assert_allclose(
        np.asarray(k2),
        np.asarray(quant.dequantize_rows(feed.k_q, feed.k_scale,
                                         jnp.float32)),
        rtol=0, atol=0,
    )
    # row-packed payloads dequantize identically through both codecs too
    np.testing.assert_array_equal(
        np.asarray(dequantize_ring_payload(quant.pack_kv(k, v), jnp.float32)[0]),
        np.asarray(quant.unpack_kv(quant.pack_kv(k, v), jnp.float32)[0]),
    )


def test_payload_slice_scale_property(rng):
    """Slicing a block payload at block boundaries commutes with the
    kernel feed: feed(payload[ofs:ofs+span]) == slice(feed(payload)) —
    the property the ring's per-hop span slicing relies on."""
    k = jnp.asarray(rng.standard_normal((1, 2, 64, 8)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((1, 2, 64, 8)), jnp.bfloat16)
    payload = quant.pack_kv(k, v, v_block=16)
    whole = quant.payload_kernel_feed(payload, 16)
    part = quant.payload_kernel_feed(payload[:, :, :, 16:48], 16)
    np.testing.assert_array_equal(np.asarray(part.k_q),
                                  np.asarray(whole.k_q[:, :, 16:48]))
    np.testing.assert_array_equal(np.asarray(part.k_scale),
                                  np.asarray(whole.k_scale[:, :, 16:48]))
    np.testing.assert_array_equal(np.asarray(part.v_scale),
                                  np.asarray(whole.v_scale[:, :, 1:3]))
    # non-dividing span: no feed (caller falls back to unpack_kv)
    assert quant.payload_kernel_feed(payload[:, :, :, :24], 16) is None


# ----------------------------------------------------------------------
# 2. kernel + ring parity fuzz
# ----------------------------------------------------------------------


def _assert_q8_close(got, ref, tag):
    got = np.asarray(got, np.float32)
    ref = np.asarray(ref, np.float32)
    worst = float(np.abs(got - ref).max())
    assert worst <= Q8_FWD_MAX_ABS, f"{tag}: max abs {worst:.4f}"
    rel = float(np.linalg.norm(got - ref) / np.linalg.norm(ref))
    assert rel <= Q8_FWD_REL_L2, f"{tag}: rel L2 {rel:.4f}"


def test_kernel_q8_parity(rng):
    """Local kernel path: int8 vs bf16 fused forward — plain causal,
    windowed, and packed-segment configs, plus the resumed-carry
    partials form (the ring-hop kernel)."""
    q, k, v = make_qkv(rng)
    for tag, kw in (
        ("causal", dict(causal=True)),
        ("window", dict(causal=True, window=48)),
    ):
        ref = pallas_flash_attention(q, k, v, **kw)
        got = pallas_flash_attention(q, k, v, compute_dtype="int8", **kw)
        _assert_q8_close(got, ref, tag)

    n = q.shape[2]
    ids = np.repeat(np.arange(4, dtype=np.int32), n // 4)
    seg = jnp.asarray(np.broadcast_to(ids, (q.shape[0], n)).copy())
    ref = pallas_flash_attention(q, k, v, causal=True, segment_ids=seg)
    got = pallas_flash_attention(q, k, v, causal=True, segment_ids=seg,
                                 compute_dtype="int8")
    _assert_q8_close(got, ref, "packed")

    # resumed carry across two spans (flash_partials_tile_resume_q8)
    scale = q.shape[-1] ** -0.5
    def two_span(cd):
        p = pallas_flash_partials(q, k, v, scale=scale, causal_offset=0,
                                  block_q=32, block_k=32, compute_dtype=cd)
        p = pallas_flash_partials(q, k, v, scale=scale, block_q=32,
                                  block_k=32, carry=p, compute_dtype=cd)
        return finalize_partials(p)[0]
    _assert_q8_close(two_span("int8"), two_span(None), "resume")


def _ring_fns(mesh, **kw):
    def build(cd):
        def fn(q, k, v):
            return ring_flash_attention(
                q, k, v, None, "seq", causal=True, bucket_size=16,
                impl="pallas", compute_dtype=cd, **kw,
            )
        qspec = P("data", None, "seq", None)
        return shard_map(fn, mesh=mesh, in_specs=(qspec,) * 3,
                         out_specs=qspec, check_vma=False)
    return build(None), build("int8")


@pytest.mark.parametrize(
    "kw",
    [{}, {"striped": True}, {"counter_rotate": True},
     {"counter_rotate": True, "hop_compression": "int8"},
     {"window": 48}],
    ids=["plain", "striped", "counter", "counter_hop8", "windowed"],
)
def test_ring_q8_parity(rng, mesh, kw):
    """Ring path: int8 compute vs bf16 compute per strategy config (the
    counter_hop8 row exercises the dequant-free payload feed)."""
    ref_fn, q8_fn = _ring_fns(mesh, **kw)
    q, k, v = make_qkv(rng)
    _assert_q8_close(q8_fn(q, k, v), ref_fn(q, k, v), str(kw))


def test_ring_q8_packed_segments(rng, mesh):
    """Packed segment ids compose with int8 compute (ids rotate
    uncompressed; cross-document pairs masked after dequant)."""
    q, k, v = make_qkv(rng)
    n = q.shape[2]
    ids = np.zeros(n, np.int32)
    ids[n // 2:] = 1
    seg = jnp.asarray(np.broadcast_to(ids, (q.shape[0], n)).copy())

    def run(cd):
        fn = partial(ring_flash_attention, axis_name="seq", causal=True,
                     bucket_size=16, impl="pallas", compute_dtype=cd)
        qspec = P("data", None, "seq", None)
        return shard_map(
            lambda q, k, v, s: fn(q, k, v, None, segment_ids=s),
            mesh=mesh,
            in_specs=(qspec, qspec, qspec, P("data", "seq")),
            out_specs=qspec, check_vma=False,
        )(q, k, v, seg)

    _assert_q8_close(run("int8"), run(None), "packed")


def test_ring_q8_grads_close(rng, mesh):
    """Grads of the int8-forward ring vs the bf16 ring: the backward is
    bf16 from exact residuals, so grad error is the forward's (out, lse)
    error propagated through the loss — bounded, and the f32 accumulator
    contract is machine-checked right here."""
    ref_fn, q8_fn = _ring_fns(mesh, counter_rotate=True,
                              hop_compression="int8")
    q, k, v = make_qkv(rng)
    ge = jax.grad(lambda *a: (ref_fn(*a) ** 2).sum(), (0, 1, 2))(q, k, v)
    gc = jax.grad(lambda *a: (q8_fn(*a) ** 2).sum(), (0, 1, 2))(q, k, v)
    for a, b, name in zip(gc, ge, "qkv"):
        rel = float(np.linalg.norm(a - b) / np.linalg.norm(b))
        assert rel <= Q8_GRAD_REL_L2, f"d{name}: rel L2 {rel:.4f}"
        worst = float(np.abs(np.asarray(a) - np.asarray(b)).max())
        assert worst <= Q8_GRAD_MAX_ABS, f"d{name}: max abs {worst:.4f}"

    from ring_attention_tpu.analysis.recompile import audit_accumulator_dtypes

    assert audit_accumulator_dtypes() == []


@pytest.mark.slow
def test_contract_counter_q8(devices):
    """The counter_q8 contract row: identical collective schedule to
    counter_compressed (quantized matmuls change the kernel FEED, never
    the ring's collectives) — exact HLO hop counts fwd+fwdbwd, permute
    pairs both directions, hop-bytes pin.  Slow tier like the compressed
    rows' fwdbwd; `check_contracts.py --strategy all`, the analysis
    self-run, and the committed fingerprint baseline also hold it."""
    from ring_attention_tpu.analysis import contracts

    reports = contracts.check_strategy("counter_q8")
    bad = [v for r in reports for v in r.violations]
    assert not bad, "\n".join(bad)


@pytest.mark.slow
@pytest.mark.parametrize("counter", [False, True], ids=["uni", "counter"])
@pytest.mark.parametrize("hk", [4, 2], ids=["mha", "gqa"])
def test_ring_q8_parity_exhaustive(mesh, counter, hk):
    """Full {uni,counter} x {mha,gqa} sweep, fwd at 3 seeds + grads."""
    ref_fn, q8_fn = _ring_fns(mesh, counter_rotate=counter,
                              hop_compression="int8")
    ge = jax.grad(lambda *a: (ref_fn(*a) ** 2).sum(), (0, 1, 2))
    gc = jax.grad(lambda *a: (q8_fn(*a) ** 2).sum(), (0, 1, 2))
    for seed in (0, 1, 2):
        rng = np.random.default_rng(seed)
        q, k, v = make_qkv(rng, hk=hk)
        _assert_q8_close(q8_fn(q, k, v), ref_fn(q, k, v), f"seed={seed}")
        for a, b, name in zip(gc(q, k, v), ge(q, k, v), "qkv"):
            rel = float(np.linalg.norm(a - b) / np.linalg.norm(b))
            assert rel <= Q8_GRAD_REL_L2, f"d{name} seed={seed}: {rel:.4f}"


# ----------------------------------------------------------------------
# 3. composition proofs
# ----------------------------------------------------------------------


def test_direct_feed_bitexact_vs_launcher_quant(rng):
    """The dequant-free hop feed (payload -> payload_kernel_feed ->
    kernel) is BIT-IDENTICAL to handing the kernel the dequantized k/v
    and letting the launcher quantize — same codec, same granularity; a
    drift here means the two quantization paths forked."""
    q, k, v = make_qkv(rng, b=1, h=2, hk=2, n=64, d=8)
    q = q.astype(jnp.bfloat16)
    scale = q.shape[-1] ** -0.5
    payload = quant.pack_kv(k.astype(jnp.bfloat16), v.astype(jnp.bfloat16),
                            v_block=16)
    feed = quant.payload_kernel_feed(payload, 16)
    direct = pallas_flash_partials(
        q, None, None, scale=scale, causal_offset=0, compute_dtype="int8",
        kv_quantized=feed, block_q=16, block_k=16,
    )
    kd, vd = quant.unpack_kv(payload, jnp.bfloat16)
    requant = pallas_flash_partials(
        q, kd, vd, scale=scale, causal_offset=0, compute_dtype="int8",
        block_q=16, block_k=16,
    )
    np.testing.assert_array_equal(np.asarray(direct.acc),
                                  np.asarray(requant.acc))
    np.testing.assert_array_equal(np.asarray(direct.l),
                                  np.asarray(requant.l))


def test_requant_pin_one_quantize_per_payload(mesh):
    """Jaxpr pin (acceptance): the counter-rotated int8 ring with int8
    compute quantizes each KV payload exactly ONCE at ring entry (2
    float->int8 casts: k and v) plus one q cast per hop's launcher —
    ``2 + passes`` total outside the kernel bodies.  The naive
    dequant->requant composition re-casts k AND v at every hop
    (``3 * passes``); both counts are pinned so either regression
    (a new requant, or a silently-dropped q quantization) fails."""
    from ring_attention_tpu.analysis.dataflow import count_int8_quantize_ops

    ring = mesh.shape["seq"]
    q = jnp.zeros((4, 4, 32 * ring, 16), jnp.float32)
    qspec = P("data", None, "seq", None)

    def traced(**kw):
        fn = shard_map(
            lambda q, k, v: ring_flash_attention(
                q, k, v, None, "seq", causal=True, bucket_size=16,
                impl="pallas", compute_dtype="int8", **kw,
            ),
            mesh=mesh, in_specs=(qspec,) * 3, out_specs=qspec,
            check_vma=False,
        )
        return jax.make_jaxpr(fn)(q, q, q)

    assert count_int8_quantize_ops(
        traced(counter_rotate=True, hop_compression="int8")
    ) == 2 + ring
    # the contrast: no wire compression -> the launcher's k/v casts run
    # per hop (each hop's kv is exact bf16 — first quantization, not a
    # re-quantization; still 3 casts per hop vs the packed path's 1)
    assert count_int8_quantize_ops(traced(counter_rotate=True)) == 3 * ring


def test_dropped_dequant_toy_fails_one_line():
    """Negative toy (acceptance): an int8 x int8 QK^T whose output skips
    the scale multiply is flagged by the precision auditor in one line
    naming the rule; the scaled form is clean."""
    from jax import lax

    from ring_attention_tpu.analysis import dataflow

    q8 = jnp.ones((8, 8), jnp.int8)

    def dropped(q8, k8):
        s = lax.dot_general(q8, k8, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        return jnp.exp(s - jnp.max(s, axis=1, keepdims=True)).sum()

    violations = dataflow.audit_precision_flow(dropped, q8, q8, label="toy")
    assert violations and all("\n" not in f for f in violations)
    assert any("[rule: int8-dequant]" in f for f in violations)

    def scaled(q8, k8, sc):
        s = lax.dot_general(q8, k8, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sc
        return jnp.exp(s - jnp.max(s, axis=1, keepdims=True)).sum()

    assert dataflow.audit_precision_flow(
        scaled, q8, q8, jnp.float32(0.1), label="toy") == []


def test_precision_auditor_covers_q8_kernels(rng):
    """Acceptance: the precision-flow auditor passes on the int8 kernel
    jaxprs (fwd int8 + bwd bf16, and the dequant-free feed chain) — no
    reduction/exp/loop-carry sees undequantized int8, f32 (acc, m, l)
    pinned.  Audits the two PR 13 chains directly (the full suite —
    which includes the same rows — rides ``check_contracts.py
    --dataflow`` and the analysis self-run)."""
    from ring_attention_tpu.analysis.dataflow import audit_precision_flow

    q = jnp.asarray(rng.standard_normal((1, 2, 32, 8)), jnp.bfloat16)
    kv = jnp.asarray(rng.standard_normal((1, 1, 32, 8)), jnp.bfloat16)

    def q8_step(q, k, v):
        return jax.grad(
            lambda q, k, v: pallas_flash_attention(
                q, k, v, causal=True, interpret=True, compute_dtype="int8",
            ).astype(jnp.float32).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)

    assert audit_precision_flow(q8_step, q, kv, kv, label="q8") == []

    def q8_feed(q, k, v):
        payload = quant.pack_kv(k, v, v_block=8)
        feed = quant.payload_kernel_feed(payload, 8)
        p = pallas_flash_partials(
            q, None, None, scale=8 ** -0.5, causal_offset=0,
            compute_dtype="int8", kv_quantized=feed, block_q=8, block_k=8,
            interpret=True,
        )
        out, lse = finalize_partials(p)
        return out.sum() + lse.sum()

    assert audit_precision_flow(q8_feed, q, kv, kv, label="q8_feed") == []


# ----------------------------------------------------------------------
# validation surfaces
# ----------------------------------------------------------------------


def test_validation_surfaces(rng, mesh):
    q, k, v = make_qkv(rng, b=4, h=2, hk=2, n=32, d=8)
    with pytest.raises(ValueError, match="compute_dtype"):
        pallas_flash_attention(q, k, v, causal=True, compute_dtype="fp4")
    with pytest.raises(NotImplementedError, match="bf16 this round"):
        pallas_flash_backward(
            q, q, k, v, jnp.zeros(q.shape[:3]), jnp.zeros(q.shape[:3]),
            scale=1.0, compute_dtype="int8",
        )
    qspec = P("data", None, "seq", None)
    with pytest.raises(ValueError, match="Pallas kernels only"):
        shard_map(
            lambda q, k, v: ring_flash_attention(
                q, k, v, None, "seq", causal=True, impl="xla",
                compute_dtype="int8",
            ),
            mesh=mesh, in_specs=(qspec,) * 3, out_specs=qspec,
        )(q, k, v)
    # the dispatcher refuses a silent bf16 fallback
    from ring_attention_tpu import ops

    with pytest.raises(ValueError, match="Pallas"):
        ops.attention(q, k, v, causal=True, impl="xla",
                      compute_dtype="int8")
    # kv_quantized at the wrong granularity names the fitted block
    feed = quant.quantize_kv_blocks(k, v, 8)
    with pytest.raises(ValueError, match="fitted block_k"):
        pallas_flash_partials(
            q, None, None, scale=1.0, causal_offset=0,
            compute_dtype="int8", kv_quantized=feed, block_q=16, block_k=16,
        )
