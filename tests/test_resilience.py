"""Fault-injection harness for the resilience layer (docs/resilience.md).

Every failure mode the subsystem exists to survive is INJECTED here and
the recovery behavior asserted, all on the 8-virtual-device CPU mesh
(fast tier — no TPU, no `slow` marks except the subprocess kill/resume
end-to-end check):

- NaN gradients at step k -> the guarded step skips the update and the
  params are bit-identical to the pre-NaN state.
- A checkpoint truncated mid-write -> restore falls back to the previous
  good step (and an empty directory / changed optimizer structure give
  the documented cold-start / clear-error behaviors).
- A forced Pallas failure -> ``impl="auto"`` degrades to the XLA path
  with parity, a one-shot warning, and a queryable record.
- A hung probe -> ``with_retries`` times the attempt out and backs off
  exponentially.
"""

import glob
import json
import os
import subprocess
import sys
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ring_attention_tpu.utils import (
    CheckpointManager,
    CheckpointStructureError,
    init_step_stats,
    make_train_step,
)
from ring_attention_tpu.utils import resilience

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_resilience_state():
    """Armed faults and degradation records are process-global; never let
    one test's injection leak into the next."""
    resilience.reset()
    yield
    resilience.reset()


# ----------------------------------------------------------------------
# with_retries: timeout + exponential backoff
# ----------------------------------------------------------------------


def test_with_retries_passthrough():
    assert resilience.with_retries(lambda: 41 + 1) == 42


def test_with_retries_retries_then_succeeds():
    sleeps = []
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    out = resilience.with_retries(
        flaky, backoff=0.5, max_attempts=5, sleep=sleeps.append
    )
    assert out == "ok"
    assert len(calls) == 3
    # exponential: backoff * 2**attempt for each failed attempt
    assert sleeps == [0.5, 1.0]


def test_with_retries_hung_callable_times_out_and_backs_off():
    """The round 3-5 wedge mode: a probe that simply never returns."""
    sleeps = []
    t0 = time.monotonic()
    with pytest.raises(resilience.RetryError) as ei:
        resilience.with_retries(
            lambda: time.sleep(30),
            timeout=0.05,
            backoff=0.01,
            max_attempts=3,
            sleep=sleeps.append,
        )
    # all three attempts timed out, each followed by doubled backoff
    assert isinstance(ei.value.last, resilience.RetryTimeout)
    assert sleeps == [0.01, 0.02]
    # wall time is attempts * timeout, NOT attempts * 30s: the hang was cut
    assert time.monotonic() - t0 < 5.0


def test_with_retries_respects_retry_on():
    with pytest.raises(KeyError):
        resilience.with_retries(
            lambda: (_ for _ in ()).throw(KeyError("boom")),
            retry_on=(OSError,),
            max_attempts=3,
        )


def test_with_retries_exhaustion_raises_retry_error():
    sleeps = []
    with pytest.raises(resilience.RetryError) as ei:
        resilience.with_retries(
            lambda: (_ for _ in ()).throw(OSError("down")),
            backoff=1.0,
            max_attempts=2,
            sleep=sleeps.append,
        )
    assert isinstance(ei.value.last, OSError)
    assert sleeps == [1.0]  # no sleep after the final attempt


def test_with_retries_validates_args():
    with pytest.raises(ValueError):
        resilience.with_retries(lambda: 1, max_attempts=0)
    with pytest.raises(ValueError):
        resilience.with_retries(lambda: 1, backoff=-1.0)


# ----------------------------------------------------------------------
# Guarded train step: NaN-grad injection
# ----------------------------------------------------------------------


def _tiny_problem():
    def loss_fn(p, x):
        return jnp.sum((p["w"] * x - 1.0) ** 2) + jnp.sum(p["b"] ** 2)

    params = {"w": jnp.arange(1.0, 5.0), "b": jnp.zeros(2)}
    opt = optax.adam(1e-2)
    return loss_fn, params, opt


def test_guarded_step_skips_nan_and_keeps_params_bit_identical():
    loss_fn, params, opt = _tiny_problem()
    # the injection hook: a pure_callback tap on the loss, so the SAME
    # compiled step can be poisoned at exactly step k from the host
    step = jax.jit(
        make_train_step(
            resilience.faulty_loss(loss_fn), opt, skip_nonfinite=True
        )
    )
    opt_state = opt.init(params)
    stats = init_step_stats()
    x = jnp.ones(4)

    for _ in range(3):  # healthy steps compile + move the params
        params, opt_state, stats, loss = step(params, opt_state, stats, x)
    assert bool(stats.step_ok) and int(stats.skipped) == 0

    pre_params = jax.device_get(params)
    pre_opt = jax.device_get(opt_state)
    with resilience.inject("nan_loss"):  # step k is poisoned
        params, opt_state, stats, loss = step(params, opt_state, stats, x)

    assert not bool(stats.step_ok)
    assert int(stats.skipped) == 1
    assert np.isnan(float(loss))  # the loss is reported, not masked
    post_params = jax.device_get(params)
    post_opt = jax.device_get(opt_state)
    for pre, post in ((pre_params, post_params), (pre_opt, post_opt)):
        for a, b in zip(jax.tree_util.tree_leaves(pre),
                        jax.tree_util.tree_leaves(post)):
            np.testing.assert_array_equal(a, b)  # bit-identical, not close

    # the run RESUMES: the next healthy step applies normally
    params, opt_state, stats, loss = step(params, opt_state, stats, x)
    assert bool(stats.step_ok)
    assert int(stats.skipped) == 1
    assert np.isfinite(float(loss))
    changed = any(
        not np.array_equal(a, b)
        for a, b in zip(jax.tree_util.tree_leaves(post_params),
                        jax.tree_util.tree_leaves(jax.device_get(params)))
    )
    assert changed, "healthy step after a skip must update params"


def test_guarded_step_matches_unguarded_when_healthy():
    loss_fn, params, opt = _tiny_problem()
    x = jnp.full(4, 0.5)
    plain = jax.jit(make_train_step(loss_fn, opt))
    guarded = jax.jit(make_train_step(loss_fn, opt, skip_nonfinite=True))
    p1, o1 = params, opt.init(params)
    p2, o2, stats = params, opt.init(params), init_step_stats()
    for _ in range(4):
        p1, o1, l1 = plain(p1, o1, x)
        p2, o2, stats, l2 = guarded(p2, o2, stats, x)
    assert int(stats.skipped) == 0
    np.testing.assert_allclose(float(l1), float(l2), rtol=0, atol=0)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(p1)),
                    jax.tree_util.tree_leaves(jax.device_get(p2))):
        np.testing.assert_array_equal(a, b)


def test_clip_grad_norm_bounds_the_update():
    def loss_fn(p, x):
        return 1e6 * jnp.sum(p["w"] * x)  # huge constant gradient

    params = {"w": jnp.zeros(4)}
    opt = optax.sgd(1.0)
    x = jnp.ones(4)
    step = jax.jit(make_train_step(loss_fn, opt, clip_grad_norm=1.0))
    new_params, _, _ = step(params, opt.init(params), x)
    gnorm = float(optax.global_norm(
        jax.tree_util.tree_map(
            lambda a, b: a - b, params, new_params
        )
    ))
    assert gnorm <= 1.0 + 1e-5, gnorm


def test_make_train_step_validates_clip():
    loss_fn, params, opt = _tiny_problem()
    with pytest.raises(ValueError):
        make_train_step(loss_fn, opt, clip_grad_norm=0.0)


def test_guarded_step_with_accumulation():
    loss_fn, params, opt = _tiny_problem()
    step = jax.jit(
        make_train_step(
            resilience.faulty_loss(loss_fn), opt,
            accum_steps=2, skip_nonfinite=True,
        )
    )
    opt_state, stats = opt.init(params), init_step_stats()
    x = jnp.ones((2, 4))  # leading batch dim splits into 2 microbatches
    params, opt_state, stats, loss = step(params, opt_state, stats, x)
    assert bool(stats.step_ok)
    pre = jax.device_get(params)
    with resilience.inject("nan_loss"):
        params, opt_state, stats, loss = step(params, opt_state, stats, x)
    assert not bool(stats.step_ok) and int(stats.skipped) == 1
    for a, b in zip(jax.tree_util.tree_leaves(pre),
                    jax.tree_util.tree_leaves(jax.device_get(params))):
        np.testing.assert_array_equal(a, b)


# ----------------------------------------------------------------------
# Checkpoints: truncation, fallback, retention, structure, resume
# ----------------------------------------------------------------------


def _make_state(seed: float = 0.0):
    params = {"w": jnp.arange(4.0) + seed, "b": jnp.zeros((2, 3)) + seed}
    opt = optax.adam(1e-3)
    return {"params": params, "opt_state": opt.init(params)}


def test_checkpoint_truncated_mid_write_falls_back(tmp_path):
    mgr = CheckpointManager(tmp_path, keep=3)
    s1, s2 = _make_state(1.0), _make_state(2.0)
    mgr.save(10, s1)
    mgr.save(20, s2)

    # the preemption: the newest checkpoint's payload is cut mid-file
    npz = os.path.join(str(tmp_path), "step_00000020", "arrays.npz")
    with open(npz, "r+b") as f:
        f.truncate(os.path.getsize(npz) // 2)

    with pytest.warns(UserWarning, match="corrupt"):
        restored = mgr.restore(_make_state())
    assert restored is not None
    state, step = restored
    assert step == 10  # fell back to the previous good step
    np.testing.assert_array_equal(
        np.asarray(state["params"]["w"]), np.asarray(s1["params"]["w"])
    )


def test_checkpoint_unreadable_manifest_falls_back(tmp_path):
    mgr = CheckpointManager(tmp_path, keep=3)
    mgr.save(1, _make_state(1.0))
    mgr.save(2, _make_state(2.0))
    man = os.path.join(str(tmp_path), "step_00000002", "manifest.json")
    with open(man, "w") as f:
        f.write("{not json")
    with pytest.warns(UserWarning, match="corrupt"):
        restored = mgr.restore(_make_state())
    assert restored is not None and restored[1] == 1


def test_checkpoint_restore_missing_and_empty_dir(tmp_path):
    # missing: the manager creates the dir, restore finds nothing
    mgr = CheckpointManager(os.path.join(str(tmp_path), "never_written"))
    assert mgr.restore(_make_state()) is None
    assert mgr.latest_step() is None
    state, start = mgr.resume_or_init(lambda: _make_state(5.0))
    assert start == 0
    np.testing.assert_array_equal(
        np.asarray(state["params"]["w"]), np.asarray(_make_state(5.0)["params"]["w"])
    )


def test_checkpoint_changed_optimizer_structure_is_a_clear_error(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save(0, _make_state())
    params = {"w": jnp.arange(4.0), "b": jnp.zeros((2, 3))}
    changed = {"params": params, "opt_state": optax.sgd(1e-3).init(params)}
    with pytest.raises(CheckpointStructureError, match="structure"):
        mgr.restore(changed)


def test_checkpoint_keep_last_n_retention(tmp_path):
    mgr = CheckpointManager(tmp_path, keep=2)
    for step in range(5):
        mgr.save(step, _make_state(float(step)))
    assert mgr.all_steps() == [3, 4]
    # the pruned directories are actually gone from disk
    dirs = sorted(glob.glob(os.path.join(str(tmp_path), "step_*")))
    assert [os.path.basename(d) for d in dirs] == [
        "step_00000003", "step_00000004"
    ]


def test_checkpoint_save_is_atomic_no_partial_step_dirs(tmp_path):
    mgr = CheckpointManager(tmp_path, keep=3)
    mgr.save(0, _make_state())
    # a stale temp dir from a preempted writer is swept by the next save
    stale = os.path.join(str(tmp_path), ".tmp-step_00000099-1234")
    os.makedirs(stale)
    mgr.save(1, _make_state(1.0))
    assert not os.path.exists(stale)
    assert mgr.all_steps() == [0, 1]


def test_resume_or_init_roundtrip_matches_uninterrupted_training(tmp_path):
    """Kill/resume equivalence on the real train-step machinery: a run
    resumed from step k's checkpoint reaches the same loss (bit-equal
    params) as one that never stopped."""
    loss_fn, params0, opt = _tiny_problem()
    step = jax.jit(make_train_step(loss_fn, opt))
    x = jnp.full(4, 0.5)

    # uninterrupted: 6 steps
    p, o = params0, opt.init(params0)
    for _ in range(6):
        p, o, loss_full = step(p, o, x)

    # interrupted: 3 steps, checkpoint, "crash", resume, 3 more
    mgr = CheckpointManager(tmp_path)
    p1, o1 = params0, opt.init(params0)
    for i in range(3):
        p1, o1, _ = step(p1, o1, x)
        mgr.save(i, {"params": p1, "opt_state": o1})
    del p1, o1  # the crash

    mgr2 = CheckpointManager(tmp_path)
    state, start = mgr2.resume_or_init(
        lambda: {"params": params0, "opt_state": opt.init(params0)}
    )
    assert start == 3
    p2, o2 = state["params"], state["opt_state"]
    for _ in range(start, 6):
        p2, o2, loss_resumed = step(p2, o2, x)

    np.testing.assert_array_equal(float(loss_full), float(loss_resumed))
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(p)),
                    jax.tree_util.tree_leaves(jax.device_get(p2))):
        np.testing.assert_array_equal(a, b)


# ----------------------------------------------------------------------
# Kernel degradation: impl="auto" Pallas -> XLA fallback
# ----------------------------------------------------------------------


def _qkv():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1, 2, 64, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 2, 64, 16)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 2, 64, 16)), jnp.float32)
    return q, k, v


def test_impl_auto_falls_back_with_xla_parity():
    from ring_attention_tpu.ops import attention, flash_attention

    q, k, v = _qkv()
    ref = flash_attention(q, k, v, causal=True)
    with pytest.warns(UserWarning, match="degraded"):
        with resilience.inject(resilience.PALLAS_FAULT):
            out = attention(q, k, v, causal=True, impl="auto")
    assert resilience.degradation.is_degraded(resilience.PALLAS_COMPONENT)
    events = resilience.degradation.events()
    assert events and events[0].component == resilience.PALLAS_COMPONENT
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)

    # the degradation is sticky: later auto calls take XLA silently
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # one-shot: no second warning
        out2 = attention(q, k, v, causal=True, impl="auto")
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref), atol=1e-6)


def test_impl_pallas_explicit_fails_loudly():
    from ring_attention_tpu.ops import attention

    q, k, v = _qkv()
    with resilience.inject(resilience.PALLAS_FAULT):
        with pytest.raises(resilience.InjectedFault):
            attention(q, k, v, causal=True, impl="pallas")


def test_impl_xla_never_touches_pallas():
    from ring_attention_tpu.ops import attention, flash_attention

    q, k, v = _qkv()
    with resilience.inject(resilience.PALLAS_FAULT):
        out = attention(q, k, v, causal=True, impl="xla")
    assert not resilience.degradation.is_degraded(resilience.PALLAS_COMPONENT)
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(flash_attention(q, k, v, causal=True)),
        atol=0,
    )


def test_impl_auto_rejects_unknown():
    from ring_attention_tpu.ops import attention

    q, k, v = _qkv()
    with pytest.raises(ValueError, match="impl"):
        attention(q, k, v, impl="tpu_magic")


def test_model_impl_auto_parity_under_forced_pallas_failure():
    """End-to-end: a RingTransformer configured impl='auto' produces the
    same loss whether the Pallas path works or is forced to fail."""
    from ring_attention_tpu.models import RingTransformer

    rng = np.random.default_rng(1)
    toks = jnp.asarray(rng.integers(0, 64, (1, 33)), jnp.int32)
    model = RingTransformer(
        num_tokens=64, dim=32, depth=1, causal=True, heads=2, dim_head=16,
        bucket_size=32, use_ring=False, impl="auto",
    )
    params = model.init(jax.random.PRNGKey(0), toks, return_loss=True)
    baseline = float(model.apply(params, toks, return_loss=True))

    resilience.reset()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        with resilience.inject(resilience.PALLAS_FAULT):
            degraded = float(model.apply(params, toks, return_loss=True))
    assert resilience.degradation.is_degraded(resilience.PALLAS_COMPONENT)
    np.testing.assert_allclose(baseline, degraded, atol=3e-5)


# ----------------------------------------------------------------------
# Satellite: loss_chunk_size validation
# ----------------------------------------------------------------------


@pytest.mark.parametrize("bad", [0, -1, -64])
def test_loss_chunk_size_validation(bad):
    from ring_attention_tpu.models import RingTransformer

    model = RingTransformer(
        num_tokens=16, dim=8, depth=1, causal=True, heads=1, dim_head=8,
        use_ring=False, loss_chunk_size=bad,
    )
    toks = jnp.zeros((1, 9), jnp.int32)
    with pytest.raises(ValueError, match="loss_chunk_size"):
        model.init(jax.random.PRNGKey(0), toks, return_loss=True)


def test_loss_chunk_size_valid_values_still_work():
    from ring_attention_tpu.models import RingTransformer

    toks = jnp.zeros((1, 9), jnp.int32)
    for ok in (None, 4):
        model = RingTransformer(
            num_tokens=16, dim=8, depth=1, causal=True, heads=1, dim_head=8,
            use_ring=False, loss_chunk_size=ok,
        )
        params = model.init(jax.random.PRNGKey(0), toks, return_loss=True)
        assert np.isfinite(float(model.apply(params, toks, return_loss=True)))


# ----------------------------------------------------------------------
# bench.py device probe through the shared retry helper
# ----------------------------------------------------------------------


def test_bench_probe_failure_emits_wedge_honest_json(tmp_path):
    """bench.py with an unusable backend still prints ONE JSON line with
    error + last_measured (the standing-numbers contract), now routed
    through with_retries."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "nonexistent_backend"
    env["BENCH_PROBE_ATTEMPTS"] = "1"
    env["BENCH_PROBE_BACKOFF_S"] = "0"
    # hermetic hwlog: the probe-failure row must not land in the repo's
    # real docs/hwlogs/results.jsonl from a CI exercise
    hwlog = os.path.join(str(tmp_path), "results.jsonl")
    env["BENCH_HWLOG"] = hwlog
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["value"] == 0.0
    assert "error" in payload
    assert "last_measured" in payload
    # the structured wedge-history row (telemetry satellite): same failure,
    # queryable from the hardware log instead of a tail string
    with open(hwlog) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    assert rows and rows[-1]["step"] == "probe_failure"
    assert "error" in rows[-1]["result"]


def test_impl_auto_input_error_does_not_degrade():
    """A caller's input mistake must raise as itself and must NOT mark the
    Pallas path degraded (the fallback is for kernel failures only)."""
    from ring_attention_tpu.ops import attention

    q, k, v = _qkv()
    bad_mask = jnp.ones((1, 7), bool)  # wrong kv length
    with pytest.raises(ValueError):
        attention(q, k, v, bad_mask, impl="auto")
    assert not resilience.degradation.is_degraded(resilience.PALLAS_COMPONENT)


def test_checkpoint_resave_same_step_is_atomic(tmp_path):
    mgr = CheckpointManager(tmp_path, keep=3)
    mgr.save(5, _make_state(1.0))
    mgr.save(5, _make_state(2.0))  # re-save over the existing step
    restored = mgr.restore(_make_state())
    assert restored is not None and restored[1] == 5
    np.testing.assert_array_equal(
        np.asarray(restored[0]["params"]["w"]),
        np.asarray(_make_state(2.0)["params"]["w"]),
    )
    # no .old backup lingers after a clean re-save
    assert not glob.glob(os.path.join(str(tmp_path), "*.old"))


def test_checkpoint_orphaned_backup_is_recovered(tmp_path):
    """Crash window between rename-aside and rename-into-place: the .old
    backup is a complete checkpoint and restore must recover it."""
    mgr = CheckpointManager(tmp_path, keep=3)
    mgr.save(7, _make_state(3.0))
    live = os.path.join(str(tmp_path), "step_00000007")
    os.replace(live, live + ".old")  # the simulated crash state
    restored = mgr.restore(_make_state())
    assert restored is not None and restored[1] == 7
    np.testing.assert_array_equal(
        np.asarray(restored[0]["params"]["w"]),
        np.asarray(_make_state(3.0)["params"]["w"]),
    )


def test_impl_auto_bad_head_chunks_raises_not_degrades():
    """A Pallas-only kwarg error is a caller mistake: it must raise, not
    silently return an un-chunked XLA result while degrading Pallas."""
    from ring_attention_tpu.ops import attention

    q, k, v = _qkv()  # 2 heads
    with pytest.raises(ValueError, match="head_chunks"):
        attention(q, k, v, causal=True, impl="auto", head_chunks=3)
    assert not resilience.degradation.is_degraded(resilience.PALLAS_COMPONENT)


def test_impl_auto_on_non_tpu_backend_prefers_xla_silently():
    """On a CPU backend 'auto' must resolve to XLA without any
    degradation record — interpret-mode Pallas would be a pessimization,
    and a warning would cry wolf on every CPU box."""
    assert jax.devices()[0].platform != "tpu"  # this suite forces CPU
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert resilience.resolve_attention_impl("auto") == "xla"
    assert not resilience.degradation.is_degraded(resilience.PALLAS_COMPONENT)


def test_checkpoint_explicit_missing_step_is_not_found_not_corrupt(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, _make_state())
    with pytest.raises(FileNotFoundError, match="step 42"):
        mgr.restore(_make_state(), step=42)


# ----------------------------------------------------------------------
# Cross-process manager races (PR 12 satellite): pid-aware sweep + the
# watcher-protocol directory lock around save/prune
# ----------------------------------------------------------------------


def test_sweep_spares_live_concurrent_writers_tmp_dir(tmp_path):
    """The pre-fix _sweep_tmp deleted ANY .tmp-* dir — including a
    concurrent manager's live in-flight save.  Now only dead writers'
    debris is swept: a temp dir stamped with a LIVE pid (another
    process's save in progress) survives, a dead pid's is removed."""
    mgr = CheckpointManager(tmp_path, keep=3)
    mgr.save(0, _make_state())
    live_pid = os.getppid()  # alive and not us: a concurrent writer
    live = os.path.join(str(tmp_path), f".tmp-step_00000099-{live_pid}")
    dead = os.path.join(str(tmp_path), ".tmp-step_00000098-999999999")
    os.makedirs(live)
    os.makedirs(dead)
    mgr.save(1, _make_state(1.0))  # save sweeps first
    assert os.path.isdir(live), "live concurrent writer's temp dir deleted"
    assert not os.path.isdir(dead), "dead writer's temp dir survived"
    # unparsable writer pid: only swept past the minimum age
    odd = os.path.join(str(tmp_path), ".tmp-whatever")
    os.makedirs(odd)
    mgr.save(2, _make_state(2.0))
    assert os.path.isdir(odd), "young unparsable temp dir swept too eagerly"


def test_keep_vs_concurrent_save_never_loses_the_latest(tmp_path):
    """Two managers (keep=2) hammering ONE directory from threads — the
    interleaving that used to let one manager's retention prune race
    another's rename window.  Under the directory lock every save+prune
    is a critical section: afterwards exactly the newest steps remain,
    every surviving step restores intact, and no .tmp debris is left."""
    import threading

    errors: list[BaseException] = []

    def writer(offset: int) -> None:
        try:
            mgr = CheckpointManager(tmp_path, keep=2, lock_stale_age=5.0)
            for i in range(4):
                mgr.save(offset + 2 * i, _make_state(float(offset + i)))
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(off,))
               for off in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    mgr = CheckpointManager(tmp_path, keep=2)
    steps = mgr.all_steps()
    assert len(steps) <= 2 and max(steps) == 7, steps
    restored = mgr.restore(_make_state())
    assert restored is not None and restored[1] == max(steps)
    assert not glob.glob(os.path.join(str(tmp_path), ".tmp-*"))


def test_directory_lock_stale_takeover_and_contention(tmp_path):
    """The watcher protocol, in library form: takeover requires pid file
    + dead pid + minimum age; a LIVE holder is never stolen from."""
    from ring_attention_tpu.utils.resilience import (
        DirectoryLock,
        LockTimeout,
    )

    # stale lock (dead pid, old): a contender takes over
    lock_dir = os.path.join(str(tmp_path), ".ckpt.lock")
    os.makedirs(lock_dir)
    with open(os.path.join(lock_dir, "pid"), "w") as f:
        f.write("999999999")
    old = time.time() - 60
    os.utime(lock_dir, (old, old))
    lock = DirectoryLock(str(tmp_path), stale_age=1.0)
    assert lock.acquire(timeout=5.0)
    lock.release()

    # live holder: a second contender times out instead of stealing
    holder = DirectoryLock(str(tmp_path), stale_age=30.0)
    assert holder.acquire(timeout=1.0)
    try:
        thief = DirectoryLock(str(tmp_path), stale_age=30.0)
        with pytest.raises(LockTimeout):
            thief.acquire(timeout=0.3)
        assert thief.acquire(timeout=0) is False  # nonblocking miss
    finally:
        holder.release()
    # released: immediately acquirable again
    assert DirectoryLock(str(tmp_path)).acquire(timeout=1.0)


def test_directory_lock_not_shared_across_threads(tmp_path):
    """A sibling thread holding the SAME DirectoryLock instance is
    contention, not ownership: the async checkpoint writer must never
    have its lock 'acquired' and released out from under it by a
    concurrent restore on the main thread."""
    import threading

    from ring_attention_tpu.utils.resilience import DirectoryLock

    lock = DirectoryLock(str(tmp_path))
    entered = threading.Event()
    done = threading.Event()

    def writer():
        with lock.locked():
            entered.set()
            done.wait(timeout=30)

    t = threading.Thread(target=writer)
    t.start()
    try:
        assert entered.wait(timeout=10)
        with lock.locked(timeout=0) as held:
            assert held is False  # busy, not re-entrant ownership
        assert os.path.isdir(lock.path), (
            "the writer's lock dir was released by another thread"
        )
    finally:
        done.set()
        t.join()
    # after the writer released, nonblocking acquire succeeds
    with lock.locked(timeout=0) as held:
        assert held is True


def test_directory_lock_pidless_debris_taken_over_by_age(tmp_path):
    """A holder killed between mkdir and the pid stamp leaves a pid-less
    lock dir; past stale_age that is debris, not a writer — it must not
    block the directory forever."""
    from ring_attention_tpu.utils.resilience import DirectoryLock

    lock_dir = os.path.join(str(tmp_path), ".ckpt.lock")
    os.makedirs(lock_dir)  # no pid file inside
    old = time.time() - 60
    os.utime(lock_dir, (old, old))
    lock = DirectoryLock(str(tmp_path), stale_age=1.0)
    assert lock.acquire(timeout=5.0)
    lock.release()


def test_restore_recovers_old_backup_despite_crashed_lock_holder(tmp_path):
    """The worst crash window: the writer died between rename-aside and
    rename-into-place WHILE HOLDING the directory lock.  Restore must
    still take the stale lock over (pid-dead + stale_age), run the
    sweep, recover the .old backup — never cold-start over it."""
    mgr = CheckpointManager(tmp_path, keep=3, lock_stale_age=0.5)
    mgr.save(7, _make_state(3.0))
    live = os.path.join(str(tmp_path), "step_00000007")
    os.replace(live, live + ".old")  # crash state: only the backup left
    lock_dir = os.path.join(str(tmp_path), ".ckpt.lock")
    os.makedirs(lock_dir)  # ...and the dead writer still "holds" the lock
    with open(os.path.join(lock_dir, "pid"), "w") as f:
        f.write("999999999")
    old = time.time() - 60
    os.utime(lock_dir, (old, old))
    restored = CheckpointManager(tmp_path, lock_stale_age=0.5).restore(
        _make_state()
    )
    assert restored is not None and restored[1] == 7
    np.testing.assert_array_equal(
        np.asarray(restored[0]["params"]["w"]),
        np.asarray(_make_state(3.0)["params"]["w"]),
    )


def test_checkpoint_explicit_corrupt_step_raises_not_cold_start(tmp_path):
    """restore(step=N) on a corrupt step must raise, not warn-and-return
    None: None reads as 'cold start' and would silently reinitialize
    over the history the operator explicitly named."""
    from ring_attention_tpu.utils.checkpoint import CheckpointCorruptError

    mgr = CheckpointManager(tmp_path, keep=3)
    mgr.save(1, _make_state(1.0))
    mgr.save(2, _make_state(2.0))
    npz = os.path.join(str(tmp_path), "step_00000002", "arrays.npz")
    with open(npz, "r+b") as f:
        f.truncate(os.path.getsize(npz) // 2)
    with pytest.raises(CheckpointCorruptError):
        mgr.restore(_make_state(), step=2)
    # without step=, the documented fallback still works
    with pytest.warns(UserWarning, match="corrupt"):
        restored = mgr.restore(_make_state())
    assert restored is not None and restored[1] == 1
