"""Parity: hybrid Ulysses x Ring 2-D sequence parallelism vs the oracle.

Capability beyond the reference (1-D context parallelism only): the
sequence axis factors as ``seq = ulysses x ring`` — all-to-all head
parallelism over the inner mesh axis, the existing KV-rotation ring over
the outer axis on each device's head subset — and must match dense
attention in outputs AND gradients on every factoring of the 8-device
mesh, composed with everything the 1-D paths support (striping, GQA,
packed segment ids, key-padding masks, bidirectional KV streams, the
Pallas kernels).

The hop-count acceptance check reads the optimized HLO: the hybrid step's
ring ``collective-permute``s must stay within outer-axis groups (never
crossing the ulysses axis) and number ``ulysses_size`` x fewer than the
pure ring's at equal world size.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ring_attention_tpu.models import RingAttention, RingTransformer
from ring_attention_tpu.ops import default_attention
from ring_attention_tpu.parallel import (
    create_mesh,
    hybrid_attention,
    seq_axes,
    seq_world,
    shard_batch,
)
from ring_attention_tpu.utils.compat import shard_map

ATOL = 2e-5
GRAD_ATOL = 5e-4

# (data, ulysses, ring) sizes of the 8 virtual devices; the mesh axis
# order itself is (data, ring, ulysses) — ulysses innermost/fastest
FACTORINGS = [(2, 2, 2), (1, 2, 4), (1, 4, 2)]


@pytest.fixture(scope="module")
def meshes():
    return {
        (d, u, r): create_mesh(ulysses_size=u, ring_size=r, data_size=d)
        for (d, u, r) in FACTORINGS
    }


def make_pair(mesh, **kw):
    """Hybrid module + single-device oracle sharing identical params."""
    common = {"dim": 32, "heads": 8, "dim_head": 8, "bucket_size": 4, **kw}
    hyb = RingAttention(
        use_ring=True, auto_shard=True, mesh=mesh,
        sequence_parallel="hybrid", **common,
    )
    ref = RingAttention(
        use_ring=False, force_regular_attn=True,
        **{k: v for k, v in common.items()
           if k not in ("striped", "ring_bidirectional", "use_pallas")},
    )
    return hyb, ref


# ----------------------------------------------------------------------
# Module parity across every factoring
# ----------------------------------------------------------------------


@pytest.mark.parametrize("factoring", FACTORINGS, ids=lambda f: "x".join(map(str, f)))
@pytest.mark.parametrize("striped", [False, True])
def test_hybrid_module_parity(rng, meshes, factoring, striped):
    """Causal parity on every mesh factoring, odd length (auto-shard pad),
    striped (outer-ring stripe factor) and contiguous layouts."""
    hyb, ref = make_pair(meshes[factoring], causal=True, striped=striped)
    x = jnp.asarray(rng.standard_normal((2, 31, 32)), jnp.float32)
    params = ref.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        hyb.apply(params, x), ref.apply(params, x), atol=ATOL
    )


@pytest.mark.parametrize("factoring", FACTORINGS, ids=lambda f: "x".join(map(str, f)))
def test_hybrid_input_grads(rng, meshes, factoring):
    hyb, ref = make_pair(meshes[factoring], causal=True, striped=True)
    x = jnp.asarray(rng.standard_normal((2, 31, 32)), jnp.float32)
    params = ref.init(jax.random.PRNGKey(0), x)
    g_ref = jax.grad(lambda x: (ref.apply(params, x) ** 2).sum())(x)
    g_out = jax.grad(lambda x: (hyb.apply(params, x) ** 2).sum())(x)
    np.testing.assert_allclose(g_out, g_ref, atol=GRAD_ATOL)


@pytest.mark.slow
def test_hybrid_param_grads(rng, meshes):
    """Param-gradient parity: dk/dv must sum correctly back through the
    all-to-all transpose AND the ring's circulating dkv accumulators."""
    hyb, ref = make_pair(meshes[(1, 2, 4)], causal=True)
    x = jnp.asarray(rng.standard_normal((2, 32, 32)), jnp.float32)
    params = ref.init(jax.random.PRNGKey(0), x)
    g_ref = jax.grad(lambda p: (ref.apply(p, x) ** 2).sum())(params)
    g_out = jax.grad(lambda p: (hyb.apply(p, x) ** 2).sum())(params)
    for a, b in zip(jax.tree.leaves(g_out), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL)


# ----------------------------------------------------------------------
# GQA: divisible, small-hk (hk < ulysses), and unaligned head groups
# ----------------------------------------------------------------------


def test_hybrid_gqa_divisible(rng, meshes):
    """hk % ulysses == 0: the plain kv all-to-all leg."""
    hyb, ref = make_pair(meshes[(2, 2, 2)], causal=True, kv_heads=4,
                         striped=True)
    x = jnp.asarray(rng.standard_normal((2, 31, 32)), jnp.float32)
    params = ref.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        hyb.apply(params, x), ref.apply(params, x), atol=ATOL
    )


def test_hybrid_gqa_small_hk(rng, meshes):
    """kv_heads < ulysses_size: the real heads transfer once (all-gather)
    and the ring circulates one deduplicated head per device — outputs and
    param grads (summed over the copies) match the oracle."""
    hyb, ref = make_pair(meshes[(1, 4, 2)], causal=True, kv_heads=2,
                         striped=True)
    x = jnp.asarray(rng.standard_normal((2, 31, 32)), jnp.float32)
    params = ref.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        hyb.apply(params, x), ref.apply(params, x), atol=ATOL
    )
    g_ref = jax.grad(lambda p: (ref.apply(p, x) ** 2).sum())(params)
    g_out = jax.grad(lambda p: (hyb.apply(p, x) ** 2).sum())(params)
    for a, b in zip(jax.tree.leaves(g_out), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL)


def test_hybrid_gqa_unaligned(rng, meshes):
    """hk neither divides the axis nor aligns with the per-device head
    block (12 q heads / 3 kv heads over a 4-way ulysses axis): the
    per-query-head local copy fallback."""
    hyb, ref = make_pair(meshes[(1, 4, 2)], causal=True, heads=12,
                         kv_heads=3, dim=48, dim_head=4)
    x = jnp.asarray(rng.standard_normal((2, 31, 48)), jnp.float32)
    params = ref.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        hyb.apply(params, x), ref.apply(params, x), atol=ATOL
    )


# ----------------------------------------------------------------------
# Masks, packing, bidirectional streams, Pallas kernels
# ----------------------------------------------------------------------


def test_hybrid_kv_mask_tail(rng, meshes):
    """Non-causal with a key-padding mask whose tail is fully masked: the
    mask all-gathers over ulysses and rides the ring per hop."""
    hyb, ref = make_pair(meshes[(1, 2, 4)], causal=False)
    x = jnp.asarray(rng.standard_normal((2, 31, 32)), jnp.float32)
    mask = jnp.asarray(rng.random((2, 31)) > 0.3).at[:, -7:].set(False)
    params = ref.init(jax.random.PRNGKey(0), x, mask)
    np.testing.assert_allclose(
        hyb.apply(params, x, mask), ref.apply(params, x, mask), atol=ATOL
    )


@pytest.mark.parametrize("factoring", [(1, 2, 4), (1, 4, 2)],
                         ids=lambda f: "x".join(map(str, f)))
def test_hybrid_packed_segments(rng, meshes, factoring):
    """Packed segment ids: cross-document masking must survive the
    all-to-all resharding and the per-hop kv-id circulation."""
    hyb, ref = make_pair(meshes[factoring], causal=True, striped=True)
    x = jnp.asarray(rng.standard_normal((2, 31, 32)), jnp.float32)
    seg = jnp.asarray(np.sort(rng.integers(0, 4, (2, 31)), axis=1), jnp.int32)
    params = ref.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        hyb.apply(params, x, None, seg),
        ref.apply(params, x, None, seg),
        atol=ATOL,
    )


def test_hybrid_bidirectional(rng, meshes):
    """ring_bidirectional composes with the hybrid outer ring: the two KV
    half-streams circulate the sub-axis in opposite directions."""
    hyb, ref = make_pair(meshes[(1, 2, 4)], causal=True,
                         ring_bidirectional=True)
    x = jnp.asarray(rng.standard_normal((2, 32, 32)), jnp.float32)
    params = ref.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        hyb.apply(params, x), ref.apply(params, x), atol=ATOL
    )
    g_ref = jax.grad(lambda x: (ref.apply(params, x) ** 2).sum())(x)
    g_out = jax.grad(lambda x: (hyb.apply(params, x) ** 2).sum())(x)
    np.testing.assert_allclose(g_out, g_ref, atol=GRAD_ATOL)


@pytest.mark.parametrize("striped", [False, True])
def test_hybrid_lookback_window(rng, meshes, striped):
    """Sliding-window bands on the ring sub-axis: every offset (contiguous
    hop skip arithmetic AND the striped window floor) must derive from the
    OUTER axis size, not the global device count — exact in both layouts."""
    hyb, ref = make_pair(meshes[(1, 2, 4)], causal=True, striped=striped,
                         max_lookback_seq_len=7)
    x = jnp.asarray(rng.standard_normal((2, 32, 32)), jnp.float32)
    params = ref.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        hyb.apply(params, x), ref.apply(params, x), atol=ATOL
    )


@pytest.mark.slow
def test_hybrid_pallas(rng, meshes):
    """The Pallas per-hop kernels (interpret mode on CPU) under the hybrid
    composition."""
    hyb, ref = make_pair(meshes[(1, 2, 4)], causal=True, use_pallas=True)
    x = jnp.asarray(rng.standard_normal((2, 32, 32)), jnp.float32)
    params = ref.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        hyb.apply(params, x), ref.apply(params, x), atol=ATOL
    )


# ----------------------------------------------------------------------
# Functional core (no flax): direct shard_map over the factored mesh
# ----------------------------------------------------------------------


def test_hybrid_functional_core(rng, meshes):
    mesh = meshes[(1, 2, 4)]
    q = jnp.asarray(rng.standard_normal((2, 8, 64, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 8, 64, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 8, 64, 16)), jnp.float32)
    spec = P("data", None, ("ring", "ulysses"), None)
    out = shard_map(
        partial(
            hybrid_attention, kv_mask=None, ulysses_axis="ulysses",
            ring_axis="ring", causal=True, bucket_size=8,
        ),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
    )(q, k, v)
    ref = default_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=ATOL)


# ----------------------------------------------------------------------
# End-to-end transformer: loss + layout agreement (rotary, striping,
# packing, loss sharding all on the factored axis)
# ----------------------------------------------------------------------


@pytest.mark.slow
def test_hybrid_transformer_loss(rng, meshes):
    mesh = meshes[(2, 2, 2)]
    common = dict(num_tokens=64, dim=32, depth=2, heads=4, dim_head=8,
                  causal=True, striped=True, bucket_size=4)
    hyb = RingTransformer(mesh=mesh, sequence_parallel="hybrid", **common)
    ref = RingTransformer(use_ring=False, force_regular_attn=True, **common)
    tokens = jnp.asarray(rng.integers(0, 64, (2, 33)), jnp.int32)
    seg = jnp.asarray(np.sort(rng.integers(0, 3, (2, 33)), axis=1), jnp.int32)
    params = ref.init(jax.random.PRNGKey(0), tokens)

    loss_h = hyb.apply(params, tokens, return_loss=True, segment_ids=seg)
    loss_r = ref.apply(params, tokens, return_loss=True, segment_ids=seg)
    np.testing.assert_allclose(loss_h, loss_r, atol=ATOL)

    g_h = jax.grad(
        lambda p: hyb.apply(p, tokens, return_loss=True, segment_ids=seg)
    )(params)
    g_r = jax.grad(
        lambda p: ref.apply(p, tokens, return_loss=True, segment_ids=seg)
    )(params)
    for a, b in zip(jax.tree.leaves(g_h), jax.tree.leaves(g_r)):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL)


@pytest.mark.slow
def test_hybrid_transformer_chunked_ce(rng, meshes):
    """The chunked-CE path un-permutes the factored striped layout before
    scanning: loss must match the dense CE bit-for-bit in f32 math."""
    mesh = meshes[(1, 2, 4)]
    common = dict(num_tokens=64, dim=32, depth=1, heads=8, dim_head=4,
                  causal=True, striped=True, bucket_size=4)
    dense = RingTransformer(mesh=mesh, sequence_parallel="hybrid", **common)
    chunked = RingTransformer(mesh=mesh, sequence_parallel="hybrid",
                              loss_chunk_size=8, **common)
    tokens = jnp.asarray(rng.integers(0, 64, (2, 33)), jnp.int32)
    params = dense.init(jax.random.PRNGKey(0), tokens)
    np.testing.assert_allclose(
        chunked.apply(params, tokens, return_loss=True),
        dense.apply(params, tokens, return_loss=True),
        atol=ATOL,
    )


# ----------------------------------------------------------------------
# Mesh helpers + strategy/mesh validation
# ----------------------------------------------------------------------


def test_factored_mesh_helpers(meshes):
    mesh = meshes[(1, 2, 4)]
    assert seq_axes(mesh) == ("ring", "ulysses")
    assert seq_world(mesh) == 8
    plain = create_mesh(ring_size=8)
    assert seq_axes(plain) == ("seq",)
    assert seq_world(plain) == 8


def test_shard_batch_factored(meshes):
    """shard_batch places (b, n) arrays ring-major / ulysses-minor: device
    (u, r) must hold subchunk u of contiguous ring chunk r."""
    mesh = meshes[(1, 2, 4)]
    batch = np.arange(2 * 16, dtype=np.int32).reshape(2, 16)
    arr = shard_batch(batch, mesh)
    np.testing.assert_array_equal(np.asarray(arr), batch)
    for shard in arr.addressable_shards:
        d, r, u = np.argwhere(
            np.vectorize(lambda dev: dev == shard.device)(mesh.devices)
        )[0]
        chunk = (r * mesh.shape["ulysses"] + u) * 2
        np.testing.assert_array_equal(
            np.asarray(shard.data), batch[:, chunk:chunk + 2]
        )


def test_hybrid_requires_factored_mesh(rng, meshes):
    x = jnp.asarray(rng.standard_normal((2, 32, 32)), jnp.float32)
    bad = RingAttention(dim=32, heads=8, dim_head=8, causal=True,
                        use_ring=True, auto_shard=True,
                        mesh=create_mesh(ring_size=8),
                        sequence_parallel="hybrid")
    with pytest.raises(ValueError, match="factored mesh"):
        bad.init(jax.random.PRNGKey(0), x)
    bad = RingAttention(dim=32, heads=8, dim_head=8, causal=True,
                        use_ring=True, auto_shard=True,
                        mesh=meshes[(1, 2, 4)], sequence_parallel="ring")
    with pytest.raises(ValueError, match="plain"):
        bad.init(jax.random.PRNGKey(0), x)
    # transformer-level mismatch must surface the same actionable error,
    # not a bare KeyError from the striped-layout factor derivation
    bad_t = RingTransformer(num_tokens=64, dim=32, depth=1, heads=8,
                            dim_head=4, causal=True, striped=True,
                            mesh=create_mesh(ring_size=8),
                            sequence_parallel="hybrid")
    with pytest.raises(ValueError, match="factored mesh"):
        bad_t.init(jax.random.PRNGKey(0),
                   jnp.zeros((2, 32), jnp.int32))


# ----------------------------------------------------------------------
# The acceptance check: ring hops shrink by the ulysses degree and never
# cross the ulysses axis
# ----------------------------------------------------------------------


def test_hybrid_hlo_hop_count(rng, meshes):
    """Optimized-HLO pin of the tentpole claim: at equal world size (8),
    the hybrid step's ring collective-permutes (the unrolled Pallas hop
    loop makes each hop a separate instruction) number ``ring_size - 1``
    — ulysses_size x fewer than the pure ring's ``world - 1`` — and every
    source->target pair keeps the ulysses coordinate fixed (the ring rides
    ONLY the outer axis; the inner axis sees all-to-alls, not permutes).

    Expectations and the pair-axis rule both come from the shared contract
    checker (``analysis/contracts.py``): this pin holds the *module-level*
    (flax, auto_shard) HLO to the same table the functional-core suite and
    ``tools/check_contracts.py`` enforce, so they cannot drift apart."""
    from ring_attention_tpu.analysis import contracts

    ulysses = 2
    hyb, _ = make_pair(meshes[(1, 2, 4)], causal=True, use_pallas=True,
                       bucket_size=8)
    ring = RingAttention(
        dim=32, heads=8, dim_head=8, bucket_size=8, causal=True,
        use_ring=True, auto_shard=True, use_pallas=True,
        mesh=create_mesh(ring_size=8), sequence_parallel="ring",
    )
    x = jnp.asarray(rng.standard_normal((1, 64, 32)), jnp.float32)
    params = ring.init(jax.random.PRNGKey(0), x)

    def compiled(mod):
        return jax.jit(
            lambda p, x: mod.apply(p, x)
        ).lower(params, x).compile().as_text()

    hops_hybrid = contracts.hlo_ppermute_pairs(compiled(hyb))
    hops_ring = contracts.hlo_ppermute_pairs(compiled(ring))

    # hop-count expectations from the ONE declarative table
    hyb_dims = {"data": 1, "ring": 4, "ulysses": ulysses, "world": 8,
                "passes": 4}
    ring_dims = {"data": 1, "ring": 8, "ulysses": 1, "world": 8, "passes": 8}
    want_hybrid = contracts.expected_counts(
        "hybrid", "fwd", hyb_dims)["collective-permute"]
    want_ring = contracts.expected_counts(
        "ring", "fwd", ring_dims)["collective-permute"]
    assert len(hops_ring) == want_ring == 8 - 1, len(hops_ring)
    assert len(hops_hybrid) == want_hybrid == (8 // ulysses) - 1, (
        len(hops_hybrid)
    )
    assert len(hops_hybrid) * ulysses < len(hops_ring) + ulysses

    # ring permutes must keep every non-ring mesh coordinate fixed — the
    # checker's axis rule on the (data=1, ring=4, ulysses=2) mesh
    violations = contracts.check_pairs_axis(
        hops_hybrid, mesh_shape=(1, 4, 2), axis_index=1, axis_name="ring",
    )
    assert not violations, "\n".join(violations)
