"""Parity: zig-zag context parallelism vs the dense causal oracle.

JAX-native analogue of the reference's ``assert_zig_zag.py``: zig-zag
sharded attention over 8 devices must match regular causal attention on the
unpermuted sequence, for outputs and gradients, with rotary applied from
explicit zig-zag positions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from ring_attention_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from ring_attention_tpu.ops import apply_rotary, default_attention, rotary_freqs
from ring_attention_tpu.parallel import (
    create_mesh,
    zigzag_attention,
    zigzag_permute,
    zigzag_positions,
    zigzag_unpermute,
)

ATOL = 2e-5
GRAD_ATOL = 5e-4


@pytest.fixture(scope="module")
def mesh():
    return create_mesh(ring_size=8)


def zigzag_global(q, k, v, mesh, *, rotary=False, **kw):
    ring = mesh.shape["seq"]
    qz = zigzag_permute(q, ring, axis=2)
    kz = zigzag_permute(k, ring, axis=2)
    vz = zigzag_permute(v, ring, axis=2)

    def core(q, k, v):
        if rotary:
            rank = jax.lax.axis_index("seq")
            pos = zigzag_positions(q.shape[2], rank, ring)
            freqs = rotary_freqs(pos, q.shape[-1])
            q = apply_rotary(q, freqs)
            k = apply_rotary(k, freqs)
        return zigzag_attention(q, k, v, "seq", **kw)

    spec = P("data", None, "seq", None)
    out = shard_map(
        core, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(qz, kz, vz)
    return zigzag_unpermute(out, ring, axis=2)


def make_qkv(rng, b=2, h=4, hk=None, n=128, d=16):
    hk = hk or h
    q = jnp.asarray(rng.standard_normal((b, h, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    return q, k, v


def test_zigzag_parity(rng, mesh):
    q, k, v = make_qkv(rng)
    ref = default_attention(q, k, v, causal=True)
    out = zigzag_global(q, k, v, mesh)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_zigzag_kv_budget_warning(rng, mesh):
    """The O(n_global) gathered-KV profile warns when it exceeds the
    budget, and points at the ring scheme (VERDICT r3 weak #6)."""
    import warnings as w

    q, k, v = make_qkv(rng)
    with w.catch_warnings():
        w.simplefilter("error")  # default budget: must NOT warn at 128 tokens
        zigzag_global(q, k, v, mesh)
    with pytest.warns(UserWarning, match="sequence_parallel='ring'"):
        zigzag_global(q, k, v, mesh, gathered_kv_budget=1024)


def test_zigzag_gqa_bucketed(rng, mesh):
    q, k, v = make_qkv(rng, h=4, hk=2)
    ref = default_attention(q, k, v, causal=True)
    out = zigzag_global(q, k, v, mesh, bucket_size=16)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_zigzag_rotary(rng, mesh):
    """Rotary from explicit zig-zag positions matches global rotary
    (ref assert_zig_zag.py:106-110)."""
    q, k, v = make_qkv(rng)
    n = q.shape[2]
    freqs = rotary_freqs(jnp.arange(n), q.shape[-1])
    ref = default_attention(
        apply_rotary(q, freqs), apply_rotary(k, freqs), v, causal=True
    )
    out = zigzag_global(q, k, v, mesh, rotary=True)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_zigzag_grads(rng, mesh):
    """Gradients flow through all_gather's transpose (reduce-scatter),
    the analogue of AllGatherFunction.backward (ref distributed.py:103-107)."""
    q, k, v = make_qkv(rng)

    g_ref = jax.grad(
        lambda *a: (default_attention(*a, causal=True) ** 2).sum(), (0, 1, 2)
    )(q, k, v)
    g_out = jax.grad(lambda *a: (zigzag_global(*a, mesh) ** 2).sum(), (0, 1, 2))(
        q, k, v
    )
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


def test_zigzag_permute_roundtrip(rng):
    x = jnp.asarray(rng.standard_normal((2, 64, 4)), jnp.float32)
    y = zigzag_unpermute(zigzag_permute(x, 4), 4)
    np.testing.assert_array_equal(x, y)


def test_zigzag_positions_cover():
    """Every device's positions union to [0, n) without overlap."""
    ring, n_local = 4, 16
    all_pos = []
    for r in range(ring):
        all_pos.append(np.asarray(zigzag_positions(n_local, r, ring)))
    got = np.sort(np.concatenate(all_pos))
    np.testing.assert_array_equal(got, np.arange(ring * n_local))


def test_zigzag_pallas_impl(rng, mesh):
    """Pallas kernels inside zig-zag attention (interpret mode on CPU)."""
    q, k, v = make_qkv(rng)
    ref = default_attention(q, k, v, causal=True)

    def zz(q, k, v):
        return zigzag_attention(q, k, v, "seq", bucket_size=16, impl="pallas")

    ring = mesh.shape["seq"]
    qz, kz, vz = (zigzag_permute(x, ring, axis=2) for x in (q, k, v))
    spec = P("data", None, "seq", None)
    out = shard_map(zz, mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
                    check_vma=False)(qz, kz, vz)
    out = zigzag_unpermute(out, ring, axis=2)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_zigzag_pallas_grads(rng, mesh, monkeypatch):
    """Training with zigzag + pallas: the chunk attention is a custom_vjp
    over the Pallas backward kernels, so grads exist and match the oracle
    (previously pallas_call had no autodiff rule on this path)."""
    q, k, v = make_qkv(rng, h=4, hk=2)

    def zz_loss(q, k, v):
        def core(q, k, v):
            return zigzag_attention(q, k, v, "seq", bucket_size=16, impl="pallas")

        ring = mesh.shape["seq"]
        qz, kz, vz = (zigzag_permute(x, ring, axis=2) for x in (q, k, v))
        spec = P("data", None, "seq", None)
        out = shard_map(core, mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
                        check_vma=False)(qz, kz, vz)
        return (zigzag_unpermute(out, ring, axis=2) ** 2).sum()

    g_ref = jax.grad(
        lambda *a: (default_attention(*a, causal=True) ** 2).sum(), (0, 1, 2)
    )(q, k, v)
    g_out = jax.grad(zz_loss, (0, 1, 2))(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


def test_zigzag_odd_bucket(rng, mesh):
    """Global KV length not divisible by bucket_size: bucket auto-shrinks."""
    q, k, v = make_qkv(rng, n=80)  # 80 % 16 == 0 for 2*8 chunks; bucket 64 not a divisor
    ref = default_attention(q, k, v, causal=True)
    out = zigzag_global(q, k, v, mesh, bucket_size=64)
    np.testing.assert_allclose(out, ref, atol=ATOL)
