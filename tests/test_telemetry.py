"""Telemetry spine: in-graph metrics, JSONL logging, trace annotations,
MFU accounting, and the no-extra-collectives HLO pin.

The contract under test (ISSUE 4 / docs/observability.md): telemetry is
ADDITIVE — the instrumented train step computes its metrics from values
the step already produces, so the compiled program issues the same
collective sequence as the uninstrumented one, and every logged number is
either exact (loss, grad_norm, counters), measured (step latency), or
analytic-and-documented-as-such (MFU, hop/byte accounting).
"""

import os
import subprocess
import sys
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ring_attention_tpu.utils import (
    MetricsLogger,
    Telemetry,
    achieved_mfu,
    attention_logit_summaries,
    device_peak_tflops,
    flash_attention_flops,
    init_step_stats,
    init_train_metrics,
    make_train_step,
    read_metrics,
    ring_comms_accounting,
    transformer_step_flops,
)
from ring_attention_tpu.utils import resilience
from ring_attention_tpu.utils.profiling import StepTimer
from ring_attention_tpu.utils.telemetry import SCHEMA_VERSION, telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACE_REPORT = os.path.join(REPO, "tools", "trace_report.py")


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.drain_events()
    yield
    telemetry.drain_events()


def _quad_step(**kwargs):
    """Tiny quadratic problem: loss/grads are hand-checkable."""
    opt = optax.sgd(0.1)

    def loss_fn(p, x):
        return ((p["w"] * x) ** 2).mean()

    params = {"w": jnp.asarray([1.0, 2.0])}
    step = make_train_step(loss_fn, opt, collect_metrics=True, **kwargs)
    return step, params, opt.init(params), jnp.asarray([1.0, 1.0])


# ----------------------------------------------------------------------
# In-graph stats: parity under jit, donated and non-donated
# ----------------------------------------------------------------------


def test_train_metrics_parity_under_jit():
    step, params, opt_state, x = _quad_step(skip_nonfinite=True,
                                            clip_grad_norm=10.0)
    m0 = init_train_metrics()
    eager = step(params, opt_state, m0, x)
    jitted = jax.jit(step)(params, opt_state, m0, x)
    for a, b in zip(jax.tree.leaves(eager), jax.tree.leaves(jitted)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    _, _, m, loss = jitted
    # loss = mean((w*x)^2) = (1 + 4)/2; grad = 2*w*x^2/2 = w -> norm sqrt(5)
    assert float(loss) == pytest.approx(2.5)
    assert float(m.grad_norm) == pytest.approx(np.sqrt(5.0), rel=1e-6)
    assert bool(m.step_ok) and int(m.skipped) == 0 and int(m.nonfinite) == 0


def test_train_metrics_parity_donated():
    step, params, opt_state, x = _quad_step(skip_nonfinite=True,
                                            jit_donate=True)
    ref_step, p2, s2, _ = _quad_step(skip_nonfinite=True)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # CPU can't honor donation
        got = step(params, opt_state, init_train_metrics(), x)
    want = jax.jit(ref_step)(p2, s2, init_train_metrics(), x)
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_train_metrics_guarded_skip_counts():
    """Poisoned step under the guard: params bit-identical, skipped and
    nonfinite both count, loss still reports the offending value."""
    opt = optax.sgd(0.1)
    loss_fn = resilience.faulty_loss(
        lambda p, x: ((p["w"] * x) ** 2).mean()
    )
    step = jax.jit(make_train_step(
        loss_fn, opt, collect_metrics=True, skip_nonfinite=True
    ))
    params = {"w": jnp.asarray([1.0, 2.0])}
    opt_state = opt.init(params)
    x = jnp.ones((2,))
    m = init_train_metrics()
    params, opt_state, m, _ = step(params, opt_state, m, x)
    with resilience.inject("nan_loss"):
        p_after, opt_state, m, loss = step(params, opt_state, m, x)
    assert not bool(m.step_ok)
    assert int(m.skipped) == 1 and int(m.nonfinite) == 1
    assert np.isnan(float(loss))
    np.testing.assert_array_equal(
        np.asarray(p_after["w"]), np.asarray(params["w"])
    )
    # recovery: counters hold, step_ok returns
    p2, _, m, _ = step(p_after, opt_state, m, x)
    assert bool(m.step_ok) and int(m.skipped) == 1 and int(m.nonfinite) == 1
    assert not np.array_equal(np.asarray(p2["w"]), np.asarray(p_after["w"]))


def test_train_metrics_unguarded_counts_nonfinite():
    """Without the guard the update is applied anyway — but the nonfinite
    counter still fires: the 'run is corrupting itself' alarm."""
    opt = optax.sgd(0.1)
    loss_fn = resilience.faulty_loss(
        lambda p, x: ((p["w"] * x) ** 2).mean()
    )
    step = jax.jit(make_train_step(loss_fn, opt, collect_metrics=True))
    params = {"w": jnp.asarray([1.0, 2.0])}
    m = init_train_metrics()
    with resilience.inject("nan_loss"):
        params, _, m, _ = step(params, opt.init(params), m, jnp.ones((2,)))
    assert bool(m.step_ok)  # applied (no guard)
    assert int(m.skipped) == 0 and int(m.nonfinite) == 1


def test_init_train_metrics_resume_counters():
    m = init_train_metrics(skipped=7, nonfinite=9)
    assert int(m.skipped) == 7 and int(m.nonfinite) == 9


# ----------------------------------------------------------------------
# Telemetry registry: in-graph observation
# ----------------------------------------------------------------------


def test_telemetry_observe_inside_jit():
    tel = Telemetry()

    @jax.jit
    def fwd(x):
        with tel.collecting() as col:
            y = (x * 2).sum()
            tel.observe("y_sum", y)
            tel.observe("lazy", lambda: y + 1)  # thunk form
        return y, col.values()

    y, vals = fwd(jnp.ones((4,)))
    assert float(vals["y_sum"]) == 8.0 and float(vals["lazy"]) == 9.0


def test_telemetry_observe_noop_when_inactive():
    tel = Telemetry()
    calls = []
    tel.observe("x", lambda: calls.append(1))  # thunk must NOT run
    assert not calls and not tel.active()


# ----------------------------------------------------------------------
# MetricsLogger: schema round-trip, atomic append under a killed writer
# ----------------------------------------------------------------------


def test_metrics_logger_roundtrip(tmp_path):
    with MetricsLogger(str(tmp_path)) as logger:
        logger.log(0, loss=1.5, grad_norm=jnp.float32(2.0), step_ok=True)
        logger.log(5, loss=1.25, tokens_per_sec=100)
    rows = read_metrics(str(tmp_path))
    assert [r["step"] for r in rows] == [0, 5]
    assert all(r["schema"] == SCHEMA_VERSION for r in rows)
    assert rows[0]["loss"] == 1.5 and rows[0]["grad_norm"] == 2.0
    assert rows[0]["step_ok"] is True
    assert rows[1]["tokens_per_sec"] == 100


def test_metrics_logger_survives_killed_writer(tmp_path):
    """A writer killed mid-line leaves one torn final line; a new writer's
    appends land on a fresh line boundary is NOT guaranteed — what IS
    guaranteed is the reader skips garbage and keeps every whole row."""
    path = os.path.join(str(tmp_path), "metrics.jsonl")
    with MetricsLogger(str(tmp_path)) as logger:
        logger.log(0, loss=3.0)
    # simulate the kill: a torn, newline-terminated-nowhere partial row
    with open(path, "a") as f:
        f.write('{"schema": 1, "step": 1, "loss": 2.')
    rows = read_metrics(str(tmp_path))
    assert [r["step"] for r in rows] == [0]
    # a fresh writer appends after the torn line; its row must survive.
    # (the torn fragment corrupts at most ITSELF plus nothing — the new
    # row is written via one O_APPEND write that starts with a newline
    # only if we add one; instead verify the reader still sees both whole
    # rows once a newline separates them)
    with open(path, "a") as f:
        f.write("\n")
    with MetricsLogger(str(tmp_path)) as logger:
        logger.log(2, loss=1.0)
    rows = read_metrics(str(tmp_path))
    assert [r["step"] for r in rows] == [0, 2]


def test_metrics_logger_csv_export(tmp_path):
    csv_path = os.path.join(str(tmp_path), "metrics.csv")
    with MetricsLogger(str(tmp_path), csv_path=csv_path) as logger:
        logger.log(0, loss=2.0)
        logger.log(1, loss=1.0)
    lines = open(csv_path).read().strip().splitlines()
    assert len(lines) == 3 and "loss" in lines[0]


def test_degraded_kernel_lands_in_metrics_and_events(tmp_path):
    """The resilience satellite: a forced Pallas failure (the injection
    harness) must surface as a telemetry event AND a degraded=1 metric
    row — not only as a one-shot warning."""
    resilience.reset()
    telemetry.drain_events()
    try:
        with resilience.inject(resilience.PALLAS_FAULT):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                assert not resilience.pallas_available(refresh=True)
        events = telemetry.events()
        assert any(
            e["event"] == "degraded"
            and e["component"] == resilience.PALLAS_COMPONENT
            for e in events
        )
        with MetricsLogger(str(tmp_path)) as logger:
            logger.log(3, loss=1.0)
        rows = read_metrics(str(tmp_path))
        assert rows[0]["event"] == "degraded"  # the event row
        assert rows[1]["degraded"] == 1  # and the next metric row's flag
        assert rows[1]["step"] == 3
    finally:
        resilience.reset()
        telemetry.drain_events()


# ----------------------------------------------------------------------
# Trace annotations: stable names present in compiled HLO and in a
# jax.profiler trace captured on CPU
# ----------------------------------------------------------------------


def test_flash_scope_names_in_profiler_trace(tmp_path):
    """End-to-end: the names land in an actual xplane capture on CPU (the
    same artifact XProf reads on TPU)."""
    from ring_attention_tpu.ops.flash import flash_attention

    q = jnp.ones((1, 2, 64, 8), jnp.float32)
    f = jax.jit(lambda q: flash_attention(q, q, q, causal=True,
                                          bucket_size=32))
    jax.block_until_ready(f(q))  # compile outside the trace
    with jax.profiler.trace(str(tmp_path)):
        jax.block_until_ready(f(q))
    blobs = []
    for root, _, files in os.walk(str(tmp_path)):
        for name in files:
            if name.endswith(".xplane.pb"):
                blobs.append(open(os.path.join(root, name), "rb").read())
    assert blobs, "profiler produced no xplane capture"
    assert any(b"flash/fwd" in blob for blob in blobs)
    # and the observatory's stdlib parser resolves the same capture into
    # a stage timeline (the per-hop/ring assertions live in
    # tests/test_observatory.py; this pins the single-device join)
    from ring_attention_tpu.utils.profiling import (
        read_xplane_events,
        stage_timeline,
    )

    events, note = read_xplane_events(str(tmp_path))
    assert events, f"stdlib xplane parser found no events: {note}"
    rows = stage_timeline(events)["stages"]
    flash = [r for r in rows if r["stage"] == "flash forward kernel"]
    assert flash and flash[0]["busy_ms"] > 0


def test_ring_scope_names_in_compiled_hlo(rng, devices):
    """Compiled-HLO metadata carries the ring's stable scope names (this
    metadata is exactly what XProf displays as the op name)."""
    from ring_attention_tpu.models.attention import RingAttention
    from ring_attention_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(ring_size=4)
    att = RingAttention(dim=32, heads=4, dim_head=8, bucket_size=8,
                        causal=True, use_ring=True, auto_shard=True,
                        mesh=mesh)
    x = jnp.asarray(rng.standard_normal((2, 64, 32)), jnp.float32)
    params = att.init(jax.random.PRNGKey(0), x)
    txt = jax.jit(
        lambda p, x: att.apply(p, x)
    ).lower(params, x).compile().as_text()
    for name in ("ring/hop", "ring/rotate"):
        assert name in txt, f"scope {name!r} missing from compiled HLO"


def test_backward_scope_names_in_compiled_hlo():
    from ring_attention_tpu.ops.flash import flash_attention

    q = jnp.ones((1, 2, 64, 8), jnp.float32)
    txt = jax.jit(jax.grad(
        lambda q: flash_attention(q, q, q, causal=True,
                                  bucket_size=32).sum()
    )).lower(q).compile().as_text()
    assert "flash/bwd" in txt


# ----------------------------------------------------------------------
# MFU formulas pinned against hand counts
# ----------------------------------------------------------------------


def test_flash_flops_pinned_hand_count():
    """One (seq, heads, dim) point counted by hand: seq 1024, 8 heads,
    d=64, causal.  qk^T is 1024*1024*64 MACs = 2*1024^2*64 FLOPs per
    head; pv the same; causal halves; 8 heads:
    2 matmuls * 2 * 1024^2 * 64 * 8 * 0.5 = 1_073_741_824."""
    got = flash_attention_flops(1024, heads=8, dim_head=64, causal=True)
    assert got == 2 * 2 * 1024 * 1024 * 8 * 64 * 0.5 == 1_073_741_824.0
    # backward = 7 matmuls (score recompute + dv, dp, dq, dk): 3.5x fwd
    bwd = flash_attention_flops(1024, heads=8, dim_head=64, causal=True,
                                backward=True)
    assert bwd == got * 3.5
    # non-causal doubles; cross-lengths multiply
    assert flash_attention_flops(1024, heads=8, dim_head=64) == 2 * got
    assert flash_attention_flops(
        512, 2048, heads=8, dim_head=64
    ) == 2 * 2 * 512 * 2048 * 8 * 64


def test_transformer_step_flops_and_mfu():
    dense_only = transformer_step_flops(
        1000, 4096, depth=0, heads=8, dim_head=64, seq_len=4096
    )
    assert dense_only == 6.0 * 1000 * 4096
    full = transformer_step_flops(
        1000, 4096, depth=2, heads=8, dim_head=64, seq_len=4096
    )
    assert full == dense_only + 2 * flash_attention_flops(
        4096, heads=8, dim_head=64, causal=True, backward=True
    )
    # a step achieving exactly peak is MFU 1.0
    assert achieved_mfu(197e12 * 0.5, 0.5, 197.0) == pytest.approx(1.0)
    assert achieved_mfu(1.0, 0.0, 197.0) == 0.0
    assert device_peak_tflops() > 0  # CPU falls back to the v5e figure


def test_ring_comms_accounting_hybrid_factoring():
    """The PR 3 claim as numbers: at equal world 8, the 2x4 hybrid
    factoring cuts latency-chain hops from 7 to 3 and circulates the
    kv-head subset of the ring chunk per hop."""
    pure = ring_comms_accounting(
        ring_size=8, seq_len=8192, kv_heads=8, dim_head=64, depth=2
    )
    hybrid = ring_comms_accounting(
        ring_size=4, ulysses_size=2, seq_len=8192, kv_heads=8,
        dim_head=64, heads=8, depth=2
    )
    assert pure["ring_hops"] == 7 and pure["pure_ring_hops"] == 7
    assert hybrid["ring_hops"] == 3 and hybrid["pure_ring_hops"] == 7
    # hop payload: 2 (k+v) * kv_heads_local * chunk * d * 2 bytes
    assert pure["hop_bytes"] == 2 * 8 * (8192 // 8) * 64 * 2
    assert hybrid["hop_bytes"] == 2 * 4 * (8192 // 4) * 64 * 2
    assert 0.0 < hybrid["hop_overlap_fraction"] <= 1.0
    # limited passes shrink the chain; indivisible seq is a loud error
    limited = ring_comms_accounting(
        ring_size=8, seq_len=8192, kv_heads=8, dim_head=64, passes=2
    )
    assert limited["ring_hops"] == 1
    with pytest.raises(ValueError, match="divide"):
        ring_comms_accounting(
            ring_size=3, seq_len=8192, kv_heads=8, dim_head=64
        )


def test_ring_comms_accounting_compression_and_counter():
    """PR 6 terms as numbers.  int8 hop compression: bytes/hop shrink
    dtype_bytes * d / (d + 4)-fold — ~3.8x from f32 at d=64 (the "~4x"
    acceptance pin), hop COUNTS untouched, backward bytes untouched (the
    compressed forward payload never enters the backward ring).  Counter-
    rotation: one extra forward collective (the out/lse catch-up), the
    backward's resident-KV schedule repays it, and the busier forward
    link direction carries about half the baseline's rotation traffic."""
    base = ring_comms_accounting(
        ring_size=8, seq_len=8192, kv_heads=8, dim_head=64, dtype_bytes=4
    )
    comp = ring_comms_accounting(
        ring_size=8, seq_len=8192, kv_heads=8, dim_head=64, dtype_bytes=4,
        hop_compression="int8",
    )
    # per-hop payload: values 1 byte + 4 bitcast f32 scale bytes per row
    assert comp["hop_bytes"] == 2 * 8 * (8192 // 8) * (64 + 4)
    ratio = base["hop_bytes"] / comp["hop_bytes"]
    assert ratio == pytest.approx(4 * 64 / (64 + 4))  # ~3.76x from f32
    assert 3.5 < ratio < 4.0
    assert comp["ring_hops"] == base["ring_hops"]
    assert comp["fwd_collectives"] == base["fwd_collectives"]
    # backward recirculates exact (k, v) + f32 (dk, dv): unchanged
    assert (comp["ring_bytes_per_step_bwd"]
            == base["ring_bytes_per_step_bwd"])

    ctr = ring_comms_accounting(
        ring_size=8, seq_len=8192, kv_heads=8, dim_head=64, dtype_bytes=4,
        counter_rotate=True,
    )
    assert ctr["counter_rotate"] is True
    # fwd: 7 rotations + the out/lse catch-up; baseline: 7
    assert ctr["fwd_collectives"] == 8 and base["fwd_collectives"] == 7
    # bwd: the q-side pack's 8 collectives vs the baseline's 2*8 - 1
    assert ctr["bwd_collectives"] == 8 and base["bwd_collectives"] == 15
    assert (ctr["fwd_collectives"] + ctr["bwd_collectives"]
            < base["fwd_collectives"] + base["bwd_collectives"])
    # full-duplex split: the busier direction carries well under the
    # baseline's single-direction total
    assert ctr["fwd_link_direction_bytes"] < base["fwd_link_direction_bytes"]
    assert ctr["q_pack_bytes"] == 4 * 1 * 8 * (8192 // 8) * (2 * 64 + 2)
    with pytest.raises(ValueError, match="hop_compression"):
        ring_comms_accounting(
            ring_size=8, seq_len=8192, kv_heads=8, dim_head=64,
            hop_compression="fp4",
        )


def test_ring_comms_accounting_compute_dtype():
    """PR 13 terms as numbers.  compute_dtype="int8": the matmul FEED
    shrinks to 1 byte/element (q + k + v per hop), the f32 (acc, m, l)
    accumulator bytes are INVARIANT (the precision auditor's contract as
    a pinned number), the wire terms are untouched (quantized matmuls
    change what the kernels read, never what the ring moves), and the
    overlap model's compute leg runs at the 2x int8 MXU rate — less
    compute time available to hide the same transfer."""
    kw = dict(ring_size=8, seq_len=8192, kv_heads=8, dim_head=64,
              dtype_bytes=2)
    bf16 = ring_comms_accounting(**kw)
    q8 = ring_comms_accounting(compute_dtype="int8", **kw)
    n_chunk = 8192 // 8
    # feed: q (8 heads) + k + v (8 kv heads) rows of the held chunk
    assert q8["matmul_operand_bytes"] == 3 * 8 * n_chunk * 64
    assert bf16["matmul_operand_bytes"] == 2 * 3 * 8 * n_chunk * 64
    # the f32 (acc, m, l) state: (d + 2) f32 per (head, token), invariant
    expected_acc = 4 * 8 * n_chunk * (64 + 2)
    assert q8["accumulator_bytes"] == expected_acc
    assert bf16["accumulator_bytes"] == expected_acc
    # wire terms untouched
    for key in ("hop_bytes", "fwd_collectives", "bwd_collectives",
                "ring_bytes_per_step", "ring_bytes_per_step_bwd"):
        assert q8[key] == bf16[key], key
    # int8 compute finishes in half the time -> overlap can only drop
    assert q8["hop_overlap_fraction"] <= bf16["hop_overlap_fraction"]
    assert q8["compute_dtype"] == "int8" and bf16["compute_dtype"] is None
    with pytest.raises(ValueError, match="compute_dtype"):
        ring_comms_accounting(compute_dtype="fp8", **kw)


def test_ring_comms_accounting_fused():
    """PR 18 terms as numbers.  ``impl="fused"``: the whole hop schedule
    rides ONE kernel launch, so the launch count drops from ``passes`` to
    1, the per-hop dispatch-overhead term vanishes, and the forward
    issues ZERO XLA collectives (hops are in-kernel remote DMAs — the
    ``fused_ring`` contract row pins the count from the lowered module).
    Analytic HOPS and bytes are EQUAL to the scan path — the fused ring
    moves the same KV the same number of times; what it deletes is the
    launch boundary."""
    kw = dict(ring_size=8, seq_len=8192, kv_heads=8, dim_head=64,
              dtype_bytes=2)
    scan = ring_comms_accounting(**kw)
    fused = ring_comms_accounting(impl="fused", **kw)
    assert scan["impl"] == "scan" and fused["impl"] == "fused"
    # the launch model: one launch, no per-hop dispatch overhead
    assert scan["kernel_launches"] == 8
    assert fused["kernel_launches"] == 1
    assert scan["dispatch_overhead_s"] > 0.0
    assert fused["dispatch_overhead_s"] == 0.0
    # hops are in-kernel remote DMAs, not XLA collectives
    assert scan["fwd_collectives"] == 7
    assert fused["fwd_collectives"] == 0
    # the backward retains the scan-path schedule
    assert fused["bwd_collectives"] == scan["bwd_collectives"]
    # analytic hop/byte accounting is IDENTICAL — same KV, same moves
    for key in ("ring_hops", "hop_bytes", "ring_bytes_per_step",
                "ring_bytes_per_step_bwd"):
        assert fused[key] == scan[key], key
    # removing the exposed dispatch term can only improve overlap
    assert fused["hop_overlap_fraction"] >= scan["hop_overlap_fraction"]
    # limited passes: the scan path pays one launch per pass, fused one
    limited = ring_comms_accounting(passes=3, **kw)
    assert limited["kernel_launches"] == 3
    assert ring_comms_accounting(
        passes=3, impl="fused", **kw
    )["kernel_launches"] == 1
    with pytest.raises(ValueError, match="impl"):
        ring_comms_accounting(impl="triton", **kw)
    # counter-rotation has no fused form (parallel/ring.py raises on the
    # same combination): the analytic model refuses it too
    with pytest.raises(ValueError, match="counter_rotate"):
        ring_comms_accounting(impl="fused", counter_rotate=True, **kw)


def test_ring_comms_accounting_fused_north_star():
    """The acceptance number: at the 262k north-star shape the fused
    ring's measured-vs-analytic overlap target is ~1.0 — with the
    dispatch term gone, per-hop compute fully hides the transfer."""
    fused = ring_comms_accounting(
        ring_size=8, seq_len=262144, kv_heads=8, dim_head=64,
        dtype_bytes=2, impl="fused",
    )
    assert fused["hop_overlap_fraction"] == pytest.approx(1.0)
    assert fused["kernel_launches"] == 1
    assert fused["fwd_collectives"] == 0


def test_train_memory_estimate_compute_dtype():
    """train_memory_estimate's int8 keys: operand bytes quarter from f32
    (halve from bf16), accumulator bytes invariant, peak untouched (the
    FFN/CE transients dominate every modeled shape)."""
    from ring_attention_tpu.utils.telemetry import train_memory_estimate

    kw = dict(seq_len=4096, dim=256, depth=2, heads=4, vocab=256,
              n_params=1_000_000, dtype_bytes=2)
    bf16 = train_memory_estimate(**kw)
    q8 = train_memory_estimate(compute_dtype="int8", **kw)
    assert bf16["attn_operand_bytes"] == 3 * 4096 * 256 * 2
    assert q8["attn_operand_bytes"] == 3 * 4096 * 256
    expected_acc = 4096 * (256 + 2 * 4) * 4
    assert q8["attn_accumulator_bytes"] == expected_acc
    assert bf16["attn_accumulator_bytes"] == expected_acc
    assert q8["peak_hbm_bytes"] == bf16["peak_hbm_bytes"]


def test_attention_logit_summaries_match_dense_oracle(rng):
    q = jnp.asarray(rng.standard_normal((1, 2, 32, 8)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 2, 32, 8)), jnp.float32)
    got = attention_logit_summaries(q, k, causal=True, bucket_size=8)
    s = np.einsum("bhid,bhjd->bhij", np.asarray(q), np.asarray(k)) * 8**-0.5
    s = np.where(np.tril(np.ones((32, 32), bool)), s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ent = -np.where(p > 0, p * np.log(np.maximum(p, 1e-30)), 0.0).sum(-1)
    assert float(got["max_logit"]) == pytest.approx(
        s.max(), rel=1e-5
    )
    assert float(got["softmax_entropy"]) == pytest.approx(
        ent.mean(), rel=1e-5
    )
    assert float(got["softmax_entropy_min"]) == pytest.approx(
        ent.min(), abs=1e-5
    )


# ----------------------------------------------------------------------
# StepTimer hardening
# ----------------------------------------------------------------------


def test_steptimer_percentiles(monkeypatch):
    t = {"now": 0.0}
    monkeypatch.setattr(
        "ring_attention_tpu.utils.profiling.time.perf_counter",
        lambda: t["now"],
    )
    timer = StepTimer(tokens_per_step=10)
    deltas = [0.1, 0.1, 0.1, 0.1, 0.5]  # one straggler step
    timer.step()
    for d in deltas:
        t["now"] += d
        timer.step()
    assert timer.step_ms_p50 == pytest.approx(100.0)
    assert timer.step_ms_p95 > 300.0  # the tail sees the straggler
    assert timer.steps_per_sec == pytest.approx(len(deltas) / sum(deltas))
    assert timer.tokens_per_sec == pytest.approx(
        10 * len(deltas) / sum(deltas)
    )


def test_steptimer_monotonic_guard(monkeypatch):
    t = {"now": 100.0}
    monkeypatch.setattr(
        "ring_attention_tpu.utils.profiling.time.perf_counter",
        lambda: t["now"],
    )
    timer = StepTimer(tokens_per_step=10)
    timer.step()
    t["now"] = 99.0  # clock went backwards
    timer.step()
    assert timer.clock_anomalies == 1
    assert timer.steps_per_sec == 0.0  # window reset, not a negative rate
    t["now"] = 100.0
    timer.step()
    assert timer.steps_per_sec > 0


def test_steptimer_warns_once_without_tokens():
    timer = StepTimer()  # tokens_per_step unset
    with pytest.warns(UserWarning, match="tokens_per_step is unset"):
        timer.step(jnp.float32(1.0))
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # second call must NOT warn again
        timer.step(jnp.float32(1.0))
    assert timer.tokens_per_sec == 0.0


# ----------------------------------------------------------------------
# The acceptance HLO pin: instrumentation adds no collectives
# ----------------------------------------------------------------------


@pytest.mark.parametrize("guarded", [True, False],
                         ids=["guarded", "unguarded"])
def test_metrics_add_no_collectives(rng, devices, guarded):
    """The instrumented train step must issue the SAME collective sequence
    as the uninstrumented one — telemetry derives every metric from values
    the step already computes.  (The unguarded baseline is compared with
    clipping on, which already computes the global grad norm the metrics
    reuse.)  The collective signature comes from the shared contract
    checker (``analysis/contracts.py::hlo_collective_sequence``) so this
    pin and the per-strategy contracts can never disagree on what counts
    as a collective."""
    from ring_attention_tpu import RingTransformer, create_mesh
    from ring_attention_tpu.analysis.contracts import hlo_collective_sequence

    mesh = create_mesh(ring_size=4)
    model = RingTransformer(
        num_tokens=64, dim=32, depth=1, heads=4, dim_head=8, causal=True,
        striped=True, bucket_size=8, mesh=mesh, use_ring=True,
    )
    toks = jnp.asarray(
        rng.integers(0, 64, (2, 64)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), toks, return_loss=True)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    def loss_fn(p, t):
        return model.apply(p, t, return_loss=True)

    kw = dict(skip_nonfinite=guarded, clip_grad_norm=1.0)
    base = make_train_step(loss_fn, opt, **kw)
    inst = make_train_step(loss_fn, opt, collect_metrics=True, **kw)
    base_args = (
        (params, opt_state, init_step_stats(), toks)
        if guarded else (params, opt_state, toks)
    )
    inst_args = (params, opt_state, init_train_metrics(), toks)

    txt_base = jax.jit(base).lower(*base_args).compile().as_text()
    txt_inst = jax.jit(inst).lower(*inst_args).compile().as_text()
    seq_base = hlo_collective_sequence(txt_base)
    seq_inst = hlo_collective_sequence(txt_inst)
    assert seq_base, "expected ring collectives in the train step"
    if guarded:
        # signatures match (StepStats vs TrainMetrics carry): the compiled
        # programs must issue the identical collective SEQUENCE
        assert seq_inst == seq_base
    else:
        # the extra metric outputs shift XLA's scheduling of independent
        # collectives; the pin here is that the SET is unchanged — no
        # collective was added by instrumentation
        from collections import Counter

        assert Counter(seq_inst) == Counter(seq_base)


# ----------------------------------------------------------------------
# trace_report.py golden output
# ----------------------------------------------------------------------

_GOLDEN_ROWS = """\
{"schema": 1, "step": 0, "time": 1.0, "loss": 4.0, "grad_norm": 2.0, "tokens_per_sec": 100.0, "mfu": 0.25, "ring_hops": 3, "skipped": 0}
{"schema": 1, "event": "degraded", "component": "pallas_flash", "reason": "boom", "time": 2.0}
{"schema": 1, "step": 5, "time": 3.0, "loss": 2.0, "grad_norm": 1.0, "tokens_per_sec": 200.0, "mfu": 0.35, "ring_hops": 3, "skipped": 1, "degraded": 1}
{"schema": 1, "step": 10, "loss": 1.\
"""

_GOLDEN_OUT = """\
rows: 2 metric + 1 event | steps 0..5 | schema 1
  event: degraded pallas_flash
  DEGRADED run: 1 kernel-fallback event(s) — see ring_attention_tpu.utils.resilience.degradation

comms accounting (analytic, per device)
  ring_hops                3

  metric                       last         mean          p50          p95
  loss                            2            3            3          3.9
  grad_norm                       1          1.5          1.5         1.95
  tokens_per_sec                200          150          150          195
  mfu                          0.35          0.3          0.3        0.345
  degraded                        1            1            1            1
  skipped                         1          0.5          0.5         0.95
"""


def test_trace_report_golden_output(tmp_path):
    """Pinned end-to-end output: schema summary, event surfacing, the
    degraded banner, accounting echo, percentile table — and the torn
    final line (a killed writer) silently skipped."""
    path = os.path.join(str(tmp_path), "metrics.jsonl")
    with open(path, "w") as f:
        f.write(_GOLDEN_ROWS)
    proc = subprocess.run(
        [sys.executable, TRACE_REPORT, str(tmp_path)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    got = proc.stdout.splitlines()
    # first line echoes the (tmp) path; compare everything after it
    assert got[0].startswith("trace report: ")
    assert "\n".join(got[1:]) + "\n" == _GOLDEN_OUT


def test_trace_report_missing_xprof_is_note_not_error(tmp_path):
    path = os.path.join(str(tmp_path), "metrics.jsonl")
    with open(path, "w") as f:
        f.write('{"schema": 1, "step": 0, "loss": 1.0}\n')
    proc = subprocess.run(
        [sys.executable, TRACE_REPORT, str(tmp_path),
         "--xprof", os.path.join(str(tmp_path), "nope")],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "loss" in proc.stdout


# ----------------------------------------------------------------------
# examples/train.py --metrics-dir end to end (the acceptance command)
# ----------------------------------------------------------------------


@pytest.mark.slow
def test_train_example_writes_schema_valid_metrics(tmp_path):
    mdir = os.path.join(str(tmp_path), "m")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "train.py"),
         "--fake-devices", "4", "--steps", "6", "--seq-len", "128",
         "--metrics-dir", mdir, "--log-every", "2", "--skip-nonfinite"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = [r for r in read_metrics(mdir) if "event" not in r]
    assert rows, "no metric rows written"
    for field in ("loss", "grad_norm", "tokens_per_sec", "mfu",
                  "ring_hops", "skipped", "nonfinite", "step_ms_p95"):
        assert field in rows[-1], f"missing {field}: {sorted(rows[-1])}"
    assert rows[-1]["schema"] == SCHEMA_VERSION
    assert rows[-1]["ring_hops"] == 3  # 4-device ring: 3 hops
    # and the report tool renders it
    proc = subprocess.run(
        [sys.executable, TRACE_REPORT, mdir],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "tokens_per_sec" in proc.stdout
