"""Test configuration: simulate an 8-device TPU mesh on host CPU.

The reference fakes a cluster with ``mp.spawn`` + gloo (``assert.py:13-25``);
the JAX-native equivalent is a single process with
``--xla_force_host_platform_device_count=N`` so every ``Mesh``/``shard_map``
test runs the exact code that runs on a real TPU slice.
"""

import os

# Must run before jax initializes its backends (conftest imports first).
# NOTE: this image pre-imports jax via sitecustomize, so JAX_PLATFORMS in
# os.environ is already baked; jax.config.update still works pre-backend-init.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)
jax.config.update("jax_default_matmul_precision", "highest")

# The suite is compile-dominated (tiny shapes, one host CPU, every parity
# test jits a fresh shard_map transformer); a persistent on-disk cache cuts
# repeat-run wall time without touching coverage (VERDICT r1 weak #6).
_CACHE_DIR = os.path.join(os.path.dirname(__file__), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
# near-zero threshold: this suite's executables are mostly tiny (sub-0.5s
# XLA compiles) — the default threshold would keep almost all of them out
# of the disk cache, forfeiting the win
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.05)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
