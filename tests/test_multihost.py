"""Multi-process cluster: the multi-host path actually runs.

The reference proves its distributed layer with ``mp.spawn`` + gloo
(``assert.py:13-25``); the analogue here is two OS processes joining one
jax cluster through ``initialize_multihost`` (the jax.distributed runtime
— same code path a multi-host TPU pod uses, with processes standing in
for hosts).  This is the only place ``shard_batch``'s
``make_array_from_process_local_data`` branch and cross-process
collectives execute for real — the 8-virtual-device conftest mesh is
always a single process.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_cluster_trains():
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers set their own device count
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO_ROOT,
        )
        for pid in range(2)
    ]
    # outputs keyed by worker index so a partial timeout can't misattribute
    # one worker's log to another (ADVICE r3)
    outs: dict[int, str] = {}
    try:
        for pid, p in enumerate(procs):
            out, _ = p.communicate(timeout=420)
            outs[pid] = out
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        # reap and collect the stuck workers' partial output — that is
        # the log that explains the hang
        for pid, p in enumerate(procs):
            if pid not in outs:
                try:
                    out, _ = p.communicate(timeout=10)
                    outs[pid] = out
                except Exception:
                    pass
        pytest.fail(
            "multihost workers timed out\n"
            + "\n".join(f"--- worker {pid} ---\n{out}"
                        for pid, out in sorted(outs.items()))
        )
    for pid, p in enumerate(procs):
        out = outs[pid]
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-2000:]}"
        assert f"MULTIHOST-OK {pid}" in out, out[-2000:]
    # both processes computed the SAME replicated loss
    losses = {ln.split("loss=")[1]
              for out in outs.values() for ln in out.splitlines()
              if "MULTIHOST-OK" in ln}
    assert len(losses) == 1, losses
