"""Multi-process cluster: the multi-host path actually runs.

The reference proves its distributed layer with ``mp.spawn`` + gloo
(``assert.py:13-25``); the analogue here is two OS processes joining one
jax cluster through ``initialize_multihost`` (the jax.distributed runtime
— same code path a multi-host TPU pod uses, with processes standing in
for hosts).  This is the only place ``shard_batch``'s
``make_array_from_process_local_data`` branch and cross-process
collectives execute for real — the 8-virtual-device conftest mesh is
always a single process.

The elastic half (PR 15) drives the POD-SCALE acceptance matrix on the
same harness: kill ONE worker of a live two-process cluster at every
commit window of the multi-process checkpoint protocol (real
``os._exit`` deaths via ``chaos_point``), restart at the surviving
process count through ``remesh_plan``, and finish with loss parity
against the uninterrupted baseline; plus cross-process drain (SIGTERM on
one host drains the whole cluster), 2 -> 1 / 1 -> 2 checkpoint
round-trip bit-exactness, and the watchdog-vs-wedged-collective pin.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from ring_attention_tpu.elastic import (
    WATCHDOG_EXIT_CODE,
    ElasticCheckpointManager,
    chaos,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "multihost_worker.py")
ELASTIC_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "elastic_worker.py")

# cross-world loss-parity tolerance (same rule as tests/test_elastic.py:
# params restore bit-exactly, only reduction order differs)
TOL = 1e-4


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_cluster_trains():
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers set their own device count
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO_ROOT,
        )
        for pid in range(2)
    ]
    # outputs keyed by worker index so a partial timeout can't misattribute
    # one worker's log to another (ADVICE r3)
    outs: dict[int, str] = {}
    try:
        for pid, p in enumerate(procs):
            out, _ = p.communicate(timeout=420)
            outs[pid] = out
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        # reap and collect the stuck workers' partial output — that is
        # the log that explains the hang
        for pid, p in enumerate(procs):
            if pid not in outs:
                try:
                    out, _ = p.communicate(timeout=10)
                    outs[pid] = out
                except Exception:
                    pass
        pytest.fail(
            "multihost workers timed out\n"
            + "\n".join(f"--- worker {pid} ---\n{out}"
                        for pid, out in sorted(outs.items()))
        )
    for pid, p in enumerate(procs):
        out = outs[pid]
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-2000:]}"
        assert f"MULTIHOST-OK {pid}" in out, out[-2000:]
    # both processes computed the SAME replicated loss
    losses = {ln.split("loss=")[1]
              for out in outs.values() for ln in out.splitlines()
              if "MULTIHOST-OK" in ln}
    assert len(losses) == 1, losses


# ----------------------------------------------------------------------
# Elastic runtime at pod scale (PR 15): kill-one-worker chaos matrix,
# cross-process drain, round-trip bit-exactness, watchdog-vs-wedge
# ----------------------------------------------------------------------


def _worker_argv(ckpt_dir, loss_log, *, steps=6, sync=True,
                 barrier=20, watchdog=None, flight=None):
    argv = [sys.executable, ELASTIC_WORKER,
            "--ckpt-dir", str(ckpt_dir), "--loss-log", str(loss_log),
            "--steps", str(steps), "--barrier-timeout", str(barrier)]
    if sync:
        argv.append("--sync-save")
    if watchdog is not None:
        argv += ["--watchdog-deadline", str(watchdog)]
    if flight is not None:
        argv += ["--flight-dir", str(flight)]
    return argv


def _read_log(path) -> dict[int, float]:
    out: dict[int, float] = {}
    try:
        with open(path) as f:
            for line in f:
                if line.strip():
                    row = json.loads(line)
                    out[row["step"]] = row["loss"]
    except FileNotFoundError:
        pass
    return out


def _cluster(ckpt_dir, loss_log, *, chaos_faults=None, chaos_process=0,
             steps=6, watchdog=25, timeout=360):
    w = chaos.ChaosWorker(
        _worker_argv(ckpt_dir, loss_log, steps=steps, watchdog=watchdog),
        cwd=REPO_ROOT, timeout=timeout,
    )
    return w.run_cluster(processes=2, devices_per_process=2,
                         chaos=chaos_faults, chaos_process=chaos_process)


def _committed(ckpt_dir) -> list[int]:
    return ElasticCheckpointManager(ckpt_dir).all_steps()


@pytest.fixture(scope="module")
def baseline4(tmp_path_factory):
    """Uninterrupted 6-step single-process run at world 4 — the parity
    reference every cluster/remesh trajectory must reproduce."""
    d = tmp_path_factory.mktemp("mh_baseline")
    log = d / "loss.jsonl"
    w = chaos.ChaosWorker(
        _worker_argv(d / "ck", log, sync=False), cwd=REPO_ROOT,
        timeout=300,
    )
    r = w.run(devices=4)
    assert r.returncode == 0, r.stdout + r.stderr
    losses = _read_log(log)
    assert sorted(losses) == list(range(6)), losses
    return losses


@pytest.mark.slow
def test_cluster_kill_one_worker_matrix_then_remesh(tmp_path, baseline4):
    """The pod-scale kill-anywhere matrix: one checkpoint directory
    survives a violent death of ONE worker of a live two-process cluster
    at every commit window — mid-step, mid-shard-write (victim writes
    its own shard group), staged-but-uncommitted (process 0 dies before
    the manifest rename), mid-resume — with the SURVIVOR bounded by the
    barrier timeout / watchdog (never an eternal hang), and the final
    single-process restart at the surviving device count reproduces the
    uninterrupted baseline's loss trajectory."""
    ck, log = tmp_path / "ck", tmp_path / "loss.jsonl"

    # (1) victim worker 1 dies mid-run at step 2, after step 0 committed;
    # worker 0's next collective loses its peer — the bounded outcomes
    # are the watchdog abort (exit 114) or the transport erroring out,
    # NEVER success and never a hang past the harness timeout
    rs = _cluster(ck, log, chaos_faults={chaos.KILL_AT_STEP: 2},
                  chaos_process=1)
    assert rs[1].returncode == chaos.CHAOS_EXIT_CODE, rs[1].stdout
    assert rs[0].returncode != 0, "survivor must not report success"
    assert _committed(ck) == [0]

    # (2) victim worker 1 dies MID-SHARD-WRITE of its own shard group:
    # no manifest can exist (process 0 commits last, behind the barrier
    # the victim never reaches) — the torn save is invisible
    rs = _cluster(ck, log, chaos_faults=[chaos.KILL_MID_SHARD],
                  chaos_process=1)
    assert rs[1].returncode == chaos.CHAOS_EXIT_CODE, rs[1].stdout
    assert rs[0].returncode != 0, "survivor must not report success"
    assert _committed(ck) == [0], (
        "a torn multi-process save leaked into the committed steps"
    )

    # (3) process 0 dies with the staging dir COMPLETE (its own shards +
    # manifest candidates written) but the commit rename not executed
    rs = _cluster(ck, log, chaos_faults=[chaos.KILL_PRE_COMMIT],
                  chaos_process=0)
    assert rs[0].returncode == chaos.CHAOS_EXIT_CODE, rs[0].stdout
    assert rs[1].returncode != 0
    assert _committed(ck) == [0]

    # (4) victim worker 1 dies mid-resume: restore is read-only — the
    # checkpoint survives a killed reader fully intact
    rs = _cluster(ck, log, chaos_faults=[chaos.KILL_MID_RESUME],
                  chaos_process=1)
    assert rs[1].returncode == chaos.CHAOS_EXIT_CODE, rs[1].stdout
    assert _committed(ck) == [0]

    # (5) restart at the SURVIVING process count (one process, half the
    # devices) — remesh_plan drops the dcn tier, the resharded load is
    # bit-exact, and every step any run logged matches the baseline
    w = chaos.ChaosWorker(
        _worker_argv(ck, log, sync=False), cwd=REPO_ROOT, timeout=300,
    )
    r = w.run(devices=2)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "re-mesh: dcn_data 2 -> 1 (process count changed)" in r.stdout
    assert "re-mesh resume" in r.stdout
    losses = _read_log(log)
    assert sorted(losses) == list(range(6))
    for step, loss in losses.items():
        assert abs(loss - baseline4[step]) < TOL, (
            f"step {step}: {loss} vs baseline {baseline4[step]}"
        )


@pytest.mark.slow
def test_cluster_grow_1_to_2_processes(tmp_path, baseline4):
    """Grow the pod mid-run: 3 steps single-process, then resume on a
    live two-process cluster — the dcn tier appears, the checkpoint
    re-scatters, and the trajectory still matches the baseline."""
    ck, log = tmp_path / "ck", tmp_path / "loss.jsonl"
    w = chaos.ChaosWorker(
        _worker_argv(ck, log, steps=3, sync=False), cwd=REPO_ROOT,
        timeout=300,
    )
    r = w.run(devices=4)
    assert r.returncode == 0, r.stdout + r.stderr
    rs = _cluster(ck, log, steps=6, watchdog=None)
    for pid, r in enumerate(rs):
        assert r.returncode == 0, f"worker {pid}:\n{r.stdout[-1500:]}"
    assert any("dcn_data 1 -> 2" in r.stdout or "re-mesh" in r.stdout
               for r in rs), rs[0].stdout
    losses = _read_log(log)
    assert sorted(losses) == list(range(6))
    for step, loss in losses.items():
        assert abs(loss - baseline4[step]) < TOL, (
            f"step {step}: {loss} vs baseline {baseline4[step]}"
        )


@pytest.mark.slow
def test_cluster_cross_process_drain(tmp_path):
    """SIGTERM ONE worker of a live two-process cluster: the drain flag
    broadcasts at the step boundary, BOTH processes finish the in-flight
    step, cooperate in one final multi-process save, and exit 0 — the
    surviving half of the pod never wedges on a half-drained peer."""
    ck, log = tmp_path / "ck", tmp_path / "loss.jsonl"
    port = _free_port()
    env_base = dict(os.environ)
    env_base.pop("XLA_FLAGS", None)
    env_base["JAX_PLATFORMS"] = "cpu"
    env_base["RING_ATTN_CHAOS_DEVICES"] = "2"
    procs = []
    for pid in range(2):
        env = dict(env_base)
        env[chaos.CLUSTER_ENV] = f"{pid}:2:{port}"
        procs.append(subprocess.Popen(
            _worker_argv(ck, log, steps=2000, sync=False,
                         barrier=60),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO_ROOT,
        ))
    outs: dict[int, str] = {}
    try:
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if len(_read_log(log)) >= 2:  # compiled and stepping
                break
            if any(p.poll() is not None for p in procs):
                break
            time.sleep(0.1)
        assert all(p.poll() is None for p in procs), [
            p.communicate()[0] for p in procs
        ]
        # preempt worker 1 ONLY — worker 0 must drain via the broadcast
        procs[1].send_signal(signal.SIGTERM)
        for pid, p in enumerate(procs):
            out, _ = p.communicate(timeout=180)
            outs[pid] = out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for pid, p in enumerate(procs):
        assert p.returncode == 0, (
            f"worker {pid} rc={p.returncode}:\n{outs.get(pid, '')[-1500:]}"
        )
    assert "DRAINED SIGTERM" in outs[1], outs[1][-800:]
    assert "DRAINED peer" in outs[0], outs[0][-800:]
    # the drained step is committed and resumable
    drained = int(outs[1].split("DRAINED SIGTERM step=")[1].split()[0])
    assert drained in _committed(ck), (drained, _committed(ck))


@pytest.mark.slow
def test_checkpoint_roundtrip_2_to_1_and_1_to_2_bitexact():
    """Both directions of the cross-process-count round-trip, via the
    machine-checked verify rows: a two-process save restores bit-exactly
    at one process, and a one-process save restores bit-exactly on a
    live two-process cluster."""
    from ring_attention_tpu.elastic.verify import (
        check_mp_barrier,
        check_mp_commit_roundtrip,
        check_mp_restore_grow,
    )

    for name, check in (
        ("mp_barrier", check_mp_barrier),
        ("mp_commit_roundtrip", check_mp_commit_roundtrip),
        ("mp_restore_grow", check_mp_restore_grow),
    ):
        violations = check()
        assert not violations, f"{name}: {violations}"


@pytest.mark.slow
def test_elastic_cli_multiprocess_rows():
    """`check_contracts.py --elastic` runs the full 7/7 including the
    spawned two-process rows."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "tools", "check_contracts.py"),
         "--elastic"],
        capture_output=True, text=True, timeout=600, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "7/7 elastic checks hold" in proc.stdout


@pytest.mark.slow
def test_cluster_watchdog_converts_wedged_collective(tmp_path):
    """The wedge pin at pod scale: an armed ``delay_tap`` holds the
    victim's compiled step for longer than the watchdog deadline; the
    peer wedges inside its real cross-process collective waiting for
    the victim's contribution.  BOTH must die the watchdog's bounded
    death (exit 114) — never an eternal hang — and the incident dumps
    record the stalled step.

    The victim is process 0: the in-graph callback of a replicated
    value executes on the process holding its first shard (see
    ``delay_tap``), so a wedge armed on any other process would no-op
    in-graph — and the pin here is the cluster-wide conversion, which
    is symmetric (the peer's wedge is a genuine stuck collective)."""
    from ring_attention_tpu.utils import read_flight_dump

    ck, log = tmp_path / "ck", tmp_path / "loss.jsonl"
    flight = tmp_path / "flight"
    w = chaos.ChaosWorker(
        _worker_argv(ck, log, steps=8, watchdog=6, flight=flight),
        cwd=REPO_ROOT, timeout=360,
    )
    rs = w.run_cluster(
        processes=2, devices_per_process=2,
        chaos={"wedge_at_step": 2, "wedge_seconds": 120},
        chaos_process=0,
    )
    for pid, r in enumerate(rs):
        assert r.returncode == WATCHDOG_EXIT_CODE, (
            f"worker {pid} rc={r.returncode}:\n{r.stdout[-1500:]}"
        )
        assert "watchdog: no heartbeat" in r.stdout, r.stdout[-800:]
    dumps = sorted(
        os.path.join(flight, n) for n in os.listdir(flight)
    ) if os.path.isdir(flight) else []
    assert dumps, "watchdog fired without an incident dump"
    kinds = {read_flight_dump(d)["trigger"]["kind"] for d in dumps}
    assert "watchdog_abort" in kinds, kinds
