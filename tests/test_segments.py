"""Packed-sequence (segment-id) attention: the segment-parity suite.

Ground truth is the *per-document dense reference*: run the oracle
independently on each document's slice and stitch the outputs — packed
attention with segment ids must match it exactly (up to normal float
noise), on every path: the oracle's own segment masking, the XLA flash
path (fwd + bwd, bucketed, GQA, softclamp, windows), the Pallas kernels
in interpret mode (runtime ids AND the trace-time block-aligned
``doc_starts`` tables), and every context-parallel scheme on the
8-virtual-device CPU mesh (plain ring, striped ring, zig-zag, ulysses).

Also pinned here: cross-segment attention weights are EXACTLY zero (a
perturbation of one document cannot change another bitwise on the XLA
path), the compact causal grid dispatches measurably fewer tiles for a
block-aligned 2-document packing (via the band-table helpers), and the
transformer's packed loss drops exactly the document-boundary labels.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ring_attention_tpu.models import RingAttention, RingTransformer
from ring_attention_tpu.ops import default_attention, flash_attention
from ring_attention_tpu.ops.pallas_flash import (
    _MAX_COMPACT_TILES,
    _TF_WORK,
    _band_tables,
    _band_tile_count,
    pallas_flash_attention,
)
from ring_attention_tpu.parallel import create_mesh

ATOL = 3e-5
GRAD_ATOL = 1e-4


def make_seg(b: int, bounds: tuple[int, ...], n: int) -> jnp.ndarray:
    """(b, n) int32 ids for documents starting at ``bounds`` (first 0)."""
    ids = np.zeros(n, np.int32)
    for doc, start in enumerate(bounds):
        ids[start:] = doc
    return jnp.asarray(np.broadcast_to(ids, (b, n)).copy())


def per_doc_reference(q, k, v, bounds, n, *, causal, softclamp_value=None):
    """Dense oracle run independently per document, outputs stitched."""
    edges = list(bounds) + [n]
    outs = []
    for lo, hi in zip(edges[:-1], edges[1:]):
        outs.append(
            default_attention(
                q[:, :, lo:hi], k[:, :, lo:hi], v[:, :, lo:hi],
                causal=causal, softclamp_value=softclamp_value,
            )
        )
    return jnp.concatenate(outs, axis=2)


def make_qkv(rng, b=2, h=4, hk=None, n=64, d=8):
    hk = hk or h
    mk = lambda *s: jnp.asarray(rng.standard_normal(s), jnp.float32)  # noqa: E731
    return mk(b, h, n, d), mk(b, hk, n, d), mk(b, hk, n, d)


# ----------------------------------------------------------------------
# Oracle + XLA flash path
# ----------------------------------------------------------------------


@pytest.mark.parametrize("causal", [False, True])
def test_oracle_segments_match_per_document(rng, causal):
    b, n = 2, 60
    bounds = (0, 17, 41)
    q, k, v = make_qkv(rng, b=b, n=n)
    seg = make_seg(b, bounds, n)
    ref = per_doc_reference(q, k, v, bounds, n, causal=causal)
    out = default_attention(q, k, v, causal=causal, segment_ids=seg)
    np.testing.assert_allclose(out, ref, atol=ATOL)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("hk,softclamp", [(4, None), (2, 5.0)])
def test_flash_segments_fwd_bwd(rng, causal, hk, softclamp):
    """Bucketed flash (buckets cross doc boundaries -> mask AND whole-
    bucket skip both exercised) vs the per-document dense reference,
    forward and dq/dk/dv."""
    b, n = 2, 64
    bounds = (0, 23, 48)
    q, k, v = make_qkv(rng, b=b, hk=hk, n=n)
    seg = make_seg(b, bounds, n)
    ref = per_doc_reference(q, k, v, bounds, n, causal=causal,
                            softclamp_value=softclamp)
    out = flash_attention(q, k, v, causal=causal, bucket_size=16,
                          softclamp_value=softclamp, segment_ids=seg)
    np.testing.assert_allclose(out, ref, atol=ATOL)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v) ** 2).sum()

    g = jax.grad(
        loss(lambda q, k, v: flash_attention(
            q, k, v, causal=causal, bucket_size=16,
            softclamp_value=softclamp, segment_ids=seg)),
        (0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        loss(lambda q, k, v: default_attention(
            q, k, v, causal=causal, softclamp_value=softclamp,
            segment_ids=seg)),
        (0, 1, 2),
    )(q, k, v)
    for ours, theirs, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(ours, theirs, atol=GRAD_ATOL,
                                   err_msg=f"d{name}")


def test_flash_cross_segment_weights_exactly_zero(rng):
    """Perturbing document B's keys/values must not change document A's
    outputs AT ALL — masked logits underflow to weight 0.0 exactly, so
    the comparison is bitwise, not approximate."""
    b, n = 1, 48
    bounds = (0, 20)
    q, k, v = make_qkv(rng, b=b, n=n)
    seg = make_seg(b, bounds, n)

    out = flash_attention(q, k, v, causal=True, bucket_size=8,
                          segment_ids=seg)
    k2 = k.at[:, :, 20:].add(37.0)
    v2 = v.at[:, :, 20:].add(-11.0)
    out2 = flash_attention(q, k2, v2, causal=True, bucket_size=8,
                           segment_ids=seg)
    assert np.array_equal(
        np.asarray(out[:, :, :20]), np.asarray(out2[:, :, :20])
    ), "document A's outputs changed when document B was perturbed"
    # and B did change (the test has power)
    assert not np.array_equal(
        np.asarray(out[:, :, 20:]), np.asarray(out2[:, :, 20:])
    )


def test_flash_segments_with_window(rng):
    """Lookback window + segments compose: reference = per-document dense
    attention windowed inside each document (window counts positions, and
    cross-document positions are masked anyway)."""
    b, n, w = 1, 48, 8
    bounds = (0, 19)
    q, k, v = make_qkv(rng, b=b, n=n)
    seg = make_seg(b, bounds, n)
    out = flash_attention(q, k, v, causal=True, bucket_size=8, window=w,
                          segment_ids=seg)

    # dense reference with the combined mask
    s = jnp.einsum("bhid,bhjd->bhij", q, k) * (q.shape[-1] ** -0.5)
    i = jnp.arange(n)[:, None]
    j = jnp.arange(n)[None, :]
    keep = (j <= i) & (j > i - w) & (seg[0][i] == seg[0][j])
    s = jnp.where(keep[None, None], s, -1e30)
    ref = jnp.einsum("bhij,bhjd->bhid", jax.nn.softmax(s, axis=-1), v)
    np.testing.assert_allclose(out, ref, atol=ATOL)


# ----------------------------------------------------------------------
# Pallas kernels (interpret mode)
# ----------------------------------------------------------------------


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_segments_fwd_bwd(rng, causal):
    b, n = 2, 64
    bounds = (0, 23, 48)
    q, k, v = make_qkv(rng, b=b, hk=2, n=n)
    seg = make_seg(b, bounds, n)
    ref = per_doc_reference(q, k, v, bounds, n, causal=causal)
    out = pallas_flash_attention(q, k, v, causal=causal, segment_ids=seg,
                                 interpret=True)
    np.testing.assert_allclose(out, ref, atol=ATOL)

    g = jax.grad(
        lambda q, k, v: (pallas_flash_attention(
            q, k, v, causal=causal, segment_ids=seg, interpret=True
        ) ** 2).sum(),
        (0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: (default_attention(
            q, k, v, causal=causal, segment_ids=seg) ** 2).sum(),
        (0, 1, 2),
    )(q, k, v)
    for ours, theirs, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(ours, theirs, atol=GRAD_ATOL,
                                   err_msg=f"d{name}")


def test_pallas_doc_starts_trace_time_skip(rng):
    """A block-boundary-aligned declared packing (trace-time tile drop,
    no runtime refs) must equal both the runtime-id path and the
    per-document dense reference — fwd and bwd."""
    from ring_attention_tpu.ops.pallas_flash import (
        finalize_partials,
        pallas_flash_backward,
        pallas_flash_partials,
    )

    b, h, n, d = 1, 2, 128, 8
    bounds = (0, 64)
    q, k, v = make_qkv(rng, b=b, h=h, n=n, d=d)
    seg = make_seg(b, bounds, n)
    scale = d ** -0.5

    aligned = pallas_flash_partials(
        q, k, v, scale=scale, causal_offset=0, block_q=32, block_k=32,
        doc_starts=bounds, interpret=True,
    )
    runtime = pallas_flash_partials(
        q, k, v, scale=scale, causal_offset=0, block_q=32, block_k=32,
        segment_ids=seg, interpret=True,
    )
    out_a, lse_a = finalize_partials(aligned)
    out_r, _ = finalize_partials(runtime)
    ref = per_doc_reference(q, k, v, bounds, n, causal=True)
    np.testing.assert_allclose(out_a, ref.astype(jnp.float32), atol=ATOL)
    np.testing.assert_allclose(out_r, ref.astype(jnp.float32), atol=ATOL)

    do = jnp.asarray(rng.standard_normal(q.shape), jnp.float32)
    delta = (do * out_a).sum(-1)
    grads_a = pallas_flash_backward(
        do, q, k, v, lse_a, delta, scale=scale, causal_offset=0,
        block_q=32, block_k=32, doc_starts=bounds, interpret=True,
    )
    g_ref = jax.grad(
        lambda q, k, v: (default_attention(
            q, k, v, causal=True, segment_ids=seg
        ).astype(jnp.float32) * do).sum(),
        (0, 1, 2),
    )(q, k, v)
    for ours, theirs, name in zip(grads_a, g_ref, "qkv"):
        np.testing.assert_allclose(ours, theirs, atol=GRAD_ATOL,
                                   err_msg=f"d{name}")


@pytest.mark.parametrize("outer_is_q", [True, False])
def test_band_tables_two_doc_packing_drops_tiles(outer_is_q):
    """Acceptance pin: a block-aligned 2-document packing measurably
    shrinks the compact grid — fewer dispatched (WORK) tiles — and the
    closed-form tile count stays exact for the doc-filtered tables."""
    n_blocks, bq, bk = 8, 16, 16
    hint = (0, 0, 0, 0)  # plain causal diagonal
    docs = (0, 64)  # two 64-token docs over a 128-token span
    plain = _band_tables(n_blocks, n_blocks, bq, bk, hint, False,
                         outer_is_q=outer_is_q)
    packed = _band_tables(n_blocks, n_blocks, bq, bk, hint, False,
                          outer_is_q=outer_is_q, doc_starts=docs)

    def work(tf):
        return int(((tf & _TF_WORK) != 0).sum())

    assert work(packed[2]) < work(plain[2])
    # two equal causal triangles: exactly half the strictly-off-diagonal
    # tiles disappear -> 36 -> 2 * 10 work tiles at 8 blocks
    assert work(plain[2]) == 36
    assert work(packed[2]) == 20
    assert packed[0].shape[0] <= _MAX_COMPACT_TILES
    # the SMEM-cap accounting must agree with the real tables
    assert _band_tile_count(
        n_blocks, n_blocks, bq, bk, hint, False, outer_is_q=outer_is_q,
        doc_starts=docs,
    ) == packed[0].shape[0]


def test_band_tile_count_matches_tables_with_docs():
    """Closed-form count vs real tables across misalignment-free layouts,
    windows, and both outer orders."""
    for hint, windowed in (((0, 0, 0, 0), False), ((0, 0, -24, -24), True)):
        for docs in ((0, 32), (0, 32, 96), (0, 64, 80)):
            for outer_is_q in (True, False):
                args = (8, 8, 16, 16, hint, windowed)
                assert _band_tile_count(
                    *args, outer_is_q=outer_is_q, doc_starts=docs
                ) == _band_tables(
                    *args, outer_is_q=outer_is_q, doc_starts=docs
                )[0].shape[0], (hint, windowed, docs, outer_is_q)


# ----------------------------------------------------------------------
# Context-parallel schemes on the 8-virtual-device mesh
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def mesh():
    return create_mesh(ring_size=8)


SP_CASES = [
    # (sequence_parallel, striped, heads, causal, use_pallas)
    ("ring", False, 4, True, False),
    ("ring", True, 4, True, False),
    ("ring", False, 4, False, False),
    ("ring", False, 4, True, True),  # pallas kernels, interpret on CPU
    ("ring", True, 4, True, True),
    ("zigzag", False, 4, True, False),
    ("ulysses", False, 8, True, False),
]


@pytest.mark.parametrize(
    "case", SP_CASES,
    ids=[f"{c[0]}{'-striped' if c[1] else ''}"
         f"{'-noncausal' if not c[3] else ''}{'-pallas' if c[4] else ''}"
         for c in SP_CASES],
)
def test_model_segments_vs_per_document_oracle(mesh, case):
    """RingAttention with segment_ids on the mesh (auto_shard pads the odd
    length) vs the force_regular_attn per-document oracle — forward, every
    context-parallel scheme."""
    sp, striped, h, causal, use_pallas = case
    b, dh, n = 2, 8, 61
    dim = h * dh
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((b, n, dim)), jnp.float32)
    seg = make_seg(b, (0, 25, 40), n)
    common = dict(dim=dim, heads=h, dim_head=dh, causal=causal,
                  bucket_size=8)
    oracle = RingAttention(use_ring=False, force_regular_attn=True, **common)
    params = oracle.init(jax.random.PRNGKey(0), x)
    ref = oracle.apply(params, x, None, seg)
    sharded = RingAttention(
        use_ring=True, auto_shard=True, mesh=mesh, sequence_parallel=sp,
        striped=striped, use_pallas=use_pallas, **common,
    )
    out = sharded.apply(params, x, None, seg)
    np.testing.assert_allclose(out, ref, atol=ATOL, err_msg=str(case))


@pytest.mark.parametrize(
    "sp,striped", [("ring", False), ("ring", True), ("zigzag", False)],
    ids=["plain", "striped", "zigzag"],
)
def test_model_segments_grads_on_mesh(mesh, sp, striped):
    """Packed backward on the mesh (ring: dk/dv circulate with the kv
    segment ids; zig-zag: dk/dv reduce-scatter through the gather's
    transpose) vs the per-document oracle's gradients."""
    b, h, dh, n = 2, 4, 8, 64
    dim = h * dh
    rng = np.random.default_rng(8)
    x = jnp.asarray(rng.standard_normal((b, n, dim)), jnp.float32)
    seg = make_seg(b, (0, 21, 44), n)
    common = dict(dim=dim, heads=h, dim_head=dh, causal=True, bucket_size=8)
    oracle = RingAttention(use_ring=False, force_regular_attn=True, **common)
    params = oracle.init(jax.random.PRNGKey(0), x)
    sharded = RingAttention(
        use_ring=True, auto_shard=True, mesh=mesh, sequence_parallel=sp,
        striped=striped, **common,
    )
    g = jax.grad(
        lambda p: (sharded.apply(p, x, None, seg) ** 2).sum()
    )(params)
    g_ref = jax.grad(
        lambda p: (oracle.apply(p, x, None, seg) ** 2).sum()
    )(params)
    for ours, theirs in zip(jax.tree.leaves(g), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(ours, theirs, atol=1e-3)


# ----------------------------------------------------------------------
# Transformer loss semantics
# ----------------------------------------------------------------------


def test_transformer_packed_loss_equals_separate_documents(rng):
    """Packing two documents with segment_ids must give the same causal-LM
    loss as training them as separate (ignore-padded) batch rows: same
    per-position nlls, same valid-label count, boundary label dropped."""
    model = RingTransformer(
        num_tokens=64, dim=32, depth=2, heads=4, dim_head=8, causal=True,
        bucket_size=8, use_ring=False,
    )
    d1 = rng.integers(0, 64, (1, 5))
    d2 = rng.integers(0, 64, (1, 7))
    packed = jnp.asarray(np.concatenate([d1, d2], axis=1), jnp.int32)
    seg = jnp.asarray(np.repeat([0, 1], [5, 7])[None, :])
    params = model.init(jax.random.PRNGKey(0), packed)
    packed_loss = model.apply(params, packed, return_loss=True,
                              segment_ids=seg)

    toks = np.zeros((2, 12), np.int64)
    toks[0, :5] = d1
    toks[1, :7] = d2
    toks[0, 5:] = -1  # ignore_index: pad labels drop out of the loss
    toks[1, 7:] = -1
    # embedding lookups need valid ids; the pad positions' LABELS stay -1
    # because labels are read before this clamp
    separate = jnp.asarray(np.where(toks < 0, 0, toks), jnp.int32)
    labels_ok = jnp.asarray(toks, jnp.int32)
    # build the separate-row loss from logits + the model's own nll rule
    logits = model.apply(params, separate[:, :-1])
    valid = labels_ok[:, 1:] >= 0
    lf = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(lf, axis=-1)
    chosen = jnp.take_along_axis(
        lf, jnp.where(valid, labels_ok[:, 1:], 0)[..., None], axis=-1
    )[..., 0]
    nll = jnp.where(valid, lse - chosen, 0.0)
    separate_loss = nll.sum() / valid.sum()
    np.testing.assert_allclose(packed_loss, separate_loss, atol=1e-5)


def test_transformer_boundary_labels_dropped(rng):
    """The first token of each packed document carries no loss: the valid
    count behind the mean must equal n-1 minus (#docs - 1)."""
    model = RingTransformer(
        num_tokens=32, dim=16, depth=1, heads=2, dim_head=8, causal=True,
        bucket_size=8, use_ring=False,
    )
    n = 12
    tokens = jnp.asarray(rng.integers(0, 32, (1, n)), jnp.int32)
    seg = make_seg(1, (0, 4, 9), n)
    params = model.init(jax.random.PRNGKey(0), tokens)
    loss = model.apply(params, tokens, return_loss=True, segment_ids=seg)

    logits = model.apply(params, tokens[:, :-1], segment_ids=seg[:, :-1])
    labels = tokens[:, 1:]
    valid = np.asarray(seg)[:, 1:] == np.asarray(seg)[:, :-1]
    assert valid.sum() == (n - 1) - 2  # two boundary labels dropped
    lf = np.asarray(logits, np.float32)
    lse = jax.nn.logsumexp(lf, axis=-1)
    chosen = np.take_along_axis(
        lf, np.asarray(labels)[..., None], axis=-1
    )[..., 0]
    expect = ((lse - chosen) * valid).sum() / valid.sum()
    np.testing.assert_allclose(loss, expect, atol=1e-5)
