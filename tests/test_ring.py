"""Parity: ring attention over an 8-device mesh vs the single-device oracle.

JAX-native analogue of the reference's ``assert_attn.py`` distributed parity
test: outputs and input-gradients of ``ring_flash_attention`` under
``shard_map`` must match ``default_attention`` run unsharded, across causal,
striped, GQA, key-padding, ring-set (data x seq mesh) and lookback configs.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from ring_attention_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from ring_attention_tpu.ops import default_attention
from ring_attention_tpu.parallel import (
    create_mesh,
    ring_flash_attention,
    stripe_permute,
    stripe_unpermute,
)

ATOL = 2e-5
GRAD_ATOL = 5e-4


def ring_attn_global(
    q, k, v, mask=None, *, mesh, striped=False, **kw
):
    """Run ring attention on global arrays through shard_map over the mesh."""
    # pallas_call with device-varying scalars trips jax's vma checker
    # (jax suggests check_vma=False as the workaround)
    check_vma = kw.get("impl", "xla") != "pallas"
    ring = mesh.shape["seq"]
    if striped:
        q = stripe_permute(q, ring, axis=2)
        k = stripe_permute(k, ring, axis=2)
        v = stripe_permute(v, ring, axis=2)
        assert mask is None

    fn = partial(
        ring_flash_attention,
        axis_name="seq",
        striped=striped,
        **kw,
    )
    qspec = P("data", None, "seq", None)
    mspec = P("data", "seq")
    out = shard_map(
        fn,
        mesh=mesh,
        in_specs=(qspec, qspec, qspec, mspec if mask is not None else P()),
        out_specs=qspec,
        check_vma=check_vma,
    )(q, k, v, mask)

    if striped:
        out = stripe_unpermute(out, ring, axis=2)
    return out


def make_qkv(rng, b=2, h=4, hk=None, n=128, d=16):
    hk = hk or h
    q = jnp.asarray(rng.standard_normal((b, h, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    return q, k, v


def banded_oracle(w):
    """Dense causal sliding-window oracle: attend iff i-(w-1) <= j <= i."""

    def oracle(q, k, v):
        n = q.shape[2]
        i = jnp.arange(n)[:, None]
        j = jnp.arange(n)[None, :]
        band = (j <= i) & (j >= i - (w - 1))
        s = jnp.einsum("bhid,bhjd->bhij", q, k) * (q.shape[-1] ** -0.5)
        return jnp.einsum(
            "bhij,bhjd->bhid", jax.nn.softmax(jnp.where(band, s, -1e30), -1), v
        )

    return oracle


@pytest.fixture(scope="module")
def mesh(  ):
    return create_mesh(ring_size=8)


@pytest.fixture(scope="module")
def mesh2x4():
    return create_mesh(ring_size=4, data_size=2)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_parity(rng, mesh, causal):
    q, k, v = make_qkv(rng)
    ref = default_attention(q, k, v, causal=causal)
    out = ring_attn_global(q, k, v, mesh=mesh, causal=causal, bucket_size=8)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_ring_striped(rng, mesh):
    q, k, v = make_qkv(rng)
    ref = default_attention(q, k, v, causal=True)
    out = ring_attn_global(q, k, v, mesh=mesh, causal=True, striped=True, bucket_size=8)
    np.testing.assert_allclose(out, ref, atol=ATOL)


@pytest.mark.parametrize("striped", [False, True])
def test_ring_gqa(rng, mesh, striped):
    q, k, v = make_qkv(rng, h=4, hk=2)
    ref = default_attention(q, k, v, causal=True)
    out = ring_attn_global(q, k, v, mesh=mesh, causal=True, striped=striped, bucket_size=8)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_ring_key_padding(rng, mesh):
    q, k, v = make_qkv(rng)
    mask = jnp.asarray(rng.random((2, 128)) > 0.3)
    ref = default_attention(q, k, v, mask)
    out = ring_attn_global(q, k, v, mask, mesh=mesh, bucket_size=8)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_ring_softclamp(rng, mesh):
    q, k, v = make_qkv(rng)
    ref = default_attention(q, k, v, causal=True, softclamp_value=5.0)
    out = ring_attn_global(
        q, k, v, mesh=mesh, causal=True, bucket_size=8, softclamp_value=5.0
    )
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_ring_data_axis(rng, mesh2x4):
    """2x4 mesh: two independent rings (the reference's ring sets)."""
    q, k, v = make_qkv(rng)
    ref = default_attention(q, k, v, causal=True)
    out = ring_attn_global(q, k, v, mesh=mesh2x4, causal=True, bucket_size=8)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_ring_window(rng, mesh):
    """Sliding-window lookback with limited ring passes vs banded oracle."""
    q, k, v = make_qkv(rng)
    w = 32  # window of 32 tokens; shard=16 -> lookback spans 3 shards
    out = ring_attn_global(
        q, k, v, mesh=mesh, causal=True, bucket_size=8, window=w, max_ring_passes=4
    )
    np.testing.assert_allclose(out, banded_oracle(w)(q, k, v), atol=ATOL)


@pytest.mark.parametrize("striped", [False, True])
@pytest.mark.parametrize("hk", [4, 2])
def test_ring_grads(rng, mesh, striped, hk):
    q, k, v = make_qkv(rng, hk=hk)

    def loss_ref(q, k, v):
        return (default_attention(q, k, v, causal=True) ** 2).sum()

    def loss_ring(q, k, v):
        return (
            ring_attn_global(q, k, v, mesh=mesh, causal=True, striped=striped, bucket_size=8)
            ** 2
        ).sum()

    g_ref = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    g_out = jax.grad(loss_ring, (0, 1, 2))(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


def test_ring_grads_limited_passes(rng, mesh):
    """dkv catch-up rotation: grads must land on the owner shard even when
    max_ring_passes < ring_size (ref ring_flash_attention.py:380-385)."""
    q, k, v = make_qkv(rng)
    w = 32

    def loss_ring(q, k, v):
        return (
            ring_attn_global(
                q, k, v, mesh=mesh, causal=True, bucket_size=8,
                window=w, max_ring_passes=4,
            )
            ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (banded_oracle(w)(q, k, v) ** 2).sum()

    g_ref = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    g_out = jax.grad(loss_ring, (0, 1, 2))(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


def test_stripe_roundtrip(rng):
    x = jnp.asarray(rng.standard_normal((2, 64, 8)), jnp.float32)
    y = stripe_unpermute(stripe_permute(x, 8), 8)
    np.testing.assert_array_equal(x, y)


@pytest.mark.parametrize("striped", [False, True])
@pytest.mark.slow
def test_ring_pallas_impl(rng, mesh, striped):
    """Ring attention with the Pallas per-hop kernels (interpret mode on CPU)
    matches the oracle, fwd and bwd."""
    q, k, v = make_qkv(rng, hk=2)
    ref = default_attention(q, k, v, causal=True)
    out = ring_attn_global(
        q, k, v, mesh=mesh, causal=True, striped=striped, bucket_size=8,
        impl="pallas",
    )
    np.testing.assert_allclose(out, ref, atol=ATOL)

    g_ref = jax.grad(
        lambda *a: (default_attention(*a, causal=True) ** 2).sum(), (0, 1, 2)
    )(q, k, v)
    g_out = jax.grad(
        lambda *a: (
            ring_attn_global(
                *a, mesh=mesh, causal=True, striped=striped, bucket_size=8,
                impl="pallas",
            )
            ** 2
        ).sum(),
        (0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


def test_ring_pallas_mask(rng, mesh):
    q, k, v = make_qkv(rng)
    mask = jnp.asarray(rng.random((2, 128)) > 0.3)
    ref = default_attention(q, k, v, mask)
    out = ring_attn_global(q, k, v, mask, mesh=mesh, bucket_size=16, impl="pallas")
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_ring_bf16(rng, mesh):
    """bf16 ring attention stays within bf16 tolerance of the f32 oracle
    across all hops (accumulators and lse are f32 throughout)."""
    q, k, v = make_qkv(rng, n=256)
    ref = default_attention(q, k, v, causal=True)
    out = ring_attn_global(
        q.astype(jnp.bfloat16), k.astype(jnp.bfloat16), v.astype(jnp.bfloat16),
        mesh=mesh, causal=True, striped=True, bucket_size=16,
    )
    np.testing.assert_allclose(out.astype(jnp.float32), ref, atol=3e-2)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
@pytest.mark.slow
def test_ring_striped_window_exact(rng, mesh, impl):
    """Sliding windows under STRIPED layout are exact (the reference only
    approximates striped lookback at bucket granularity): per-hop band
    lower offsets reproduce the banded oracle, fwd and bwd."""
    q, k, v = make_qkv(rng)
    w = 40
    oracle = banded_oracle(w)

    out = ring_attn_global(
        q, k, v, mesh=mesh, causal=True, striped=True, bucket_size=8, window=w,
        impl=impl,
    )
    np.testing.assert_allclose(out, oracle(q, k, v), atol=ATOL)

    g_ref = jax.grad(lambda *a: (oracle(*a) ** 2).sum(), (0, 1, 2))(q, k, v)
    g_out = jax.grad(
        lambda *a: (
            ring_attn_global(
                *a, mesh=mesh, causal=True, striped=True, bucket_size=8,
                window=w, impl=impl,
            )
            ** 2
        ).sum(),
        (0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_ring_cross_attention_degrades(rng, mesh, impl):
    """Unequal q/kv shard lengths (cross-attention): the ring entry bypasses
    the ring and runs local flash per shard, exactly like the reference's
    silent non-ring fallback (ref ring_flash_attention.py:81-83) — instead
    of hard-failing.  Oracle: dense attention of each q shard against its
    own KV shard, fwd and bwd."""
    b, h, d, ring = 2, 4, 16, 8
    nq, nk = 64, 128  # per-shard 8 vs 16
    q = jnp.asarray(rng.standard_normal((b, h, nq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, nk, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, nk, d)), jnp.float32)

    def oracle(q, k, v):
        qs = q.reshape(b, h, ring, nq // ring, d)
        ks = k.reshape(b, h, ring, nk // ring, d)
        vs = v.reshape(b, h, ring, nk // ring, d)
        outs = [
            default_attention(qs[:, :, i], ks[:, :, i], vs[:, :, i])
            for i in range(ring)
        ]
        return jnp.concatenate(outs, axis=2)

    out = ring_attn_global(q, k, v, mesh=mesh, impl=impl)
    np.testing.assert_allclose(out, oracle(q, k, v), atol=ATOL)

    g_ref = jax.grad(lambda *a: (oracle(*a) ** 2).sum(), (0, 1, 2))(q, k, v)
    g_out = jax.grad(
        lambda *a: (ring_attn_global(*a, mesh=mesh, impl=impl) ** 2).sum(),
        (0, 1, 2),
    )(q, k, v)
    for a, b_, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b_, atol=GRAD_ATOL, err_msg=f"d{name}")


@pytest.mark.parametrize(
    "causal,striped", [(False, False), (True, False), (True, True)]
)
def test_ring_bidirectional_parity(rng, mesh, causal, striped):
    """Bidirectional half-KV ring (opposite-direction ppermutes riding both
    ICI directions): every origin's both halves are visited exactly once, so
    outputs must match the oracle in all layouts."""
    q, k, v = make_qkv(rng)
    ref = default_attention(q, k, v, causal=causal)
    out = ring_attn_global(
        q, k, v, mesh=mesh, causal=causal, striped=striped, bucket_size=8,
        bidirectional=True,
    )
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_ring_bidirectional_mask_gqa_grads(rng, mesh):
    """Key-padding mask halves rotate with their KV halves; GQA dk/dv
    group-sums land on the owner shard from both streams."""
    q, k, v = make_qkv(rng, hk=2)
    mask = jnp.asarray(rng.random((2, 128)) > 0.3)

    def loss_ref(q, k, v):
        return (default_attention(q, k, v, mask) ** 2).sum()

    def loss_ring(q, k, v):
        return (
            ring_attn_global(
                q, k, v, mask, mesh=mesh, bucket_size=8, bidirectional=True
            )
            ** 2
        ).sum()

    np.testing.assert_allclose(
        ring_attn_global(q, k, v, mask, mesh=mesh, bucket_size=8, bidirectional=True),
        default_attention(q, k, v, mask),
        atol=ATOL,
    )
    g_ref = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    g_out = jax.grad(loss_ring, (0, 1, 2))(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


def test_ring_bidirectional_window_limited_passes(rng, mesh):
    """max_ring_passes < ring_size is incompatible with bidirectional
    circulation (the reverse stream delivers future origins first, so a
    window's trailing key halves would only arrive after a full ring) —
    the implementation must silently fall back to unidirectional and still
    match the banded oracle, fwd and bwd."""
    q, k, v = make_qkv(rng)
    w = 32
    oracle = banded_oracle(w)

    def ring(q, k, v):
        return ring_attn_global(
            q, k, v, mesh=mesh, causal=True, bucket_size=8, window=w,
            max_ring_passes=4, bidirectional=True,
        )

    np.testing.assert_allclose(ring(q, k, v), oracle(q, k, v), atol=ATOL)
    g_ref = jax.grad(lambda *a: (oracle(*a) ** 2).sum(), (0, 1, 2))(q, k, v)
    g_out = jax.grad(lambda *a: (ring(*a) ** 2).sum(), (0, 1, 2))(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


def test_ring_bidirectional_striped_window(rng, mesh):
    """Striped + sliding window + bidirectional at FULL passes: the reverse
    stream's band lower-bound shift (lo - key_offset under the stripe
    interleave) is the trickiest line of the band math — pin it to the
    banded oracle, fwd and bwd."""
    q, k, v = make_qkv(rng)
    w = 32
    oracle = banded_oracle(w)

    def ring(q, k, v):
        return ring_attn_global(
            q, k, v, mesh=mesh, causal=True, striped=True, bucket_size=8,
            window=w, bidirectional=True,
        )

    np.testing.assert_allclose(ring(q, k, v), oracle(q, k, v), atol=ATOL)
    g_ref = jax.grad(lambda *a: (oracle(*a) ** 2).sum(), (0, 1, 2))(q, k, v)
    g_out = jax.grad(lambda *a: (ring(*a) ** 2).sum(), (0, 1, 2))(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


@pytest.mark.slow
def test_ring_bidirectional_pallas(rng, mesh):
    """Bidirectional streams through the Pallas per-hop kernels."""
    q, k, v = make_qkv(rng, hk=2)
    ref = default_attention(q, k, v, causal=True)
    out = ring_attn_global(
        q, k, v, mesh=mesh, causal=True, striped=True, bucket_size=8,
        impl="pallas", bidirectional=True,
    )
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_ring_determinism(rng, mesh):
    """Bitwise repeatability across FRESH compilations (caches cleared
    between runs): the compiled collective schedule fixes the reduction
    order, replacing the reference's reliance on per-hop barriers for
    reproducibility."""
    q, k, v = make_qkv(rng)
    # the persistent on-disk cache (conftest) would hand the second compile
    # the identical serialized executable, making the comparison trivial —
    # bypass it for this test so both compiles are genuinely fresh
    cache_dir = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        a = np.asarray(
            ring_attn_global(q, k, v, mesh=mesh, causal=True, striped=True, bucket_size=8)
        )
        jax.clear_caches()  # force a recompile; same-executable equality is trivial
        b = np.asarray(
            ring_attn_global(q, k, v, mesh=mesh, causal=True, striped=True, bucket_size=8)
        )
    finally:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
@pytest.mark.slow
def test_ring_dkv_bf16_circulation(rng, mesh, impl):
    """dkv_dtype="bfloat16" halves the backward ring's ICI bandwidth (the
    reference circulates half-precision dkv, ring_flash_attention_cuda.py:
    255-260).  Accumulation suffers bf16 round-off per hop; grads must stay
    within a bf16-scale tolerance of the exact f32 circulation."""
    q, k, v = make_qkv(rng)

    def loss(dkv_dtype):
        def f(q, k, v):
            return (
                ring_attn_global(
                    q, k, v, mesh=mesh, causal=True, bucket_size=16,
                    impl=impl, dkv_dtype=dkv_dtype,
                )
                ** 2
            ).sum()
        return f

    g_f32 = jax.grad(loss(None), (0, 1, 2))(q, k, v)
    g_bf16 = jax.grad(loss("bfloat16"), (0, 1, 2))(q, k, v)
    # dq never circulates: it must be bit-identical between the two modes
    np.testing.assert_array_equal(g_bf16[0], g_f32[0])
    # dk/dv accumulate in bf16 across 8 hops: relative error ~ bf16 eps
    for a, b, name in zip(g_bf16[1:], g_f32[1:], "kv"):
        np.testing.assert_allclose(a, b, atol=2e-2, rtol=2e-2,
                                   err_msg=f"d{name}")


# ----------------------------------------------------------------------
# TokenRing counter-rotation (arXiv 2412.20501): the Q shard + its
# (acc, m, l) accumulators circulate one ring direction while the KV
# stream rotates the other; the backward keeps KV and dKV resident.
# ----------------------------------------------------------------------


@pytest.mark.parametrize("striped", [False, True])
def test_ring_counter_parity(rng, mesh, striped):
    """Counter-rotation visits the same (q_origin, kv_origin) pairings as
    the baseline ring (hop i pairs each query block with the KV block i
    ranks behind), so outputs must match the oracle in both causal
    layouts (the non-causal path rides test_ring_counter_kv_mask)."""
    q, k, v = make_qkv(rng)
    ref = default_attention(q, k, v, causal=True)
    out = ring_attn_global(
        q, k, v, mesh=mesh, causal=True, striped=striped, bucket_size=8,
        counter_rotate=True,
    )
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_ring_counter_grads(rng, mesh):
    """Backward with the q-side pack circulating and KV/dKV resident: dq
    comes home with the pack, dk/dv accumulate in place on the owner
    shard (GQA — the group-sum is the harder case; full heads ride the
    same path and are covered by the slow pallas test and the fuzz)."""
    q, k, v = make_qkv(rng, hk=2)

    def loss_ref(q, k, v):
        return (default_attention(q, k, v, causal=True) ** 2).sum()

    def loss_ring(q, k, v):
        return (
            ring_attn_global(
                q, k, v, mesh=mesh, causal=True, bucket_size=8,
                counter_rotate=True,
            )
            ** 2
        ).sum()

    g_ref = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    g_out = jax.grad(loss_ring, (0, 1, 2))(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


def test_ring_counter_window_limited_passes(rng, mesh):
    """Counter-rotation preserves the baseline's pairing-visit ORDER, so
    max_ring_passes + sliding windows keep their semantics; the dq
    catch-up must land limited-pass grads on the owner shard."""
    q, k, v = make_qkv(rng)
    w = 32
    oracle = banded_oracle(w)

    def ring(q, k, v):
        return ring_attn_global(
            q, k, v, mesh=mesh, causal=True, bucket_size=8, window=w,
            max_ring_passes=4, counter_rotate=True,
        )

    np.testing.assert_allclose(ring(q, k, v), oracle(q, k, v), atol=ATOL)
    g_ref = jax.grad(lambda *a: (oracle(*a) ** 2).sum(), (0, 1, 2))(q, k, v)
    g_out = jax.grad(lambda *a: (ring(*a) ** 2).sum(), (0, 1, 2))(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


@pytest.mark.slow
def test_ring_counter_kv_mask(rng, mesh):
    """The key-padding mask rides the KV stream (opposite the Q pack).
    Slow tier: the kv-side payload rotation is the same code path the
    fast packed-segment test exercises with kv segment ids."""
    q, k, v = make_qkv(rng)
    mask = jnp.asarray(rng.random((2, 128)) > 0.3)
    ref = default_attention(q, k, v, mask)
    out = ring_attn_global(
        q, k, v, mask, mesh=mesh, bucket_size=8, counter_rotate=True
    )
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_ring_counter_packed_segments(rng, mesh):
    """Packed documents under counter-rotation: the q-side segment ids
    circulate WITH the Q pack while the kv ids ride the KV stream — the
    cross-document mask and the no-shared-document hop skip must follow
    both streams, fwd and bwd."""
    q, k, v = make_qkv(rng)
    n = q.shape[2]
    ids = np.zeros(n, np.int32)
    for doc, start in enumerate((0, 48, 96)):
        ids[start:] = doc
    seg = jnp.asarray(np.broadcast_to(ids, (2, n)).copy())

    def per_doc(q, k, v):
        outs = []
        for lo, hi in ((0, 48), (48, 96), (96, n)):
            outs.append(default_attention(
                q[:, :, lo:hi], k[:, :, lo:hi], v[:, :, lo:hi], causal=True
            ))
        return jnp.concatenate(outs, axis=2)

    def counter(q, k, v):
        fn = partial(
            ring_flash_attention, axis_name="seq", causal=True,
            bucket_size=8, counter_rotate=True,
        )
        qspec = P("data", None, "seq", None)
        return shard_map(
            lambda q, k, v, s: fn(q, k, v, None, segment_ids=s),
            mesh=mesh,
            in_specs=(qspec, qspec, qspec, P("data", "seq")),
            out_specs=qspec,
        )(q, k, v, seg)

    np.testing.assert_allclose(counter(q, k, v), per_doc(q, k, v), atol=ATOL)
    # grads — the q-side ids circulating WITH the pack through the
    # backward's pure-Q rotation is the novel packed-counter logic
    g_ref = jax.grad(lambda *a: (per_doc(*a) ** 2).sum(), (0, 1, 2))(q, k, v)
    g_out = jax.grad(lambda *a: (counter(*a) ** 2).sum(), (0, 1, 2))(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


@pytest.mark.slow
def test_ring_counter_data_axis(rng, mesh2x4):
    """Counter-rotation inside ring sets: ppermute over the seq sub-axis
    of a (data, seq) mesh scopes per mesh row, both directions.  (Slow
    tier: the per-row scoping is also pinned structurally by the contract
    axis-discipline rule on the 2x4 mesh.)"""
    q, k, v = make_qkv(rng)
    ref = default_attention(q, k, v, causal=True)
    out = ring_attn_global(
        q, k, v, mesh=mesh2x4, causal=True, bucket_size=8,
        counter_rotate=True,
    )
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_ring_counter_supersedes_bidirectional(rng, mesh):
    """counter_rotate + bidirectional cannot compose (a KV half co-moving
    with the Q stream never advances its pairing): requesting both warns
    and runs pure counter-rotation.  The warning fires at TRACE time, so
    eval_shape under shard_map pins it without compiling or running (the
    counter schedule's numerics are covered by the parity tests)."""
    q, k, v = make_qkv(rng)

    def fn(q, k, v):
        return ring_flash_attention(
            q, k, v, None, "seq", causal=True, bucket_size=8,
            counter_rotate=True, bidirectional=True,
        )

    qspec = P("data", None, "seq", None)
    sharded = shard_map(
        fn, mesh=mesh, in_specs=(qspec, qspec, qspec), out_specs=qspec
    )
    with pytest.warns(UserWarning, match="counter_rotate"):
        out_shape = jax.eval_shape(sharded, q, k, v)
    assert out_shape.shape == q.shape


@pytest.mark.slow
def test_ring_counter_pallas(rng, mesh):
    """Counter-rotation through the unrolled Pallas per-hop kernels
    (static band hints engage the compact causal grid), fwd and bwd."""
    q, k, v = make_qkv(rng, hk=2)
    ref = default_attention(q, k, v, causal=True)
    out = ring_attn_global(
        q, k, v, mesh=mesh, causal=True, striped=True, bucket_size=8,
        impl="pallas", counter_rotate=True,
    )
    np.testing.assert_allclose(out, ref, atol=ATOL)

    g_ref = jax.grad(
        lambda *a: (default_attention(*a, causal=True) ** 2).sum(), (0, 1, 2)
    )(q, k, v)
    g_out = jax.grad(
        lambda *a: (
            ring_attn_global(
                *a, mesh=mesh, causal=True, striped=True, bucket_size=8,
                impl="pallas", counter_rotate=True,
            )
            ** 2
        ).sum(),
        (0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


# ----------------------------------------------------------------------
# int8 hop compression: forward KV hops ship as per-token absmax int8
# values + bitcast f32 scales in one payload; quantized once at ring
# entry, exact-dtype residuals (and f32 accumulators) in backward.
# ----------------------------------------------------------------------

# Tolerance pins for int8-compressed hops vs f32 hops on unit-variance
# inputs: ONE symmetric per-(head, token) absmax quantization costs
# ~0.4% RMS on the kv values, which bounds the output error at ~2.5e-2
# regardless of ring size — hops are lossless moves of the quantized
# payload.  Grads recompute scores from the exact residual (k, v) but
# reuse the quantized forward's (out, lse), so their error is that
# forward error propagated through the quadratic test loss: measured
# <= 1% in L2 (the meaningful number) with a <= 0.11 elementwise tail
# on grad entries of O(10).  A regression past these pins means a
# second quantization (or a lossy hop) crept into the schedule.
INT8_FWD_TOL = 2.5e-2
INT8_GRAD_REL_L2 = 1.5e-2
INT8_GRAD_MAX_ABS = 0.15


def test_ring_hop_compression_validation(rng, mesh):
    q, k, v = make_qkv(rng)
    with pytest.raises(ValueError, match="hop_compression"):
        ring_attn_global(
            q, k, v, mesh=mesh, causal=True, hop_compression="fp4"
        )


def _int8_fuzz_fns(mesh, counter, hk):
    """Built ONCE per config so repeated seeds hit jax's trace cache:
    (fwd_exact, fwd_int8, grad_exact, grad_int8) over global arrays."""
    def build(compressed):
        def fn(q, k, v):
            return ring_flash_attention(
                q, k, v, None, "seq", causal=True, bucket_size=8,
                counter_rotate=counter,
                hop_compression="int8" if compressed else None,
            )
        qspec = P("data", None, "seq", None)
        fwd = shard_map(
            fn, mesh=mesh, in_specs=(qspec, qspec, qspec), out_specs=qspec
        )
        grad = jax.grad(lambda *a: (fwd(*a) ** 2).sum(), (0, 1, 2))
        return fwd, grad

    fe, ge = build(False)
    fc, gc = build(True)
    return fe, fc, ge, gc


def _assert_int8_grad_close(g_comp, g_exact, tag):
    for a, b, name in zip(g_comp, g_exact, "qkv"):
        rel = float(np.linalg.norm(a - b) / np.linalg.norm(b))
        assert rel <= INT8_GRAD_REL_L2, (
            f"d{name} {tag}: relative L2 {rel:.4f} > {INT8_GRAD_REL_L2}"
        )
        worst = float(np.abs(np.asarray(a) - np.asarray(b)).max())
        assert worst <= INT8_GRAD_MAX_ABS, (
            f"d{name} {tag}: max abs {worst:.4f} > {INT8_GRAD_MAX_ABS}"
        )


def test_ring_int8_hop_parity_fuzz(mesh):
    """Fuzz: int8-compressed hops vs f32 hops across random draws, fwd
    AND grads, pinned tolerances.  Fast tier runs the hardest config —
    counter-rotated GQA (compression composing with the Q-pack schedule
    AND the group-summed dk/dv) — with compiled-fn reuse across seeds;
    the full {uni,counter} x {mha,gqa} sweep is the slow-tier test
    below.  The f32 (acc, m, l) accumulator contract the compression
    relies on is machine-checked right here via
    audit_accumulator_dtypes."""
    fe, fc, ge, gc = _int8_fuzz_fns(mesh, counter=True, hk=2)
    for seed in (0, 1, 2):
        rng = np.random.default_rng(seed)
        q, k, v = make_qkv(rng, hk=2)
        np.testing.assert_allclose(
            fc(q, k, v), fe(q, k, v), atol=INT8_FWD_TOL,
            err_msg=f"fwd seed={seed}",
        )
        if seed == 0:  # grads: one seed in the fast tier
            _assert_int8_grad_close(
                gc(q, k, v), ge(q, k, v), f"seed={seed}"
            )

    from ring_attention_tpu.analysis.recompile import audit_accumulator_dtypes

    assert audit_accumulator_dtypes() == []


@pytest.mark.slow
@pytest.mark.parametrize("counter", [False, True], ids=["uni", "counter"])
@pytest.mark.parametrize("hk", [4, 2], ids=["mha", "gqa"])
def test_ring_int8_hop_parity_fuzz_exhaustive(mesh, counter, hk):
    """The full config sweep with grads at every seed."""
    fe, fc, ge, gc = _int8_fuzz_fns(mesh, counter, hk)
    for seed in (0, 1, 2):
        rng = np.random.default_rng(seed)
        q, k, v = make_qkv(rng, hk=hk)
        np.testing.assert_allclose(
            fc(q, k, v), fe(q, k, v), atol=INT8_FWD_TOL,
            err_msg=f"fwd seed={seed}",
        )
        _assert_int8_grad_close(gc(q, k, v), ge(q, k, v), f"seed={seed}")


def test_ring_int8_hop_packed_segments(rng, mesh):
    """Compressed hops compose with packed segment ids (the ids ppermute
    uncompressed alongside the int8 KV handle)."""
    q, k, v = make_qkv(rng)
    n = q.shape[2]
    ids = np.zeros(n, np.int32)
    ids[64:] = 1
    seg = jnp.asarray(np.broadcast_to(ids, (2, n)).copy())

    def run(compressed):
        fn = partial(
            ring_flash_attention, axis_name="seq", causal=True,
            bucket_size=8,
            hop_compression="int8" if compressed else None,
        )
        qspec = P("data", None, "seq", None)
        return shard_map(
            lambda q, k, v, s: fn(q, k, v, None, segment_ids=s),
            mesh=mesh,
            in_specs=(qspec, qspec, qspec, P("data", "seq")),
            out_specs=qspec,
        )(q, k, v, seg)

    np.testing.assert_allclose(run(True), run(False), atol=INT8_FWD_TOL)


# ----------------------------------------------------------------------
# Rotation-elision pins: size-1 axes and None payloads never ppermute
# ----------------------------------------------------------------------


def _ring_ppermute_count(mesh, with_seg=False, **kw):
    """Traced ppermute count (scan-multiplied) of one forward call."""
    from ring_attention_tpu.analysis.contracts import jaxpr_collectives

    ring = mesh.shape["seq"]
    b = mesh.shape["data"]
    n = 16 * ring
    q = jnp.zeros((b, 4, n, 8), jnp.float32)
    seg = jnp.zeros((b, n), jnp.int32) if with_seg else None

    def fn(q, k, v, s):
        return ring_flash_attention(
            q, k, v, None, "seq", causal=True, bucket_size=8,
            segment_ids=s, **kw,
        )

    qspec = P("data", None, "seq", None)
    sspec = P("data", "seq") if with_seg else P()
    sharded = shard_map(
        fn, mesh=mesh, in_specs=(qspec, qspec, qspec, sspec),
        out_specs=qspec,
    )
    jc = jaxpr_collectives(jax.make_jaxpr(sharded)(q, q, q, seg))
    return jc.counts.get("ppermute", 0)


@pytest.mark.parametrize(
    "kw",
    [{}, {"bidirectional": True}, {"counter_rotate": True},
     {"counter_rotate": True, "hop_compression": "int8"}],
    ids=["uni", "bidi", "counter", "counter_int8"],
)
def test_ring_size1_axis_elides_every_rotation(kw):
    """A size-1 seq axis (degenerate hybrid factorings) must trace ZERO
    ppermutes in every stream scheme — identity rotations are real
    collectives on some backends, so they are elided at trace time."""
    mesh = create_mesh(ring_size=1, data_size=8)
    assert _ring_ppermute_count(mesh, **kw) == 0


def test_ring_none_payloads_never_rotate(mesh):
    """kv_mask=None / segment_ids=None must not enter the rotation state:
    an unpacked, unmasked hop ppermutes exactly its KV handle (packed
    calls add one segment-id stream; the counter schedule splits the same
    totals across its Q and KV streams)."""
    base = _ring_ppermute_count(mesh)
    packed = _ring_ppermute_count(mesh, with_seg=True)
    assert base == 8  # 8 KV rotations (scan-traced), nothing else
    assert packed == 2 * base  # + one segment-id payload per rotation
    ctr = _ring_ppermute_count(mesh, counter_rotate=True)
    ctr_packed = _ring_ppermute_count(mesh, with_seg=True,
                                      counter_rotate=True)
    assert ctr_packed == 2 * ctr - 1  # ids ride both streams, not catch-up
