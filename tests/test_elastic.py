"""Chaos matrix for the elastic runtime (docs/resilience.md).

The acceptance property is *kill-anywhere safety*: a process killed at
any injected point — mid-step, mid-shard-write, with a complete staging
dir but no commit, mid-resume — comes back from a valid checkpoint with
no torn state, and a resume at a CHANGED device count (4 -> 2 and
2 -> 4) reproduces the uninterrupted run's loss trajectory within
:data:`TOL` (same-world resumes restore params bit-exactly; cross-world
differences are reduction-order noise, measured ~2e-7 on this suite's
model).  All of it on CPU virtual devices, with real OS processes dying
real deaths (``tests/elastic_worker.py`` + ``elastic/chaos.py``).

Plus the in-process halves: PreemptionGuard drain under a real SIGTERM
and under the fault injector, async-save double buffering and error
propagation, the ``on_step_end`` hook HLO pin, the wedge-simulation
delay tap, and the hardened bench probe's kill path.
"""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ring_attention_tpu.elastic import (
    AsyncSaveError,
    ElasticCheckpointManager,
    PreemptionGuard,
    chaos,
)
from ring_attention_tpu.utils import make_train_step
from ring_attention_tpu.utils import resilience

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "elastic_worker.py")

# loss-trajectory parity tolerance across a re-mesh resume (documented
# in docs/resilience.md): params restore bit-exactly, so the only drift
# is reduction order at the new mesh factoring
TOL = 1e-4


def _run_worker(ckpt_dir, loss_log, *, devices, steps=8, chaos_faults=None,
                sync=False, timeout=280):
    w = chaos.ChaosWorker(
        [sys.executable, WORKER, "--ckpt-dir", str(ckpt_dir),
         "--loss-log", str(loss_log), "--steps", str(steps)]
        + (["--sync-save"] if sync else []),
        cwd=REPO, timeout=timeout,
    )
    return w.run(devices=devices, chaos=chaos_faults)


def _read_log(path) -> dict[int, float]:
    out: dict[int, float] = {}
    try:
        with open(path) as f:
            for line in f:
                if line.strip():
                    row = json.loads(line)
                    out[row["step"]] = row["loss"]
    except FileNotFoundError:
        pass
    return out


def _committed_steps(ckpt_dir) -> list[int]:
    return ElasticCheckpointManager(ckpt_dir).all_steps()


@pytest.fixture(scope="module")
def baseline4(tmp_path_factory):
    """Uninterrupted 8-step run at world 4: the parity reference."""
    d = tmp_path_factory.mktemp("elastic_baseline")
    log = d / "loss.jsonl"
    r = _run_worker(d / "ck", log, devices=4, steps=8)
    assert r.returncode == 0, r.stdout + r.stderr
    losses = _read_log(log)
    assert sorted(losses) == list(range(8)), losses
    return losses


# ----------------------------------------------------------------------
# The kill-anywhere matrix (real process deaths, subprocess worker)
# ----------------------------------------------------------------------


def test_kill_anywhere_matrix_then_remesh_4_to_2(tmp_path, baseline4):
    """One checkpoint directory survives four consecutive violent deaths
    — mid-step, mid-shard-write, staged-but-uncommitted, mid-resume —
    and the fifth run finishes at HALF the device count with the
    baseline's loss trajectory."""
    ck, log = tmp_path / "ck", tmp_path / "loss.jsonl"

    # (1) die mid-run at step 3, after step 0's checkpoint committed
    r = _run_worker(ck, log, devices=4, sync=True,
                    chaos_faults={chaos.KILL_AT_STEP: 3})
    assert r.returncode == chaos.CHAOS_EXIT_CODE, r.stdout + r.stderr
    assert _committed_steps(ck) == [0]

    # (2) die mid-shard-write: some shard files durable, no manifest —
    # the step must NOT become visible, step 0 stays the resume point
    r = _run_worker(ck, log, devices=4, sync=True,
                    chaos_faults=[chaos.KILL_MID_SHARD])
    assert r.returncode == chaos.CHAOS_EXIT_CODE, r.stdout + r.stderr
    assert _committed_steps(ck) == [0], (
        "a torn save leaked into the committed steps"
    )
    assert any(".writing-" in n for n in os.listdir(ck)), (
        "expected the dead writer's staging debris"
    )

    # (3) die with the staging dir COMPLETE (manifest written) but the
    # commit rename not executed: still not a committed checkpoint
    r = _run_worker(ck, log, devices=4, sync=True,
                    chaos_faults=[chaos.KILL_PRE_COMMIT])
    assert r.returncode == chaos.CHAOS_EXIT_CODE, r.stdout + r.stderr
    assert _committed_steps(ck) == [0]

    # (4) die mid-resume: restore is read-only — the checkpoint must
    # survive a killed reader fully intact
    r = _run_worker(ck, log, devices=4, sync=True,
                    chaos_faults=[chaos.KILL_MID_RESUME])
    assert r.returncode == chaos.CHAOS_EXIT_CODE, r.stdout + r.stderr
    assert _committed_steps(ck) == [0]

    # (5) come back at HALF the world and finish; every step any run
    # logged must match the uninterrupted baseline (re-executed steps
    # restore bit-exact params; world-2 steps differ only by reduction
    # order).  The staging debris from (2)/(3) is swept by the saves.
    r = _run_worker(ck, log, devices=2)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "re-mesh: ring 4 -> 2" in r.stdout
    assert "re-mesh resume" in r.stdout
    losses = _read_log(log)
    assert sorted(losses) == list(range(8))
    for step, loss in losses.items():
        assert abs(loss - baseline4[step]) < TOL, (
            f"step {step}: {loss} vs baseline {baseline4[step]}"
        )
    assert not any(".writing-" in n for n in os.listdir(ck)), (
        "staging debris survived the post-resume saves"
    )


def test_remesh_2_to_4_matches_baseline(tmp_path, baseline4):
    """Grow the world mid-run: 4 steps at world 2, then resume at world
    4 — the full trajectory still matches the world-4 baseline."""
    ck, log = tmp_path / "ck", tmp_path / "loss.jsonl"
    r = _run_worker(ck, log, devices=2, steps=4)
    assert r.returncode == 0, r.stdout + r.stderr
    r = _run_worker(ck, log, devices=4, steps=8)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "re-mesh: ring 2 -> 4" in r.stdout
    losses = _read_log(log)
    assert sorted(losses) == list(range(8))
    for step, loss in losses.items():
        assert abs(loss - baseline4[step]) < TOL, (
            f"step {step}: {loss} vs baseline {baseline4[step]}"
        )


def test_sigterm_drain_end_to_end(tmp_path):
    """A real SIGTERM mid-run: the worker finishes its in-flight step,
    saves synchronously, reports the drain, and exits 0; the checkpoint
    holds the drained step."""
    ck, log = tmp_path / "ck", tmp_path / "loss.jsonl"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["RING_ATTN_CHAOS_DEVICES"] = "4"
    proc = subprocess.Popen(
        [sys.executable, WORKER, "--ckpt-dir", str(ck),
         "--loss-log", str(log), "--steps", "2000",
         "--save-every", "100000"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO,
    )
    try:
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if len(_read_log(log)) >= 3:  # compiled and stepping
                break
            if proc.poll() is not None:
                break
            time.sleep(0.05)
        assert proc.poll() is None, proc.communicate()[0]
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, out
    assert "DRAINED SIGTERM step=" in out, out
    drained = int(out.split("DRAINED SIGTERM step=")[1].split()[0])
    steps = _committed_steps(ck)
    assert drained in steps, (drained, steps, out)
    # and the drained checkpoint actually resumes one step later
    r = _run_worker(ck, log, devices=4, steps=drained + 2)
    assert r.returncode == 0, r.stdout + r.stderr
    assert f"ELASTIC-OK start={drained + 1}" in r.stdout, r.stdout


# ----------------------------------------------------------------------
# PreemptionGuard, in process
# ----------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _clean_faults():
    resilience.get_injector().clear()
    yield
    resilience.get_injector().clear()


def test_preemption_guard_fault_injector_drain(tmp_path, devices):
    """The signal-free chaos path: arming PREEMPT_FAULT trips
    should_stop, and drain saves + dumps a 'preemption' incident with
    the run's trajectory attached."""
    from ring_attention_tpu.elastic.preemption import PREEMPT_FAULT
    from ring_attention_tpu.utils import FlightRecorder, read_flight_dump

    recorder = FlightRecorder(str(tmp_path / "flight"), window=8)
    recorder.record(1, loss=2.0)
    recorder.record(2, loss=1.5)
    saved = []
    with PreemptionGuard() as guard:
        assert not guard.should_stop()
        with resilience.inject(PREEMPT_FAULT):
            assert guard.should_stop()
            guard.drain(lambda: saved.append(True), recorder=recorder,
                        step=2)
    assert saved == [True]
    assert guard.signal_name == "injected"
    assert len(recorder.dumps) == 1
    dump = read_flight_dump(recorder.dumps[0])
    assert dump["trigger"]["kind"] == "preemption"
    assert dump["trigger"]["step"] == 2
    assert [r["loss"] for r in dump["rows"]] == [2.0, 1.5]


def test_preemption_guard_real_signal_and_escalation():
    with PreemptionGuard() as guard:
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.05)  # let the handler run at a bytecode boundary
        assert guard.requested and guard.signal_name == "SIGTERM"
        # a second signal during the drain escalates to KeyboardInterrupt
        with pytest.raises(KeyboardInterrupt, match="second SIGTERM"):
            os.kill(os.getpid(), signal.SIGTERM)
            time.sleep(1.0)
    # handlers restored: a guard-less process keeps default behavior
    assert signal.getsignal(signal.SIGTERM) is not guard._handler


def test_preemption_guard_drain_is_idempotent_and_save_first(tmp_path):
    calls = []
    guard = PreemptionGuard()
    guard.drain(lambda: calls.append("save"))
    guard.drain(lambda: calls.append("save"))
    assert calls == ["save"]  # latched


# ----------------------------------------------------------------------
# Async saves: double buffering + error propagation
# ----------------------------------------------------------------------


def _mesh(n):
    from ring_attention_tpu.parallel.mesh import create_mesh

    return create_mesh(ring_size=n, devices=jax.devices()[:n])


def _sharded_state(mesh, scale=1.0):
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = jnp.arange(64.0).reshape(4, 16) * scale
    return {
        "x": jax.device_put(x, NamedSharding(mesh, P(None, "seq"))),
        "step": jnp.asarray(7, jnp.int32),
    }


def test_async_save_returns_before_write_and_snapshot_is_isolated(tmp_path):
    """save() must return after the host snapshot, not the file write —
    and the snapshot must be insulated from later mutation of the live
    state (the double-buffer contract donated buffers rely on)."""
    import threading

    mesh = _mesh(4)
    state = _sharded_state(mesh, scale=1.0)
    mgr = ElasticCheckpointManager(str(tmp_path), async_save=True)
    gate = threading.Event()
    real_write = mgr._write

    def slow_write(step, snap):
        assert gate.wait(timeout=60)
        return real_write(step, snap)

    mgr._write = slow_write
    t0 = time.monotonic()
    mgr.save(5, state)
    assert mgr.all_steps() == []  # returned while the write is gated
    assert time.monotonic() - t0 < 30
    gate.set()
    mgr.wait()
    assert mgr.all_steps() == [5]
    restored = mgr.restore(_sharded_state(mesh, 0.0), mesh=mesh)
    np.testing.assert_array_equal(
        np.asarray(restored[0]["x"]), np.arange(64.0).reshape(4, 16)
    )


def test_async_save_error_surfaces_on_next_call(tmp_path):
    mesh = _mesh(2)
    mgr = ElasticCheckpointManager(str(tmp_path), async_save=True)

    def boom(step, snap):
        raise OSError("disk full")

    mgr._write = boom
    mgr.save(1, _sharded_state(mesh))
    with pytest.raises(AsyncSaveError, match="disk full"):
        mgr.wait()
    # the error is consumed: the manager stays usable
    mgr.wait()


def test_elastic_contract_suite_is_clean():
    """The --elastic CLI checks, in-process: manifest round-trip,
    resharded == direct load, corrupt-shard fallback, debris sweep."""
    from ring_attention_tpu.elastic import run_elastic_suite

    for name, violations in run_elastic_suite():
        assert not violations, f"{name}: {violations}"


def test_elastic_explicit_corrupt_step_raises(tmp_path):
    """restore(step=N) on a corrupt elastic step raises instead of
    returning None (which callers read as 'cold start')."""
    from ring_attention_tpu.utils.checkpoint import CheckpointCorruptError

    mesh = _mesh(2)
    mgr = ElasticCheckpointManager(str(tmp_path), async_save=False)
    mgr.save(3, _sharded_state(mesh))
    step3 = mgr._step_dir(3)
    shard = sorted(n for n in os.listdir(step3)
                   if n.startswith("shard_"))[0]
    chaos.corrupt_file(os.path.join(step3, shard), "truncate")
    with pytest.raises(CheckpointCorruptError):
        mgr.restore(_sharded_state(mesh), mesh=mesh, step=3)


def test_corrupted_shard_garbage_falls_back(tmp_path):
    """Bit-rot (not just truncation) in a shard file fails the digest
    and falls back to the previous step."""
    mesh = _mesh(4)
    mgr = ElasticCheckpointManager(str(tmp_path), async_save=False)
    mgr.save(1, _sharded_state(mesh, 1.0))
    mgr.save(2, _sharded_state(mesh, 2.0))
    step2 = mgr._step_dir(2)
    shard = sorted(n for n in os.listdir(step2)
                   if n.startswith("shard_"))[0]
    chaos.corrupt_file(os.path.join(step2, shard), "garbage")
    with pytest.warns(UserWarning, match="corrupt"):
        restored = mgr.restore(_sharded_state(mesh, 0.0), mesh=mesh)
    assert restored is not None and restored[1] == 1
    np.testing.assert_array_equal(
        np.asarray(restored[0]["x"]), np.arange(64.0).reshape(4, 16)
    )


# ----------------------------------------------------------------------
# Re-mesh planning + divisibility diagnostics
# ----------------------------------------------------------------------


def test_remesh_plan_preserves_data_and_ulysses():
    from ring_attention_tpu.parallel import remesh_plan

    old = {"axes": ["data", "ring", "ulysses"], "shape": [2, 4, 2]}
    plan, diags = remesh_plan(old, 8)
    assert plan == {"ring_size": 2, "data_size": 2, "ulysses_size": 2}
    assert any("world 16 -> 8" in d for d in diags)
    assert any("ring 4 -> 2" in d for d in diags)
    # same world: no diagnostics, same factoring
    plan, diags = remesh_plan(old, 16)
    assert plan == {"ring_size": 4, "data_size": 2, "ulysses_size": 2}
    assert diags == []
    # data no longer divides: shrink to gcd, say so
    plan, diags = remesh_plan(
        {"axes": ["data", "seq"], "shape": [4, 2]}, 2
    )
    assert plan["data_size"] == 2 and plan["ring_size"] == 1
    assert any("does not divide" in d for d in diags)


def test_remesh_plan_dcn_tier():
    """The pod-scale re-mesh rules: the dcn tier tracks the CURRENT
    process count when given (dropping to 1 removes the axis), is
    preserved-while-dividing otherwise, and divisibility violations are
    one-line errors."""
    from ring_attention_tpu.parallel import remesh_plan

    old = {"axes": ["dcn_data", "data", "seq"], "shape": [2, 1, 4]}
    # lost a host: re-plan at 1 process, half the world — dcn drops
    plan, diags = remesh_plan(old, 4, dcn_data_size=1)
    assert plan == {"ring_size": 4, "data_size": 1}
    assert any("dcn_data 2 -> 1 (process count changed)" in d
               for d in diags)
    # same cluster shape: same factoring, no diagnostics
    plan, diags = remesh_plan(old, 8, dcn_data_size=2)
    assert plan == {"ring_size": 4, "data_size": 1, "dcn_data_size": 2}
    assert diags == []
    # no process count given: dcn preserved while it divides
    plan, _ = remesh_plan(old, 16)
    assert plan["dcn_data_size"] == 2 and plan["ring_size"] == 8
    # grew the pod: 1 -> 2 processes over a flat checkpoint
    plan, diags = remesh_plan(
        {"axes": ["data", "seq"], "shape": [1, 4]}, 4, dcn_data_size=2
    )
    assert plan == {"ring_size": 2, "data_size": 1, "dcn_data_size": 2}
    assert any("dcn_data 1 -> 2" in d for d in diags)
    # indivisible process count is a one-line error
    with pytest.raises(ValueError, match="dcn_data_size 3"):
        remesh_plan(old, 8, dcn_data_size=3)


def test_create_mesh_dcn_shape_and_validation(devices):
    """The hierarchical mesh: dcn_data outermost, inner axes unchanged,
    divisibility violations one-line."""
    from ring_attention_tpu.parallel import (
        create_mesh,
        data_partition,
        data_world,
        has_dcn,
        mesh_descriptor,
        seq_world,
    )

    mesh = create_mesh(dcn_data_size=2, ring_size=2, data_size=2)
    assert tuple(mesh.axis_names) == ("dcn_data", "data", "seq")
    assert dict(mesh.shape) == {"dcn_data": 2, "data": 2, "seq": 2}
    assert has_dcn(mesh) and data_partition(mesh) == ("dcn_data", "data")
    assert data_world(mesh) == 4 and seq_world(mesh) == 2
    assert mesh_descriptor(mesh)["axes"] == ["dcn_data", "data", "seq"]
    factored = create_mesh(dcn_data_size=2, ring_size=2, ulysses_size=2)
    assert tuple(factored.axis_names) == (
        "dcn_data", "data", "ring", "ulysses"
    )
    # flat meshes are unchanged by the new axis machinery
    flat = _mesh(4)
    assert not has_dcn(flat) and data_partition(flat) == "data"
    with pytest.raises(ValueError, match="dcn_data_size 3"):
        create_mesh(dcn_data_size=3)


def test_validate_seq_len_one_line_diagnostic(devices):
    from ring_attention_tpu.parallel import validate_seq_len

    mesh = _mesh(4)
    validate_seq_len(64, mesh)  # divisible: fine
    with pytest.raises(ValueError, match=r"seq_len 66 % sequence world 4"):
        validate_seq_len(66, mesh)


# ----------------------------------------------------------------------
# on_step_end hook
# ----------------------------------------------------------------------


def _tiny_problem():
    def loss_fn(p, x):
        return jnp.sum((p["w"] * x - 1.0) ** 2)

    params = {"w": jnp.arange(1.0, 5.0)}
    opt = optax.sgd(1e-2)
    return loss_fn, params, opt


def test_on_step_end_unset_is_strict_noop():
    loss_fn, params, opt = _tiny_problem()
    step = make_train_step(loss_fn, opt)
    assert not hasattr(step, "__wrapped__")  # the same bare callable


def test_on_step_end_fires_with_outputs():
    loss_fn, params, opt = _tiny_problem()
    seen = []
    step = make_train_step(loss_fn, opt, on_step_end=seen.append)
    out = step(params, opt.init(params), jnp.ones(4))
    assert len(seen) == 1 and seen[0] is out
    assert len(out) == 3  # (params, opt_state, loss) handed over intact


def test_on_step_end_rejects_outer_jit_instead_of_silently_dropping():
    """jitting the HOOKED wrapper would bake the host hook away at trace
    time (it would fire once, on tracers, then never again) — the
    wrapper must refuse loudly and point at the supported patterns."""
    loss_fn, params, opt = _tiny_problem()
    hooked = make_train_step(loss_fn, opt, on_step_end=lambda out: None)
    jitted = jax.jit(hooked)
    with pytest.raises(RuntimeError, match="__wrapped__|jit_donate"):
        jitted(params, opt.init(params), jnp.ones(4))
    # the supported patterns still work (donating call LAST: it deletes
    # the donated params/opt_state buffers)
    jax.jit(hooked.__wrapped__)(params, opt.init(params), jnp.ones(4))
    make_train_step(
        loss_fn, opt, jit_donate=True, on_step_end=lambda out: None
    )(params, opt.init(params), jnp.ones(4))


def test_on_step_end_adds_zero_collectives(rng, devices):
    """The HLO pin: the hook's inner (lowerable) step compiles to the
    IDENTICAL collective sequence as the hookless step — the hook lives
    entirely outside the compiled program."""
    from ring_attention_tpu import RingTransformer, create_mesh
    from ring_attention_tpu.analysis.contracts import hlo_collective_sequence

    mesh = create_mesh(ring_size=4)
    model = RingTransformer(
        num_tokens=64, dim=32, depth=1, heads=4, dim_head=8, causal=True,
        striped=True, bucket_size=8, mesh=mesh, use_ring=True,
    )
    toks = jnp.asarray(rng.integers(0, 64, (2, 64)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks, return_loss=True)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    def loss_fn(p, t):
        return model.apply(p, t, return_loss=True)

    plain = make_train_step(loss_fn, opt)
    hooked = make_train_step(loss_fn, opt, on_step_end=lambda out: None)
    args = (params, opt_state, toks)
    txt_plain = jax.jit(plain).lower(*args).compile().as_text()
    txt_hooked = jax.jit(hooked.__wrapped__).lower(*args).compile().as_text()
    seq_plain = hlo_collective_sequence(txt_plain)
    seq_hooked = hlo_collective_sequence(txt_hooked)
    assert seq_plain, "expected ring collectives in the train step"
    assert seq_hooked == seq_plain


# ----------------------------------------------------------------------
# Wedge simulation: injected delay + the hardened bench probe
# ----------------------------------------------------------------------


def test_delay_tap_simulates_hung_step():
    """The SAME compiled step runs fast when disarmed and stalls for the
    armed delay — and a with_retries deadline cuts the stall off, the
    way the bench probe ladder handles a real wedge."""
    @jax.jit
    def step(x):
        return jnp.sum(chaos.delay_tap(x, "hang_collective"))

    x = jnp.ones(16)
    float(step(x))  # compile, disarmed
    t0 = time.monotonic()
    float(step(x))
    assert time.monotonic() - t0 < 0.2
    with resilience.inject("hang_collective", 0.6):
        t0 = time.monotonic()
        float(step(x))
        assert time.monotonic() - t0 >= 0.5
    # keep the armed hang short: inject()'s exit drains pending jax
    # callbacks (effects_barrier), so the abandoned sleeper still runs
    # to completion before the block closes
    with resilience.inject("hang_collective", 2.0):
        with pytest.raises(resilience.RetryError):
            resilience.with_retries(
                lambda: float(step(x)),
                timeout=0.3, max_attempts=1, backoff=0.0,
            )


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "_bench_under_test", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def test_bench_probe_hard_deadline_kills_wedged_child(tmp_path,
                                                      monkeypatch):
    """A wedged probe child (simulated sleep) is killed at the hard
    deadline: one timeout, not a hung round — and the failure lands as
    a structured probe_failure row with killed=true plus a wedge-streak
    count."""
    bench = _load_bench()
    monkeypatch.setenv("BENCH_PROBE_WEDGE_S", "30")
    monkeypatch.setenv("BENCH_PROBE_DEADLINE_S", "1")
    monkeypatch.setenv("BENCH_PROBE_BACKOFF_S", "0")
    monkeypatch.setenv("BENCH_PROBE_ATTEMPTS", "1")
    hwlog = tmp_path / "results.jsonl"
    monkeypatch.setenv("BENCH_HWLOG", str(hwlog))
    t0 = time.monotonic()
    probe = bench._run_probe()
    elapsed = time.monotonic() - t0
    assert elapsed < 15, f"wedged probe cost {elapsed:.1f}s, not ~1s"
    assert probe == {
        "ok": False, "killed": True,
        "error": probe["error"],
    } and "hard deadline" in probe["error"]
    bench._log_probe_failure(probe)
    bench._log_probe_failure(probe)
    rows = [json.loads(line) for line in open(hwlog)]
    assert all(r["step"] == "probe_failure" for r in rows)
    assert all(r["result"]["killed"] is True for r in rows)
    assert bench._wedge_streak(str(hwlog)) == 2
    # a measured row resets the streak
    with open(hwlog, "a") as f:
        f.write(json.dumps(
            {"step": "fwd262k", "result": {"value": 69.7}}
        ) + "\n")
    assert bench._wedge_streak(str(hwlog)) == 0


def test_bench_probe_healthy_path_still_passes(monkeypatch):
    bench = _load_bench()
    monkeypatch.delenv("BENCH_PROBE_WEDGE_S", raising=False)
    monkeypatch.setenv("BENCH_PROBE_DEADLINE_S", "120")
    monkeypatch.setenv("BENCH_PROBE_ATTEMPTS", "1")
    assert bench._run_probe() == {"ok": True}


# ----------------------------------------------------------------------
# Watchdog: a wedged step becomes a bounded abort (in-process half; the
# spawned-cluster pin lives in tests/test_multihost.py)
# ----------------------------------------------------------------------


def test_watchdog_fires_after_deadline_with_incident(tmp_path):
    """A heartbeat that goes stale past the deadline fires the abort
    exactly once, with the stalled step named in the message AND in a
    ``watchdog_abort`` flight incident — the conversion that turns an
    eternal hang into a restartable death."""
    from ring_attention_tpu.elastic import Watchdog
    from ring_attention_tpu.utils import FlightRecorder, read_flight_dump

    recorder = FlightRecorder(str(tmp_path), window=4)
    fired = []
    dog = Watchdog(0.3, recorder=recorder, abort=fired.append,
                   poll_s=0.05)
    with dog:
        dog.beat(7)
        deadline = time.monotonic() + 10
        while not fired and time.monotonic() < deadline:
            time.sleep(0.05)
    assert dog.fired and len(fired) == 1, fired
    assert "watchdog: no heartbeat" in fired[0]
    assert "step 7" in fired[0]
    dumps = sorted(os.listdir(tmp_path))
    assert dumps, "watchdog fired without dumping the incident"
    dump = read_flight_dump(os.path.join(tmp_path, dumps[-1]))
    assert dump["trigger"]["kind"] == "watchdog_abort"
    assert dump["trigger"]["step"] == 7
    assert dump["trigger"]["deadline_s"] == 0.3


def test_watchdog_not_armed_before_first_beat_and_beats_reset():
    """No abort before the first beat (the compile window is legal), and
    regular beats keep the clock fresh forever."""
    from ring_attention_tpu.elastic import Watchdog

    fired = []
    with Watchdog(0.25, abort=fired.append, poll_s=0.05) as dog:
        time.sleep(0.6)          # unarmed: way past the deadline
        assert not fired and not dog.fired
        for step in range(8):    # armed, but never stale
            dog.beat(step)
            time.sleep(0.05)
        assert not fired
    with pytest.raises(ValueError, match="deadline_s"):
        Watchdog(0.0)


def test_watchdog_exit_code_is_distinct():
    """114 collides with nothing the harness already distinguishes:
    success, crash, and the chaos kill code."""
    from ring_attention_tpu.elastic import WATCHDOG_EXIT_CODE

    assert WATCHDOG_EXIT_CODE not in (0, 1, chaos.CHAOS_EXIT_CODE)


# ----------------------------------------------------------------------
# Cluster-wide drain + cross-process barrier: single-process halves
# (the live two-process forms run in tests/test_multihost.py)
# ----------------------------------------------------------------------


def test_broadcast_drain_single_process_is_identity():
    from ring_attention_tpu.elastic import broadcast_drain

    assert broadcast_drain(False) is False
    assert broadcast_drain(True) is True


def test_should_stop_cluster_drains_and_thins(tmp_path):
    """``should_stop_cluster`` sees the injector-driven preemption like
    ``should_stop`` does, and the ``every`` thinning defers the check to
    aligned boundaries only — the alignment that keeps every process's
    broadcast schedule identical."""
    from ring_attention_tpu.elastic import PREEMPT_FAULT

    with PreemptionGuard() as guard:
        assert guard.should_stop_cluster(step=0) is False
        with resilience.inject(PREEMPT_FAULT):
            assert guard.should_stop()  # latch the injected drain
        # thinned: step 3 is not a multiple of every=4
        assert guard.should_stop_cluster(every=4, step=3) is False
        assert guard.should_stop_cluster(every=4, step=4) is True
        assert guard.should_stop_cluster(step=5) is True


def test_cross_process_barrier_single_process_noop():
    from ring_attention_tpu.elastic import cross_process_barrier

    t0 = time.monotonic()
    cross_process_barrier("test:solo", timeout_s=0.1)
    assert time.monotonic() - t0 < 0.1


# ----------------------------------------------------------------------
# ZeRO-1 optimizer-state sharding (the in-step knob; the base helper is
# pinned in tests/test_utils.py)
# ----------------------------------------------------------------------


def test_shard_opt_state_knob_shards_moments_and_matches(rng, devices):
    """``make_train_step(shard_opt_state=True)``: the returned Adam
    moments carry a data-axis sharding (both tiers on a hierarchical
    mesh), values match the unsharded step bit-for-bit on CPU, the
    donation/offload audits cover the composed program, and the analytic
    memory model divides the moment bytes."""
    import optax

    from ring_attention_tpu.analysis import (
        audit_donation,
        audit_host_offload,
    )
    from ring_attention_tpu.parallel import create_mesh, data_partition
    from ring_attention_tpu.utils import train_memory_estimate
    from ring_attention_tpu.utils.train import shard_optimizer_state

    mesh = create_mesh(dcn_data_size=2, ring_size=2, data_size=2)
    assert data_partition(mesh) == ("dcn_data", "data")
    w = {"w": jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)}
    x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)

    def loss_fn(params, x):
        return jnp.mean((x @ params["w"]) ** 2)

    opt = optax.adam(1e-2)
    with pytest.raises(ValueError, match="shard_mesh"):
        make_train_step(loss_fn, opt, shard_opt_state=True)
    plain = jax.jit(make_train_step(loss_fn, opt))
    step = make_train_step(loss_fn, opt, shard_opt_state=True,
                           shard_mesh=mesh, jit_donate=True)

    state0 = shard_optimizer_state(
        opt.init(w), mesh, axis=data_partition(mesh)
    )
    p1, s1, l1 = step(w, state0, x)
    mu = s1[0].mu["w"]
    assert "dcn_data" in str(mu.sharding.spec) and "data" in str(
        mu.sharding.spec
    ), mu.sharding
    # the constraint never changes semantics (the partitioned program
    # may re-associate reductions: tolerance, not bit-equality)
    p0, s0, l0 = plain(w, opt.init(w), x)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(p0["w"]), np.asarray(p1["w"]), atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(s0[0].mu["w"]), np.asarray(mu), atol=1e-6
    )
    assert audit_donation(step, w, state0, x, label="zero1") == []
    assert audit_host_offload(step, w, state0, x, label="zero1") == []

    n_params = 1_000_000
    kw = dict(n_params=n_params, batch=1, seq_len=4096, dim=256,
              heads=8, depth=4, vocab=256)
    base = train_memory_estimate(**kw)
    div = train_memory_estimate(**kw, shard_opt_data=4)
    # Adam moments (2x f32) divide 4-ways; everything else is untouched
    moments = 2 * n_params * 4
    assert base["params_bytes"] - div["params_bytes"] == (
        moments - moments // 4
    )
    assert div["peak_hbm_bytes"] < base["peak_hbm_bytes"]
