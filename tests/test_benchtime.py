"""utils.benchtime: chained timing must produce sane, positive numbers."""

import jax
import jax.numpy as jnp
import pytest

from ring_attention_tpu.utils.benchtime import fetch_rtt, timed_chained


def test_fetch_rtt_positive():
    rtt = fetch_rtt(samples=2)
    assert 0 < rtt < 60


def test_timed_chained_measures_work():
    iters = 4

    @jax.jit
    def chained(x):
        def body(c, _):
            c = jnp.tanh(c @ c) + c
            return c, c[0, 0]
        _, ys = jax.lax.scan(body, x, None, length=iters)
        return ys.sum()

    x = jnp.eye(512) * 0.1
    compile_s, per_iter = timed_chained(chained, (x,), iters)
    assert compile_s >= 0
    assert per_iter > 0


def test_timed_chained_rejects_sub_rtt_measurement(monkeypatch):
    import ring_attention_tpu.utils.benchtime as bt

    monkeypatch.setattr(bt, "fetch_rtt", lambda samples=3: 1e6)

    @jax.jit
    def trivial(x):
        return x + 1

    with pytest.raises(RuntimeError, match="RTT"):
        bt.timed_chained(trivial, (jnp.float32(1),), iters=1)
