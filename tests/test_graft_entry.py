"""The driver contract: entry() compiles, dryrun_multichip() runs a step.

These are the integration points an external harness exercises; breaking
them silently would cost a whole round.
"""

import os
import sys

import jax
import jax.numpy as jnp
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def test_entry_compiles():
    import __graft_entry__ as g

    fn, args = g.entry()
    loss = jax.jit(fn)(*args)
    assert bool(jnp.isfinite(loss))


@pytest.mark.slow
def test_dryrun_multichip_8():
    import __graft_entry__ as g

    g.dryrun_multichip(8)  # raises on any failure


@pytest.fixture(scope="module")
def bench_records():
    """All three bench worker modes measured in ONE subprocess (a fresh
    jax import per mode would triple the fixed cost on this 1-CPU image)."""
    import json
    import subprocess

    bench_path = os.path.join(REPO_ROOT, "bench.py")
    lines = [
        "import json, sys, traceback",
        "import jax; jax.config.update('jax_platforms', 'cpu')",
    ]
    # per-mode try/except so one mode's crash still reports the others
    for mode, impl in (
        ("fwd", "xla"), ("fwdbwd", "xla"), ("train", "xla"),
        ("decode", "pallas"), ("hybrid", "pallas"),
    ):
        argv = ["bench.py", "--worker", impl, "1024", mode]
        lines += [
            "try:",
            f"    sys.argv = {argv!r}",
            f"    exec(open({bench_path!r}).read())",
            "except Exception:",
            f"    print(json.dumps({{'mode_error': {mode!r},"
            " 'tb': traceback.format_exc()[-400:]}))",
        ]
    env = dict(
        os.environ,
        JAX_COMPILATION_CACHE_DIR=os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".jax_cache"
        ),
    )
    proc = subprocess.run(
        [sys.executable, "-c", "\n".join(lines)], capture_output=True,
        text=True, timeout=1200, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-500:]
    recs = [
        json.loads(ln) for ln in proc.stdout.strip().splitlines()
        if ln.startswith("{")
    ]
    assert len(recs) == 5, proc.stdout[-500:]
    return dict(zip(("fwd", "fwdbwd", "train", "decode", "hybrid"), recs))


@pytest.mark.slow
def test_bench_worker_contract(bench_records):
    """bench.py --worker prints one parseable JSON measurement line, with
    compile time recorded separately from step time."""
    rec = bench_records["fwd"]
    assert {"value", "vs_baseline", "seq_len", "impl", "compile_s"} <= set(rec)


@pytest.mark.slow
def test_bench_worker_fwdbwd(bench_records):
    """Backward-included attention timing (the other half of the
    north-star: BASELINE.md wants fwd AND training-relevant numbers)."""
    rec = bench_records["fwdbwd"]
    assert rec["value"] > 0 and rec["ms_per_step"] > 0


@pytest.mark.slow
def test_bench_worker_decode(bench_records):
    """Million-token-decode mode (here at 1024): ms/token + effective
    KV-read bandwidth via the decode kernel (interpret mode on CPU)."""
    rec = bench_records["decode"]
    assert rec["decode_ms_per_token"] > 0 and rec["decode_kv_gbps"] > 0
    assert rec["decode_impl"] == "pallas"


@pytest.mark.slow
def test_bench_worker_hybrid(bench_records):
    """Hybrid Ulysses x Ring hop-sequence mode: the hybrid262k entry's
    worker must report the shortened hop chain next to tokens/sec."""
    rec = bench_records["hybrid"]
    assert rec["impl"] == "pallas-hybrid"
    assert rec["ulysses"] == 2 and rec["ring"] == 2
    assert rec["hops"] == 1 and rec["pure_ring_hops"] == 3
    assert rec["tokens_per_sec"] > 0


@pytest.mark.slow
def test_bench_worker_train(bench_records):
    """Train-step (fwd+bwd+adam) tokens/sec measurement."""
    rec = bench_records["train"]
    assert rec["tokens_per_sec"] > 0
    assert rec["train_seq_len"] == 1024
    import math

    assert math.isfinite(rec["train_loss"])
