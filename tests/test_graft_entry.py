"""The driver contract: entry() compiles, dryrun_multichip() runs a step.

These are the integration points an external harness exercises; breaking
them silently would cost a whole round.
"""

import os
import sys

import jax
import jax.numpy as jnp
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def test_entry_compiles():
    import __graft_entry__ as g

    fn, args = g.entry()
    loss = jax.jit(fn)(*args)
    assert bool(jnp.isfinite(loss))


def test_dryrun_multichip_8():
    import __graft_entry__ as g

    g.dryrun_multichip(8)  # raises on any failure


def test_bench_worker_contract():
    """bench.py --worker prints one parseable JSON measurement line."""
    import json
    import subprocess

    bench_path = os.path.join(REPO_ROOT, "bench.py")
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        "import sys; sys.argv = ['bench.py', '--worker', 'xla', '1024'];"
        f"exec(open({bench_path!r}).read())"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, timeout=300
    )
    assert proc.returncode == 0, proc.stderr[-500:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert {"value", "vs_baseline", "seq_len", "impl"} <= set(rec)
