"""The driver contract: entry() compiles, dryrun_multichip() runs a step.

These are the integration points an external harness exercises; breaking
them silently would cost a whole round.
"""

import os
import sys

import jax
import jax.numpy as jnp
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def test_entry_compiles():
    import __graft_entry__ as g

    fn, args = g.entry()
    loss = jax.jit(fn)(*args)
    assert bool(jnp.isfinite(loss))


def test_dryrun_multichip_8():
    import __graft_entry__ as g

    g.dryrun_multichip(8)  # raises on any failure


def _run_bench_worker(args, timeout=300):
    import json
    import subprocess

    bench_path = os.path.join(REPO_ROOT, "bench.py")
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        f"import sys; sys.argv = {['bench.py', '--worker'] + args!r};"
        f"exec(open({bench_path!r}).read())"
    )
    env = dict(
        os.environ,
        JAX_COMPILATION_CACHE_DIR=os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".jax_cache"
        ),
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=timeout, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-500:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_bench_worker_contract():
    """bench.py --worker prints one parseable JSON measurement line, with
    compile time recorded separately from step time."""
    rec = _run_bench_worker(["xla", "1024", "fwd"])
    assert {"value", "vs_baseline", "seq_len", "impl", "compile_s"} <= set(rec)


def test_bench_worker_fwdbwd():
    """Backward-included attention timing (the other half of the
    north-star: BASELINE.md wants fwd AND training-relevant numbers)."""
    rec = _run_bench_worker(["xla", "1024", "fwdbwd"])
    assert rec["value"] > 0 and rec["ms_per_step"] > 0


def test_bench_worker_train():
    """Train-step (fwd+bwd+adam) tokens/sec measurement."""
    rec = _run_bench_worker(["xla", "1024", "train"], timeout=600)
    assert rec["tokens_per_sec"] > 0
    assert rec["train_seq_len"] == 1024
    import math

    assert math.isfinite(rec["train_loss"])
