"""Parity: Ulysses (all-to-all head-parallel) attention vs the oracle.

Capability beyond the reference (which has no Ulysses, SURVEY §2.2):
sequence-sharded inputs reshard to head-sharded via all-to-all, attend the
full sequence locally, and reshard back — outputs and gradients must match
dense attention.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from ring_attention_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from ring_attention_tpu.ops import default_attention
from ring_attention_tpu.parallel import create_mesh
from ring_attention_tpu.parallel.ulysses import ulysses_attention

ATOL = 2e-5
GRAD_ATOL = 5e-4


@pytest.fixture(scope="module")
def mesh():
    return create_mesh(ring_size=8)


def ulysses_global(q, k, v, mesh, **kw):
    spec = P("data", None, "seq", None)
    return shard_map(
        partial(ulysses_attention, axis_name="seq", **kw),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
    )(q, k, v)


def make_qkv(rng, b=2, h=8, hk=None, n=128, d=16):
    hk = hk or h
    q = jnp.asarray(rng.standard_normal((b, h, n, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hk, n, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_parity(rng, mesh, causal):
    q, k, v = make_qkv(rng)
    ref = default_attention(q, k, v, causal=causal)
    out = ulysses_global(q, k, v, mesh, causal=causal, bucket_size=16)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_ulysses_gqa(rng, mesh):
    """GQA with hk == world: one kv head per device."""
    q, k, v = make_qkv(rng, h=16, hk=8)
    ref = default_attention(q, k, v, causal=True)
    out = ulysses_global(q, k, v, mesh, causal=True, bucket_size=16)
    np.testing.assert_allclose(out, ref, atol=ATOL)


def test_ulysses_grads(rng, mesh):
    q, k, v = make_qkv(rng)
    g_ref = jax.grad(
        lambda *a: (default_attention(*a, causal=True) ** 2).sum(), (0, 1, 2)
    )(q, k, v)
    g_out = jax.grad(
        lambda *a: (ulysses_global(*a, mesh, causal=True, bucket_size=16) ** 2).sum(),
        (0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


def test_ulysses_head_divisibility(rng, mesh):
    q, k, v = make_qkv(rng, h=4)  # 4 heads over 8 devices
    with pytest.raises(AssertionError):
        ulysses_global(q, k, v, mesh, causal=True)


@pytest.mark.parametrize("hk", [2, 4])
def test_ulysses_gqa_auto_repeat(rng, mesh, hk):
    """GQA with hk < world (the flagship GQA shape that used to hard-fail):
    the real KV heads transfer once and expand locally after the
    collective; outputs AND k/v grads (summed back over the copies) match
    the oracle."""
    q, k, v = make_qkv(rng, h=16, hk=hk)
    ref = default_attention(q, k, v, causal=True)
    out = ulysses_global(q, k, v, mesh, causal=True, bucket_size=16)
    np.testing.assert_allclose(out, ref, atol=ATOL)

    g_ref = jax.grad(
        lambda *a: (default_attention(*a, causal=True) ** 2).sum(), (0, 1, 2)
    )(q, k, v)
    g_out = jax.grad(
        lambda *a: (ulysses_global(*a, mesh, causal=True, bucket_size=16) ** 2).sum(),
        (0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(g_out, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=GRAD_ATOL, err_msg=f"d{name}")


def test_ulysses_gqa_no_repeated_all_to_all(rng, mesh):
    """Bandwidth pin for the small-hk fix: the collective layer must move
    the real kv heads once, never world/gcd repeated copies.  The
    expectation (two all-to-alls for q/out, two kv all-gathers — a
    reintroduced repeat-then-all-to-all shows up as four all-to-alls and
    zero gathers) lives in the shared contract table
    (``analysis/contracts.py::CONTRACTS["ulysses_gqa"]``); this test holds
    the *module-level* HLO to it so the pin cannot drift from the checker."""
    from ring_attention_tpu.analysis import contracts

    q, k, v = make_qkv(rng, h=16, hk=2)
    fn = jax.jit(
        lambda q, k, v: ulysses_global(q, k, v, mesh, causal=True,
                                       bucket_size=16)
    )
    txt = fn.lower(q, k, v).compile().as_text()
    dims = {"ring": 8, "ulysses": 1, "world": 8, "passes": 8, "data": 1}
    violations = contracts.verify_hlo(
        "ulysses_gqa", "fwd", txt, dims,
        mesh_shape=(1, 8), axis_names=["data", "seq"],
    )
    assert not violations, "\n".join(violations)
    # and the checker's own canonical run agrees (shared single source)
    assert contracts.expected_counts("ulysses_gqa", "fwd", dims) == {
        "all-to-all": 2, "all-gather": 2,
    }
