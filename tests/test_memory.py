"""Memory-axis scale: blockwise FFN, named remat policies, host offload.

Pins the ISSUE-7 claims (docs/memory.md): the chunked feedforward is
value-identical to the dense block and never materializes the full
``(b, n, mult*dim)`` intermediate, each named remat policy has a
machine-checkable recompute signature, host offload degrades to a no-op
on backends without a host memory space, and the memory audits
(``analysis/recompile.py``) catch the silent failure modes.

Lean by design — tier-1 sits near its time cap: the fast tier pins one
configuration per claim with shared params/compiled fns; the full
policy x chunk-size x strategy sweep lives in the slow tier.
"""

import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ring_attention_tpu.analysis.recompile import (
    assert_compiles_once,
    audit_donation,
    audit_host_offload,
    audit_remat_residuals,
)
from ring_attention_tpu.models import (
    REMAT_POLICIES,
    FeedForward,
    RingTransformer,
    resolve_remat_policy,
)
from ring_attention_tpu.parallel import create_mesh
from ring_attention_tpu.utils import compat, make_train_step
from ring_attention_tpu.utils.telemetry import (
    compiled_memory,
    train_memory_estimate,
)

VOCAB = 64
D, MULT = 16, 4


# ----------------------------------------------------------------------
# Blockwise feedforward
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def ffn_case():
    """One dense/chunked FeedForward pair sharing params, with a sequence
    length (33) that exercises the pad path at chunk 8."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 33, D)), jnp.float32)
    dense = FeedForward(D, MULT)
    params = dense.init(jax.random.PRNGKey(0), x)
    return dense, params, x


def test_ffn_chunk_parity_fwd_and_grads(ffn_case):
    """Chunked vs dense: forward and all weight grads, including a chunk
    that does not divide the sequence (pad path)."""
    dense, params, x = ffn_case
    chunked = FeedForward(D, MULT, chunk_size=8)
    np.testing.assert_allclose(
        chunked.apply(params, x), dense.apply(params, x), atol=1e-6
    )
    gd = jax.grad(lambda p: dense.apply(p, x).sum())(params)
    gc = jax.grad(lambda p: chunked.apply(p, x).sum())(params)
    for a, b in zip(jax.tree.leaves(gd), jax.tree.leaves(gc)):
        np.testing.assert_allclose(a, b, atol=1e-5)


def test_ffn_chunk_clamp_falls_back_to_dense(ffn_case):
    """chunk >= sequence length takes the dense path bit-identically
    (padding UP would make memory strictly worse — the loss_chunk_size
    clamp rule)."""
    dense, params, x = ffn_case
    big = FeedForward(D, MULT, chunk_size=64)
    np.testing.assert_array_equal(
        np.asarray(big.apply(params, x)), np.asarray(dense.apply(params, x))
    )
    # a shape that cannot split shard-aligned (decode steps: n=1) also
    # falls back rather than erroring
    short = FeedForward(D, MULT, chunk_size=8, seq_shards=4)
    y = short.apply(params, x[:, :1])
    np.testing.assert_array_equal(
        np.asarray(y), np.asarray(dense.apply(params, x[:, :1]))
    )


def test_ffn_chunk_never_materializes_full_intermediate(ffn_case):
    """The whole point: no (b, n, mult*dim) array exists anywhere in the
    grad program — forward or backward."""
    _, params, _ = ffn_case
    n = 64
    x = jnp.zeros((1, n, D), jnp.float32)
    chunked = FeedForward(D, MULT, chunk_size=16)
    jaxpr = jax.make_jaxpr(
        jax.grad(lambda p: chunked.apply(p, x).sum())
    )(params)
    full = f"1,{n},{MULT * D}"
    assert full not in str(jaxpr), f"found full FFN intermediate ({full})"


def test_ffn_chunk_residual_audit_clean(ffn_case):
    """The remat-residual audit agrees: nothing of full (b, n, mult*dim)
    extent is saved across the chunked scan's fwd/bwd boundary."""
    _, params, x = ffn_case
    chunked = FeedForward(D, MULT, chunk_size=8)
    b, n, _ = x.shape
    assert audit_remat_residuals(
        lambda p: chunked.apply(p, x).sum(), params,
        forbidden=[(b, n, MULT * D)], label="chunked_ffn",
    ) == []


def test_ffn_chunk_scan_compiles_once(ffn_case):
    """CompileCounter pin: the chunked scan is ONE compilation across a
    steady-state loop, not a retrace per step."""
    _, params, x = ffn_case
    chunked = FeedForward(D, MULT, chunk_size=8)
    fn = compat.jit(lambda p, x: chunked.apply(p, x).sum())
    assert assert_compiles_once(
        fn, lambda step: (params, x + step), label="chunked_ffn",
    ) <= 1


def test_transformer_ff_chunked_parity_on_mesh(rng):
    """End-to-end: ff_chunk_size through the striped-ring transformer —
    loss and every grad leaf match the dense-FFN model (chunks split
    per shard; the scan crosses no device boundary)."""
    mesh = create_mesh(ring_size=8)
    kw = dict(num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
              bucket_size=4, causal=True, striped=True, mesh=mesh)
    m_d = RingTransformer(**kw)
    m_c = RingTransformer(ff_chunk_size=4, **kw)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 63)), jnp.int32)
    params = m_d.init(jax.random.PRNGKey(0), tokens)
    ld, gd = jax.jit(jax.value_and_grad(
        lambda p: m_d.apply(p, tokens, return_loss=True)))(params)
    lc, gc = jax.jit(jax.value_and_grad(
        lambda p: m_c.apply(p, tokens, return_loss=True)))(params)
    np.testing.assert_allclose(lc, ld, atol=1e-6)
    for a, b in zip(jax.tree.leaves(gd), jax.tree.leaves(gc)):
        np.testing.assert_allclose(a, b, atol=1e-5)


def test_blockwise_ffn_contract_row(devices):
    """The PR-5 contract-table row: the chunked scan adds ZERO collectives
    — none at all forward, exactly the dense FFN's two weight-grad
    all-reduces backward — verified from compiled HLO on the 8-device
    mesh (any undeclared collective kind fails the row)."""
    from ring_attention_tpu.analysis import contracts

    reports = contracts.check_strategy("blockwise_ffn")
    bad = [v for r in reports for v in r.violations]
    assert not bad, "\n".join(bad)
    fwd = next(r for r in reports if r.direction == "fwd")
    assert fwd.counts == {}, fwd.counts  # zero collectives, literally


# ----------------------------------------------------------------------
# Named remat policies
# ----------------------------------------------------------------------


def test_remat_policy_validation_lists_names():
    """Unknown policy -> ValueError naming every valid policy (the old
    assert vanished under -O); bad ff_chunk_size -> the loss_chunk_size-
    style ValueError; tuple length must match depth."""
    kw = dict(num_tokens=VOCAB, dim=16, depth=2, heads=2, dim_head=8,
              bucket_size=8, causal=True, use_ring=False)
    tokens = jnp.zeros((1, 9), jnp.int32)
    with pytest.raises(ValueError) as e:
        RingTransformer(remat=True, remat_policy="bogus", **kw).init(
            jax.random.PRNGKey(0), tokens)
    msg = str(e.value)
    assert "save_attn" in msg and "nothing_saveable" in msg
    assert "offload_attn" in msg
    with pytest.raises(ValueError, match="ff_chunk_size"):
        RingTransformer(ff_chunk_size=0, **kw).init(
            jax.random.PRNGKey(0), tokens)
    with pytest.raises(ValueError, match="3 entries for depth 2"):
        RingTransformer(
            remat=True, remat_policy=("save_attn",) * 3, depth=2,
            **{k: v for k, v in kw.items() if k != "depth"},
        ).init(jax.random.PRNGKey(0), tokens)
    with pytest.raises(ValueError, match="valid policies"):
        resolve_remat_policy("nope")
    assert resolve_remat_policy(None) is None
    assert set(REMAT_POLICIES) >= {
        "nothing_saveable", "everything_saveable", "checkpoint_dots",
        "save_attn", "save_ffn_inputs", "offload_attn",
    }


@pytest.fixture(scope="module")
def policy_model_case():
    """One tiny local transformer + params + the no-remat baseline
    (loss, grads), shared across the policy tests."""
    kw = dict(num_tokens=VOCAB, dim=16, depth=2, heads=2, dim_head=8,
              bucket_size=8, causal=True, use_ring=False)
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (1, 33)), jnp.int32)
    base = RingTransformer(**kw)
    params = base.init(jax.random.PRNGKey(0), tokens)
    l0, g0 = jax.jit(jax.value_and_grad(
        lambda p: base.apply(p, tokens, return_loss=True)))(params)
    return kw, tokens, params, l0, g0


def _policy_loss_grads(kw, tokens, params, policy):
    model = RingTransformer(remat=True, remat_policy=policy, **kw)
    return jax.jit(jax.value_and_grad(
        lambda p: model.apply(p, tokens, return_loss=True)))(params)


@pytest.mark.parametrize("policy", ["nothing_saveable", "save_ffn_inputs"])
def test_remat_policy_parity_fast(policy_model_case, policy):
    """Every policy changes memory/recompute only, never values — fast
    tier pins the two ends; the full registry sweep is in the slow tier."""
    kw, tokens, params, l0, g0 = policy_model_case
    loss, grads = _policy_loss_grads(kw, tokens, params, policy)
    np.testing.assert_allclose(loss, l0, atol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(grads)):
        np.testing.assert_allclose(a, b, atol=1e-5)


@pytest.mark.slow
@pytest.mark.parametrize("policy", sorted(
    set(REMAT_POLICIES) - {"nothing_saveable", "save_ffn_inputs"}
))
def test_remat_policy_parity_full(policy_model_case, policy):
    kw, tokens, params, l0, g0 = policy_model_case
    loss, grads = _policy_loss_grads(kw, tokens, params, policy)
    np.testing.assert_allclose(loss, l0, atol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(grads)):
        np.testing.assert_allclose(a, b, atol=1e-5)


def test_remat_policy_per_layer_tuple(policy_model_case):
    """A per-layer policy tuple (mirroring max_lookback_seq_len) is
    value-identical too."""
    kw, tokens, params, l0, g0 = policy_model_case
    loss, grads = _policy_loss_grads(
        kw, tokens, params, ("save_attn", None))
    np.testing.assert_allclose(loss, l0, atol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(grads)):
        np.testing.assert_allclose(a, b, atol=1e-5)


def _policy_dots(kw, tokens, params, policy):
    """Dot ops in the compiled train grad — the recompute-size signature
    (scan bodies count once; see test_transformer._train_dots)."""
    model = RingTransformer(remat=True, remat_policy=policy, **kw)
    fn = compat.jit(jax.value_and_grad(
        lambda p: model.apply(p, tokens, return_loss=True)))
    return fn.lower(params).compile().as_text().count("dot(")


def test_remat_policy_recompute_signatures(policy_model_case):
    """HLO-verified recompute signatures: what a policy SAVES must vanish
    from the backward recompute — everything_saveable elides the whole
    recompute (fewest dots), checkpoint_dots elides the matmul recompute,
    nothing_saveable recomputes it all (most dots).  save_attn's elision
    is pinned separately (test_transformer.py)."""
    kw, tokens, params, _, _ = policy_model_case
    dots = {
        p: _policy_dots(kw, tokens, params, p)
        for p in ("nothing_saveable", "checkpoint_dots",
                  "everything_saveable")
    }
    # checkpoint_dots saves every dot output, so its backward recompute
    # carries no extra dots either — at this all-dots-and-elementwise
    # model it meets everything_saveable's floor; nothing_saveable pays
    # the full recompute
    assert dots["everything_saveable"] <= dots["checkpoint_dots"], dots
    assert dots["checkpoint_dots"] < dots["nothing_saveable"], dots


def test_remat_residual_audit_catches_policy_leak(policy_model_case):
    """The negative toy: a remat that keeps the (b, n, mult*dim) FFN
    intermediate under an everything_saveable policy must be flagged by
    the residual audit with a one-line diagnostic; the honest
    nothing_saveable program is clean."""
    b, n, d, mult = 1, 64, 16, 4
    w1, w2 = jnp.ones((d, mult * d)), jnp.ones((mult * d, d))
    x = jnp.ones((b, n, d))

    def blk(x):
        return ((jax.nn.gelu(x @ w1)) @ w2).sum()

    forbidden = [(b, n, mult * d)]
    bad = jax.checkpoint(
        blk, policy=jax.checkpoint_policies.everything_saveable)
    violations = audit_remat_residuals(
        bad, x, forbidden=forbidden, label="toy")
    assert len(violations) == 1, violations  # ONE line, deduped
    assert "remat-residual" in violations[0]
    assert str((b, n, mult * d)) in violations[0]
    good = jax.checkpoint(
        blk, policy=jax.checkpoint_policies.nothing_saveable)
    assert audit_remat_residuals(
        good, x, forbidden=forbidden, label="toy") == []


# ----------------------------------------------------------------------
# Host offload + donation / memory audits
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_step_case():
    """One tiny chunked train step shared by the offload/donation tests."""
    model = RingTransformer(
        num_tokens=VOCAB, dim=16, depth=1, heads=2, dim_head=8,
        bucket_size=8, causal=True, use_ring=False, remat=True,
        remat_policy="nothing_saveable", ff_chunk_size=8,
        loss_chunk_size=8,
    )
    tokens = jnp.zeros((1, 33), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    opt = optax.adam(1e-3)

    def loss_fn(p, t):
        return model.apply(p, t, return_loss=True)

    return loss_fn, opt, params, opt.init(params), tokens


def test_host_offload_degrades_to_noop_on_cpu(tiny_step_case):
    """jax 0.4.x CPU exposes no pinned_host space: the compat probe says
    so, host_device_put is the identity, and the offloaded step is
    bit-identical to the plain one — offload must never change values,
    with or without a host space."""
    assert compat.host_memory_kind() is None
    assert compat.host_sharding(None) is None
    tree = {"a": jnp.ones(3)}
    assert compat.host_device_put(tree)["a"] is tree["a"]

    loss_fn, opt, params, opt_state, tokens = tiny_step_case
    base = make_train_step(loss_fn, opt)
    off = make_train_step(loss_fn, opt, offload_opt_state=True)
    pb, ob, lb = base(params, opt_state, tokens)
    po, oo, lo = off(params, opt_state, tokens)
    assert float(lb) == float(lo)
    for a, b in zip(jax.tree.leaves(pb), jax.tree.leaves(po)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_donation_audit_on_chunked_step(tiny_step_case):
    """The composed chunked step with jit_donate: every donated byte
    (params + opt state) aliases in the compiled executable — and the
    host-offload placement audit passes (vacuously here: no host space)."""
    loss_fn, opt, params, opt_state, tokens = tiny_step_case
    step = make_train_step(loss_fn, opt, jit_donate=True)
    assert audit_donation(
        step, params, opt_state, tokens, label="step") == []
    assert audit_host_offload(
        step, params, opt_state, tokens, label="step") == []


def test_chunked_step_temp_bytes_below_dense(tiny_step_case):
    """The compiler's own accounting proves the memory claim: the chunked
    (FFN + CE) train program's peak scratch bytes sit strictly below the
    dense program's at equal shape — the relation bench.py's train1m
    phase reports at proof scale."""
    loss_fn, opt, params, opt_state, tokens = tiny_step_case
    dense_model = RingTransformer(
        num_tokens=VOCAB, dim=16, depth=1, heads=2, dim_head=8,
        bucket_size=8, causal=True, use_ring=False, remat=True,
        remat_policy="nothing_saveable",
    )

    def temp(loss):
        fn = compat.jit(jax.value_and_grad(loss))
        mem = compiled_memory(fn.lower(params, tokens).compile())
        assert "temp_bytes" in mem, mem
        return mem["temp_bytes"]

    t_chunk = temp(loss_fn)
    t_dense = temp(lambda p, t: dense_model.apply(p, t, return_loss=True))
    assert t_chunk < t_dense, (t_chunk, t_dense)


def test_train_memory_estimate_tracks_knobs():
    """The analytic peak-HBM model: chunking shrinks the transient term,
    save_attn grows the saved term, offload drops the optimizer term —
    and the 1M-token bench config fits a 16 GB chip."""
    kw = dict(seq_len=1 << 20, dim=512, depth=2, heads=8, vocab=256,
              n_params=28_000_000, dtype_bytes=2)
    chunked = train_memory_estimate(
        ff_chunk_size=2048, loss_chunk_size=2048, remat_policy="save_attn",
        **kw)
    dense = train_memory_estimate(remat_policy="save_attn", **kw)
    assert chunked["peak_hbm_bytes"] < dense["peak_hbm_bytes"]
    assert chunked["peak_hbm_gb"] < 16.0, chunked
    off = train_memory_estimate(
        ff_chunk_size=2048, loss_chunk_size=2048,
        remat_policy="save_attn", offload_opt_state=True, **kw)
    assert off["peak_hbm_bytes"] < chunked["peak_hbm_bytes"]
    saved_light = train_memory_estimate(
        ff_chunk_size=2048, loss_chunk_size=2048,
        remat_policy="nothing_saveable", **kw)
    assert (saved_light["saved_activation_bytes"]
            < chunked["saved_activation_bytes"])


# ----------------------------------------------------------------------
# Slow tier: CLI + bench worker + the full sweeps
# ----------------------------------------------------------------------


@pytest.mark.slow
def test_check_contracts_memory_cli():
    """tools/check_contracts.py --memory: 6/6 checks hold, exit 0."""
    proc = subprocess.run(
        [sys.executable, "tools/check_contracts.py", "--memory"],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "memory checks hold" in proc.stdout
    assert "FAIL" not in proc.stdout


@pytest.mark.slow
def test_bench_train1m_mem_worker():
    """The bench train1m memory phase at a CI-sized proof shape: chunked
    temp bytes strictly below dense, plus the analytic 1M estimate."""
    import json

    proc = subprocess.run(
        [sys.executable, "bench.py", "--worker", "cpu", "0", "train1m_mem",
         json.dumps({"proof_seq": 1024, "ff_chunk": 128,
                     "loss_chunk": 128})],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-500:]
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["chunked_below_dense"] is True, payload
    assert payload["temp_bytes_chunked"] < payload["temp_bytes_dense"]
    assert payload["peak_hbm_estimate_gb"] < payload[
        "peak_hbm_dense_estimate_gb"]


@pytest.mark.slow
@pytest.mark.parametrize("layout", ["zigzag", "hybrid"])
def test_transformer_ff_chunked_other_layouts(rng, layout):
    """ff_chunk_size under the other sequence-parallel layouts (the fast
    tier pins striped ring)."""
    if layout == "hybrid":
        mesh = create_mesh(ulysses_size=2, ring_size=4)
        kw = dict(sequence_parallel="hybrid", heads=4)
    else:
        mesh = create_mesh(ring_size=8)
        kw = dict(sequence_parallel="zigzag", heads=4)
    common = dict(num_tokens=VOCAB, dim=32, depth=2, dim_head=8,
                  bucket_size=4, causal=True, mesh=mesh, **kw)
    m_d = RingTransformer(**common)
    m_c = RingTransformer(ff_chunk_size=2, **common)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 64)), jnp.int32)
    params = m_d.init(jax.random.PRNGKey(0), tokens)
    ld, gd = jax.jit(jax.value_and_grad(
        lambda p: m_d.apply(p, tokens, return_loss=True)))(params)
    lc, gc = jax.jit(jax.value_and_grad(
        lambda p: m_c.apply(p, tokens, return_loss=True)))(params)
    np.testing.assert_allclose(lc, ld, atol=1e-6)
    for a, b in zip(jax.tree.leaves(gd), jax.tree.leaves(gc)):
        np.testing.assert_allclose(a, b, atol=1e-5)


@pytest.mark.slow
def test_train_example_memory_flags(tmp_path):
    """examples/train.py with the whole memory-axis flag set: loss falls,
    metrics carry the compiled peak-memory fields."""
    import json as _json

    proc = subprocess.run(
        [sys.executable, "examples/train.py", "--fake-devices", "8",
         "--steps", "6", "--seq-len", "128", "--remat-policy", "save_attn",
         "--ff-chunk-size", "8", "--loss-chunk-size", "32",
         "--offload-opt-state", "--metrics-dir", str(tmp_path),
         "--log-every", "2"],
        capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    rows = [
        _json.loads(line)
        for line in (tmp_path / "metrics.jsonl").read_text().splitlines()
        if line.strip()
    ]
    assert rows and "temp_bytes" in rows[-1], rows[-1].keys()
