"""Certified mask algebra (PR 11), tier-1.

Four layers:

  - **algebra semantics**: oracles and the exact tile classifier agree
    elementwise over fuzzed compositions; the mini-language round-trips
    and lists its registry on unknown names.
  - **certification**: certificates cache (memory + disk, keyed by
    mask x geometry), cap their elementwise proof at
    ``CERT_ELEMENTWISE_MAX``, and NEGATIVE toys prove the certifier is
    live — a corrupted lowering (window off by one tile) fails with a
    one-line diagnostic naming the mask, hop, and tile.
  - **execution**: ``mask=`` through ``ops.attention`` / RingAttention /
    RingTransformer matches the legacy knobs and the dense oracle on
    both kernel paths, including the in-kernel fallbacks (misaligned
    ``doc_starts``, non-divisor window) pinned bit-consistent with the
    oracle's masking decisions.
  - **scale**: the certified sliding-window grid at 262k is strictly
    smaller than causal (the bench ``window262k`` phase's claim).
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ring_attention_tpu as rat
from ring_attention_tpu import masks as M
from ring_attention_tpu.analysis import coverage
from ring_attention_tpu.ops import attention, default_attention

ATOL = 3e-5


# ----------------------------------------------------------------------
# Algebra semantics
# ----------------------------------------------------------------------


def _rand_mask(rng, depth=0):
    roll = rng.random()
    if depth < 2 and roll < 0.35:
        kind = rng.integers(0, 3)
        if kind == 0:
            return M.And((_rand_mask(rng, depth + 1),
                          _rand_mask(rng, depth + 1)))
        if kind == 1:
            return M.Or((_rand_mask(rng, depth + 1),
                         _rand_mask(rng, depth + 1)))
        return M.Not(_rand_mask(rng, depth + 1))
    kind = rng.integers(0, 6)
    if kind == 0:
        return M.Causal()
    if kind == 1:
        return M.Full()
    if kind == 2:
        return M.SlidingWindow(int(rng.integers(1, 40)))
    if kind == 3:
        return M.PrefixLM(int(rng.integers(0, 40)))
    if kind == 4:
        s = int(rng.integers(1, 6))
        return M.Dilated(s, int(rng.integers(0, s)))
    cuts = sorted({0, *(int(x) for x in rng.integers(1, 64, 2))})
    return M.DocumentMask(tuple(cuts))


def test_tile_status_matches_oracle_fuzz():
    """The exact tile classifier (every lowering's source of truth) is
    held elementwise to the oracle over fuzzed masks x tiles."""
    rng = np.random.default_rng(0xA1)
    for _ in range(120):
        mask = _rand_mask(rng)
        qlo = int(rng.integers(0, 60))
        klo = int(rng.integers(0, 60))
        qhi = qlo + int(rng.integers(0, 12))
        khi = klo + int(rng.integers(0, 12))
        any_live, all_live = mask.tile_status(qlo, qhi, klo, khi)
        o = mask.oracle(np.arange(qlo, qhi + 1), np.arange(klo, khi + 1))
        assert (any_live, all_live) == (bool(o.any()), bool(o.all())), (
            mask.key, (qlo, qhi, klo, khi)
        )


def test_oracle_compositions():
    q = np.arange(16)
    cw = M.Causal() & M.SlidingWindow(4)
    o = cw.oracle(q, q)
    d = q[None, :] - q[:, None]
    np.testing.assert_array_equal(o, (d <= 0) & (d > -4))
    p = M.PrefixLM(5).oracle(q, q)
    np.testing.assert_array_equal(p, (q[None, :] < 5) | (d <= 0))
    ph = M.PerHead((M.Causal(), M.Full()))
    assert ph.per_head
    np.testing.assert_array_equal(ph.oracle(q, q, head=0), d <= 0)
    assert ph.oracle(q, q, head=1).all()
    assert ph.oracle(q, q, head=2).sum() == (d <= 0).sum()  # wraps


def test_parse_round_trip_and_registry():
    for expr in ("causal", "causal&window:512", "prefix:128|docs:0,64",
                 "causal&~window:8", "perhead(causal;causal&window:64)",
                 "(causal|full)&dilated:4+1", "segments&causal"):
        mask = M.parse_mask(expr)
        assert M.parse_mask(mask.key).key == mask.key, expr
    with pytest.raises(M.MaskParseError, match="registry"):
        M.parse_mask("bogus:3")
    with pytest.raises(M.MaskParseError, match="window needs"):
        M.parse_mask("window")
    with pytest.raises(M.MaskParseError):
        M.parse_mask("causal&&window:4")


def test_kernel_form_mapping():
    assert M.kernel_form(M.Causal()) == M.KernelForm(causal=True)
    assert M.kernel_form(M.Causal() & M.SlidingWindow(512)) == M.KernelForm(
        causal=True, window=512
    )
    assert M.kernel_form(M.Full()) == M.KernelForm()
    form = M.kernel_form(
        M.Causal() & M.DocumentMask((0, 16)) & M.Segments()
    )
    assert form.causal and form.doc_starts == (0, 16)
    assert form.needs_segment_ids
    for bad in (M.PrefixLM(8), M.Dilated(4), M.SlidingWindow(8),
                M.Causal() | M.Full(), ~M.Causal()):
        with pytest.raises(M.MaskLoweringError,
                           match="certifies and lowers to grids"):
            M.kernel_form(bad)


def test_band_form():
    assert M.band_form(M.Causal()) == (0, None)
    assert M.band_form(M.SlidingWindow(8)) == (7, -7)
    assert M.band_form(M.Causal() & M.SlidingWindow(8)) == (0, -7)
    assert M.band_form(M.PrefixLM(4)) is None
    assert M.band_form(M.Full()) == (None, None)


# ----------------------------------------------------------------------
# Certification: cache + negative toys
# ----------------------------------------------------------------------


def _ring_spec(**kw):
    base = dict(strategy="ring", ring=4, n_local=16, block_q=4, block_k=4)
    base.update(kw)
    return M.GridSpec(**base)


def test_certificate_memo_and_disk_cache(tmp_path, monkeypatch):
    mask = M.Causal() & M.SlidingWindow(24)
    spec = _ring_spec()
    monkeypatch.setenv("RING_ATTN_CERT_CACHE", str(tmp_path))
    M._CERT_MEMO.clear()
    c1 = M.certify(mask, spec)
    assert c1.ok and c1.tiles > 0
    files = list(tmp_path.glob("*.json"))
    assert len(files) == 1  # the proof landed on disk
    # a fresh process (cleared memo) loads the disk certificate
    M._CERT_MEMO.clear()
    c2 = M.certify(mask, spec)
    assert c2.ok and (c2.tiles, c2.work, c2.edge) == (
        c1.tiles, c1.work, c1.edge
    )
    # a corrupt cache entry is ignored, not fatal
    files[0].write_text("{broken")
    M._CERT_MEMO.clear()
    assert M.certify(mask, spec).ok


def test_certificate_elementwise_cap():
    """262k-scale certificates cap the elementwise proof and still run
    the closed-form-vs-enumeration accounting at the full shape."""
    spec = M.GridSpec(strategy="single", n_local=1 << 18, block_q=1024,
                      block_k=1024)
    cert = M.certify(M.Causal() & M.SlidingWindow(4096), spec,
                     use_cache=False)
    assert cert.ok and cert.proof_n == M.CERT_ELEMENTWISE_MAX


def test_corrupted_window_lowering_fails_naming_mask_hop_tile():
    """Acceptance negative toy: a window lowering off by one TILE (the
    band table built one block narrower than the mask) fails soundness
    with a one-line diagnostic naming the mask, hop, and tile."""
    from ring_attention_tpu.ops.pallas_flash import band_plan

    mask = M.Causal() & M.SlidingWindow(24)
    spec = _ring_spec()
    low = M.lower(mask, spec)
    # hop 1: the window's lower boundary cuts through the local span
    # (hop 0's window covers the whole span, so nothing would drop)
    hop = low.hops[1]
    hi, _, lo, _ = hop.plan.hint
    b = spec.block_q
    # off-by-one-tile: the table believes the window starts a block later
    bad = band_plan((spec.n_local, spec.n_local), (b, b),
                    (hi, hi, lo + b, lo + b), windowed=True)
    hop.plan = bad
    report = coverage.prove_mask_lowering(mask, spec, lowering=low)
    assert not report.ok
    line = report.violations[0]
    assert "\n" not in line
    assert mask.key in line and f"hop{hop.hop}" in line and "tile" in line
    assert "tile-coverage-sound" in line or "tile-count" in line


def test_widened_lowering_fails_tightness():
    """The dual toy: a table one block WIDER than the window visits dead
    tiles — flagged by the tightness rule, naming the tile."""
    from ring_attention_tpu.ops.pallas_flash import band_plan

    mask = M.Causal() & M.SlidingWindow(24)
    spec = M.GridSpec(strategy="single", n_local=64, block_q=8, block_k=8)
    low = M.lower(mask, spec)
    hop = low.hops[0]
    hi, _, lo, _ = hop.plan.hint
    b = spec.block_q
    wide = band_plan((64, 64), (b, b), (hi, hi, lo - 2 * b, lo - 2 * b),
                     windowed=True)
    hop.plan = hop.plan_kmajor = wide
    report = coverage.prove_mask_lowering(mask, spec, lowering=low)
    assert not report.ok
    assert any("tile-coverage-tight" in v and "tile" in v
               for v in report.violations)


def test_require_certified_raises_one_line(monkeypatch):
    mask = M.Causal() & M.SlidingWindow(24)
    spec = _ring_spec()
    real_lower = M.lower

    def corrupt_lower(m, s):
        from ring_attention_tpu.ops.pallas_flash import band_plan

        low = real_lower(m, s)
        hop = low.hops[1]  # see the corrupted-window toy above
        hi, _, lo, _ = hop.plan.hint
        b = s.block_q
        hop.plan = band_plan((s.n_local, s.n_local), (b, b),
                             (hi, hi, lo + b, lo + b), windowed=True)
        return low

    monkeypatch.setattr(M, "lower", corrupt_lower)
    with pytest.raises(M.MaskCertificationError) as e:
        M.require_certified(mask, spec, use_cache=False)
    assert "\n" not in str(e.value)
    assert mask.key in str(e.value)


def test_hop_pairing_disagreement_is_a_violation():
    """The certifier recomputes the hop schedule independently; a
    lowering that pairs the wrong origins is caught even when its own
    tables are self-consistent."""
    mask = M.Causal()
    spec = _ring_spec()
    low = M.lower(mask, spec)
    low.hops[2].ranks[1].kv_origin = (
        low.hops[2].ranks[1].kv_origin + 1
    ) % spec.ring
    report = coverage.prove_mask_lowering(mask, spec, lowering=low)
    assert any("pairing disagrees" in v for v in report.violations)


# ----------------------------------------------------------------------
# Execution: mask= through the entry points
# ----------------------------------------------------------------------


def _qkv(rng, b=1, h=4, n=64, d=8, hk=None):
    mk = lambda heads: jnp.asarray(
        rng.standard_normal((b, heads, n, d)), jnp.float32
    )
    return mk(h), mk(hk or h), mk(hk or h)


def _dense_reference(q, k, v, mask):
    """Independent dense oracle: materialize the mask's oracle and
    softmax in f32 — no shared code with the flash paths."""
    from ring_attention_tpu.ops.attention import MASK_VALUE

    b, h, n, d = q.shape
    hk = k.shape[1]
    g = h // hk
    keep = M.dense_mask(mask, n, n, heads=h)
    if keep.ndim == 2:
        keep = np.broadcast_to(keep, (h, n, n))
    s = jnp.einsum(
        "bhid,bhjd->bhij", q.astype(jnp.float32),
        jnp.repeat(k, g, axis=1).astype(jnp.float32),
    ) * (d ** -0.5)
    s = jnp.where(jnp.asarray(keep)[None], s, MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhij,bhjd->bhid", p, jnp.repeat(v, g, axis=1).astype(jnp.float32)
    ).astype(q.dtype)


def test_ops_attention_mask_matches_legacy_knobs():
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng)
    out_m = attention(q, k, v, mask=M.Causal() & M.SlidingWindow(16),
                      impl="xla", bucket_size=8)
    out_l = attention(q, k, v, causal=True, window=16, impl="xla",
                      bucket_size=8)
    np.testing.assert_allclose(out_m, out_l, atol=1e-6)
    np.testing.assert_allclose(
        out_m, _dense_reference(q, k, v, M.Causal() & M.SlidingWindow(16)),
        atol=ATOL,
    )


def test_ops_attention_mask_conflicts_and_unlowered():
    rng = np.random.default_rng(2)
    q, k, v = _qkv(rng, n=16)
    with pytest.raises(ValueError, match="subsumes"):
        attention(q, k, v, mask=M.Causal(), causal=True)
    with pytest.raises(M.MaskLoweringError, match="kernels speak"):
        attention(q, k, v, mask=M.PrefixLM(4))
    with pytest.raises(ValueError, match="segment_ids"):
        attention(q, k, v, mask=M.Causal() & M.Segments())
    with pytest.raises(ValueError, match="doc_starts"):
        attention(q, k, v, mask=M.Causal() & M.DocumentMask((0, 8)),
                  doc_starts=(0, 8))


def test_misaligned_docs_fallback_parity_both_paths():
    """Satellite pin: a mask whose lowering falls back to in-kernel
    masking (misaligned doc_starts) is bit-consistent with the dense
    oracle on BOTH paths — cross-document values cannot influence the
    output AT ALL (outputs bit-identical under cross-document value
    perturbation), and the kept attention matches the oracle."""
    rng = np.random.default_rng(3)
    q, k, v = _qkv(rng, n=64)
    mask = M.Causal() & M.DocumentMask((0, 13, 41))  # 13: misaligned
    ids = np.zeros(64, np.int32)
    ids[13:] = 1
    ids[41:] = 2
    for impl in ("xla", "pallas"):
        kw = dict(impl=impl, bucket_size=8)
        if impl == "pallas":
            kw["interpret"] = True
        out = attention(q, k, v, mask=mask, **kw)
        np.testing.assert_allclose(
            out, _dense_reference(q, k, v, mask), atol=ATOL,
            err_msg=impl,
        )
        # bit-consistency of the masking decision: scrambling every
        # OTHER document's k/v rows leaves document-0 queries untouched
        scr = np.asarray(v).copy()
        scr[:, :, 13:] = rng.standard_normal(scr[:, :, 13:].shape)
        k_scr = np.asarray(k).copy()
        k_scr[:, :, 13:] = rng.standard_normal(k_scr[:, :, 13:].shape)
        out_scr = attention(q, jnp.asarray(k_scr), jnp.asarray(scr),
                            mask=mask, **kw)
        np.testing.assert_array_equal(
            np.asarray(out)[:, :, :13], np.asarray(out_scr)[:, :, :13],
            err_msg=f"{impl}: cross-document leak",
        )


def test_nondivisor_window_fallback_parity_both_paths():
    """Satellite pin, window half: a window that divides neither the
    bucket nor the block (w=11 at bucket 8) masks in-kernel; both paths
    match the dense oracle."""
    rng = np.random.default_rng(4)
    q, k, v = _qkv(rng, n=48)
    mask = M.Causal() & M.SlidingWindow(11)
    ref = _dense_reference(q, k, v, mask)
    out_x = attention(q, k, v, mask=mask, impl="xla", bucket_size=8)
    np.testing.assert_allclose(out_x, ref, atol=ATOL)
    out_p = attention(q, k, v, mask=mask, impl="pallas", interpret=True)
    np.testing.assert_allclose(out_p, ref, atol=ATOL)


@pytest.fixture(scope="module")
def mesh():
    return rat.create_mesh(ring_size=8)


def test_ring_attention_mask_sugar(mesh):
    """causal=True is sugar for mask=Causal() across strategies, and a
    DocumentMask lowers onto the proven segment-id ring machinery."""
    rng = np.random.default_rng(5)
    h = 4
    common = dict(dim=h * 8, heads=h, dim_head=8, bucket_size=8)
    x = jnp.asarray(rng.standard_normal((1, 63, h * 8)), jnp.float32)
    legacy = rat.RingAttention(
        use_ring=True, auto_shard=True, mesh=mesh, causal=True,
        max_lookback_seq_len=16, **common,
    )
    params = legacy.init(jax.random.PRNGKey(0), x)
    sugar = rat.RingAttention(
        use_ring=True, auto_shard=True, mesh=mesh,
        mask=M.Causal() & M.SlidingWindow(16), **common,
    )
    np.testing.assert_allclose(
        sugar.apply(params, x), legacy.apply(params, x), atol=1e-6
    )
    # counter-rotated + striped geometry under mask=
    c_legacy = rat.RingAttention(
        use_ring=True, auto_shard=True, mesh=mesh, striped=True,
        ring_counter_rotate=True, causal=True, max_lookback_seq_len=24,
        **common,
    )
    c_sugar = rat.RingAttention(
        use_ring=True, auto_shard=True, mesh=mesh, striped=True,
        ring_counter_rotate=True,
        mask=M.Causal() & M.SlidingWindow(24), **common,
    )
    np.testing.assert_allclose(
        c_sugar.apply(params, x), c_legacy.apply(params, x), atol=1e-6
    )
    # document mask -> segment-id machinery, vs the per-document oracle
    doc = rat.RingAttention(
        use_ring=True, auto_shard=True, mesh=mesh,
        mask=M.Causal() & M.DocumentMask((0, 20, 41)), **common,
    )
    oracle = rat.RingAttention(
        use_ring=False, force_regular_attn=True, causal=True, **common,
    )
    ids = np.zeros(63, np.int32)
    ids[20:] = 1
    ids[41:] = 2
    seg = jnp.asarray(np.broadcast_to(ids, (1, 63)).copy())
    np.testing.assert_allclose(
        doc.apply(params, x), oracle.apply(params, x, None, seg),
        atol=ATOL,
    )


def test_ring_attention_mask_conflicts(mesh):
    rng = np.random.default_rng(6)
    h = 4
    common = dict(dim=h * 8, heads=h, dim_head=8, bucket_size=8)
    x = jnp.asarray(rng.standard_normal((1, 16, h * 8)), jnp.float32)
    oracle = rat.RingAttention(use_ring=False, causal=True, **common)
    params = oracle.init(jax.random.PRNGKey(0), x)
    with pytest.raises(ValueError, match="sugar for mask=Causal"):
        rat.RingAttention(
            use_ring=False, causal=True, mask=M.Causal(), **common
        ).apply(params, x)
    with pytest.raises(ValueError, match="SlidingWindow"):
        rat.RingAttention(
            use_ring=False, max_lookback_seq_len=8, mask=M.Causal(),
            **common,
        ).apply(params, x)
    with pytest.raises(M.MaskLoweringError):
        rat.RingAttention(
            use_ring=False, mask=M.Dilated(2), **common
        ).apply(params, x)
    with pytest.raises(ValueError, match="Segments"):
        rat.RingAttention(
            use_ring=False, mask=M.Causal() & M.Segments(), **common
        ).apply(params, x)


def test_transformer_mask_per_layer(mesh):
    """A per-layer mask tuple (local window below a global layer)
    matches the equivalent per-layer lookback tuple."""
    rng = np.random.default_rng(7)
    toks = jnp.asarray(rng.integers(0, 64, (1, 33)), jnp.int32)
    common = dict(num_tokens=64, dim=32, depth=2, heads=4, dim_head=8,
                  bucket_size=8, mesh=mesh)
    legacy = rat.RingTransformer(
        causal=True, max_lookback_seq_len=(16, None), **common
    )
    params = legacy.init(jax.random.PRNGKey(0), toks)
    sugar = rat.RingTransformer(
        mask=(M.Causal() & M.SlidingWindow(16), M.Causal()), **common
    )
    np.testing.assert_allclose(
        sugar.apply(params, toks), legacy.apply(params, toks), atol=1e-5
    )
    with pytest.raises(ValueError, match="mask tuple"):
        rat.RingTransformer(mask=(M.Causal(),), **common).init(
            jax.random.PRNGKey(0), toks
        )


# ----------------------------------------------------------------------
# Scale: the 262k certified tile accounting (the bench claim)
# ----------------------------------------------------------------------


def test_window_262k_strictly_smaller_certified_grid():
    spec = M.GridSpec(strategy="single", n_local=1 << 18, block_q=1024,
                      block_k=1024)
    wmask = M.Causal() & M.SlidingWindow(4096)
    assert M.certify(wmask, spec, use_cache=False).ok
    assert M.certify(M.Causal(), spec, use_cache=False).ok
    w = sum(h.plan.work_tiles for h in M.lower(wmask, spec).hops)
    c = sum(h.plan.work_tiles for h in M.lower(M.Causal(), spec).hops)
    assert w < c  # the raw-speed claim, CPU-countable
    assert c / w > 10


@pytest.mark.slow
def test_bench_window262k_worker():
    """The bench phase payload: both grids certified, window strictly
    smaller, reduction reported."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py"), "--worker",
         "cpu", "0", "window262k", "{}"],
        capture_output=True, text=True, timeout=180, cwd=root,
    )
    assert proc.returncode == 0, proc.stderr[-500:]
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["causal_certified"] and payload["window_certified"]
    assert payload["window_work_tiles"] < payload["causal_work_tiles"]
    assert payload["tile_reduction_x"] > 10


def test_segments_mask_executes_and_certifies():
    """Review pin: the documented ``... & Segments()`` form works end to
    end — the runtime leaf drops out of the static grids
    (``static_mask``), certification proves the remaining conjunction,
    and execution masks through the segment_ids path."""
    assert M.static_mask(M.Causal() & M.Segments()).key == "causal"
    assert M.static_mask(M.Segments()).key == "full"
    cert = M.certify(M.Causal() & M.Segments(),
                     M.GridSpec(strategy="single", n_local=64,
                                block_q=8, block_k=8), use_cache=False)
    assert cert.ok
    rng = np.random.default_rng(11)
    q, k, v = _qkv(rng, n=48)
    ids = np.zeros(48, np.int32)
    ids[20:] = 1
    seg = jnp.asarray(np.broadcast_to(ids, (1, 48)).copy())
    out = attention(q, k, v, mask=M.Causal() & M.Segments(),
                    segment_ids=seg, impl="xla", bucket_size=8)
    ref = attention(q, k, v, causal=True, segment_ids=seg, impl="xla",
                    bucket_size=8)
    np.testing.assert_allclose(out, ref, atol=1e-6)


def test_perhead_inside_combinator_certifies_every_head():
    """Review pin: PerHead nested under a combinator enumerates EVERY
    distinct head variant (lcm period), not just head 0 — and the
    coverage row machinery accepts the composition."""
    mask = M.PerHead((M.Causal(), M.Full())) & M.SlidingWindow(8)
    assert mask.head_period == 2
    spec = M.GridSpec(strategy="single", n_local=32, block_q=8, block_k=8)
    cert = M.certify(mask, spec, use_cache=False)
    assert cert.ok
    # head variants genuinely differ, so proving both must cost more
    # tiles than proving either alone
    solo = M.certify(M.Causal() & M.SlidingWindow(8), spec,
                     use_cache=False)
    assert cert.tiles > solo.tiles
    report = coverage.prove_mask_case(coverage.MaskCoverageCase(
        name="toy", expr="perhead(causal;full)&window:8",
        n_local=32, block=8,
    ))
    assert report.ok, "\n".join(report.violations)


def test_malformed_inputs_raise_at_api_boundary_with_mask():
    """Review pin: a malformed q with a mask expression still gets the
    one-line check_attention_args ValueError, not an IndexError from
    mask resolution."""
    bad = jnp.zeros((2, 8, 4))  # 3-D
    with pytest.raises(ValueError, match="attention"):
        attention(bad, bad, bad, mask=M.Causal())


def test_spec_for_call_mapping():
    s = M.spec_for_call("ring", n=128, ring=8, striped=True)
    assert (s.strategy, s.layout, s.ring, s.n_local) == (
        "ring", "striped", 8, 16
    )
    assert M.spec_for_call("ulysses", n=128, ring=8).strategy == "single"
    assert M.spec_for_call("hybrid", n=128, ring=4).strategy == "ring"
    assert M.spec_for_call("ring", n=128, ring=1).strategy == "single"
    with pytest.raises(ValueError, match="unknown strategy"):
        M.spec_for_call("warp", n=128)
