"""Parity: incremental KV-cache decoding vs the full causal forward.

Extends the reference's decode story (standalone ``tree_attn_decode``,
``assert_tree_attn.py``) to the model level: feeding tokens one at a time
through ``decode_step`` against a (ring-sharded) KV cache must reproduce
the full-sequence causal forward logits at every step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ring_attention_tpu.models import RingTransformer
from ring_attention_tpu.parallel import create_mesh

ATOL = 3e-5
VOCAB = 128


def _jit_decode_fns(model):
    """Jitted (prefill, decode_step) closures for ``model``."""
    prefill = jax.jit(
        lambda p, t, c: model.apply(p, t, c, method=RingTransformer.prefill)
    )
    step = jax.jit(
        lambda p, tok, c, i: model.apply(
            p, tok, c, i, method=RingTransformer.decode_step
        )
    )
    return prefill, step


def _decode_all(model, params, tokens, max_len):
    """Run decode_step over each token; stack per-step logits."""
    b, n = tokens.shape
    cache = model.apply(params, b, max_len, method=RingTransformer.init_cache)
    _, step = _jit_decode_fns(model)
    outs = []
    for i in range(n):
        logits, cache = step(params, tokens[:, i], cache, jnp.int32(i))
        outs.append(logits)
    return jnp.stack(outs, axis=1)  # (b, n, vocab)


@pytest.mark.parametrize("kv_heads", [None, 2])
def test_decode_matches_forward_local(rng, kv_heads):
    model = RingTransformer(
        num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
        causal=True, bucket_size=8, use_ring=False, kv_heads=kv_heads,
    )
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 12)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    full = model.apply(params, tokens)
    inc = _decode_all(model, params, tokens, max_len=16)
    np.testing.assert_allclose(inc, full, atol=ATOL)


@pytest.mark.parametrize("use_ring", [False, True])
def test_decode_pallas_matches_forward(rng, use_ring):
    """use_pallas decoding (the single-sweep decode kernel, interpret mode
    on CPU) reproduces the full forward — locally and through the
    tree-attention merge on the 8-ring."""
    kw = dict(
        num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
        causal=True, bucket_size=8, kv_heads=2,
    )
    model = RingTransformer(
        use_pallas=True,
        **(dict(kw, mesh=create_mesh(ring_size=8)) if use_ring
           else dict(kw, use_ring=False)),
    )
    ref_model = RingTransformer(use_ring=False, **kw)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 12)), jnp.int32)
    params = ref_model.init(jax.random.PRNGKey(0), tokens)
    full = ref_model.apply(params, tokens)
    inc = _decode_all(model, params, tokens, max_len=16)
    np.testing.assert_allclose(inc, full, atol=ATOL)


def test_decode_matches_forward_ring(rng):
    """Cache sharded over an 8-ring; tree-attention merge per step."""
    mesh = create_mesh(ring_size=8)
    model = RingTransformer(
        num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
        causal=True, bucket_size=8, mesh=mesh,
    )
    ref_model = RingTransformer(
        num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
        causal=True, bucket_size=8, use_ring=False,
    )
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 12)), jnp.int32)
    params = ref_model.init(jax.random.PRNGKey(0), tokens)
    full = ref_model.apply(params, tokens)
    inc = _decode_all(model, params, tokens, max_len=16)
    np.testing.assert_allclose(inc, full, atol=ATOL)


def test_generate_greedy(rng):
    """generate() returns the same tokens as greedy decoding over the
    full-forward logits."""
    model = RingTransformer(
        num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
        causal=True, bucket_size=8, use_ring=False,
    )
    prompt = jnp.asarray(rng.integers(0, VOCAB, (2, 6)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)
    gen = model.apply(
        params, prompt, 32, 4, method=RingTransformer.generate
    )
    assert gen.shape == (2, 4)

    # oracle: repeatedly run the full forward and take argmax (jitted so
    # the per-shape executables land in the persistent cache)
    fwd = jax.jit(lambda p, s: model.apply(p, s))
    seq = prompt
    expect = []
    for _ in range(4):
        logits = fwd(params, seq)
        tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        expect.append(tok)
        seq = jnp.concatenate([seq, tok[:, None]], axis=1)
    np.testing.assert_array_equal(gen, jnp.stack(expect, axis=1))


def test_generate_compile_once(rng):
    """The decode loop is one lax.scan body: the traced program must not
    grow with num_steps (VERDICT r3 weak #5 — the old Python loop emitted
    one decode-step trace per generated token)."""
    model = RingTransformer(
        num_tokens=VOCAB, dim=16, depth=1, heads=2, dim_head=8,
        causal=True, bucket_size=8, use_ring=False,
    )
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 4)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)

    def eqns(num_steps):
        jaxpr = jax.make_jaxpr(
            lambda p, t: model.apply(
                p, t, 512, num_steps, method=RingTransformer.generate
            )
        )(params, prompt)
        return len(jaxpr.jaxpr.eqns)

    assert eqns(8) == eqns(64) == eqns(256)


def test_generate_sampling(rng):
    """temperature/top_k sampling: deterministic under a fixed rng, valid
    token range, and top_k=1 collapses to greedy."""
    model = RingTransformer(
        num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
        causal=True, bucket_size=8, use_ring=False,
    )
    prompt = jnp.asarray(rng.integers(0, VOCAB, (2, 6)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)
    key = jax.random.PRNGKey(7)

    kw = dict(method=RingTransformer.generate, temperature=1.0, top_k=8)
    a = model.apply(params, prompt, 32, 8, rng=key, **kw)
    b = model.apply(params, prompt, 32, 8, rng=key, **kw)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (2, 8)
    assert ((a >= 0) & (a < VOCAB)).all()

    greedy = model.apply(params, prompt, 32, 8, method=RingTransformer.generate)
    top1 = model.apply(
        params, prompt, 32, 8, rng=key,
        method=RingTransformer.generate, temperature=0.5, top_k=1,
    )
    np.testing.assert_array_equal(top1, greedy)
    # a tiny nucleus similarly collapses to greedy (the top token's
    # mass-before is always 0 < top_p, so exactly it survives)
    nucleus = model.apply(
        params, prompt, 32, 8, rng=key,
        method=RingTransformer.generate, temperature=0.7, top_p=1e-9,
    )
    np.testing.assert_array_equal(nucleus, greedy)
    # permissive nucleus: valid tokens, deterministic under the key
    p9 = model.apply(
        params, prompt, 32, 8, rng=key,
        method=RingTransformer.generate, temperature=1.0, top_p=0.9,
    )
    assert ((p9 >= 0) & (p9 < VOCAB)).all()

    with pytest.raises(ValueError):
        model.apply(
            params, prompt, 32, 4,
            method=RingTransformer.generate, temperature=1.0,
        )
    # greedy mode must reject sampling knobs rather than ignore them
    with pytest.raises(ValueError):
        model.apply(
            params, prompt, 32, 4,
            method=RingTransformer.generate, top_k=5,
        )
    with pytest.raises(ValueError):
        model.apply(
            params, prompt, 32, 4, rng=key,
            method=RingTransformer.generate, temperature=1.0, top_p=0.0,
        )


@pytest.mark.slow
def test_generate_256_on_ring(rng):
    """256 generated tokens against the 8-device ring-sharded cache in one
    jit compile (VERDICT r3 next #5 done-criterion)."""
    mesh = create_mesh(ring_size=8)
    model = RingTransformer(
        num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
        causal=True, bucket_size=8, mesh=mesh,
    )
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 8)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)
    traces = 0

    def gen(p, t):
        nonlocal traces
        traces += 1
        return model.apply(p, t, 512, 256, method=RingTransformer.generate)

    jgen = jax.jit(gen)
    out = jgen(params, prompt)
    assert out.shape == (1, 256)
    assert ((out >= 0) & (out < VOCAB)).all()
    # local greedy reference: the ring-sharded scan decode must agree on a
    # prefix (full 256-token equality would be brittle — the tree-decode
    # merge re-associates the softmax reduction, so a near-tie argmax flip
    # anywhere diverges every later token; logit-level ring parity is
    # test_decode_matches_forward_ring's job)
    local = RingTransformer(
        num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
        causal=True, bucket_size=8, use_ring=False,
    )
    ref = local.apply(params, prompt, 512, 256, method=RingTransformer.generate)
    np.testing.assert_array_equal(out[:, :64], ref[:, :64])
    assert traces == 1


@pytest.mark.parametrize("use_pallas", [False, True])
def test_windowed_cache_decode(rng, use_pallas):
    """windowed_cache: a lookback layer's ring-buffer cache (W slots
    instead of max_len) decodes identically to the full-length cache —
    per-layer sizes, mixed windowed/global depth."""
    kw = dict(
        num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
        causal=True, bucket_size=8, use_ring=False,
        max_lookback_seq_len=(4, None), use_pallas=use_pallas,
    )
    model = RingTransformer(windowed_cache=True, **kw)
    ref_model = RingTransformer(**kw)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 12)), jnp.int32)
    params = ref_model.init(jax.random.PRNGKey(0), tokens)
    full = ref_model.apply(params, tokens)

    cache = model.apply(params, 2, 16, method=RingTransformer.init_cache)
    assert cache["k"][0].shape[2] == 4 and cache["k"][1].shape[2] == 16
    _, step = _jit_decode_fns(model)
    for i in range(12):
        logits, cache = step(params, tokens[:, i], cache, jnp.int32(i))
        np.testing.assert_allclose(logits, full[:, i], atol=ATOL, err_msg=i)


def test_windowed_cache_prefill_long_prompt(rng):
    """A prompt longer than the window-sized cache prefills the last W
    rows in ring-buffer order; decode continues exactly."""
    kw = dict(
        num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
        causal=True, bucket_size=8, use_ring=False,
        max_lookback_seq_len=4,
    )
    model = RingTransformer(windowed_cache=True, **kw)
    ref_model = RingTransformer(**kw)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 12)), jnp.int32)
    params = ref_model.init(jax.random.PRNGKey(0), tokens)
    full = ref_model.apply(params, tokens)

    cache = model.apply(params, 2, 16, method=RingTransformer.init_cache)
    assert cache["k"][0].shape[2] == 4  # window-sized: prompt 10 > 4
    logits, cache = model.apply(
        params, tokens[:, :10], cache, method=RingTransformer.prefill
    )
    np.testing.assert_allclose(logits, full[:, 9], atol=ATOL)
    _, step = _jit_decode_fns(model)
    for i in (10, 11):
        logits, cache = step(params, tokens[:, i], cache, jnp.int32(i))
        np.testing.assert_allclose(logits, full[:, i], atol=ATOL, err_msg=i)

    # windowed + quantized combination: quantization is deterministic, so
    # the windowed int8 cache must match the full-length int8 cache to
    # reduction-order tolerance (the ring buffer rotates slot order, so
    # the softmax sums reassociate at ulp level) — catches mis-rolled
    # rows/scales, not just shape bugs
    qwin = RingTransformer(windowed_cache=True, quantize_cache=True, **kw)
    qfull = RingTransformer(quantize_cache=True, **kw)
    cw = qwin.apply(params, 2, 16, method=RingTransformer.init_cache)
    cf = qfull.apply(params, 2, 16, method=RingTransformer.init_cache)
    lw, cw = qwin.apply(params, tokens[:, :10], cw,
                        method=RingTransformer.prefill)
    lf, cf = qfull.apply(params, tokens[:, :10], cf,
                         method=RingTransformer.prefill)
    np.testing.assert_allclose(lw, lf, atol=1e-4)
    for i in (10, 11):
        lw, cw = qwin.apply(params, tokens[:, i], cw, jnp.int32(i),
                            method=RingTransformer.decode_step)
        lf, cf = qfull.apply(params, tokens[:, i], cf, jnp.int32(i),
                             method=RingTransformer.decode_step)
        np.testing.assert_allclose(lw, lf, atol=1e-4)

    # over-long prompt on an unwindowed cache must hard-error, not truncate
    bad = RingTransformer(
        **{**kw, "max_lookback_seq_len": None}, windowed_cache=True
    )
    c = bad.apply(params, 2, 8, method=RingTransformer.init_cache)
    with pytest.raises(ValueError, match="window-sized"):
        bad.apply(params, tokens, c, method=RingTransformer.prefill)


@pytest.mark.parametrize("use_ring,use_pallas", [
    # local variants stay in the fast tier so `-m "not slow"` still covers
    # the model-level quantized dispatch for BOTH impl paths; the
    # ring-sharded variants (~40 s each on 1 CPU) are the slow tier
    (False, False),
    (False, True),
    pytest.param(True, False, marks=pytest.mark.slow),
    pytest.param(True, True, marks=pytest.mark.slow),
])
def test_decode_quantized_cache(rng, use_ring, use_pallas):
    """quantize_cache: int8 decode cache through prefill + decode_step
    (local and ring-sharded) tracks the exact forward to quantization
    tolerance, and generate() runs on the quantized-cache pytree."""
    kw = dict(
        num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
        causal=True, bucket_size=8, kv_heads=2, quantize_cache=True,
        use_pallas=use_pallas,
    )
    model = RingTransformer(
        **(dict(kw, mesh=create_mesh(ring_size=8)) if use_ring
           else dict(kw, use_ring=False)),
    )
    ref_model = RingTransformer(
        **{k: v for k, v in kw.items()
           if k not in ("quantize_cache", "use_pallas")},
        use_ring=False,
    )
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 12)), jnp.int32)
    params = ref_model.init(jax.random.PRNGKey(0), tokens)
    full = ref_model.apply(params, tokens)

    # prefill 8, decode 4 more: logits stay within quantization tolerance
    cache = model.apply(params, 2, 16, method=RingTransformer.init_cache)
    logits, cache = model.apply(
        params, tokens[:, :8], cache, method=RingTransformer.prefill
    )
    np.testing.assert_allclose(logits, full[:, 7], atol=ATOL)  # exact path
    for i in (8, 9, 10, 11):
        logits, cache = model.apply(
            params, tokens[:, i], cache, jnp.int32(i),
            method=RingTransformer.decode_step,
        )
        rel = float(jnp.abs(logits - full[:, i]).max()
                    / jnp.abs(full[:, i]).max())
        assert rel < 0.05, (i, rel)

    gen = model.apply(
        params, tokens[:, :4], 16, 6, method=RingTransformer.generate
    )
    assert gen.shape == (2, 6)
    assert ((gen >= 0) & (gen < VOCAB)).all()


@pytest.mark.parametrize("cfg", [
    # (prompt_len, steps, temperature, top_k, top_p)
    (3, 7, 0.0, None, None),
    (9, 5, 1.3, 3, None),
    (5, 11, 0.6, None, 0.7),
    (1, 4, 2.0, 7, 0.99),
])
def test_fuzz_generate_configs(rng, cfg):
    """Generate across odd prompt/step/sampling combos: shape, range and
    fixed-rng determinism hold for every knob combination."""
    n, steps, temp, tk, tp = cfg
    model = RingTransformer(
        num_tokens=VOCAB, dim=32, depth=1, heads=2, dim_head=16,
        causal=True, bucket_size=8, use_ring=False,
    )
    prompt = jnp.asarray(rng.integers(0, VOCAB, (2, n)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)
    kw = dict(method=RingTransformer.generate, temperature=temp,
              top_k=tk, top_p=tp)
    if temp > 0:
        kw["rng"] = jax.random.PRNGKey(11)
    out = model.apply(params, prompt, 32, steps, **kw)
    assert out.shape == (2, steps)
    assert ((out >= 0) & (out < VOCAB)).all()
    np.testing.assert_array_equal(
        out, model.apply(params, prompt, 32, steps, **kw)
    )


def test_decode_with_lookback(rng):
    """Layers with lookback windows must decode identically to the forward
    (regression: decode_step ignoring max_lookback_seq_len)."""
    model = RingTransformer(
        num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
        causal=True, bucket_size=8, use_ring=False, max_lookback_seq_len=4,
    )
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 12)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    full = model.apply(params, tokens)
    inc = _decode_all(model, params, tokens, max_len=16)
    np.testing.assert_allclose(inc, full, atol=ATOL)


def test_prefill_then_decode(rng):
    """One prefill pass + decode steps == token-by-token decoding."""
    model = RingTransformer(
        num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
        causal=True, bucket_size=8, use_ring=False,
    )
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 10)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    full = model.apply(params, tokens)

    cache = model.apply(params, 2, 16, method=RingTransformer.init_cache)
    prefill, step = _jit_decode_fns(model)
    logits, cache = prefill(params, tokens[:, :8], cache)
    np.testing.assert_allclose(logits, full[:, 7], atol=ATOL)
    # continue decoding from position 8
    for i in (8, 9):
        logits, cache = step(params, tokens[:, i], cache, jnp.int32(i))
        np.testing.assert_allclose(logits, full[:, i], atol=ATOL)


def test_generate_edge_asserts(rng):
    model = RingTransformer(
        num_tokens=VOCAB, dim=16, depth=1, heads=2, dim_head=8,
        causal=True, bucket_size=8, use_ring=False,
    )
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 4)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)
    with pytest.raises(AssertionError):
        model.apply(params, prompt[:, :0], 16, 2, method=RingTransformer.generate)
    with pytest.raises(AssertionError):
        model.apply(params, prompt, 16, 0, method=RingTransformer.generate)
    with pytest.raises(AssertionError):
        model.apply(params, prompt, 4, 4, method=RingTransformer.generate)


def test_ring_prefill_then_decode(rng):
    """Ring-sharded prefill (sequence-parallel prompt pass) + tree-decode
    steps == the unsharded causal forward."""
    mesh = create_mesh(ring_size=8)
    model = RingTransformer(
        num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
        causal=True, bucket_size=8, mesh=mesh,
    )
    ref_model = RingTransformer(
        num_tokens=VOCAB, dim=32, depth=2, heads=4, dim_head=8,
        causal=True, bucket_size=8, use_ring=False,
    )
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 11)), jnp.int32)
    params = ref_model.init(jax.random.PRNGKey(0), tokens)
    full = ref_model.apply(params, tokens)

    cache = model.apply(params, 2, 16, method=RingTransformer.init_cache)
    prefill, step = _jit_decode_fns(model)
    logits, cache = prefill(params, tokens[:, :9], cache)
    np.testing.assert_allclose(logits, full[:, 8], atol=ATOL)
    for i in (9, 10):
        logits, cache = step(params, tokens[:, i], cache, jnp.int32(i))
        np.testing.assert_allclose(logits, full[:, i], atol=ATOL)


def test_decode_matches_forward_ulysses(rng):
    """Decode is SP-scheme-independent: a model configured with ulysses
    sequence parallelism for training still decodes via the contiguous
    sharded cache + tree merge, and must reproduce ITS full forward."""
    mesh = create_mesh(ring_size=8)
    model = RingTransformer(
        num_tokens=VOCAB, dim=32, depth=2, heads=8, dim_head=8,
        causal=True, bucket_size=8, kv_heads=2, mesh=mesh,
        sequence_parallel="ulysses",
    )
    tokens = jnp.asarray(rng.integers(0, VOCAB, (2, 16)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    full = model.apply(params, tokens)
    inc = _decode_all(model, params, tokens, max_len=16)
    np.testing.assert_allclose(inc, full, atol=ATOL)
