"""Victim process for the elastic chaos matrix (tests/test_elastic.py).

One tiny ring-attention training run on virtual CPU devices, wired
exactly the way a production job would be: elastic sharded checkpoints
(async saves, manifest commit), re-mesh resume planned from the latest
manifest, and a PreemptionGuard drain.  The parent kills it anywhere —
chaos faults arrive via ``RING_ATTN_CHAOS`` (armed at startup), the
device count via ``RING_ATTN_CHAOS_DEVICES`` — restarts it at any
device count, and audits the per-step loss log this worker appends
(one fsync'd JSON line per completed step, so a hard death can never
lose or tear the evidence).

    python tests/elastic_worker.py --ckpt-dir D --loss-log L [--steps 10]
"""

import argparse
import json
import os
import sys


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--loss-log", required=True)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--save-every", type=int, default=3)
    ap.add_argument("--sync-save", action="store_true",
                    help="synchronous saves (the chaos kill points then "
                         "fire on the main thread, deterministically "
                         "ordered against the loss log)")
    args = ap.parse_args()

    n_dev = int(os.environ.get("RING_ATTN_CHAOS_DEVICES", "4"))
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_dev}"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_matmul_precision", "highest")
    # share the test suite's persistent compile cache: repeat chaos runs
    # pay XLA compilation once per (device count, shape), not per run
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.05)

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import numpy as np
    import optax

    from ring_attention_tpu.elastic import (
        ElasticCheckpointManager,
        PreemptionGuard,
        chaos,
    )
    from ring_attention_tpu.models import RingTransformer
    from ring_attention_tpu.parallel import (
        create_mesh,
        remesh_plan,
        shard_batch,
    )
    from ring_attention_tpu.utils import make_train_step

    armed = chaos.arm_from_env()
    if armed:
        print(f"chaos armed: {armed}", flush=True)

    mgr = ElasticCheckpointManager(
        args.ckpt_dir, keep=3, async_save=not args.sync_save
    )
    manifest = mgr.latest_manifest()
    if manifest is not None:
        plan, diags = remesh_plan(manifest.get("mesh"), n_dev)
        for line in diags:
            print(line, flush=True)
    else:
        plan = {"ring_size": n_dev}
    mesh = create_mesh(**plan)
    ring = plan["ring_size"] * (plan.get("ulysses_size") or 1)

    model = RingTransformer(
        num_tokens=64, dim=16, depth=1, heads=2, dim_head=8, causal=True,
        striped=True, bucket_size=args.seq_len // ring, mesh=mesh,
        use_ring=True,
    )
    # the SAME synthetic batch every step and every run: loss
    # trajectories are then comparable across kills and device counts
    rng = np.random.default_rng(0)
    base = rng.integers(0, 64, (2, args.seq_len // 2))
    tokens = shard_batch(
        np.concatenate([base, base], axis=1).astype(np.int32), mesh
    )
    opt = optax.adamw(1e-2)

    def fresh():
        params = model.init(jax.random.PRNGKey(0), tokens)
        return {"params": params, "opt_state": opt.init(params)}

    state, start = mgr.resume_or_init(
        fresh, mesh=mesh, seq_len=args.seq_len
    )
    if mgr.last_resume is not None:
        for line in mgr.last_resume["diagnostics"]:
            print(line, flush=True)

    def loss_fn(p, t):
        return model.apply(p, t, return_loss=True)

    step_fn = jax.jit(make_train_step(loss_fn, opt))

    log = open(args.loss_log, "a")

    def log_row(step: int, loss: float) -> None:
        log.write(json.dumps(
            {"step": step, "loss": loss, "world": n_dev}
        ) + "\n")
        log.flush()
        os.fsync(log.fileno())

    params, opt_state = state["params"], state["opt_state"]
    with PreemptionGuard() as guard:
        for step in range(start, args.steps):
            params, opt_state, loss = step_fn(params, opt_state, tokens)
            loss = float(loss)  # sync: the step is genuinely finished
            # mid-run hard death (kill_at_step=K): after the step
            # computed, before anything was saved or logged
            chaos.chaos_point(chaos.KILL_AT_STEP, step=step)
            log_row(step, loss)
            if guard.should_stop():
                mgr.save(
                    step,
                    {"params": params, "opt_state": opt_state},
                    block=True,
                )
                print(f"DRAINED {guard.signal_name} step={step}",
                      flush=True)
                break
            if step % args.save_every == 0 or step == args.steps - 1:
                mgr.save(step, {"params": params, "opt_state": opt_state})
    mgr.close()
    log.close()
    print(f"ELASTIC-OK start={start} world={n_dev}", flush=True)


if __name__ == "__main__":
    main()
