"""Victim process for the elastic chaos matrix (tests/test_elastic.py,
tests/test_multihost.py).

One tiny ring-attention training run on virtual CPU devices, wired
exactly the way a production job would be: elastic sharded checkpoints
(async saves, manifest commit), re-mesh resume planned from the latest
manifest, a PreemptionGuard drain (cluster-broadcast when multi-process),
and an optional heartbeat watchdog.  The parent kills it anywhere —
chaos faults arrive via ``RING_ATTN_CHAOS`` (armed at startup), the
device count via ``RING_ATTN_CHAOS_DEVICES`` — restarts it at any
device count, and audits the per-step loss log this worker appends
(one fsync'd JSON line per completed step, so a hard death can never
lose or tear the evidence).

Multi-process mode: ``RING_ATTN_CLUSTER="<pid>:<nproc>:<port>"`` joins a
``jax.distributed`` cluster (``ChaosWorker.run_cluster`` sets it); the
mesh grows the ``dcn_data`` level (one group per process, rings strictly
inside), every process writes its own checkpoint shard group, process 0
commits the manifest behind the cross-process barrier, and process 0
alone appends the loss log.

    python tests/elastic_worker.py --ckpt-dir D --loss-log L [--steps 10]
"""

import argparse
import json
import os
import sys


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--loss-log", required=True)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--save-every", type=int, default=3)
    ap.add_argument("--sync-save", action="store_true",
                    help="synchronous saves (the chaos kill points then "
                         "fire on the main thread, deterministically "
                         "ordered against the loss log)")
    ap.add_argument("--barrier-timeout", type=float, default=60.0,
                    help="cross-process checkpoint barrier budget: a dead "
                         "peer costs this many seconds, never a hang")
    ap.add_argument("--watchdog-deadline", type=float, default=None,
                    help="arm the heartbeat watchdog: a step boundary "
                         "further apart than this aborts the process "
                         "(exit 114) with a watchdog_abort flight "
                         "incident — the wedged-collective conversion")
    ap.add_argument("--flight-dir", default=None,
                    help="FlightRecorder dump directory (watchdog/"
                         "preemption incidents land here)")
    args = ap.parse_args()

    n_dev = int(os.environ.get("RING_ATTN_CHAOS_DEVICES", "4"))
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_dev}"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_matmul_precision", "highest")
    # share the test suite's persistent compile cache: repeat chaos runs
    # pay XLA compilation once per (device count, shape), not per run
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.05)

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import numpy as np
    import optax

    from ring_attention_tpu.elastic import (
        ElasticCheckpointManager,
        PreemptionGuard,
        Watchdog,
        chaos,
    )
    from ring_attention_tpu.models import RingTransformer
    from ring_attention_tpu.parallel import (
        create_mesh,
        initialize_multihost,
        remesh_plan,
        shard_batch,
    )
    from ring_attention_tpu.utils import (
        FlightRecorder,
        make_train_step,
        resilience,
        tracing,
    )

    cluster = chaos.cluster_from_env()
    if cluster is not None:
        pid, nproc, port = cluster
        initialize_multihost(
            coordinator_address=f"127.0.0.1:{port}",
            num_processes=nproc,
            process_id=pid,
        )
    proc = int(jax.process_index())
    # span tracing (env-armed like chaos): every barrier wait, save
    # phase, watchdog beat, and chaos kill from this process lands in
    # its own spans_pNNNNN.jsonl for the merged cluster timeline
    tracing.configure_from_env(process=proc)
    nproc = int(jax.process_count())
    world = int(jax.device_count())  # global across the cluster

    armed = chaos.arm_from_env()
    if armed:
        print(f"chaos armed: {armed}", flush=True)

    mgr = ElasticCheckpointManager(
        args.ckpt_dir, keep=3, async_save=not args.sync_save,
        barrier_timeout_s=args.barrier_timeout,
    )
    manifest = mgr.latest_manifest()
    if manifest is not None:
        plan, diags = remesh_plan(
            manifest.get("mesh"), world, dcn_data_size=nproc
        )
        for line in diags:
            print(line, flush=True)
    elif nproc > 1:
        # fresh multi-process start: the dcn level is the process count,
        # each process's devices form one ring strictly inside it
        plan = {"ring_size": world // nproc, "dcn_data_size": nproc}
    else:
        plan = {"ring_size": world}
    mesh = create_mesh(**plan)
    ring = plan["ring_size"] * (plan.get("ulysses_size") or 1)

    model = RingTransformer(
        num_tokens=64, dim=16, depth=1, heads=2, dim_head=8, causal=True,
        striped=True, bucket_size=args.seq_len // ring, mesh=mesh,
        use_ring=True,
    )
    # the SAME synthetic batch every step and every run: loss
    # trajectories are then comparable across kills and device counts
    rng = np.random.default_rng(0)
    base = rng.integers(0, 64, (2, args.seq_len // 2))
    full = np.concatenate([base, base], axis=1).astype(np.int32)
    if nproc > 1:
        # each process passes only ITS dcn group's batch rows
        rows = full.shape[0] // nproc
        local = full[proc * rows:(proc + 1) * rows]
    else:
        local = full
    tokens = shard_batch(local, mesh)
    opt = optax.adamw(1e-2)

    def fresh():
        params = model.init(jax.random.PRNGKey(0), tokens)
        return {"params": params, "opt_state": opt.init(params)}

    state, start = mgr.resume_or_init(
        fresh, mesh=mesh, seq_len=args.seq_len
    )
    if mgr.last_resume is not None:
        for line in mgr.last_resume["diagnostics"]:
            print(line, flush=True)

    def loss_fn(p, t):
        loss = model.apply(p, t, return_loss=True)
        # wedge simulation point: armed hang_collective stalls the
        # compiled step at RUN time (chaos.delay_tap) — the watchdog's
        # prey.  Disarmed it is an exact multiply by 1.0.
        return chaos.delay_tap(loss)

    # ZeRO-1: optimizer moments sharded over the full data-parallel
    # world, both tiers (utils/train.py).  Multi-process this is what
    # makes every process OWN part of the checkpoint — the per-process
    # shard write sets are disjoint and NON-EMPTY (a replicated state
    # would dedupe every leaf onto process 0's lowest device), so the
    # mid-shard chaos window exists on every worker.  Single-process
    # meshes here keep data=1, where the constraint is a no-op.
    step_fn = jax.jit(make_train_step(
        loss_fn, opt, shard_opt_state=True, shard_mesh=mesh
    ))

    recorder = None
    if args.flight_dir:
        recorder = FlightRecorder(args.flight_dir, window=16)
    dog = None
    if args.watchdog_deadline:
        dog = Watchdog(
            args.watchdog_deadline, recorder=recorder
        ).start()

    log = open(args.loss_log, "a") if proc == 0 else None

    def log_row(step: int, loss: float) -> None:
        if log is None:
            return
        log.write(json.dumps(
            {"step": step, "loss": loss, "world": world}
        ) + "\n")
        log.flush()
        os.fsync(log.fileno())

    def should_stop(guard, step: int) -> bool:
        if nproc > 1:
            return guard.should_stop_cluster(step=step)
        return guard.should_stop()

    params, opt_state = state["params"], state["opt_state"]
    injector = resilience.get_injector()
    with PreemptionGuard() as guard:
        for step in range(start, args.steps):
            # step-gated wedge (chaos env "wedge_at_step=K"): arm the
            # in-graph delay at exactly step K, so earlier steps beat
            # the watchdog normally and THEN the compiled step stalls —
            # the deterministic wedged-collective simulation
            if injector.armed("wedge_at_step") and step == int(
                injector.value("wedge_at_step")
            ):
                injector.arm("hang_collective", float(
                    injector.value("wedge_seconds", 120) or 120
                ))
            with tracing.get_tracer().span("train/step", step=step):
                params, opt_state, loss = step_fn(
                    params, opt_state, tokens
                )
                loss = float(loss)  # sync: the step genuinely finished
            if dog is not None:
                dog.beat(step)
            # mid-run hard death (kill_at_step=K): after the step
            # computed, before anything was saved or logged
            chaos.chaos_point(chaos.KILL_AT_STEP, step=step)
            log_row(step, loss)
            if should_stop(guard, step):
                mgr.save(
                    step,
                    {"params": params, "opt_state": opt_state},
                    block=True,
                )
                print(f"DRAINED {guard.signal_name} step={step}",
                      flush=True)
                break
            if step % args.save_every == 0 or step == args.steps - 1:
                mgr.save(step, {"params": params, "opt_state": opt_state})
    mgr.close()
    if dog is not None:
        dog.stop()
    tracing.shutdown()
    if log is not None:
        log.close()
    print(f"ELASTIC-OK start={start} world={world} proc={proc}",
          flush=True)


if __name__ == "__main__":
    main()
