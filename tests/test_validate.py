"""Runtime shape/dtype validation on the public API surface.

Analogue of the reference's beartype layer (ref tensor_typing.py:11-20,
applied at ring_attention.py:47,284): malformed calls must fail fast with a
one-line diagnostic naming the entry point, instead of erroring deep inside
an einsum or silently computing nonsense on a transposed layout.
"""

import jax
import jax.numpy as jnp
import pytest
from ring_attention_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from ring_attention_tpu.models import RingAttention, RingTransformer
from ring_attention_tpu.ops import flash_attention, pallas_flash_attention
from ring_attention_tpu.parallel import create_mesh, ring_flash_attention
from ring_attention_tpu.parallel.tree_decode import tree_attn_decode
from ring_attention_tpu.parallel.ulysses import ulysses_attention


def make(shape, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


Q = make((2, 4, 32, 16))
K = make((2, 4, 32, 16))


def test_flash_rejects_3d():
    with pytest.raises(ValueError, match=r"flash_attention: q must be 4-D"):
        flash_attention(make((2, 32, 16)), K, K)


def test_flash_rejects_seq_major_layout():
    # a (b, n, h, d) kv against (b, h, n, d) q: the head axis lands on the
    # seq slot and trips the GQA multiple check with a layout hint
    with pytest.raises(ValueError, match=r"flash_attention: .*\(batch, seq, heads, dim\) call"):
        flash_attention(Q, make((2, 32, 4, 16)), make((2, 32, 4, 16)))


def test_flash_rejects_kv_shape_mismatch():
    with pytest.raises(ValueError, match=r"k and v must have identical shapes"):
        flash_attention(Q, K, make((2, 4, 16, 16)))


def test_flash_rejects_bad_gqa():
    # 3 query heads against 2 kv heads
    with pytest.raises(ValueError, match=r"multiple of kv heads"):
        flash_attention(make((2, 3, 32, 16)), make((2, 2, 32, 16)), make((2, 2, 32, 16)))


def test_flash_rejects_int_dtype():
    with pytest.raises(ValueError, match=r"q must be floating point"):
        flash_attention(make((2, 4, 32, 16), jnp.int32), K, K)


def test_flash_rejects_bad_mask():
    with pytest.raises(ValueError, match=r"kv_mask must be \(batch, n_kv\)"):
        flash_attention(Q, K, K, make((2, 16), jnp.bool_))


def test_pallas_flash_rejects_3d():
    with pytest.raises(ValueError, match=r"pallas_flash_attention: q must be 4-D"):
        pallas_flash_attention(make((2, 32, 16)), K, K)


def test_ring_rejects_bad_layout():
    mesh = create_mesh(ring_size=8)
    spec = P("data", None, "seq", None)

    def run(q, k, v):
        return shard_map(
            lambda q, k, v: ring_flash_attention(q, k, v, None, "seq"),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
        )(q, k, v)

    with pytest.raises(ValueError, match=r"ring_flash_attention: .*disagree"):
        run(make((2, 4, 64, 16)), make((2, 4, 64, 32)), make((2, 4, 64, 32)))


def test_tree_decode_rejects_bad_mask():
    mesh = create_mesh(ring_size=8)

    def run():
        qspec = P("data", None, None, None)
        cspec = P("data", None, "seq", None)
        return shard_map(
            lambda q, k, v, m: tree_attn_decode(q, k, v, m, axis_name="seq"),
            mesh=mesh,
            in_specs=(qspec, cspec, cspec, P("data", None)),
            out_specs=qspec,
        )(
            make((2, 4, 1, 16)),
            make((2, 4, 64, 16)),
            make((2, 4, 64, 16)),
            make((2, 32), jnp.bool_),  # wrong: local shard is 8 slots
        )

    with pytest.raises(ValueError, match=r"tree_attn_decode: kv_mask"):
        run()


def test_ulysses_rejects_cross_attention():
    mesh = create_mesh(ring_size=8)
    spec = P("data", None, "seq", None)
    with pytest.raises(ValueError, match=r"ulysses_attention: .*sequence length"):
        shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, "seq"),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
        )(make((2, 8, 64, 16)), make((2, 8, 128, 16)), make((2, 8, 128, 16)))


def test_module_rejects_2d_input():
    layer = RingAttention(dim=32, heads=4, dim_head=8)
    with pytest.raises(ValueError, match=r"RingAttention: x must be \(batch, seq, dim=32\)"):
        layer.init(jax.random.PRNGKey(0), make((2, 32)))


def test_module_rejects_wrong_dim():
    layer = RingAttention(dim=32, heads=4, dim_head=8)
    with pytest.raises(ValueError, match=r"RingAttention: x must be"):
        layer.init(jax.random.PRNGKey(0), make((2, 16, 64)))


def test_transformer_rejects_float_tokens():
    model = RingTransformer(num_tokens=64, dim=32, depth=1, causal=True)
    with pytest.raises(ValueError, match=r"RingTransformer: tokens must be integer"):
        model.init(jax.random.PRNGKey(0), make((2, 16), jnp.float32))


def test_transformer_rejects_3d_tokens():
    model = RingTransformer(num_tokens=64, dim=32, depth=1, causal=True)
    with pytest.raises(ValueError, match=r"RingTransformer: tokens must be \(batch, seq\)"):
        model.init(
            jax.random.PRNGKey(0), make((2, 16, 3), jnp.int32)
        )
